"""Quality-parity convergence runs — the acceptance evidence the reference
establishes with in-loop metrics on real Goodreads data
(jax-flax/train_dp.py:219-245 prints per-epoch eval ROC-AUC;
torchrec/train.py:143-144 prints Recall@K/NDCG@K per epoch).

Runs full ``Trainer.fit()`` to convergence for BOTH model families on the
signal-bearing synthetic Goodreads fixtures (``write_synthetic_goodreads``
``signal=0.85``: latent book clusters + user themes make the CTR label and
the next-item distribution learnable), on the 8-device spoofed CPU mesh in
the DMP regime.  Metric trajectories land in ``docs/quality/*.jsonl``
(committed artifacts) and a summary table prints at the end; the slow test
``tests/test_quality.py`` asserts the same floors in CI.

    python tools/quality_run.py [--out docs/quality]
"""
import argparse
import json
import shutil
import sys
import tempfile
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from tdfo_tpu.core.mesh import spoof_cpu_devices

spoof_cpu_devices(8)

from tdfo_tpu.core.config import read_configs  # noqa: E402
from tdfo_tpu.data.ctr_preprocessing import run_ctr_preprocessing  # noqa: E402
from tdfo_tpu.data.seq_preprocessing import run_seq_preprocessing  # noqa: E402
from tdfo_tpu.data.synthetic import write_synthetic_goodreads  # noqa: E402
from tdfo_tpu.train.trainer import Trainer  # noqa: E402


def run_twotower(data_dir: Path, log_dir: Path) -> dict:
    write_synthetic_goodreads(data_dir, n_users=800, n_books=320,
                              interactions_per_user=(30, 60), seed=5,
                              signal=0.85)
    size_map = run_ctr_preprocessing(data_dir)
    cfg = read_configs(
        None, data_dir=data_dir, model="twotower", model_parallel=True,
        n_epochs=15, learning_rate=3e-3, weight_decay=1e-3, embed_dim=8,
        per_device_train_batch_size=64, per_device_eval_batch_size=64,
        shuffle_buffer_size=20_000, log_every_n_steps=10_000,
        size_map=size_map,
    )
    tr = Trainer(cfg, log_dir=log_dir)
    return tr.fit()


def run_bert4rec(data_dir: Path, log_dir: Path) -> dict:
    write_synthetic_goodreads(data_dir, n_users=400, n_books=320,
                              interactions_per_user=(30, 60), seed=7,
                              signal=0.85)
    stats = run_seq_preprocessing(data_dir, max_len=16, sliding_step=8,
                                  seed=7)
    cfg = read_configs(
        None, data_dir=data_dir, model="bert4rec", model_parallel=True,
        n_epochs=25, learning_rate=3e-3, embed_dim=32, n_heads=2,
        n_layers=2, max_len=16, sliding_step=8,
        per_device_train_batch_size=32, per_device_eval_batch_size=32,
        shuffle_buffer_size=20_000, log_every_n_steps=10_000,
        size_map={"n_items": stats["n_items"]},
    )
    tr = Trainer(cfg, log_dir=log_dir)
    return tr.fit()


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="docs/quality")
    args = ap.parse_args()
    out = Path(args.out)
    out.mkdir(parents=True, exist_ok=True)
    summary = {}
    for family, runner in (("twotower", run_twotower),
                           ("bert4rec", run_bert4rec)):
        with tempfile.TemporaryDirectory() as tmp:
            log_dir = Path(tmp) / "logs"
            metrics = runner(Path(tmp) / "data", log_dir)
            shutil.copy(log_dir / "metrics.jsonl", out / f"{family}.jsonl")
        summary[family] = metrics
        print(f"[quality] {family}: "
              + ", ".join(f"{k}={v:.4f}" for k, v in sorted(metrics.items())),
              flush=True)
    with open(out / "summary.json", "w") as f:
        json.dump(summary, f, indent=2, sort_keys=True)
    # convergence floors (mirrored by tests/test_quality.py)
    ok = (summary["twotower"]["auc"] >= 0.60
          and summary["bert4rec"]["Recall@10"] >= 0.35
          and summary["bert4rec"]["NDCG@10"] >= 0.20)
    print(f"[quality] floors {'OK' if ok else 'NOT MET'}", flush=True)
    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    main()
