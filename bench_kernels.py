"""Pallas-kernel micro-benchmarks vs their XLA formulations (real chip).

Supplementary to bench.py (the driver's single-line headline metric): prints
one JSON line PER kernel comparison.  Inputs VARY per timed iteration — the
tunnelled TPU runtime caches identical executions, so repeating one input
measures the cache, not the chip.
"""

from __future__ import annotations

import json

import jax
import jax.numpy as jnp
import numpy as np


def bench_flash(t: int = 4096) -> dict:
    """Forward-only comparison, chain-differenced (block_until_ready does not
    sync through the tunnelled runtime — see bench.py)."""
    from tdfo_tpu.ops.pallas_kernels import flash_attention

    b, h, dh = 1, 8, 64

    def xla_attn(q, k, v):
        s = jnp.einsum("bhtd,bhsd->bhts", q, k).astype(jnp.float32) / dh**0.5
        return jnp.einsum("bhts,bhsd->bhtd", jax.nn.softmax(s, -1).astype(v.dtype), v)

    def build(attn):
        def run(kn):
            @jax.jit
            def chain(qs, ks_, vs):
                def body(c, xs):
                    q, kk, v = xs
                    o = attn(q + c.astype(q.dtype), kk, v)
                    return o.astype(jnp.float32).sum() % 1024.0, None

                c, _ = jax.lax.scan(body, jnp.float32(0), (qs, ks_, vs))
                return c

            return chain

        return run

    def make_args(kn, seed):
        xs = jax.random.split(jax.random.key(seed), 3)
        q, kk, v = (jax.random.normal(x, (kn, b, h, t, dh), jnp.bfloat16) for x in xs)
        float(jnp.sum(q.astype(jnp.float32)))
        return (q, kk, v)

    pl_sec = _chain_time(build(lambda q, k, v: flash_attention(q, k, v)),
                         make_args, ks=(8, 32))
    xla_sec = _chain_time(build(xla_attn), make_args, ks=(8, 32))
    return {
        "metric": f"flash_attention_T{t}_ms",
        "value": round(pl_sec * 1e3, 3),
        "unit": "ms",
        "xla_ms": round(xla_sec * 1e3, 3),
        "vs_baseline": round(xla_sec / max(pl_sec, 1e-9), 3),  # >1 = pallas faster
    }


def _chain_time(run, make_args, ks=(16, 96), reps=2) -> float:
    """Per-step seconds by chain-length differencing — the single shared
    implementation lives in bench.py (the tunnelled runtime makes
    block_until_ready a no-op, so only value fetches of scan chains measure
    real device time)."""
    from bench import chain_time

    return chain_time(run, make_args, ks=ks, reps=reps)


def bench_fat_adam(v: int = 2_000_000, d: int = 64, b: int = 8192) -> dict:
    """Fused fat-row Adam tier (in-place DMA kernel on TPU) vs the plain
    three-buffer gather/scatter tier on the same updates.  State is created
    inside each chain (a per-chain constant the differencing cancels) so no
    second HBM copy of a big table ever exists.
    """
    from tdfo_tpu.ops.pallas_kernels import fat_pack
    from tdfo_tpu.ops.sparse import sparse_optimizer

    opt = sparse_optimizer("adam", lr=1e-2, small_vocab_threshold=0)
    probe = jax.random.normal(jax.random.key(9), (d,))

    def build(fused: bool):
        def run(k):
            @jax.jit
            def chain(key, ids_stack, grads_stack):
                table = jax.random.uniform(key, (v, d), jnp.float32)
                if fused:
                    table = fat_pack(table, jnp.zeros((v, d), jnp.float32),
                                     jnp.zeros((v, d), jnp.float32))
                slots = opt.init(table)

                def body(carry, xs):
                    t, s = carry
                    ids, g = xs
                    t, s = opt.update(t, s, ids, g, embedding_dim=d)
                    return (t, s), None

                (t, _), _ = jax.lax.scan(body, (table, slots),
                                         (ids_stack, grads_stack))
                first = t[0, 0, :d] if fused else t[0]
                return (first @ probe).sum()

            return chain

        return run

    def make_args(k, seed):
        r = np.random.default_rng(seed)
        ids = jax.device_put(r.integers(0, v, (k, b)).astype(np.int32))
        grads = jax.device_put(r.standard_normal((k, b, d), np.float32))
        float(jnp.sum(ids) + jnp.sum(grads))
        return (jax.random.key(seed), ids, grads)

    fat_sec = _chain_time(build(fused=True), make_args)
    plain_sec = _chain_time(build(fused=False), make_args)
    return {
        "metric": f"fat_adam_V{v}_B{b}_D{d}_ms",
        "value": round(fat_sec * 1e3, 3),
        "unit": "ms",
        "plain_tier_ms": round(plain_sec * 1e3, 3),
        "vs_baseline": round(plain_sec / max(fat_sec, 1e-9), 3),  # >1 = fat faster
    }


def bench_fat_bf16(v: int = 2_000_000, d: int = 64, b: int = 8192) -> dict:
    """Quantized fat-line storage ablation: bf16 packed lines (half the
    per-line DMA bytes, in-kernel stochastic-rounding writeback keyed per
    step) vs the f32 fat tier on identical updates.  vs_baseline > 1 means
    bf16 wins — expect roughly the DMA-byte ratio at this profile, since
    the fat tier is line-traffic-bound (docs/BUDGET.md)."""
    from tdfo_tpu.ops.pallas_kernels import fat_pack
    from tdfo_tpu.ops.quant import sr_key as make_sr_key
    from tdfo_tpu.ops.sparse import sparse_optimizer

    opt = sparse_optimizer("adam", lr=1e-2, small_vocab_threshold=0)
    probe = jax.random.normal(jax.random.key(9), (d,))

    def build(dtype):
        quant = dtype != jnp.float32

        def run(k):
            @jax.jit
            def chain(key, ids_stack, grads_stack):
                table = jax.random.uniform(key, (v, d), jnp.float32)
                fat = fat_pack(table, jnp.zeros((v, d), jnp.float32),
                               jnp.zeros((v, d), jnp.float32), dtype=dtype)
                slots = opt.init(fat)

                def body(carry, xs):
                    t, s, step = carry
                    ids, g = xs
                    sk = make_sr_key(step, "bench_fat") if quant else None
                    t, s = opt.update(t, s, ids, g, embedding_dim=d,
                                      sr_key=sk)
                    return (t, s, step + 1), None

                (t, _, _), _ = jax.lax.scan(
                    body, (fat, slots, jnp.int32(0)),
                    (ids_stack, grads_stack))
                return (t[0, 0, :d].astype(jnp.float32) @ probe).sum()

            return chain

        return run

    def make_args(k, seed):
        r = np.random.default_rng(seed)
        ids = jax.device_put(r.integers(0, v, (k, b)).astype(np.int32))
        grads = jax.device_put(r.standard_normal((k, b, d), np.float32))
        float(jnp.sum(ids) + jnp.sum(grads))
        return (jax.random.key(seed), ids, grads)

    bf16_sec = _chain_time(build(jnp.bfloat16), make_args)
    f32_sec = _chain_time(build(jnp.float32), make_args)
    return {
        "metric": f"fat_adam_bf16_V{v}_B{b}_D{d}_ms",
        "value": round(bf16_sec * 1e3, 3),
        "unit": "ms",
        "f32_fat_ms": round(f32_sec * 1e3, 3),
        "vs_baseline": round(f32_sec / max(bf16_sec, 1e-9), 3),  # >1 = bf16 faster
    }


def bench_fat_int8(v: int = 2_000_000, d: int = 64, b: int = 8192) -> dict:
    """int8 byte-container fat lines (1-byte codes + the bitcast f32
    (scale, offset) sidecar + f32 adam state in ONE line: 640 B/row at
    d=64 vs 1160 B/row for plain int8 codes + sidecar + f32 slot arrays)
    vs the f32 fat tier AND the plain-int8 dedupe + scatter path on
    identical updates.  vs_baseline > 1 means the int8 fat line wins over
    f32 fat; vs_plain_int8 > 1 means it also beats the eager plain-int8
    scatter — the planner's cross-over at this profile (docs/BUDGET.md)."""
    from tdfo_tpu.ops.pallas_kernels import fat_pack
    from tdfo_tpu.ops.quant import quantize_rows, sr_key as make_sr_key
    from tdfo_tpu.ops.sparse import sparse_optimizer

    opt = sparse_optimizer("adam", lr=1e-2, small_vocab_threshold=0)
    probe = jax.random.normal(jax.random.key(9), (d,))

    def build_fat(dtype):
        quant = dtype != jnp.float32

        def run(k):
            @jax.jit
            def chain(key, ids_stack, grads_stack):
                table = jax.random.uniform(key, (v, d), jnp.float32)
                fat = fat_pack(table, jnp.zeros((v, d), jnp.float32),
                               jnp.zeros((v, d), jnp.float32), dtype=dtype)
                slots = opt.init(fat)

                def body(carry, xs):
                    t, s, step = carry
                    ids, g = xs
                    sk = make_sr_key(step, "bench_fat") if quant else None
                    t, s = opt.update(t, s, ids, g, embedding_dim=d,
                                      sr_key=sk)
                    return (t, s, step + 1), None

                (t, _, _), _ = jax.lax.scan(
                    body, (fat, slots, jnp.int32(0)),
                    (ids_stack, grads_stack))
                return (t[0, 0, :d].astype(jnp.float32) @ probe).sum()

            return chain

        return run

    def run_plain(k):
        @jax.jit
        def chain(key, ids_stack, grads_stack):
            codes, qs = quantize_rows(
                jax.random.uniform(key, (v, d), jnp.float32))
            slots = opt.init(codes)

            def body(carry, xs):
                t, s, q, step = carry
                ids, g = xs
                t, s, q = opt.update(t, s, ids, g,
                                     sr_key=make_sr_key(step, "bench_fat"),
                                     qscale=q)
                return (t, s, q, step + 1), None

            (t, _, q, _), _ = jax.lax.scan(
                body, (codes, slots, qs, jnp.int32(0)),
                (ids_stack, grads_stack))
            return ((t[0].astype(jnp.float32) * q[0, 0] + q[0, 1])
                    @ probe).sum()

        return chain

    def make_args(k, seed):
        r = np.random.default_rng(seed)
        ids = jax.device_put(r.integers(0, v, (k, b)).astype(np.int32))
        grads = jax.device_put(r.standard_normal((k, b, d), np.float32))
        float(jnp.sum(ids) + jnp.sum(grads))
        return (jax.random.key(seed), ids, grads)

    i8_sec = _chain_time(build_fat(jnp.int8), make_args)
    f32_sec = _chain_time(build_fat(jnp.float32), make_args)
    plain_sec = _chain_time(run_plain, make_args)
    return {
        "metric": f"fat_adam_int8_V{v}_B{b}_D{d}_ms",
        "value": round(i8_sec * 1e3, 3),
        "unit": "ms",
        "f32_fat_ms": round(f32_sec * 1e3, 3),
        "plain_int8_ms": round(plain_sec * 1e3, 3),
        "vs_baseline": round(f32_sec / max(i8_sec, 1e-9), 3),  # >1 = int8 faster
        "vs_plain_int8": round(plain_sec / max(i8_sec, 1e-9), 3),
    }


def bench_hot_cold_update(v: int = 10_131_227, d: int = 16, b: int = 8192,
                          k_hot: int = 16_384) -> dict:
    """Frequency-partitioned update ablation at the Criteo big-table profile
    (the largest Kaggle table: 10.13M rows, dim 16) under power-law (zipf)
    traffic: plain dedupe + XLA row-scatter over ALL ids vs the hot/cold
    split — branch-free prefix routing, scatter-free one-hot MXU update for
    the [0, 16k) head (where the lookup mass concentrates), dedupe + scatter
    for the much smaller cold residual.  Both run the SAME rowwise-adagrad
    math; vs_baseline > 1 means the split wins."""
    from tdfo_tpu.data.synthetic import zipf_ids
    from tdfo_tpu.ops.sparse import sparse_optimizer

    opt = sparse_optimizer("rowwise_adagrad", lr=1e-3)

    def build(split: bool):
        def run(k):
            @jax.jit
            def chain(ids_stack, grads_stack):
                table = jnp.zeros((v, d), jnp.float32)
                slots = opt.init(table)
                hot = jnp.zeros((k_hot, d), jnp.float32)
                hot_slots = opt.init(hot)

                def body(carry, xs):
                    t, s, h, hs = carry
                    ids, g = xs
                    if split:
                        hit = ids < k_hot
                        hp = jnp.where(hit, ids, -1)
                        ci = jnp.where(hit, -1, ids)
                        h, hs = opt.dense_update(h, hs, hp, g)
                        t, s = opt.update(t, s, ci, g)
                    else:
                        t, s = opt.update(t, s, ids, g)
                    return (t, s, h, hs), None

                (t, _, h, _), _ = jax.lax.scan(
                    body, (table, slots, hot, hot_slots),
                    (ids_stack, grads_stack))
                return t[0].sum() + h[0].sum()

            return chain

        return run

    hit_rates: list[float] = []

    def make_args(k, seed):
        r = np.random.default_rng(seed)
        ids_np = zipf_ids(r, v, (k, b))
        hit_rates.append(float((ids_np < k_hot).mean()))
        ids = jax.device_put(ids_np)
        grads = jax.device_put(r.standard_normal((k, b, d), np.float32))
        float(jnp.sum(ids) + jnp.sum(grads))
        return (ids, grads)

    split_sec = _chain_time(build(True), make_args, ks=(32, 160))
    plain_sec = _chain_time(build(False), make_args, ks=(32, 160))
    return {
        "metric": f"hot_cold_update_V{v}_B{b}_D{d}_K{k_hot}_ms",
        "value": round(split_sec * 1e3, 3),
        "unit": "ms",
        "plain_scatter_ms": round(plain_sec * 1e3, 3),
        "hit_rate": round(float(np.mean(hit_rates)), 4),
        "vs_baseline": round(plain_sec / max(split_sec, 1e-9), 3),  # >1 = split faster
    }


def bench_cache_route(v: int = 10_131_227, d: int = 16, b: int = 8192,
                      c: int = 16_384) -> dict:
    """Isolated cost of the update-cache directory route
    (``ops/sparse.py cache_route``: one ``searchsorted(method="sort")``
    into the sorted-id directory + a slot gather — branch-free) on a warm
    C=16k directory, vs the eager dedupe + XLA row-scatter update it
    displaces on non-flush steps (largest Criteo-Kaggle table,
    10.13M x 16, rowwise-adagrad, zipf a=1.2 traffic).  vs_baseline > 1 =
    the route costs less than the scatter it amortizes away; the claim the
    MANAGED_CACHING mode banks on is ~2 orders of magnitude (8k-scale
    sorts are ~tens of µs on v5e, the scatter path ~10+ ms here)."""
    from tdfo_tpu.data.synthetic import zipf_ids
    from tdfo_tpu.ops.sparse import cache_route, sparse_optimizer

    # warm directory: the hottest C ids resident — the steady state the
    # (freq, recency) retention policy converges to under power-law traffic
    dir_ids = jax.device_put(jnp.arange(c, dtype=jnp.int32))
    dir_slot = jax.device_put(jnp.arange(c, dtype=jnp.int32))

    def run_route(k):
        @jax.jit
        def chain(dir_ids, dir_slot, ids_stack):
            cache = {"ids": dir_ids, "slot": dir_slot}

            def body(carry, ids):
                # fold the carry in so no two routed batches are identical
                ids = (ids + carry) % v
                phys, hit = cache_route(cache, ids)
                return (phys.sum() + hit.sum()).astype(jnp.int32) % 128, None

            final, _ = jax.lax.scan(body, jnp.int32(0), ids_stack)
            return final

        return lambda stack: chain(dir_ids, dir_slot, stack)

    def make_route_args(k, seed):
        r = np.random.default_rng(seed)
        ids = jax.device_put(zipf_ids(r, v, (k, b)))
        float(jnp.sum(ids))
        return (ids,)

    opt = sparse_optimizer("rowwise_adagrad", lr=1e-3)

    def run_scatter(k):
        @jax.jit
        def chain(ids_stack, grads_stack):
            # table + slots created in-chain (a per-chain constant the
            # differencing cancels; see bench.py bench_big_table)
            table = jnp.zeros((v, d), jnp.float32)
            slots = opt.init(table)

            def body(carry, xs):
                t, s = carry
                ids, g = xs
                t, s = opt.update(t, s, ids, g)
                return (t, s), None

            (t, _), _ = jax.lax.scan(body, (table, slots),
                                     (ids_stack, grads_stack))
            return t[0].sum()

        return chain

    def make_scatter_args(k, seed):
        r = np.random.default_rng(seed)
        ids = jax.device_put(zipf_ids(r, v, (k, b)))
        grads = jax.device_put(r.standard_normal((k, b, d), np.float32))
        float(jnp.sum(ids) + jnp.sum(grads))
        return (ids, grads)

    # µs-scale route needs long chains to clear the tunnel-RPC noise
    route_sec = _chain_time(run_route, make_route_args, ks=(64, 512), reps=3)
    scatter_sec = _chain_time(run_scatter, make_scatter_args, ks=(32, 160),
                              reps=3)
    return {
        "metric": f"cache_route_B{b}_C{c}_us",
        "value": round(route_sec * 1e6, 1),
        "unit": "us",
        "eager_scatter_ms": round(scatter_sec * 1e3, 3),
        "vs_baseline": round(scatter_sec / max(route_sec, 1e-9), 3),  # >1 = route cheaper
    }


def bench_flash_bwd(t: int = 4096) -> dict:
    """Training-direction comparison: flash fwd+bwd (both Pallas, O(T)
    memory) vs the [T, T]-materialising XLA attention's VJP."""
    from tdfo_tpu.ops.pallas_kernels import _xla_attention, flash_attention

    b, h, dh = 1, 8, 64

    def build(attn):
        def run(k):
            @jax.jit
            def chain(qs, ks_, vs):
                def body(c, xs):
                    q, kk, v = xs

                    def loss(q, kk, v):
                        return (attn(q + c.astype(q.dtype), kk, v) ** 2).sum().astype(jnp.float32)

                    _, grads = jax.value_and_grad(loss, argnums=(0, 1, 2))(q, kk, v)
                    return (sum(g.astype(jnp.float32).sum() for g in grads) % 1024.0), None

                c, _ = jax.lax.scan(body, jnp.float32(0), (qs, ks_, vs))
                return c

            return chain

        return run

    def make_args(k, seed):
        xs = jax.random.split(jax.random.key(seed), 3)
        q, kk, v = (jax.random.normal(x, (k, b, h, t, dh), jnp.bfloat16) for x in xs)
        float(jnp.sum(q.astype(jnp.float32)))
        return (q, kk, v)

    pl_sec = _chain_time(build(lambda q, k, v: flash_attention(q, k, v)),
                         make_args, ks=(4, 16))
    xla_sec = _chain_time(build(lambda q, k, v: _xla_attention(q, k, v, None)),
                          make_args, ks=(4, 16))
    return {
        "metric": f"flash_fwd_bwd_T{t}_ms",
        "value": round(pl_sec * 1e3, 3),
        "unit": "ms",
        "xla_ms": round(xla_sec * 1e3, 3),
        "vs_baseline": round(xla_sec / max(pl_sec, 1e-9), 3),  # >1 = pallas faster
    }


def bench_ring_flash(t: int = 8192) -> dict:
    """Ring attention with flash innards vs the XLA blockwise ring, fwd+bwd,
    on the real chip's 1-device mesh (seq axis 1: the ring program — shard_map
    + scan + ppermute + the Pallas custom_vjp — compiles and runs end to end;
    multi-chip rotation is exercised by the CPU-mesh tests and the driver
    dryrun)."""
    from tdfo_tpu.core.config import MeshSpec
    from tdfo_tpu.core.mesh import make_mesh
    from tdfo_tpu.parallel.ring_attention import ring_self_attention

    mesh = make_mesh(MeshSpec(data=1, model=1, seq=-1))
    b, h, dh = 1, 4, 64

    def build(impl, block_k=None):
        def run(k):
            @jax.jit
            def chain(qs, ks_, vs):
                def body(c, xs):
                    q, kk, v = xs

                    def loss(q, kk, v):
                        out = ring_self_attention(
                            mesh, q + c.astype(q.dtype), kk, v,
                            impl=impl, block_k=block_k)
                        return (out.astype(jnp.float32) ** 2).sum()

                    _, grads = jax.value_and_grad(loss, argnums=(0, 1, 2))(q, kk, v)
                    return (sum(g.astype(jnp.float32).sum() for g in grads) % 1024.0), None

                c, _ = jax.lax.scan(body, jnp.float32(0), (qs, ks_, vs))
                return c

            return chain

        return run

    def make_args(k, seed):
        xs = jax.random.split(jax.random.key(seed), 3)
        q, kk, v = (jax.random.normal(x, (k, b, h, t, dh), jnp.bfloat16) for x in xs)
        float(jnp.sum(q.astype(jnp.float32)))
        return (q, kk, v)

    fl_sec = _chain_time(build("flash"), make_args, ks=(2, 8))
    xla_sec = _chain_time(build("xla", block_k=512), make_args, ks=(2, 8))
    return {
        "metric": f"ring_flash_fwd_bwd_T{t}_ms",
        "value": round(fl_sec * 1e3, 3),
        "unit": "ms",
        "xla_ring_ms": round(xla_sec * 1e3, 3),
        "vs_baseline": round(xla_sec / max(fl_sec, 1e-9), 3),  # >1 = flash faster
    }


if __name__ == "__main__":
    print(json.dumps(bench_flash()))
    print(json.dumps(bench_flash_bwd()))
    print(json.dumps(bench_fat_adam()))
    print(json.dumps(bench_fat_bf16()))
    print(json.dumps(bench_fat_int8()))
    print(json.dumps(bench_hot_cold_update()))
    print(json.dumps(bench_cache_route()))
    print(json.dumps(bench_ring_flash()))
