"""Pallas-kernel micro-benchmarks vs their XLA formulations (real chip).

Supplementary to bench.py (the driver's single-line headline metric): prints
one JSON line PER kernel comparison.  Inputs VARY per timed iteration — the
tunnelled TPU runtime caches identical executions, so repeating one input
measures the cache, not the chip.
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp
import numpy as np


def _time_varying(f, inputs_list) -> float:
    """min ms over calls with distinct inputs; first input used to compile."""
    jax.block_until_ready(f(*inputs_list[0]))
    times = []
    for inputs in inputs_list[1:]:
        t0 = time.perf_counter()
        jax.block_until_ready(f(*inputs))
        times.append(time.perf_counter() - t0)
    return min(times) * 1e3


def bench_flash(t: int = 4096, n_iters: int = 6) -> dict:
    from tdfo_tpu.ops.pallas_kernels import flash_attention

    b, h, dh = 1, 8, 64
    inputs = []
    for i in range(n_iters):
        ks = jax.random.split(jax.random.key(i), 3)
        inputs.append(tuple(
            jax.random.normal(kk, (b, h, t, dh), jnp.bfloat16) for kk in ks
        ))
    jax.block_until_ready(inputs)

    def xla_attn(q, k, v):
        s = jnp.einsum("bhtd,bhsd->bhts", q, k).astype(jnp.float32) / dh**0.5
        return jnp.einsum("bhts,bhsd->bhtd", jax.nn.softmax(s, -1).astype(v.dtype), v)

    pl_ms = _time_varying(
        jax.jit(lambda q, k, v: flash_attention(q, k, v, None, 128, 128, False)),
        inputs,
    )
    xla_ms = _time_varying(jax.jit(xla_attn), inputs)
    return {
        "metric": f"flash_attention_T{t}_ms",
        "value": round(pl_ms, 3),
        "unit": "ms",
        "vs_baseline": round(xla_ms / pl_ms, 3),  # >1 = pallas faster
    }


def bench_sparse_adam(v: int = 2_000_000, d: int = 128, b: int = 8192,
                      n_iters: int = 5) -> dict:
    from tdfo_tpu.ops.pallas_kernels import sparse_adam_rows
    from tdfo_tpu.ops.sparse import dedupe_grads, sparse_adam

    rng = np.random.default_rng(0)
    table_h = rng.normal(size=(v, d)).astype(np.float32)
    count = jnp.asarray(1, jnp.int32)

    def make_inputs(seed):
        r = np.random.default_rng(seed)
        ids = jnp.asarray(r.integers(0, v, b).astype(np.int32))
        grads = jnp.asarray(r.normal(size=(b, d)).astype(np.float32))
        uids, g, valid = dedupe_grads(ids, grads)
        # fresh (copied) state buffers so donation never reuses deleted arrays
        return (
            jnp.array(table_h), jnp.zeros((v, d)), jnp.zeros((v, d)),
            uids, g, valid,
        )

    f_pl = jax.jit(
        lambda t_, m_, n_, u_, g_, _v: sparse_adam_rows(
            t_, m_, n_, u_, g_, count, lr=1e-2
        ),
        donate_argnums=(0, 1, 2),
    )
    f_x = jax.jit(
        lambda t_, m_, n_, u_, g_, v_: sparse_adam(
            t_, m_, n_, count - 1, u_, g_, v_, lr=1e-2
        )[:3],
        donate_argnums=(0, 1, 2),
    )

    def run(f, seed):
        inputs = make_inputs(seed)
        jax.block_until_ready(inputs)
        t0 = time.perf_counter()
        jax.block_until_ready(f(*inputs))
        return (time.perf_counter() - t0) * 1e3

    run(f_pl, 0)  # compile
    run(f_x, 0)
    pl_ms = min(run(f_pl, i + 1) for i in range(n_iters))
    xla_ms = min(run(f_x, i + 1) for i in range(n_iters))
    return {
        "metric": f"sparse_adam_V{v}_B{b}_ms",
        "value": round(pl_ms, 3),
        "unit": "ms",
        "vs_baseline": round(xla_ms / pl_ms, 3),
    }


if __name__ == "__main__":
    print(json.dumps(bench_flash()))
    print(json.dumps(bench_sparse_adam()))
