"""Sequence-serving subsystem tests: bert4rec bundles, masked-position
scoring, ragged-history windows, and item-table retrieval.

The contracts under test, in order of importance:

  * train/serve skew is ZERO for the seq family too — a ``SeqScorer`` built
    from an exported bert4rec bundle produces bitwise the same
    masked-position candidate scores as the trainer's seq eval chain
    (``train/trainer.py _build_bert4rec`` eval_accum);
  * ragged histories batch through the SAME bounded-jit-cache discipline as
    CTR traffic — ``history_window`` fixes the row shape, bucket padding
    fixes the batch shape, so compiled programs stay <= len(buckets);
  * next-item retrieval searches the OUTPUT head as the corpus
    (``item_corpus``: bias-folded out_proj columns — NOT the input item
    table, out_proj is untied) so MIPS ranks exactly like ``score()``, and
    inherits the retrieval contracts unchanged: exact-path bitwise equality
    to the stable-argsort reference, and the int8 two-stage path holding
    its recall floor;
  * request-log replay forms deterministic [B, width] panels from seq
    feature payloads and quarantines width drift (the multihost-lockstep
    guard of ``trainer._eval_schema`` extended to the serve->retrain loop).
"""

import numpy as np
import optax
import pytest

import jax
import jax.numpy as jnp

from tdfo_tpu.data.replay import ReplayConsumer, RequestLog
from tdfo_tpu.models.bert4rec import (
    PAD_ID,
    Bert4RecConfig,
    key_padding_mask,
    make_sharded_bert4rec,
)
from tdfo_tpu.ops.sparse import sparse_optimizer
from tdfo_tpu.serve.export import ServingBundle, export_bundle, export_delta, load_bundle
from tdfo_tpu.serve.frontend import MicroBatcher
from tdfo_tpu.serve.retrieval import make_retrieval, retrieval_reference
from tdfo_tpu.serve.scoring import make_scorer
from tdfo_tpu.serve.seq_scoring import (
    SeqScorer,
    history_window,
    item_corpus,
    make_seq_scorer,
)
from tdfo_tpu.train.seq import score_candidates
from tdfo_tpu.train.sparse_step import SparseTrainState

CFG = Bert4RecConfig(n_items=50, max_len=8, embed_dim=16, n_heads=2,
                     n_layers=2)
N_CANDS = 101  # EVAL_NEG_NUM + 1, the eval panel width


def _bert4rec_sparse(mesh, seed=0, cfg=CFG):
    """Item collection + transformer backbone + SparseTrainState, mirroring
    the trainer's ``_build_bert4rec`` at toy scale."""
    coll, tables, backbone, dense = make_sharded_bert4rec(
        jax.random.key(seed), cfg, mesh, sharding="row",
        fused_threshold=None)
    state = SparseTrainState.create(
        dense_params=dense, tx=optax.adamw(1e-3), tables=tables,
        sparse_opt=sparse_optimizer("adam", lr=1e-3, weight_decay=0.0))
    return coll, backbone, state


def _export_seq(out_dir, coll, state, cfg=CFG, **kw):
    return export_bundle(
        out_dir, model="bert4rec", embed_dim=cfg.embed_dim, cat_columns=(),
        cont_columns=(), size_map={"n_items": cfg.n_items}, coll=coll,
        tables=state.tables, dense_params=state.dense_params,
        seq={"max_len": cfg.max_len, "n_heads": cfg.n_heads,
             "n_layers": cfg.n_layers}, **kw)


def _seq_batch(rng, n, cfg=CFG):
    """Ragged histories -> the eval window schema (appended MASK, left pad)
    plus a candidate panel — exactly what a live request carries."""
    seqs = np.stack([
        history_window(
            rng.integers(1, cfg.n_items + 1,
                         size=int(rng.integers(1, 2 * cfg.max_len))),
            n_items=cfg.n_items, max_len=cfg.max_len)
        for _ in range(n)])
    cands = rng.integers(1, cfg.n_items + 1,
                         size=(n, N_CANDS)).astype(np.int32)
    return {"seqs": seqs, "cands": cands}


def _eval_chain(coll, backbone):
    """The trainer's seq eval forward (train/trainer.py eval_accum): the
    bitwise reference every served score must reproduce."""

    @jax.jit
    def scores(state, batch):
        embs = coll.lookup(state.tables, {"item": batch["seqs"]},
                           mode="gspmd")
        logits = backbone.apply(
            {"params": state.dense_params}, embs["item"],
            key_padding_mask(batch["seqs"]))
        return score_candidates(logits, batch["cands"])

    return scores


# ------------------------------------------------------- train/serve skew


def test_seq_bundle_scores_match_eval_step(mesh8, tmp_path):
    """The zero-skew bar for the second model family: served masked-position
    candidate scores from a round-tripped bundle are BITWISE equal to the
    trainer's seq eval chain."""
    coll, backbone, state = _bert4rec_sparse(mesh8)
    batch = _seq_batch(np.random.default_rng(7), 16)
    ref = np.asarray(_eval_chain(coll, backbone)(
        state, {k: jnp.asarray(v) for k, v in batch.items()}))

    scorer = make_seq_scorer(
        load_bundle(_export_seq(tmp_path / "b", coll, state), verify=True),
        mesh=mesh8)
    got = np.asarray(scorer.score(dict(batch)))
    assert got.dtype == np.float32 and got.shape == (16, N_CANDS)
    np.testing.assert_array_equal(got.view(np.uint32), ref.view(np.uint32))


def test_seq_scoring_never_materializes_the_logits_cube(mesh8, tmp_path):
    """XLA does not sink the last-position slice into the vocab matmul, so
    an eval-shaped serving program would materialize the full [B, T, V]
    logits (420 GB at the bench profile).  The scorer applies out_proj to
    the [B, d] row slice instead; pin that the compiled program's largest
    f32 tensor stays an order of magnitude under the cube."""
    import re

    # vocab must dwarf the legit intermediates (FF hidden is [B, T, 4d]) so
    # the cube/10 bound separates them cleanly
    cfg = Bert4RecConfig(n_items=5000, max_len=16, embed_dim=16, n_heads=2,
                         n_layers=2)
    coll, backbone, state = _bert4rec_sparse(mesh8, cfg=cfg)
    bundle = load_bundle(_export_seq(tmp_path / "b", coll, state, cfg=cfg))
    scorer = make_seq_scorer(bundle, mesh=mesh8)

    n = 32
    batch = _seq_batch(np.random.default_rng(3), n, cfg=cfg)
    hlo = scorer._score.lower(
        {k: jnp.asarray(v) for k, v in batch.items()},
        *scorer._params).compile().as_text()
    largest = max(
        int(np.prod([int(d) for d in dims.split(",")]))
        for dims in re.findall(r"f32\[([0-9,]+)\]", hlo))
    cube = n * cfg.max_len * cfg.vocab_size
    assert largest < cube / 10, (
        f"largest compiled f32 tensor has {largest} elements — the serving "
        f"program is materializing at [B, T, V] cube scale ({cube})")


def test_make_scorer_dispatches_seq_family(mesh8, tmp_path):
    """Pointer followers (fleet replicas, swap controllers) build scorers
    through ONE entry point; bert4rec bundles must come back as the seq
    scorer with an empty continuous-column set."""
    coll, _, state = _bert4rec_sparse(mesh8)
    bundle = load_bundle(_export_seq(tmp_path / "b", coll, state))
    scorer = make_scorer(bundle, mesh=mesh8)
    assert isinstance(scorer, SeqScorer)
    assert scorer.model == "bert4rec" and scorer.cont_columns == ()
    assert scorer.features == ("seqs", "cands")
    assert scorer.max_len == CFG.max_len and scorer.n_items == CFG.n_items
    assert scorer.mask_id == CFG.n_items + 1


def test_query_embed_is_the_retrieval_head_query(mesh8, tmp_path):
    """``query_embed`` must be ``[h, 1]`` — the hidden state FEEDING
    out_proj with the constant that picks up the bias column: pushing it
    through the bias-folded output head by hand reproduces the served
    candidate scores (the identity next-item retrieval relies on; out_proj
    is UNTIED, so the input table would be the wrong head)."""
    coll, backbone, state = _bert4rec_sparse(mesh8)
    batch = _seq_batch(np.random.default_rng(11), 8)
    bundle = load_bundle(_export_seq(tmp_path / "b", coll, state))
    scorer = make_seq_scorer(bundle, mesh=mesh8)

    q = np.asarray(scorer.query_embed(dict(batch)))
    assert q.shape == (8, CFG.embed_dim + 1) and q.dtype == np.float32
    np.testing.assert_array_equal(q[:, -1], 1.0)
    W = np.asarray(bundle.dense_params["out_proj"]["kernel"])
    b = np.asarray(bundle.dense_params["out_proj"]["bias"])
    head = np.concatenate([W, b[None, :]], axis=0)  # [d+1, V]
    manual = np.take_along_axis(q @ head, batch["cands"], axis=1)
    ref = np.asarray(scorer.score(dict(batch)))
    np.testing.assert_allclose(manual, ref, rtol=2e-5, atol=2e-5)


# --------------------------------------------------------- bundle refusals


def _toy_bundle(**over):
    vocab = CFG.n_items + 2
    kw = dict(
        kind="sparse", model="bert4rec", embed_dim=CFG.embed_dim,
        cat_columns=(), cont_columns=(),
        size_map={"n_items": CFG.n_items}, step=0, dtype="float32",
        tables={"item_embedding": np.zeros((vocab, CFG.embed_dim),
                                           np.float32)},
        dense_params={}, params=None,
        seq={"max_len": CFG.max_len, "n_heads": CFG.n_heads,
             "n_layers": CFG.n_layers})
    kw.update(over)
    return ServingBundle(**kw)


@pytest.mark.parametrize("over,msg", [
    ({"model": "twotower"}, "CTR family"),
    ({"kind": "dense", "tables": None, "dense_params": None, "params": {}},
     "sparse"),
    ({"seq": None}, "no seq hyperparameters"),
    ({"seq": {"max_len": CFG.max_len}}, "missing"),
    ({"size_map": {}}, "needs n_items"),
    ({"tables": {"wrong_table": np.zeros((52, 16), np.float32)}},
     "do not match"),
    ({"size_map": {"n_items": CFG.n_items - 3}}, "vocab drift"),
], ids=["ctr-family", "dense-kind", "no-seq", "missing-keys", "no-n-items",
        "wrong-tables", "vocab-drift"])
def test_seq_scorer_refusals(over, msg):
    with pytest.raises(ValueError, match=msg):
        make_seq_scorer(_toy_bundle(**over))


def test_delta_export_refuses_seq_geometry_drift(mesh8, tmp_path):
    """``seq`` is a frozen manifest field: a delta whose max_len drifted
    would silently mis-position the appended MASK, so the chain refuses."""
    coll, _, state = _bert4rec_sparse(mesh8)
    base = _export_seq(tmp_path / "base", coll, state)
    with pytest.raises(ValueError, match="schema drift on 'seq'"):
        export_delta(
            tmp_path / "d1", base, model="bert4rec",
            embed_dim=CFG.embed_dim, cat_columns=(), cont_columns=(),
            size_map={"n_items": CFG.n_items}, step=1, coll=coll,
            tables=state.tables, dense_params=state.dense_params,
            seq={"max_len": CFG.max_len + 1, "n_heads": CFG.n_heads,
                 "n_layers": CFG.n_layers})


# --------------------------------------------------------- history windows


class TestHistoryWindow:
    """torchrec/preprocessing.py:229-239 applied to a live request:
    truncate LEFT (keep newest), append MASK, LEFT-pad with PAD_ID."""

    def test_long_history_keeps_newest(self):
        w = history_window(range(1, 21), n_items=50, max_len=8)
        np.testing.assert_array_equal(w, [14, 15, 16, 17, 18, 19, 20, 51])

    def test_short_history_left_pads(self):
        w = history_window([5, 9], n_items=50, max_len=8)
        np.testing.assert_array_equal(
            w, [PAD_ID] * 5 + [5, 9, 51])

    def test_empty_history_is_all_pad_plus_mask(self):
        w = history_window([], n_items=50, max_len=8)
        np.testing.assert_array_equal(w, [PAD_ID] * 7 + [51])

    def test_max_history_caps_the_window(self):
        w = history_window(range(1, 21), n_items=50, max_len=8,
                           max_history=3)
        np.testing.assert_array_equal(
            w, [PAD_ID] * 4 + [18, 19, 20, 51])

    def test_reserved_ids_refused(self):
        with pytest.raises(ValueError, match="reserved"):
            history_window([0, 3], n_items=50, max_len=8)
        with pytest.raises(ValueError, match="outside the catalog"):
            history_window([51], n_items=50, max_len=8)


# ------------------------------------------------- ragged-history batching


def test_microbatcher_seq_panels_and_compile_pin(mesh8, tmp_path):
    """Ragged seq traffic through the frontend's bucket batcher: 2-D panel
    columns pad/unpad row-wise like CTR columns, per-request scores match
    the direct scorer bitwise, and the jit cache stays <= len(buckets) —
    the bounded-compile contract that makes live serving viable."""
    coll, _, state = _bert4rec_sparse(mesh8)
    bundle = load_bundle(_export_seq(tmp_path / "b", coll, state))
    scorer = make_seq_scorer(bundle, mesh=mesh8)
    buckets = (2, 4, 8)
    mb = MicroBatcher(scorer.score, buckets=buckets, max_batch=8,
                      batch_deadline_ms=0.0,
                      program_cache_size=scorer.score_cache_size)
    rng = np.random.default_rng(23)
    requests = {f"r{i}": _seq_batch(rng, n)
                for i, n in enumerate([1, 3, 2, 5, 8, 4, 1, 7, 6, 2])}
    for rid, batch in requests.items():
        mb.submit(rid, batch)
        mb.poll()
    assert set(mb.results) == set(requests)
    assert scorer.score_cache_size() <= len(buckets)
    assert {p for _, p in mb.shipped} <= set(buckets)
    # reference scores through an INDEPENDENT scorer so the pinned cache
    # above only ever saw the batcher's bucketed shapes
    ref_scorer = make_seq_scorer(bundle, mesh=mesh8)
    for rid, batch in requests.items():
        ref = np.asarray(ref_scorer.score(dict(batch)))
        assert mb.results[rid].shape == ref.shape  # unpadded [n, C] panels
        np.testing.assert_array_equal(mb.results[rid], ref)


# ------------------------------------------------------ item-table corpus


def test_item_corpus_layout(mesh8, tmp_path):
    """Bias-folded out_proj columns 1..n_items (each row ``[W[:, v]; b_v]``,
    width d+1), 1-based catalog ids, PAD/MASK columns excluded, shard
    padding id -1 — ``build_corpus``'s alignment contract on the bundle's
    own output head."""
    coll, _, state = _bert4rec_sparse(mesh8)
    bundle = load_bundle(_export_seq(tmp_path / "b", coll, state))
    corpus = item_corpus(bundle, mesh=mesh8)
    assert corpus.n_items == CFG.n_items
    n_pad = -(-CFG.n_items // mesh8.shape["data"]) * mesh8.shape["data"]
    assert corpus.vectors.shape == (n_pad, CFG.embed_dim + 1)
    ids = np.asarray(corpus.ids)
    np.testing.assert_array_equal(ids[:CFG.n_items],
                                  np.arange(1, CFG.n_items + 1))
    assert (ids[CFG.n_items:] == -1).all()
    W = np.asarray(bundle.dense_params["out_proj"]["kernel"], np.float32)
    b = np.asarray(bundle.dense_params["out_proj"]["bias"], np.float32)
    head = np.concatenate([W.T, b[:, None]], axis=1)  # [V, d+1]
    np.testing.assert_array_equal(
        np.asarray(corpus.vectors)[:CFG.n_items],
        head[1:CFG.n_items + 1])
    with pytest.raises(ValueError, match="not in"):
        item_corpus(bundle, mesh=mesh8, dtype="int4")
    with pytest.raises(ValueError, match="no out_proj"):
        item_corpus(_toy_bundle())
    with pytest.raises(ValueError, match="head drift"):
        item_corpus(_toy_bundle(dense_params={"out_proj": {
            "kernel": np.zeros((CFG.embed_dim, CFG.n_items + 1), np.float32),
            "bias": np.zeros((CFG.n_items + 1,), np.float32)}}))


def test_item_retrieval_exact_matches_reference(mesh8, tmp_path):
    """Sharded exact MIPS over the item corpus, queried with the scorer's
    own last-position hidden states, is bitwise-equal (ids AND f32 scores)
    to the single-device stable-argsort reference."""
    coll, _, state = _bert4rec_sparse(mesh8)
    bundle = load_bundle(_export_seq(tmp_path / "b", coll, state))
    scorer = make_seq_scorer(bundle, mesh=mesh8)
    corpus = item_corpus(bundle, mesh=mesh8)
    q = scorer.query_embed(_seq_batch(np.random.default_rng(5), 16))
    for k in (1, 10):
        scores, ids = make_retrieval(corpus, mesh=mesh8, top_k=k)(q)
        ref_s, ref_i = retrieval_reference(q, corpus, top_k=k)
        np.testing.assert_array_equal(np.asarray(ids), np.asarray(ref_i))
        np.testing.assert_array_equal(
            np.asarray(scores).view(np.uint32),
            np.asarray(ref_s).view(np.uint32))


def test_item_retrieval_ranks_like_the_served_scores(mesh8, tmp_path):
    """THE identity the corpus exists for: MIPS top-k over ``item_corpus``
    agrees with the argsort of the SERVED full-catalog logits — ``score()``
    with every catalog item as a candidate.  out_proj is untied, so a
    corpus built from the input item table ranks by ``h @ e_v`` instead of
    ``h @ W[:, v] + b_v`` and fails this by a wide margin.  ``mips_scores``
    runs bf16 x bf16 -> f32 while ``score()`` is an f32 matmul, so adjacent
    ranks inside the bf16 rounding bound may legitimately swap: the
    retrieved items' exact logits must match the true top-k logits within
    that bound everywhere, and the id lists must agree exactly wherever the
    k-boundary gap exceeds it."""
    coll, _, state = _bert4rec_sparse(mesh8)
    bundle = load_bundle(_export_seq(tmp_path / "b", coll, state))
    scorer = make_seq_scorer(bundle, mesh=mesh8)
    corpus = item_corpus(bundle, mesh=mesh8)

    n = 16
    batch = _seq_batch(np.random.default_rng(13), n)
    catalog = np.arange(1, CFG.n_items + 1, dtype=np.int32)
    q = np.asarray(scorer.query_embed(dict(batch)))
    full = np.asarray(scorer.score(
        {"seqs": batch["seqs"], "cands": np.tile(catalog, (n, 1))}))
    # per-row bf16 dot-product error bound: sum_i |q_i||c_i| * 2^-7 covers
    # rounding both operands to bf16 (8-bit mantissa) before the f32 matmul
    head = np.asarray(jax.device_get(corpus.vectors))[:CFG.n_items]
    tol = (np.abs(q) @ np.abs(head).T).max(axis=1) * 2.0 ** -7  # [n]

    for k in (1, 10):
        _, ids_ret = make_retrieval(corpus, mesh=mesh8, top_k=k)(q)
        ids_ret = np.asarray(ids_ret)
        for row in range(n):
            order = np.argsort(-full[row], kind="stable")
            best = full[row, order[:k]]
            got = full[row, ids_ret[row] - 1]
            assert np.all(best - got <= tol[row]), (
                f"row {row} top-{k}: retrieved items' served logits trail "
                f"the true top-k by {(best - got).max()} > {tol[row]} — the "
                "corpus is not the output head")
            boundary_gap = full[row, order[k - 1]] - full[row, order[k]]
            if boundary_gap > 2 * tol[row]:
                assert set(map(int, ids_ret[row])) == \
                    set(map(int, catalog[order[:k]])), f"row {row} top-{k}"


def _recall(ids, ids_ref):
    hits = sum(len(set(map(int, a)) & set(map(int, b)))
               for a, b in zip(np.asarray(ids), np.asarray(ids_ref)))
    return hits / ids_ref.size


def test_item_corpus_int8_twostage_recall_floor(mesh8, tmp_path):
    """The PR-11 int8 two-stage path applies to the item corpus unchanged:
    coarse-over-codes + exact rerank at coarse_k = 4*top_k holds the same
    recall floor against the exact scan of the SAME int8 corpus."""
    coll, _, state = _bert4rec_sparse(mesh8)
    bundle = load_bundle(_export_seq(tmp_path / "b", coll, state))
    scorer = make_seq_scorer(bundle, mesh=mesh8)
    corpus = item_corpus(bundle, mesh=mesh8, dtype="int8")
    assert corpus.qscale is not None
    q = scorer.query_embed(_seq_batch(np.random.default_rng(9), 32))
    top_k = 10
    _, ids_two = make_retrieval(corpus, mesh=mesh8, top_k=top_k,
                                coarse_k=4 * top_k)(q)
    _, ids_ref = retrieval_reference(q, corpus, top_k=top_k)
    assert _recall(ids_two, np.asarray(ids_ref)) >= 0.95


# -------------------------------------------------------- replay seq panels


_REPLAY_SCHEMA = {"seqs": (np.int32, (CFG.max_len,)),
                  "cands": (np.int32, (5,))}


def _log_seq_records(root, rows_per_record, *, widths=None, cands_w=5):
    log = RequestLog(root)
    rng = np.random.default_rng(31)
    for r, n in enumerate(rows_per_record):
        w = CFG.max_len if widths is None else widths[r]
        log.append({
            "event": "serve_request", "request": f"q{r}", "rows": n,
            "outcome": "ok",
            "features": {
                "seqs": rng.integers(1, 51, (n, w)).astype(int).tolist(),
                "cands": rng.integers(1, 51, (n, cands_w)).astype(int).tolist(),
            },
        })
    log.seal_active()
    log.close()


def test_replay_forms_seq_panels(tmp_path):
    """Seq feature payloads (fixed-width per-row vectors) batch into
    deterministic [B, width] panels — the schema discipline that keeps every
    replayed batch shaped exactly like ``trainer._eval_schema``."""
    _log_seq_records(tmp_path, [4, 3, 5])
    con = ReplayConsumer(tmp_path, schema=_REPLAY_SCHEMA, batch_size=6)
    batch, consumed = con.next_batch()
    assert batch["seqs"].shape == (6, CFG.max_len)
    assert batch["cands"].shape == (6, 5)
    assert batch["seqs"].dtype == np.int32
    assert [(s, a, b) for s, a, b in consumed] == [(1, 0, 4), (2, 0, 2)]
    # 12 rows total: the second batch drains the log mid-record-free,
    # the third cannot fill and commits nothing (all-or-nothing)
    batch2, consumed2 = con.next_batch()
    assert batch2["seqs"].shape == (6, CFG.max_len)
    assert [(s, a, b) for s, a, b in consumed2] == [(2, 2, 3), (3, 0, 5)]
    assert con.next_batch() is None


def test_replay_quarantines_width_drift(tmp_path):
    """A record whose seq panel width drifted from the schema is BAD, not
    trainable — width drift would desync multihost lockstep downstream."""
    _log_seq_records(tmp_path, [3, 3, 3], widths=[8, 7, 8])
    con = ReplayConsumer(tmp_path, schema=_REPLAY_SCHEMA, batch_size=6,
                         max_bad_records=1)
    batch, consumed = con.next_batch()
    assert batch["seqs"].shape == (6, CFG.max_len)
    assert [s for s, _, _ in consumed] == [1, 3]  # record 2 quarantined
    assert con.counters()["replay/bad"] == 1.0


def test_replay_schema_rejects_ragged_and_high_rank():
    with pytest.raises(ValueError, match="fixed-width 1-D"):
        ReplayConsumer("/nonexistent",
                       schema={"seqs": (np.int32, (4, 4))}, batch_size=2)
    with pytest.raises(ValueError, match="fixed-width 1-D"):
        ReplayConsumer("/nonexistent",
                       schema={"seqs": (np.int32, (0,))}, batch_size=2)
