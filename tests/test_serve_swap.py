"""Zero-downtime serving tests: delta export chain, atomic hot-swap, recovery.

The ROADMAP acceptance chain, on the 8-device CPU mesh: train N steps ->
full export -> serve -> train M more -> delta export -> hot-swap -> served
logits BITWISE match a fresh full export at every version.  Around it, the
failure half: out-of-order / wrong-parent / corrupt deltas refused loudly,
corrupt payloads quarantined without crashing the frontend (degraded mode
after ``max_bad_deltas``), and a kill injected mid-apply (``[faults]
kill_during_swap``) whose restart recovers to the last verified version —
the serving twin of ``tests/test_faults.py``'s training kill/restart story.
"""

import json
import os
import subprocess
import sys
from pathlib import Path

import numpy as np
import optax
import pytest

import jax
import jax.numpy as jnp

from tdfo_tpu.models.twotower import TwoTowerBackbone, ctr_embedding_specs
from tdfo_tpu.ops.sparse import sparse_optimizer
from tdfo_tpu.parallel.embedding import ShardedEmbeddingCollection
from tdfo_tpu.serve.export import (
    bundle_digest,
    export_bundle,
    export_delta,
    load_bundle,
    read_raw_bundle,
    write_raw_bundle,
)
from tdfo_tpu.serve.frontend import MicroBatcher
from tdfo_tpu.serve.scoring import make_scorer
from tdfo_tpu.serve.swap import (
    BundleStore,
    CorruptDeltaError,
    DeltaChainError,
    DeltaPoller,
    SwapController,
    atomic_write_json,
)
from tdfo_tpu.train.ctr import ctr_sparse_forward, make_ctr_sparse_eval_step
from tdfo_tpu.train.sparse_step import SparseTrainState, make_sparse_train_step
from tdfo_tpu.utils import faults
from tdfo_tpu.utils.faults import FaultSpec
from tdfo_tpu.utils.retry import recent_failures, set_failure_log

# small even vocabs (2-shard model axis) so exports stay KB-scale; train
# batches touch a strict SUBSET of rows so deltas are genuinely sparse
SIZE_MAP = {"user": 32, "item": 24, "language": 8, "is_ebook": 2,
            "format": 8, "publisher": 16, "pub_decade": 16}
CAT_COLS = ("user_id", "item_id", "language", "is_ebook", "format",
            "publisher", "pub_decade")
CONT_COLS = ("avg_rating", "num_pages")
_INPUT = {"user": "user_id", "item": "item_id", "language": "language",
          "is_ebook": "is_ebook", "format": "format",
          "publisher": "publisher", "pub_decade": "pub_decade"}
D = 8


def _batch(rng, n, with_label=True):
    batch = {_INPUT[f]: rng.integers(0, v, n).astype(np.int32)
             for f, v in SIZE_MAP.items()}
    batch["avg_rating"] = rng.random(n).astype(np.float32)
    batch["num_pages"] = rng.random(n).astype(np.float32)
    if with_label:
        batch["label"] = rng.integers(0, 2, n).astype(np.float32)
    return batch


def _setup(mesh, seed=0):
    coll = ShardedEmbeddingCollection(
        ctr_embedding_specs(SIZE_MAP, D, "row", fused_threshold=None),
        mesh=mesh)
    backbone = TwoTowerBackbone(embed_dim=D)
    tables = coll.init(jax.random.key(seed))
    dummy_e = {f: jnp.zeros((1, D), jnp.float32) for f in coll.features()}
    dummy_c = {c: jnp.zeros((1,), jnp.float32) for c in CONT_COLS}
    state = SparseTrainState.create(
        dense_params=backbone.init(jax.random.key(seed + 1),
                                   dummy_e, dummy_c)["params"],
        tx=optax.adamw(1e-2), tables=tables,
        sparse_opt=sparse_optimizer("adam", lr=1e-2, weight_decay=0.0))
    step = make_sparse_train_step(coll, ctr_sparse_forward(backbone),
                                  donate=False)
    return coll, backbone, state, step


def _train(state, step, rng, k, n=8):
    for _ in range(k):
        state, _ = step(state, {k2: jnp.asarray(v)
                                for k2, v in _batch(rng, n).items()})
    return state


def _export_kw(coll, state):
    return dict(model="twotower", embed_dim=D, cat_columns=CAT_COLS,
                cont_columns=CONT_COLS, size_map=SIZE_MAP, coll=coll,
                tables=state.tables, dense_params=state.dense_params)


# --------------------------------------------------------- digest contract


def test_bundle_digest_and_verified_load(mesh8, tmp_path):
    """Manifests carry version + content digest; ``load_bundle(verify=True)``
    accepts the genuine bundle and refuses a bit-flipped payload."""
    coll, _, state, _ = _setup(mesh8)
    out = export_bundle(tmp_path / "b", step=3, version=5,
                        **_export_kw(coll, state))
    manifest, arrays = read_raw_bundle(out)
    assert manifest["version"] == 5
    assert manifest["digest"] == bundle_digest(manifest, arrays)
    b = load_bundle(out, verify=True)
    assert (b.version, b.digest, b.step) == (5, manifest["digest"], 3)

    key = sorted(arrays)[0]
    flipped = np.array(arrays[key])
    flipped.view(np.uint8).reshape(-1)[0] ^= 0xFF
    write_raw_bundle(out, manifest, dict(arrays, **{key: flipped}))
    with pytest.raises(ValueError, match="corrupt bundle"):
        load_bundle(out, verify=True)


def test_delta_export_refuses_drift_and_stale_hint(mesh8, tmp_path):
    coll, _, state, step = _setup(mesh8)
    base = export_bundle(tmp_path / "v0", step=0, **_export_kw(coll, state))
    state2 = _train(state, step, np.random.default_rng(0), 1)

    with pytest.raises(ValueError, match="schema drift"):
        export_delta(tmp_path / "bad", base, step=1,
                     **dict(_export_kw(coll, state2), embed_dim=D,
                            cont_columns=("avg_rating",)))
    # a touched-row hint that misses changed rows must refuse, not under-ship
    with pytest.raises(ValueError, match="stale"):
        export_delta(tmp_path / "bad2", base, step=1,
                     touched={n: np.array([], np.int64) for n in SIZE_MAP},
                     **_export_kw(coll, state2))


# ------------------------------------------------- the ROADMAP chain test


def test_delta_chain_hot_swap_bitwise(mesh8, tmp_path):
    """train -> full export -> serve -> train more -> delta export -> swap:
    at every version the store's composed bundle has the SAME digest and
    bytes as a fresh full export, and the logits served through the live
    MicroBatcher are bitwise a fresh-full-export scorer's (and track the
    training eval step to float tolerance — exact bitwise equality with the
    eval step holds only for replicated fresh-init states; trained states
    carry jit-output shardings that legally reorder reductions)."""
    coll, backbone, state, step = _setup(mesh8)
    eval_step = make_ctr_sparse_eval_step(coll, backbone)
    rng = np.random.default_rng(1)
    qbatch = _batch(np.random.default_rng(99), 16)
    feats = {k: v for k, v in qbatch.items() if k != "label"}

    state = _train(state, step, rng, 2)
    chain = tmp_path / "chain"
    full0 = export_bundle(chain / "v000000", step=2, version=0,
                          **_export_kw(coll, state))
    store = BundleStore(tmp_path / "store")
    assert store.ingest_full(full0) == 0

    scorer = make_scorer(load_bundle(store.current_dir(), verify=True),
                         mesh=mesh8)
    mb = MicroBatcher(scorer.score, buckets=(16, 32), max_batch=32,
                      batch_deadline_ms=0.0)
    ctrl = SwapController(
        store,
        lambda d: make_scorer(load_bundle(d, verify=True), mesh=mesh8).score,
        batcher=mb)

    def served(rid):
        mb.submit(rid, feats)
        mb.poll()
        return np.asarray(mb.results[rid])

    _, ref = eval_step(state, {k: jnp.asarray(v) for k, v in qbatch.items()})
    want0 = np.asarray(make_scorer(load_bundle(full0, verify=True),
                                   mesh=mesh8).score(feats))
    got0 = served("q0")
    np.testing.assert_array_equal(got0, want0)
    np.testing.assert_allclose(got0, np.asarray(ref), rtol=1e-5,
                               atol=1e-7)

    prev = full0
    for v in (1, 2):
        state = _train(state, step, rng, 1)
        delta = export_delta(chain / f"v{v:06d}", prev, step=2 + v,
                             **_export_kw(coll, state))
        dmanifest, _ = read_raw_bundle(delta)
        assert dmanifest["version"] == v
        assert dmanifest["parent_version"] == v - 1
        # the delta is genuinely sparse: a 1-step train batch of 8 rows
        # touches at most 8 rows per table
        assert dmanifest["tables_delta"]
        assert all(c <= 8 for c in dmanifest["tables_delta"].values())

        fresh = export_bundle(tmp_path / f"fresh{v}", step=2 + v, version=v,
                              **_export_kw(coll, state))
        assert ctrl.apply(delta) is True
        assert store.current_version() == v
        m_store, a_store = read_raw_bundle(store.current_dir())
        m_fresh, a_fresh = read_raw_bundle(fresh)
        assert m_store["digest"] == m_fresh["digest"]
        assert set(a_store) == set(a_fresh)
        for k in a_fresh:
            np.testing.assert_array_equal(a_store[k], a_fresh[k])

        _, ref = eval_step(state,
                           {k: jnp.asarray(v2) for k, v2 in qbatch.items()})
        want = np.asarray(make_scorer(load_bundle(fresh, verify=True),
                                      mesh=mesh8).score(feats))
        got = served(f"q{v}")
        np.testing.assert_array_equal(got, want)
        np.testing.assert_allclose(got, np.asarray(ref), rtol=1e-5,
                                   atol=1e-7)
        prev = fresh
    assert [s["version"] for s in mb.swaps] == [1, 2]
    assert all(s["swap_ms"] >= 0.0 for s in mb.swaps)


def test_delta_chain_refusals(mesh8, tmp_path):
    """Gaps, re-orders, wrong parents, and tampered parents are refused
    loudly — CURRENT never moves on a refused apply."""
    coll, _, state, step = _setup(mesh8)
    rng = np.random.default_rng(2)
    kw = lambda s: _export_kw(coll, s)  # noqa: E731

    full0 = export_bundle(tmp_path / "v0", step=0, **kw(state))
    state1 = _train(state, step, rng, 1)
    delta1 = export_delta(tmp_path / "d1", full0, step=1, **kw(state1))
    full1 = export_bundle(tmp_path / "full1", step=1, version=1, **kw(state1))
    state2 = _train(state1, step, rng, 1)
    delta2 = export_delta(tmp_path / "d2", full1, step=2, **kw(state2))

    store = BundleStore(tmp_path / "store")
    store.ingest_full(full0)
    with pytest.raises(DeltaChainError, match="out of order"):
        store.apply_delta(delta2)  # gap: v2 onto v0
    assert store.current_version() == 0
    assert store.apply_delta(delta1) == 1
    with pytest.raises(DeltaChainError, match="out of order"):
        store.apply_delta(delta1)  # re-order: v1 onto v1
    with pytest.raises(ValueError, match="not a delta"):
        store.apply_delta(full1)
    with pytest.raises(ValueError, match="stale full export"):
        store.ingest_full(full0)

    # a delta exported against a DIFFERENT v1 than the one being served:
    # same version arithmetic, wrong parent digest
    other1 = _train(state, step, np.random.default_rng(77), 1)
    otherfull = export_bundle(tmp_path / "other1", step=1, version=1,
                              **kw(other1))
    rogue = export_delta(tmp_path / "rogue", otherfull, step=2,
                         **kw(_train(other1, step, rng, 1)))
    with pytest.raises(DeltaChainError, match="parent digest"):
        store.apply_delta(rogue)

    # corrupted parent: tamper the SERVED version's arrays (manifest digest
    # intact) — the base is re-verified before composing, never served on
    cur = store.current_dir()
    m, a = read_raw_bundle(cur)
    key = sorted(k for k in a if k.startswith("table:"))[0]
    t = np.array(a[key])
    t.view(np.uint8).reshape(-1)[0] ^= 0xFF
    write_raw_bundle(cur, m, dict(a, **{key: t}))
    with pytest.raises(CorruptDeltaError, match="corrupt base"):
        store.apply_delta(delta2)
    assert store.current_version() == 1


# ------------------------------------------ quarantine + degraded + polling


def test_corrupt_delta_quarantined_degraded_then_recovers(mesh8, tmp_path):
    """[faults] corrupt_delta_nth: the Nth delta read is bit-flipped in
    memory.  The frontend quarantines it, keeps serving the last good
    version, flips degraded mode after max_bad_deltas, and a later good
    apply clears the flag — never a crash."""
    from tdfo_tpu.obs.watchdog import StallWatchdog
    from tdfo_tpu.train.trainer import MetricLogger

    coll, _, state, step = _setup(mesh8)
    full0 = export_bundle(tmp_path / "v0", step=0, **_export_kw(coll, state))
    state1 = _train(state, step, np.random.default_rng(3), 1)
    delta1 = export_delta(tmp_path / "d1", full0, step=1,
                          **_export_kw(coll, state1))

    store = BundleStore(tmp_path / "store")
    store.ingest_full(full0)
    wd = StallWatchdog(tmp_path / "hb.jsonl", 60.0, label="serve",
                       clock=lambda: 0.0)
    logger = MetricLogger(tmp_path / "mlog")
    ctrl = SwapController(store, lambda d: (lambda b: b), batcher=None,
                          max_bad_deltas=1, logger=logger, watchdog=wd)
    try:
        faults.configure(FaultSpec(corrupt_delta_nth=1))
        assert ctrl.apply(delta1) is False  # quarantined, not raised
    finally:
        faults.configure(None)
    assert store.current_version() == 0  # still serving the last good
    assert ctrl.degraded and ctrl.consecutive_bad == 1
    q = store.quarantined()
    assert len(q) == 1 and q[0]["path"] == str(delta1)
    wd.check()
    hb = [json.loads(line) for line in
          (tmp_path / "hb.jsonl").read_text().splitlines()]
    assert hb[-1]["degraded"] is True and hb[-1]["bad_deltas"] == 1
    assert hb[-1]["label"] == "serve"

    # the poller never re-feeds a quarantined path: stage the successor in a
    # chain root, quarantine it, and confirm poll() refuses to touch it
    chain = tmp_path / "chain"
    nxt = chain / "v000001"
    nxt.mkdir(parents=True)
    (nxt / "bundle.json").write_text("{}")
    store.record_quarantine(nxt, "poisoned")
    poller = DeltaPoller(chain, poll_s=0.0, clock=lambda: 0.0)
    assert ctrl.poll(poller) is False
    assert store.current_version() == 0

    # the delta on disk was never corrupt — a direct re-apply (operator
    # retry) succeeds and clears degraded mode
    assert ctrl.apply(delta1) is True
    assert store.current_version() == 1
    assert not ctrl.degraded and ctrl.consecutive_bad == 0
    wd.check()
    hb = [json.loads(line) for line in
          (tmp_path / "hb.jsonl").read_text().splitlines()]
    assert hb[-1]["degraded"] is False
    logger.close()
    events = [json.loads(line) for line in
              (tmp_path / "mlog" / "metrics.jsonl").read_text().splitlines()]
    kinds = [e.get("event") for e in events]
    assert "delta_quarantined" in kinds and "serving_degraded" in kinds


def test_poller_cadence_and_discovery(tmp_path):
    """swap_poll_s is the poll cadence (injectable clock), and discovery
    finds exactly the successor version directory."""
    now = [0.0]
    p = DeltaPoller(tmp_path, poll_s=2.0, clock=lambda: now[0])
    assert p.due() is True  # first poll immediate
    assert p.due() is False
    now[0] = 1.9
    assert p.due() is False
    now[0] = 2.0
    assert p.due() is True

    assert p.next_delta(0) is None
    (tmp_path / "v000001").mkdir()
    assert p.next_delta(0) is None  # no manifest yet -> not discoverable
    (tmp_path / "v000001" / "bundle.json").write_text("{}")
    assert p.next_delta(0) == tmp_path / "v000001"
    assert p.next_delta(1) is None


def test_poller_backwards_clock_jump_rearms(tmp_path):
    """An NTP step / VM migration moves the injectable clock BACKWARDS: the
    poller must re-arm relative to the new epoch, not stall until the old
    deadline is reached again (hours of frozen swaps)."""
    now = [1000.0]
    p = DeltaPoller(tmp_path, poll_s=2.0, clock=lambda: now[0])
    assert p.due() is True
    now[0] = 100.0  # 900 s backwards; old deadline 1002.0 is unreachable
    assert p.due() is False  # the jump tick re-arms, it does not fire
    now[0] = 101.9
    assert p.due() is False  # cadence contract holds in the new epoch
    now[0] = 102.0
    assert p.due() is True  # ...and polling resumes one interval later
    # a small backwards wobble (< one interval) is NOT a jump: the armed
    # deadline stays valid and fires on schedule
    now[0] = 101.0
    assert p.due() is False
    now[0] = 104.0
    assert p.due() is True


@pytest.mark.parametrize("poll_s", [0.0, -1.0])
def test_poller_degenerate_interval_never_stalls(tmp_path, poll_s):
    """swap_poll_s <= 0 degenerates to 'always due': every tick polls, and
    neither a frozen nor a backwards clock can wedge the gate."""
    now = [50.0]
    p = DeltaPoller(tmp_path, poll_s=poll_s, clock=lambda: now[0])
    for t in (50.0, 50.0, 10.0, 1e9, -5.0):
        now[0] = t
        assert p.due() is True


# --------------------------------------------------- durability primitives


def _toy_bundle(out, version, seed=0, corrupt=False):
    """A tiny hand-built dense-kind bundle with a valid digest."""
    rng = np.random.default_rng(seed + version)
    manifest = {"bundle_version": 1, "kind": "dense", "model": "twotower",
                "embed_dim": 4, "cat_columns": [], "cont_columns": [],
                "size_map": {}, "step": version, "dtype": "float32",
                "version": version}
    arrays = {"params:w": rng.random((4, 4)).astype(np.float32)}
    manifest["digest"] = bundle_digest(manifest, arrays)
    if corrupt:
        arrays["params:w"] = arrays["params:w"] + 1.0
    return write_raw_bundle(out, manifest, arrays)


def test_atomic_write_json(tmp_path):
    path = tmp_path / "CURRENT"
    atomic_write_json(path, {"version": 1})
    atomic_write_json(path, {"version": 2})
    assert json.loads(path.read_text()) == {"version": 2}
    assert list(tmp_path.glob("*.tmp")) == []


def test_recovery_picks_last_verified(tmp_path):
    """Restart semantics: stray staging dirs are cleaned, a corrupt newest
    version is pruned, and CURRENT re-points at the newest version whose
    digest verifies."""
    store = BundleStore(tmp_path / "store")
    assert store.recover() is None
    store.ingest_full(_toy_bundle(tmp_path / "b0", 0))
    store.ingest_full(_toy_bundle(tmp_path / "b1", 1))
    assert store.current_version() == 1

    # simulate a crash mid-apply: a staged-but-unpublished successor plus a
    # stale CURRENT pointing at a version whose bytes were later torn
    (store.versions / "v000002.tmp").mkdir()
    (store.versions / "v000002.tmp" / "arrays.npz").write_bytes(b"partial")
    v1 = store.versions / "v000001"
    (v1 / "arrays.npz").write_bytes(b"torn")
    assert store.recover() == 0
    assert store.current_version() == 0
    assert not (store.versions / "v000002.tmp").exists()
    assert not v1.exists()  # pruned: unreachable corrupt version
    # the survivor still verifies end to end
    m, a = read_raw_bundle(store.current_dir())
    assert bundle_digest(m, a) == m["digest"]


def test_ingest_refuses_corrupt_full(tmp_path):
    store = BundleStore(tmp_path / "store")
    with pytest.raises(ValueError, match="corrupt bundle"):
        store.ingest_full(_toy_bundle(tmp_path / "bad", 0, corrupt=True))


def test_bundle_load_retry_flows_to_jsonl(tmp_path):
    """[faults] fail_io_nth: the first store read raises an injected OSError,
    the retry succeeds, and the failure record lands in retries.jsonl — the
    serve path shares the training I/O discipline (utils/retry.py)."""
    store = BundleStore(tmp_path / "store")
    log = tmp_path / "retries.jsonl"
    try:
        set_failure_log(log)
        faults.configure(FaultSpec(fail_io_nth=1))
        assert store.ingest_full(_toy_bundle(tmp_path / "b0", 0)) == 0
    finally:
        faults.configure(None)
        set_failure_log(None)
    recs = [json.loads(line) for line in log.read_text().splitlines()]
    assert any("full bundle read" in r["description"] and not r["final"]
               for r in recs)
    assert any("full bundle read" in r["description"]
               for r in recent_failures())


def test_backoff_delay_cap_and_jitter():
    import random

    from tdfo_tpu.utils.retry import backoff_delay

    # deterministic growth then cap, jitter off
    bare = [backoff_delay(a, base_delay=0.1, max_delay=1.0, jitter=0.0)
            for a in range(6)]
    assert bare == [0.1, 0.2, 0.4, 0.8, 1.0, 1.0]
    # jitter spreads within [d, d * (1 + jitter)], injectable rng
    rng = random.Random(0)
    for a in range(6):
        d = backoff_delay(a, base_delay=0.1, max_delay=1.0, jitter=0.5,
                          rng=rng)
        assert bare[a] <= d <= bare[a] * 1.5
    with pytest.raises(ValueError, match="attempt"):
        backoff_delay(-1)


# ------------------------------------- canary pointer lifecycle + retention


def _toy_delta(out, store, seed=0):
    """A hand-built dense-kind delta extending the store's CURRENT: replaces
    ``params:w`` whole (dense arrays ship whole, ``export.py`` contract)."""
    base_m, base_a = read_raw_bundle(store.current_dir())
    rng = np.random.default_rng(seed + 100 + base_m["version"])
    new_w = rng.random((4, 4)).astype(np.float32)
    out_m = {k: v for k, v in base_m.items() if k != "digest"}
    out_m["version"] = base_m["version"] + 1
    out_m["step"] = base_m["version"] + 1
    result_digest = bundle_digest(out_m, {"params:w": new_w})
    dm = {"bundle_version": 1, "kind": "delta", "base_kind": "dense",
          "model": "twotower", "step": out_m["step"], "dtype": "float32",
          "version": out_m["version"],
          "parent_version": base_m["version"],
          "parent_digest": base_m["digest"],
          "result_digest": result_digest, "tables_delta": {},
          "replaced": ["params:w"]}
    da = {"params:w": new_w}
    dm["digest"] = bundle_digest(dm, da)
    return write_raw_bundle(out, dm, da)


def _digest_of(path):
    m, _ = read_raw_bundle(path)
    return m["digest"]


def test_canary_publish_promote_lifecycle(tmp_path):
    """publish_canary leaves CURRENT untouched while CANARY names the
    candidate; promote_canary advances CURRENT to the digest-verified
    candidate and clears CANARY.  Both are idempotent redo targets."""
    store = BundleStore(tmp_path / "store")
    store.ingest_full(_toy_bundle(tmp_path / "b0", 0))
    delta = _toy_delta(tmp_path / "d1", store)
    assert store.publish_canary(delta) == 1
    assert store.current_version() == 0  # the fleet majority is untouched
    assert store.canary_version() == 1
    assert (store.canary_dir() / "bundle.json").exists()
    # redo (crashed supervisor re-runs the same publish): same outcome
    assert store.publish_canary(delta) == 1
    assert store.current_version() == 0 and store.canary_version() == 1

    assert store.promote_canary() == 1
    assert store.current_version() == 1
    assert store.canary_version() is None
    assert store.promote_canary() == 1  # idempotent: nothing pending


def test_canary_rollback_records_and_reuses_version(tmp_path):
    """rollback_canary ledgers the rejection, deletes the candidate dir,
    and frees the version NUMBER for the next candidate — whose different
    bytes at the same version must publish and promote cleanly."""
    store = BundleStore(tmp_path / "store")
    store.ingest_full(_toy_bundle(tmp_path / "b0", 0))
    bad = _toy_delta(tmp_path / "bad", store, seed=1)
    store.publish_canary(bad)
    bad_digest = _digest_of(store.versions / "v000001")
    assert store.rollback_canary("canary AUC regression") == 0
    assert store.canary_version() is None
    assert not (store.versions / "v000001").exists()
    rej = store.rejections()
    assert [r["version"] for r in rej] == [1]
    assert rej[0]["digest"] == bad_digest
    assert rej[0]["reason"] == "canary AUC regression"
    # rollback is idempotent: redo records nothing twice
    store.rollback_canary("canary AUC regression")
    assert len(store.rejections()) == 1

    good = _toy_delta(tmp_path / "good", store, seed=2)
    assert _digest_of(good) != _digest_of(bad)
    assert store.publish_canary(good) == 1  # the NUMBER is reusable
    assert store.promote_canary() == 1
    assert store.current_version() == 1
    assert _digest_of(store.current_dir()) != bad_digest


def test_recover_clears_pointer_only_canary(tmp_path):
    """The publish_canary crash window: pointer written, directory never
    published.  recover() clears the dangling pointer and leaves CURRENT
    alone — the supervisor's redo republishes identical bytes."""
    store = BundleStore(tmp_path / "store")
    store.ingest_full(_toy_bundle(tmp_path / "b0", 0))
    atomic_write_json(store.root / "CANARY",
                      {"version": 1, "digest": "f" * 16})
    assert store.recover() == 0
    assert store.canary_version() is None
    assert store.current_version() == 0


def test_recover_never_adopts_unvetted_canary(tmp_path):
    """A crash mid-watch leaves a fully-published, digest-valid canary
    directory.  recover() must NOT adopt it as CURRENT (it is staged but
    unvetted); the pointer and directory survive for the supervisor's
    verdict redo."""
    store = BundleStore(tmp_path / "store")
    store.ingest_full(_toy_bundle(tmp_path / "b0", 0))
    store.publish_canary(_toy_delta(tmp_path / "d1", store))
    assert store.recover() == 0  # newest-first walk skipped the canary
    assert store.current_version() == 0
    assert store.canary_version() == 1
    assert (store.versions / "v000001" / "bundle.json").exists()


def test_recover_finishes_crashed_promotion(tmp_path):
    """Promotion writes CURRENT first, then clears CANARY.  A kill in
    between leaves canary <= current: recover() treats that as a COMPLETED
    promotion — clears the stale pointer, never regresses CURRENT."""
    store = BundleStore(tmp_path / "store")
    store.ingest_full(_toy_bundle(tmp_path / "b0", 0))
    store.publish_canary(_toy_delta(tmp_path / "d1", store))
    can = json.loads((store.root / "CANARY").read_text())
    atomic_write_json(store.root / "CURRENT", can)  # promote's first half
    assert store.recover() == 1
    assert store.current_version() == 1
    assert store.canary_version() is None


def test_recover_finishes_crashed_rollback(tmp_path):
    """Rollback records the rejection FIRST; a kill before the directory
    delete leaves the rejected bytes published.  recover() prunes them by
    (version, digest) and never re-adopts."""
    store = BundleStore(tmp_path / "store")
    store.ingest_full(_toy_bundle(tmp_path / "b0", 0))
    store.publish_canary(_toy_delta(tmp_path / "d1", store))
    digest = _digest_of(store.versions / "v000001")
    store._record_rejection(1, digest, "canary AUC regression")
    # ...crash here: dir + CANARY pointer still on disk
    assert store.recover() == 0
    assert store.current_version() == 0
    assert store.canary_version() is None
    assert not (store.versions / "v000001").exists()


def test_recover_rejects_corrupt_canary_bytes(tmp_path):
    store = BundleStore(tmp_path / "store")
    store.ingest_full(_toy_bundle(tmp_path / "b0", 0))
    store.publish_canary(_toy_delta(tmp_path / "d1", store))
    vdir = store.versions / "v000001"
    m, a = read_raw_bundle(vdir)
    t = np.array(a["params:w"])
    t.view(np.uint8).reshape(-1)[0] ^= 0xFF
    write_raw_bundle(vdir, m, dict(a, **{"params:w": t}))
    assert store.recover() == 0
    assert store.canary_version() is None  # torn candidate: redo republishes
    assert not vdir.exists()


def test_keep_versions_gc_protects_live_chain(tmp_path):
    """[serving] keep_versions retention: promotes prune history beyond the
    budget but NEVER the current, canary, or last-good directories."""
    store = BundleStore(tmp_path / "store", keep_versions=2)
    store.ingest_full(_toy_bundle(tmp_path / "b0", 0))
    for v in (1, 2, 3, 4):
        store.publish_canary(_toy_delta(tmp_path / f"d{v}", store, seed=v))
        assert store.promote_canary() == v
    live = sorted(p.name for p in store.versions.iterdir())
    # v4 is CURRENT (protected), v3+v2 are the retention budget
    assert live == ["v000002", "v000003", "v000004"]

    # a pending canary is protected OUTSIDE the budget: it neither counts
    # as a survivor nor gets pruned while the watch runs
    store.publish_canary(_toy_delta(tmp_path / "d5", store, seed=5))
    assert store.gc_versions() == []
    assert sorted(p.name for p in store.versions.iterdir()) == \
        ["v000002", "v000003", "v000004", "v000005"]
    # promoting it slides the retention window by one
    assert store.promote_canary() == 5
    assert sorted(p.name for p in store.versions.iterdir()) == \
        ["v000003", "v000004", "v000005"]


def test_keep_versions_zero_disables_gc(tmp_path):
    store = BundleStore(tmp_path / "store")  # keep_versions=0
    store.ingest_full(_toy_bundle(tmp_path / "b0", 0))
    for v in (1, 2, 3):
        store.publish_canary(_toy_delta(tmp_path / f"d{v}", store, seed=v))
        store.promote_canary()
    assert len(list(store.versions.iterdir())) == 4  # everything kept
    assert store.gc_versions() == []


def test_gc_refuses_while_current_corrupt(tmp_path):
    """The sweep digest-verifies CURRENT first: with a corrupt serving
    head, history is fallback material and nothing is deleted."""
    store = BundleStore(tmp_path / "store", keep_versions=1)
    store.ingest_full(_toy_bundle(tmp_path / "b0", 0))
    for v in (1, 2, 3):
        store.publish_canary(_toy_delta(tmp_path / f"d{v}", store, seed=v))
        store.promote_canary()
    cur = store.current_dir()
    m, a = read_raw_bundle(cur)
    t = np.array(a["params:w"])
    t.view(np.uint8).reshape(-1)[0] ^= 0xFF
    write_raw_bundle(cur, m, dict(a, **{"params:w": t}))
    assert store.gc_versions() == []  # refuse: the head cannot be trusted
    # recover() falls back to the newest intact version, THEN sweeps
    assert store.recover() == 2
    assert not cur.exists()


def test_swap_controller_degraded_clears_via_poll_repair(tmp_path):
    """Satellite regression: a frontend driven into degraded mode by real
    corrupt deltas must recover WITHOUT an operator poke when the exporter
    re-writes good bytes at the same quarantined chain position — the
    ``SwapController.poll`` on-disk re-verification path."""
    store = BundleStore(tmp_path / "store")
    store.ingest_full(_toy_bundle(tmp_path / "b0", 0))
    chain = tmp_path / "chain"
    delta = _toy_delta(chain / "v000001", store)
    good = read_raw_bundle(delta)

    ctrl = SwapController(store, lambda d: (lambda b: b), batcher=None,
                          max_bad_deltas=2)
    poller = DeltaPoller(chain, poll_s=0.0, clock=lambda: 0.0)
    try:
        # TWO real corrupt reads (bit-flipped in memory) through the poll
        # path: quarantined both times, degraded flips at the budget
        faults.configure(FaultSpec(corrupt_delta_nth=1))
        assert ctrl.poll(poller) is False
        assert ctrl.consecutive_bad == 1 and not ctrl.degraded
        faults.configure(FaultSpec(corrupt_delta_nth=1))
        assert ctrl.poll(poller) is False
    finally:
        faults.configure(None)
    assert ctrl.degraded and ctrl.consecutive_bad == 2
    assert store.current_version() == 0
    assert {q["path"] for q in store.quarantined()} == {str(delta)}

    # the exporter heals the chain position with verifiably good bytes;
    # the very next poll re-verifies, applies, and clears degraded mode
    write_raw_bundle(delta, *good)
    assert ctrl.poll(poller) is True
    assert store.current_version() == 1
    assert not ctrl.degraded and ctrl.consecutive_bad == 0


def test_swap_controller_poll_skips_still_corrupt_quarantined(tmp_path):
    """The re-verification gate's other half: a quarantined path whose
    bytes are STILL corrupt on disk is never re-applied (no quarantine
    loop), and the store keeps serving the last good version."""
    store = BundleStore(tmp_path / "store")
    store.ingest_full(_toy_bundle(tmp_path / "b0", 0))
    chain = tmp_path / "chain"
    delta = _toy_delta(chain / "v000001", store)
    m, a = read_raw_bundle(delta)
    bad = np.array(a["params:w"])
    bad.view(np.uint8).reshape(-1)[0] ^= 0xFF
    write_raw_bundle(delta, m, dict(a, **{"params:w": bad}))  # torn on DISK

    ctrl = SwapController(store, lambda d: (lambda b: b), batcher=None)
    poller = DeltaPoller(chain, poll_s=0.0, clock=lambda: 0.0)
    assert ctrl.poll(poller) is False  # quarantined on first contact
    assert ctrl.consecutive_bad == 1
    for _ in range(3):
        assert ctrl.poll(poller) is False  # still-bad bytes: gate holds
    assert ctrl.consecutive_bad == 1  # no re-apply, no counter churn
    assert store.current_version() == 0


# ------------------------------------------------- kill/restart mid-swap


def test_kill_during_swap_then_restart_recovers(mesh8, tmp_path):
    """[faults] kill_during_swap: run 1 dies (exit 17) with the composed
    v1 staged but unpublished; run 2 of the SAME command recovers to the
    verified v0, re-applies, and proves the composed bundle + its logits
    bitwise-equal a fresh full export — the serving twin of the trainer's
    kill/restart convergence."""
    coll, _, state, step = _setup(mesh8)
    root = tmp_path
    export_bundle(root / "full_v0", step=0, **_export_kw(coll, state))
    state1 = _train(state, step, np.random.default_rng(5), 1)
    export_delta(root / "delta_v1", root / "full_v0", step=1,
                 **_export_kw(coll, state1))
    export_bundle(root / "full_v1", step=1, version=1,
                  **_export_kw(coll, state1))
    np.savez(root / "batch.npz",
             **{k: v for k, v in _batch(np.random.default_rng(6), 8,
                                        with_label=False).items()})

    worker = Path(__file__).parent / "swap_worker.py"
    cmd = [sys.executable, str(worker), str(root)]
    env = os.environ.copy()
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = (f"{Path(__file__).parents[1]}{os.pathsep}"
                         + env.get("PYTHONPATH", ""))

    run1 = subprocess.run(cmd, capture_output=True, text=True, timeout=300,
                          env=env)
    assert run1.returncode == faults.KILL_EXIT_CODE, run1.stderr
    store = BundleStore(root / "store")
    assert store.current_version() == 0  # CURRENT untouched by the crash
    assert list(store.versions.glob("*.tmp"))  # half-applied staging left

    run2 = subprocess.run(cmd, capture_output=True, text=True, timeout=300,
                          env=env)
    assert run2.returncode == 0, run2.stderr
    out = json.loads(run2.stdout.splitlines()[-1])
    assert out == {"recovered": 0, "version": 1, "ok": True}
    assert store.current_version() == 1
    assert not list(store.versions.glob("*.tmp"))
