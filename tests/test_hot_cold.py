"""Frequency-partitioned hot/cold embedding tests.

The hot/cold mode must be a pure LAYOUT optimisation: same-seed runs with
and without the split produce the same trajectory (losses and effective
tables) for every optimizer kind, across routing flavours (contiguous
prefix, scattered set, fully hot) and both forward paths (dedup_lookup
on/off).  The artifact pipeline (counts -> hot_ids.json -> collection ->
checkpoint stamps) is covered end to end.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from tdfo_tpu.data.hot_ids import (
    hot_ids_from_counts,
    hot_ids_digest,
    load_hot_ids,
    write_hot_ids,
)
from tdfo_tpu.ops.sparse import (
    dedupe_grads,
    sparse_adagrad,
    sparse_adam,
    sparse_optimizer,
    sparse_rowwise_adagrad,
    sparse_sgd,
)
from tdfo_tpu.parallel.embedding import EmbeddingSpec, ShardedEmbeddingCollection

# --------------------------------------------------------------- artifacts


def test_hot_ids_from_counts_coverage_cut():
    # 80% of mass on id 3, the rest uniform: hot_fraction=0.5 takes just it
    counts = np.array([1, 1, 1, 12, 1], np.int64)
    ids = hot_ids_from_counts(counts, hot_vocab=4, hot_fraction=0.5)
    np.testing.assert_array_equal(ids, [3])
    # raising the fraction pulls in more ids (ties break toward lower ids)
    ids = hot_ids_from_counts(counts, hot_vocab=4, hot_fraction=0.85)
    np.testing.assert_array_equal(ids, [0, 1, 3])


def test_hot_ids_from_counts_cap_binds():
    counts = np.ones(100, np.int64)  # uniform: coverage wants all of them
    ids = hot_ids_from_counts(counts, hot_vocab=8, hot_fraction=0.99)
    assert ids.shape == (8,)
    assert np.all(np.diff(ids) > 0)


def test_hot_ids_from_counts_small_vocab_fully_hot():
    ids = hot_ids_from_counts(np.array([5, 0, 1]), hot_vocab=16,
                              hot_fraction=0.1)
    np.testing.assert_array_equal(ids, [0, 1, 2])


def test_hot_ids_from_counts_rejects_bad_cap():
    with pytest.raises(ValueError, match="hot_vocab"):
        hot_ids_from_counts(np.ones(4), hot_vocab=0)


def test_artifact_roundtrip_and_digest(tmp_path):
    per = {"c0": np.array([0, 1, 2], np.int32),
           "c1": np.array([3, 9, 11], np.int32)}
    write_hot_ids(tmp_path, per, hot_vocab=16, hot_fraction=0.9,
                  coverage={"c0": 1.0, "c1": 0.93})
    loaded = load_hot_ids(tmp_path)
    assert set(loaded) == {"c0", "c1"}
    for k in per:
        np.testing.assert_array_equal(loaded[k], per[k])
        assert loaded[k].dtype == np.int32
    # digest is stable through the round trip and sensitive to the id set
    assert hot_ids_digest(loaded) == hot_ids_digest(per)
    changed = dict(per, c1=np.array([3, 9, 12], np.int32))
    assert hot_ids_digest(changed)["c1"] != hot_ids_digest(per)["c1"]
    assert hot_ids_digest(changed)["c0"] == hot_ids_digest(per)["c0"]


def test_artifact_absent_and_corrupt(tmp_path):
    assert load_hot_ids(tmp_path) is None
    write_hot_ids(tmp_path, {"c0": np.array([2, 1])}, hot_vocab=4,
                  hot_fraction=0.9)  # unsorted: corrupt on read
    with pytest.raises(ValueError, match="sorted"):
        load_hot_ids(tmp_path)
    import json
    p = tmp_path / "hot_ids.json"
    payload = json.loads(p.read_text())
    payload["tables"] = {"c0": [1, 2]}
    payload["format_version"] = 99
    p.write_text(json.dumps(payload))
    with pytest.raises(ValueError, match="format_version"):
        load_hot_ids(tmp_path)


def test_criteo_preprocessing_emits_artifact(tmp_path):
    from tdfo_tpu.data.criteo_preprocessing import (
        CRITEO_CATEGORICAL,
        run_criteo_preprocessing,
    )
    from tdfo_tpu.data.synthetic import write_synthetic_criteo

    write_synthetic_criteo(tmp_path, n_rows=600, seed=0)
    size_map = run_criteo_preprocessing(tmp_path, hot_vocab=8,
                                        hot_fraction=0.8, min_freq=2)
    loaded = load_hot_ids(tmp_path)
    assert loaded is not None and set(loaded) == set(CRITEO_CATEGORICAL)
    for c in CRITEO_CATEGORICAL:
        ids = loaded[c]
        assert 1 <= ids.shape[0] <= max(8, size_map[c])
        assert ids.shape[0] <= size_map[c]
        assert np.all(ids >= 0) and np.all(ids < size_map[c])
        assert np.all(np.diff(ids) > 0)
    import json
    payload = json.loads((tmp_path / "hot_ids.json").read_text())
    cov = payload["coverage"]
    assert all(0.0 < cov[c] <= 1.0 + 1e-9 for c in CRITEO_CATEGORICAL)


# ---------------------------------------------------------------- routing


def _routed_coll():
    specs = [
        EmbeddingSpec("prefix", 10, 8, features=("prefix",)),
        EmbeddingSpec("scatter", 10, 8, features=("scatter",)),
        EmbeddingSpec("full", 5, 8, features=("full",)),
    ]
    hot = {
        "prefix": np.arange(4, dtype=np.int32),
        "scatter": np.array([1, 3, 7], np.int32),
        "full": np.arange(5, dtype=np.int32),
    }
    return ShardedEmbeddingCollection(specs, hot_ids=hot)


def test_route_ids_prefix_scatter_full():
    coll = _routed_coll()
    assert coll._hot_prefix["prefix"] and not coll._hot_full["prefix"]
    assert not coll._hot_prefix["scatter"]
    assert coll.hot_full("full") and coll.hot_count("full") == 5

    ids = jnp.asarray([0, 3, 4, 9, -1], jnp.int32)
    hp, ci = coll.route_ids("prefix", ids)
    np.testing.assert_array_equal(np.asarray(hp), [0, 3, -1, -1, -1])
    np.testing.assert_array_equal(np.asarray(ci), [-1, -1, 4, 9, -1])

    ids = jnp.asarray([1, 3, 7, 0, 2, 9, -1], jnp.int32)
    hp, ci = coll.route_ids("scatter", ids)
    np.testing.assert_array_equal(np.asarray(hp), [0, 1, 2, -1, -1, -1, -1])
    np.testing.assert_array_equal(np.asarray(ci), [-1, -1, -1, 0, 2, 9, -1])

    ids = jnp.asarray([4, 0, -1], jnp.int32)
    hp, ci = coll.route_ids("full", ids)
    np.testing.assert_array_equal(np.asarray(hp), [4, 0, -1])
    np.testing.assert_array_equal(np.asarray(ci), [-1, -1, -1])

    # unsplit table: identity routing
    coll2 = ShardedEmbeddingCollection(
        [EmbeddingSpec("a", 10, 8, features=("a",))])
    hp, ci = coll2.route_ids("a", ids)
    assert hp is None
    np.testing.assert_array_equal(np.asarray(ci), np.asarray(ids))


def test_hot_ids_validation():
    spec = [EmbeddingSpec("a", 10, 8, features=("a",))]
    with pytest.raises(KeyError, match="neither a table nor a feature"):
        ShardedEmbeddingCollection(spec, hot_ids={"nope": np.arange(2)})
    with pytest.raises(ValueError, match="sorted"):
        ShardedEmbeddingCollection(spec, hot_ids={"a": np.array([2, 1])})
    with pytest.raises(ValueError, match="outside"):
        ShardedEmbeddingCollection(spec, hot_ids={"a": np.array([8, 10])})
    fused = [EmbeddingSpec("a", 10, 8, features=("a",), fused=True)]
    with pytest.raises(ValueError, match="non-fused"):
        ShardedEmbeddingCollection(fused, hot_ids={"a": np.arange(2)})


def test_hot_lookup_matches_plain(mesh8):
    """Routed lookup (prefix, scattered and fully hot tables) returns the
    same vectors as the same-seed unsplit collection."""
    specs = lambda: [
        EmbeddingSpec("prefix", 10, 8, features=("prefix",), sharding="row"),
        EmbeddingSpec("scatter", 10, 8, features=("scatter",), sharding="row"),
        EmbeddingSpec("full", 5, 8, features=("full",), sharding="row"),
    ]
    hot = {
        "prefix": np.arange(4, dtype=np.int32),
        "scatter": np.array([1, 3, 7], np.int32),
        "full": np.arange(5, dtype=np.int32),
    }
    base = ShardedEmbeddingCollection(specs(), mesh=mesh8)
    split = ShardedEmbeddingCollection(specs(), mesh=mesh8, hot_ids=hot)
    t_base = base.init(jax.random.key(0))
    t_split = split.init(jax.random.key(0))
    ids = {
        "prefix": jnp.asarray([0, 3, 4, 9], jnp.int32),
        "scatter": jnp.asarray([1, 0, 7, 9], jnp.int32),
        "full": jnp.asarray([4, 0, 2, 1], jnp.int32),
    }
    out_b = base.lookup(t_base, ids)
    out_s = split.lookup(t_split, ids)
    for f in ids:
        np.testing.assert_allclose(np.asarray(out_s[f]),
                                   np.asarray(out_b[f]), rtol=1e-6)


# ------------------------------------------------- dense lazy tier parity


def _ref_update(kind, table, slots, ids, grads, lr=1e-2, wd=1e-3):
    """Reference: dedupe + the sparse_* row functions (the cold path)."""
    cap = ids.shape[0] + 1
    uids, g, valid = dedupe_grads(ids, grads, capacity=cap,
                                  vocab=table.shape[0] + 1)
    if kind == "sgd":
        return sparse_sgd(table, uids, g, valid, lr=lr, weight_decay=wd), ()
    if kind == "adagrad":
        t, a = sparse_adagrad(table, slots[0], uids, g, valid, lr=lr,
                              weight_decay=wd)
        return t, (a,)
    if kind == "rowwise_adagrad":
        t, a = sparse_rowwise_adagrad(table, slots[0], uids, g, valid, lr=lr,
                                      weight_decay=wd)
        return t, (a,)
    t, m, n, c = sparse_adam(table, *slots, uids, g, valid, lr=lr,
                             weight_decay=wd)
    return t, (m, n, c)


@pytest.mark.parametrize("kind", ["sgd", "adagrad", "rowwise_adagrad", "adam"])
def test_dense_update_matches_sparse_reference(kind):
    """dense_update (one-hot MXU + masked RMW) must equal the dedupe +
    gather/scatter formulation row for row — duplicates merged, negative
    (routed-away) ids ignored, untouched rows bit-untouched."""
    rng = np.random.default_rng(3)
    v, d, b = 12, 8, 20
    table = jnp.asarray(rng.normal(size=(v, d)).astype(np.float32))
    ids_np = rng.integers(0, v, b).astype(np.int32)
    ids_np[::5] = -1  # padding / routed-to-other-half entries
    ids = jnp.asarray(ids_np)
    grads = jnp.asarray(rng.normal(size=(b, d)).astype(np.float32))

    opt = sparse_optimizer(kind, lr=1e-2, weight_decay=1e-3)
    slots = opt.init(table)
    new_t, new_s = jax.jit(opt.dense_update)(table, slots, ids, grads)
    ref_t, ref_s = _ref_update(kind, table, slots, ids, grads)

    np.testing.assert_allclose(np.asarray(new_t), np.asarray(ref_t),
                               rtol=1e-5, atol=1e-6)
    for a, b_ in zip(new_s, ref_s):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=1e-5, atol=1e-6)
    # untouched rows are IDENTICAL (lazy state: no decay, no wd)
    untouched = np.setdiff1d(np.arange(v), ids_np[ids_np >= 0])
    np.testing.assert_array_equal(np.asarray(new_t)[untouched],
                                  np.asarray(table)[untouched])


def test_dense_update_rejects_fat_tables():
    opt = sparse_optimizer("sgd", lr=1e-2)
    fat = jnp.zeros((4, 2, 128), jnp.float32)
    with pytest.raises(ValueError, match="2D"):
        opt.dense_update(fat, (), jnp.zeros((2,), jnp.int32),
                         jnp.zeros((2, 8), jnp.float32))


# ------------------------------------------- end-to-end trajectory parity


CATS = ("c0", "c1", "c2")
CONTS = ("x0",)
SIZES = {"c0": 7, "c1": 50, "c2": 300}
# c0 fully hot, c1 a contiguous prefix, c2 a genuine scattered set — the
# three routing flavours in one model
HOT = {
    "c0": np.arange(7, dtype=np.int32),
    "c1": np.arange(8, dtype=np.int32),
    "c2": np.sort(np.random.default_rng(5).choice(
        300, size=12, replace=False)).astype(np.int32),
}


def _run_trajectory(mesh, kind, dedup, hot):
    from tdfo_tpu.models.dlrm import DLRMBackbone, generic_embedding_specs
    from tdfo_tpu.train.ctr import ctr_sparse_forward
    from tdfo_tpu.train.sparse_step import (
        SparseTrainState,
        make_sparse_train_step,
    )

    coll = ShardedEmbeddingCollection(
        generic_embedding_specs(SIZES, CATS, 8, "row", fused_threshold=None),
        mesh=mesh, stack_tables=True, hot_ids=hot,
    )
    bb = DLRMBackbone(embed_dim=8, cat_columns=CATS, cont_columns=CONTS)
    tables = coll.init(jax.random.key(0))
    dummy_e = {c: jnp.zeros((1, 8), jnp.float32) for c in CATS}
    dummy_c = {c: jnp.zeros((1,), jnp.float32) for c in CONTS}
    state = SparseTrainState.create(
        dense_params=bb.init(jax.random.key(1), dummy_e, dummy_c)["params"],
        tx=optax.adam(1e-2),
        tables=tables,
        sparse_opt=sparse_optimizer(kind, lr=1e-2, weight_decay=1e-3),
    )
    step = make_sparse_train_step(coll, ctr_sparse_forward(bb), donate=False,
                                  dedup_lookup=dedup)
    rr = np.random.default_rng(12)
    losses = []
    for _ in range(4):
        batch = {c: jnp.asarray(rr.integers(0, SIZES[c], 32), jnp.int32)
                 for c in CATS}
        batch["x0"] = jnp.asarray(rr.random(32, dtype=np.float32))
        batch["label"] = jnp.asarray(rr.integers(0, 2, 32), jnp.float32)
        state, loss = step(state, batch)
        losses.append(float(loss))
    return losses, state, coll


def _effective_tables(state, coll):
    """Logical-table views with hot rows overlaid onto the cold storage."""
    out = {}
    for c in CATS:
        tname = coll.resolve(c)[1].name
        aname, spec, off = coll.resolve_table(tname)
        eff = np.asarray(state.tables[aname])[off:off + spec.num_embeddings].copy()
        k = coll.hot_count(tname)
        if k:
            eff[np.asarray(coll.hot_ids[tname])] = np.asarray(
                state.tables[coll.hot_array_name(tname)])
        out[c] = eff
    return out


@pytest.mark.parametrize("kind,dedup", [
    # tier-1 keeps the adaptive kinds (distinct state shapes) + the
    # non-dedup forward; sgd/adagrad ride the slow tier — their dense_update
    # math is already pinned by test_dense_update_matches_sparse_reference
    pytest.param("sgd", True, marks=pytest.mark.slow),
    pytest.param("adagrad", True, marks=pytest.mark.slow),
    ("rowwise_adagrad", True), ("adam", True), ("rowwise_adagrad", False),
])
def test_hot_cold_matches_single_table(mesh8, kind, dedup):
    """The tentpole equivalence bar: same seed, same batches — the hot/cold
    run's losses and EFFECTIVE tables (cold storage with hot rows overlaid)
    must match the unsplit baseline for every optimizer kind, with fully
    hot, prefix and scattered tables in the same model, under both forward
    paths."""
    l_base, s_base, coll_base = _run_trajectory(mesh8, kind, dedup, None)
    l_hot, s_hot, coll_hot = _run_trajectory(mesh8, kind, dedup, HOT)
    np.testing.assert_allclose(l_hot, l_base, rtol=1e-5)
    eff_base = _effective_tables(s_base, coll_base)
    eff_hot = _effective_tables(s_hot, coll_hot)
    for c in CATS:
        np.testing.assert_allclose(eff_hot[c], eff_base[c],
                                   rtol=1e-5, atol=1e-6)


def test_hot_cold_requires_gspmd():
    from tdfo_tpu.train.sparse_step import make_sparse_train_step

    coll = _routed_coll()
    with pytest.raises(ValueError, match="gspmd"):
        make_sparse_train_step(coll, lambda d, e, b: 0.0, mode="psum")
    with pytest.raises(ValueError, match="gspmd"):
        coll.lookup(coll.init(jax.random.key(0)),
                    {"full": jnp.zeros((4,), jnp.int32)}, mode="psum")


def test_hot_init_gathers_cold_rows(mesh8):
    """Hot heads must be initialised FROM the cold rows (no extra rng
    keys): same-seed split and unsplit collections start bit-identical."""
    mk = lambda hot: ShardedEmbeddingCollection(
        [EmbeddingSpec("a", 20, 8, features=("a",), sharding="row")],
        mesh=mesh8, hot_ids=hot)
    hot = {"a": np.array([2, 5, 11], np.int32)}
    t_base = mk(None).init(jax.random.key(7))
    coll = mk(hot)
    t_split = coll.init(jax.random.key(7))
    np.testing.assert_array_equal(np.asarray(t_split["a"]),
                                  np.asarray(t_base["a"]))
    np.testing.assert_array_equal(np.asarray(t_split["a__hot"]),
                                  np.asarray(t_base["a"])[hot["a"]])
    # replicated head on the mesh
    from jax.sharding import PartitionSpec as P
    assert t_split["a__hot"].sharding.spec == P()


def test_trainer_stamps_from_artifact(tmp_path):
    """The trainer-facing digest contract: collection digests match the
    artifact digests, and change when the artifact changes."""
    per = {"a": np.array([2, 5, 11], np.int32)}
    coll = ShardedEmbeddingCollection(
        [EmbeddingSpec("a", 20, 8, features=("a",))], hot_ids=per)
    assert coll.hot_digest() == hot_ids_digest(per)
    # unsplit collection: no stamps at all
    plain = ShardedEmbeddingCollection(
        [EmbeddingSpec("a", 20, 8, features=("a",))])
    assert plain.hot_digest() == {}
