"""End-to-end slice: TwoTower on synthetic data — loss must decrease.

Parity target: jax-flax/train.py single-device loop and train_dp.py DP loop;
here DP is a sharding spec on the same step function.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from tdfo_tpu.core.precision import DynamicLossScale
from tdfo_tpu.models.twotower import TwoTower, init_twotower
from tdfo_tpu.train.state import TrainState, make_adamw
from tdfo_tpu.train.step import make_eval_step, make_train_step

SIZE_MAP = {
    "user": 100, "item": 80, "language": 5, "is_ebook": 2,
    "format": 6, "publisher": 20, "pub_decade": 14,
}


def synth_batch(rng: np.random.Generator, b: int) -> dict:
    batch = {
        "user_id": rng.integers(0, SIZE_MAP["user"], b, dtype=np.int32),
        "item_id": rng.integers(0, SIZE_MAP["item"], b, dtype=np.int32),
        "language": rng.integers(0, SIZE_MAP["language"], b, dtype=np.int32),
        "is_ebook": rng.integers(0, 2, b, dtype=np.int32),
        "format": rng.integers(0, SIZE_MAP["format"], b, dtype=np.int32),
        "publisher": rng.integers(0, SIZE_MAP["publisher"], b, dtype=np.int32),
        "pub_decade": rng.integers(0, SIZE_MAP["pub_decade"], b, dtype=np.int32),
        "avg_rating": rng.random(b, dtype=np.float32),
        "num_pages": rng.random(b, dtype=np.float32),
    }
    # learnable structure: label depends on user/item parity
    batch["label"] = ((batch["user_id"] + batch["item_id"]) % 2).astype(np.float32)
    return {k: jnp.asarray(v) for k, v in batch.items()}


def make_state(loss_scale=None):
    model, params = init_twotower(jax.random.key(0), SIZE_MAP, embed_dim=16)
    return TrainState.create(
        apply_fn=model.apply, params=params,
        tx=make_adamw(3e-3, 1e-4), loss_scale=loss_scale,
    )


def run_steps(state, step_fn, n=30, b=256):
    rng = np.random.default_rng(0)
    losses = []
    for _ in range(n):
        state, loss = step_fn(state, synth_batch(rng, b))
        losses.append(float(loss))
    return state, losses


def test_single_stream_loss_decreases():
    # overfit one fixed batch: loss must collapse
    state = make_state()
    step = make_train_step()
    batch = synth_batch(np.random.default_rng(0), 256)
    losses = []
    for _ in range(60):
        state, loss = step(state, batch)
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])


def test_eval_step():
    state, _ = run_steps(make_state(), make_train_step(), n=5)
    rng = np.random.default_rng(1)
    loss, logits = make_eval_step()(state, synth_batch(rng, 64))
    assert logits.shape == (64,)
    assert np.isfinite(float(loss))


def test_dp_matches_single_device(mesh_dp):
    """DP on 8 devices must track the unsharded run exactly (same global batch)."""
    state_a, losses_a = run_steps(make_state(), make_train_step(), n=8, b=64)
    step_dp = make_train_step(mesh=mesh_dp)
    state_b = jax.device_put(make_state(), NamedSharding(mesh_dp, P()))
    state_b, losses_b = run_steps(state_b, step_dp, n=8, b=64)
    np.testing.assert_allclose(losses_a, losses_b, rtol=2e-5)


def test_dynamic_loss_scale_step():
    state = make_state(loss_scale=DynamicLossScale.create(initial_scale=2.0**10))
    state, losses = run_steps(state, make_train_step(), n=10, b=128)
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]
    assert float(state.loss_scale.scale) >= 1.0


def test_loss_scale_overflow_rollback():
    state = make_state(loss_scale=DynamicLossScale.create(initial_scale=2.0**10))
    step = make_train_step(donate_state=False)
    rng = np.random.default_rng(2)
    batch = synth_batch(rng, 32)
    bad = dict(batch)
    bad["avg_rating"] = jnp.full_like(batch["avg_rating"], jnp.inf)
    params_before = jax.tree.map(lambda x: np.asarray(x), state.params)
    new_state, _ = step(state, bad)
    # params unchanged, scale halved
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(a, np.asarray(b)),
        params_before, new_state.params,
    )
    assert float(new_state.loss_scale.scale) == 2.0**9
