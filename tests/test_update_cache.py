"""Device-resident embedding update cache (software MANAGED_CACHING).

The tentpole contract under test: with ``cache_rows > 0`` every plain
big-table array routes its per-step row updates through a device-resident
cache (sorted-id directory + value/slot mirrors riding ``state.slots``),
admits misses gather-only, serves hits scatter-free, and writes dirty rows
back in ONE coalesced scatter every ``flush_every`` steps — and the
trajectory is BIT-IDENTICAL to the eager path for every optimizer kind,
any flush cadence, and every composition (dedup_lookup, hot/cold, bf16
storage + stochastic rounding).

Bitwise assertions run the step with ``jit=False``: op-for-op the cached
math IS the eager math (same operands, same order, same SR key positions),
which eager execution preserves exactly.  Under jit the cached and eager
runs are two DIFFERENT XLA programs, and XLA's fusion-dependent FMA
contraction in the adam mul-add chains drifts ~1 ulp on some inputs — a
property of comparing any two programs, not of the cache (the jitted test
pins the params-free sparse half bitwise where contraction is stable, and
bounds adam at float-eps scale).  Same-program determinism — what
kill/resume and rollback actually need — is exact and covered by the
trainer tests below.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from tdfo_tpu.models.dlrm import DLRMBackbone
from tdfo_tpu.ops.sparse import cache_overlay_rows, cache_route, sparse_optimizer
from tdfo_tpu.parallel.embedding import (
    CACHE_PREFIX,
    EmbeddingSpec,
    ShardedEmbeddingCollection,
)
from tdfo_tpu.train.ctr import ctr_sparse_forward
from tdfo_tpu.train.sparse_step import (
    SparseTrainState,
    make_cache_flush_fn,
    make_sparse_train_step,
)

CATS = ("c0", "c1", "c2")
CONTS = ("x0",)
SIZES = {"c0": 7, "c1": 50, "c2": 300}
# the three hot/cold routing flavours (tests/test_hot_cold.py): fully hot,
# contiguous prefix, scattered set
HOT = {
    "c0": np.arange(7, dtype=np.int32),
    "c1": np.arange(8, dtype=np.int32),
    "c2": np.sort(np.random.default_rng(5).choice(
        300, size=12, replace=False)).astype(np.int32),
}
N_STEPS = 5


# ------------------------------------------------------------- unit: ops


def test_cache_route_and_overlay():
    """Directory routing is branch-free: hits return the physical slot,
    misses/sentinels return C; overlay replaces exactly the hit rows."""
    opt = sparse_optimizer("sgd", lr=0.1)
    table = jnp.arange(40, dtype=jnp.float32).reshape(10, 4)
    cache = opt.cache_init(table, 6)
    # admit via the public update; zero grads + wd=0 leave values bitwise
    # equal to the admitted table rows
    ids = jnp.asarray([3, 7, 2], jnp.int32)
    cache, _ = opt.cache_update_unique(
        cache, table, (), ids, jnp.zeros((3, 4)), jnp.ones((3,), bool),
        step=jnp.int32(0))
    phys, hit = cache_route(cache, jnp.asarray([2, 5, 7, -1], jnp.int32))
    np.testing.assert_array_equal(np.asarray(hit), [True, False, True, False])
    assert int(phys[1]) == 6 and int(phys[3]) == 6  # miss => C
    # overlay: hit positions show cache rows, misses keep the gathered row
    rows = jnp.full((4, 4), -1.0)
    out = np.asarray(cache_overlay_rows(
        cache, jnp.asarray([2, 5, 7, -1], jnp.int32), rows))
    assert (out[1] == -1).all() and (out[3] == -1).all()
    # id 2's cached value: sgd with lr=0.1, g=0 => row unchanged from table
    np.testing.assert_array_equal(out[0], np.asarray(table)[2])
    np.testing.assert_array_equal(out[2], np.asarray(table)[7])


def test_cache_admission_overflow_is_counted_and_fatal():
    """Ids past the free directory capacity never enter the cache: the
    flush reports them and the trainer refuses to continue (their updates
    would be silently lost)."""
    from tdfo_tpu.train.trainer import _check_cache_overflow

    opt = sparse_optimizer("sgd", lr=0.1)
    table = jnp.zeros((64, 4), jnp.float32)
    cache = opt.cache_init(table, 8)
    ids = jnp.arange(20, dtype=jnp.int32)  # 20 distinct into 8 slots
    cache, _ = opt.cache_update_unique(
        cache, table, (), ids, jnp.ones((20, 4)), jnp.ones((20,), bool),
        step=jnp.int32(0))
    cache, table, _, over = opt.cache_flush(cache, table, ())
    assert int(over) == 12
    with pytest.raises(RuntimeError, match="cache_rows"):
        _check_cache_overflow({"t": over})
    _check_cache_overflow({"t": jnp.zeros((), jnp.int32)})  # clean passes


def test_cache_init_shapes_per_kind():
    table = jnp.zeros((40, 8), jnp.bfloat16)
    for kind, mirrors in (("sgd", ()), ("adagrad", ("acc",)),
                          ("rowwise_adagrad", ("acc",)),
                          ("adam", ("mu", "nu"))):
        opt = sparse_optimizer(kind, lr=0.1, slot_dtype="bfloat16")
        c = opt.cache_init(table, 16)
        assert c["ids"].shape == (16,) and c["rows"].dtype == jnp.bfloat16
        for m in mirrors:
            assert m in c
            if kind == "rowwise_adagrad":
                assert c[m].shape == (16,) and c[m].dtype == jnp.float32
            else:
                assert c[m].shape == (16, 8)
    with pytest.raises(ValueError, match="2D"):
        sparse_optimizer("sgd", lr=0.1).cache_init(
            jnp.zeros((4, 2, 128)), 8)


# ---------------------------------------- trajectory bit-equivalence


def _run(mesh, kind, dedup, cache_rows, flush_every, *, jit=False,
         hot=None, dtype=jnp.float32, n=N_STEPS):
    """Train n steps through the full step path; cached runs flush at the
    cadence + once at the end so the big tables are authoritative."""
    specs = [EmbeddingSpec(c, SIZES[c], 8, features=(c,), sharding="row",
                           dtype=dtype) for c in CATS]
    coll = ShardedEmbeddingCollection(
        specs, mesh=mesh, stack_tables=True, hot_ids=hot,
        cache_rows=cache_rows)
    bb = DLRMBackbone(embed_dim=8, cat_columns=CATS, cont_columns=CONTS)
    dummy_e = {c: jnp.zeros((1, 8), jnp.float32) for c in CATS}
    dummy_c = {c: jnp.zeros((1,), jnp.float32) for c in CONTS}
    sd = "bfloat16" if dtype == jnp.bfloat16 else "float32"
    state = SparseTrainState.create(
        dense_params=bb.init(jax.random.key(1), dummy_e, dummy_c)["params"],
        tx=optax.adam(1e-2),
        tables=coll.init(jax.random.key(0)),
        # threshold below the 357-row stack so adam exercises the cached
        # sparse tier instead of the small-vocab one-hot tier
        sparse_opt=sparse_optimizer(kind, lr=1e-2, weight_decay=1e-3,
                                    small_vocab_threshold=100,
                                    slot_dtype=sd))
    flush = None
    if cache_rows:
        caches = coll.init_caches(state.tables, state.sparse_opt)
        assert caches, "collection produced no cacheable arrays"
        state = dataclasses.replace(state, slots={**state.slots, **caches})
        flush = make_cache_flush_fn(donate=False, jit=jit)
    step = make_sparse_train_step(coll, ctr_sparse_forward(bb), donate=False,
                                  dedup_lookup=dedup, jit=jit)
    rr = np.random.default_rng(12)
    losses = []
    for i in range(n):
        batch = {c: jnp.asarray(rr.integers(0, SIZES[c], 32), jnp.int32)
                 for c in CATS}
        batch["x0"] = jnp.asarray(rr.random(32, dtype=np.float32))
        batch["label"] = jnp.asarray(rr.integers(0, 2, 32), jnp.float32)
        state, loss = step(state, batch)
        losses.append(np.asarray(loss).astype(np.float32).view(np.uint32).item())
        if flush is not None and (i + 1) % flush_every == 0:
            state, over = flush(state)
            assert all(int(v) == 0 for v in over.values()), over
    if flush is not None:
        state, over = flush(state)
        assert all(int(v) == 0 for v in over.values()), over
    return losses, state, coll


def _assert_state_bitwise(s0, s1, ctx=""):
    for a in s0.tables:
        x, y = np.asarray(s0.tables[a]), np.asarray(s1.tables[a])
        v = np.uint16 if x.dtype == jnp.bfloat16 else np.uint32
        np.testing.assert_array_equal(
            x.view(v), y.view(v), err_msg=f"{ctx}: table {a}")
    for a in s0.slots:  # eager slots only — cache entries have no baseline
        for j, (x, y) in enumerate(zip(
                jax.tree_util.tree_leaves(s0.slots[a]),
                jax.tree_util.tree_leaves(s1.slots[a]))):
            assert np.asarray(x).tobytes() == np.asarray(y).tobytes(), \
                f"{ctx}: slot {a} leaf {j}"


_BASELINES: dict = {}


def _baseline(mesh, kind, dedup, **kw):
    key = (kind, dedup, kw.get("hot") is not None,
           str(kw.get("dtype", jnp.float32)), kw.get("jit", False))
    if key not in _BASELINES:
        _BASELINES[key] = _run(mesh, kind, dedup, 0, 0, **kw)
    return _BASELINES[key]


@pytest.mark.parametrize("kind,dedup,flush_every", [
    # tier-1 keeps one case per distinct code path (hit-dominated fe=1
    # with rowwise mirrors, adam's two full mirrors mid-cadence, the
    # non-dedup forward); the optimizer x cadence cross-product rides the
    # slow tier — each case is an eager 2x5-step mesh8 run, too heavy to
    # keep them all in the timed tier
    ("rowwise_adagrad", True, 1),
    ("adam", True, 3),
    ("sgd", False, 3),
    pytest.param("adagrad", True, 8, marks=pytest.mark.slow),
    pytest.param("sgd", True, 1, marks=pytest.mark.slow),
    pytest.param("sgd", True, 8, marks=pytest.mark.slow),
    pytest.param("adagrad", False, 1, marks=pytest.mark.slow),
    pytest.param("adagrad", True, 3, marks=pytest.mark.slow),
    pytest.param("rowwise_adagrad", False, 3, marks=pytest.mark.slow),
    pytest.param("rowwise_adagrad", True, 8, marks=pytest.mark.slow),
    pytest.param("adam", False, 8, marks=pytest.mark.slow),
    pytest.param("adam", True, 1, marks=pytest.mark.slow),
])
def test_cache_matches_eager_trajectory(mesh8, kind, dedup, flush_every):
    """The tentpole bar: same seed, same batches — N cached steps + flushes
    reproduce the eager run's losses, tables AND optimizer slots
    bit-for-bit, for every optimizer kind and flush cadence."""
    l0, s0, _ = _baseline(mesh8, kind, dedup)
    l1, s1, _ = _run(mesh8, kind, dedup, 1024, flush_every)
    assert l0 == l1
    _assert_state_bitwise(s0, s1, f"{kind}/dedup={dedup}/fe={flush_every}")


@pytest.mark.parametrize("hot,dtype", [
    (HOT, jnp.bfloat16),
    pytest.param(HOT, jnp.float32, marks=pytest.mark.slow),
    pytest.param(None, jnp.bfloat16, marks=pytest.mark.slow),
])
def test_cache_composes_hot_cold_and_bf16(mesh8, hot, dtype):
    """Composition parity: hot/cold routing (hot heads stay uncached and
    dense-updated; the cache covers the cold stack) and bf16 storage with
    stochastic rounding (same SR keys, same noise positions) stay
    bit-identical to their cache-off runs."""
    kind, dedup = "rowwise_adagrad", True
    l0, s0, _ = _baseline(mesh8, kind, dedup, hot=hot, dtype=dtype)
    l1, s1, coll = _run(mesh8, kind, dedup, 1024, 3, hot=hot, dtype=dtype)
    assert l0 == l1
    _assert_state_bitwise(s0, s1, "hot/bf16 composition")
    if hot is not None:
        # hot heads are excluded from caching (dense RMW already
        # scatter-free); the cold stack is covered
        cached = {k for k in s1.slots if k.startswith(CACHE_PREFIX)}
        assert cached and all(
            "__hot" not in k for k in cached), cached


@pytest.mark.parametrize("kind,flush_every", [
    # PR 18 lifts the int8 x cache refusal: the cache stores the codes
    # plus a "qs" (scale, offset) mirror, write-time requantize runs the
    # SAME quantize_rows call with the SAME sr_key(step, table) as the
    # eager plain-int8 path, and the flush bit-copies codes + one qs
    # scatter — so the whole trajectory (codes, sidecars, slots, losses)
    # is bit-identical to the cache-off plain-int8 run.  One
    # hit-dominated case + one mid-cadence case tier-1; the remaining
    # kinds ride the slow tier (each case is 2x5 eager mesh8 steps).
    ("rowwise_adagrad", 1),
    pytest.param("adam", 3, marks=pytest.mark.slow),
    pytest.param("sgd", 3, marks=pytest.mark.slow),
    pytest.param("adagrad", 8, marks=pytest.mark.slow),
])
def test_cache_matches_eager_int8(mesh8, kind, flush_every):
    """int8 storage x update cache, bit-identical to the plain-int8
    eager reference for every optimizer kind (the PR 18 acceptance bar —
    SR keys preserved through the cached write path)."""
    l0, s0, _ = _baseline(mesh8, kind, True, dtype=jnp.int8)
    l1, s1, _ = _run(mesh8, kind, True, 1024, flush_every, dtype=jnp.int8)
    assert l0 == l1
    _assert_state_bitwise(s0, s1, f"int8/{kind}/fe={flush_every}")


def test_cache_int8_kill_resume_mid_flush_interval(mesh8):
    """Kill/resume MID-interval with dirty int8 rows in the cache: the
    cache (codes + qs mirror) rides state.slots, so a host round trip +
    rebuilt step and flush fns replays into the same bits as the
    uninterrupted cached run — and both match the eager reference.  No
    flush-time SR exists to desynchronise (requantize happens at write
    time inside the step).  rowwise_adagrad shares its eager baseline
    with the parity case above (the module-level _BASELINES cache)."""
    kind, dedup, fe = "rowwise_adagrad", True, 3
    l0, s0, _ = _baseline(mesh8, kind, dedup, dtype=jnp.int8)

    coll = ShardedEmbeddingCollection(
        [EmbeddingSpec(c, SIZES[c], 8, features=(c,), sharding="row",
                       dtype=jnp.int8) for c in CATS],
        mesh=mesh8, stack_tables=True, cache_rows=1024)
    bb = DLRMBackbone(embed_dim=8, cat_columns=CATS, cont_columns=CONTS)
    dummy_e = {c: jnp.zeros((1, 8), jnp.float32) for c in CATS}
    dummy_c = {c: jnp.zeros((1,), jnp.float32) for c in CONTS}
    state = SparseTrainState.create(
        dense_params=bb.init(jax.random.key(1), dummy_e, dummy_c)["params"],
        tx=optax.adam(1e-2), tables=coll.init(jax.random.key(0)),
        sparse_opt=sparse_optimizer(kind, lr=1e-2, weight_decay=1e-3,
                                    small_vocab_threshold=100))
    caches = coll.init_caches(state.tables, state.sparse_opt)
    state = dataclasses.replace(state, slots={**state.slots, **caches})
    flush = make_cache_flush_fn(donate=False, jit=False)
    step = make_sparse_train_step(coll, ctr_sparse_forward(bb), donate=False,
                                  dedup_lookup=dedup, jit=False)
    rr = np.random.default_rng(12)
    batches = []
    for _ in range(N_STEPS):
        b = {c: jnp.asarray(rr.integers(0, SIZES[c], 32), jnp.int32)
             for c in CATS}
        b["x0"] = jnp.asarray(rr.random(32, dtype=np.float32))
        b["label"] = jnp.asarray(rr.integers(0, 2, 32), jnp.float32)
        batches.append(b)

    losses = []
    for i, b in enumerate(batches):
        state, loss = step(state, b)
        losses.append(
            np.asarray(loss).astype(np.float32).view(np.uint32).item())
        if (i + 1) % fe == 0:
            state, over = flush(state)
            assert all(int(v) == 0 for v in over.values())
        if i == 3:  # step 4 of 5: one step past the fe=3 flush — dirty rows
            state = jax.tree_util.tree_map(
                lambda x: jnp.asarray(np.asarray(x)), state)
            step = make_sparse_train_step(
                coll, ctr_sparse_forward(bb), donate=False,
                dedup_lookup=dedup, jit=False)
            flush = make_cache_flush_fn(donate=False, jit=False)
    state, over = flush(state)
    assert all(int(v) == 0 for v in over.values())
    assert losses == l0
    _assert_state_bitwise(s0, state, "int8 kill/resume mid-interval")


@pytest.mark.parametrize("kind", [
    # each case compiles two distinct mesh8 programs — one representative
    # (rowwise: the Criteo default) in tier-1, the rest slow
    "rowwise_adagrad",
    pytest.param("sgd", marks=pytest.mark.slow),
    pytest.param("adagrad", marks=pytest.mark.slow),
    pytest.param("adam", marks=pytest.mark.slow),
])
def test_cache_matches_eager_jitted(mesh8, kind):
    """Jitted cross-program parity on a params-free forward (grads of the
    embeddings are a fixed function of the batch, isolating the sparse
    half): bitwise for the kinds whose chains XLA contracts identically;
    adam's longer mul-add chains FMA-drift ~1 ulp on some inputs, bounded
    at float-eps scale."""

    def fwd(dense_params, embs, batch):
        s = sum(jnp.sum(e, axis=-1) for e in embs.values())
        return jnp.mean((s - batch["label"]) ** 2)

    def run(cache_rows, flush_every):
        coll = ShardedEmbeddingCollection(
            [EmbeddingSpec(c, SIZES[c], 8, features=(c,), sharding="row")
             for c in CATS],
            mesh=mesh8, stack_tables=True, cache_rows=cache_rows)
        state = SparseTrainState.create(
            dense_params={}, tx=optax.sgd(1e-2),
            tables=coll.init(jax.random.key(0)),
            sparse_opt=sparse_optimizer(kind, lr=1e-2, weight_decay=1e-3,
                                        small_vocab_threshold=100))
        flush = None
        if cache_rows:
            caches = coll.init_caches(state.tables, state.sparse_opt)
            state = dataclasses.replace(
                state, slots={**state.slots, **caches})
            flush = make_cache_flush_fn(donate=False)
        step = make_sparse_train_step(coll, fwd, donate=False,
                                      dedup_lookup=True)
        rr = np.random.default_rng(12)
        for i in range(N_STEPS):
            batch = {c: jnp.asarray(rr.integers(0, SIZES[c], 32), jnp.int32)
                     for c in CATS}
            batch["label"] = jnp.asarray(rr.integers(0, 2, 32), jnp.float32)
            state, _ = step(state, batch)
            if flush is not None and (i + 1) % 2 == 0:
                state, over = flush(state)
                assert all(int(v) == 0 for v in over.values())
        if flush is not None:
            state, _ = flush(state)
        return state

    s0, s1 = run(0, 0), run(1024, 2)
    for a in s0.tables:
        x, y = np.asarray(s0.tables[a]), np.asarray(s1.tables[a])
        if kind == "adam":
            np.testing.assert_allclose(x, y, rtol=0, atol=1e-6)
        else:
            np.testing.assert_array_equal(x.view(np.uint32),
                                          y.view(np.uint32), err_msg=a)


# ------------------------------------------------------------ graph pins


def _scatter_operand_dims(closed) -> list[int]:
    """Leading dim of the updated operand of every scatter in the jaxpr,
    sub-jaxprs included."""
    dims = []

    def walk(jaxpr):
        for eqn in jaxpr.eqns:
            if eqn.primitive.name.startswith("scatter"):
                dims.append(eqn.invars[0].aval.shape[0])
            for v in eqn.params.values():
                for j in jax.tree_util.tree_leaves(
                        v, is_leaf=lambda x: hasattr(x, "eqns")
                        or hasattr(x, "jaxpr")):
                    if hasattr(j, "jaxpr"):
                        j = j.jaxpr
                    if hasattr(j, "eqns"):
                        walk(j)

    walk(closed.jaxpr)
    return dims


def _pin_setup(mesh, cache_rows, dtype=jnp.float32):
    coll = ShardedEmbeddingCollection(
        [EmbeddingSpec(c, SIZES[c], 8, features=(c,), sharding="row",
                       dtype=dtype)
         for c in CATS],
        mesh=mesh, stack_tables=True, cache_rows=cache_rows)
    bb = DLRMBackbone(embed_dim=8, cat_columns=CATS, cont_columns=CONTS)
    dummy_e = {c: jnp.zeros((1, 8), jnp.float32) for c in CATS}
    dummy_c = {c: jnp.zeros((1,), jnp.float32) for c in CONTS}
    state = SparseTrainState.create(
        dense_params=bb.init(jax.random.key(1), dummy_e, dummy_c)["params"],
        tx=optax.adam(1e-2), tables=coll.init(jax.random.key(0)),
        sparse_opt=sparse_optimizer("rowwise_adagrad", lr=1e-2))
    step = make_sparse_train_step(coll, ctr_sparse_forward(bb), donate=False,
                                  dedup_lookup=True, jit=False)
    rr = np.random.default_rng(0)
    batch = {c: jnp.asarray(rr.integers(0, SIZES[c], 32), jnp.int32)
             for c in CATS}
    batch["x0"] = jnp.asarray(rr.random(32, dtype=np.float32))
    batch["label"] = jnp.asarray(rr.integers(0, 2, 32), jnp.float32)
    return coll, state, step, batch


def test_nonflush_step_has_no_big_table_scatter(mesh8):
    """The perf claim, pinned in the IR: with the cache on, the train-step
    jaxpr contains NO scatter whose updated operand is a big-table-sized
    array — every scatter lands in cache space (or segment-sum space, both
    bounded by cache_rows/batch).  The flush program carries the one
    coalesced big scatter instead."""
    coll, state, step, batch = _pin_setup(mesh8, 128)
    caches = coll.init_caches(state.tables, state.sparse_opt)
    state = dataclasses.replace(state, slots={**state.slots, **caches})
    v_big = min(t.shape[0] for t in state.tables.values())
    assert v_big >= 357  # the stacked array (modulo shard padding)

    dims = _scatter_operand_dims(jax.make_jaxpr(step)(state, batch))
    big = [d for d in dims if d >= v_big]
    assert not big, f"big-table scatters in the non-flush step: {dims}"

    flush = make_cache_flush_fn(donate=False, jit=False)
    fdims = _scatter_operand_dims(jax.make_jaxpr(flush)(state))
    assert any(d >= v_big for d in fdims), \
        f"flush lost its coalesced big-table scatter: {fdims}"

    # the eager step DOES scatter into the big table (the cost the cache
    # removes) — proves the pin detects what it claims to
    _, estate, estep, _ = _pin_setup(mesh8, 0)
    edims = _scatter_operand_dims(jax.make_jaxpr(estep)(estate, batch))
    assert any(d >= v_big for d in edims)


def test_nonflush_step_has_no_big_table_scatter_int8(mesh8):
    """The acceptance jaxpr pin for the int8 composition: with int8
    storage + cache, non-flush steps scatter into NEITHER the big code
    table NOR the big [V, 2] qscale sidecar — requantized codes and
    grids land in cache space; the flush program carries both coalesced
    big scatters (codes bit-copy + one qs scatter)."""
    coll, state, step, batch = _pin_setup(mesh8, 128, dtype=jnp.int8)
    caches = coll.init_caches(state.tables, state.sparse_opt)
    state = dataclasses.replace(state, slots={**state.slots, **caches})
    v_big = min(t.shape[0] for t in state.tables.values())
    assert v_big >= 357  # stacked codes AND the [V, 2] qscale sidecar

    dims = _scatter_operand_dims(jax.make_jaxpr(step)(state, batch))
    big = [d for d in dims if d >= v_big]
    assert not big, f"big-table scatters in the int8 non-flush step: {dims}"

    flush = make_cache_flush_fn(donate=False, jit=False)
    fdims = _scatter_operand_dims(jax.make_jaxpr(flush)(state))
    assert sum(d >= v_big for d in fdims) >= 2, \
        f"int8 flush must scatter codes AND qscale: {fdims}"


def test_cache_off_graph_is_byte_identical(mesh8):
    """cache_rows = 0 must not change the compiled program at all — and a
    cache_rows > 0 COLLECTION with a cache-free state (the enable signal
    is the cache entries in state.slots) traces the same bytes too."""
    import re

    _, state0, step0, batch = _pin_setup(mesh8, 0)
    _, state8, step8, _ = _pin_setup(mesh8, 8)  # knob set, no cache entries
    # the jaxpr pretty-printer embeds function-object addresses in pjit /
    # custom_jvp params — normalize them; everything semantic must match
    norm = lambda j: re.sub(r"0x[0-9a-f]+", "0xADDR", str(j))
    j0 = norm(jax.make_jaxpr(step0)(state0, batch))
    j8 = norm(jax.make_jaxpr(step8)(state8, batch))
    assert j0 == j8


# ------------------------------------------------------------- refusals


def test_cache_requires_gspmd_step_and_no_pipelining(mesh8):
    from tdfo_tpu.train.sparse_step import make_pipelined_sparse_train_step

    coll = ShardedEmbeddingCollection(
        [EmbeddingSpec("a", 40, 8, features=("a",), sharding="row")],
        mesh=mesh8, cache_rows=16)
    with pytest.raises(ValueError, match="gspmd"):
        make_sparse_train_step(coll, lambda d, e, b: 0.0, mode="alltoall")
    grouped = ShardedEmbeddingCollection(
        [EmbeddingSpec("a", 40, 8, features=("a",), sharding="row")],
        mesh=mesh8, grouped_a2a=True, cache_rows=16)
    with pytest.raises(ValueError, match="cache"):
        make_pipelined_sparse_train_step(grouped, lambda d, e, b: 0.0)
    with pytest.raises(ValueError, match=">= 0"):
        ShardedEmbeddingCollection(
            [EmbeddingSpec("a", 40, 8, features=("a",))], cache_rows=-1)


def test_cache_config_validation():
    from tdfo_tpu.core.config import read_configs

    ok = dict(model="dlrm", embeddings={"cache_rows": 1024})
    cfg = read_configs(None, **ok)
    assert cfg.embeddings.cache_rows == 1024 and cfg.embeddings.flush_every == 64
    with pytest.raises(ValueError, match="cache_rows"):
        read_configs(None, model="dlrm", embeddings={"cache_rows": -1})
    with pytest.raises(ValueError, match="flush_every"):
        read_configs(None, model="dlrm",
                     embeddings={"cache_rows": 8, "flush_every": 0})
    # regime: dense twotower would silently ignore the knob
    with pytest.raises(ValueError, match="model_parallel"):
        read_configs(None, model="twotower", embeddings={"cache_rows": 8})
    # lookup modes: the cache routes inside the gspmd jitted step only
    with pytest.raises(ValueError, match="gspmd"):
        read_configs(None, model="dlrm", model_parallel=True,
                     lookup_mode="alltoall", embeddings={"cache_rows": 8})
    # grouped_a2a forces alltoall, transitively refused
    with pytest.raises(ValueError, match="gspmd|alltoall"):
        read_configs(None, model="dlrm", model_parallel=True,
                     embeddings={"cache_rows": 8, "grouped_a2a": True})
    with pytest.raises(ValueError, match="steps_per_execution"):
        read_configs(None, model="dlrm", steps_per_execution=4,
                     embeddings={"cache_rows": 8})
    with pytest.raises(ValueError, match="pipeline_overlap"):
        read_configs(None, model="dlrm", train={"pipeline_overlap": True},
                     embeddings={"cache_rows": 8})


# ------------------------------------------- checkpoint stamps + resume


def test_cache_stamps_refuse_mismatched_restore(tmp_path):
    """A cached-run checkpoint carries cache arrays inside slots: restoring
    across cache_rows/flush_every (either direction) must refuse instead of
    silently mis-shaping state; legacy stampless checkpoints restore into
    cache-off runs untouched."""
    from tdfo_tpu.train.checkpoint import CheckpointManager

    state = {"t": jnp.zeros((4, 8), jnp.float32)}
    stamp = {"update_cache": {"cache_rows": 1024, "flush_every": 8}}
    mgr = CheckpointManager(tmp_path / "c")
    mgr.save(0, state, stamps=stamp)
    step, _, _ = mgr.restore(state, stamps=dict(stamp))
    assert step == 0
    for bad in (None,  # cache-off run reading a cached checkpoint
                {"update_cache": {"cache_rows": 512, "flush_every": 8}},
                {"update_cache": {"cache_rows": 1024, "flush_every": 64}}):
        with pytest.raises(ValueError, match="stamps"):
            mgr.restore(state, stamps=bad)
    mgr.close()
    # other direction: a cached run refuses a legacy/cache-off checkpoint
    mgr2 = CheckpointManager(tmp_path / "c2")
    mgr2.save(0, state)
    s, _, _ = mgr2.restore(state, stamps=None)  # legacy -> cache-off: fine
    assert s == 0
    with pytest.raises(ValueError, match="stamps"):
        mgr2.restore(state, stamps=dict(stamp))
    mgr2.close()


# ------------------------------------------------------- serving export


@pytest.mark.slow  # three extra eager mesh8 runs; the flush-before-export
# invariant it certifies is also exercised by the tier-1 trainer tests
def test_export_identity_cached_vs_eager(mesh8):
    """Serving bundles are trajectory artifacts, not schedule artifacts:
    merged tables from (a) the eager run, (b) the cached run after flush,
    and (c) the cached run MID-interval with dirty rows + the caches
    overlay are all bitwise identical."""
    from tdfo_tpu.serve.export import merged_tables

    kind, dedup = "rowwise_adagrad", True
    _, s0, coll0 = _baseline(mesh8, kind, dedup)
    _, s1, coll1 = _run(mesh8, kind, dedup, 1024, 3)  # flushed at the end
    out0 = merged_tables(coll0, s0.tables)
    out1 = merged_tables(coll1, s1.tables)
    for t in out0:
        np.testing.assert_array_equal(out0[t].view(np.uint32),
                                      out1[t].view(np.uint32), err_msg=t)

    # mid-interval: never flush periodically, skip the terminal flush by
    # re-running with flush_every > n and intercepting before the final
    # flush — reproduce inline for the dirty state
    specs = [EmbeddingSpec(c, SIZES[c], 8, features=(c,), sharding="row")
             for c in CATS]
    coll = ShardedEmbeddingCollection(specs, mesh=mesh8, stack_tables=True,
                                      cache_rows=1024)
    bb = DLRMBackbone(embed_dim=8, cat_columns=CATS, cont_columns=CONTS)
    dummy_e = {c: jnp.zeros((1, 8), jnp.float32) for c in CATS}
    dummy_c = {c: jnp.zeros((1,), jnp.float32) for c in CONTS}
    state = SparseTrainState.create(
        dense_params=bb.init(jax.random.key(1), dummy_e, dummy_c)["params"],
        tx=optax.adam(1e-2), tables=coll.init(jax.random.key(0)),
        sparse_opt=sparse_optimizer(kind, lr=1e-2, weight_decay=1e-3,
                                    small_vocab_threshold=100))
    caches = coll.init_caches(state.tables, state.sparse_opt)
    state = dataclasses.replace(state, slots={**state.slots, **caches})
    step = make_sparse_train_step(coll, ctr_sparse_forward(bb), donate=False,
                                  dedup_lookup=dedup, jit=False)
    rr = np.random.default_rng(12)
    for _ in range(N_STEPS):
        batch = {c: jnp.asarray(rr.integers(0, SIZES[c], 32), jnp.int32)
                 for c in CATS}
        batch["x0"] = jnp.asarray(rr.random(32, dtype=np.float32))
        batch["label"] = jnp.asarray(rr.integers(0, 2, 32), jnp.float32)
        state, _ = step(state, batch)
    live_caches = {k: v for k, v in state.slots.items()
                   if k.startswith(CACHE_PREFIX)}
    assert any(bool(np.asarray(c["dirty"]).any())
               for c in live_caches.values()), "no dirty rows to overlay"
    out2 = merged_tables(coll, state.tables, live_caches)
    for t in out0:
        np.testing.assert_array_equal(out0[t].view(np.uint32),
                                      out2[t].view(np.uint32), err_msg=t)
    # without the overlay the stale big table shows — the caches param is
    # load-bearing, not decorative
    out_stale = merged_tables(coll, state.tables)
    assert any((out_stale[t].view(np.uint32)
                != out0[t].view(np.uint32)).any() for t in out0)


# ------------------------------------------------------ trainer end to end


@pytest.fixture(scope="module")
def cache_data(tmp_path_factory):
    from tdfo_tpu.data.ctr_preprocessing import run_ctr_preprocessing
    from tdfo_tpu.data.synthetic import write_synthetic_goodreads

    d = tmp_path_factory.mktemp("gr_cache")
    write_synthetic_goodreads(d, n_users=80, n_books=120,
                              interactions_per_user=(15, 40), seed=7)
    ctr = run_ctr_preprocessing(d)
    return d, ctr


def _trainer_cfg(d, ctr, **kw):
    from tdfo_tpu.core.config import read_configs

    return read_configs(
        None, data_dir=d, model="twotower", model_parallel=True,
        mesh={"data": 4, "model": 2}, n_epochs=1, learning_rate=3e-3,
        embed_dim=8, per_device_train_batch_size=16,
        per_device_eval_batch_size=16, shuffle_buffer_size=500,
        log_every_n_steps=2, size_map=ctr,
        sparse_optimizer="rowwise_adagrad", **kw)


@pytest.mark.slow  # two full fits
def test_trainer_cache_matches_eager_run(cache_data, tmp_path):
    """Trainer-level knob semantics: a cached fit (flush_every=3, so the
    epoch crosses several flush boundaries + the pre-eval sync flush)
    produces the same metrics as the cache-off fit, and the cache actually
    engaged (cache entries in slots, flush program built)."""
    import math

    from tdfo_tpu.train.trainer import Trainer

    d, ctr = cache_data
    tr_off = Trainer(_trainer_cfg(d, ctr), log_dir=tmp_path / "off")
    m_off = tr_off.fit()
    tr_on = Trainer(
        _trainer_cfg(d, ctr,
                     embeddings={"cache_rows": 512, "flush_every": 3}),
        log_dir=tmp_path / "on")
    m_on = tr_on.fit()
    assert tr_on._cache_flush is not None
    assert any(k.startswith(CACHE_PREFIX) for k in tr_on.state.slots)
    assert not any(k.startswith(CACHE_PREFIX) for k in tr_off.state.slots)
    assert set(m_on) == set(m_off)
    for k in m_off:
        assert math.isfinite(m_on[k])
        # same trajectory modulo cross-program FMA contraction (see module
        # docstring); the jit=False tests above pin exact bits
        np.testing.assert_allclose(m_on[k], m_off[k], rtol=1e-4, atol=1e-6,
                                   err_msg=k)
    # post-fit tables are flushed (the epoch-end sync flush): dirty empty
    for k, c in tr_on.state.slots.items():
        if k.startswith(CACHE_PREFIX):
            assert not np.asarray(c["dirty"]).any()


def test_trainer_cache_overflow_fails_loudly(cache_data, tmp_path):
    """An undersized cache must kill the run with the overflow diagnostic,
    not silently drop updates."""
    from tdfo_tpu.train.trainer import Trainer

    d, ctr = cache_data
    tr = Trainer(
        _trainer_cfg(d, ctr,
                     embeddings={"cache_rows": 8, "flush_every": 3}),
        log_dir=tmp_path / "log")
    with pytest.raises(RuntimeError, match="overflow"):
        tr.fit()


@pytest.mark.slow  # three full fits + checkpoint roundtrips
def test_trainer_kill_resume_mid_flush_interval(cache_data, tmp_path,
                                                monkeypatch):
    """Kill/resume INSIDE a flush interval (checkpoint at step 3, flush
    cadence 5): the pre-save sync flush makes the checkpoint authoritative,
    the cache arrays restore through state.slots, and the resumed run lands
    bit-identical to the uninterrupted reference."""
    from tdfo_tpu.train.checkpoint import CheckpointManager
    from tdfo_tpu.train.trainer import Trainer
    from tdfo_tpu.utils import faults

    d, ctr = cache_data

    class Killed(SystemExit):
        pass

    monkeypatch.setattr(faults.os, "_exit",
                        lambda code: (_ for _ in ()).throw(Killed(code)))
    emb = {"cache_rows": 512, "flush_every": 5}
    base = dict(checkpoint_dir=str(tmp_path / "ckpt"),
                checkpoint_every_n_steps=3, embeddings=emb,
                faults={"kill_at_step": 5})
    with pytest.raises(Killed):
        Trainer(_trainer_cfg(d, ctr, **base), log_dir=tmp_path / "l1").fit()

    mgr = CheckpointManager(tmp_path / "ckpt")
    s = mgr.latest_step()
    cursor = mgr.read_cursor(s)
    mgr.close()
    assert cursor is not None and not cursor["epoch_complete"]
    assert cursor["step"] == 3  # mid-epoch AND mid-flush-interval

    tr2 = Trainer(_trainer_cfg(d, ctr, **base), log_dir=tmp_path / "l2")
    m_resumed = tr2.fit()

    tr_ref = Trainer(
        _trainer_cfg(d, ctr, checkpoint_dir=str(tmp_path / "ckpt_ref"),
                     checkpoint_every_n_steps=3, embeddings=dict(emb)),
        log_dir=tmp_path / "l3")
    m_ref = tr_ref.fit()

    assert m_resumed == m_ref
    for a, b in zip(jax.tree.leaves(tr2.state), jax.tree.leaves(tr_ref.state)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
