"""Pallas kernels in interpreter mode vs XLA references (CPU-exact).

The compiled path runs on the real chip via bench_kernels.py; here the same
kernel code executes interpreted so the math is verified everywhere.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tdfo_tpu.ops.pallas_kernels import (
    fat_pack,
    fat_unpack,
    fat_view,
    flash_attention,
    line_layout,
)
from tdfo_tpu.ops.sparse import (
    dedupe_grads,
    fat_apply_unique,
    sparse_adagrad,
    sparse_adam,
    sparse_rowwise_adagrad,
    sparse_sgd,
)


def _qkv(key, b=2, h=2, t=128, dh=32):
    ks = jax.random.split(key, 3)
    return tuple(jax.random.normal(k, (b, h, t, dh)) for k in ks)


def _ref_attention(q, k, v, valid=None):
    s = jnp.einsum("bhtd,bhsd->bhts", q, k) / (q.shape[-1] ** 0.5)
    if valid is not None:
        s = jnp.where(valid[:, None, None, :], s, jnp.finfo(jnp.float32).min)
    p = jax.nn.softmax(s.astype(jnp.float32), -1)
    return jnp.einsum("bhts,bhsd->bhtd", p.astype(v.dtype), v)


class TestFlashAttention:
    def test_matches_reference(self):
        q, k, v = _qkv(jax.random.key(0))
        out = flash_attention(q, k, v, None, 64, 64, True)
        ref = _ref_attention(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)

    def test_key_padding_mask(self):
        q, k, v = _qkv(jax.random.key(1))
        valid = jnp.asarray(np.random.default_rng(0).random((2, 128)) > 0.4)
        valid = valid.at[:, 0].set(True)
        out = flash_attention(q, k, v, valid, 64, 64, True)
        ref = _ref_attention(q, k, v, valid)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)

    def test_fully_masked_rows_zero(self):
        q, k, v = _qkv(jax.random.key(2), b=1, t=64)
        valid = jnp.zeros((1, 64), bool)
        out = flash_attention(q, k, v, valid, 64, 64, True)
        assert not bool(jnp.isnan(out).any())
        np.testing.assert_allclose(np.asarray(out), 0.0)

    def test_uneven_seq_len_padded(self):
        q, k, v = _qkv(jax.random.key(3), t=100)
        out = flash_attention(q, k, v, None, 64, 64, True)
        ref = _ref_attention(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)

    def test_gradients_flow(self):
        q, k, v = _qkv(jax.random.key(4), b=1, h=1, t=64, dh=16)

        def loss(q, k, v):
            return (flash_attention(q, k, v, None, 64, 64, True) ** 2).sum()

        def ref_loss(q, k, v):
            return (_ref_attention(q, k, v) ** 2).sum()

        g = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4)

    def test_block_sizes_do_not_change_result(self):
        q, k, v = _qkv(jax.random.key(5), t=128)
        a = flash_attention(q, k, v, None, 128, 128, True)
        b = flash_attention(q, k, v, None, 32, 64, True)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-5, atol=2e-5)


class TestFatLayout:
    @pytest.mark.parametrize("d,kind,w,r,tiles", [
        (16, "rowwise_adagrad", 32, 4, 1),
        (16, "sgd", 16, 8, 1),
        (16, "adagrad", 32, 4, 1),
        (16, "adam", 64, 2, 1),
        (64, "rowwise_adagrad", 128, 1, 1),
        (64, "adam", 256, 1, 2),
        (8, "sgd", 8, 16, 1),
        (128, "adam", 384, 1, 3),
    ])
    def test_geometry(self, d, kind, w, r, tiles):
        lay = line_layout(d, kind)
        assert (lay.w, lay.r, lay.tiles) == (w, r, tiles)
        assert lay.r * lay.w == lay.tiles * 128  # contiguous-view invariant

    @pytest.mark.parametrize("d", [16, 42, 64, 96, 128, 200])
    def test_pack_unpack_roundtrip_adam(self, d):
        rng = np.random.default_rng(d)
        v = 24
        t, mu, nu = (jnp.asarray(rng.normal(size=(v, d)).astype(np.float32))
                     for _ in range(3))
        fat = fat_pack(t, mu, nu)
        lay = line_layout(d, "adam")
        assert fat.shape == (lay.n_lines(v), lay.tiles, 128)
        got = fat_unpack(fat, lay, rows=v)
        for a, b in zip(got, (t, mu, nu)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    @pytest.mark.parametrize("kind", ["sgd", "rowwise_adagrad", "adagrad"])
    def test_pack_unpack_roundtrip_other_kinds(self, kind):
        rng = np.random.default_rng(11)
        v, d = 37, 16
        t = jnp.asarray(rng.normal(size=(v, d)).astype(np.float32))
        state = ()
        if kind == "rowwise_adagrad":
            state = (jnp.asarray(rng.random(v).astype(np.float32)),)
        elif kind == "adagrad":
            state = (jnp.asarray(rng.random((v, d)).astype(np.float32)),)
        fat = fat_pack(t, *state, kind=kind)
        got = fat_unpack(fat, line_layout(d, kind), rows=v)
        np.testing.assert_array_equal(np.asarray(got[0]), np.asarray(t))
        for a, b in zip(got[1:], state):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_view_gather_matches_table(self):
        rng = np.random.default_rng(5)
        v, d = 100, 16
        lay = line_layout(d, "rowwise_adagrad")
        t = jnp.asarray(rng.normal(size=(v, d)).astype(np.float32))
        view = fat_view(fat_pack(t, kind="rowwise_adagrad"), lay)
        ids = jnp.asarray(rng.integers(0, v, 33).astype(np.int32))
        np.testing.assert_array_equal(
            np.asarray(jnp.take(view, ids, axis=0)[:, :d]), np.asarray(t[ids])
        )


def _ref_update(kind, table, state, uids, g, valid, lr, wd):
    if kind == "sgd":
        return sparse_sgd(table, uids, g, valid, lr=lr, weight_decay=wd), ()
    if kind == "rowwise_adagrad":
        t, acc = sparse_rowwise_adagrad(table, state[0], uids, g, valid,
                                        lr=lr, eps=1e-8, weight_decay=wd)
        return t, (acc,)
    if kind == "adagrad":
        t, acc = sparse_adagrad(table, state[0], uids, g, valid, lr=lr,
                                eps=1e-8, weight_decay=wd)
        return t, (acc,)
    t, mu, nu, _ = sparse_adam(table, state[0], state[1],
                               jnp.asarray(0, jnp.int32), uids, g, valid,
                               lr=lr, weight_decay=wd)
    return t, (mu, nu)


def _zero_state(kind, v, d):
    if kind == "sgd":
        return ()
    if kind == "rowwise_adagrad":
        return (jnp.zeros((v,), jnp.float32),)
    if kind == "adagrad":
        return (jnp.zeros((v, d), jnp.float32),)
    return (jnp.zeros((v, d), jnp.float32), jnp.zeros((v, d), jnp.float32))


class TestFatLineUpdate:
    """The in-place DMA kernel (interpret mode) must reproduce the plain
    per-row XLA formulations for EVERY fused optimizer kind — fbgemm fused
    EmbOptimType parity (torchrec/train.py:187-195)."""

    def _setup(self, v=64, d=64, b=32, seed=0):
        rng = np.random.default_rng(seed)
        table = jnp.asarray(rng.normal(size=(v, d)).astype(np.float32))
        ids = jnp.asarray(rng.integers(0, v, b).astype(np.int32))
        grads = jnp.asarray(rng.normal(size=(b, d)).astype(np.float32))
        return table, ids, grads

    @pytest.mark.parametrize("kind,d", [
        ("adam", 16), ("adam", 64),
        ("rowwise_adagrad", 16), ("rowwise_adagrad", 64),
        ("adagrad", 16), ("sgd", 16),
    ])
    def test_matches_xla_row_formulation(self, kind, d):
        table, ids, grads = self._setup(d=d)
        v = table.shape[0]
        uids, g, valid = dedupe_grads(ids, grads)
        state = _zero_state(kind, v, d)
        t_ref, s_ref = _ref_update(kind, table, state, uids, g, valid,
                                   lr=1e-2, wd=0.01)
        fat = fat_pack(table, kind=kind)
        slots = (jnp.zeros((), jnp.int32),) if kind == "adam" else ()
        fat_new, _ = fat_apply_unique(
            fat, slots, uids, g, valid, embedding_dim=d, kind=kind, lr=1e-2,
            weight_decay=0.01, interpret=True,
        )
        got = fat_unpack(fat_new, line_layout(d, kind), rows=v)
        np.testing.assert_allclose(np.asarray(got[0]), np.asarray(t_ref),
                                   rtol=1e-5, atol=1e-6)
        for a, b in zip(got[1:], s_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-5, atol=1e-6)

    def test_untouched_rows_unchanged(self):
        table, ids, grads = self._setup()
        uids, g, valid = dedupe_grads(ids, grads)
        fat = fat_pack(table, kind="adam")
        fat_new, _ = fat_apply_unique(
            fat, (jnp.zeros((), jnp.int32),), uids, g, valid,
            embedding_dim=table.shape[1], kind="adam", lr=1e-2, interpret=True,
        )
        touched = set(np.asarray(uids[np.asarray(valid)]).tolist())
        view, view_new = (np.asarray(fat_view(f, line_layout(64, "adam")))
                          for f in (fat, fat_new))
        for r in range(table.shape[0]):
            if r not in touched:
                np.testing.assert_array_equal(view_new[r], view[r])

    def test_padding_slots_are_noops(self):
        table, _, _ = self._setup(b=8)
        d = table.shape[1]
        sent = jnp.iinfo(jnp.int32).max
        uids = jnp.array([3, 7] + [sent] * 6, jnp.int32)
        g = jnp.ones((8, d), jnp.float32)
        g = g.at[2:].set(999.0)  # garbage grads on padding slots must not land
        fat = fat_pack(table, kind="adam")
        fat_new, _ = fat_apply_unique(
            fat, (jnp.zeros((), jnp.int32),), uids, g, None, embedding_dim=d,
            kind="adam", lr=1e-2, interpret=True,
        )
        t_pl = fat_unpack(fat_new, line_layout(d, "adam"))[0]
        assert not np.array_equal(np.asarray(t_pl[3]), np.asarray(table[3]))
        assert not np.array_equal(np.asarray(t_pl[7]), np.asarray(table[7]))
        np.testing.assert_array_equal(np.asarray(t_pl[0]), np.asarray(table[0]))

    def test_shared_line_slots_update_independently(self):
        """Two touched rows in the SAME packed line (R > 1) plus untouched
        neighbours: per-slot gating must keep neighbours bit-identical."""
        rng = np.random.default_rng(9)
        v, d, kind = 16, 16, "rowwise_adagrad"  # R = 4: rows 0-3 share line 0
        table = jnp.asarray(rng.normal(size=(v, d)).astype(np.float32))
        ids = jnp.asarray([0, 2, 0, 9], jnp.int32)
        grads = jnp.asarray(rng.normal(size=(4, d)).astype(np.float32))
        uids, g, valid = dedupe_grads(ids, grads)
        acc = jnp.zeros((v,), jnp.float32)
        t_ref, s_ref = _ref_update(kind, table, (acc,), uids, g, valid,
                                   lr=1e-2, wd=0.01)
        fat_new, _ = fat_apply_unique(
            fat_pack(table, kind=kind), (), uids, g, valid, embedding_dim=d,
            kind=kind, lr=1e-2, weight_decay=0.01, interpret=True,
        )
        got_t, got_acc = fat_unpack(fat_new, line_layout(d, kind), rows=v)
        np.testing.assert_allclose(np.asarray(got_t), np.asarray(t_ref),
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(np.asarray(got_acc), np.asarray(s_ref[0]),
                                   rtol=1e-5, atol=1e-6)
        # rows 1 and 3 share line 0 with touched rows 0/2 but must be intact
        np.testing.assert_array_equal(np.asarray(got_t[1]), np.asarray(table[1]))
        np.testing.assert_array_equal(np.asarray(got_t[3]), np.asarray(table[3]))


class TestSparseOptimizerTiers:
    """The three adam tiers (one-hot small-vocab, fat fused, plain) are one
    optimizer semantically: identical trajectories on identical data."""

    def _data(self, v, d, b=24, seed=3):
        rng = np.random.default_rng(seed)
        table = jnp.asarray(rng.normal(size=(v, d)).astype(np.float32))
        ids = jnp.asarray(rng.integers(0, v, b).astype(np.int32))
        grads = jnp.asarray(rng.normal(size=(b, d)).astype(np.float32))
        return table, ids, grads

    def test_onehot_tier_matches_plain(self):
        from tdfo_tpu.ops.sparse import sparse_optimizer

        table, ids, grads = self._data(v=50, d=32)
        small = sparse_optimizer("adam", lr=1e-2, weight_decay=0.01)  # v<=thresh
        plain = sparse_optimizer("adam", lr=1e-2, weight_decay=0.01,
                                 small_vocab_threshold=0)
        t_a, s_a = small.update(table, small.init(table), ids, grads)
        t_b, s_b = plain.update(table, plain.init(table), ids, grads)
        np.testing.assert_allclose(np.asarray(t_a), np.asarray(t_b), rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(np.asarray(s_a[0]), np.asarray(s_b[0]), rtol=1e-5, atol=1e-6)
        assert int(s_a[2]) == int(s_b[2]) == 1

    @pytest.mark.parametrize("kind,d", [
        ("adam", 64), ("adam", 200), ("rowwise_adagrad", 16), ("sgd", 16),
    ])
    def test_fat_tier_matches_plain(self, kind, d):
        from tdfo_tpu.ops.sparse import sparse_optimizer

        table, ids, grads = self._data(v=64, d=d)
        opt = sparse_optimizer(kind, lr=1e-2, weight_decay=0.01,
                               small_vocab_threshold=0)
        t_ref, _ = opt.update(table, opt.init(table), ids, grads)
        fat = fat_pack(table, kind=kind)
        fat_new, slots = opt.update(fat, opt.init(fat), ids, grads,
                                    embedding_dim=d)
        t_fat = fat_unpack(fat_new, line_layout(d, kind), rows=64)[0]
        np.testing.assert_allclose(np.asarray(t_fat), np.asarray(t_ref), rtol=1e-5, atol=1e-6)
        if kind == "adam":
            assert int(slots[0]) == 1


def test_bert4rec_flash_attn_matches_full(mesh8):
    from tdfo_tpu.models.bert4rec import Bert4RecConfig, key_padding_mask, make_sharded_bert4rec

    cfg = Bert4RecConfig(n_items=40, max_len=16, embed_dim=16, n_heads=2, n_layers=1)
    coll, tables, bb_full, dense = make_sharded_bert4rec(
        jax.random.key(0), cfg, None, sharding="replicated", attn="full"
    )
    _, _, bb_flash, _ = make_sharded_bert4rec(
        jax.random.key(0), cfg, None, sharding="replicated", attn="flash"
    )
    ids = jnp.array([[1, 2, 3, 4, 5, 41, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0]] * 2)
    embs = coll.lookup(tables, {"item": ids})
    lf = bb_full.apply({"params": dense}, embs["item"], key_padding_mask(ids))
    lfl = bb_flash.apply({"params": dense}, embs["item"], key_padding_mask(ids))
    np.testing.assert_allclose(np.asarray(lfl), np.asarray(lf), rtol=3e-5, atol=3e-5)


def test_flash_pads_non_multiple_seq_len():
    # T=200 is not a block multiple; pad-and-slice path must match reference
    q, k, v = _qkv(jax.random.key(7), b=1, h=2, t=200, dh=16)
    valid = jnp.asarray(np.random.default_rng(1).random((1, 200)) > 0.3)
    valid = valid.at[:, 0].set(True)
    out = flash_attention(q, k, v, valid, 128, 128, True)
    ref = _ref_attention(q, k, v, valid)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_flash_backward_with_mask_matches_reference():
    """The Pallas backward kernels under key-padding masks (incl. a fully
    masked row) must match the XLA attention VJP."""
    q, k, v = _qkv(jax.random.key(8), b=2, h=2, t=128, dh=32)
    valid = jnp.asarray(np.random.default_rng(2).random((2, 128)) > 0.35)
    valid = valid.at[:, 0].set(True)
    valid = valid.at[1, :].set(False)  # batch 1: every key masked

    def loss(q, k, v):
        return (flash_attention(q, k, v, valid, 64, 64, True) ** 2).sum()

    def ref_loss(q, k, v):
        s = jnp.einsum("bhtd,bhsd->bhts", q, k) / (q.shape[-1] ** 0.5)
        s = jnp.where(valid[:, None, None, :], s, jnp.finfo(jnp.float32).min)
        p = jax.nn.softmax(s.astype(jnp.float32), -1)
        p = jnp.where(valid.any(-1)[:, None, None, None], p, 0.0)
        return (jnp.einsum("bhts,bhsd->bhtd", p.astype(v.dtype), v) ** 2).sum()

    g = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_), rtol=1e-4, atol=1e-4)


def test_flash_backward_padded_seq_len():
    """T not a block multiple: the backward pad-and-slice path must match."""
    q, k, v = _qkv(jax.random.key(9), b=1, h=2, t=100, dh=16)

    def loss(q, k, v):
        return (flash_attention(q, k, v, None, 64, 64, True) ** 2).sum()

    def ref_loss(q, k, v):
        return (_ref_attention(q, k, v) ** 2).sum()

    g = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_), rtol=1e-4, atol=1e-4)


class TestFatRouted:
    """The routed fat-line path: dedupe_rows_and_lines + row-level
    segment-sum + fat_apply_routed (in-kernel operand routing reusing the
    forward's line gather) must reproduce the plain-table formulations for
    every kind, including padding ids, shared lines, and multi-block."""

    # rowwise_adagrad d=16 (the multi-row-per-line Criteo layout where
    # parity matters most) and the slot-free sgd stay tier-1; adam and
    # adagrad repeat the same routed plumbing at ~35 s of interpret-mode
    # time each on CPU and ride the slow tier to stay inside the tier-1
    # budget.
    @pytest.mark.parametrize("kind,d", [
        ("rowwise_adagrad", 16),
        pytest.param("adam", 64, marks=pytest.mark.slow),
        ("sgd", 8),
        pytest.param("adagrad", 16, marks=pytest.mark.slow),
    ])
    def test_matches_plain_path(self, kind, d):
        from tdfo_tpu.ops.sparse import (
            SparseOptimizer,
            dedupe_rows_and_lines,
            fat_apply_routed,
        )

        rng = np.random.default_rng(17)
        v, b = 530, 700  # > 128 lines at d=16 kinds -> multi-block
        lr, wd = 1e-2, 1e-3
        lay = line_layout(d, kind)
        table = jnp.asarray(rng.standard_normal((v, d)).astype(np.float32))
        ids = jnp.asarray(rng.integers(-1, v, b).astype(np.int32))
        grads = jnp.asarray(rng.standard_normal((b, d)).astype(np.float32))
        grads = jnp.where((ids >= 0)[:, None], grads, 0.0)
        opt = SparseOptimizer(kind=kind, lr=lr, weight_decay=wd,
                              small_vocab_threshold=0)
        t_ref, _ = opt.update(table, opt.init(table), ids, grads)

        seg, ulines, row_lidx, row_slot = dedupe_rows_and_lines(
            ids, capacity_rows=b, capacity_lines=b, rows_per_line=lay.r)
        fat = fat_pack(table, kind=kind)
        oob = jnp.iinfo(jnp.int32).max
        lines = jnp.take(fat, jnp.where(ulines < oob, ulines, 0), axis=0)
        # forward parity: expanded rows == table[ids] (negatives -> row 0)
        flat = lines.reshape(b, lay.tiles * 128)
        rows = jnp.take(flat, jnp.minimum(row_lidx, b - 1), axis=0)[:, :d]
        for s in range(1, lay.r):
            rl = jnp.take(flat, jnp.minimum(row_lidx, b - 1), axis=0)
            rows = jnp.where((row_slot == s)[:, None],
                             rl[:, s * lay.w: s * lay.w + d], rows)
        np.testing.assert_array_equal(
            np.asarray(jnp.take(rows, seg, axis=0)),
            np.asarray(jnp.take(table, jnp.maximum(ids, 0), axis=0)))

        g_u = jax.ops.segment_sum(grads.astype(jnp.float32), seg,
                                  num_segments=b)
        slots = (jnp.zeros((), jnp.int32),) if kind == "adam" else ()
        for interpret in (True, False):  # kernel (interpret) and XLA paths
            t_new, _ = fat_apply_routed(
                fat, slots, ulines, g_u, row_lidx, row_slot, lines,
                embedding_dim=d, kind=kind, lr=lr, weight_decay=wd,
                interpret=interpret)
            got = fat_unpack(t_new, lay, rows=v)[0]
            np.testing.assert_allclose(np.asarray(got), np.asarray(t_ref),
                                       rtol=1e-5, atol=1e-6)


    # one kind suffices: the drain skip is per-grid structure, not per-math
    # (the multi-kind parity matrix above covers the math); rowwise_adagrad
    # d=16 is the multi-row-per-line Criteo layout where parity matters most
    @pytest.mark.parametrize("kind,d", [("rowwise_adagrad", 16)])
    def test_one_block_grid(self, kind, d):
        """nblocks == 1 regression: the final drain used to construct
        write_copy for the off-parity block index -1, loading ids_ref at a
        negative SMEM index before the guard.  The drain must be statically
        skipped for one-block grids and still produce the plain-path
        result."""
        from tdfo_tpu.ops.sparse import (
            SparseOptimizer,
            dedupe_rows_and_lines,
            fat_apply_routed,
        )
        from tdfo_tpu.ops.pallas_kernels import routed_lines_per_step

        rng = np.random.default_rng(23)
        lay = line_layout(d, kind)
        lps = routed_lines_per_step(lay)
        v, b = 200, lps  # capacity_lines == lps -> exactly one grid block
        lr, wd = 1e-2, 1e-3
        table = jnp.asarray(rng.standard_normal((v, d)).astype(np.float32))
        ids = jnp.asarray(rng.integers(-1, v, b).astype(np.int32))
        grads = jnp.asarray(rng.standard_normal((b, d)).astype(np.float32))
        grads = jnp.where((ids >= 0)[:, None], grads, 0.0)
        opt = SparseOptimizer(kind=kind, lr=lr, weight_decay=wd,
                              small_vocab_threshold=0)
        t_ref, _ = opt.update(table, opt.init(table), ids, grads)

        seg, ulines, row_lidx, row_slot = dedupe_rows_and_lines(
            ids, capacity_rows=b, capacity_lines=lps, rows_per_line=lay.r)
        fat = fat_pack(table, kind=kind)
        oob = jnp.iinfo(jnp.int32).max
        lines = jnp.take(fat, jnp.where(ulines < oob, ulines, 0), axis=0)
        g_u = jax.ops.segment_sum(grads.astype(jnp.float32), seg,
                                  num_segments=b)
        slots = (jnp.zeros((), jnp.int32),) if kind == "adam" else ()
        for interpret in (True, False):
            t_new, _ = fat_apply_routed(
                fat, slots, ulines, g_u, row_lidx, row_slot, lines,
                embedding_dim=d, kind=kind, lr=lr, weight_decay=wd,
                interpret=interpret)
            got = fat_unpack(t_new, lay, rows=v)[0]
            np.testing.assert_allclose(np.asarray(got), np.asarray(t_ref),
                                       rtol=1e-5, atol=1e-6)


# u=129 (one line past a block) already forces the multi-block steady
# state; u=400 re-runs it at more grid steps for ~53 s of interpret-mode
# time and rides the slow tier.
@pytest.mark.parametrize("u", [129, pytest.param(400,
                                                 marks=pytest.mark.slow)])
def test_fat_multi_block_pipeline(u):
    """>128 touched lines forces multiple grid steps, exercising the
    double-buffered steady state (block i-1 write drain, block i+1 read
    prefetch, final-block drain) — not just the i==0 branch."""
    rng = np.random.default_rng(u)
    v, d = 512, 64
    table = jnp.asarray(rng.normal(size=(v, d)).astype(np.float32))
    mu = jnp.asarray(rng.normal(size=(v, d)).astype(np.float32)) * 0.1
    nu = jnp.abs(jnp.asarray(rng.normal(size=(v, d)).astype(np.float32))) * 0.1
    ids = jnp.asarray(rng.choice(v, size=u, replace=False).astype(np.int32))
    grads = jnp.asarray(rng.normal(size=(u, d)).astype(np.float32))
    uids, g, valid = dedupe_grads(ids, grads)
    count = jnp.asarray(4, jnp.int32)
    t_ref, mu_ref, nu_ref, _ = sparse_adam(
        table, mu, nu, count, uids, g, valid, lr=1e-2, weight_decay=0.01
    )
    fat_new, slots = fat_apply_unique(
        fat_pack(table, mu, nu), (count,), uids, g, valid, embedding_dim=d,
        kind="adam", lr=1e-2, weight_decay=0.01, interpret=True,
    )
    assert int(slots[0]) == 5
    t_pl, mu_pl, nu_pl = fat_unpack(fat_new, line_layout(d, "adam"), rows=v)
    np.testing.assert_allclose(np.asarray(t_pl), np.asarray(t_ref), rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(mu_pl), np.asarray(mu_ref), rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(nu_pl), np.asarray(nu_ref), rtol=1e-5, atol=1e-6)


class TestQuantizedFatLine:
    """bf16 fat-line storage with in-kernel stochastic rounding: the packed
    lines live at bf16 (half the DMA bytes), the line math runs f32, and
    the writeback requantizes through the counter-hashed SR (fbgemm
    quantized-TBE intra-training parity).  Kernel (interpret) and XLA
    fallback are both exercised; they are NOT required bit-equal to each
    other — each path is deterministic per platform."""

    def _setup(self, v=64, d=16, b=32, seed=0):
        rng = np.random.default_rng(seed)
        table = jnp.asarray(rng.normal(size=(v, d)).astype(np.float32))
        ids = jnp.asarray(rng.integers(0, v, b).astype(np.int32))
        grads = jnp.asarray(rng.normal(size=(b, d)).astype(np.float32))
        return table, ids, grads

    # interpret-mode runs execute the kernel python per block (~20-40 s on
    # CPU); they ride the slow tier to stay inside the tier-1 budget, same
    # as the test_hot_cold non-default-kind params.  The compiled variants
    # stay tier-1.
    @pytest.mark.parametrize("interpret", [
        pytest.param(True, marks=pytest.mark.slow), False])
    def test_bf16_sr_stays_close_to_f32_and_is_deterministic(self, interpret):
        table, ids, grads = self._setup()
        d = table.shape[1]
        uids, g, valid = dedupe_grads(ids, grads)
        slots = (jnp.zeros((), jnp.int32),)
        # f32 reference trajectory on the same fat geometry
        ref, _ = fat_apply_unique(
            fat_pack(table, kind="adam"), slots, uids, g, valid,
            embedding_dim=d, kind="adam", lr=1e-2, interpret=interpret)
        t_ref = fat_unpack(ref, line_layout(d, "adam"), rows=64)[0]
        fat16 = fat_pack(table, kind="adam", dtype=jnp.bfloat16)
        assert fat16.dtype == jnp.bfloat16
        key = jax.random.PRNGKey(11)
        out = []
        for _ in range(2):
            got, _ = fat_apply_unique(
                fat16, slots, uids, g, valid, embedding_dim=d, kind="adam",
                lr=1e-2, interpret=interpret, sr_key=key)
            assert got.dtype == jnp.bfloat16
            out.append(np.asarray(
                fat_unpack(got, line_layout(d, "adam"), rows=64)[0],
                dtype=np.float32))
        np.testing.assert_array_equal(out[0], out[1])  # same key -> same bits
        np.testing.assert_allclose(out[0], np.asarray(t_ref),
                                   rtol=2e-2, atol=2e-2)
        other, _ = fat_apply_unique(
            fat16, slots, uids, g, valid, embedding_dim=d, kind="adam",
            lr=1e-2, interpret=interpret, sr_key=jax.random.PRNGKey(12))
        o = np.asarray(fat_unpack(other, line_layout(d, "adam"), rows=64)[0],
                       dtype=np.float32)
        assert (o != out[0]).any()  # a different key flips some low bits

    @pytest.mark.parametrize("interpret", [True, False])
    def test_bf16_untouched_rows_bit_identical(self, interpret):
        """SR is the identity on already-representable values, so rows that
        ride a touched block without being touched keep their exact bits —
        including neighbours INSIDE a touched packed line (R > 1)."""
        rng = np.random.default_rng(9)
        v, d, kind = 16, 16, "adagrad"  # R = 4: rows 0-3 share line 0
        table = jnp.asarray(rng.normal(size=(v, d)).astype(np.float32))
        ids = jnp.asarray([0, 2, 9], jnp.int32)
        grads = jnp.asarray(rng.normal(size=(3, d)).astype(np.float32))
        uids, g, valid = dedupe_grads(ids, grads)
        fat16 = fat_pack(table, kind=kind, dtype=jnp.bfloat16)
        got, _ = fat_apply_unique(
            fat16, (), uids, g, valid, embedding_dim=d, kind=kind, lr=1e-2,
            interpret=interpret, sr_key=jax.random.PRNGKey(5))
        lay = line_layout(d, kind)
        before = np.asarray(fat_view(fat16, lay)).view(np.uint16)
        after = np.asarray(fat_view(got, lay)).view(np.uint16)
        touched = {0, 2, 9}
        for r in range(v):
            if r not in touched:
                np.testing.assert_array_equal(after[r], before[r],
                                              err_msg=f"row {r}")

    @pytest.mark.parametrize("interpret", [
        pytest.param(True, marks=pytest.mark.slow), False])
    def test_f32_fat_ignores_sr_key(self, interpret):
        """float32 fat storage must stay byte-identical with or without a
        key: the seed operand only exists for narrow storage, so the f32
        kernel call graph is the pre-quantization one."""
        table, ids, grads = self._setup(d=16)
        uids, g, valid = dedupe_grads(ids, grads)
        fat = fat_pack(table, kind="sgd")
        a, _ = fat_apply_unique(fat, (), uids, g, valid, embedding_dim=16,
                                kind="sgd", lr=1e-2, interpret=interpret)
        b, _ = fat_apply_unique(fat, (), uids, g, valid, embedding_dim=16,
                                kind="sgd", lr=1e-2, interpret=interpret,
                                sr_key=jax.random.PRNGKey(3))
        np.testing.assert_array_equal(
            np.asarray(a).view(np.uint32), np.asarray(b).view(np.uint32))
