"""Pallas kernels in interpreter mode vs XLA references (CPU-exact).

The compiled path runs on the real chip via bench_kernels.py; here the same
kernel code executes interpreted so the math is verified everywhere.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tdfo_tpu.ops.pallas_kernels import (
    fat_adam_rows,
    fat_components,
    fat_layout,
    fat_pack,
    flash_attention,
)
from tdfo_tpu.ops.sparse import dedupe_grads, sparse_adam


def _qkv(key, b=2, h=2, t=128, dh=32):
    ks = jax.random.split(key, 3)
    return tuple(jax.random.normal(k, (b, h, t, dh)) for k in ks)


def _ref_attention(q, k, v, valid=None):
    s = jnp.einsum("bhtd,bhsd->bhts", q, k) / (q.shape[-1] ** 0.5)
    if valid is not None:
        s = jnp.where(valid[:, None, None, :], s, jnp.finfo(jnp.float32).min)
    p = jax.nn.softmax(s.astype(jnp.float32), -1)
    return jnp.einsum("bhts,bhsd->bhtd", p.astype(v.dtype), v)


class TestFlashAttention:
    def test_matches_reference(self):
        q, k, v = _qkv(jax.random.key(0))
        out = flash_attention(q, k, v, None, 64, 64, True)
        ref = _ref_attention(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)

    def test_key_padding_mask(self):
        q, k, v = _qkv(jax.random.key(1))
        valid = jnp.asarray(np.random.default_rng(0).random((2, 128)) > 0.4)
        valid = valid.at[:, 0].set(True)
        out = flash_attention(q, k, v, valid, 64, 64, True)
        ref = _ref_attention(q, k, v, valid)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)

    def test_fully_masked_rows_zero(self):
        q, k, v = _qkv(jax.random.key(2), b=1, t=64)
        valid = jnp.zeros((1, 64), bool)
        out = flash_attention(q, k, v, valid, 64, 64, True)
        assert not bool(jnp.isnan(out).any())
        np.testing.assert_allclose(np.asarray(out), 0.0)

    def test_uneven_seq_len_padded(self):
        q, k, v = _qkv(jax.random.key(3), t=100)
        out = flash_attention(q, k, v, None, 64, 64, True)
        ref = _ref_attention(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)

    def test_gradients_flow(self):
        q, k, v = _qkv(jax.random.key(4), b=1, h=1, t=64, dh=16)

        def loss(q, k, v):
            return (flash_attention(q, k, v, None, 64, 64, True) ** 2).sum()

        def ref_loss(q, k, v):
            return (_ref_attention(q, k, v) ** 2).sum()

        g = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4)

    def test_block_sizes_do_not_change_result(self):
        q, k, v = _qkv(jax.random.key(5), t=128)
        a = flash_attention(q, k, v, None, 128, 128, True)
        b = flash_attention(q, k, v, None, 32, 64, True)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-5, atol=2e-5)


class TestFatLayout:
    @pytest.mark.parametrize("d", [16, 42, 64, 96, 128, 200])
    def test_pack_components_roundtrip(self, d):
        rng = np.random.default_rng(d)
        v = 24
        t, mu, nu = (jnp.asarray(rng.normal(size=(v, d)).astype(np.float32))
                     for _ in range(3))
        fat = fat_pack(t, mu, nu)
        stride, tiles = fat_layout(d)
        assert fat.shape == (v, tiles, 128)
        assert stride >= d and stride % 64 == 0
        got = fat_components(fat, d)
        for a, b in zip(got, (t, mu, nu)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


class TestFatAdamRows:
    def _setup(self, v=64, d=64, b=32, seed=0):
        rng = np.random.default_rng(seed)
        table = jnp.asarray(rng.normal(size=(v, d)).astype(np.float32))
        mu = jnp.zeros((v, d), jnp.float32)
        nu = jnp.zeros((v, d), jnp.float32)
        ids = jnp.asarray(rng.integers(0, v, b).astype(np.int32))
        grads = jnp.asarray(rng.normal(size=(b, d)).astype(np.float32))
        return table, mu, nu, ids, grads

    @pytest.mark.parametrize("d", [16, 64, 128])
    def test_matches_xla_sparse_adam(self, d):
        """The in-place DMA kernel (interpret mode) must reproduce the plain
        three-buffer XLA lazy Adam exactly."""
        table, mu, nu, ids, grads = self._setup(d=d)
        uids, g, valid = dedupe_grads(ids, grads)
        count = jnp.asarray(0, jnp.int32)
        t_ref, mu_ref, nu_ref, _ = sparse_adam(
            table, mu, nu, count, uids, g, valid, lr=1e-2, weight_decay=0.01
        )
        fat = fat_pack(table, mu, nu)
        fat_new = fat_adam_rows(
            fat, uids, g, count + 1, d=d, lr=1e-2, weight_decay=0.01,
            interpret=True,
        )
        t_pl, mu_pl, nu_pl = fat_components(fat_new, d)
        np.testing.assert_allclose(np.asarray(t_pl), np.asarray(t_ref), rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(np.asarray(mu_pl), np.asarray(mu_ref), rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(np.asarray(nu_pl), np.asarray(nu_ref), rtol=1e-5, atol=1e-6)

    def test_untouched_rows_unchanged(self):
        table, mu, nu, ids, grads = self._setup()
        uids, g, valid = dedupe_grads(ids, grads)
        fat = fat_pack(table, mu, nu)
        fat_new = fat_adam_rows(
            fat, uids, g, jnp.asarray(1, jnp.int32), d=table.shape[1], lr=1e-2,
            interpret=True,
        )
        touched = set(np.asarray(uids[np.asarray(valid)]).tolist())
        for r in range(table.shape[0]):
            if r not in touched:
                np.testing.assert_array_equal(
                    np.asarray(fat_new[r]), np.asarray(fat[r])
                )

    def test_padding_slots_are_noops(self):
        table, mu, nu, _, _ = self._setup(b=8)
        d = table.shape[1]
        sent = jnp.iinfo(jnp.int32).max
        uids = jnp.array([3, 7] + [sent] * 6, jnp.int32)
        g = jnp.ones((8, d), jnp.float32)
        g = g.at[2:].set(999.0)  # garbage grads on padding slots must not land
        fat = fat_pack(table, mu, nu)
        fat_new = fat_adam_rows(
            fat, uids, g, jnp.asarray(1, jnp.int32), d=d, lr=1e-2, interpret=True
        )
        t_pl = fat_components(fat_new, d)[0]
        assert not np.array_equal(np.asarray(t_pl[3]), np.asarray(table[3]))
        assert not np.array_equal(np.asarray(t_pl[7]), np.asarray(table[7]))
        np.testing.assert_array_equal(np.asarray(t_pl[0]), np.asarray(table[0]))


class TestSparseOptimizerTiers:
    """The three adam tiers (one-hot small-vocab, fat fused, plain) are one
    optimizer semantically: identical trajectories on identical data."""

    def _data(self, v, d, b=24, seed=3):
        rng = np.random.default_rng(seed)
        table = jnp.asarray(rng.normal(size=(v, d)).astype(np.float32))
        ids = jnp.asarray(rng.integers(0, v, b).astype(np.int32))
        grads = jnp.asarray(rng.normal(size=(b, d)).astype(np.float32))
        return table, ids, grads

    def test_onehot_tier_matches_plain(self):
        from tdfo_tpu.ops.sparse import sparse_optimizer

        table, ids, grads = self._data(v=50, d=32)
        small = sparse_optimizer("adam", lr=1e-2, weight_decay=0.01)  # v<=thresh
        plain = sparse_optimizer("adam", lr=1e-2, weight_decay=0.01,
                                 small_vocab_threshold=0)
        t_a, s_a = small.update(table, small.init(table), ids, grads)
        t_b, s_b = plain.update(table, plain.init(table), ids, grads)
        np.testing.assert_allclose(np.asarray(t_a), np.asarray(t_b), rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(np.asarray(s_a[0]), np.asarray(s_b[0]), rtol=1e-5, atol=1e-6)
        assert int(s_a[2]) == int(s_b[2]) == 1

    @pytest.mark.parametrize("d", [64, 200])
    def test_fat_tier_matches_plain(self, d):
        from tdfo_tpu.ops.sparse import sparse_optimizer

        table, ids, grads = self._data(v=64, d=d)
        opt = sparse_optimizer("adam", lr=1e-2, weight_decay=0.01,
                               small_vocab_threshold=0)
        t_ref, _ = opt.update(table, opt.init(table), ids, grads)
        fat = fat_pack(table, jnp.zeros_like(table), jnp.zeros_like(table))
        fat_new, slots = opt.update(fat, opt.init(fat), ids, grads,
                                    embedding_dim=d)
        t_fat = fat_components(fat_new, d)[0]
        np.testing.assert_allclose(np.asarray(t_fat), np.asarray(t_ref), rtol=1e-5, atol=1e-6)
        assert int(slots[0]) == 1


def test_bert4rec_flash_attn_matches_full(mesh8):
    from tdfo_tpu.models.bert4rec import Bert4RecConfig, key_padding_mask, make_sharded_bert4rec

    cfg = Bert4RecConfig(n_items=40, max_len=16, embed_dim=16, n_heads=2, n_layers=1)
    coll, tables, bb_full, dense = make_sharded_bert4rec(
        jax.random.key(0), cfg, None, sharding="replicated", attn="full"
    )
    _, _, bb_flash, _ = make_sharded_bert4rec(
        jax.random.key(0), cfg, None, sharding="replicated", attn="flash"
    )
    ids = jnp.array([[1, 2, 3, 4, 5, 41, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0]] * 2)
    embs = coll.lookup(tables, {"item": ids})
    lf = bb_full.apply({"params": dense}, embs["item"], key_padding_mask(ids))
    lfl = bb_flash.apply({"params": dense}, embs["item"], key_padding_mask(ids))
    np.testing.assert_allclose(np.asarray(lfl), np.asarray(lf), rtol=3e-5, atol=3e-5)


def test_flash_pads_non_multiple_seq_len():
    # T=200 is not a block multiple; pad-and-slice path must match reference
    q, k, v = _qkv(jax.random.key(7), b=1, h=2, t=200, dh=16)
    valid = jnp.asarray(np.random.default_rng(1).random((1, 200)) > 0.3)
    valid = valid.at[:, 0].set(True)
    out = flash_attention(q, k, v, valid, 128, 128, True)
    ref = _ref_attention(q, k, v, valid)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_flash_backward_with_mask_matches_reference():
    """The Pallas backward kernels under key-padding masks (incl. a fully
    masked row) must match the XLA attention VJP."""
    q, k, v = _qkv(jax.random.key(8), b=2, h=2, t=128, dh=32)
    valid = jnp.asarray(np.random.default_rng(2).random((2, 128)) > 0.35)
    valid = valid.at[:, 0].set(True)
    valid = valid.at[1, :].set(False)  # batch 1: every key masked

    def loss(q, k, v):
        return (flash_attention(q, k, v, valid, 64, 64, True) ** 2).sum()

    def ref_loss(q, k, v):
        s = jnp.einsum("bhtd,bhsd->bhts", q, k) / (q.shape[-1] ** 0.5)
        s = jnp.where(valid[:, None, None, :], s, jnp.finfo(jnp.float32).min)
        p = jax.nn.softmax(s.astype(jnp.float32), -1)
        p = jnp.where(valid.any(-1)[:, None, None, None], p, 0.0)
        return (jnp.einsum("bhts,bhsd->bhtd", p.astype(v.dtype), v) ** 2).sum()

    g = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_), rtol=1e-4, atol=1e-4)


def test_flash_backward_padded_seq_len():
    """T not a block multiple: the backward pad-and-slice path must match."""
    q, k, v = _qkv(jax.random.key(9), b=1, h=2, t=100, dh=16)

    def loss(q, k, v):
        return (flash_attention(q, k, v, None, 64, 64, True) ** 2).sum()

    def ref_loss(q, k, v):
        return (_ref_attention(q, k, v) ** 2).sum()

    g = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_), rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("u", [129, 257, 400])
def test_fat_adam_multi_block_pipeline(u):
    """>128 touched rows forces multiple grid steps, exercising the
    double-buffered steady state (block i-1 write drain, block i+1 read
    prefetch, final-block drain) — not just the i==0 branch."""
    rng = np.random.default_rng(u)
    v, d = 512, 64
    table = jnp.asarray(rng.normal(size=(v, d)).astype(np.float32))
    mu = jnp.asarray(rng.normal(size=(v, d)).astype(np.float32)) * 0.1
    nu = jnp.abs(jnp.asarray(rng.normal(size=(v, d)).astype(np.float32))) * 0.1
    ids = jnp.asarray(rng.choice(v, size=u, replace=False).astype(np.int32))
    grads = jnp.asarray(rng.normal(size=(u, d)).astype(np.float32))
    uids, g, valid = dedupe_grads(ids, grads)
    count = jnp.asarray(4, jnp.int32)
    t_ref, mu_ref, nu_ref, _ = sparse_adam(
        table, mu, nu, count, uids, g, valid, lr=1e-2, weight_decay=0.01
    )
    fat_new = fat_adam_rows(
        fat_pack(table, mu, nu), uids, g, count + 1, d=d, lr=1e-2,
        weight_decay=0.01, interpret=True,
    )
    t_pl, mu_pl, nu_pl = fat_components(fat_new, d)
    np.testing.assert_allclose(np.asarray(t_pl), np.asarray(t_ref), rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(mu_pl), np.asarray(mu_ref), rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(nu_pl), np.asarray(nu_ref), rtol=1e-5, atol=1e-6)
