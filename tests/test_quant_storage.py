"""Quantized embedding storage: bf16 tables + compressed optimizer slots
with stochastic rounding (fbgemm quantized-TBE / intra-training embedding
quantization parity).

The storage-dtype contract under test:

* tables and slots are STORED at the spec/slot dtype and COMPUTED in f32 —
  reads dequantize after the row gather, writes requantize through
  stochastic rounding keyed by a counter-derived threefry key folded from
  ``(state.step, table)``.  Same state + same batch => bitwise-identical
  update, on a fresh process too (kill/restart-identity rides on PR-1's
  step-granular resume).
* ``float32`` defaults stay KEY-FREE: quantize is the identity and no PRNG
  enters the graph, so default builds are byte-identical to the
  unquantized program.
* the grouped all-to-all exchanges vectors at STORAGE dtype (half the
  payload bytes for bf16) and never concatenates tables of different
  dtypes into one stream.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from tdfo_tpu.ops.quant import (
    component_key,
    quantize,
    sr_key,
    stochastic_round,
)
from tdfo_tpu.ops.sparse import sparse_optimizer
from tdfo_tpu.parallel.embedding import EmbeddingSpec, ShardedEmbeddingCollection
from tdfo_tpu.train.metrics import AUC
from tdfo_tpu.train.sparse_step import SparseTrainState, make_sparse_train_step

B, D = 64, 8


# ------------------------------------------------------------ quant unit


class TestStochasticRound:
    def test_identity_on_representable(self):
        """Values already exactly representable in bf16 must survive SR
        bit-for-bit under ANY key — this is what lets untouched rows ride a
        whole-block requantize without drift."""
        x = jnp.asarray(np.random.default_rng(0).normal(size=(256,)),
                        jnp.float32).astype(jnp.bfloat16).astype(jnp.float32)
        want = x.astype(jnp.bfloat16)
        for s in range(3):
            got = stochastic_round(x, jnp.bfloat16, jax.random.PRNGKey(s))
            np.testing.assert_array_equal(
                np.asarray(got).view(np.uint16), np.asarray(want).view(np.uint16))

    def test_rounds_to_neighbours_unbiased(self):
        """A value a quarter of the way between two bf16 neighbours lands on
        one of exactly those two, low-side ~75% of the time."""
        # bf16 (7 mantissa bits) neighbours of 1.0 are 1.0 and 1 + 2^-7
        lo, hi = 1.0, 1.0 + 2.0 ** -7
        v = lo + 0.25 * (hi - lo)
        n = 200_000
        x = jnp.full((n,), v, jnp.float32)
        out = np.asarray(stochastic_round(
            x, jnp.bfloat16, jax.random.PRNGKey(7)), dtype=np.float32)
        assert set(np.unique(out)) <= {lo, hi}
        p_hi = (out == hi).mean()
        # binomial std of the mean at p=0.25 over 200k draws ≈ 0.001
        assert abs(p_hi - 0.25) < 0.006, p_hi

    def test_deterministic_and_key_sensitive(self):
        x = jnp.asarray(np.random.default_rng(1).normal(size=(512,)),
                        jnp.float32)
        a = stochastic_round(x, jnp.bfloat16, jax.random.PRNGKey(3))
        b = stochastic_round(x, jnp.bfloat16, jax.random.PRNGKey(3))
        c = stochastic_round(x, jnp.bfloat16, jax.random.PRNGKey(4))
        np.testing.assert_array_equal(np.asarray(a).view(np.uint16),
                                      np.asarray(b).view(np.uint16))
        assert (np.asarray(a).view(np.uint16)
                != np.asarray(c).view(np.uint16)).any()

    def test_quantize_f32_and_keyless_paths(self):
        """quantize to f32 is the identity (key or not); bf16 without a key
        is plain round-to-nearest — the deterministic eval/export path."""
        x = jnp.asarray(np.random.default_rng(2).normal(size=(64,)),
                        jnp.float32)
        same = quantize(x, jnp.float32, jax.random.PRNGKey(0))
        np.testing.assert_array_equal(np.asarray(same).view(np.uint32),
                                      np.asarray(x).view(np.uint32))
        np.testing.assert_array_equal(
            np.asarray(quantize(x, jnp.bfloat16)).view(np.uint16),
            np.asarray(x.astype(jnp.bfloat16)).view(np.uint16))

    def test_sr_key_stream(self):
        """One key per (step, table), derived from the resume-surviving step
        counter: deterministic across processes, distinct across both axes."""
        k = lambda s, n: np.asarray(jax.random.key_data(sr_key(s, n)))
        np.testing.assert_array_equal(k(3, "user"), k(3, "user"))
        assert (k(3, "user") != k(4, "user")).any()
        assert (k(3, "user") != k(3, "item")).any()
        assert component_key(None, 1) is None
        ck = sr_key(0, "t")
        assert (np.asarray(jax.random.key_data(component_key(ck, 0)))
                != np.asarray(jax.random.key_data(component_key(ck, 1)))).any()


# ---------------------------------------------------- storage semantics


def _qspecs(n_tables, dtype, dim=D):
    return [
        EmbeddingSpec(name=f"t{i}", num_embeddings=40 + 9 * i,
                      embedding_dim=dim, features=(f"f{i}",),
                      sharding="row", init_scale=0.1, dtype=dtype)
        for i in range(n_tables)
    ]


def _qcoll(mesh, dtype, n_tables=3, *, grouped=True):
    return ShardedEmbeddingCollection(
        _qspecs(n_tables, dtype), mesh=mesh, grouped_a2a=grouped,
        fused_kind="adam",
    )


def _qfeats(mesh, n_tables=3, b=B, key=1):
    k = jax.random.PRNGKey(key)
    return {
        f"f{i}": jax.device_put(
            jax.random.randint(jax.random.fold_in(k, i), (b,), 0, 40),
            NamedSharding(mesh, P("model")))
        for i in range(n_tables)
    }


def test_tables_and_slots_stored_narrow(mesh8):
    coll = _qcoll(mesh8, jnp.bfloat16)
    tables = coll.init(jax.random.PRNGKey(0))
    for a, t in tables.items():
        assert t.dtype == jnp.bfloat16, a
        assert t.nbytes == t.size * 2, a  # half the f32 footprint
    opt = sparse_optimizer("adam", lr=1e-2, slot_dtype="bfloat16")
    slots = opt.init(jnp.zeros((40, D), jnp.bfloat16))
    assert slots[0].dtype == slots[1].dtype == jnp.bfloat16  # mu, nu
    # the rowwise accumulator is contractually f32 whatever slot_dtype says
    # (fbgemm EXACT_ROWWISE_ADAGRAD keeps a full-precision per-row count)
    row = sparse_optimizer("rowwise_adagrad", lr=1e-2, slot_dtype="bfloat16")
    assert row.init(jnp.zeros((40, D), jnp.bfloat16))[0].dtype == jnp.float32
    # reads dequantize AFTER the gather: lookup ships f32 activations
    embs = jax.jit(lambda t, f: coll.lookup(t, f, mode="alltoall"))(
        tables, _qfeats(mesh8))
    assert all(e.dtype == jnp.float32 for e in embs.values())


def test_grouped_exchange_carries_bf16_payload(mesh8):
    """The vector all_to_all moves bf16 — the bandwidth claim, pinned in
    the jaxpr; id exchange stays int32 and the op count stays 2."""
    coll = _qcoll(mesh8, jnp.bfloat16)
    tables = coll.init(jax.random.PRNGKey(0))
    j = str(jax.make_jaxpr(
        lambda t, f: coll.lookup(t, f, mode="alltoall"))(
            tables, _qfeats(mesh8)))
    a2a_lines = [ln for ln in j.splitlines() if "all_to_all" in ln]
    assert len(a2a_lines) == 2, j
    assert any("bf16[" in ln for ln in a2a_lines), a2a_lines


def test_mixed_dtype_tables_never_share_a_stream(mesh8):
    """Satellite: grouping keys on (dim, dtype).  bf16 and f32 tables of the
    same dim ride SEPARATE exchanges (2 each) and the forward stays bitwise
    equal to the per-table program."""
    specs = _qspecs(2, jnp.bfloat16) + [
        dataclasses.replace(s, name=f"g{i}", features=(f"h{i}",))
        for i, s in enumerate(_qspecs(2, jnp.float32))
    ]
    mk = lambda grouped: ShardedEmbeddingCollection(
        specs, mesh=mesh8, grouped_a2a=grouped, fused_kind="adam")
    grouped, per_table = mk(True), mk(False)
    tables = grouped.init(jax.random.PRNGKey(0))
    feats = dict(_qfeats(mesh8, 2))
    feats.update({f"h{i}": feats[f"f{i}"] for i in range(2)})
    j = str(jax.make_jaxpr(
        lambda t, f: grouped.lookup(t, f, mode="alltoall"))(tables, feats))
    assert j.count("all_to_all") == 4, j.count("all_to_all")
    lk_g = jax.jit(lambda t, f: grouped.lookup(t, f, mode="alltoall"))(
        tables, feats)
    lk_p = jax.jit(lambda t, f: per_table.lookup(t, f, mode="alltoall"))(
        tables, feats)
    for f in feats:
        np.testing.assert_array_equal(
            np.asarray(lk_g[f]), np.asarray(lk_p[f]), err_msg=f)


def test_grouped_update_bf16_matches_sequential_reference(mesh8):
    """Keyless (round-to-nearest) bf16 grouped update == the sequential
    per-table reference bitwise: identical f32 math, identical final
    requantize."""
    coll = _qcoll(mesh8, jnp.bfloat16)
    tables = coll.init(jax.random.PRNGKey(0))
    opt = sparse_optimizer("adam", lr=1e-2, slot_dtype="bfloat16")
    slots = {a: opt.init(t) for a, t in tables.items()}
    feats = _qfeats(mesh8)
    k = jax.random.PRNGKey(9)
    grads = {
        f: jax.device_put(
            jax.random.normal(jax.random.fold_in(k, i), (B, D)),
            NamedSharding(mesh8, P("model", None)))
        for i, f in enumerate(feats)
    }
    ref_t = {a: jnp.asarray(np.asarray(t)) for a, t in tables.items()}
    ref_s = {a: tuple(jnp.asarray(np.asarray(x)) for x in s)
             for a, s in slots.items()}
    for f in feats:
        aname, spec, off = coll.resolve(f)
        ids = jnp.asarray(np.asarray(feats[f])) + off
        ref_t[aname], ref_s[aname] = opt.update(
            ref_t[aname], ref_s[aname], ids,
            jnp.asarray(np.asarray(grads[f])), embedding_dim=D)
    got_t, got_s = jax.jit(
        lambda t, s, i, g: coll.grouped_update(opt, t, s, i, g)
    )(tables, slots, feats, grads)
    for a in got_t:
        assert got_t[a].dtype == jnp.bfloat16
        np.testing.assert_array_equal(
            np.asarray(ref_t[a]).view(np.uint16),
            np.asarray(got_t[a]).view(np.uint16), err_msg=a)
        for x, y in zip(ref_s[a], got_s[a]):
            np.testing.assert_array_equal(np.asarray(x, np.float32),
                                          np.asarray(y, np.float32))


# ------------------------------------------------------------ trajectory


def _label_fn(ids):
    return (np.asarray(ids) < 20).astype(np.float32)


def _traj_batches(n, seed=3):
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n):
        b = {f"f{i}": jnp.asarray(rng.integers(0, 40, B), jnp.int32)
             for i in range(3)}
        b["label"] = jnp.asarray(_label_fn(b["f0"]))
        out.append(b)
    return out


def _traj_forward(dense, embs, batch):
    logits = sum(e @ dense["w"] for e in embs.values())
    return optax.sigmoid_binary_cross_entropy(logits, batch["label"]).mean()


_TRAJ_LR = {"sgd": 1.0, "adagrad": 0.5, "rowwise_adagrad": 0.5, "adam": 0.3}


def _run_traj(mesh, dtype, kind, n_steps=32):
    coll = _qcoll(mesh, dtype)
    slot_dtype = ("float32" if (kind == "rowwise_adagrad"
                                or dtype == jnp.float32) else "bfloat16")
    step = make_sparse_train_step(
        coll, _traj_forward, mode="alltoall", donate=False)
    state = SparseTrainState.create(
        # nonzero dense read-out so the embeddings see gradient from step 0
        dense_params={"w": jnp.full((D,), 0.3)},
        tx=optax.adam(3e-2),
        tables=coll.init(jax.random.PRNGKey(0)),
        sparse_opt=sparse_optimizer(kind, lr=_TRAJ_LR[kind],
                                    slot_dtype=slot_dtype),
    )
    bs = _traj_batches(8)
    losses = []
    for s in range(n_steps):
        state, l = step(state, bs[s % len(bs)])
        losses.append(float(l))
    # held-out AUC
    hb = _traj_batches(4, seed=77)
    auc = AUC.empty(200)
    lookup = jax.jit(lambda t, f: coll.lookup(t, f, mode="alltoall"))
    for b in hb:
        embs = lookup(state.tables, {f: b[f] for f in coll.features()})
        logits = sum(np.asarray(e) @ np.asarray(state.dense_params["w"])
                     for e in embs.values())
        auc = auc.update(b["label"], jax.nn.sigmoid(jnp.asarray(logits)))
    return float(auc.result()), losses, state


@pytest.mark.parametrize("kind", ["sgd", "adagrad", "rowwise_adagrad", "adam"])
def test_bf16_sr_training_tracks_f32(mesh8, kind):
    """The headline quality claim on every EmbOptimType kind: bf16 tables
    (+ bf16 slots where the kind permits) with stochastic rounding reach
    held-out AUC within tolerance of the f32 run on a learnable synthetic
    CTR task."""
    auc_f32, losses_f32, _ = _run_traj(mesh8, jnp.float32, kind)
    auc_bf16, losses_bf16, _ = _run_traj(mesh8, jnp.bfloat16, kind)
    assert losses_f32[-1] < losses_f32[0], losses_f32
    assert losses_bf16[-1] < losses_bf16[0], losses_bf16
    assert auc_f32 > 0.75, (kind, auc_f32)
    assert abs(auc_f32 - auc_bf16) < 0.08, (kind, auc_f32, auc_bf16)


def test_bf16_sr_bit_deterministic_and_resume_identical(mesh8):
    """SR keys come from (state.step, table) only: two fresh runs of the
    same batches are bitwise identical, and a kill/restart after step 2
    (state round-tripped through host memory, step fn rebuilt — the PR-1
    resume path) replays into the SAME bits as the uninterrupted run."""
    coll = _qcoll(mesh8, jnp.bfloat16)
    bs = _traj_batches(4)

    def fresh_state():
        return SparseTrainState.create(
            dense_params={"w": jnp.full((D,), 0.3)},
            tx=optax.adam(1e-2),
            tables=coll.init(jax.random.PRNGKey(0)),
            sparse_opt=sparse_optimizer("adam", lr=0.3,
                                        slot_dtype="bfloat16"),
        )

    def run(step, state, batches):
        for b in batches:
            state, _ = step(state, b)
        return state

    step1 = make_sparse_train_step(coll, _traj_forward, mode="alltoall",
                                   donate=False)
    full_a = run(step1, fresh_state(), bs)
    full_b = run(step1, fresh_state(), bs)
    # interrupted run: host round-trip + a NEW step function mid-stream
    half = run(step1, fresh_state(), bs[:2])
    half = jax.tree_util.tree_map(lambda x: jnp.asarray(np.asarray(x)), half)
    step2 = make_sparse_train_step(coll, _traj_forward, mode="alltoall",
                                   donate=False)
    resumed = run(step2, half, bs[2:])
    assert int(resumed.step) == int(full_a.step) == len(bs)
    for name, want in full_a.tables.items():
        w16 = np.asarray(want).view(np.uint16)
        np.testing.assert_array_equal(
            w16, np.asarray(full_b.tables[name]).view(np.uint16),
            err_msg=f"{name}: rerun not deterministic")
        np.testing.assert_array_equal(
            w16, np.asarray(resumed.tables[name]).view(np.uint16),
            err_msg=f"{name}: resume diverged")


def test_f32_default_update_graph_is_key_free(mesh8):
    """float32 tables must never pay for the feature: no PRNG primitives in
    the step jaxpr (threefry shows up the moment a key is threaded), so the
    default program is the pre-quantization program."""
    coll = _qcoll(mesh8, jnp.float32)
    step = make_sparse_train_step(
        coll, _traj_forward, mode="alltoall", donate=False, jit=False)
    state = SparseTrainState.create(
        dense_params={"w": jnp.zeros((D,))},
        tx=optax.adam(1e-2),
        tables=coll.init(jax.random.PRNGKey(0)),
        sparse_opt=sparse_optimizer("adam", lr=0.3),
    )
    j = str(jax.make_jaxpr(step)(state, _traj_batches(1)[0]))
    assert "bf16" not in j
    assert not any(p in j for p in ("random_bits", "random_fold_in",
                                    "random_seed"))
    qc = _qcoll(mesh8, jnp.bfloat16)
    qstep = make_sparse_train_step(
        qc, _traj_forward, mode="alltoall", donate=False, jit=False)
    qstate = SparseTrainState.create(
        dense_params={"w": jnp.zeros((D,))},
        tx=optax.adam(1e-2),
        tables=qc.init(jax.random.PRNGKey(0)),
        sparse_opt=sparse_optimizer("adam", lr=0.3, slot_dtype="bfloat16"),
    )
    qj = str(jax.make_jaxpr(qstep)(qstate, _traj_batches(1)[0]))
    assert "random_bits" in qj and "bf16" in qj


# ------------------------------------------------- checkpoint + export


def test_dtype_stamps_refuse_mismatched_restore(tmp_path):
    """A bf16-stored checkpoint must refuse to restore into an f32 run and
    vice versa — restoring across storage dtypes would silently change
    every subsequent update."""
    from tdfo_tpu.train.checkpoint import CheckpointManager

    state = {"t": jnp.zeros((4, D), jnp.bfloat16)}
    dstamp = {"table_dtype": {"t0": "bfloat16"}, "slot_dtype": "bfloat16"}
    mgr = CheckpointManager(tmp_path / "q")
    mgr.save(0, state, stamps=dstamp)
    step, restored, _ = mgr.restore(state, stamps=dict(dstamp))
    assert step == 0 and restored["t"].dtype == jnp.bfloat16
    for bad in (None,                                      # f32-default run
                {"table_dtype": {"t0": "float32"},         # dtype flipped
                 "slot_dtype": "bfloat16"}):
        with pytest.raises(ValueError, match="stamps"):
            mgr.restore(state, stamps=bad)
    mgr.close()
    # f32-default checkpoint (no stamps) refused by a bf16 run
    mgr2 = CheckpointManager(tmp_path / "q2")
    mgr2.save(0, state)
    with pytest.raises(ValueError, match="stamps"):
        mgr2.restore(state, stamps=dict(dstamp))
    mgr2.close()


def test_export_upcasts_bf16_exactly(mesh8):
    """Serving bundles stay f32 at the interface: merged_tables upcasts
    bf16 rows exactly (every bf16 is representable in f32), so a
    quantized-training run exports through the unchanged pipeline."""
    from tdfo_tpu.serve.export import merged_tables

    coll = _qcoll(mesh8, jnp.bfloat16, n_tables=2, grouped=False)
    tables = coll.init(jax.random.PRNGKey(0))
    out = merged_tables(coll, tables)
    for i in range(2):
        spec = coll.specs[f"t{i}"]
        got = out[f"t{i}"]
        assert got.dtype == np.float32
        assert got.shape == (spec.num_embeddings, D)
        aname, _, off = coll.resolve_table(f"t{i}")
        want = np.asarray(jax.device_get(tables[aname]))[
            off:off + spec.num_embeddings].astype(np.float32)
        np.testing.assert_array_equal(got, want)


# ------------------------------------------------------------- int8 rows
# PR 12: int8 storage rides every bf16 lane plus a per-row f32
# (scale, offset) sidecar (__qscale__/ arrays in state.tables, the fbgemm
# rowwise-quantized TBE layout).


def test_int8_tables_store_codes_plus_sidecar(mesh8):
    """int8 tables are [V, D] codes (1 byte/row-element) plus a [V, 2] f32
    qscale sidecar; reads dequantize AFTER the gather so lookups still ship
    f32 activations; same-seed init is the RTN quantization of the f32
    init (max err <= scale/2 per row)."""
    from tdfo_tpu.ops.quant import dequantize_rows
    from tdfo_tpu.parallel.embedding import qscale_name

    coll = _qcoll(mesh8, jnp.int8)
    tables = coll.init(jax.random.PRNGKey(0))
    f32 = _qcoll(mesh8, jnp.float32).init(jax.random.PRNGKey(0))
    data_names = [a for a in tables if not a.startswith("__qscale__/")]
    assert data_names and all(qscale_name(a) in tables for a in data_names)
    for a in data_names:
        t, qs = tables[a], tables[qscale_name(a)]
        assert t.dtype == jnp.int8 and t.nbytes == t.size
        assert qs.dtype == jnp.float32 and qs.shape == (t.shape[0], 2)
        err = np.abs(np.asarray(dequantize_rows(t, qs)) - np.asarray(f32[a]))
        scale = np.asarray(qs)[:, :1]
        assert (err <= scale / 2 + 1e-7).all(), a
    embs = jax.jit(lambda t, f: coll.lookup(t, f, mode="alltoall"))(
        tables, _qfeats(mesh8))
    assert all(e.dtype == jnp.float32 for e in embs.values())


def test_grouped_exchange_carries_int8_payload(mesh8):
    """Jaxpr pin (acceptance criterion): with table_dtype="int8" +
    grouped_a2a the VECTOR all_to_all payload is i8 — a quarter of the f32
    wire bytes — and the (scale, offset) rows ride a separate small f32
    collective; ids stay int32."""
    coll = _qcoll(mesh8, jnp.int8)
    tables = coll.init(jax.random.PRNGKey(0))
    j = str(jax.make_jaxpr(
        lambda t, f: coll.lookup(t, f, mode="alltoall"))(
            tables, _qfeats(mesh8)))
    a2a_lines = [ln for ln in j.splitlines() if "all_to_all" in ln]
    assert len(a2a_lines) == 3, j  # ids (i32) + codes (i8) + qscale (f32)
    assert any("i8[" in ln for ln in a2a_lines), a2a_lines
    qs_lines = [ln for ln in a2a_lines if "f32[" in ln and "i8[" not in ln]
    assert len(qs_lines) == 1, a2a_lines  # the sidecar exchange, nothing fat
    # the sidecar is (scale, offset) pairs: trailing dim 2
    assert ",2]" in qs_lines[0].split("all_to_all")[0], qs_lines


def test_int8_lookup_matches_per_table_modes(mesh8):
    """Grouped, per-table alltoall, psum, and gspmd lookups agree bitwise
    on int8 tables: dequantize commutes with every exchange program because
    each dequantizes at the row's OWNER before mixing rows across tables."""
    coll_g = _qcoll(mesh8, jnp.int8, grouped=True)
    coll_p = _qcoll(mesh8, jnp.int8, grouped=False)
    tables = coll_g.init(jax.random.PRNGKey(0))
    feats = _qfeats(mesh8)
    want = jax.jit(lambda t, f: coll_g.lookup(t, f, mode="gspmd"))(
        tables, feats)
    for coll, mode in ((coll_g, "alltoall"), (coll_p, "alltoall"),
                      (coll_p, "psum")):
        got = jax.jit(lambda t, f, _m=mode, _c=coll: _c.lookup(
            t, f, mode=_m))(tables, feats)
        for f in feats:
            np.testing.assert_array_equal(
                np.asarray(want[f]).view(np.uint32),
                np.asarray(got[f]).view(np.uint32),
                err_msg=f"{mode}:{f}")


@pytest.mark.parametrize("kind", ["sgd", "adagrad", "rowwise_adagrad", "adam"])
def test_int8_sr_training_tracks_f32(mesh8, kind):
    """Acceptance criterion: int8 rowwise storage with SR requantize reaches
    held-out AUC within tolerance of f32 on the synthetic CTR task, for all
    four EmbOptimType kinds."""
    auc_f32, losses_f32, _ = _run_traj(mesh8, jnp.float32, kind)
    auc_i8, losses_i8, _ = _run_traj(mesh8, jnp.int8, kind)
    assert losses_f32[-1] < losses_f32[0], losses_f32
    assert losses_i8[-1] < losses_i8[0], losses_i8
    assert auc_f32 > 0.75, (kind, auc_f32)
    assert abs(auc_f32 - auc_i8) < 0.1, (kind, auc_f32, auc_i8)


def test_int8_sr_bit_deterministic_and_resume_identical(mesh8):
    """Rerun and kill/resume identity for int8: SR keys fold from
    (state.step, table) only, and the qscale sidecar rides state.tables, so
    a host round-trip restores codes AND grids bit-exactly."""
    coll = _qcoll(mesh8, jnp.int8)
    bs = _traj_batches(4)

    def fresh_state():
        return SparseTrainState.create(
            dense_params={"w": jnp.full((D,), 0.3)},
            tx=optax.adam(1e-2),
            tables=coll.init(jax.random.PRNGKey(0)),
            sparse_opt=sparse_optimizer("adam", lr=0.3,
                                        slot_dtype="bfloat16"),
        )

    def run(step, state, batches):
        for b in batches:
            state, _ = step(state, b)
        return state

    step1 = make_sparse_train_step(coll, _traj_forward, mode="alltoall",
                                   donate=False)
    full_a = run(step1, fresh_state(), bs)
    full_b = run(step1, fresh_state(), bs)
    half = run(step1, fresh_state(), bs[:2])
    half = jax.tree_util.tree_map(lambda x: jnp.asarray(np.asarray(x)), half)
    step2 = make_sparse_train_step(coll, _traj_forward, mode="alltoall",
                                   donate=False)
    resumed = run(step2, half, bs[2:])
    assert int(resumed.step) == int(full_a.step) == len(bs)
    for name, want in full_a.tables.items():
        w = np.asarray(want)
        np.testing.assert_array_equal(
            w, np.asarray(full_b.tables[name]),
            err_msg=f"{name}: rerun not deterministic")
        np.testing.assert_array_equal(
            w, np.asarray(resumed.tables[name]),
            err_msg=f"{name}: resume diverged")


def test_f32_default_graph_has_no_int8(mesh8):
    """Extends the PR 5 key-free pin: the f32 default step jaxpr contains
    no i8 buffers and no PRNG, while the int8 step contains both — the
    feature costs nothing unless switched on."""
    coll = _qcoll(mesh8, jnp.float32)
    step = make_sparse_train_step(
        coll, _traj_forward, mode="alltoall", donate=False, jit=False)
    state = SparseTrainState.create(
        dense_params={"w": jnp.zeros((D,))},
        tx=optax.adam(1e-2),
        tables=coll.init(jax.random.PRNGKey(0)),
        sparse_opt=sparse_optimizer("adam", lr=0.3),
    )
    j = str(jax.make_jaxpr(step)(state, _traj_batches(1)[0]))
    assert "i8[" not in j
    assert not any(p in j for p in ("random_bits", "random_fold_in",
                                    "random_seed"))
    qc = _qcoll(mesh8, jnp.int8)
    qstep = make_sparse_train_step(
        qc, _traj_forward, mode="alltoall", donate=False, jit=False)
    qstate = SparseTrainState.create(
        dense_params={"w": jnp.zeros((D,))},
        tx=optax.adam(1e-2),
        tables=qc.init(jax.random.PRNGKey(0)),
        sparse_opt=sparse_optimizer("adam", lr=0.3, slot_dtype="bfloat16"),
    )
    qj = str(jax.make_jaxpr(qstep)(qstate, _traj_batches(1)[0]))
    assert "random_bits" in qj and "i8[" in qj


def test_int8_hbm_geometry_criteo_profile():
    """Acceptance criterion: plan/costs.py geometry shows >= 3.5x table HBM
    drop vs f32 at the Criteo d=64 profile.  At d=16 the narrow-tile rule
    (<=16 lanes stay unpadded for BOTH dtypes) caps the win at the honest
    byte ratio — pinned >= 2.4x so the docstring's ceiling stays true."""
    from tdfo_tpu.plan.costs import line_geometry, table_hbm_bytes

    V = 33_762_577  # the Criteo-TB vocab the ROADMAP names
    for dim, floor in ((64, 3.5), (16, 2.4)):
        f32 = table_hbm_bytes(V, dim, optimizer="sgd", dtype="float32")
        i8 = table_hbm_bytes(V, dim, optimizer="sgd", dtype="int8")
        assert f32 / i8 >= floor, (dim, f32 / i8)
    # the lifted composition: fused int8 prices a byte-container line of
    # [codes | 8 B (scale, offset) sidecar | packed f32 slots] per row.
    # At d=64 the byte packing beats plain int8's f32 slot lane padding
    # (adam: 640 vs 1160 B/row); at d=16 plain slots already tile narrow
    # so fusing only rounds rows UP to a power-of-two line — never pick
    # fused int8 for HBM at d<=16.
    for opt, width, rpl in (("sgd", 128, 1), ("adagrad", 384, 1),
                            ("adam", 640, 1)):
        assert line_geometry(64, opt, "int8") == (width, rpl)
        fused = table_hbm_bytes(V, 64, optimizer=opt, dtype="int8",
                                fused=True)
        plain = table_hbm_bytes(V, 64, optimizer=opt, dtype="int8")
        assert fused == V * width
        assert fused < plain, (opt, fused, plain)
    assert line_geometry(16, "sgd", "int8") == (32, 4)
    assert table_hbm_bytes(V, 16, optimizer="sgd", dtype="int8",
                           fused=True) > \
        table_hbm_bytes(V, 16, optimizer="sgd", dtype="int8")
    # the one retained geometry refusal: rowwise_adagrad's shared scalar
    # accumulator has no per-row byte-container home
    with pytest.raises(ValueError, match="rowwise_adagrad"):
        line_geometry(64, "rowwise_adagrad", "int8")
    with pytest.raises(ValueError, match="rowwise_adagrad"):
        table_hbm_bytes(V, 64, optimizer="rowwise_adagrad", dtype="int8",
                        fused=True)


def test_int8_stamps_refuse_mismatched_restore(tmp_path):
    """Both directions (mirrors the PR 5/8 stamp tests): an int8 checkpoint
    carries table_dtype=int8 + qscale_layout and refuses to restore into an
    f32 run, a run with no layout stamp, or a run on a DIFFERENT sidecar
    layout; a stampless f32 checkpoint refuses an int8 run."""
    from tdfo_tpu.ops.quant import QSCALE_LAYOUT
    from tdfo_tpu.train.checkpoint import CheckpointManager

    state = {"t": jnp.zeros((4, D), jnp.int8),
             "__qscale__/t": jnp.zeros((4, 2), jnp.float32)}
    stamp = {"table_dtype": {"t0": "int8"}, "slot_dtype": "bfloat16",
             "qscale_layout": QSCALE_LAYOUT}
    mgr = CheckpointManager(tmp_path / "q")
    mgr.save(0, state, stamps=stamp)
    step, restored, _ = mgr.restore(state, stamps=dict(stamp))
    assert step == 0 and restored["t"].dtype == jnp.int8
    for bad in (None,                                       # f32-default run
                {"table_dtype": {"t0": "float32"},          # dtype flipped
                 "slot_dtype": "bfloat16"},
                {**stamp, "qscale_layout": "rowwise-f32-scale-offset-v2"},
                {k: v for k, v in stamp.items()             # layout dropped
                 if k != "qscale_layout"}):
        with pytest.raises(ValueError, match="stamps"):
            mgr.restore(state, stamps=bad)
    mgr.close()
    # stampless f32 checkpoint refused by an int8 run (other direction)
    mgr2 = CheckpointManager(tmp_path / "q2")
    mgr2.save(0, state)
    with pytest.raises(ValueError, match="stamps"):
        mgr2.restore(state, stamps=dict(stamp))
    mgr2.close()
    # fused int8 packs the sidecar IN-LINE (no __qscale__/ array): the
    # qscale_storage stamp keys the layout, so a legacy int8-unfused
    # checkpoint refuses to restore into an int8-fused run and vice versa
    fused = {**stamp, "qscale_storage": {"t0": "fat-inline"}}
    mgr3 = CheckpointManager(tmp_path / "q3")
    mgr3.save(0, state, stamps=fused)
    assert mgr3.restore(state, stamps=dict(fused))[0] == 0
    with pytest.raises(ValueError, match="stamps"):
        mgr3.restore(state, stamps=dict(stamp))     # fused ckpt, unfused run
    mgr3.close()
    mgr4 = CheckpointManager(tmp_path / "q4")
    mgr4.save(0, state, stamps=dict(stamp))
    with pytest.raises(ValueError, match="stamps"):
        mgr4.restore(state, stamps=dict(fused))     # unfused ckpt, fused run
    mgr4.close()


def test_trainer_stamps_qscale_layout(tmp_path):
    """The trainer's checkpoint stamps carry qscale_layout exactly when an
    int8 table is configured — f32/bf16 runs keep the stamp absent so their
    sidecars stay byte-compatible with pre-int8 checkpoints.  The newly
    legal combos stamp COMPOSITIONALLY: fused int8 adds the per-array
    qscale_storage key (sidecar rides the fat line), cache-fronted int8
    adds update_cache — qscale_layout alongside both."""
    from tdfo_tpu.core.config import read_configs
    from tdfo_tpu.ops.quant import QSCALE_LAYOUT
    from tdfo_tpu.train.trainer import Trainer

    size_map = {"user": 100, "item": 80, "language": 8, "is_ebook": 2,
                "format": 8, "publisher": 16, "pub_decade": 16}

    def build(embeddings=None, **kw):
        cfg = read_configs(
            None, model="dlrm", data_dir=str(tmp_path), embed_dim=8,
            size_map=size_map, stack_tables=False,
            embeddings=embeddings or {}, **kw)
        return Trainer(cfg, log_dir=tmp_path)

    t = build(dict(table_dtype="int8", slot_dtype="bfloat16"))
    assert t._ckpt_stamps.get("qscale_layout") == QSCALE_LAYOUT
    assert "qscale_storage" not in t._ckpt_stamps
    assert t.state.tables["user_embed"].dtype == jnp.int8
    assert "__qscale__/user_embed" in t.state.tables
    t2 = build()
    assert "qscale_layout" not in (t2._ckpt_stamps or {})
    # int8 x fused (threshold 0 fuses every table): the sidecar moves into
    # the byte-container line, stamped per array so unfused checkpoints
    # refuse fused runs (and vice versa — see the restore test above)
    tf = build(dict(table_dtype="int8"), fused_table_threshold=0)
    assert tf._ckpt_stamps["qscale_layout"] == QSCALE_LAYOUT
    assert set(tf._ckpt_stamps["qscale_storage"].values()) == {"fat-inline"}
    fats = [a for a in tf.state.tables.values() if a.ndim == 3]
    assert fats and all(a.dtype == jnp.int8 for a in fats)  # byte containers
    assert not any(k.startswith("__qscale__/") for k in tf.state.tables)
    # int8 x update cache: both stamps ride together
    tc = build(dict(table_dtype="int8", cache_rows=64),
               lookup_mode="gspmd")
    assert tc._ckpt_stamps["qscale_layout"] == QSCALE_LAYOUT
    assert tc._ckpt_stamps["update_cache"]["cache_rows"] == 64


def test_export_dequantizes_int8_exactly(mesh8):
    """merged_tables inverts int8 storage through the sidecar: the bundle
    rows are exactly dequantize_rows(codes, qscale) in f32 — never a raw
    cast of the codes."""
    from tdfo_tpu.ops.quant import dequantize_rows
    from tdfo_tpu.parallel.embedding import qscale_name
    from tdfo_tpu.serve.export import merged_tables

    coll = _qcoll(mesh8, jnp.int8, n_tables=2, grouped=False)
    tables = coll.init(jax.random.PRNGKey(0))
    out = merged_tables(coll, tables)
    for i in range(2):
        spec = coll.specs[f"t{i}"]
        got = out[f"t{i}"]
        assert got.dtype == np.float32
        assert got.shape == (spec.num_embeddings, D)
        aname, _, off = coll.resolve_table(f"t{i}")
        sl = slice(off, off + spec.num_embeddings)
        want = np.asarray(dequantize_rows(
            np.asarray(jax.device_get(tables[aname]))[sl],
            np.asarray(jax.device_get(tables[qscale_name(aname)]))[sl]),
            dtype=np.float32)
        np.testing.assert_array_equal(got, want)
