"""Worker for the fleet/canary-gatekeeper drills (run as a subprocess,
NOT pytest).

Usage:
    python fleet_worker.py <spec_json_path>

Spec keys: ``data_dir``, ``checkpoint_dir``, ``log_dir``, ``request_log``
(a fleet layout — ``replica-<k>`` subdirectories), ``out_json``,
``local_devices``, ``steps_per_cycle``, ``max_cycles``, ``replicas``,
``canary_cycles``, ``canary_fraction``, ``max_auc_regression``,
``max_p99_regression_ms``, ``shadow_eval_batches``, ``keep_versions``,
``keep_consumed_segments``, ``telemetry`` (a ``[telemetry]`` dict — trace
/ log_rotate_bytes), ``faults`` (a ``[faults]`` dict —
regress_auc_at_cycle / kill_during_canary / kill_replica_nth /
kill_replica_signal / corrupt_candidate / kill_between_stages /
kill_during_swap / slow_canary_at_cycle + slow_score_ms),
``fleet_mode`` ("inproc" default; "process" runs the fleet as real OS
processes behind the socket ingress — tests/test_fleet_process.py),
``probe_seed``.

Spoofs CPU devices and runs the REAL gated ``OnlineLoop``
(``train/online.py`` with ``[online] canary_cycles > 0``) over a
``ServingFleet`` of ``[serving] replicas`` frontends sharing one
``BundleStore``.  On completion it scores a deterministic probe trace
through EVERY alive replica's live micro-batcher and writes the verdict to
``out_json``: final store version + digest, canary/rejection ledgers, the
merged replay cursor, per-replica served logits and per-replica served
versions.  Injected hard kills exit via ``os._exit(KILL_EXIT_CODE)`` and
write nothing; restarting the SAME spec must converge bitwise
(tests/test_fleet.py asserts it).
"""

import json
import sys
from pathlib import Path


def main() -> None:
    spec = json.loads(Path(sys.argv[1]).read_text())

    from tdfo_tpu.core.mesh import spoof_cpu_devices

    spoof_cpu_devices(int(spec.get("local_devices", 8)))

    import jax

    jax.config.update("jax_default_matmul_precision", "highest")

    from tdfo_tpu.core.config import load_size_map, read_configs
    from tdfo_tpu.train.online import OnlineLoop

    cfg = read_configs(
        None,
        data_dir=spec["data_dir"],
        model="twotower",
        model_parallel=True,
        n_epochs=1,
        learning_rate=3e-3,
        embed_dim=8,
        per_device_train_batch_size=8,
        per_device_eval_batch_size=8,
        shuffle_buffer_size=500,
        log_every_n_steps=1000,
        size_map=load_size_map(spec["data_dir"]),
        checkpoint_dir=spec["checkpoint_dir"],
        faults=dict(spec.get("faults") or {}),
        telemetry=dict(spec.get("telemetry") or {}),
        serving=dict(
            replicas=int(spec.get("replicas", 2)),
            keep_versions=int(spec.get("keep_versions", 0)),
            # "process" runs the fleet as real OS processes behind the
            # socket ingress (serve/supervisor.py); kill drills then use
            # [faults] kill_replica_signal (a real SIGKILL) instead of the
            # in-process kill_replica_nth flag
            fleet_mode=str(spec.get("fleet_mode", "inproc")),
        ),
        online=dict(
            request_log=spec["request_log"],
            steps_per_cycle=int(spec.get("steps_per_cycle", 2)),
            max_cycles=int(spec.get("max_cycles", 0)),
            canary_cycles=int(spec.get("canary_cycles", 1)),
            canary_fraction=float(spec.get("canary_fraction", 0.5)),
            max_auc_regression=float(spec.get("max_auc_regression", 0.3)),
            max_p99_regression_ms=float(
                spec.get("max_p99_regression_ms", 0.0)),
            shadow_eval_batches=int(spec.get("shadow_eval_batches", 1)),
            keep_consumed_segments=int(
                spec.get("keep_consumed_segments", 0)),
        ),
    )
    loop = OnlineLoop(cfg, log_dir=spec["log_dir"])
    try:
        _probe_and_report(loop, cfg, spec)
    finally:
        loop.close()  # even on a crash: never leak replica children


def _probe_and_report(loop, cfg, spec: dict) -> None:
    import numpy as np

    from tdfo_tpu.serve.export import read_raw_bundle
    from tdfo_tpu.serve.frontend import _column_vocab
    from tdfo_tpu.train.trainer import _ctr_columns

    stats = loop.run()

    # deterministic probe trace through EVERY alive replica's live batcher:
    # the per-replica served-logits fingerprint the fleet-convergence and
    # bitwise-rollback acceptance compares
    cat_cols, cont_cols = _ctr_columns(cfg)
    vocab = _column_vocab(cfg, cat_cols)
    rng = np.random.default_rng(int(spec.get("probe_seed", 606)))
    requests = []
    for i, n in enumerate((3, 5, 2, 8)):
        batch = {c: rng.integers(0, vocab[c], size=n, dtype=np.int32)
                 for c in cat_cols}
        for c in cont_cols:
            batch[c] = rng.random(n, dtype=np.float32)
        requests.append((f"probe{i}", batch))
    per_replica = loop.fleet.probe_each(requests)

    # process fleets: how often the supervisor respawned each replica (the
    # SIGKILL drill asserts the victim's lineage actually died and came back)
    respawns = {str(k): v
                for k, v in getattr(getattr(loop.fleet, "supervisor", None),
                                    "respawns", {}).items()}

    manifest, _ = read_raw_bundle(loop.store.current_dir())
    Path(spec["out_json"]).write_text(json.dumps({
        "stats": stats,
        "version": int(loop.store.current_version()),
        "digest": manifest["digest"],
        "canary_version": loop.store.canary_version(),
        "rejections": loop.store.rejections(),
        "cursor": loop.consumer.cursor(),
        "cycles_done": int(loop.cycles_done),
        "replica_versions": {str(k): v
                             for k, v in loop.fleet.versions().items()},
        "dead_replicas": sorted(loop.fleet._dead),
        "respawns": respawns,
        "logits": {str(rid): {q: np.asarray(v).tolist()
                              for q, v in res.items()}
                   for rid, res in per_replica.items()},
    }))


if __name__ == "__main__":
    main()
