"""Worker for the fleet/canary-gatekeeper drills (run as a subprocess,
NOT pytest).

Usage:
    python fleet_worker.py <spec_json_path>

Spec keys: ``data_dir``, ``checkpoint_dir``, ``log_dir``, ``request_log``
(a fleet layout — ``replica-<k>`` subdirectories), ``out_json``,
``local_devices``, ``steps_per_cycle``, ``max_cycles``, ``replicas``,
``canary_cycles``, ``canary_fraction``, ``max_auc_regression``,
``max_p99_regression_ms``, ``shadow_eval_batches``, ``keep_versions``,
``keep_consumed_segments``, ``telemetry`` (a ``[telemetry]`` dict — trace
/ log_rotate_bytes), ``faults`` (a ``[faults]`` dict —
regress_auc_at_cycle / kill_during_canary / kill_replica_nth /
kill_replica_signal / corrupt_candidate / kill_between_stages /
kill_during_swap / slow_canary_at_cycle + slow_score_ms),
``fleet_mode`` ("inproc" default; "process" runs the fleet as real OS
processes behind the socket ingress — tests/test_fleet_process.py),
``probe_seed``, ``model`` ("twotower" default; "bert4rec" runs the gated
loop over the SEQUENCE serving family — requires ``n_items`` from the seq
preprocessing stats and request logs carrying ``seqs``/``cands`` panels)
and ``n_items``.

For the bert4rec drill the worker additionally records a served-vs-eval
fingerprint: the SAME probe requests are scored through every replica's
live scorer (``score_direct``) AND through the trainer's own eval chain
(``coll.lookup -> backbone.apply -> score_candidates``, the
``trainer.py`` seq eval step) — once BEFORE ``loop.run()`` against the
pristine v0 head and once AFTER against the promoted head — so the test
can assert the serving path is bitwise-equal to the eval step on both
sides of the swap.

Spoofs CPU devices and runs the REAL gated ``OnlineLoop``
(``train/online.py`` with ``[online] canary_cycles > 0``) over a
``ServingFleet`` of ``[serving] replicas`` frontends sharing one
``BundleStore``.  On completion it scores a deterministic probe trace
through EVERY alive replica's live micro-batcher and writes the verdict to
``out_json``: final store version + digest, canary/rejection ledgers, the
merged replay cursor, per-replica served logits and per-replica served
versions.  Injected hard kills exit via ``os._exit(KILL_EXIT_CODE)`` and
write nothing; restarting the SAME spec must converge bitwise
(tests/test_fleet.py asserts it).
"""

import json
import sys
from pathlib import Path


def main() -> None:
    spec = json.loads(Path(sys.argv[1]).read_text())

    from tdfo_tpu.core.mesh import spoof_cpu_devices

    spoof_cpu_devices(int(spec.get("local_devices", 8)))

    import jax

    jax.config.update("jax_default_matmul_precision", "highest")

    from tdfo_tpu.core.config import load_size_map, read_configs
    from tdfo_tpu.train.online import OnlineLoop

    model = str(spec.get("model", "twotower"))
    if model == "bert4rec":
        # the second serving family: masked-position scoring over replay
        # panels.  history_buckets covers the probe sizes (2/4/8) AND the
        # heartbeat's shadow-slice batch (32 = per-device 8 x data axis 4)
        # so the shared scorer's jit cache stays within the batcher's
        # bounded-cache invariant.
        model_kw = dict(model="bert4rec", n_heads=2, n_layers=1, max_len=12,
                        sliding_step=6,
                        size_map={"n_items": int(spec["n_items"])})
        serving_kw = dict(max_batch=8, history_buckets=[2, 4, 8, 32])
    else:
        model_kw = dict(model="twotower",
                        size_map=load_size_map(spec["data_dir"]))
        serving_kw = {}
    cfg = read_configs(
        None,
        data_dir=spec["data_dir"],
        model_parallel=True,
        n_epochs=1,
        learning_rate=3e-3,
        embed_dim=8,
        per_device_train_batch_size=8,
        per_device_eval_batch_size=8,
        shuffle_buffer_size=500,
        log_every_n_steps=1000,
        checkpoint_dir=spec["checkpoint_dir"],
        faults=dict(spec.get("faults") or {}),
        telemetry=dict(spec.get("telemetry") or {}),
        serving=dict(
            replicas=int(spec.get("replicas", 2)),
            keep_versions=int(spec.get("keep_versions", 0)),
            # "process" runs the fleet as real OS processes behind the
            # socket ingress (serve/supervisor.py); kill drills then use
            # [faults] kill_replica_signal (a real SIGKILL) instead of the
            # in-process kill_replica_nth flag
            fleet_mode=str(spec.get("fleet_mode", "inproc")),
            **serving_kw,
        ),
        online=dict(
            request_log=spec["request_log"],
            steps_per_cycle=int(spec.get("steps_per_cycle", 2)),
            max_cycles=int(spec.get("max_cycles", 0)),
            canary_cycles=int(spec.get("canary_cycles", 1)),
            canary_fraction=float(spec.get("canary_fraction", 0.5)),
            max_auc_regression=float(spec.get("max_auc_regression", 0.3)),
            max_p99_regression_ms=float(
                spec.get("max_p99_regression_ms", 0.0)),
            shadow_eval_batches=int(spec.get("shadow_eval_batches", 1)),
            keep_consumed_segments=int(
                spec.get("keep_consumed_segments", 0)),
        ),
        **model_kw,
    )
    loop = OnlineLoop(cfg, log_dir=spec["log_dir"])
    try:
        _probe_and_report(loop, cfg, spec)
    finally:
        loop.close()  # even on a crash: never leak replica children


def _ctr_probe_trace(cfg, rng):
    import numpy as np

    from tdfo_tpu.serve.frontend import _column_vocab
    from tdfo_tpu.train.trainer import _ctr_columns

    cat_cols, cont_cols = _ctr_columns(cfg)
    vocab = _column_vocab(cfg, cat_cols)
    requests = []
    for i, n in enumerate((3, 5, 2, 8)):
        batch = {c: rng.integers(0, vocab[c], size=n, dtype=np.int32)
                 for c in cat_cols}
        for c in cont_cols:
            batch[c] = rng.random(n, dtype=np.float32)
        requests.append((f"probe{i}", batch))
    return requests


def _seq_probe_trace(cfg, spec: dict, rng):
    """Masked-position probe panels: windowed histories + candidate sets.

    Sizes are drawn from the configured ``history_buckets`` so the direct
    served-vs-eval probes below never add a jit-cache shape the batcher's
    bounded-cache invariant did not budget for."""
    import numpy as np

    from tdfo_tpu.data.seq_preprocessing import EVAL_NEG_NUM
    from tdfo_tpu.serve.seq_scoring import history_window

    n_items = int(spec["n_items"])
    requests = []
    for i, n in enumerate((2, 4, 8, 8)):
        seqs = np.stack([
            history_window(
                rng.integers(1, n_items + 1,
                             size=int(rng.integers(1, 2 * cfg.max_len))),
                n_items=n_items, max_len=cfg.max_len)
            for _ in range(n)])
        cands = rng.integers(
            1, n_items + 1, size=(n, EVAL_NEG_NUM + 1)).astype(np.int32)
        requests.append((f"probe{i}", {"seqs": seqs, "cands": cands}))
    return requests


def _seq_eval_chain(loop, cfg):
    """The trainer's own seq eval step (trainer.py eval_accum inner chain):
    the bitwise reference the served masked-position logits must equal."""
    import jax

    from tdfo_tpu.models.bert4rec import key_padding_mask
    from tdfo_tpu.train.seq import score_candidates

    coll, backbone = loop.trainer.coll, loop.trainer.backbone
    mode = cfg.lookup_mode

    @jax.jit
    def eval_scores(tables, dense_params, seqs, cands):
        embs = coll.lookup(tables, {"item": seqs}, mode=mode)
        logits = backbone.apply({"params": dense_params}, embs["item"],
                                key_padding_mask(seqs))
        return score_candidates(logits, cands)

    return eval_scores


def _seq_served_vs_eval(loop, eval_scores, requests) -> dict:
    """Score the probe trace through the trainer eval chain AND every alive
    replica's live scorer (``score_direct`` — the heartbeat path, which does
    not append to the request logs, so pre-run probes cannot perturb the
    replayed traffic)."""
    import numpy as np

    state = loop.trainer.state
    evals = {rid: np.asarray(eval_scores(
                 state.tables, state.dense_params,
                 batch["seqs"], batch["cands"])).tolist()
             for rid, batch in requests}
    served = {str(r.replica_id): {
        rid: np.asarray(r.score_direct(
            {k: np.array(v) for k, v in batch.items()})).tolist()
        for rid, batch in requests} for r in loop.fleet.alive()}
    return {"eval": evals, "served": served}


def _probe_and_report(loop, cfg, spec: dict) -> None:
    import numpy as np

    from tdfo_tpu.serve.export import read_raw_bundle

    # deterministic probe trace through EVERY alive replica's live batcher:
    # the per-replica served-logits fingerprint the fleet-convergence and
    # bitwise-rollback acceptance compares
    rng = np.random.default_rng(int(spec.get("probe_seed", 606)))
    served_eval = None
    if str(spec.get("model", "twotower")) == "bert4rec":
        requests = _seq_probe_trace(cfg, spec, rng)
        eval_scores = _seq_eval_chain(loop, cfg)
        # before the swap: the fleet serves the pristine v0 bundle and the
        # trainer holds the matching pristine state
        served_eval = {"pre": _seq_served_vs_eval(loop, eval_scores,
                                                  requests)}
    else:
        requests = _ctr_probe_trace(cfg, rng)

    stats = loop.run()

    if served_eval is not None:
        # after the swap: the fleet serves the promoted head and the trainer
        # holds the state that exported it
        served_eval["final"] = _seq_served_vs_eval(loop, eval_scores,
                                                   requests)
    per_replica = loop.fleet.probe_each(requests)

    # process fleets: how often the supervisor respawned each replica (the
    # SIGKILL drill asserts the victim's lineage actually died and came back)
    respawns = {str(k): v
                for k, v in getattr(getattr(loop.fleet, "supervisor", None),
                                    "respawns", {}).items()}

    manifest, _ = read_raw_bundle(loop.store.current_dir())
    report = {
        "stats": stats,
        "version": int(loop.store.current_version()),
        "digest": manifest["digest"],
        "canary_version": loop.store.canary_version(),
        "rejections": loop.store.rejections(),
        "cursor": loop.consumer.cursor(),
        "cycles_done": int(loop.cycles_done),
        "replica_versions": {str(k): v
                             for k, v in loop.fleet.versions().items()},
        "dead_replicas": sorted(loop.fleet._dead),
        "respawns": respawns,
        "logits": {str(rid): {q: np.asarray(v).tolist()
                              for q, v in res.items()}
                   for rid, res in per_replica.items()},
    }
    if served_eval is not None:
        report["served_eval"] = served_eval
    Path(spec["out_json"]).write_text(json.dumps(report))


if __name__ == "__main__":
    main()
