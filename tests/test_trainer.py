"""Trainer end-to-end on the spoofed 8-device mesh: both workloads, resume.

Covers the loop capabilities of all five reference main()s (SURVEY.md §3):
epoch driving, padded eval, metric computation, checkpoint/resume with
optimizer state, and the CLI wiring.
"""

import json

import numpy as np
import pytest

from tdfo_tpu.core.config import read_configs
from tdfo_tpu.data.ctr_preprocessing import run_ctr_preprocessing
from tdfo_tpu.data.seq_preprocessing import run_seq_preprocessing
from tdfo_tpu.data.synthetic import write_synthetic_goodreads
from tdfo_tpu.train.trainer import Trainer, pad_batch


@pytest.fixture(scope="module")
def prepared_dir(tmp_path_factory):
    d = tmp_path_factory.mktemp("gr")
    write_synthetic_goodreads(d, n_users=100, n_books=150,
                              interactions_per_user=(15, 50), seed=3)
    ctr = run_ctr_preprocessing(d)
    seq = run_seq_preprocessing(d, max_len=12, sliding_step=6, seed=3)
    return d, ctr, seq


def test_pad_batch():
    b = {"x": np.arange(5, dtype=np.float32), "y": np.ones((5, 3))}
    padded, w = pad_batch(b, 8)
    assert padded["x"].shape == (8,) and padded["y"].shape == (8, 3)
    assert w.tolist() == [1] * 5 + [0] * 3
    same, w2 = pad_batch(b, 5)
    assert same is b or same["x"].shape == (5,)
    assert w2.sum() == 5


def test_twotower_trainer_fits_and_improves(prepared_dir, tmp_path):
    d, ctr, _ = prepared_dir
    cfg = read_configs(
        None,
        data_dir=d,
        model="twotower",
        n_epochs=2,
        learning_rate=3e-3,
        embed_dim=16,
        per_device_train_batch_size=16,
        per_device_eval_batch_size=16,
        shuffle_buffer_size=1000,
        log_every_n_steps=1000,
        size_map=ctr,
    )
    tr = Trainer(cfg, log_dir=tmp_path)
    metrics = tr.fit()
    assert 0.0 <= metrics["auc"] <= 1.0
    assert metrics["eval_loss"] > 0
    # metrics.jsonl written with epoch records
    lines = [json.loads(l) for l in (tmp_path / "metrics.jsonl").read_text().splitlines()]
    assert any("train_loss_epoch" in l for l in lines)
    assert any("auc" in l for l in lines)


def test_bert4rec_trainer_model_parallel(prepared_dir, tmp_path):
    d, _, seq = prepared_dir
    cfg = read_configs(
        None,
        data_dir=d,
        model="bert4rec",
        model_parallel=True,
        n_epochs=1,
        learning_rate=3e-3,
        embed_dim=16,
        n_heads=2,
        n_layers=1,
        max_len=12,
        sliding_step=6,
        per_device_train_batch_size=8,
        per_device_eval_batch_size=8,
        shuffle_buffer_size=1000,
        log_every_n_steps=1000,
        size_map={"n_items": seq["n_items"]},
    )
    tr = Trainer(cfg, log_dir=tmp_path)
    metrics = tr.fit()
    eval_keys = {"Recall@10", "Recall@20", "Recall@50",
                 "NDCG@10", "NDCG@20", "NDCG@50"}
    # fit() now also runs the final held-out TEST evaluation (the split the
    # reference computes and never consumes, torchrec/train.py:147-177)
    assert set(metrics) == eval_keys | {"test_" + k for k in eval_keys}
    for v in metrics.values():
        assert 0.0 <= v <= 1.0


def test_checkpoint_resume_roundtrip(prepared_dir, tmp_path):
    d, ctr, _ = prepared_dir
    common = dict(
        data_dir=d, model="twotower", learning_rate=3e-3, embed_dim=8,
        per_device_train_batch_size=16, per_device_eval_batch_size=16,
        shuffle_buffer_size=500, log_every_n_steps=1000, size_map=ctr,
        checkpoint_dir=str(tmp_path / "ckpt"), checkpoint_every_n_epochs=1,
    )
    m1 = Trainer(read_configs(None, n_epochs=1, **common)).fit()
    # second trainer resumes from epoch 0's checkpoint and trains one more;
    # checkpoint ids are global data steps, with the epoch recorded in the
    # cursor sidecar
    tr2 = Trainer(read_configs(None, n_epochs=2, **common))
    restored = tr2._ckpt.latest_step()
    assert restored is not None
    cursor = tr2._ckpt.read_cursor(restored)
    assert cursor["epoch"] == 0 and cursor["epoch_complete"]
    m2 = tr2.fit()
    assert m2["eval_loss"] <= m1["eval_loss"] * 1.1  # did not regress from scratch


def test_launch_cli_end_to_end(tmp_path, capsys):
    from tdfo_tpu.launch import main

    d = tmp_path / "data"
    cfgp = tmp_path / "config.toml"
    cfgp.write_text(
        f"""
data_dir = "{d}"
model = "twotower"
n_epochs = 1
learning_rate = 3e-3
embed_dim = 8
per_device_train_batch_size = 16
per_device_eval_batch_size = 16
shuffle_buffer_size = 500
log_every_n_steps = 1000
"""
    )
    assert main(["synth", "--config", str(cfgp)]) == 0
    assert main(["preprocess-ctr", "--config", str(cfgp)]) == 0
    assert (d / "size_map.json").exists()
    assert main(["train", "--config", str(cfgp), "--distributed", "never",
                 "--log-dir", str(tmp_path / "logs")]) == 0
    out = capsys.readouterr().out
    assert "auc" in out


def test_steps_per_execution_matches_single_step(prepared_dir, tmp_path):
    """The compiled multi-step loop must train identically to per-step
    dispatch (tensorflow2 steps_per_execution parity) — same data order,
    same math, just one dispatch per K steps."""
    d, ctr, _ = prepared_dir
    common = dict(
        data_dir=d, model="twotower", learning_rate=3e-3, embed_dim=8,
        per_device_train_batch_size=16, per_device_eval_batch_size=16,
        shuffle_buffer_size=500, log_every_n_steps=1000, size_map=ctr,
        n_epochs=1,
    )
    tr1 = Trainer(read_configs(None, **common))
    avg1 = tr1.train_epoch(0)
    tr4 = Trainer(read_configs(None, steps_per_execution=4, **common))
    avg4 = tr4.train_epoch(0)
    assert np.isclose(avg1, avg4, rtol=1e-4), (avg1, avg4)
    e1, e4 = tr1.evaluate(0), tr4.evaluate(0)
    assert np.isclose(e1["eval_loss"], e4["eval_loss"], rtol=1e-4)


def test_pipeline_overlap_matches_eager_grouped(prepared_dir, tmp_path):
    """train.pipeline_overlap (TrainPipelineSparseDist parity) trains the
    same batches with the same math one call later: epoch average, final
    tables and eval AUC all bit-identical to the eager grouped run, and the
    grouped run itself tracks the per-table baseline."""
    d, ctr, _ = prepared_dir
    common = dict(
        data_dir=d, model="twotower", model_parallel=True,
        mesh={"data": 4, "model": 2}, lookup_mode="alltoall",
        learning_rate=3e-3, embed_dim=8,
        per_device_train_batch_size=16, per_device_eval_batch_size=16,
        shuffle_buffer_size=500, log_every_n_steps=1000, size_map=ctr,
        n_epochs=1,
    )
    tr_g = Trainer(read_configs(None, embeddings={"grouped_a2a": True},
                                **common))
    avg_g = tr_g.train_epoch(0)
    tr_p = Trainer(read_configs(None, embeddings={"grouped_a2a": True},
                                train={"pipeline_overlap": True}, **common))
    avg_p = tr_p.train_epoch(0)
    assert avg_g == avg_p, (avg_g, avg_p)
    for a in tr_g.state.tables:
        np.testing.assert_array_equal(
            np.asarray(tr_g.state.tables[a]),
            np.asarray(tr_p.state.tables[a]), err_msg=a)
    assert tr_g.evaluate(0)["auc"] == tr_p.evaluate(0)["auc"]
    tr_0 = Trainer(read_configs(None, **common))
    assert np.isclose(avg_g, tr_0.train_epoch(0), rtol=1e-5)


def test_pipeline_overlap_bert4rec_matches_eager(prepared_dir, tmp_path):
    """The bert4rec pipelined branch (dropout rng threaded through
    prime/step/flush, jagged-free padded batches): same epoch average and
    final tables as the eager grouped run."""
    d, _, seq = prepared_dir
    common = dict(
        data_dir=d, model="bert4rec", model_parallel=True,
        mesh={"data": 4, "model": 2}, lookup_mode="alltoall",
        n_epochs=1, learning_rate=3e-3,
        embed_dim=16, n_heads=2, n_layers=1, max_len=12, sliding_step=6,
        per_device_train_batch_size=8, per_device_eval_batch_size=8,
        shuffle_buffer_size=1000, log_every_n_steps=1000,
        size_map={"n_items": seq["n_items"]},
        embeddings={"grouped_a2a": True},
    )
    tr_g = Trainer(read_configs(None, **common))
    avg_g = tr_g.train_epoch(0)
    tr_p = Trainer(read_configs(None, train={"pipeline_overlap": True},
                                **common))
    avg_p = tr_p.train_epoch(0)
    assert avg_g == avg_p, (avg_g, avg_p)
    for a in tr_g.state.tables:
        np.testing.assert_array_equal(
            np.asarray(tr_g.state.tables[a]),
            np.asarray(tr_p.state.tables[a]), err_msg=a)


def test_a2a_overflow_metric_logged_in_grouped_mode(prepared_dir, tmp_path):
    """alltoall + finite a2a_capacity_factor surfaces the dropped-id count
    in the periodic metrics stream (JSONL + the TB mirror) — including in
    grouped_a2a mode, where the counter measures the COMBINED per-group
    stream against the same bucket cap the real exchange uses."""
    d, ctr, _ = prepared_dir
    cfg = read_configs(
        None, data_dir=d, model="twotower", model_parallel=True,
        mesh={"data": 4, "model": 2}, lookup_mode="alltoall",
        a2a_capacity_factor=2.0, embeddings={"grouped_a2a": True},
        learning_rate=3e-3, embed_dim=8,
        per_device_train_batch_size=16, per_device_eval_batch_size=16,
        shuffle_buffer_size=500, log_every_n_steps=2, size_map=ctr,
        n_epochs=1,
    )
    tr = Trainer(cfg, log_dir=tmp_path)
    tr.train_epoch(0)
    recs = [json.loads(l) for l in open(tmp_path / "metrics.jsonl")]
    vals = [r["a2a_overflow_ids"] for r in recs if "a2a_overflow_ids" in r]
    assert vals, recs  # the diagnostic reached the stream
    assert all(isinstance(v, int) and v >= 0 for v in vals)


def test_twotower_map_style_loader(prepared_dir, tmp_path):
    """config streaming=false -> in-memory map-style epochs (jax-flax
    train.py data_loader parity) through the same trainer."""
    d, ctr, _ = prepared_dir
    cfg = read_configs(
        None, data_dir=d, model="twotower", streaming=False, n_epochs=1,
        learning_rate=3e-3, embed_dim=8, per_device_train_batch_size=16,
        per_device_eval_batch_size=16, log_every_n_steps=1000, size_map=ctr,
    )
    tr = Trainer(cfg, log_dir=tmp_path)
    metrics = tr.fit()
    assert 0.0 <= metrics["auc"] <= 1.0


def test_bert4rec_config_wired_islands(prepared_dir, tmp_path):
    """attn/lookup_mode/fused_table_threshold/steps_per_execution are
    reachable from Config: flash attention (interpret on CPU), psum lookup
    program over a 2-shard model axis, fused fat-row sparse Adam (threshold
    forced low so the item table takes the fat tier), 2-step compiled loop."""
    d, _, seq = prepared_dir
    cfg = read_configs(
        None,
        data_dir=d,
        model="bert4rec",
        model_parallel=True,
        attn="flash",
        lookup_mode="psum",
        fused_table_threshold=8,
        steps_per_execution=2,
        mesh={"data": 4, "model": 2},
        n_epochs=1,
        learning_rate=3e-3,
        embed_dim=16,
        n_heads=2,
        n_layers=1,
        max_len=12,
        sliding_step=6,
        per_device_train_batch_size=8,
        per_device_eval_batch_size=8,
        shuffle_buffer_size=1000,
        log_every_n_steps=1000,
        size_map={"n_items": seq["n_items"]},
    )
    tr = Trainer(cfg, log_dir=tmp_path)
    metrics = tr.fit()
    for v in metrics.values():
        assert 0.0 <= v <= 1.0


def test_eval_template_synthesis_for_empty_host(prepared_dir, tmp_path):
    """A host with ZERO eval rows must synthesise zero-weight template
    batches from the schema and run the full lockstep budget (on a real pod
    one shard-starved host would otherwise kill eval for everyone)."""
    d, ctr, _ = prepared_dir
    cfg = read_configs(
        None, data_dir=d, model="twotower", n_epochs=1, learning_rate=3e-3,
        embed_dim=8, per_device_train_batch_size=16,
        per_device_eval_batch_size=16, shuffle_buffer_size=500,
        log_every_n_steps=1000, size_map=ctr,
    )
    tr = Trainer(cfg, log_dir=tmp_path)

    class EmptyStream:
        batch_size = 16

        def set_epoch(self, e):
            pass

        def max_batches_per_host(self):
            return 3  # other hosts have 3 batches; we must march in lockstep

        def __iter__(self):
            return iter(())

    tr._stream = lambda pattern, train: EmptyStream()
    batches = list(tr._eval_batches())
    assert len(batches) == 3
    for b in batches:
        assert float(b["_weight"].sum()) == 0.0  # pure padding
    # and the metric math over pure padding stays finite / neutral
    metrics = tr.evaluate(0)
    assert metrics["eval_loss"] == 0.0
    import math
    assert math.isnan(metrics["auc"])  # no rows -> undefined AUC, not a crash


def test_tensor_parallel_bert4rec(prepared_dir, tmp_path):
    """tensor_parallel=true shards the feed-forward and vocab-projection
    kernels over the model axis (Megatron split as sharding specs) and the
    metrics match the replicated run (GSPMD inserts the collectives; only
    reduction order differs)."""
    import jax

    d, _, seq = prepared_dir
    common = dict(
        data_dir=d, model="bert4rec", model_parallel=True,
        mesh={"data": 4, "model": 2}, n_epochs=1, learning_rate=3e-3,
        embed_dim=16, n_heads=2, n_layers=1, max_len=12, sliding_step=6,
        per_device_train_batch_size=8, per_device_eval_batch_size=8,
        shuffle_buffer_size=1000, log_every_n_steps=1000,
        size_map={"n_items": seq["n_items"]},
    )
    tr_tp = Trainer(read_configs(None, tensor_parallel=True, **common))
    sharded = {
        "/".join(str(getattr(k, "key", k)) for k in path)
        for path, leaf in jax.tree_util.tree_leaves_with_path(tr_tp.state.dense_params)
        if any(ax is not None for ax in leaf.sharding.spec)
    }
    assert any("out_proj/kernel" in p for p in sharded), sharded
    assert any("fc1/kernel" in p for p in sharded)
    assert any("fc2/kernel" in p for p in sharded)
    # full Megatron: attention QKV column-parallel, out-proj row-parallel
    assert any("attn/qkv/kernel" in p for p in sharded), sharded
    assert any("attn/out/kernel" in p for p in sharded), sharded

    m_tp = tr_tp.fit()
    m_rep = Trainer(read_configs(None, **common)).fit()
    for k in m_rep:
        assert np.isclose(m_tp[k], m_rep[k], rtol=1e-3, atol=1e-5), (k, m_tp[k], m_rep[k])


def test_megatron_head_divisibility_guard():
    """A mesh whose model axis does not divide n_heads must be rejected at
    plan time, not silently resharded mid-layer (VERDICT r3 next #3)."""
    import jax
    import jax.numpy as jnp
    import pytest

    from tdfo_tpu.core.config import MeshSpec
    from tdfo_tpu.core.mesh import make_mesh
    from tdfo_tpu.parallel.sharding import make_sharding_plan, megatron_tp_rule

    mesh = make_mesh(MeshSpec(data=4, model=2, seq=1))
    tree = {"block_0": {"attn": {"qkv": {"kernel": jnp.zeros((16, 48))}}}}
    with pytest.raises(ValueError, match="n_heads"):
        make_sharding_plan(tree, mesh, megatron_tp_rule(mesh, n_heads=3))
    # divisible heads shard; unknown heads leave attention replicated
    plan = make_sharding_plan(tree, mesh, megatron_tp_rule(mesh, n_heads=2))
    spec = plan["block_0"]["attn"]["qkv"]["kernel"].spec
    assert any(ax is not None for ax in spec), spec
    plan_unknown = make_sharding_plan(tree, mesh, megatron_tp_rule(mesh))
    assert all(ax is None for ax in plan_unknown["block_0"]["attn"]["qkv"]["kernel"].spec)


def test_train_auc_matches_exact(prepared_dir, tmp_path):
    """train_auc (streaming, device-side) must match binary_auc on the
    epoch's predictions.  lr=0 freezes the model, so recomputing logits after
    the epoch reproduces exactly what the steps saw (VERDICT r3 missing #1)."""
    d, ctr, _ = prepared_dir
    cfg = read_configs(
        None,
        data_dir=d,
        model="twotower",
        n_epochs=1,
        learning_rate=0.0,
        weight_decay=0.0,
        embed_dim=8,
        per_device_train_batch_size=16,
        per_device_eval_batch_size=16,
        shuffle_buffer_size=1000,
        log_every_n_steps=1000,
        size_map=ctr,
    )
    tr = Trainer(cfg, log_dir=tmp_path)
    tr.fit()
    lines = [json.loads(l) for l in (tmp_path / "metrics.jsonl").read_text().splitlines()]
    logged = [l["train_auc"] for l in lines if "train_auc" in l]
    assert logged, "train_auc missing from the epoch log"

    # recompute the exact AUC over every train row with the (frozen) model
    import jax.numpy as jnp

    from tdfo_tpu.train.metrics import binary_auc

    labels, scores = [], []
    for batch, _k in tr._train_batches(epoch=0):
        loss, logits = tr.eval_step(tr.state, batch)
        labels.append(np.asarray(batch["label"]).reshape(-1))
        scores.append(np.asarray(jnp.ravel(logits)))
    exact = binary_auc(np.concatenate(labels), 1 / (1 + np.exp(-np.concatenate(scores))))
    # 200-bin histogram quantisation bounds the streaming estimate's error
    assert abs(logged[-1] - exact) < 0.02, (logged[-1], exact)


def test_param_summary(prepared_dir, capsys):
    from tdfo_tpu.utils.summary import param_summary

    d, ctr, _ = prepared_dir
    cfg = read_configs(
        None, data_dir=d, model="twotower", model_parallel=True,
        embed_dim=8, size_map=ctr, shuffle_buffer_size=100,
    )
    tr = Trainer(cfg)
    out = capsys.readouterr().out
    assert "twotower parameters" in out and "total" in out
    # fat tables report TRUE param counts (vocab x dim), not storage size
    s = param_summary(tr.state.dense_params, tables=tr.state.tables, coll=tr.coll)
    assert "tables/" in s


def test_preempted_save_does_not_poison_resume(prepared_dir, tmp_path):
    """A kill DURING checkpoint save leaves an in-progress tmp dir; the
    manager must keep resuming from the last COMPLETE checkpoint (the
    BackupAndRestore failure-recovery contract, tensorflow2/train_ps.py:156)."""
    from tdfo_tpu.train.checkpoint import CheckpointManager

    d, ctr, _ = prepared_dir
    cfg = read_configs(
        None, data_dir=d, model="twotower", n_epochs=1, learning_rate=3e-3,
        embed_dim=8, per_device_train_batch_size=16,
        per_device_eval_batch_size=16, shuffle_buffer_size=500,
        log_every_n_steps=1000, size_map=ctr,
        checkpoint_dir=str(tmp_path / "ckpt"), checkpoint_every_n_epochs=1,
    )
    tr = Trainer(cfg)
    tr.fit()  # writes a complete checkpoint for epoch 0
    mgr = CheckpointManager(tmp_path / "ckpt")
    s0 = mgr.latest_step()
    assert s0 is not None
    assert mgr.read_cursor(s0)["epoch"] == 0
    mgr.close()
    # simulate a preemption mid-save of a later step: orbax-style in-progress
    # dir with no committed payload
    (tmp_path / "ckpt" / f"{s0 + 1}.orbax-checkpoint-tmp-1234567").mkdir()
    tr2 = Trainer(cfg.replace(n_epochs=2))
    assert tr2._ckpt.latest_step() == s0  # incomplete save ignored
    m = tr2.fit()  # resumes from epoch 0 and completes epoch 1
    assert 0.0 <= m["auc"] <= 1.0
    s1 = tr2._ckpt.latest_step()
    assert s1 > s0
    assert tr2._ckpt.read_cursor(s1)["epoch"] == 1


def test_checkpoint_layout_version_guard(tmp_path):
    """Restoring a checkpoint with a foreign (or missing) storage-layout
    stamp must REFUSE with a clear error: parameter layout changes (the
    round-4 fused-QKV reorder, the round-5 fat-line packing) restore
    without shape errors but scramble values — the exact silent-corruption
    hazard the stamp exists to block."""
    import jax.numpy as jnp
    import orbax.checkpoint as ocp
    import pytest

    from tdfo_tpu.train import checkpoint as ckpt_mod
    from tdfo_tpu.train.checkpoint import LAYOUT_VERSION, CheckpointManager

    state = {"w": jnp.arange(6.0).reshape(2, 3)}

    # roundtrip at the current version works and preserves values
    mgr = CheckpointManager(tmp_path / "ok")
    mgr.save(0, state)
    step, restored, cursor = mgr.restore(state)
    assert step == 0 and cursor is None  # no cursor saved with this step
    np.testing.assert_array_equal(np.asarray(restored["w"]),
                                  np.asarray(state["w"]))
    mgr.close()

    # legacy checkpoint (no stamp — pre-versioning format): refused
    legacy = ocp.CheckpointManager(
        (tmp_path / "legacy").absolute(),
        options=ocp.CheckpointManagerOptions(create=True))
    legacy.save(0, args=ocp.args.StandardSave(state))
    legacy.wait_until_finished()
    legacy.close()
    mgr2 = CheckpointManager(tmp_path / "legacy")
    with pytest.raises(ValueError, match="layout_version"):
        mgr2.restore(state)
    mgr2.close()

    # foreign version stamp: refused with both versions named
    mgr3 = CheckpointManager(tmp_path / "old")
    try:
        ckpt_mod.LAYOUT_VERSION = LAYOUT_VERSION - 1
        mgr3.save(0, state)
    finally:
        ckpt_mod.LAYOUT_VERSION = LAYOUT_VERSION
    with pytest.raises(ValueError, match="layout version"):
        mgr3.restore(state)
    mgr3.close()


def test_checkpoint_unstamped_probe_failure_guidance(tmp_path):
    """When the item_metadata probe itself FAILS on a legacy unstamped
    checkpoint, the early refusal cannot fire and restore used to die with
    an opaque orbax structure mismatch (the abstract tree expects the
    layout_version leaf the legacy save never wrote).  That error must now
    arrive wrapped with the layout-version guidance."""
    import jax.numpy as jnp
    import orbax.checkpoint as ocp
    import pytest

    from tdfo_tpu.train.checkpoint import CheckpointManager

    state = {"w": jnp.arange(6.0).reshape(2, 3)}
    legacy = ocp.CheckpointManager(
        (tmp_path / "legacy").absolute(),
        options=ocp.CheckpointManagerOptions(create=True))
    legacy.save(0, args=ocp.args.StandardSave(state))
    legacy.wait_until_finished()
    legacy.close()

    mgr = CheckpointManager(tmp_path / "legacy")

    def broken_probe(step_id):
        raise ValueError("simulated metadata schema drift")

    mgr._mgr.item_metadata = broken_probe
    with pytest.raises(ValueError, match="layout_version"):
        mgr.restore(state)
    mgr.close()


def test_checkpoint_stamps_mismatch_refused(tmp_path):
    """The stamps sidecar must round-trip, and ANY asymmetry — different
    values, missing on either side — refuses the restore (the hot/cold
    hot-id digest contract: same shapes under a different hot set restore
    cleanly but pair every hot row with the wrong id)."""
    import jax.numpy as jnp
    import pytest

    from tdfo_tpu.train.checkpoint import CheckpointManager

    state = {"w": jnp.arange(4.0)}
    mgr = CheckpointManager(tmp_path / "ck")
    mgr.save(0, state, stamps={"hot_digest": {"item": "abc123"}})
    # matching stamps restore fine
    step, restored, _ = mgr.restore(
        state, stamps={"hot_digest": {"item": "abc123"}})
    assert step == 0
    np.testing.assert_array_equal(np.asarray(restored["w"]),
                                  np.asarray(state["w"]))
    # wrong digest, missing expectation, or extra expectation: all refused
    for bad in ({"hot_digest": {"item": "zzz999"}}, None, {"other": 1}):
        with pytest.raises(ValueError, match="stamps"):
            mgr.restore(state, stamps=bad)
    mgr.close()
    # and the symmetric case: checkpoint without stamps, run expecting some
    mgr2 = CheckpointManager(tmp_path / "ck2")
    mgr2.save(0, state)
    with pytest.raises(ValueError, match="stamps"):
        mgr2.restore(state, stamps={"hot_digest": {"item": "abc123"}})
    mgr2.close()


def test_bert4rec_dedup_lookup_matches_default(prepared_dir):
    """dedup_lookup on the sequence family ([B, T] ids, fat item table,
    model-parallel mesh): same metrics as the default path."""
    d, _, seq = prepared_dir
    common = dict(
        data_dir=d, model="bert4rec", model_parallel=True,
        fused_table_threshold=8,  # fat item table
        n_epochs=1, learning_rate=3e-3, embed_dim=16, n_heads=2, n_layers=1,
        max_len=12, sliding_step=6, per_device_train_batch_size=8,
        per_device_eval_batch_size=8, shuffle_buffer_size=1000,
        log_every_n_steps=1000, size_map={"n_items": seq["n_items"]},
    )
    m_dd = Trainer(read_configs(None, dedup_lookup=True, **common)).fit()
    m_def = Trainer(read_configs(None, **common)).fit()
    for k in m_def:
        assert np.isclose(m_dd[k], m_def[k], rtol=1e-4, atol=1e-6), (k, m_dd, m_def)
