"""Out-of-process serving fleet tests (``tdfo_tpu/serve/supervisor.py`` +
``serve/ingress.py`` + ``serve/loadgen.py``).

Three layers:

* **Unit** (tier 1, no processes): the ingress's power-of-two-choices
  balance and heartbeat-staleness eviction under an injected
  ``elapsed_ms`` (the PR-16 heartbeat fix: a stalled replica must stop
  receiving traffic within one eviction window), the supervisor's
  respawn-backoff schedule and flap quarantine under injected
  popen/clock/sleep/rng, and the load generator's closed/open arrival
  disciplines against a fake ingress — no wall-clock sleeps anywhere.

* **Acceptance** (tier 1, real processes): the gated online loop with
  ``[serving] fleet_mode = "process"`` — replicas are real OS processes
  behind the socket ingress — SIGKILLed mid-canary-watch
  (``[faults] kill_replica_signal``) versus the identical unkilled
  process-mode run.  The supervisor must respawn the victim, the
  respawned lineage must re-follow ``CURRENT``/``CANARY`` by
  (version, digest) and relearn every armed fault from the full-digest
  sync, and the verdicts / store state / per-replica probe logits must
  converge BITWISE to the unkilled reference.

* **Slow matrix**: the canary-rollback drill across the RPC boundary,
  permanent quarantine (``kill_replica_nth``) degrading the fleet, and a
  standalone mini-fleet proving the per-replica request log resumes
  seq-contiguously across a SIGKILL + respawn, with the load generator
  driving the same live fleet.
"""

import json
import os
import random
import signal
import socket
from pathlib import Path

import numpy as np
import pytest

from test_fleet import (  # noqa: F401  (fleet_env is a fixture)
    N_CYCLES,
    N_REPLICAS,
    _events,
    _make_spec,
    _run_worker,
    _run_workers,
    fleet_env,
)

from tdfo_tpu.serve import wire
from tdfo_tpu.serve.ingress import Ingress
from tdfo_tpu.serve.supervisor import ProcessSupervisor
from tdfo_tpu.utils.retry import backoff_delay


class _Recorder:
    """Duck-typed logger: collects ``log(**kw)`` records."""

    def __init__(self):
        self.events = []

    def log(self, **kw):
        self.events.append(kw)


# ------------------------------------------------- ingress balance + eviction


def _bare_ingress(stale_ms=100.0, seed=0, **kw):
    """An Ingress with no real connections: ``elapsed_ms`` is injected as
    the IDENTITY, so tests write ``hb_at`` stamps that are literally the
    observation's age in milliseconds."""
    return Ingress({}, stale_ms=stale_ms, rng=random.Random(seed),
                   elapsed_ms=lambda hb_at: hb_at, **kw)


def _stat(ing, k, age_ms, depth=0, fill=0.0):
    ing._stats[k] = {"queue_depth": depth, "batch_fill": fill,
                     "hb_at": float(age_ms)}


def test_ingress_evicts_stale_heartbeats_within_one_window():
    """The PR-16 heartbeat-staleness regression: a replica whose last
    observation is older than ``[serving] heartbeat_stale_ms`` stops
    receiving requests immediately — it used to keep its last
    ``queue_depth`` forever and kept winning the balance after death."""
    ing = _bare_ingress(stale_ms=100.0)
    ing._conns = {0: object(), 1: object()}
    _stat(ing, 0, age_ms=10.0, depth=5)
    _stat(ing, 1, age_ms=10.0, depth=0)
    assert ing.fresh() == [0, 1]
    assert {ing.pick() for _ in range(20)} == {1}  # less loaded wins

    # replica 1 stalls holding the WINNING queue_depth — the exact shape
    # the fix targets: a dead replica's frozen stats used to keep beating
    # the balance forever
    _stat(ing, 1, age_ms=150.0, depth=0)
    assert ing.fresh() == [0]
    assert {ing.pick() for _ in range(20)} == {0}

    # the whole fleet stale is a LOUD error, never a silent route-to-dead
    _stat(ing, 0, age_ms=101.0)
    with pytest.raises(RuntimeError, match="no fresh replica"):
        ing.pick()


def test_ingress_p2c_prefers_less_loaded():
    """Power-of-two-choices over (queue_depth, batch_fill, id): with two
    replicas both samples always land, so the ordering is exact."""
    ing = _bare_ingress()
    ing._conns = {0: object(), 1: object()}
    _stat(ing, 0, age_ms=0.0, depth=6)
    _stat(ing, 1, age_ms=0.0, depth=0)
    assert {ing.pick() for _ in range(20)} == {1}
    _stat(ing, 0, age_ms=0.0, depth=2, fill=0.9)
    _stat(ing, 1, age_ms=0.0, depth=2, fill=0.1)
    assert {ing.pick() for _ in range(20)} == {1}  # depth tie -> lower fill
    _stat(ing, 1, age_ms=0.0, depth=2, fill=0.9)
    assert {ing.pick() for _ in range(20)} == {0}  # full tie -> lower id


def test_ingress_rpc_folds_interleaved_score_replies():
    """Drain-on-swap ordering at the wire level: score replies that land
    before the drain acknowledgment are folded into ``completed`` (shed
    = ``null`` scores counted), and the rpc returns the control reply."""
    ours, theirs = socket.socketpair()
    try:
        ing = _bare_ingress(stale_ms=1e9)
        ing._conns[0] = ours
        ing._inflight["r9"] = (0, 123.0)
        ing._inflight["r10"] = (0, 5.0)
        wire.send_msg(theirs, {"type": "reply", "rid": "r9",
                               "scores": [0.5, 2.0], "queue_depth": 3,
                               "batch_fill": 0.75})
        wire.send_msg(theirs, {"type": "reply", "rid": "r10",
                               "scores": None, "queue_depth": 2,
                               "batch_fill": 0.5})
        wire.send_msg(theirs, {"type": "drained", "replica": 0})
        reply = ing.rpc(0, {"type": "drain"})
        assert reply == {"type": "drained", "replica": 0}
        np.testing.assert_array_equal(ing.completed["r9"],
                                      np.asarray([0.5, 2.0], np.float32))
        assert ing.completed["r10"] is None
        assert ing.sheds == 1
        assert ing.latencies_ms == [123.0]  # identity elapsed_ms: the stamp
        # score replies double as balance observations
        assert ing._stats[0]["queue_depth"] == 2
        assert wire.recv_msg(theirs) == {"type": "drain"}
    finally:
        ours.close()
        theirs.close()


def test_ingress_disconnect_fails_inflight_loudly():
    """Requests in flight on a dying connection land as ``None`` in
    ``completed`` with the failure counted and ledgered — never silently
    dropped (the caller would hang waiting for them)."""
    ours, theirs = socket.socketpair()
    log = _Recorder()
    try:
        ing = _bare_ingress(logger=log)
        ing._conns[0] = ours
        ing._inflight["lost1"] = (0, 0.0)
        ing._inflight["lost2"] = (0, 0.0)
        ing.disconnect(0)
        assert ing.completed == {"lost1": None, "lost2": None}
        assert ing.failures == 2
        assert ing.inflight() == 0
        assert log.events == [{"event": "ingress_inflight_lost",
                               "replica": 0, "requests": 2}]
    finally:
        theirs.close()


# ------------------------------------------------- supervisor respawn + flap


class _FakeProc:
    def __init__(self, pid):
        self.pid = pid
        self.returncode = None

    def poll(self):
        return self.returncode


def _fake_supervisor(**kw):
    spawned = []

    def popen(spec_path):
        proc = _FakeProc(pid=1000 + len(spawned))
        spawned.append(proc)
        return proc

    clock = {"t": 0.0}
    slept = []
    sup = ProcessSupervisor(
        {0: "/dev/null"}, sleep=slept.append, clock=lambda: clock["t"],
        rng=random.Random(7), popen=popen, **kw)
    return sup, spawned, slept, clock


def test_supervisor_backoff_schedule_and_flap_quarantine():
    """Respawn delays follow the single ``utils/retry.backoff_delay`` law
    bit-for-bit (capped exponential, injected rng), and the third death
    inside the flap window quarantines instead of respawning — loudly."""
    log = _Recorder()
    sup, spawned, slept, clock = _fake_supervisor(
        respawn_base_ms=50.0, respawn_max_ms=400.0, flap_window_s=30.0,
        flap_max_deaths=3, logger=log)
    sup.spawn_all()
    assert sup.alive_ids() == [0] and len(spawned) == 1

    spawned[-1].returncode = 9
    clock["t"] = 1.0
    assert sup.check() == [0]
    spawned[-1].returncode = 9
    clock["t"] = 2.0
    assert sup.check() == [0]
    assert sup.respawns == {0: 2} and len(spawned) == 3

    ref = random.Random(7)
    assert slept == [backoff_delay(i, base_delay=0.050, max_delay=0.400,
                                   rng=ref) for i in range(2)]

    spawned[-1].returncode = 9
    clock["t"] = 3.0
    assert sup.check() == []  # third death in the window: quarantined
    assert sup.quarantined == {0}
    assert len(spawned) == 3 and len(slept) == 2  # no fourth spawn, no sleep
    with pytest.raises(RuntimeError, match="quarantined"):
        sup.spawn(0)

    deaths = [e for e in log.events if e["event"] == "replica_died"]
    assert [e["deaths_in_window"] for e in deaths] == [1, 2, 3]
    assert [e["event"] for e in log.events].count("replica_quarantined") == 1


def test_supervisor_window_expiry_and_mark_healthy():
    """Deaths spaced wider than ``flap_window_s`` never quarantine, and
    ``mark_healthy`` (a respawned replica answered an RPC) resets the
    consecutive-death backoff to the base delay."""
    sup, spawned, slept, clock = _fake_supervisor(
        respawn_base_ms=50.0, respawn_max_ms=400.0, flap_window_s=30.0,
        flap_max_deaths=2)
    sup.spawn_all()
    for t in (0.0, 100.0, 200.0):  # each death alone in its window
        spawned[-1].returncode = 9
        clock["t"] = t
        assert sup.check() == [0]
        sup.mark_healthy(0)
    assert not sup.quarantined
    assert sup.respawns == {0: 3}
    ref = random.Random(7)
    expected = [backoff_delay(0, base_delay=0.050, max_delay=0.400, rng=ref)
                for _ in range(3)]
    assert slept == expected  # backoff index pinned at 0 by mark_healthy


def test_spawn_prebinds_listener_and_detaches_child_stdio(
        tmp_path, monkeypatch):
    """The socket-activation + stdio-hygiene contract of the REAL spawn
    path (``_spawn_child``), with ``Popen`` faked out:

    * the socket accepts a connection BEFORE any child process exists —
      a child spending a minute importing jax on a loaded single-core
      box can no longer outlast the ingress's connect-retry budget (the
      regression that wedged the tier-1 suite);
    * the bound listener fd rides down via ``--listen-fd`` + ``pass_fds``;
    * child stdio is the per-replica log file + DEVNULL stdin, never an
      inherited pipe — an orphaned child must not be able to hold a test
      harness's ``communicate()`` open after the parent dies.
    """
    import subprocess as sp

    sock = tmp_path / "replica-0.sock"
    spec = tmp_path / "replica-0.json"
    spec.write_text(json.dumps({"replica_id": 0, "socket": str(sock)}))

    calls = []
    inherited = []

    def fake_popen(argv, **kw):
        # what fork+exec under pass_fds does for a real child: duplicate
        # the fd so it outlives the parent's listener.close()
        inherited.extend(os.dup(fd) for fd in kw.get("pass_fds", ()))
        calls.append((argv, kw))
        return _FakeProc(4242)

    monkeypatch.setattr(sp, "Popen", fake_popen)
    proc = ProcessSupervisor._spawn_child(spec)
    assert isinstance(proc, _FakeProc)
    (argv, kw), = calls
    fd = int(argv[argv.index("--listen-fd") + 1])
    assert kw["pass_fds"] == (fd,)
    assert kw["stdin"] is sp.DEVNULL
    assert kw["stdout"].name == str(tmp_path / "replica-0.log")
    assert kw["stderr"] is kw["stdout"]

    # no child process exists (Popen was fake) and the parent has already
    # closed its listener copy, yet the path connects instantly: the
    # pre-bound socket's backlog — kept alive by the "inherited" fd — is
    # holding the connection
    client = wire.connect(sock, attempts=1)
    adopted = wire.listener_from_fd(inherited.pop())
    try:
        conn, _ = adopted.accept()
        wire.send_msg(conn, {"type": "synced"})
        assert wire.recv_msg(client) == {"type": "synced"}
        conn.close()
    finally:
        client.close()
        adopted.close()


# ---------------------------------------------------- loadgen disciplines


class _FakeIngress:
    """The duck-typed submit/poll surface: completes one request per poll
    at a fixed latency, records the high-water inflight mark."""

    def __init__(self, latency_ms=5.0, clock=None):
        self.completed = {}
        self.latencies_ms = []
        self.sheds = 0
        self.failures = 0
        self._queue = []
        self._latency_ms = latency_ms
        self._clock = clock
        self.max_inflight = 0

    def submit(self, rid, feats):
        self._queue.append(rid)
        self.max_inflight = max(self.max_inflight, len(self._queue))
        return 0

    def inflight(self):
        return len(self._queue)

    def poll(self, timeout_s=0.0):
        if self._clock is not None:
            self._clock["ms"] += 1.0  # a poll IS the passage of time here
        if not self._queue:
            return 0
        rid = self._queue.pop(0)
        self.completed[rid] = np.zeros(1, np.float32)
        self.latencies_ms.append(self._latency_ms)
        return 1


def test_loadgen_request_is_zipf_in_vocab():
    from tdfo_tpu.core.config import LoadgenSpec
    from tdfo_tpu.serve.loadgen import LoadGenerator

    spec = LoadgenSpec(rows_per_request=64, seed=3, zipf_a=2.0)
    gen = LoadGenerator(_FakeIngress(), spec,
                        {"user_id": 50, "item_id": 7}, ("avg_rating",))
    rids = set()
    for _ in range(4):
        rid, batch = gen.request()
        rids.add(rid)
        assert batch["user_id"].dtype == np.int32
        assert batch["user_id"].shape == (64,)
        assert batch["user_id"].min() >= 0 and batch["user_id"].max() < 50
        assert batch["item_id"].max() < 7
        assert batch["avg_rating"].dtype == np.float32
    assert len(rids) == 4  # serial rids never collide
    # zipf head-heaviness: rank-0 ids dominate a uniform draw's share
    big = gen.request()[1]["user_id"]
    assert (big == 0).mean() > 0.3


def test_loadgen_closed_loop_respects_concurrency():
    from tdfo_tpu.core.config import LoadgenSpec
    from tdfo_tpu.serve.loadgen import LoadGenerator

    ing = _FakeIngress(latency_ms=5.0)
    spec = LoadgenSpec(mode="closed", requests=10, concurrency=3,
                       rows_per_request=2, p99_slo_ms=50.0)
    gen = LoadGenerator(ing, spec, {"user_id": 8})
    stats = gen.run()
    assert stats["mode"] == "closed"
    assert stats["offered"] == 10 and stats["completed"] == 10
    assert stats["concurrency"] == 3 and stats["offered_qps"] is None
    assert ing.max_inflight <= 3  # replies fund sends; never over-admits
    assert stats["p50_ms"] == 5.0 and stats["p99_ms"] == 5.0
    assert stats["slo_ok"] is True and stats["shed"] == 0


def test_loadgen_open_loop_paces_by_rate_not_replies():
    """Open loop submits on the arrival schedule whether or not replies
    came back — the discipline that can see past saturation.  Time is a
    fake millisecond counter advanced by ingress polls, so the pacing
    math runs without wall-clock sleeps."""
    from tdfo_tpu.core.config import LoadgenSpec
    from tdfo_tpu.serve.loadgen import LoadGenerator

    clock = {"ms": 0.0}
    ing = _FakeIngress(latency_ms=5.0, clock=clock)
    spec = LoadgenSpec(mode="open", requests=8, rate_qps=100.0,
                       rows_per_request=2, p99_slo_ms=50.0)
    gen = LoadGenerator(ing, spec, {"user_id": 8},
                        elapsed_ms=lambda t0: clock["ms"])
    stats = gen.run()
    assert stats["mode"] == "open"
    assert stats["offered_qps"] == 100.0 and stats["concurrency"] is None
    assert stats["completed"] == 8 and stats["failed"] == 0
    # 8 arrivals at 10 ms spacing: the wall is the schedule, not the sum
    # of service times
    assert clock["ms"] >= 70.0
    assert stats["achieved_qps"] > 0


def test_loadgen_knee_doubles_the_load_axis():
    from tdfo_tpu.core.config import LoadgenSpec
    from tdfo_tpu.serve.loadgen import LoadGenerator

    ing = _FakeIngress(latency_ms=5.0)
    spec = LoadgenSpec(mode="closed", requests=6, rows_per_request=2,
                       p99_slo_ms=50.0)
    gen = LoadGenerator(ing, spec, {"user_id": 8})
    report = gen.knee(steps=3)
    assert [r["concurrency"] for r in report["steps"]] == [1, 2, 4]
    assert all(r["slo_ok"] for r in report["steps"])
    assert report["knee"] is report["steps"][-1]  # last SLO-meeting step


# ------------------------------------------- tier-1 process-fleet acceptance


@pytest.fixture(scope="module")
def proc_runs(fleet_env, tmp_path_factory):
    """Two concurrent gated runs with ``fleet_mode = "process"``:

    * ``procref`` — fault-free: the unkilled reference.
    * ``prockill`` — ``kill_replica_signal = 1``: replica 0 (the canary
      member) takes a real SIGKILL at the first canary-watch round; the
      supervisor must respawn it before the verdict heartbeats.
    """
    tmp = tmp_path_factory.mktemp("proc_runs")
    ref_p = _make_spec(tmp, fleet_env, "procref", ckpt="ckpt_ref",
                       log="log_ref", fleet_mode="process",
                       telemetry={"trace": True})
    kill_p = _make_spec(tmp, fleet_env, "prockill", ckpt="ckpt_kill",
                        log="log_kill", fleet_mode="process",
                        telemetry={"trace": True},
                        faults={"kill_replica_signal": 1})
    rcs, outs = _run_workers([ref_p, kill_p])
    assert rcs[0] == 0, f"procref failed rc={rcs[0]}\n{outs[0][-2000:]}"
    assert rcs[1] == 0, f"prockill failed rc={rcs[1]}\n{outs[1][-2000:]}"
    return dict(
        ref=json.loads((tmp / "procref.json").read_text()),
        kill=json.loads((tmp / "prockill.json").read_text()),
        ref_metrics=tmp / "log_ref" / "metrics.jsonl",
        kill_metrics=tmp / "log_kill" / "metrics.jsonl",
    )


def test_sigkill_respawn_converges_bitwise(proc_runs):
    """The PR-16 robustness bar: SIGKILL a replica process mid-watch ->
    supervisor respawns it -> the respawned lineage re-follows
    CURRENT/CANARY by (version, digest) -> the gated run's store state,
    replay cursor, verdicts, and per-replica probe logits are BITWISE
    identical to the unkilled process-mode reference."""
    ref, kd = proc_runs["ref"], proc_runs["kill"]
    assert int(kd["respawns"].get("0", 0)) >= 1  # the victim really died
    assert all(int(v) == 0 for v in ref["respawns"].values())
    assert kd["dead_replicas"] == []  # respawned, never quarantined
    assert ref["dead_replicas"] == []
    for key in ("version", "digest", "cursor", "cycles_done",
                "replica_versions", "rejections", "logits"):
        assert kd[key] == ref[key], key


def test_sigkill_drill_is_ledgered(proc_runs):
    """The kill and the death are both ledgered events (a drill that
    leaves no trace proves nothing), the returncode is the signal, and
    every cycle still promoted in BOTH runs."""
    sigkills = _events(proc_runs["kill_metrics"], "replica_sigkilled")
    assert [e["replica"] for e in sigkills] == [0]
    died = _events(proc_runs["kill_metrics"], "replica_died")
    assert died and died[0]["replica"] == 0
    assert died[0]["returncode"] == -int(signal.SIGKILL)
    assert not _events(proc_runs["ref_metrics"], "replica_died")
    for key in ("ref_metrics", "kill_metrics"):
        cycles = _events(proc_runs[key], "online_cycle")
        assert [c["verdict"] for c in cycles] == ["promote"] * N_CYCLES, key


def test_process_replicas_agree_bitwise(proc_runs):
    """Both replica processes serve identical logits for the identical
    probe trace — the wire codec and the process boundary perturb
    nothing."""
    logits = proc_runs["ref"]["logits"]
    assert sorted(logits) == [str(k) for k in range(N_REPLICAS)]
    per_replica = [logits[k] for k in sorted(logits)]
    assert all(r == per_replica[0] for r in per_replica[1:])


# --------------------------------------------------------------- slow matrix


@pytest.mark.slow
def test_process_drill_rollback_over_rpc(fleet_env, tmp_path):
    """The canary-rollback drill across the RPC boundary: the skew digest
    rides the sync fan-out, only the canary CHILD PROCESS serves skewed
    logits, and the verdict sequence matches the in-process drill —
    rollback at cycle 1, promote at cycle 2, rejection ledgered."""
    spec = _make_spec(tmp_path, fleet_env, "procdrill", ckpt="ckpt",
                      log="log", fleet_mode="process",
                      faults={"regress_auc_at_cycle": 1})
    rc, out = _run_worker(spec)
    assert rc == 0, f"rc={rc}\n{out[-2000:]}"
    res = json.loads((tmp_path / "procdrill.json").read_text())
    cycles = _events(tmp_path / "log" / "metrics.jsonl", "online_cycle")
    assert [c["verdict"] for c in cycles] == ["rollback", "promote"]
    assert len(res["rejections"]) == 1
    assert res["rejections"][0]["version"] == cycles[0]["version"]
    assert res["dead_replicas"] == []


@pytest.mark.slow
def test_process_quarantine_degrades_fleet(fleet_env, tmp_path):
    """``kill_replica_nth = 2`` in process mode permanently quarantines
    the stable replica (the in-process soft-kill twin): membership stays
    degraded, no respawn, and the healthy candidate still promotes —
    exactly the in-process expectation for a stable-cohort death."""
    spec = _make_spec(tmp_path, fleet_env, "procq", ckpt="ckpt", log="log",
                      fleet_mode="process",
                      faults={"kill_replica_nth": 2})
    rc, out = _run_worker(spec)
    assert rc == 0, f"rc={rc}\n{out[-2000:]}"
    res = json.loads((tmp_path / "procq.json").read_text())
    assert res["dead_replicas"] == [1]
    assert all(int(v) == 0 for v in res["respawns"].values())
    assert sorted(res["replica_versions"]) == ["0"]  # survivors only
    assert res["version"] == N_CYCLES and res["rejections"] == []
    cycles = _events(tmp_path / "log" / "metrics.jsonl", "online_cycle")
    assert [c["verdict"] for c in cycles] == ["promote"] * N_CYCLES
    quarantines = _events(tmp_path / "log" / "metrics.jsonl",
                          "replica_quarantined")
    assert [e["replica"] for e in quarantines] == [1]


@pytest.mark.slow
def test_process_fleet_request_log_and_loadgen_survive_sigkill(mesh8,
                                                               tmp_path):
    """A standalone mini-fleet (no training loop): route traffic, SIGKILL
    a replica, respawn, route more — every request is answered, the
    victim's per-replica request log resumes SEQ-CONTIGUOUSLY across its
    death (segments rotate mid-run, so the resume crosses a seal
    boundary), and the load generator sweeps the same live fleet."""
    from test_serve_swap import CONT_COLS, SIZE_MAP, _batch, _export_kw, \
        _setup

    from tdfo_tpu.core.config import Config, LoadgenSpec, ServingSpec
    from tdfo_tpu.data.replay import replica_log_dir
    from tdfo_tpu.serve.export import export_bundle
    from tdfo_tpu.serve.loadgen import LoadGenerator
    from tdfo_tpu.serve.supervisor import ProcessFleet
    from tdfo_tpu.serve.swap import BundleStore

    coll, _, state, _ = _setup(mesh8)
    bdir = export_bundle(tmp_path / "b", step=0, version=0,
                         **_export_kw(coll, state))
    store = BundleStore(tmp_path / "store")
    store.ingest_full(bdir)
    cfg = Config().replace(
        serving=ServingSpec(replicas=2, fleet_mode="process",
                            log_features=True, log_segment_bytes=2048),
        loadgen=LoadgenSpec(mode="closed", requests=12, rows_per_request=4,
                            p99_slo_ms=60_000.0))

    def _seqs(k):
        d = replica_log_dir(tmp_path / "rl", k)
        return [json.loads(line)["seq"]
                for seg in sorted(d.glob("requests-*.jsonl"))
                for line in seg.read_text().splitlines()]

    rng = np.random.default_rng(17)
    fleet = ProcessFleet(store, cfg, workdir=tmp_path,
                         request_log_root=tmp_path / "rl")
    try:
        fleet.ingress._rng = random.Random(3)  # pin the P2C draws
        fleet.sync()
        out1 = fleet.run([(f"a{i}", _batch(rng, 6)) for i in range(16)])
        assert len(out1) == 16
        assert all(v is not None for v in out1.values())
        victim_before = len(_seqs(0))
        assert victim_before >= 1  # the victim served some of phase 1

        fleet.supervisor.kill(0)  # real SIGKILL, mid-fleet
        fleet.ingress.disconnect(0)
        fleet.sync()  # check() respawns + reconnects, then re-arms
        assert fleet.supervisor.respawns[0] == 1
        assert fleet.alive_ids() == [0, 1]

        # completed is cumulative at the ingress; check the new rids
        out2 = fleet.run([(f"b{i}", _batch(rng, 6)) for i in range(16)])
        assert all(out2[f"b{i}"] is not None for i in range(16))

        gen = LoadGenerator(fleet.ingress, cfg.loadgen,
                            {c: SIZE_MAP[f] for f, c in
                             {"user": "user_id", "item": "item_id",
                              "language": "language", "is_ebook": "is_ebook",
                              "format": "format", "publisher": "publisher",
                              "pub_decade": "pub_decade"}.items()},
                            CONT_COLS)
        report = gen.knee(steps=2)
        assert [r["concurrency"] for r in report["steps"]] == [1, 2]
        assert all(r["completed"] == 12 and r["failed"] == 0
                   for r in report["steps"])
        assert report["knee"] is not None  # generous SLO: the knee exists
    finally:
        fleet.close()

    seqs0, seqs1 = _seqs(0), _seqs(1)
    # contiguous from 1, no gap at the death, no dup after the respawn
    assert seqs0 == list(range(1, len(seqs0) + 1))
    assert seqs1 == list(range(1, len(seqs1) + 1))
    assert len(seqs0) > victim_before  # the respawned lineage kept writing
    assert len(seqs0) + len(seqs1) == 32 + 2 * 12
    # rotation actually happened: the resume crossed a sealed segment
    assert len(list(replica_log_dir(tmp_path / "rl", 0)
                    .glob("requests-*.jsonl"))) > 1
