"""Crash/resume acceptance with a REAL process kill (``@pytest.mark.slow``).

The in-process variant lives in tests/test_fault_tolerance.py; this tier
spawns actual subprocesses (pattern from tests/test_multihost.py) so the kill
is a genuine ``os._exit`` — no cleanup, no atexit, no flushed buffers — and
asserts the three-way contract:

  1. the killed run exits with ``KILL_EXIT_CODE`` and leaves a mid-epoch
     step-granular checkpoint behind,
  2. restarting the SAME command resumes (the kill marker disarms the fault)
     and completes,
  3. the resumed run's final metrics AND full train state are bit-identical
     to an uninterrupted run's.
"""

import json
import os
import socket
import subprocess
import sys
from pathlib import Path

import pytest

pytestmark = pytest.mark.slow

REPO = str(Path(__file__).resolve().parents[1])
WORKER = str(Path(__file__).with_name("crash_worker.py"))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _spawn(spec_path: Path) -> subprocess.Popen:
    env = os.environ.copy()
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = f"{REPO}{os.pathsep}" + env.get("PYTHONPATH", "")
    return subprocess.Popen(
        [sys.executable, WORKER, str(spec_path)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True, env=env,
    )


def _run_workers(spec_paths: list[Path]) -> tuple[list[int], list[str]]:
    procs = [_spawn(p) for p in spec_paths]
    rcs, outs = [], []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=600)
            rcs.append(p.returncode)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        raise
    return rcs, outs


def _run_worker(spec_path: Path) -> tuple[int, str]:
    rcs, outs = _run_workers([spec_path])
    return rcs[0], outs[0]


@pytest.fixture(scope="module")
def ctr_data(tmp_path_factory):
    from tdfo_tpu.data.ctr_preprocessing import run_ctr_preprocessing
    from tdfo_tpu.data.synthetic import write_synthetic_goodreads

    d = tmp_path_factory.mktemp("gr_crash")
    write_synthetic_goodreads(d, n_users=80, n_books=120,
                              interactions_per_user=(15, 40), seed=13)
    run_ctr_preprocessing(d)
    return d


def test_kill_restart_resumes_bit_identical(ctr_data, tmp_path):
    from tdfo_tpu.utils.faults import KILL_EXIT_CODE

    def make_spec(name: str, kill: int, ckpt: str) -> tuple[Path, dict]:
        spec = dict(
            data_dir=str(ctr_data), checkpoint_dir=str(tmp_path / ckpt),
            log_dir=str(tmp_path / f"log_{name}"),
            out_json=str(tmp_path / f"{name}.json"),
            kill_at_step=kill, checkpoint_every_n_steps=3, local_devices=4,
        )
        p = tmp_path / f"{name}_spec.json"
        p.write_text(json.dumps(spec))
        return p, spec

    killed_spec, killed = make_spec("killed", kill=5, ckpt="ckpt")
    rc, out = _run_worker(killed_spec)
    assert rc == KILL_EXIT_CODE, f"expected injected kill, got rc={rc}\n{out[-2000:]}"
    assert not Path(killed["out_json"]).exists()  # died before finishing
    assert (tmp_path / "ckpt" / "faults_kill.marker").exists()

    # restart the SAME command: marker disarms the kill, the run resumes
    # from the last step-granular checkpoint and completes
    rc, out = _run_worker(killed_spec)
    assert rc == 0, f"resumed run failed rc={rc}\n{out[-2000:]}"
    resumed = json.loads(Path(killed["out_json"]).read_text())
    log = (tmp_path / "log_killed" / "metrics.jsonl").read_text()
    recs = [json.loads(l) for l in log.splitlines()]
    mid = [r for r in recs if "resumed_mid_epoch" in r]
    assert mid and mid[-1]["step"] > 0, "resume must be mid-epoch, not epoch start"

    ref_spec, ref_s = make_spec("ref", kill=0, ckpt="ckpt_ref")
    rc, out = _run_worker(ref_spec)
    assert rc == 0, f"reference run failed rc={rc}\n{out[-2000:]}"
    ref = json.loads(Path(ref_s["out_json"]).read_text())

    assert resumed["metrics"] == ref["metrics"]
    assert resumed["state_digest"] == ref["state_digest"]


def test_two_process_kill_restart_bit_identical(ctr_data, tmp_path):
    """The multihost variant (tests/test_multihost.py style): a 2-process
    jax.distributed cluster is preempted — SPMD lockstep means both workers
    hit the injected kill at the same step boundary — and a restart of the
    same pair resumes mid-epoch to bit-identical global metrics and
    per-process state shards."""
    from tdfo_tpu.utils.faults import KILL_EXIT_CODE

    def make_pair(name: str, kill: int, ckpt: str) -> list[Path]:
        port = _free_port()
        paths = []
        for pid in range(2):
            spec = dict(
                data_dir=str(ctr_data), checkpoint_dir=str(tmp_path / ckpt),
                log_dir=str(tmp_path / f"log_{name}_p{pid}"),
                out_json=str(tmp_path / f"{name}_p{pid}.json"),
                kill_at_step=kill, checkpoint_every_n_steps=3,
                local_devices=2,
                distributed=dict(port=port, nprocs=2, pid=pid),
            )
            p = tmp_path / f"{name}_p{pid}_spec.json"
            p.write_text(json.dumps(spec))
            paths.append(p)
        return paths

    killed_pair = make_pair("killed2", kill=5, ckpt="ckpt2")
    rcs, outs = _run_workers(killed_pair)
    if rcs != [KILL_EXIT_CODE] * 2 and any(
        "Multiprocess computations aren't implemented" in o for o in outs
    ):
        # same backend limitation that fails tests/test_multihost.py on this
        # jax build; the single-process variant above still covers the path
        pytest.skip("CPU backend lacks multiprocess collectives")
    assert rcs == [KILL_EXIT_CODE] * 2, f"rcs={rcs}\n{outs[0][-1500:]}\n{outs[1][-1500:]}"
    assert (tmp_path / "ckpt2" / "faults_kill.marker").exists()

    # restart the SAME command pair: the marker disarms the kill on both
    rcs, outs = _run_workers(killed_pair)
    assert rcs == [0, 0], f"rcs={rcs}\n{outs[0][-1500:]}\n{outs[1][-1500:]}"
    resumed = [json.loads((tmp_path / f"killed2_p{pid}.json").read_text())
               for pid in range(2)]

    ref_pair = make_pair("ref2", kill=0, ckpt="ckpt2_ref")
    rcs, outs = _run_workers(ref_pair)
    assert rcs == [0, 0], f"rcs={rcs}\n{outs[0][-1500:]}\n{outs[1][-1500:]}"
    ref = [json.loads((tmp_path / f"ref2_p{pid}.json").read_text())
           for pid in range(2)]

    # global metrics identical across processes AND across resumed/reference
    assert resumed[0]["metrics"] == resumed[1]["metrics"]
    assert resumed[0]["metrics"] == ref[0]["metrics"] == ref[1]["metrics"]
    # each process's addressable state shards bit-identical to the reference
    for pid in range(2):
        assert resumed[pid]["state_digest"] == ref[pid]["state_digest"]
