"""Ring attention vs full attention: exactness on a sequence-sharded mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tdfo_tpu.core.config import MeshSpec
from tdfo_tpu.core.mesh import make_mesh
from tdfo_tpu.models.transformer import dot_product_attention
from tdfo_tpu.parallel.ring_attention import ring_self_attention


@pytest.fixture(scope="module")
def mesh_seq():
    return make_mesh(MeshSpec(data=2, model=1, seq=4))


def _rand_qkv(key, b=2, h=2, t=16, dh=8):
    ks = jax.random.split(key, 3)
    return tuple(jax.random.normal(k, (b, h, t, dh)) for k in ks)


def test_matches_full_attention_unmasked(mesh_seq):
    q, k, v = _rand_qkv(jax.random.key(0))
    ref = dot_product_attention(q, k, v)
    out = ring_self_attention(mesh_seq, q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_matches_full_attention_with_key_padding(mesh_seq):
    q, k, v = _rand_qkv(jax.random.key(1))
    valid = jnp.asarray(np.random.default_rng(0).random((2, 16)) > 0.3)
    valid = valid.at[:, 0].set(True)  # at least one valid key per row
    ref = dot_product_attention(q, k, v, valid[:, None, None, :])
    out = ring_self_attention(mesh_seq, q, k, v, valid)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_all_keys_masked_returns_zero(mesh_seq):
    q, k, v = _rand_qkv(jax.random.key(2))
    valid = jnp.zeros((2, 16), bool)
    out = ring_self_attention(mesh_seq, q, k, v, valid)
    assert not bool(jnp.isnan(out).any())
    np.testing.assert_allclose(np.asarray(out), 0.0)


def test_gradients_match(mesh_seq):
    q, k, v = _rand_qkv(jax.random.key(3))
    valid = jnp.ones((2, 16), bool)

    def ring_loss(q, k, v):
        return (ring_self_attention(mesh_seq, q, k, v, valid) ** 2).sum()

    def full_loss(q, k, v):
        return (dot_product_attention(q, k, v) ** 2).sum()

    gr = jax.grad(ring_loss, argnums=(0, 1, 2))(q, k, v)
    gf = jax.grad(full_loss, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gr, gf):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=5e-4, atol=5e-4)


def test_rejects_indivisible_seq_len(mesh_seq):
    q, k, v = _rand_qkv(jax.random.key(4), t=15)
    with pytest.raises(ValueError, match="not divisible"):
        ring_self_attention(mesh_seq, q, k, v)


def test_bf16_operands(mesh_seq):
    q, k, v = (x.astype(jnp.bfloat16) for x in _rand_qkv(jax.random.key(5)))
    out = ring_self_attention(mesh_seq, q, k, v)
    assert out.dtype == jnp.bfloat16
    ref = dot_product_attention(q, k, v)
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32), rtol=0.05, atol=0.05
    )


def test_long_sequence_under_jit(mesh_seq):
    # longer-than-reference context (the capability the reference lacks)
    q, k, v = _rand_qkv(jax.random.key(6), b=1, h=1, t=512, dh=16)
    f = jax.jit(lambda q, k, v: ring_self_attention(mesh_seq, q, k, v))
    out = f(q, k, v)
    ref = dot_product_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_bert4rec_with_ring_attention_matches_full(mesh_seq):
    """Sequence-parallel Bert4Rec == full-attention Bert4Rec, same params."""
    from tdfo_tpu.models.bert4rec import Bert4RecConfig, key_padding_mask, make_sharded_bert4rec

    cfg = Bert4RecConfig(n_items=40, max_len=16, embed_dim=16, n_heads=2, n_layers=2)
    coll, tables, bb_full, dense = make_sharded_bert4rec(
        jax.random.key(0), cfg, mesh_seq, sharding="replicated", attn="full"
    )
    _, _, bb_ring, _ = make_sharded_bert4rec(
        jax.random.key(0), cfg, mesh_seq, sharding="replicated", attn="ring"
    )
    ids = jnp.array([[1, 2, 3, 4, 5, 41, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0]] * 2)
    embs = coll.lookup(tables, {"item": ids})
    lf = bb_full.apply({"params": dense}, embs["item"], key_padding_mask(ids))
    lr = bb_ring.apply({"params": dense}, embs["item"], key_padding_mask(ids))
    np.testing.assert_allclose(np.asarray(lr), np.asarray(lf), rtol=3e-5, atol=3e-5)


def test_ring_block_k_chunking_matches_unchunked(mesh_seq):
    """Inner blockwise chunking (O(Tq x block_k) logits + rematerialised
    backward) must be numerically identical to the unchunked ring, for
    outputs AND gradients."""
    import jax

    from tdfo_tpu.parallel.ring_attention import ring_self_attention

    rng = np.random.default_rng(5)
    b, h, t, dh = 2, 2, 32, 8
    q, k, v = (jnp.asarray(rng.normal(size=(b, h, t, dh)).astype(np.float32))
               for _ in range(3))
    valid = jnp.asarray(rng.random((b, t)) > 0.3)
    valid = valid.at[:, 0].set(True)

    out_full = ring_self_attention(mesh_seq, q, k, v, valid)
    out_blk = ring_self_attention(mesh_seq, q, k, v, valid, block_k=8)
    np.testing.assert_allclose(np.asarray(out_blk), np.asarray(out_full),
                               rtol=1e-5, atol=1e-6)

    def loss(fn_kwargs, q, k, v):
        return (ring_self_attention(mesh_seq, q, k, v, valid, **fn_kwargs) ** 2).sum()

    g_full = jax.grad(lambda q, k, v: loss({}, q, k, v), argnums=(0, 1, 2))(q, k, v)
    g_blk = jax.grad(lambda q, k, v: loss({"block_k": 8}, q, k, v), argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g_blk, g_full):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_), rtol=1e-5, atol=1e-6)


class TestRingFlash:
    """Flash kernels INSIDE the ring (impl="flash"): parity with the XLA ring
    for outputs and gradients — fwd merges per-chunk (out, lse) carries,
    bwd re-rotates K/V through the FlashAttention-2 recompute kernels."""

    def test_matches_ring_fwd(self, mesh_seq):
        q, k, v = _rand_qkv(jax.random.key(7))
        valid = jnp.asarray(np.random.default_rng(3).random((2, 16)) > 0.3)
        valid = valid.at[:, 0].set(True)
        ref = ring_self_attention(mesh_seq, q, k, v, valid)
        out = ring_self_attention(mesh_seq, q, k, v, valid, impl="flash")
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def test_all_masked_rows_zero(self, mesh_seq):
        q, k, v = _rand_qkv(jax.random.key(8))
        valid = jnp.zeros((2, 16), bool)
        out = ring_self_attention(mesh_seq, q, k, v, valid, impl="flash")
        assert not bool(jnp.isnan(out).any())
        np.testing.assert_allclose(np.asarray(out), 0.0)

    def test_gradients_match_ring(self, mesh_seq):
        q, k, v = _rand_qkv(jax.random.key(9))
        valid = jnp.asarray(np.random.default_rng(4).random((2, 16)) > 0.25)
        valid = valid.at[:, 0].set(True)

        def loss(impl, q, k, v):
            out = ring_self_attention(mesh_seq, q, k, v, valid, impl=impl)
            return (out ** 2).sum()

        gf = jax.grad(lambda *a: loss("flash", *a), argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(lambda *a: loss("xla", *a), argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gf, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=5e-4, atol=5e-4)

    def test_bert4rec_ring_flash_matches_full(self, mesh_seq):
        from tdfo_tpu.models.bert4rec import (
            Bert4RecConfig, key_padding_mask, make_sharded_bert4rec,
        )

        cfg = Bert4RecConfig(n_items=40, max_len=16, embed_dim=16, n_heads=2,
                             n_layers=1)
        coll, tables, bb_full, dense = make_sharded_bert4rec(
            jax.random.key(0), cfg, mesh_seq, sharding="replicated", attn="full"
        )
        _, _, bb_rf, _ = make_sharded_bert4rec(
            jax.random.key(0), cfg, mesh_seq, sharding="replicated",
            attn="ring_flash"
        )
        ids = jnp.array([[1, 2, 3, 4, 5, 41, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0]] * 2)
        embs = coll.lookup(tables, {"item": ids})
        lf = bb_full.apply({"params": dense}, embs["item"], key_padding_mask(ids))
        lr = bb_rf.apply({"params": dense}, embs["item"], key_padding_mask(ids))
        np.testing.assert_allclose(np.asarray(lr), np.asarray(lf),
                                   rtol=3e-5, atol=3e-5)
