import json
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tdfo_tpu.core.config import Config, MeshSpec, read_configs


def test_defaults_roundtrip():
    cfg = read_configs()
    assert cfg.n_epochs == 10
    assert cfg.embed_dim == 16
    assert cfg.mesh == MeshSpec()


def test_reference_compatible_toml(tmp_path: Path):
    # exact key set of the reference's jax-flax/config.toml
    (tmp_path / "config.toml").write_text(
        """
data_dir = "{d}"
train_data = "train_part_*.parquet"
eval_data = "eval_part_*.parquet"
streaming = true
n_epochs = 3
learning_rate = 3e-4
weight_decay = 1e-4
embed_dim = 16
per_device_train_batch_size = 2048
per_device_eval_batch_size = 2048
mixed_precision = false
seed = 42
""".format(d=tmp_path)
    )
    (tmp_path / "size_map.json").write_text(json.dumps({"user": 100, "item": 50}))
    cfg = read_configs(tmp_path / "config.toml")
    assert cfg.n_epochs == 3
    assert cfg.size_map == {"user": 100, "item": 50}
    assert cfg.data_dir == tmp_path


def test_torchrec_compatible_toml(tmp_path: Path):
    (tmp_path / "config.toml").write_text(
        """
data_dir = "/data"
n_heads = 2
n_layers = 2
max_len = 20
sliding_step = 10
mask_prob = 0.2
model_parallel = true
num_workers = 2
seed = 42
"""
    )
    cfg = read_configs(tmp_path / "config.toml")
    assert cfg.model_parallel and cfg.max_len == 20


def test_jit_xla_values_preserved(tmp_path: Path):
    # false is a real value now (eager debug mode) — no normalise-to-None
    (tmp_path / "config.toml").write_text("jit_xla = false\n")
    assert read_configs(tmp_path / "config.toml").jit_xla is False
    (tmp_path / "config.toml").write_text("jit_xla = true\n")
    assert read_configs(tmp_path / "config.toml").jit_xla is True


def test_unknown_key_rejected(tmp_path: Path):
    (tmp_path / "config.toml").write_text("bogus_key = 1\n")
    with pytest.raises(ValueError, match="bogus_key"):
        read_configs(tmp_path / "config.toml")


def test_max_len_sliding_step_assert():
    with pytest.raises(ValueError, match="sliding_step"):
        Config(max_len=5, sliding_step=10)


def test_mesh_table(tmp_path: Path):
    (tmp_path / "config.toml").write_text("[mesh]\ndata = 4\nmodel = 2\nseq = 1\n")
    cfg = read_configs(tmp_path / "config.toml")
    assert cfg.mesh.sizes() == (4, 2, 1)


def test_new_knob_validation():
    import pytest as _pytest

    from tdfo_tpu.core.config import Config

    for bad in (
        dict(lookup_mode="nccl"),
        dict(attn="linear"),
        dict(steps_per_execution=0),
        dict(streaming=False, write_format="tfrecord"),
    ):
        with _pytest.raises(ValueError):
            Config(**bad)
    # valid combinations construct fine
    Config(lookup_mode="alltoall", attn="ring", fused_table_threshold=8,
           steps_per_execution=4, streaming=False)


def test_faults_table(tmp_path: Path):
    """The [faults] section maps onto FaultSpec; unknown keys fail loudly
    like every other config key."""
    (tmp_path / "config.toml").write_text(
        "[faults]\nkill_at_step = 7\nnan_at_step = 3\n")
    cfg = read_configs(tmp_path / "config.toml")
    assert cfg.faults.kill_at_step == 7
    assert cfg.faults.nan_at_step == 3
    assert cfg.faults.fail_io_nth == 0
    assert cfg.faults.any()
    # defaults: no faults armed
    assert not read_configs().faults.any()
    (tmp_path / "config.toml").write_text("[faults]\nbogus = 1\n")
    with pytest.raises(ValueError, match="bogus"):
        read_configs(tmp_path / "config.toml")


def test_fault_tolerance_knob_validation():
    from tdfo_tpu.utils.faults import FaultSpec

    for bad in (
        dict(checkpoint_every_n_steps=-1),
        dict(max_bad_shards=-1),
        dict(nonfinite_tolerance=-1),
        dict(snapshot_every_n_steps=0),
    ):
        with pytest.raises(ValueError):
            Config(**bad)
    with pytest.raises(ValueError, match="kill_at_step"):
        FaultSpec(kill_at_step=-1)
    # valid combinations construct fine
    Config(checkpoint_every_n_steps=50, max_bad_shards=2,
           nonfinite_tolerance=0, snapshot_every_n_steps=10,
           faults=FaultSpec(fail_io_nth=2))


def test_fused_threshold_disable_semantics(tmp_path: Path):
    """-1 is the explicit opt-out: effective_fused_threshold becomes None so
    NO table fuses (the old magic 100000000 relied on no vocab exceeding
    it).  0 still means "fuse everything"; other negatives are rejected."""
    assert Config(fused_table_threshold=-1).effective_fused_threshold is None
    assert Config(fused_table_threshold=0).effective_fused_threshold == 0
    assert Config().effective_fused_threshold == 16384
    with pytest.raises(ValueError, match="fused_table_threshold"):
        Config(fused_table_threshold=-2)
    (tmp_path / "config.toml").write_text("fused_table_threshold = -1\n")
    assert read_configs(tmp_path / "config.toml").effective_fused_threshold is None
    # the observable semantic: -1 yields NO fused spec even for huge vocabs
    from tdfo_tpu.models.dlrm import generic_embedding_specs

    specs = generic_embedding_specs(
        {"c": 10**9}, ("c",), 16, "row",
        fused_threshold=Config(fused_table_threshold=-1).effective_fused_threshold)
    assert not specs[0].fused


def test_embeddings_table(tmp_path: Path):
    """The [embeddings] section maps onto EmbeddingsSpec; unknown keys and
    invalid values fail loudly like every other config key."""
    (tmp_path / "config.toml").write_text(
        "[embeddings]\nhot_vocab = 4096\nhot_fraction = 0.8\n")
    cfg = read_configs(tmp_path / "config.toml")
    assert cfg.embeddings.hot_vocab == 4096
    assert cfg.embeddings.hot_fraction == 0.8
    # defaults: hot/cold disabled
    assert read_configs().embeddings.hot_vocab == 0
    (tmp_path / "config.toml").write_text("[embeddings]\nbogus = 1\n")
    with pytest.raises(ValueError, match="bogus"):
        read_configs(tmp_path / "config.toml")


def test_embeddings_knob_validation():
    from tdfo_tpu.core.config import EmbeddingsSpec

    with pytest.raises(ValueError, match="hot_vocab"):
        Config(embeddings=EmbeddingsSpec(hot_vocab=-1))
    with pytest.raises(ValueError, match="hot_fraction"):
        Config(embeddings=EmbeddingsSpec(hot_vocab=8, hot_fraction=0.0))
    with pytest.raises(ValueError, match="gspmd"):
        Config(embeddings=EmbeddingsSpec(hot_vocab=8), lookup_mode="psum")
    Config(embeddings=EmbeddingsSpec(hot_vocab=8, hot_fraction=1.0))


def test_bert4rec_rejects_tfrecord():
    """write_format must DO something for every model: the seq ETL writes
    list-valued columns tfrecord does not carry (VERDICT r3 weak #4)."""
    import pytest as _pytest

    from tdfo_tpu.core.config import Config

    with _pytest.raises(ValueError, match="bert4rec"):
        Config(model="bert4rec", write_format="tfrecord")
    Config(model="bert4rec", write_format="parquet")


def test_train_table(tmp_path: Path):
    """The [train] section maps onto TrainSpec; unknown keys rejected,
    both pipelining knobs default OFF."""
    cfg = read_configs()
    assert cfg.train.pipeline_overlap is False
    assert cfg.embeddings.grouped_a2a is False
    (tmp_path / "config.toml").write_text(
        "model_parallel = true\nlookup_mode = \"alltoall\"\n"
        "[embeddings]\ngrouped_a2a = true\n"
        "[train]\npipeline_overlap = true\n")
    cfg = read_configs(tmp_path / "config.toml")
    assert cfg.embeddings.grouped_a2a is True
    assert cfg.train.pipeline_overlap is True
    (tmp_path / "config.toml").write_text("[train]\nbogus = 1\n")
    with pytest.raises(ValueError, match="bogus"):
        read_configs(tmp_path / "config.toml")


def test_grouped_a2a_knob_validation():
    from tdfo_tpu.core.config import EmbeddingsSpec, TrainSpec

    # grouped_a2a groups the alltoall exchange: other lookup modes have no
    # per-table collectives to group
    with pytest.raises(ValueError, match="alltoall"):
        Config(embeddings=EmbeddingsSpec(grouped_a2a=True),
               model_parallel=True)
    with pytest.raises(ValueError, match="model_parallel"):
        Config(embeddings=EmbeddingsSpec(grouped_a2a=True),
               lookup_mode="alltoall")
    Config(embeddings=EmbeddingsSpec(grouped_a2a=True),
           lookup_mode="alltoall", model_parallel=True)
    # pipeline_overlap rides the grouped input-dist and single-step dispatch
    with pytest.raises(ValueError, match="grouped_a2a"):
        Config(train=TrainSpec(pipeline_overlap=True))
    with pytest.raises(ValueError, match="steps_per_execution"):
        Config(train=TrainSpec(pipeline_overlap=True),
               embeddings=EmbeddingsSpec(grouped_a2a=True),
               lookup_mode="alltoall", model_parallel=True,
               steps_per_execution=4)
    Config(train=TrainSpec(pipeline_overlap=True),
           embeddings=EmbeddingsSpec(grouped_a2a=True),
           lookup_mode="alltoall", model_parallel=True)


def test_serving_table(tmp_path: Path):
    """The [serving] section maps onto ServingSpec; unknown keys rejected,
    buckets land as a tuple."""
    cfg = read_configs()
    assert cfg.serving.top_k == 100
    assert cfg.serving.buckets == (256, 1024, 8192)
    assert cfg.serving.coarse_k == 0  # exact single-stage by default
    assert cfg.serving.coarse_dtype == "int8"
    (tmp_path / "config.toml").write_text(
        "[serving]\ntop_k = 10\ncorpus_batch = 512\nmax_batch = 64\n"
        "batch_deadline_ms = 2.5\nbuckets = [16, 64]\ncoarse_k = 40\n"
        'coarse_dtype = "bfloat16"\n')
    cfg = read_configs(tmp_path / "config.toml")
    assert cfg.serving.top_k == 10
    assert cfg.serving.corpus_batch == 512
    assert cfg.serving.max_batch == 64
    assert cfg.serving.batch_deadline_ms == 2.5
    assert cfg.serving.buckets == (16, 64)
    assert cfg.serving.coarse_k == 40
    assert cfg.serving.coarse_dtype == "bfloat16"
    (tmp_path / "config.toml").write_text("[serving]\nbogus = 1\n")
    with pytest.raises(ValueError, match="bogus"):
        read_configs(tmp_path / "config.toml")


def test_serving_knob_validation():
    from tdfo_tpu.core.config import ServingSpec

    for bad, match in (
        (dict(top_k=0), "top_k"),
        (dict(corpus_batch=0), "corpus_batch"),
        (dict(max_batch=0), "max_batch"),
        (dict(batch_deadline_ms=-1.0), "batch_deadline_ms"),
        (dict(buckets=()), "buckets"),
        (dict(buckets=(8, 8)), "strictly increasing"),
        (dict(buckets=(32, 8)), "strictly increasing"),
        (dict(buckets=(0, 8)), "buckets"),
        (dict(max_batch=64, buckets=(8, 32)), "max_batch"),
        (dict(coarse_k=-1), "coarse_k"),
        (dict(coarse_k=50, top_k=100), "coarse_k"),
        (dict(coarse_dtype="int4"), "coarse_dtype"),
    ):
        with pytest.raises(ValueError, match=match):
            Config(serving=ServingSpec(**bad))
    Config(serving=ServingSpec(top_k=1, max_batch=8, buckets=(8,),
                               batch_deadline_ms=0.0))


def test_serving_knobs_observable():
    """Every [serving] key changes observable behaviour: the bucket set
    changes shipped padding, the deadline changes when partials ship, and
    max_batch changes when full batches ship."""
    import numpy as np

    from tdfo_tpu.serve.frontend import MicroBatcher

    score = lambda b: np.asarray(b["x"], np.float32)

    class Clock:
        t = 0.0

        def __call__(self):
            return self.t

    trace = [(i, {"x": np.arange(5)}) for i in range(2)]
    for buckets, padded in (((8, 64), 8), ((16, 64), 16)):
        mb = MicroBatcher(score, buckets=buckets, max_batch=64,
                          batch_deadline_ms=0.0, clock=Clock())
        mb.run(trace)
        assert {p for _, p in mb.shipped} == {padded}

    # deadline: 5 ms holds a 4 ms-old partial that 3 ms would have shipped
    for deadline, ships in ((3.0, True), (5.0, False)):
        clk = Clock()
        mb = MicroBatcher(score, buckets=(8,), max_batch=8,
                          batch_deadline_ms=deadline, clock=clk)
        mb.submit("r", {"x": np.arange(2)})
        clk.t = 0.004
        mb.poll()
        assert bool(mb.shipped) is ships

    # max_batch: the same trace ships full at 8 rows vs waits at 16
    for max_batch, batches in ((8, 2), (16, 1)):
        mb = MicroBatcher(score, buckets=(16,), max_batch=max_batch,
                          batch_deadline_ms=1e9, clock=Clock())
        for i in range(4):
            mb.submit(i, {"x": np.arange(4)})
        mb.drain()
        assert len(mb.shipped) == batches


def test_embeddings_dtype_table(tmp_path: Path):
    """[embeddings] table_dtype / slot_dtype / per-table overrides round-trip
    from toml; defaults stay float32 (byte-identical unquantized storage)."""
    cfg = read_configs()
    assert cfg.embeddings.table_dtype == "float32"
    assert cfg.embeddings.slot_dtype == "float32"
    assert cfg.embeddings.table_dtype_overrides == ()
    (tmp_path / "config.toml").write_text(
        'model = "dlrm"\n'
        "[embeddings]\n"
        'table_dtype = "bfloat16"\n'
        'slot_dtype = "bfloat16"\n'
        "[embeddings.table_dtype_overrides]\n"
        'user = "float32"\n')
    cfg = read_configs(tmp_path / "config.toml")
    assert cfg.embeddings.table_dtype == "bfloat16"
    assert cfg.embeddings.slot_dtype == "bfloat16"
    assert cfg.embeddings.dtype_for("user") == "float32"
    assert cfg.embeddings.dtype_for("item") == "bfloat16"
    hash(cfg.embeddings)  # overrides normalise to a tuple: spec stays hashable


def test_embeddings_dtype_validation():
    from tdfo_tpu.core.config import EmbeddingsSpec

    # unknown dtype strings rejected wherever they appear
    with pytest.raises(ValueError, match="table_dtype"):
        Config(model="dlrm", embeddings=EmbeddingsSpec(table_dtype="fp8"))
    with pytest.raises(ValueError, match="slot_dtype"):
        Config(model="dlrm", embeddings=EmbeddingsSpec(slot_dtype="float16"))
    with pytest.raises(ValueError, match="table_dtype_overrides"):
        Config(model="dlrm", embeddings=EmbeddingsSpec(
            table_dtype_overrides={"user": "int4"}))
    # int8 is a TABLE storage dtype only — slots refuse it
    with pytest.raises(ValueError, match="slot_dtype"):
        Config(model="dlrm", embeddings=EmbeddingsSpec(slot_dtype="int8"))
    # rowwise_adagrad keeps its f32 per-row accumulator: bf16 slots refused
    with pytest.raises(ValueError, match="rowwise_adagrad"):
        Config(model="dlrm", sparse_optimizer="rowwise_adagrad",
               embeddings=EmbeddingsSpec(slot_dtype="bfloat16"))
    # the knob configures the DMP sparse regime only
    with pytest.raises(ValueError, match="DMP"):
        Config(model="bert4rec",
               embeddings=EmbeddingsSpec(table_dtype="bfloat16"))
    with pytest.raises(ValueError, match="DMP"):
        Config(model="twotower", model_parallel=False,
               embeddings=EmbeddingsSpec(table_dtype="bfloat16"))
    # valid combinations construct fine
    Config(model="dlrm", embeddings=EmbeddingsSpec(
        table_dtype="bfloat16", slot_dtype="bfloat16"))
    Config(model="twotower", model_parallel=True,
           embeddings=EmbeddingsSpec(
               table_dtype="bfloat16",
               table_dtype_overrides={"user": "float32"}))
    # table bf16 with f32 slots is the rowwise-compatible combination
    Config(model="dlrm", sparse_optimizer="rowwise_adagrad",
           embeddings=EmbeddingsSpec(table_dtype="bfloat16"))


def test_int8_composition_matrix():
    """PR 18 makes storage dtype and layout orthogonal: int8 composes with
    the update cache, hot/cold, and the fused fat line.  The retained
    refusals (int8 slots, fused-int8 x rowwise_adagrad, int8 x column
    sharding) keep actionable errors."""
    from tdfo_tpu.core.config import EmbeddingsSpec

    # lifted: int8 x update cache (rows admitted dequantized, requantized
    # per row at write time, codes + sidecar scattered at flush)
    Config(model="dlrm", lookup_mode="gspmd",
           embeddings=EmbeddingsSpec(table_dtype="int8", cache_rows=4096))
    # lifted: int8 x hot/cold (the one-hot MXU update only ever touches
    # the f32 hot HEAD; the cold residual stays row-sparse int8)
    Config(model="dlrm", lookup_mode="gspmd",
           embeddings=EmbeddingsSpec(table_dtype="int8", hot_vocab=1024))
    # lifted: all three knobs at once
    Config(model="dlrm", lookup_mode="gspmd",
           embeddings=EmbeddingsSpec(table_dtype="int8", hot_vocab=1024,
                                     cache_rows=4096))
    # retained: rowwise_adagrad's shared f32 accumulator cannot ride a
    # quantized fat line — refused unless fusing is disabled outright,
    # and the message names the escape hatches
    with pytest.raises(ValueError, match="fused_table_threshold = -1"):
        Config(model="dlrm", sparse_optimizer="rowwise_adagrad",
               embeddings=EmbeddingsSpec(table_dtype="int8"))
    Config(model="dlrm", sparse_optimizer="rowwise_adagrad",
           fused_table_threshold=-1,
           embeddings=EmbeddingsSpec(table_dtype="int8"))
    # retained: int8 x column sharding (a column shard has no whole rows
    # to requantize against the per-ROW sidecar) — collection-level
    from tdfo_tpu.parallel.embedding import (
        EmbeddingSpec, ShardedEmbeddingCollection)

    mesh = jax.sharding.Mesh(np.array(jax.devices()), ("model",))
    with pytest.raises(ValueError, match="column"):
        ShardedEmbeddingCollection(
            [EmbeddingSpec("t", 256, 16, features=("t",), sharding="column",
                           dtype=jnp.int8)],
            mesh=mesh)


def test_planner_table(tmp_path: Path):
    """The [planner] section maps onto PlannerSpec; unknown keys rejected."""
    cfg = read_configs()
    assert cfg.planner.plan == ""
    assert cfg.planner.hbm_gb == 0.0
    assert cfg.planner.n_devices == 1
    (tmp_path / "config.toml").write_text(
        'model = "dlrm"\n'
        '[planner]\nplan = "plans/sharding_plan.json"\n'
        "hbm_gb = 14.5\nn_devices = 8\n")
    cfg = read_configs(tmp_path / "config.toml")
    assert cfg.planner.plan == "plans/sharding_plan.json"
    assert cfg.planner.hbm_gb == 14.5
    assert cfg.planner.n_devices == 8
    (tmp_path / "config.toml").write_text("[planner]\nbogus = 1\n")
    with pytest.raises(ValueError, match="bogus"):
        read_configs(tmp_path / "config.toml")


def test_planner_knob_validation():
    from tdfo_tpu.core.config import EmbeddingsSpec, PlannerSpec

    with pytest.raises(ValueError, match="hbm_gb"):
        Config(planner=PlannerSpec(hbm_gb=-1.0))
    with pytest.raises(ValueError, match="n_devices"):
        Config(planner=PlannerSpec(n_devices=0))
    plan = PlannerSpec(plan="sharding_plan.json")
    # the plan configures the DMP sparse regime only
    with pytest.raises(ValueError, match="regime"):
        Config(model="twotower", model_parallel=False, planner=plan)
    with pytest.raises(ValueError, match="regime"):
        Config(model="bert4rec", planner=plan)
    with pytest.raises(ValueError, match="gspmd"):
        Config(model="dlrm", lookup_mode="alltoall", planner=plan)
    # the plan OWNS the per-table levers; hand-set knobs must refuse
    with pytest.raises(ValueError, match="hot_vocab"):
        Config(model="dlrm", planner=plan,
               embeddings=EmbeddingsSpec(hot_vocab=128))
    with pytest.raises(ValueError, match="cache_rows"):
        Config(model="dlrm", planner=plan,
               embeddings=EmbeddingsSpec(cache_rows=1024))
    for hand in (dict(table_dtype="bfloat16"),
                 dict(table_dtype="bfloat16", slot_dtype="bfloat16"),
                 dict(table_dtype_overrides={"user": "bfloat16"})):
        with pytest.raises(ValueError, match="dtype"):
            Config(model="dlrm", planner=plan,
                   embeddings=EmbeddingsSpec(**hand))
    # valid combinations construct fine
    Config(model="dlrm", planner=plan)
    Config(model="twotower", model_parallel=True, planner=plan)


def test_serving_resilience_knobs(tmp_path: Path):
    """[serving] max_queue/shed_policy/swap_poll_s/max_bad_deltas: defaults,
    toml round-trip, rejections, and observable semantics for each."""
    import numpy as np

    from tdfo_tpu.core.config import ServingSpec
    from tdfo_tpu.serve.frontend import MicroBatcher
    from tdfo_tpu.serve.swap import DeltaPoller, SwapController

    cfg = read_configs()
    assert cfg.serving.max_queue == 0  # unbounded by default
    assert cfg.serving.shed_policy == "oldest"
    assert cfg.serving.swap_poll_s == 1.0
    assert cfg.serving.max_bad_deltas == 3

    (tmp_path / "config.toml").write_text(
        "[serving]\nmax_queue = 4\nshed_policy = \"reject\"\n"
        "swap_poll_s = 0.25\nmax_bad_deltas = 1\n")
    cfg = read_configs(tmp_path / "config.toml")
    assert cfg.serving.max_queue == 4
    assert cfg.serving.shed_policy == "reject"
    assert cfg.serving.swap_poll_s == 0.25
    assert cfg.serving.max_bad_deltas == 1

    for bad, match in (
        (dict(max_queue=-1), "max_queue"),
        (dict(shed_policy="drop-newest"), "shed_policy"),
        (dict(swap_poll_s=-0.5), "swap_poll_s"),
        (dict(max_bad_deltas=0), "max_bad_deltas"),
    ):
        with pytest.raises(ValueError, match=match):
            Config(serving=ServingSpec(**bad))

    # each knob is observable through the component it parameterizes:
    # max_queue bounds admissions, shed_policy picks the victim
    score = lambda b: np.asarray(b["x"], np.float32)  # noqa: E731
    for policy, victim in (("oldest", "r0"), ("reject", "r2")):
        mb = MicroBatcher(score, buckets=(8,), max_batch=8,
                          batch_deadline_ms=1e9, clock=lambda: 0.0,
                          max_queue=2, shed_policy=policy)
        for i in range(3):
            mb.submit(f"r{i}", {"x": np.arange(1)})
        assert [rid for rid, _ in mb.shed] == [victim]

    # swap_poll_s is the poll cadence
    now = [0.0]
    p = DeltaPoller(tmp_path, poll_s=0.25, clock=lambda: now[0])
    assert p.due() and not p.due()
    now[0] = 0.25
    assert p.due()

    # max_bad_deltas is the degraded-mode threshold
    class _Store:
        def record_quarantine(self, *a):
            pass

        def apply_delta(self, d):
            from tdfo_tpu.serve.swap import CorruptDeltaError

            raise CorruptDeltaError("corrupt delta")

    for threshold, after_one in ((1, True), (2, False)):
        ctrl = SwapController(_Store(), lambda d: None,
                              max_bad_deltas=threshold)
        assert ctrl.apply(tmp_path / "d") is False
        assert ctrl.degraded is after_one


def test_online_table(tmp_path: Path):
    """The [online] supervisor table: defaults, toml round-trip, unknown-key
    rejection, and the crash-safety coupling to checkpoint_dir."""
    from tdfo_tpu.core.config import OnlineSpec

    cfg = read_configs()
    assert cfg.online.request_log == ""  # off by default
    assert cfg.online.steps_per_cycle == 8
    assert cfg.online.max_cycles == 0  # drain mode
    assert cfg.online.max_bad_records == 0
    assert cfg.online.max_lag_records == 0  # unbounded lag
    assert cfg.online.lag_policy == "fail"

    (tmp_path / "config.toml").write_text(
        "checkpoint_dir = \"ckpt\"\n"
        "[online]\nrequest_log = \"rl\"\nsteps_per_cycle = 4\n"
        "max_cycles = 2\nmax_bad_records = 3\nmax_lag_records = 100\n"
        "lag_policy = \"skip\"\n")
    cfg = read_configs(tmp_path / "config.toml")
    assert cfg.online.request_log == "rl"
    assert cfg.online.steps_per_cycle == 4
    assert cfg.online.max_cycles == 2
    assert cfg.online.max_bad_records == 3
    assert cfg.online.max_lag_records == 100
    assert cfg.online.lag_policy == "skip"

    (tmp_path / "config.toml").write_text("[online]\nbogus = 1\n")
    with pytest.raises(ValueError, match="bogus"):
        read_configs(tmp_path / "config.toml")

    for bad, match in (
        (dict(steps_per_cycle=0), "steps_per_cycle"),
        (dict(max_cycles=-1), "max_cycles"),
        (dict(max_bad_records=-1), "max_bad_records"),
        (dict(max_lag_records=-1), "max_lag_records"),
        (dict(lag_policy="drop"), "lag_policy"),
    ):
        with pytest.raises(ValueError, match=match):
            Config(online=OnlineSpec(**bad))
    # the replay cursor persists as a checkpoint sidecar: a request_log
    # without checkpoint_dir cannot be crash-safe and is refused
    with pytest.raises(ValueError, match="checkpoint_dir"):
        Config(online=OnlineSpec(request_log="rl"))
    Config(online=OnlineSpec(request_log="rl"), checkpoint_dir="ckpt")


def test_request_log_and_rotation_knobs(tmp_path: Path):
    """[serving] log_features/log_segment_bytes + [telemetry]
    log_rotate_bytes: round-trip, rejections, and coupling."""
    from tdfo_tpu.core.config import ServingSpec, TelemetrySpec

    cfg = read_configs()
    assert cfg.serving.log_features is False
    assert cfg.serving.log_segment_bytes == 0
    assert cfg.telemetry.log_rotate_bytes == 0

    (tmp_path / "config.toml").write_text(
        "[serving]\nlog_features = true\nlog_segment_bytes = 65536\n"
        "[telemetry]\nlog_rotate_bytes = 1048576\n")
    cfg = read_configs(tmp_path / "config.toml")
    assert cfg.serving.log_features is True
    assert cfg.serving.log_segment_bytes == 65536
    assert cfg.telemetry.log_rotate_bytes == 1048576

    with pytest.raises(ValueError, match="log_segment_bytes"):
        Config(serving=ServingSpec(log_features=True, log_segment_bytes=-1))
    # rotation without the replayable log is a dead knob -> refused
    with pytest.raises(ValueError, match="log_features"):
        Config(serving=ServingSpec(log_segment_bytes=4096))
    with pytest.raises(ValueError, match="log_rotate_bytes"):
        Config(telemetry=TelemetrySpec(log_rotate_bytes=-1))


def test_replay_fault_triggers_table(tmp_path: Path):
    """The PR-10 [faults] triggers round-trip like the existing ones."""
    (tmp_path / "config.toml").write_text(
        "[faults]\ntruncate_log_at_byte = 100\ndup_record_nth = 2\n"
        "corrupt_record_nth = 3\nkill_during_replay = 4\n"
        "kill_between_stages = 5\n")
    cfg = read_configs(tmp_path / "config.toml")
    assert cfg.faults.truncate_log_at_byte == 100
    assert cfg.faults.dup_record_nth == 2
    assert cfg.faults.corrupt_record_nth == 3
    assert cfg.faults.kill_during_replay == 4
    assert cfg.faults.kill_between_stages == 5
    assert cfg.faults.any()


def test_fleet_and_gate_knobs(tmp_path: Path):
    """PR-14 knobs: [serving] replicas/keep_versions and the [online]
    canary-gatekeeper table — defaults, toml round-trip, and the
    validation couplings (a gate needs a fleet to stage on, and a watch
    window needs last-good + candidate co-resident on disk)."""
    from tdfo_tpu.core.config import OnlineSpec, ServingSpec

    cfg = read_configs()
    assert cfg.serving.replicas == 1  # single frontend: the PR-9/10 path
    assert cfg.serving.keep_versions == 0  # keep everything
    assert cfg.online.canary_cycles == 0  # ungated publish
    assert cfg.online.canary_fraction == 0.25
    assert cfg.online.max_auc_regression == 0.02
    assert cfg.online.shadow_eval_batches == 1
    assert cfg.online.keep_consumed_segments == 0

    (tmp_path / "config.toml").write_text(
        "checkpoint_dir = \"ckpt\"\n"
        "[serving]\nreplicas = 4\nkeep_versions = 3\n"
        "[online]\nrequest_log = \"rl\"\ncanary_cycles = 2\n"
        "canary_fraction = 0.5\nmax_auc_regression = 0.05\n"
        "shadow_eval_batches = 2\nkeep_consumed_segments = 4\n")
    cfg = read_configs(tmp_path / "config.toml")
    assert cfg.serving.replicas == 4
    assert cfg.serving.keep_versions == 3
    assert cfg.online.canary_cycles == 2
    assert cfg.online.canary_fraction == 0.5
    assert cfg.online.max_auc_regression == 0.05
    assert cfg.online.shadow_eval_batches == 2
    assert cfg.online.keep_consumed_segments == 4

    for kw, match in (
        (dict(serving=ServingSpec(replicas=0)), "replicas"),
        (dict(serving=ServingSpec(keep_versions=-1)), "keep_versions"),
        (dict(online=OnlineSpec(canary_cycles=-1)), "canary_cycles"),
        (dict(online=OnlineSpec(canary_fraction=0.0)), "canary_fraction"),
        (dict(online=OnlineSpec(canary_fraction=1.0)), "canary_fraction"),
        (dict(online=OnlineSpec(max_auc_regression=-0.1)),
         "max_auc_regression"),
        (dict(online=OnlineSpec(shadow_eval_batches=0)),
         "shadow_eval_batches"),
        (dict(online=OnlineSpec(keep_consumed_segments=-1)),
         "keep_consumed_segments"),
    ):
        with pytest.raises(ValueError, match=match):
            Config(**kw)
    # the gate stages candidates on a canary SLICE of the fleet: a single
    # frontend has no stable cohort to compare against
    with pytest.raises(ValueError, match="replicas >= 2"):
        Config(online=OnlineSpec(canary_cycles=1))
    # keep_versions = 1 cannot hold last-good + candidate simultaneously
    with pytest.raises(ValueError, match="keep_versions"):
        Config(online=OnlineSpec(canary_cycles=1),
               serving=ServingSpec(replicas=2, keep_versions=1))
    Config(online=OnlineSpec(canary_cycles=1),
           serving=ServingSpec(replicas=2, keep_versions=2))
    Config(online=OnlineSpec(canary_cycles=1),
           serving=ServingSpec(replicas=2))  # unbounded retention is fine


def test_fleet_fault_triggers_table(tmp_path: Path):
    """The PR-14 [faults] triggers round-trip and arm the injector."""
    (tmp_path / "config.toml").write_text(
        "[faults]\ncorrupt_candidate = 1\nregress_auc_at_cycle = 2\n"
        "kill_during_canary = 3\nkill_replica_nth = 4\n")
    cfg = read_configs(tmp_path / "config.toml")
    assert cfg.faults.corrupt_candidate == 1
    assert cfg.faults.regress_auc_at_cycle == 2
    assert cfg.faults.kill_during_canary == 3
    assert cfg.faults.kill_replica_nth == 4
    assert cfg.faults.any()


def test_trace_and_latency_gate_knobs(tmp_path: Path):
    """PR-15 knobs: [telemetry] trace, [online] max_p99_regression_ms and
    the [faults] slow_canary_at_cycle trigger — defaults, toml round-trip,
    rejection, and injector arming."""
    from tdfo_tpu.core.config import OnlineSpec

    cfg = read_configs()
    assert cfg.telemetry.trace is False  # off by default: tracing is free
    assert cfg.online.max_p99_regression_ms == 0.0  # latency gate disabled

    (tmp_path / "config.toml").write_text(
        "checkpoint_dir = \"ckpt\"\n"
        "[telemetry]\ntrace = true\n"
        "[serving]\nreplicas = 4\n"
        "[online]\nrequest_log = \"rl\"\ncanary_cycles = 2\n"
        "max_p99_regression_ms = 75.0\n"
        "[faults]\nslow_canary_at_cycle = 1\nslow_score_ms = 200\n")
    cfg = read_configs(tmp_path / "config.toml")
    assert cfg.telemetry.trace is True
    assert cfg.online.max_p99_regression_ms == 75.0
    assert cfg.faults.slow_canary_at_cycle == 1
    assert cfg.faults.slow_score_ms == 200
    assert cfg.faults.any()
    from tdfo_tpu.utils.faults import FaultInjector

    inj = FaultInjector(cfg.faults)
    assert inj.slow_canary_due(1) and not inj.slow_canary_due(2)

    with pytest.raises(ValueError, match="max_p99_regression_ms"):
        Config(online=OnlineSpec(max_p99_regression_ms=-1.0))


def test_process_fleet_knobs(tmp_path: Path):
    """PR-16 [serving] knobs for the out-of-process fleet: fleet_mode,
    the ingress eviction window / frame cap / connect schedule, and the
    supervisor respawn-backoff + flap-quarantine parameters — defaults,
    toml round-trip, and every rejection."""
    from tdfo_tpu.core.config import ServingSpec

    cfg = read_configs()
    assert cfg.serving.fleet_mode == "inproc"  # in-process fleet: PR-14
    assert cfg.serving.heartbeat_stale_ms == 5000.0
    assert cfg.serving.max_frame_bytes == 8 << 20
    assert cfg.serving.connect_retries == 10
    assert cfg.serving.connect_base_ms == 10.0
    assert cfg.serving.respawn_base_ms == 50.0
    assert cfg.serving.respawn_max_ms == 2000.0
    assert cfg.serving.flap_window_s == 30.0
    assert cfg.serving.flap_max_deaths == 3

    (tmp_path / "config.toml").write_text(
        "[serving]\nreplicas = 3\nfleet_mode = \"process\"\n"
        "heartbeat_stale_ms = 750.0\nmax_frame_bytes = 65536\n"
        "connect_retries = 4\nconnect_base_ms = 5.0\n"
        "respawn_base_ms = 25.0\nrespawn_max_ms = 400.0\n"
        "flap_window_s = 10.0\nflap_max_deaths = 2\n")
    cfg = read_configs(tmp_path / "config.toml")
    assert cfg.serving.fleet_mode == "process"
    assert cfg.serving.heartbeat_stale_ms == 750.0
    assert cfg.serving.max_frame_bytes == 65536
    assert cfg.serving.connect_retries == 4
    assert cfg.serving.connect_base_ms == 5.0
    assert cfg.serving.respawn_base_ms == 25.0
    assert cfg.serving.respawn_max_ms == 400.0
    assert cfg.serving.flap_window_s == 10.0
    assert cfg.serving.flap_max_deaths == 2

    for kw, match in (
        (dict(fleet_mode="threads"), "fleet_mode"),
        (dict(heartbeat_stale_ms=0.0), "heartbeat_stale_ms"),
        (dict(max_frame_bytes=512), "max_frame_bytes"),
        (dict(connect_retries=0), "connect_retries"),
        (dict(connect_base_ms=0.0), "connect_base_ms"),
        (dict(respawn_base_ms=0.0), "respawn_base_ms"),
        (dict(respawn_base_ms=100.0, respawn_max_ms=50.0),
         "respawn_max_ms"),
        (dict(flap_window_s=0.0), "flap_window_s"),
        (dict(flap_max_deaths=1), "flap_max_deaths"),
    ):
        with pytest.raises(ValueError, match=match):
            Config(serving=ServingSpec(**kw))
    # a process fleet needs at least two replicas: one process cannot host
    # a canary cohort AND a stable cohort
    with pytest.raises(ValueError, match="replicas >= 2"):
        Config(serving=ServingSpec(replicas=1, fleet_mode="process"))
    Config(serving=ServingSpec(replicas=2, fleet_mode="process"))


def test_loadgen_table(tmp_path: Path):
    """The [loadgen] table: defaults, toml round-trip, unknown-key
    rejection, and every validation — plus the observable semantics of
    mode/seed (the generated stream is a pure function of the spec)."""
    from tdfo_tpu.core.config import LoadgenSpec

    cfg = read_configs()
    assert cfg.loadgen.mode == "closed"
    assert cfg.loadgen.requests == 200
    assert cfg.loadgen.concurrency == 8
    assert cfg.loadgen.rate_qps == 100.0
    assert cfg.loadgen.zipf_a == 1.1
    assert cfg.loadgen.rows_per_request == 4
    assert cfg.loadgen.seed == 606
    assert cfg.loadgen.p99_slo_ms == 50.0

    (tmp_path / "config.toml").write_text(
        "[loadgen]\nmode = \"open\"\nrequests = 32\nconcurrency = 2\n"
        "rate_qps = 250.0\nzipf_a = 1.5\nrows_per_request = 8\n"
        "seed = 7\np99_slo_ms = 20.0\n")
    cfg = read_configs(tmp_path / "config.toml")
    assert cfg.loadgen.mode == "open"
    assert cfg.loadgen.requests == 32
    assert cfg.loadgen.concurrency == 2
    assert cfg.loadgen.rate_qps == 250.0
    assert cfg.loadgen.zipf_a == 1.5
    assert cfg.loadgen.rows_per_request == 8
    assert cfg.loadgen.seed == 7
    assert cfg.loadgen.p99_slo_ms == 20.0

    (tmp_path / "config.toml").write_text("[loadgen]\nbogus = 1\n")
    with pytest.raises(ValueError, match="loadgen"):
        read_configs(tmp_path / "config.toml")

    for kw, match in (
        (dict(mode="poisson"), "mode"),
        (dict(requests=0), "requests"),
        (dict(concurrency=0), "concurrency"),
        (dict(rate_qps=0.0), "rate_qps"),
        (dict(zipf_a=1.0), "zipf_a"),
        (dict(rows_per_request=0), "rows_per_request"),
        (dict(p99_slo_ms=0.0), "p99_slo_ms"),
    ):
        with pytest.raises(ValueError, match=match):
            Config(loadgen=LoadgenSpec(**kw))

    # seed/rows_per_request are observable: the synthetic stream is a pure
    # function of the spec (same seed -> same ids; different seed differs)
    from tdfo_tpu.serve.loadgen import LoadGenerator

    def stream(seed):
        gen = LoadGenerator(None, LoadgenSpec(seed=seed, rows_per_request=6),
                            {"user_id": 100})
        return [gen.request()[1]["user_id"].tolist() for _ in range(3)]

    assert stream(3) == stream(3)
    assert stream(3) != stream(4)
    assert all(len(b) == 6 for b in stream(5))


def test_sigkill_fault_trigger(tmp_path: Path):
    """[faults] kill_replica_signal round-trips, arms the injector
    exactly once per process, and rejects negatives — the real-SIGKILL
    twin of kill_replica_nth (tests/test_fleet_process.py uses the
    signal, tests/test_fleet.py the in-process flag)."""
    from tdfo_tpu.utils.faults import FaultInjector, FaultSpec

    (tmp_path / "config.toml").write_text(
        "[faults]\nkill_replica_signal = 2\n")
    cfg = read_configs(tmp_path / "config.toml")
    assert cfg.faults.kill_replica_signal == 2
    assert cfg.faults.any()

    inj = FaultInjector(cfg.faults)
    assert inj.replica_sigkill_due()  # fires once...
    assert not inj.replica_sigkill_due()  # ...and only once per process
    assert not FaultInjector(FaultSpec()).replica_sigkill_due()
    with pytest.raises(ValueError, match="kill_replica_signal"):
        FaultSpec(kill_replica_signal=-1)


def test_serving_seq_family_knobs(tmp_path: Path):
    """[serving] model_kind/max_history/history_buckets: defaults, toml
    round-trip, rejections, and the serve/online family-dispatch map
    (``serving_model_kind``) the launch entry points refuse through."""
    from tdfo_tpu.core.config import ServingSpec, serving_model_kind

    cfg = read_configs()
    assert cfg.serving.model_kind == "auto"
    assert cfg.serving.max_history == 0  # 0 = the full max_len - 1 window
    assert cfg.serving.history_buckets == ()  # empty = reuse `buckets`

    (tmp_path / "config.toml").write_text(
        'model = "bert4rec"\n[serving]\nmodel_kind = "seq"\n'
        "max_history = 6\nhistory_buckets = [4, 16, 64]\n")
    cfg = read_configs(tmp_path / "config.toml")
    assert cfg.serving.model_kind == "seq"
    assert cfg.serving.max_history == 6
    assert cfg.serving.history_buckets == (4, 16, 64)  # lands as a tuple

    for kwargs, match in (
        (dict(serving=ServingSpec(model_kind="bogus")), "model_kind"),
        # an explicit kind is cross-checked against the model family
        (dict(model="bert4rec", serving=ServingSpec(model_kind="ctr")),
         "does not match"),
        (dict(serving=ServingSpec(model_kind="seq")), "does not match"),
        (dict(serving=ServingSpec(max_history=-1)), "max_history"),
        # the window must leave room for the appended MASK position
        (dict(max_len=8, sliding_step=4, serving=ServingSpec(max_history=8)),
         "MASK"),
        (dict(serving=ServingSpec(history_buckets=(8, 8))),
         "strictly increasing"),
        (dict(serving=ServingSpec(history_buckets=(0, 8))),
         "history_buckets"),
    ):
        with pytest.raises(ValueError, match=match):
            Config(**kwargs)

    # the dispatch map: auto follows the model, explicit kinds pass through
    assert serving_model_kind(Config()) == "ctr"
    assert serving_model_kind(Config(model="dlrm")) == "ctr"
    assert serving_model_kind(Config(model="bert4rec")) == "seq"
    assert serving_model_kind(
        Config(model="bert4rec", serving=ServingSpec(model_kind="seq"))
    ) == "seq"

    # unknown models refuse LOUDLY at the serve/online entry points (the
    # launch.py dispatch wraps this in SystemExit) instead of shape-crashing
    # deep in a scorer
    class _Unmapped:
        model = "sasrec"
        serving = ServingSpec()

    with pytest.raises(ValueError, match="no serving family"):
        serving_model_kind(_Unmapped())
