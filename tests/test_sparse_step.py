"""Hybrid sparse/dense step parity tests.

* SGD: row-sparse SGD is mathematically identical to dense SGD (untouched
  rows get zero update), so the runs must match to fp tolerance.
* Adam: sparse/lazy Adam intentionally differs from dense Adam (dense decays
  the moments of untouched rows every step; lazy Adam — like fbgemm's fused
  ADAM — only touches gathered rows).  Parity bar is a NumPy lazy-Adam
  reference, plus exactness across sharding modes.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from tdfo_tpu.ops.sparse import sparse_optimizer
from tdfo_tpu.parallel.embedding import EmbeddingSpec, ShardedEmbeddingCollection
from tdfo_tpu.train.sparse_step import SparseTrainState, make_sparse_train_step

V, D, B = 40, 8, 16


def forward(dense_params, embs, batch):
    x = embs["item"]  # [B, D]
    logits = x @ dense_params["w"] + dense_params["b"]  # [B]
    return optax.sigmoid_binary_cross_entropy(logits, batch["label"]).mean()


def make_setup(mesh=None):
    coll = ShardedEmbeddingCollection(
        [EmbeddingSpec("item", V, D, features=("item",))], mesh=mesh
    )
    tables = coll.init(jax.random.key(0))
    dense_params = {
        "w": jnp.full((D,), 0.1, jnp.float32),
        "b": jnp.zeros((), jnp.float32),
    }
    return coll, tables, dense_params


def batches(n):
    rng = np.random.default_rng(0)
    for _ in range(n):
        ids = rng.integers(0, V, B, dtype=np.int32)
        yield {
            "item": jnp.asarray(ids),
            "label": jnp.asarray((ids % 2).astype(np.float32)),
        }


def run_sparse(n_steps=10, mesh=None, mode="gspmd", kind="adam", lr=1e-2):
    coll, tables, dense_params = make_setup(mesh)
    state = SparseTrainState.create(
        dense_params=dense_params,
        tx=optax.sgd(lr) if kind == "sgd" else optax.adam(lr),
        tables=tables,
        sparse_opt=sparse_optimizer(kind, lr=lr),
    )
    step = make_sparse_train_step(coll, forward, mode=mode, donate=False)
    losses = []
    for batch in batches(n_steps):
        state, loss = step(state, batch)
        losses.append(float(loss))
    return state, losses


def run_dense_sgd(n_steps=10, lr=1e-2):
    coll, tables, dense_params = make_setup(None)
    params = {"table": tables["item"], **dense_params}
    tx = optax.sgd(lr)
    opt_state = tx.init(params)

    @jax.jit
    def step(params, opt_state, batch):
        def loss_fn(p):
            embs = {"item": jnp.take(p["table"], batch["item"], axis=0)}
            return forward({"w": p["w"], "b": p["b"]}, embs, batch)

        loss, g = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(g, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    losses = []
    for batch in batches(n_steps):
        params, opt_state, loss = step(params, opt_state, batch)
        losses.append(float(loss))
    return params, losses


def lazy_adam_reference(n_steps, lr=1e-2, b1=0.9, b2=0.999, eps=1e-8):
    """NumPy lazy-Adam on the table; full Adam on dense params (they're
    touched every step, so lazy == dense for them)."""
    coll, tables, dense_params = make_setup(None)
    table = np.asarray(tables["item"], np.float64)
    w = np.asarray(dense_params["w"], np.float64)
    b = float(dense_params["b"])
    m_t, v_t = np.zeros_like(table), np.zeros_like(table)
    m_w, v_w = np.zeros_like(w), np.zeros_like(w)
    m_b = v_b = 0.0
    losses = []
    t = 0
    for batch in batches(n_steps):
        ids = np.asarray(batch["item"])
        y = np.asarray(batch["label"], np.float64)
        x = table[ids]
        logits = x @ w + b
        p = 1.0 / (1.0 + np.exp(-logits))
        losses.append(float(np.mean(
            np.logaddexp(0, logits) - y * logits
        )))
        dlogits = (p - y) / B
        gw = x.T @ dlogits
        gb = dlogits.sum()
        gx = np.outer(dlogits, w)
        gtab = np.zeros_like(table)
        np.add.at(gtab, ids, gx)
        t += 1
        c1, c2 = 1 - b1**t, 1 - b2**t
        touched = np.unique(ids)
        m_t[touched] = b1 * m_t[touched] + (1 - b1) * gtab[touched]
        v_t[touched] = b2 * v_t[touched] + (1 - b2) * gtab[touched] ** 2
        table[touched] -= lr * (m_t[touched] / c1) / (np.sqrt(v_t[touched] / c2) + eps)
        m_w = b1 * m_w + (1 - b1) * gw
        v_w = b2 * v_w + (1 - b2) * gw**2
        w -= lr * (m_w / c1) / (np.sqrt(v_w / c2) + eps)
        m_b = b1 * m_b + (1 - b1) * gb
        v_b = b2 * v_b + (1 - b2) * gb**2
        b -= lr * (m_b / c1) / (np.sqrt(v_b / c2) + eps)
    return table, losses


def test_sparse_sgd_matches_dense_sgd():
    state, sparse_losses = run_sparse(10, kind="sgd")
    params, dense_losses = run_dense_sgd(10)
    np.testing.assert_allclose(sparse_losses, dense_losses, rtol=1e-5)
    np.testing.assert_allclose(
        np.asarray(state.tables["item"]), np.asarray(params["table"]), rtol=1e-5, atol=1e-7
    )


def test_sparse_adam_matches_lazy_adam_reference():
    state, sparse_losses = run_sparse(10, kind="adam")
    table_ref, ref_losses = lazy_adam_reference(10)
    np.testing.assert_allclose(sparse_losses, ref_losses, rtol=1e-4)
    np.testing.assert_allclose(
        np.asarray(state.tables["item"]), table_ref, rtol=1e-4, atol=1e-6
    )


def test_sparse_step_loss_decreases():
    # overfit one fixed batch
    coll, tables, dense_params = make_setup()
    state = SparseTrainState.create(
        dense_params=dense_params, tx=optax.adam(1e-2), tables=tables,
        sparse_opt=sparse_optimizer("adam", lr=1e-2),
    )
    step = make_sparse_train_step(coll, forward, donate=False)
    batch = next(iter(batches(1)))
    losses = []
    for _ in range(80):
        state, loss = step(state, batch)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])


def test_sharded_matches_unsharded(mesh8):
    _, base = run_sparse(8, mesh=None)
    state, gspmd = run_sparse(8, mesh=mesh8, mode="gspmd")
    _, psum = run_sparse(8, mesh=mesh8, mode="psum")
    np.testing.assert_allclose(gspmd, base, rtol=1e-5)
    np.testing.assert_allclose(psum, base, rtol=1e-5)
    # tables remain row-sharded after updates
    assert state.tables["item"].sharding.spec[0] == "model"


def test_step_counter_and_slots():
    state, _ = run_sparse(5)
    assert int(state.step) == 5
    assert int(state.slots["item"][2]) == 5  # adam count advanced


def test_fused_fat_table_sharded_update_matches_unsharded(mesh8):
    """Fused (fat-row) tables ROW-SHARDED over the model axis must update
    through the explicit shard_map program (Pallas has no GSPMD partition
    rule — a plain jit would all-gather the whole fat table) and produce the
    same result as the unsharded fat path, with the output still sharded."""
    from tdfo_tpu.ops.sparse import sparse_optimizer as mk_opt

    d = 8
    specs = [EmbeddingSpec("item", V, d, features=("item",), sharding="row",
                           fused=True)]
    coll_sh = ShardedEmbeddingCollection(specs, mesh=mesh8)
    coll_un = ShardedEmbeddingCollection(
        [EmbeddingSpec("item", V, d, features=("item",), fused=True)]
    )
    tables_sh = coll_sh.init(jax.random.key(0))
    tables_un = coll_un.init(jax.random.key(0))
    opt = mk_opt("adam", lr=1e-2)
    slots = (jnp.zeros((), jnp.int32),)

    rng = np.random.default_rng(1)
    ids = jnp.asarray(rng.integers(0, V, B, dtype=np.int32))
    grads = jnp.asarray(rng.normal(size=(B, d)).astype(np.float32))

    upd_sh = jax.jit(lambda t, s, i, g: coll_sh.sparse_update(opt, "item", t, s, i, g))
    upd_un = jax.jit(lambda t, s, i, g: coll_un.sparse_update(opt, "item", t, s, i, g))
    t_sh, s_sh = upd_sh(tables_sh["item"], slots, ids, grads)
    t_un, s_un = upd_un(tables_un["item"], slots, ids, grads)

    np.testing.assert_allclose(np.asarray(t_sh), np.asarray(t_un), rtol=1e-5, atol=1e-7)
    assert int(s_sh[0]) == int(s_un[0]) == 1
    assert t_sh.sharding.spec[0] == "model"  # still row-sharded after update
    # lookups agree too (fat component extraction under both placements)
    v_sh = coll_sh.lookup(tables_sh, {"item": ids})["item"]
    v_un = coll_un.lookup(tables_un, {"item": ids})["item"]
    np.testing.assert_allclose(np.asarray(v_sh), np.asarray(v_un), rtol=1e-6)


def test_dedup_lookup_matches_default_path(mesh8):
    """dedup_lookup=True (TBE unique-then-expand, shared sort between fwd
    and update) must produce the SAME trajectory as the default path: same
    gather values, same segment construction, same optimizer math."""
    import optax

    from tdfo_tpu.models.dlrm import DLRMBackbone, generic_embedding_specs
    from tdfo_tpu.ops.sparse import sparse_optimizer
    from tdfo_tpu.parallel.embedding import ShardedEmbeddingCollection
    from tdfo_tpu.train.ctr import ctr_sparse_forward

    cats = ("c0", "c1", "c2")
    conts = ("x0",)
    sizes = {"c0": 50, "c1": 300, "c2": 7}
    r = np.random.default_rng(11)

    def run(dedup):
        coll = ShardedEmbeddingCollection(
            generic_embedding_specs(sizes, cats, 8, "row", fused_threshold=None),
            mesh=mesh8, stack_tables=True,
        )
        bb = DLRMBackbone(embed_dim=8, cat_columns=cats, cont_columns=conts)
        tables = coll.init(jax.random.key(0))
        dummy_e = {c: jnp.zeros((1, 8), jnp.float32) for c in cats}
        dummy_c = {c: jnp.zeros((1,), jnp.float32) for c in conts}
        state = SparseTrainState.create(
            dense_params=bb.init(jax.random.key(1), dummy_e, dummy_c)["params"],
            tx=optax.adam(1e-2),
            tables=tables,
            sparse_opt=sparse_optimizer("rowwise_adagrad", lr=1e-2),
        )
        step = make_sparse_train_step(
            coll, ctr_sparse_forward(bb), donate=False, dedup_lookup=dedup
        )
        rr = np.random.default_rng(12)
        losses = []
        for _ in range(4):
            batch = {c: jnp.asarray(rr.integers(0, sizes[c], 32), jnp.int32)
                     for c in cats}
            batch["x0"] = jnp.asarray(rr.random(32, dtype=np.float32))
            batch["label"] = jnp.asarray(rr.integers(0, 2, 32), jnp.float32)
            state, loss = step(state, batch)
            losses.append(float(loss))
        return losses, state

    l_def, s_def = run(False)
    l_dd, s_dd = run(True)
    np.testing.assert_allclose(l_dd, l_def, rtol=1e-6)
    for n in s_def.tables:
        np.testing.assert_allclose(
            np.asarray(s_dd.tables[n]), np.asarray(s_def.tables[n]),
            rtol=1e-6, atol=1e-7)


def test_dedup_lookup_requires_gspmd():
    import pytest

    from tdfo_tpu.models.dlrm import generic_embedding_specs
    from tdfo_tpu.parallel.embedding import ShardedEmbeddingCollection

    coll = ShardedEmbeddingCollection(
        generic_embedding_specs({"a": 10}, ("a",), 8, "replicated"))
    with pytest.raises(ValueError, match="gspmd"):
        make_sparse_train_step(coll, lambda d, e, b: 0.0, mode="psum",
                               dedup_lookup=True)
