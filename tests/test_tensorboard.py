"""TF-free TensorBoard scalar writer, cross-validated against TensorFlow's
own event reader (TF is in the test image; the framework never imports it).
Reference parity: tensorflow2/train_ps.py:154 TensorBoard callback."""

import numpy as np
import pytest

from tdfo_tpu.utils.tensorboard import TBScalarWriter


def _read_events(log_dir):
    tf = pytest.importorskip("tensorflow")
    files = sorted(log_dir.glob("events.out.tfevents.*"))
    assert len(files) == 1, files
    return list(tf.compat.v1.train.summary_iterator(str(files[0])))


def test_tf_reads_our_events(tmp_path):
    w = TBScalarWriter(tmp_path)
    w.scalars(0, {"train_loss": 0.75, "auc": 0.5})
    w.scalars(10, {"train_loss": 0.25})
    w.close()
    events = _read_events(tmp_path)
    assert events[0].file_version == "brain.Event:2"
    got = {}
    for ev in events[1:]:
        for v in ev.summary.value:
            got[(ev.step, v.tag)] = v.simple_value
    np.testing.assert_allclose(got[(0, "train_loss")], 0.75)
    np.testing.assert_allclose(got[(0, "auc")], 0.5)
    np.testing.assert_allclose(got[(10, "train_loss")], 0.25)
    assert all(ev.wall_time > 0 for ev in events)


def test_trainer_tensorboard_knob(tmp_path):
    """Config(tensorboard=true) must produce a parseable events file with
    the training curves (every config key DOES something)."""
    from tdfo_tpu.core.config import read_configs
    from tdfo_tpu.data.ctr_preprocessing import run_ctr_preprocessing
    from tdfo_tpu.data.synthetic import write_synthetic_goodreads
    from tdfo_tpu.train.trainer import Trainer

    d = tmp_path / "gr"
    write_synthetic_goodreads(d, n_users=40, n_books=60,
                              interactions_per_user=(8, 16), seed=11)
    size_map = run_ctr_preprocessing(d)
    cfg = read_configs(
        None, data_dir=d, model="twotower", n_epochs=2, learning_rate=3e-3,
        embed_dim=8, per_device_train_batch_size=16,
        per_device_eval_batch_size=16, shuffle_buffer_size=500,
        log_every_n_steps=5, size_map=size_map, tensorboard=True,
    )
    log_dir = tmp_path / "logs"
    Trainer(cfg, log_dir=log_dir).fit()
    events = _read_events(log_dir)
    tags = {v.tag for ev in events for v in ev.summary.value}
    assert "train_loss_epoch" in tags and "auc" in tags, tags
    # per-epoch eval points carry the epoch as the step
    auc_steps = sorted(ev.step for ev in events
                       for v in ev.summary.value if v.tag == "auc")
    assert auc_steps == [0, 1], auc_steps
