"""TF-free TensorBoard scalar writer, cross-validated against TensorFlow's
own event reader (TF is in the test image; the framework never imports it).
Reference parity: tensorflow2/train_ps.py:154 TensorBoard callback."""

import numpy as np
import pytest

from tdfo_tpu.utils.tensorboard import TBScalarWriter


def _read_events(log_dir):
    tf = pytest.importorskip("tensorflow")
    files = sorted(log_dir.glob("events.out.tfevents.*"))
    assert len(files) == 1, files
    return list(tf.compat.v1.train.summary_iterator(str(files[0])))


def test_tf_reads_our_events(tmp_path):
    w = TBScalarWriter(tmp_path)
    w.scalars(0, {"train_loss": 0.75, "auc": 0.5})
    w.scalars(10, {"train_loss": 0.25})
    w.close()
    events = _read_events(tmp_path)
    assert events[0].file_version == "brain.Event:2"
    got = {}
    for ev in events[1:]:
        for v in ev.summary.value:
            got[(ev.step, v.tag)] = v.simple_value
    np.testing.assert_allclose(got[(0, "train_loss")], 0.75)
    np.testing.assert_allclose(got[(0, "auc")], 0.5)
    np.testing.assert_allclose(got[(10, "train_loss")], 0.25)
    assert all(ev.wall_time > 0 for ev in events)


def test_tf_reads_our_histograms(tmp_path):
    """The minimal HistogramProto encoding round-trips through TF's own
    reader: min/max/num/sum/sum_squares and the packed bucket arrays match
    np.histogram exactly; non-finite values are filtered, and an
    all-non-finite input writes nothing."""
    w = TBScalarWriter(tmp_path)
    finite = np.linspace(-1.0, 2.0, 50)
    vals = np.concatenate([finite, [np.nan, np.inf, -np.inf]])
    w.histogram(7, "grad_norm_dist", vals, wall_time=123.0, bins=8)
    w.histogram(8, "empty_dist", [np.nan, np.inf])  # filtered to nothing
    w.close()
    events = _read_events(tmp_path)
    histos = [(ev, v) for ev in events for v in ev.summary.value
              if v.HasField("histo")]
    assert len(histos) == 1  # the all-non-finite histogram was dropped
    ev, v = histos[0]
    assert ev.step == 7 and ev.wall_time == 123.0
    assert v.tag == "grad_norm_dist"
    counts, edges = np.histogram(finite, bins=8)
    h = v.histo
    assert h.min == finite.min() and h.max == finite.max()
    assert h.num == finite.size
    np.testing.assert_allclose(h.sum, finite.sum())
    np.testing.assert_allclose(h.sum_squares, (finite * finite).sum())
    # bucket_limit[i] is bucket i's RIGHT edge (TB convention)
    np.testing.assert_allclose(list(h.bucket_limit), edges[1:])
    np.testing.assert_array_equal(list(h.bucket), counts)


def test_metric_logger_flushes_norm_histograms(tmp_path):
    """MetricLogger buffers every grad/param norm it logs and close()
    flushes ONE run-wide distribution histogram per tag."""
    from tdfo_tpu.train.trainer import MetricLogger

    lg = MetricLogger(tmp_path, tensorboard=True)
    for i, g in enumerate((0.5, 1.5, 2.5)):
        lg.log(global_step=i, train_loss=0.1, grad_norm=g, param_norm=10.0)
    lg.close()
    events = _read_events(tmp_path)
    hist_tags = {v.tag for ev in events for v in ev.summary.value
                 if v.HasField("histo")}
    assert hist_tags == {"grad_norm_dist", "param_norm_dist"}
    for ev in events:
        for v in ev.summary.value:
            if v.tag == "grad_norm_dist":
                assert v.histo.num == 3 and v.histo.min == 0.5
                assert v.histo.max == 2.5


def test_trainer_tensorboard_knob(tmp_path):
    """Config(tensorboard=true) must produce a parseable events file with
    the training curves (every config key DOES something)."""
    from tdfo_tpu.core.config import read_configs
    from tdfo_tpu.data.ctr_preprocessing import run_ctr_preprocessing
    from tdfo_tpu.data.synthetic import write_synthetic_goodreads
    from tdfo_tpu.train.trainer import Trainer

    d = tmp_path / "gr"
    write_synthetic_goodreads(d, n_users=40, n_books=60,
                              interactions_per_user=(8, 16), seed=11)
    size_map = run_ctr_preprocessing(d)
    cfg = read_configs(
        None, data_dir=d, model="twotower", n_epochs=2, learning_rate=3e-3,
        embed_dim=8, per_device_train_batch_size=16,
        per_device_eval_batch_size=16, shuffle_buffer_size=500,
        log_every_n_steps=5, size_map=size_map, tensorboard=True,
    )
    log_dir = tmp_path / "logs"
    Trainer(cfg, log_dir=log_dir).fit()
    events = _read_events(log_dir)
    tags = {v.tag for ev in events for v in ev.summary.value}
    assert "train_loss_epoch" in tags and "auc" in tags, tags
    # per-epoch eval points carry the epoch as the step
    auc_steps = sorted(ev.step for ev in events
                       for v in ev.summary.value if v.tag == "auc")
    assert auc_steps == [0, 1], auc_steps
