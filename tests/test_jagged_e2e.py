"""Jagged end-to-end: Bert4Rec trains from RAGGED parquet rows.

torchrec parity for the part that made its input path hard
(``torchrec/train.py:33-41`` builds a KJT per batch;
``torchrec/models.py:163-178`` consumes it): preprocessing writes
variable-length windows with no offline padding, the loader carries them as
object columns, the trainer packs (values, lengths) per batch, and
``jagged_to_dense`` materialises [B, T] ids INSIDE the jitted step.
"""

import numpy as np
import pytest

from tdfo_tpu.core.config import read_configs
from tdfo_tpu.data.jagged import jagged_to_dense_per_host, pack_rows
from tdfo_tpu.data.seq_preprocessing import run_seq_preprocessing
from tdfo_tpu.data.synthetic import write_synthetic_goodreads
from tdfo_tpu.train.trainer import Trainer


@pytest.fixture(scope="module")
def twin_dirs(tmp_path_factory):
    """The SAME raw data preprocessed twice: offline-padded and ragged."""
    padded = tmp_path_factory.mktemp("gr_padded")
    ragged = tmp_path_factory.mktemp("gr_ragged")
    stats = {}
    for d, pad in ((padded, True), (ragged, False)):
        write_synthetic_goodreads(d, n_users=100, n_books=150,
                                  interactions_per_user=(15, 50), seed=9)
        stats[pad] = run_seq_preprocessing(d, max_len=12, sliding_step=6,
                                           seed=9, pad=pad)
    assert stats[True]["n_items"] == stats[False]["n_items"]
    return padded, ragged, stats[True]["n_items"]


def test_loader_yields_object_columns_for_ragged(twin_dirs):
    from tdfo_tpu.data.loader import ParquetStream, resolve_files

    _, ragged, _ = twin_dirs
    files = resolve_files(ragged, "parquet_bert4rec/train_part_*.parquet")
    # without opting in, ragged shards fail loudly with an actionable message
    guard = ParquetStream(files, batch_size=16, shuffle=False, drop_last=True)
    with pytest.raises(ValueError, match="jagged"):
        next(iter(guard))
    stream = ParquetStream(files, batch_size=16, shuffle=False, drop_last=True,
                           allow_ragged=True)
    batch = next(iter(stream))
    col = batch["train_interactions"]
    assert col.dtype == object
    lens = {len(r) for r in col}
    assert len(lens) > 1, "expected variable-length windows"
    assert max(lens) <= 12


def test_pack_roundtrip_matches_padded_windows(twin_dirs):
    """pack_rows + jagged_to_dense == the offline-padded windows, row for
    row (both ETLs share seed, so window order is identical)."""
    from tdfo_tpu.data.loader import ParquetStream, resolve_files

    padded, ragged, _ = twin_dirs
    sp = ParquetStream(resolve_files(padded, "parquet_bert4rec/train_part_*.parquet"),
                       batch_size=32, shuffle=False, drop_last=True)
    sr = ParquetStream(resolve_files(ragged, "parquet_bert4rec/train_part_*.parquet"),
                       batch_size=32, shuffle=False, drop_last=True,
                       allow_ragged=True)
    bp, br = next(iter(sp)), next(iter(sr))
    values, lengths = pack_rows(list(br["train_interactions"]), 32 * 12)
    dense = np.asarray(jagged_to_dense_per_host(values, lengths, 12, 0))
    np.testing.assert_array_equal(dense, bp["train_interactions"])


def test_jagged_trainer_matches_padded_trainer(twin_dirs, tmp_path):
    """One epoch from ragged rows == one epoch from padded rows: identical
    shuffle seeds and window order mean the materialised [B, T] batches are
    the same, so the loss trajectories must agree to fp tolerance."""
    padded, ragged, n_items = twin_dirs
    common = dict(
        model="bert4rec", model_parallel=True, n_epochs=1, learning_rate=3e-3,
        embed_dim=16, n_heads=2, n_layers=1, max_len=12, sliding_step=6,
        per_device_train_batch_size=8, per_device_eval_batch_size=8,
        shuffle_buffer_size=1000, log_every_n_steps=1000,
        size_map={"n_items": n_items},
    )
    tr_p = Trainer(read_configs(None, data_dir=padded, **common))
    tr_j = Trainer(read_configs(None, data_dir=ragged, jagged=True, **common))
    loss_p = tr_p.train_epoch(0)
    loss_j = tr_j.train_epoch(0)
    assert np.isclose(loss_p, loss_j, rtol=1e-4), (loss_p, loss_j)
    # eval protocol unchanged (padded eval seqs in both modes)
    m_j = tr_j.evaluate(0)
    for v in m_j.values():
        assert 0.0 <= v <= 1.0


def test_jagged_step_skewed_lengths():
    """Extreme skew (empty rows next to full rows) through the jitted step."""
    import jax
    import jax.numpy as jnp
    import optax

    from tdfo_tpu.data.jagged import jagged_to_dense
    from tdfo_tpu.models.bert4rec import Bert4RecConfig, make_sharded_bert4rec
    from tdfo_tpu.ops.sparse import sparse_optimizer
    from tdfo_tpu.train.seq import bert4rec_sparse_forward
    from tdfo_tpu.train.sparse_step import SparseTrainState, make_sparse_train_step

    cfg = Bert4RecConfig(n_items=40, max_len=8, embed_dim=16, n_heads=2, n_layers=1)
    coll, tables, backbone, dense = make_sharded_bert4rec(
        jax.random.key(0), cfg, None, sharding="replicated"
    )
    state = SparseTrainState.create(
        dense_params=dense, tx=optax.adam(1e-3), tables=tables,
        sparse_opt=sparse_optimizer("adam", lr=1e-3),
    )

    def transform(batch):
        item = jagged_to_dense(batch["item_values"], batch["item_lengths"], 8, 0)
        label = jagged_to_dense(batch["label_values"], batch["item_lengths"], 8, 0)
        return {"item": item, "label": label}

    step = make_sparse_train_step(
        coll, bert4rec_sparse_forward(backbone), donate=False,
        batch_transform=transform,
    )
    rows = [np.array([], np.int32), np.arange(1, 9, dtype=np.int32),
            np.array([3], np.int32), np.arange(1, 9, dtype=np.int32)]
    iv, il = pack_rows(rows, 4 * 8)
    lv = iv.copy()  # labels mirror items (every position supervised)
    batch = {"item_values": jnp.asarray(iv), "item_lengths": jnp.asarray(il),
             "label_values": jnp.asarray(lv)}
    state, loss = step(state, batch, jax.random.key(1))
    assert np.isfinite(float(loss))
