import jax.numpy as jnp
import numpy as np

from tdfo_tpu.data.jagged import JaggedTensor, dense_to_jagged, jagged_to_dense


def test_from_lists_and_offsets():
    jt = JaggedTensor.from_lists([[1, 2, 3], [4], [5, 6]])
    assert jt.batch_size == 3
    np.testing.assert_array_equal(jt.lengths, [3, 1, 2])
    np.testing.assert_array_equal(jt.offsets, [0, 3, 4, 6])
    np.testing.assert_array_equal(jt.values, [1, 2, 3, 4, 5, 6])


def test_to_dense_pad_and_truncate():
    jt = JaggedTensor.from_lists([[1, 2, 3], [4], [5, 6]])
    dense = jt.to_dense(max_len=2, pad_value=0)
    np.testing.assert_array_equal(dense, [[1, 2], [4, 0], [5, 6]])
    dense4 = jt.to_dense(max_len=4, pad_value=-1)
    np.testing.assert_array_equal(dense4, [[1, 2, 3, -1], [4, -1, -1, -1], [5, 6, -1, -1]])


def test_to_dense_2d_values():
    values = jnp.arange(12.0).reshape(6, 2)
    lengths = jnp.asarray([2, 1, 3], jnp.int32)
    dense = jagged_to_dense(values, lengths, max_len=3, pad_value=0.0)
    assert dense.shape == (3, 3, 2)
    np.testing.assert_array_equal(dense[0, 0], [0.0, 1.0])
    np.testing.assert_array_equal(dense[1, 1], [0.0, 0.0])  # padded
    np.testing.assert_array_equal(dense[2, 2], [10.0, 11.0])


def test_dense_jagged_roundtrip():
    rows = [[7, 8], [9], [10, 11, 12]]
    jt = JaggedTensor.from_lists(rows)
    dense = jt.to_dense(max_len=3)
    packed = dense_to_jagged(dense, jt.lengths)
    np.testing.assert_array_equal(packed[:6], [7, 8, 9, 10, 11, 12])
    # invariant: tail slots are zeroed even when dense used a nonzero pad
    dense_pad = jt.to_dense(max_len=3, pad_value=-1)
    packed_pad = dense_to_jagged(dense_pad, jt.lengths)
    np.testing.assert_array_equal(packed_pad[6:], 0)
    jt2 = JaggedTensor.from_dense(dense, jt.lengths)
    np.testing.assert_array_equal(jt2.to_dense(max_len=3), dense)


def test_capacity_padding():
    jt = JaggedTensor.from_lists([[1], [2, 3]], capacity=10)
    assert jt.values.shape == (10,)
    dense = jt.to_dense(max_len=2)
    np.testing.assert_array_equal(dense, [[1, 0], [2, 3]])


def test_jagged_to_dense_per_host_segmented_offsets():
    """Per-host packing: offsets restart at every host boundary; the result
    must equal the single-host conversion of the same logical rows."""
    import numpy as np

    from tdfo_tpu.data.jagged import jagged_to_dense, jagged_to_dense_per_host, pack_rows

    rows = [np.array([1, 2, 3], np.int32), np.array([4], np.int32),
            np.array([], np.int32), np.array([5, 6], np.int32)]
    t = 4
    # two hosts, two rows each, per-host capacity 2*t
    v0, l0 = pack_rows(rows[:2], 2 * t)
    v1, l1 = pack_rows(rows[2:], 2 * t)
    values = jnp.concatenate([jnp.asarray(v0), jnp.asarray(v1)])
    lengths = jnp.concatenate([jnp.asarray(l0), jnp.asarray(l1)])
    got = np.asarray(jagged_to_dense_per_host(values, lengths, t, 0, n_hosts=2))

    vg, lg = pack_rows(rows, 4 * t)
    want = np.asarray(jagged_to_dense(jnp.asarray(vg), jnp.asarray(lg), t, 0))
    np.testing.assert_array_equal(got, want)


def test_per_host_divisibility_rejected():
    import pytest

    from tdfo_tpu.data.jagged import jagged_to_dense_per_host

    values = jnp.zeros((10,), jnp.int32)  # 10 % 3 != 0
    lengths = jnp.zeros((6,), jnp.int32)
    with pytest.raises(ValueError, match="divide"):
        jagged_to_dense_per_host(values, lengths, 4, 0, n_hosts=3)
