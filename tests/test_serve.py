"""Serving subsystem tests: export, scoring parity, corpus, retrieval.

The three contracts under test, in order of importance:

  * train/serve skew is ZERO — a scorer built from an exported bundle
    produces bitwise the same logits as the training eval step it mirrors
    (``train/ctr.py make_ctr_sparse_eval_step``), for both CTR regimes;
  * bundles are hot/cold-AGNOSTIC — the ``{name}__hot`` merge writes the
    live head rows over their dead cold duplicates, so a split and an
    unsplit run of the same state export byte-identical tables;
  * sharded exact retrieval is bitwise-equal (ids AND f32 scores) to the
    single-device stable-argsort reference, including tie-breaks, for
    k in {10, 100} and a corpus that does NOT divide the mesh evenly.
"""

import numpy as np
import optax
import pytest

import jax
import jax.numpy as jnp

from tdfo_tpu.models.twotower import (
    TWOTOWER_CATEGORICAL,
    TwoTower,
    TwoTowerBackbone,
    ctr_embedding_specs,
)
from tdfo_tpu.ops.sparse import sparse_optimizer
from tdfo_tpu.parallel.embedding import ShardedEmbeddingCollection
from tdfo_tpu.serve.corpus import build_corpus, synthetic_item_features
from tdfo_tpu.serve.export import (
    BUNDLE_VERSION,
    export_bundle,
    load_bundle,
    merged_tables,
)
from tdfo_tpu.serve.retrieval import (
    make_retrieval,
    mips_scores,
    retrieval_reference,
)
from tdfo_tpu.serve.scoring import make_scorer
from tdfo_tpu.train.ctr import make_ctr_sparse_eval_step
from tdfo_tpu.train.sparse_step import SparseTrainState

SIZE_MAP = {"user": 1000, "item": 800, "language": 8, "is_ebook": 2,
            "format": 8, "publisher": 64, "pub_decade": 16}
CAT_COLS = ("user_id", "item_id", "language", "is_ebook", "format",
            "publisher", "pub_decade")
CONT_COLS = ("avg_rating", "num_pages")


def _ctr_batch(rng, n, with_label=True):
    batch = {
        "user_id": rng.integers(0, SIZE_MAP["user"], n).astype(np.int32),
        "item_id": rng.integers(0, SIZE_MAP["item"], n).astype(np.int32),
        "language": rng.integers(0, 8, n).astype(np.int32),
        "is_ebook": rng.integers(0, 2, n).astype(np.int32),
        "format": rng.integers(0, 8, n).astype(np.int32),
        "publisher": rng.integers(0, 64, n).astype(np.int32),
        "pub_decade": rng.integers(0, 16, n).astype(np.int32),
        "avg_rating": rng.random(n).astype(np.float32),
        "num_pages": rng.random(n).astype(np.float32),
    }
    if with_label:
        batch["label"] = rng.integers(0, 2, n).astype(np.float32)
    return batch


def _twotower_sparse(mesh, hot_ids=None, seed=0):
    """ShardedEmbeddingCollection + TwoTowerBackbone + SparseTrainState,
    mirroring the trainer's ``_build_ctr_sparse`` at toy scale."""
    specs = ctr_embedding_specs(SIZE_MAP, 16, sharding="row",
                                fused_threshold=None)
    coll = ShardedEmbeddingCollection(specs, mesh=mesh, hot_ids=hot_ids)
    backbone = TwoTowerBackbone(embed_dim=16)
    tables = coll.init(jax.random.key(seed))
    dummy_e = {f: jnp.zeros((1, 16), jnp.float32) for f in coll.features()}
    dummy_c = {c: jnp.zeros((1,), jnp.float32) for c in CONT_COLS}
    state = SparseTrainState.create(
        dense_params=backbone.init(jax.random.key(seed + 1),
                                   dummy_e, dummy_c)["params"],
        tx=optax.adamw(1e-3), tables=tables,
        sparse_opt=sparse_optimizer("adam", lr=1e-3, weight_decay=0.0),
    )
    return coll, backbone, state


def _export_sparse(out_dir, coll, state, **kw):
    return export_bundle(
        out_dir, model="twotower", embed_dim=16, cat_columns=CAT_COLS,
        cont_columns=CONT_COLS, size_map=SIZE_MAP, coll=coll,
        tables=state.tables, dense_params=state.dense_params, **kw)


# ------------------------------------------------------- train/serve skew


def test_sparse_bundle_scores_match_eval_step(mesh8, tmp_path):
    """The zero-skew bar: serving logits from a round-tripped bundle are
    BITWISE equal to the training eval step's logits."""
    coll, backbone, state = _twotower_sparse(mesh8)
    batch = _ctr_batch(np.random.default_rng(7), 64)
    _, ref = make_ctr_sparse_eval_step(coll, backbone)(state, batch)

    scorer = make_scorer(
        load_bundle(_export_sparse(tmp_path / "b", coll, state)), mesh=mesh8)
    got = scorer.score({k: v for k, v in batch.items() if k != "label"})
    assert np.asarray(got).dtype == np.float32
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))


def test_dlrm_bundle_scores_match_eval_step(mesh8, tmp_path):
    """Same zero-skew bar for the custom-schema DLRM regime (one table per
    categorical column, generic specs)."""
    from tdfo_tpu.models.dlrm import DLRMBackbone, generic_embedding_specs

    cats, conts = ("c0", "c1", "c2"), ("x0",)
    sizes = {"c0": 7, "c1": 50, "c2": 300}
    coll = ShardedEmbeddingCollection(
        generic_embedding_specs(sizes, cats, 8, "row", fused_threshold=None),
        mesh=mesh8)
    bb = DLRMBackbone(embed_dim=8, cat_columns=cats, cont_columns=conts)
    tables = coll.init(jax.random.key(0))
    dummy_e = {c: jnp.zeros((1, 8), jnp.float32) for c in cats}
    dummy_c = {c: jnp.zeros((1,), jnp.float32) for c in conts}
    state = SparseTrainState.create(
        dense_params=bb.init(jax.random.key(1), dummy_e, dummy_c)["params"],
        tx=optax.adam(1e-3), tables=tables,
        sparse_opt=sparse_optimizer("adam", lr=1e-3, weight_decay=0.0))
    rng = np.random.default_rng(3)
    batch = {c: rng.integers(0, sizes[c], 32).astype(np.int32) for c in cats}
    batch["x0"] = rng.random(32).astype(np.float32)
    batch["label"] = rng.integers(0, 2, 32).astype(np.float32)
    _, ref = make_ctr_sparse_eval_step(coll, bb)(state, batch)

    out = export_bundle(
        tmp_path / "b", model="dlrm", embed_dim=8, cat_columns=cats,
        cont_columns=conts, size_map=sizes, coll=coll, tables=state.tables,
        dense_params=state.dense_params)
    scorer = make_scorer(load_bundle(out), mesh=mesh8)
    got = scorer.score({k: v for k, v in batch.items() if k != "label"})
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))
    with pytest.raises(ValueError, match="no user tower"):
        scorer.user_embed(batch)


def test_dense_bundle_roundtrip(tmp_path):
    """Dense (replicated nn.Embed) regime: bundle scoring matches a direct
    model.apply bitwise; the tower methods factorize the dot."""
    sizes = {k: max(4, v // 10) for k, v in SIZE_MAP.items()}
    model = TwoTower(size_map=sizes, embed_dim=8)
    rng = np.random.default_rng(0)
    batch = {c: rng.integers(0, sizes[f], 16).astype(np.int32)
             for c, f in (("user_id", "user"), ("item_id", "item"),
                          ("language", "language"), ("is_ebook", "is_ebook"),
                          ("format", "format"), ("publisher", "publisher"),
                          ("pub_decade", "pub_decade"))}
    for c in CONT_COLS:
        batch[c] = rng.random(16).astype(np.float32)
    jb = {k: jnp.asarray(v) for k, v in batch.items()}
    params = model.init(jax.random.key(0), jb)["params"]
    ref = np.asarray(model.apply({"params": params}, jb))

    out = export_bundle(
        tmp_path / "b", model="twotower", embed_dim=8, cat_columns=CAT_COLS,
        cont_columns=CONT_COLS, size_map=sizes, params=params)
    bundle = load_bundle(out)
    assert bundle.kind == "dense" and bundle.dtype == "float32"
    scorer = make_scorer(bundle)
    np.testing.assert_array_equal(np.asarray(scorer.score(dict(batch))), ref)
    u = np.asarray(scorer.user_embed(dict(batch)))
    it = np.asarray(scorer.item_embed(dict(batch)))
    np.testing.assert_allclose(np.sum(u * it, axis=-1), ref, atol=1e-5)


def test_sparse_towers_factorize_score(mesh8, tmp_path):
    """user_embed . item_embed reproduces score() for the sparse regime —
    the property that makes corpus-based retrieval score-consistent."""
    coll, _, state = _twotower_sparse(mesh8)
    scorer = make_scorer(
        load_bundle(_export_sparse(tmp_path / "b", coll, state)), mesh=mesh8)
    batch = _ctr_batch(np.random.default_rng(11), 32, with_label=False)
    s = np.asarray(scorer.score(dict(batch)))
    u = np.asarray(scorer.user_embed(dict(batch)))
    it = np.asarray(scorer.item_embed(dict(batch)))
    np.testing.assert_allclose(np.sum(u * it, axis=-1), s, atol=1e-5)


# --------------------------------------------------- hot/cold agnosticism


def test_hot_split_bundle_matches_unsplit(mesh8, tmp_path):
    """Satellite bar: a bundle exported from a hot-split collection is
    byte-identical to the unsplit equivalent — the merge takes the LIVE
    ``{name}__hot`` rows, not the dead cold duplicates."""
    hot = {"item_embed": np.array([0, 3, 97, 512], np.int32),
           "user_embed": np.arange(16, dtype=np.int32)}
    coll_b, _, state_b = _twotower_sparse(mesh8, hot_ids=None)
    coll_h, _, state_h = _twotower_sparse(mesh8, hot_ids=hot)

    # Same-seed init starts bit-identical (hot heads gather cold rows), so
    # perturb the LIVE storage the way training would: new values into the
    # hot heads (split run) == same values into the cold rows (unsplit run),
    # and poison the split run's dead cold duplicates to prove the merge
    # never reads them.
    tables_h = dict(state_h.tables)
    tables_b = dict(state_b.tables)
    for tname, hids in hot.items():
        aname, spec, off = coll_h.resolve_table(tname)
        fresh = np.random.default_rng(len(hids)).normal(
            size=(len(hids), spec.embedding_dim)).astype(np.float32)
        tables_h[coll_h.hot_array_name(tname)] = jnp.asarray(fresh)
        cold = np.asarray(tables_h[aname]).copy()
        cold[off + hids] = 7777.0  # dead storage; must never be exported
        tables_h[aname] = jnp.asarray(cold)
        base = np.asarray(tables_b[aname]).copy()
        base[off + hids] = fresh
        tables_b[aname] = jnp.asarray(base)

    merged_h = merged_tables(coll_h, tables_h)
    merged_b = merged_tables(coll_b, tables_b)
    for name in merged_b:
        np.testing.assert_array_equal(merged_h[name], merged_b[name])
        assert not np.any(merged_h[name] == 7777.0)

    export = lambda d, coll, tables, state: export_bundle(
        d, model="twotower", embed_dim=16, cat_columns=CAT_COLS,
        cont_columns=CONT_COLS, size_map=SIZE_MAP, coll=coll, tables=tables,
        dense_params=state.dense_params)
    sc_h = make_scorer(load_bundle(
        export(tmp_path / "hot", coll_h, tables_h, state_h)), mesh=mesh8)
    sc_b = make_scorer(load_bundle(
        export(tmp_path / "base", coll_b, tables_b, state_b)), mesh=mesh8)
    batch = _ctr_batch(np.random.default_rng(5), 64, with_label=False)
    np.testing.assert_array_equal(np.asarray(sc_h.score(dict(batch))),
                                  np.asarray(sc_b.score(dict(batch))))


def test_merged_tables_inverts_fused_storage(mesh8):
    """merged_tables must invert the fat-line fused layout and table
    stacking too: the exported rows equal what lookup() serves."""
    from tdfo_tpu.models.dlrm import generic_embedding_specs

    sizes = {"big": 40000, "small": 60}  # big > fused_threshold -> fat lines
    coll = ShardedEmbeddingCollection(
        generic_embedding_specs(sizes, ("big", "small"), 16, "row",
                                fused_threshold=16384),
        mesh=mesh8, stack_tables=True)
    tables = coll.init(jax.random.key(2))
    merged = merged_tables(coll, tables)
    for col, size in sizes.items():
        assert merged[f"{col}_embed"].shape == (size, 16)
        ids = np.random.default_rng(1).integers(0, size, 64).astype(np.int32)
        looked = coll.lookup(tables, {col: jnp.asarray(ids)}, mode="gspmd")
        np.testing.assert_array_equal(merged[f"{col}_embed"][ids],
                                      np.asarray(looked[col]))


# ----------------------------------------------------------- bundle refusals


def test_bundle_refusals(mesh8, tmp_path):
    import json

    coll, _, state = _twotower_sparse(mesh8)
    out = _export_sparse(tmp_path / "b", coll, state)

    with pytest.raises(ValueError, match="not a serving bundle"):
        load_bundle(tmp_path / "nope")

    manifest = json.loads((out / "bundle.json").read_text())
    stale = dict(manifest, bundle_version=BUNDLE_VERSION + 1)
    (out / "bundle.json").write_text(json.dumps(stale))
    with pytest.raises(ValueError, match="bundle_version"):
        load_bundle(out)

    torn = dict(manifest)
    torn["tables"] = dict(manifest["tables"], ghost=[4, 16])
    (out / "bundle.json").write_text(json.dumps(torn))
    with pytest.raises(ValueError, match="torn bundle"):
        load_bundle(out)

    torn = dict(manifest)
    torn["tables"] = dict(manifest["tables"], item_embed=[3, 3])
    (out / "bundle.json").write_text(json.dumps(torn))
    with pytest.raises(ValueError, match="torn bundle"):
        load_bundle(out)

    (out / "bundle.json").write_text(json.dumps(dict(manifest, kind="ann")))
    with pytest.raises(ValueError, match="unknown kind"):
        load_bundle(out)

    # a valid bundle whose tables do not cover the model's schema (here a
    # 2-table DLRM bundle re-labelled as a 1-column config) is refused by
    # make_scorer, not served with a missing table
    from tdfo_tpu.models.dlrm import generic_embedding_specs

    sizes = {"c0": 5, "c1": 6}
    coll2 = ShardedEmbeddingCollection(generic_embedding_specs(
        sizes, ("c0", "c1"), 4, "replicated", fused_threshold=None))
    out2 = export_bundle(
        tmp_path / "d", model="dlrm", embed_dim=4, cat_columns=("c0", "c1"),
        cont_columns=("x0",), size_map=sizes, coll=coll2,
        tables=coll2.init(jax.random.key(0)),
        dense_params={"w": np.zeros((4,), np.float32)})
    m2 = json.loads((out2 / "bundle.json").read_text())
    (out2 / "bundle.json").write_text(json.dumps(
        dict(m2, cat_columns=["c0"])))
    with pytest.raises(ValueError, match="do not match"):
        make_scorer(load_bundle(out2))

    with pytest.raises(ValueError, match="not both"):
        export_bundle(tmp_path / "x", model="twotower", embed_dim=16,
                      cat_columns=CAT_COLS, cont_columns=CONT_COLS,
                      size_map=SIZE_MAP)


def test_bf16_export_policy(mesh8, tmp_path):
    """mixed_precision=True on a TPU platform casts every floating array to
    bf16 (stored as uint16 bit patterns) and the loader views them back."""
    coll, _, state = _twotower_sparse(mesh8)
    out = _export_sparse(tmp_path / "b", coll, state,
                         mixed_precision=True, platform="tpu")
    bundle = load_bundle(out)
    assert bundle.dtype == "bfloat16"
    assert all(t.dtype == jnp.bfloat16 for t in bundle.tables.values())
    ref = merged_tables(coll, state.tables)
    np.testing.assert_array_equal(
        np.asarray(bundle.tables["item_embed"], np.float32),
        np.asarray(ref["item_embed"].astype(jnp.bfloat16), np.float32))
    # the default policy keeps f32 (the zero-skew guarantee)
    f32 = load_bundle(_export_sparse(tmp_path / "f", coll, state))
    assert f32.dtype == "float32"


# ------------------------------------------------------------------ corpus


def test_corpus_build_chunked(mesh8, tmp_path):
    """Chunked sweep == one-shot sweep; uneven catalogs pad with id -1 rows
    up to a shard multiple and land sharded over the data axis."""
    from jax.sharding import PartitionSpec as P

    coll, _, state = _twotower_sparse(mesh8)
    scorer = make_scorer(
        load_bundle(_export_sparse(tmp_path / "b", coll, state)), mesh=mesh8)
    n_items = 333  # does not divide the 4-way data axis
    feats = synthetic_item_features(SIZE_MAP, n_items, seed=3)
    corpus = build_corpus(scorer, feats, corpus_batch=128, mesh=mesh8)
    assert corpus.n_items == n_items
    assert corpus.vectors.shape == (336, 16)  # padded to a multiple of 4
    assert corpus.vectors.sharding.spec == P("data", None)
    ids = np.asarray(corpus.ids)
    np.testing.assert_array_equal(ids[:n_items], np.arange(n_items))
    np.testing.assert_array_equal(ids[n_items:], [-1, -1, -1])
    np.testing.assert_array_equal(np.asarray(corpus.vectors)[n_items:], 0.0)

    oneshot = build_corpus(scorer, feats, corpus_batch=n_items, mesh=mesh8)
    np.testing.assert_allclose(np.asarray(corpus.vectors),
                               np.asarray(oneshot.vectors),
                               rtol=1e-6, atol=1e-7)

    with pytest.raises(ValueError, match="align"):
        build_corpus(scorer, dict(feats, language=feats["language"][:-1]))
    with pytest.raises(ValueError, match="missing columns"):
        build_corpus(scorer, {"item_id": np.arange(4, dtype=np.int32)})


# --------------------------------------------------------------- retrieval


def test_sharded_retrieval_bitwise(mesh8, tmp_path):
    """THE acceptance bar: sharded top-k returns bitwise the same ids AND
    f32 scores as the single-device stable-argsort reference, for k in
    {10, 100}, on a corpus that does not divide the 4-way data axis."""
    coll, _, state = _twotower_sparse(mesh8)
    scorer = make_scorer(
        load_bundle(_export_sparse(tmp_path / "b", coll, state)), mesh=mesh8)
    corpus = build_corpus(
        scorer, synthetic_item_features(SIZE_MAP, 333, seed=3),
        corpus_batch=128, mesh=mesh8)
    rng = np.random.default_rng(9)
    queries = scorer.user_embed(
        {"user_id": rng.integers(0, SIZE_MAP["user"], 16).astype(np.int32)})
    for k in (10, 100):
        s, i = make_retrieval(corpus, mesh=mesh8, top_k=k)(queries)
        s_ref, i_ref = retrieval_reference(queries, corpus, top_k=k)
        np.testing.assert_array_equal(np.asarray(i), np.asarray(i_ref))
        np.testing.assert_array_equal(np.asarray(s), np.asarray(s_ref))
        assert np.asarray(s).dtype == np.float32
        assert np.all(np.asarray(i) >= 0)  # padding rows never retrieved


def test_retrieval_ties_prefer_lower_id(mesh8):
    """Duplicate corpus vectors straddling shard boundaries: ties must
    resolve to the LOWER corpus id in both programs."""
    rng = np.random.default_rng(4)
    base = rng.normal(size=(5, 8)).astype(np.float32)
    vectors = jnp.asarray(np.tile(base, (8, 1)))  # 40 rows, every score x8
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tdfo_tpu.serve.corpus import Corpus
    c = Corpus(
        vectors=jax.device_put(vectors,
                               NamedSharding(mesh8, P("data", None))),
        ids=jax.device_put(jnp.arange(40, dtype=jnp.int32),
                           NamedSharding(mesh8, P("data"))),
        n_items=40)
    queries = jnp.asarray(rng.normal(size=(6, 8)).astype(np.float32))
    s, i = make_retrieval(c, mesh=mesh8, top_k=10)(queries)
    s_ref, i_ref = retrieval_reference(queries, c, top_k=10)
    np.testing.assert_array_equal(np.asarray(i), np.asarray(i_ref))
    np.testing.assert_array_equal(np.asarray(s), np.asarray(s_ref))
    # the winning duplicate of each clone group is its lowest id (< 5)
    assert np.all(np.asarray(i)[:, 0] < 5)


def test_retrieval_single_device_and_validation(mesh8, tmp_path):
    coll, _, state = _twotower_sparse(mesh8)
    scorer = make_scorer(
        load_bundle(_export_sparse(tmp_path / "b", coll, state)), mesh=mesh8)
    corpus = build_corpus(
        scorer, synthetic_item_features(SIZE_MAP, 50, seed=1),
        corpus_batch=64)  # no mesh: single-device layout
    queries = scorer.user_embed(
        {"user_id": np.arange(4, dtype=np.int32)})
    s, i = make_retrieval(corpus, top_k=10)(queries)
    s_ref, i_ref = retrieval_reference(queries, corpus, top_k=10)
    np.testing.assert_array_equal(np.asarray(i), np.asarray(i_ref))
    np.testing.assert_array_equal(np.asarray(s), np.asarray(s_ref))

    with pytest.raises(ValueError, match="top_k"):
        make_retrieval(corpus, top_k=0)
    with pytest.raises(ValueError, match="exceeds the corpus"):
        make_retrieval(corpus, top_k=51)


def test_mips_scores_formula():
    """The shared score formula: bf16 operands, f32 accumulation."""
    q = jnp.asarray(np.random.default_rng(0).normal(size=(3, 8)), jnp.float32)
    v = jnp.asarray(np.random.default_rng(1).normal(size=(5, 8)), jnp.float32)
    s = mips_scores(q, v)
    assert s.shape == (3, 5) and s.dtype == jnp.float32
    ref = np.asarray(q.astype(jnp.bfloat16), np.float32) @ \
        np.asarray(v.astype(jnp.bfloat16), np.float32).T
    np.testing.assert_allclose(np.asarray(s), ref, rtol=1e-2)


# -------------------------------------------- int8 corpora + two-stage


def _rand_corpus(mesh, n_items, dim=16, dtype="float32", seed=0):
    """Manually assembled corpus (no scorer sweep): padded to a shard
    multiple like ``build_corpus``, ids -1 on padding, quantized AFTER
    padding — the layout every retrieval program assumes."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tdfo_tpu.ops.quant import quantize_rows
    from tdfo_tpu.serve.corpus import Corpus

    rng = np.random.default_rng(seed)
    n_shards = mesh.shape["data"] if mesh is not None else 1
    pad = (-n_items) % n_shards
    vecs = np.zeros((n_items + pad, dim), np.float32)
    vecs[:n_items] = rng.normal(size=(n_items, dim)).astype(np.float32)
    ids = np.concatenate([np.arange(n_items, dtype=np.int32),
                          np.full(pad, -1, np.int32)])
    v, qs = jnp.asarray(vecs), None
    if dtype == "int8":
        v, qs = quantize_rows(v)
    elif dtype == "bfloat16":
        v = v.astype(jnp.bfloat16)
    i = jnp.asarray(ids)
    if mesh is not None:
        v = jax.device_put(v, NamedSharding(mesh, P("data", None)))
        i = jax.device_put(i, NamedSharding(mesh, P("data")))
        if qs is not None:
            qs = jax.device_put(qs, NamedSharding(mesh, P("data", None)))
    return Corpus(vectors=v, ids=i, n_items=n_items, qscale=qs)


def _recall(ids, ids_ref):
    a, b = np.asarray(ids), np.asarray(ids_ref)
    return sum(len(set(r) & set(rr)) for r, rr in zip(a, b)) / b.size


def test_int8_corpus_build_and_exact_retrieval(mesh8, tmp_path):
    """``build_corpus(dtype="int8")`` stores codes + [N_pad, 2] f32 sidecar
    sharded with the rows, and the EXACT program over it (dequantize
    in-shard, then the usual scan) is bitwise the reference — which itself
    scores the corpus as served (dequantized), not pre-quantization."""
    from jax.sharding import PartitionSpec as P

    coll, _, state = _twotower_sparse(mesh8)
    scorer = make_scorer(
        load_bundle(_export_sparse(tmp_path / "b", coll, state)), mesh=mesh8)
    feats = synthetic_item_features(SIZE_MAP, 333, seed=3)
    corpus = build_corpus(scorer, feats, corpus_batch=128, mesh=mesh8,
                          dtype="int8")
    assert corpus.vectors.dtype == jnp.int8
    assert corpus.qscale.shape == (336, 2)
    assert corpus.qscale.dtype == jnp.float32
    assert corpus.qscale.sharding.spec == P("data", None)

    rng = np.random.default_rng(9)
    queries = scorer.user_embed(
        {"user_id": rng.integers(0, SIZE_MAP["user"], 16).astype(np.int32)})
    s, i = make_retrieval(corpus, mesh=mesh8, top_k=10)(queries)
    s_ref, i_ref = retrieval_reference(queries, corpus, top_k=10)
    np.testing.assert_array_equal(np.asarray(i), np.asarray(i_ref))
    np.testing.assert_array_equal(np.asarray(s), np.asarray(s_ref))
    assert np.all(np.asarray(i) >= 0)

    # the quantized corpus still serves the same catalog: recall vs the
    # f32 corpus stays high (rowwise int8 at D=16 is a gentle grid)
    f32 = build_corpus(scorer, feats, corpus_batch=128, mesh=mesh8)
    _, i_f32 = retrieval_reference(queries, f32, top_k=10)
    assert _recall(i_ref, i_f32) >= 0.9

    with pytest.raises(ValueError, match="dtype"):
        build_corpus(scorer, feats, corpus_batch=128, dtype="int4")


def test_twostage_recall_floor_on_zipf_corpus(mesh8):
    """ISSUE acceptance: two-stage recall@10 >= 0.95 vs the exact
    reference at ``coarse_k = 4 * top_k`` on a zipf-queried synthetic
    corpus (popular items queried most, the serving skew)."""
    corpus = _rand_corpus(mesh8, 1234, dtype="int8", seed=11)
    rng = np.random.default_rng(12)
    pop = np.minimum(rng.zipf(1.5, size=32) - 1, 1233)
    base = np.asarray(jax.device_get(corpus.vectors), np.float32)[pop]
    queries = jnp.asarray(
        base + 0.3 * rng.normal(size=base.shape).astype(np.float32))
    s2, i2 = make_retrieval(
        corpus, mesh=mesh8, top_k=10, coarse_k=40)(queries)
    s_ref, i_ref = retrieval_reference(queries, corpus, top_k=10)
    assert _recall(i2, i_ref) >= 0.95
    assert np.all(np.asarray(i2) >= 0)
    del s2, s_ref  # bit-exactness of survivor scores asserted below


def test_twostage_rerank_scores_are_exact_bits(mesh8):
    """Every surviving (query, id) pair's score is bitwise the exact
    scan's score for that pair — the re-rank stage adds NO approximation
    on top of storage quantization."""
    from tdfo_tpu.ops.quant import dequantize_rows

    corpus = _rand_corpus(mesh8, 200, dtype="int8", seed=21)
    rng = np.random.default_rng(22)
    queries = jnp.asarray(rng.normal(size=(8, 16)).astype(np.float32))
    s2, i2 = make_retrieval(
        corpus, mesh=mesh8, top_k=10, coarse_k=40)(queries)
    vecs = dequantize_rows(
        jnp.asarray(jax.device_get(corpus.vectors))[:200],
        jnp.asarray(jax.device_get(corpus.qscale))[:200])
    full = np.asarray(mips_scores(queries, vecs))  # [B, N] exact bits
    got = np.asarray(s2).view(np.uint32)
    want = np.take_along_axis(full, np.asarray(i2), axis=1).view(np.uint32)
    np.testing.assert_array_equal(got, want)


def test_twostage_degenerate_routes_to_exact(mesh8):
    """``coarse_k >= n_items`` is statically the exact program: bitwise-
    equal ids AND scores (recall@k == 1.0 by construction)."""
    corpus = _rand_corpus(mesh8, 120, dtype="int8", seed=31)
    rng = np.random.default_rng(32)
    queries = jnp.asarray(rng.normal(size=(8, 16)).astype(np.float32))
    s_exact, i_exact = make_retrieval(corpus, mesh=mesh8, top_k=10)(queries)
    s_deg, i_deg = make_retrieval(
        corpus, mesh=mesh8, top_k=10, coarse_k=120)(queries)
    np.testing.assert_array_equal(np.asarray(i_deg), np.asarray(i_exact))
    np.testing.assert_array_equal(
        np.asarray(s_deg).view(np.uint32),
        np.asarray(s_exact).view(np.uint32))
    s_ref, i_ref = retrieval_reference(queries, corpus, top_k=10)
    assert _recall(i_deg, i_ref) == 1.0


def test_twostage_tiny_ragged_corpus_clamps_coarse_k(mesh8):
    """13 items over 4 shards (4 rows/shard after padding): ``coarse_k``
    clamps to the shard row count, padding ids (-1) never survive the
    coarse stage, and the output still matches the reference."""
    corpus = _rand_corpus(mesh8, 13, dtype="int8", seed=41)
    rng = np.random.default_rng(42)
    queries = jnp.asarray(rng.normal(size=(4, 16)).astype(np.float32))
    retrieve = make_retrieval(corpus, mesh=mesh8, top_k=5, coarse_k=12)
    s, i = retrieve(queries)
    ia = np.asarray(i)
    assert np.all(ia >= 0) and np.all(ia < 13)
    for row in ia:
        assert len(set(row.tolist())) == 5  # no duplicate survivors
    s_ref, i_ref = retrieval_reference(queries, corpus, top_k=5)
    np.testing.assert_array_equal(ia, np.asarray(i_ref))
    np.testing.assert_array_equal(
        np.asarray(s).view(np.uint32), np.asarray(s_ref).view(np.uint32))


def test_twostage_single_device_and_float_corpus(mesh8):
    """The meshless two-stage program and the f32-corpus two-stage program
    both reduce to the reference answer (coarse == exact scores when
    nothing is quantized)."""
    single = _rand_corpus(None, 100, dtype="int8", seed=51)
    rng = np.random.default_rng(52)
    queries = jnp.asarray(rng.normal(size=(4, 16)).astype(np.float32))
    s, i = make_retrieval(single, top_k=10, coarse_k=40)(queries)
    s_ref, i_ref = retrieval_reference(queries, single, top_k=10)
    assert _recall(i, i_ref) >= 0.95

    f32 = _rand_corpus(mesh8, 100, dtype="float32", seed=53)
    s, i = make_retrieval(f32, mesh=mesh8, top_k=10, coarse_k=100 - 1)(
        queries)
    s_ref, i_ref = retrieval_reference(queries, f32, top_k=10)
    np.testing.assert_array_equal(np.asarray(i), np.asarray(i_ref))
    np.testing.assert_array_equal(
        np.asarray(s).view(np.uint32), np.asarray(s_ref).view(np.uint32))


def test_twostage_validation(mesh8):
    corpus = _rand_corpus(mesh8, 50, dtype="int8", seed=61)
    with pytest.raises(ValueError, match="coarse_k"):
        make_retrieval(corpus, mesh=mesh8, top_k=10, coarse_k=-1)
    with pytest.raises(ValueError, match="coarse_k"):
        make_retrieval(corpus, mesh=mesh8, top_k=10, coarse_k=5)


def test_corpus_store_roundtrip_and_refusals(mesh8, tmp_path):
    """``export_corpus``/``load_corpus``: int8 corpora round-trip bitwise
    (codes, sidecar, ids) and refuse a future qscale re-grid or a store
    predating the stamp — the same refuse-on-mismatch discipline as
    training restores."""
    import json

    from tdfo_tpu.serve.export import bundle_digest, export_corpus, load_corpus

    corpus = _rand_corpus(mesh8, 333, dtype="int8", seed=71)
    cdir = tmp_path / "corpus"
    export_corpus(cdir, corpus, step=7)
    back = load_corpus(cdir, mesh=mesh8)
    assert back.vectors.dtype == jnp.int8 and back.n_items == 333
    np.testing.assert_array_equal(np.asarray(back.vectors),
                                  np.asarray(corpus.vectors))
    np.testing.assert_array_equal(
        np.asarray(back.qscale).view(np.uint32),
        np.asarray(corpus.qscale).view(np.uint32))
    np.testing.assert_array_equal(np.asarray(back.ids),
                                  np.asarray(corpus.ids))

    # a served answer from the reloaded corpus is bitwise the original's
    rng = np.random.default_rng(72)
    queries = jnp.asarray(rng.normal(size=(4, 16)).astype(np.float32))
    s0, i0 = make_retrieval(corpus, mesh=mesh8, top_k=10,
                            coarse_k=40)(queries)
    s1, i1 = make_retrieval(back, mesh=mesh8, top_k=10,
                            coarse_k=40)(queries)
    np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))
    np.testing.assert_array_equal(
        np.asarray(s0).view(np.uint32), np.asarray(s1).view(np.uint32))

    manifest = cdir / "corpus.json"
    good = json.loads(manifest.read_text())
    with np.load(cdir / "corpus.npz") as z:
        arrays = {k: z[k] for k in z.files}

    def _restamp(m):  # a legitimately-stamped store from another build
        return dict(m, digest=bundle_digest(m, arrays))

    bad = _restamp(dict(good, qscale_layout="rowwise-f32-scale-offset-v2"))
    manifest.write_text(json.dumps(bad))
    with pytest.raises(ValueError, match="qscale_layout"):
        load_corpus(cdir, mesh=mesh8)
    bad = _restamp({k: v for k, v in good.items() if k != "qscale_layout"})
    manifest.write_text(json.dumps(bad))
    with pytest.raises(ValueError, match="qscale"):
        load_corpus(cdir, mesh=mesh8)
    # a plainly corrupted store (manifest edited, digest stale) also refuses
    manifest.write_text(json.dumps(dict(good, step=99)))
    with pytest.raises(ValueError, match="digest"):
        load_corpus(cdir, mesh=mesh8)
    manifest.write_text(json.dumps(good))

    # float corpora round-trip too (no sidecar on disk, none tolerated)
    f32 = _rand_corpus(mesh8, 50, dtype="float32", seed=73)
    export_corpus(tmp_path / "f32", f32)
    back32 = load_corpus(tmp_path / "f32", mesh=mesh8)
    assert back32.qscale is None
    np.testing.assert_array_equal(np.asarray(back32.vectors),
                                  np.asarray(f32.vectors))
