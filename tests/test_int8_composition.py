"""int8 storage composed with the fused fat-line and hot/cold layouts.

PR 18 lifts the int8 refusal matrix: quantized storage is a per-table
DTYPE decision orthogonal to the LAYOUT decision (plain 2D, fused
byte-container fat line, hot/cold split, cache-fronted).  The contracts
under test here:

- fused int8 is the SAME trajectory as plain int8, bit for bit: the fat
  line stores ``dim`` code bytes + the bitcast f32 (scale, offset)
  sidecar + the f32 optimizer state as bytes, and the update decodes to
  the identical [U, d] f32 blocks, runs the identical sparse_* math with
  the identical ``sr_key(step, table)``, and requantizes through the
  identical ``ops/quant.quantize_rows`` call — so nothing observable can
  differ from the plain path (tests run the step eagerly: op-for-op the
  fat math IS the plain math, which eager execution preserves exactly).
- hot/cold composes: the hot head stays f32 with the scatter-free
  one-hot MXU update, ONLY the cold residual stores int8 — the split is
  a layout detail invisible to loss tracking, rerun determinism, and the
  kill/resume identity.
- rowwise_adagrad x fused-int8 stays refused at every layer (the shared
  scalar accumulator has no byte-container home): ``line_layout``,
  ``plan/costs.line_geometry``, and the config loader all raise.

int8 x update-cache parity lives in tests/test_update_cache.py (the
cache harness already parametrizes storage dtype); planner pricing of
the new cross products lives in tests/test_planner.py.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from tdfo_tpu.ops.sparse import sparse_optimizer
from tdfo_tpu.parallel.embedding import (
    EmbeddingSpec,
    ShardedEmbeddingCollection,
    qscale_name,
)
from tdfo_tpu.train.sparse_step import SparseTrainState, make_sparse_train_step

V, D, B = 300, 16, 64
N_STEPS = 5


def _coll(mesh, *, fused=False, hot=None, sharding="replicated",
          dtype=jnp.int8, kind="adam"):
    spec = EmbeddingSpec("item", V, D, features=("item",), sharding=sharding,
                         init_scale=0.1, dtype=dtype, fused=fused)
    return ShardedEmbeddingCollection(
        [spec], mesh=mesh, fused_kind=kind, hot_ids=hot)


def _forward(dense, embs, batch):
    logits = embs["item"] @ dense["w"]
    return optax.sigmoid_binary_cross_entropy(logits, batch["label"]).mean()


def _batches(n, seed=3):
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n):
        ids = rng.integers(0, V, B)
        out.append({"item": jnp.asarray(ids, jnp.int32),
                    "label": jnp.asarray((ids < 100).astype(np.float32))})
    return out


def _run(mesh, kind, *, fused=False, hot=None, sharding="replicated",
         n=N_STEPS, batches=None):
    """Train n eager steps; return (loss bit patterns, state, coll)."""
    coll = _coll(mesh, fused=fused, hot=hot, sharding=sharding, kind=kind)
    # threshold below V so plain int8 exercises the row-sparse tier the
    # fat path mirrors (int8 never takes the dense one-hot tier anyway —
    # SR requantize is not identity — but pinning the knob keeps the two
    # programs comparable by construction)
    state = SparseTrainState.create(
        dense_params={"w": jnp.full((D,), 0.3)},
        tx=optax.adam(1e-2),
        tables=coll.init(jax.random.PRNGKey(0)),
        sparse_opt=sparse_optimizer(kind, lr=0.5,
                                    small_vocab_threshold=100),
    )
    step = make_sparse_train_step(
        coll, _forward, mode="gspmd" if hot else "alltoall",
        donate=False, jit=False)
    losses = []
    for b in batches or _batches(n):
        state, loss = step(state, b)
        losses.append(
            np.asarray(loss).astype(np.float32).view(np.uint32).item())
    return losses, state, coll


def _all_rows(coll, tables):
    """Dequantized f32 rows of the whole vocab — the storage-independent
    observable (codes + scales fold in; layout does not)."""
    ids = jnp.arange(V, dtype=jnp.int32)
    return np.asarray(coll.lookup(tables, {"item": ids})["item"])


# ------------------------------------------------- fused x int8 parity


@pytest.mark.parametrize("kind", ["sgd", "adagrad", "adam"])
def test_fused_int8_matches_plain_trajectory(mesh8, kind):
    """The tentpole bar: N full train steps on the fused int8 byte
    container reproduce the plain-int8 run bit for bit — losses and every
    dequantized row — for every fat-line-capable optimizer kind."""
    lp, sp, cp = _run(mesh8, kind, fused=False)
    lf, sf, cf = _run(mesh8, kind, fused=True)
    assert lp == lf, kind
    np.testing.assert_array_equal(
        _all_rows(cp, sp.tables).view(np.uint32),
        _all_rows(cf, sf.tables).view(np.uint32), err_msg=kind)
    # the layouts really are different: plain carries a separate qscale
    # sidecar array, fused packs it into the byte container
    assert qscale_name("item") in sp.tables
    assert qscale_name("item") not in sf.tables
    assert sf.tables["item"].dtype == jnp.int8
    assert sf.tables["item"].ndim == 3  # [lines, tiles, 128] byte container


@pytest.mark.slow
def test_fused_int8_row_sharded_matches_replicated(mesh8):
    """Row-sharded fused int8 runs the shard_map fat program (Pallas has
    no GSPMD rule).  Sharding changes the dedupe/segment program, so the
    SR draws may land one code apart — the contract is tracking within
    quantization noise plus exact same-program rerun determinism."""
    lr_, sr_, cr_ = _run(mesh8, "adam", fused=True, sharding="replicated")
    ls_, ss_, cs_ = _run(mesh8, "adam", fused=True, sharding="row")
    f = lambda bits: np.asarray(bits, np.uint32).view(np.float32)
    np.testing.assert_allclose(f(ls_), f(lr_), rtol=1e-4)
    np.testing.assert_allclose(_all_rows(cs_, ss_.tables),
                               _all_rows(cr_, sr_.tables),
                               rtol=0, atol=0.05)
    ls2, ss2, _ = _run(mesh8, "adam", fused=True, sharding="row")
    assert ls_ == ls2
    np.testing.assert_array_equal(np.asarray(ss_.tables["item"]),
                                  np.asarray(ss2.tables["item"]))


def test_fused_int8_sr_keys_and_resume(mesh8):
    """SR keys fold from (state.step, table) only, fused exactly like
    plain: a rerun is bitwise identical and a kill/resume after step 2
    (host round trip + a rebuilt step fn) replays into the same bits."""
    bs = _batches(4)
    la, sa, ca = _run(mesh8, "adam", fused=True, batches=bs)
    lb, sb, _ = _run(mesh8, "adam", fused=True, batches=bs)
    assert la == lb
    np.testing.assert_array_equal(np.asarray(sa.tables["item"]),
                                  np.asarray(sb.tables["item"]))
    # interrupted run
    lh, sh, ch = _run(mesh8, "adam", fused=True, batches=bs[:2])
    half = jax.tree_util.tree_map(lambda x: jnp.asarray(np.asarray(x)), sh)
    step2 = make_sparse_train_step(ch, _forward, mode="alltoall",
                                   donate=False, jit=False)
    for b in bs[2:]:
        half, loss = step2(half, b)
        lh.append(np.asarray(loss).astype(np.float32).view(np.uint32).item())
    assert lh == la
    np.testing.assert_array_equal(np.asarray(sa.tables["item"]),
                                  np.asarray(half.tables["item"]))


# --------------------------------------------------- hot/cold x int8


def test_hot_cold_int8_splits_storage_and_trains(mesh8):
    """The hot head is f32 (dense one-hot RMW needs exact identity
    writes; int8 SR requantize has none), the cold residual stores int8
    codes + sidecar, lookups route both tiers, training moves both, and
    the run is rerun-deterministic."""
    hot = {"item": np.sort(np.random.default_rng(5).choice(
        V, size=24, replace=False)).astype(np.int32)}
    l0, s0, c0 = _run(mesh8, "adam", hot=hot)
    hot_name = c0.hot_array_name("item")
    assert s0.tables[hot_name].dtype == jnp.float32
    assert s0.tables["item"].dtype == jnp.int8
    assert qscale_name("item") in s0.tables
    # both tiers actually learned (moved off their init)
    init = c0.init(jax.random.PRNGKey(0))
    assert (np.asarray(s0.tables[hot_name])
            != np.asarray(init[hot_name])).any()
    assert (np.asarray(s0.tables["item"])
            != np.asarray(init["item"])).any()
    # loss tracks the int8-without-hot run (same data, same lr): hot/cold
    # is a layout split, not a different model
    lp, _, _ = _run(mesh8, "adam")
    f = lambda bits: np.asarray(bits, np.uint32).view(np.float32)
    assert abs(f(l0)[-1] - f(lp)[-1]) < 0.1, (f(l0), f(lp))
    assert f(l0)[-1] < f(l0)[0]
    # rerun determinism (hot head SR-free, cold tier same-keyed)
    l1, s1, _ = _run(mesh8, "adam", hot=hot)
    assert l0 == l1
    for a in s0.tables:
        np.testing.assert_array_equal(np.asarray(s0.tables[a]),
                                      np.asarray(s1.tables[a]), err_msg=a)


# ------------------------------------------------- retained refusals


def test_fused_int8_rowwise_adagrad_refused_at_kernel_layer():
    from tdfo_tpu.ops.pallas_kernels import line_layout

    with pytest.raises(ValueError, match="rowwise_adagrad"):
        line_layout(D, "rowwise_adagrad", dtype="int8")
