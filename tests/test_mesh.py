import jax
import numpy as np
import pytest

from tdfo_tpu.core.config import MeshSpec
from tdfo_tpu.core.mesh import make_mesh


def test_eight_devices_spoofed():
    assert jax.device_count() == 8


def test_wildcard_axis():
    mesh = make_mesh(MeshSpec(data=-1, model=2))
    assert mesh.shape == {"data": 4, "model": 2, "seq": 1}


def test_full_dp():
    mesh = make_mesh(MeshSpec(data=-1))
    assert mesh.shape["data"] == 8


def test_bad_sizes():
    with pytest.raises(ValueError):
        make_mesh(MeshSpec(data=3, model=2))
    with pytest.raises(ValueError):
        make_mesh(MeshSpec(data=-1, model=-1))


def test_sharded_array_placement(mesh8):
    from jax.sharding import NamedSharding, PartitionSpec as P

    x = jax.device_put(np.arange(16.0).reshape(8, 2), NamedSharding(mesh8, P("data", None)))
    assert len(x.addressable_shards) == 8
    assert x.addressable_shards[0].data.shape == (2, 2)
