"""Cost-model-driven auto-sharding planner tests (``tdfo_tpu/plan``).

The calibration contract is the load-bearing piece: ``estimate_step_ms``
must reproduce BOTH docs/BUDGET.md in-situ step budgets — DLRM-Criteo
plain 22.4 ms vs fused 29-32 ms, TwoTower fused 1.40 ms vs plain ~2.8 ms
— with the correct plain-vs-fused ORDERING on each profile, because that
ordering is exactly the decision the planner exists to make.  On top of
that: the stats artifact round trip (preprocessing -> table_stats.json ->
planner), plan determinism/byte-identity, the HBM budget repair, the
telemetry-refinement round trip, and the trainer-level wiring (plan ->
actual spec/array placement, trajectory equivalence with hand-set knobs,
checkpoint plan-digest refusal).
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tdfo_tpu.plan.costs import (
    TableLoad,
    cache_hbm_bytes,
    estimate_step_ms,
    expected_lines,
    in_situ_multiplier,
    line_geometry,
    padded_lane_width,
    table_hbm_bytes,
)
from tdfo_tpu.plan.planner import (
    CACHE_FLUSH_EVERY,
    FUSED_MIN_VOCAB,
    apply_plan_to_specs,
    format_plan,
    load_plan,
    plan_digest,
    plan_tables,
    write_plan,
)
from tdfo_tpu.plan.stats import (
    _expected_unique,
    head_ids_for,
    head_mass_at,
    load_table_stats,
    refine_stats_from_metrics,
    table_stats_digest,
    table_stats_from_counts,
    unique_rows_at,
    unique_rows_over,
    write_table_stats,
)

# ---------------------------------------------------- calibration profiles
#
# Pinned to the docs/BUDGET.md chip facts (bench.py CRITEO_KAGGLE_VOCABS +
# the measured per-step touch counts): 26 tables, 213k ids/step deduping to
# ~102k touched rows / ~77k touched fat lines at B=8192.  Uniques are the
# per-table occupancy expectations rescaled to land the MEASURED totals —
# the budget numbers are chip-observed truth, so the profile pins them
# rather than trusting the analytic estimate end to end.

CRITEO_VOCABS = (
    1460, 583, 10131227, 2202608, 305, 24, 12517, 633, 3, 93145, 5683,
    8351593, 3194, 27, 14992, 5461306, 10, 5652, 2173, 4, 7046547, 18, 15,
    286181, 105, 142572,
)
CRITEO_TOUCHED_ROWS = 102_000
CRITEO_TOUCHED_LINES = 77_000

# TwoTower bench profile (docs/BUDGET.md TwoTower table): ~8k touched rows
# across the 7 tables at B=8192 under the power-law goodreads traffic.
TWOTOWER_PROFILE = {
    "user": (1_600_000, 4000.0),
    "item": (760_000, 3500.0),
    "language": (32, 32.0),
    "is_ebook": (2, 2.0),
    "format": (16, 16.0),
    "publisher": (5000, 630.0),
    "pub_decade": (16, 16.0),
}


def _criteo_loads(fused: bool) -> list[TableLoad]:
    raw = [_expected_unique(v, 8192) for v in CRITEO_VOCABS]
    scale = CRITEO_TOUCHED_ROWS / sum(raw)
    uniq = [u * scale for u in raw]
    lines = None
    if fused:
        _, rpl = line_geometry(16, "rowwise_adagrad", "float32")
        lraw = [expected_lines(u, v, rpl)
                for u, v in zip(uniq, CRITEO_VOCABS)]
        lscale = CRITEO_TOUCHED_LINES / sum(lraw)
        lines = [l * lscale for l in lraw]
    return [
        TableLoad(name=f"cat_{i}", vocab=v, dim=16, ids_per_batch=8192.0,
                  unique_rows=u, fused=fused,
                  unique_lines=lines[i] if fused else None)
        for i, (v, u) in enumerate(zip(CRITEO_VOCABS, uniq))
    ]


def _twotower_loads(fused: bool) -> list[TableLoad]:
    return [
        TableLoad(name=n, vocab=v, dim=64, ids_per_batch=8192.0,
                  unique_rows=u, fused=fused,
                  # d=64 adam packs 1 row/line: touched lines == rows
                  unique_lines=u if fused else None)
        for n, (v, u) in TWOTOWER_PROFILE.items()
    ]


def test_calibration_reproduces_budget_anchors():
    """The planner's license to operate: the estimator lands both measured
    step budgets within 30% AND orders plain-vs-fused correctly on both
    profiles (Criteo prefers plain, TwoTower prefers fused)."""
    crit_plain = estimate_step_ms(
        _criteo_loads(False), optimizer="rowwise_adagrad",
        dense_model="dlrm", batch_size=8192)
    crit_fused = estimate_step_ms(
        _criteo_loads(True), optimizer="rowwise_adagrad",
        dense_model="dlrm", batch_size=8192)
    assert abs(crit_plain["total_ms"] - 22.4) / 22.4 < 0.30, crit_plain
    assert abs(crit_fused["total_ms"] - 30.5) / 30.5 < 0.30, crit_fused
    assert crit_plain["total_ms"] < crit_fused["total_ms"]

    tt_fused = estimate_step_ms(
        _twotower_loads(True), optimizer="adam", dense_model="twotower",
        batch_size=8192)
    tt_plain = estimate_step_ms(
        _twotower_loads(False), optimizer="adam", dense_model="twotower",
        batch_size=8192)
    assert abs(tt_fused["total_ms"] - 1.40) / 1.40 < 0.30, tt_fused
    assert abs(tt_plain["total_ms"] - 2.8) / 2.8 < 0.30, tt_plain
    assert tt_fused["total_ms"] < tt_plain["total_ms"]

    # the Criteo step runs deep in the in-situ regime, TwoTower does not —
    # the contention ramp is what separates the two orderings
    assert crit_plain["in_situ_multiplier"] == 3.0
    assert tt_fused["in_situ_multiplier"] == 1.0


def test_cost_model_geometry():
    # d=16 rowwise-adagrad f32: 17 elems -> 32-wide row, 4 rows per line
    assert line_geometry(16, "rowwise_adagrad", "float32") == (32, 4)
    # d=64 adam f32: 192 elems -> 256-wide row, one row per (2-line) row
    assert line_geometry(64, "adam", "float32") == (256, 1)
    # occupancy: saturated small tables compress ~R-fold, and the
    # single-line guard never divides by zero
    assert expected_lines(0.0, 100, 4) == 0.0
    assert expected_lines(5.0, 3, 4) == 1.0
    assert 24.0 < expected_lines(100.0, 100, 4) <= 25.0
    # ramp endpoints
    assert in_situ_multiplier(1000) == 1.0
    assert in_situ_multiplier(1 << 20) == 3.0
    # XLA lane padding: [V, 64] allocates 128 lanes (2x), narrow dims do not
    assert padded_lane_width(64) == 128 and padded_lane_width(16) == 16
    v = 1000
    assert table_hbm_bytes(v, 64, optimizer="sgd") == v * 128 * 4
    assert table_hbm_bytes(v, 64, optimizer="sgd", dtype="bfloat16") \
        == v * 128 * 2
    # rowwise-adagrad plain: padded table + the f32 [V] accumulator
    assert table_hbm_bytes(v, 16, optimizer="rowwise_adagrad") \
        == v * 16 * 4 + v * 4


# ------------------------------------------------------- stats artifact


def test_table_stats_from_counts_basic():
    counts = np.array([40, 0, 10, 10, 40], np.int64)
    e = table_stats_from_counts(counts)
    assert e["vocab"] == 5 and e["total_count"] == 100.0
    # occupancy expectation is monotone in B and bounded by the support
    us = [e["unique_per_batch"][str(b)] for b in (1024, 8192, 32768)]
    assert us[0] <= us[1] <= us[2] <= 4.0 + 1e-9  # id 1 never appears
    # head ranking: stable ties toward lower ids -> 0, 4, 2, 3 (1 is last)
    assert e["head_ids"][:4] == [0, 4, 2, 3]
    assert head_mass_at(e, 5) == 1.0
    assert head_ids_for(e, 2) == [0, 4]
    with pytest.raises(ValueError, match="head"):
        head_ids_for({"vocab": 10, "head_ids": [1]}, 5)


def test_stats_roundtrip_digest_and_corruption(tmp_path):
    per = {"a": np.array([5, 1, 1], np.int64), "b": np.ones(64, np.int64)}
    write_table_stats(tmp_path, per)
    loaded = load_table_stats(tmp_path)
    assert set(loaded) == {"a", "b"}
    assert loaded["a"]["vocab"] == 3
    # digest: stable across a round trip, sensitive to the counts
    d1 = table_stats_digest(loaded)
    write_table_stats(tmp_path, per)
    assert table_stats_digest(load_table_stats(tmp_path)) == d1
    per2 = dict(per, b=np.arange(64, dtype=np.int64))
    write_table_stats(tmp_path, per2)
    assert table_stats_digest(load_table_stats(tmp_path)) != d1
    # absent and corrupt artifacts
    assert load_table_stats(tmp_path / "nope") is None
    p = tmp_path / "table_stats.json"
    payload = json.loads(p.read_text())
    payload["format_version"] = 99
    p.write_text(json.dumps(payload))
    with pytest.raises(ValueError, match="format_version"):
        load_table_stats(tmp_path)
    payload["format_version"] = 1
    payload["tables"]["a"]["head_ids"] = [0, 99]
    p.write_text(json.dumps(payload))
    with pytest.raises(ValueError, match="head_ids"):
        load_table_stats(tmp_path)


def test_unique_rows_interpolation_and_observed_priority():
    e = table_stats_from_counts(np.ones(100_000, np.int64))
    u4k = unique_rows_at(e, 4096)
    u6k = unique_rows_at(e, 6144)
    u8k = unique_rows_at(e, 8192)
    assert u4k < u6k < u8k <= 8192.0
    assert abs(u6k - (u4k + u8k) / 2) < 1e-6  # linear between grid points
    # a telemetry-observed mean at the SAME batch size wins outright
    e2 = dict(e, observed={"batch": 6144, "unique_rows": 1234.0})
    assert unique_rows_at(e2, 6144) == 1234.0
    assert unique_rows_at(e2, 8192) == u8k  # other batch sizes fall back


def test_criteo_preprocessing_emits_stats(tmp_path):
    """The ETL emits table_stats.json unconditionally, its head ranking is
    consistent with the hot/cold artifact (same stable ordering), and the
    occupancy estimates are sane."""
    from tdfo_tpu.data.criteo_preprocessing import (
        CRITEO_CATEGORICAL,
        run_criteo_preprocessing,
    )
    from tdfo_tpu.data.hot_ids import load_hot_ids
    from tdfo_tpu.data.synthetic import write_synthetic_criteo

    write_synthetic_criteo(tmp_path, n_rows=600, seed=0)
    size_map = run_criteo_preprocessing(tmp_path, hot_vocab=8,
                                        hot_fraction=0.8, min_freq=2)
    stats = load_table_stats(tmp_path)
    assert stats is not None and set(stats) == set(CRITEO_CATEGORICAL)
    hot = load_hot_ids(tmp_path)
    for c in CRITEO_CATEGORICAL:
        e = stats[c]
        assert e["vocab"] == size_map[c]
        assert e["total_count"] == 600.0  # one lookup per row per column
        u = unique_rows_at(e, 8192)
        assert 0 < u <= size_map[c]
        # hot/cold sets are prefixes of the SAME frequency ranking
        k = len(hot[c])
        np.testing.assert_array_equal(hot[c], head_ids_for(e, k))


def test_ctr_preprocessing_emits_stats(tmp_path):
    from tdfo_tpu.data.ctr_preprocessing import run_ctr_preprocessing
    from tdfo_tpu.data.synthetic import write_synthetic_goodreads

    write_synthetic_goodreads(tmp_path, n_users=60, n_books=90,
                              interactions_per_user=(10, 20), seed=3)
    size_map = run_ctr_preprocessing(tmp_path)
    stats = load_table_stats(tmp_path)
    assert set(stats) == {"user_id", "item_id", "language", "is_ebook",
                          "format", "publisher", "pub_decade"}
    assert stats["user_id"]["vocab"] == size_map["user"]
    assert stats["item_id"]["vocab"] == size_map["item"]
    # category traffic is the item traffic folded through book features:
    # same total lookup mass as the item table (train-split pairs)
    assert sum(stats[c]["total_count"] for c in ("language",)) > 0
    for c in ("language", "is_ebook", "format", "publisher", "pub_decade"):
        assert stats[c]["total_count"] == stats["item_id"]["total_count"]


# ------------------------------------------------------------- planner


def _uniform_stats(vocabs: dict[str, int]) -> dict:
    return {n: table_stats_from_counts(np.ones(v, np.int64))
            for n, v in vocabs.items()}


@pytest.fixture(scope="module")
def criteo_stats():
    return _uniform_stats(
        {f"cat_{i}": v for i, v in enumerate(CRITEO_VOCABS)})


def _criteo_plan(criteo_stats, **kw):
    kw.setdefault("dim", 16)
    kw.setdefault("batch_size", 8192)
    kw.setdefault("optimizer", "rowwise_adagrad")
    kw.setdefault("dense_model", "dlrm")
    return plan_tables(criteo_stats, **kw)


def test_planner_keeps_criteo_big_tables_plain(criteo_stats):
    """The BUDGET.md headline decision: at the Criteo profile every
    fused-eligible table stays on the plain-scatter path, and the plan
    beats the all-defaults (fused) baseline it reports."""
    plan = _criteo_plan(criteo_stats)
    big = {n: e for n, e in plan["tables"].items()
           if e["vocab"] > FUSED_MIN_VOCAB}
    assert len(big) == 8
    assert all(not e["fused"] for e in big.values()), big
    assert all(e["sharding"] == "row" for e in big.values())
    assert plan["predicted_step_ms"] < plan["predicted_default_ms"]
    # small tables ride the one-hot MXU tier (fully hot) — the hot/cold
    # subsystem's measured sweet spot, never fat-packed
    small = {n: e for n, e in plan["tables"].items()
             if e["vocab"] <= FUSED_MIN_VOCAB}
    assert all(not e["fused"] for e in small.values())


def test_planner_prefers_fused_on_twotower_profile():
    """The other half of the ordering: d=64 adam tables at ~8k touches
    choose the fused fat-line path (the 1.40 vs 2.8 ms measurement)."""
    stats = _uniform_stats({n: v for n, (v, _) in TWOTOWER_PROFILE.items()})
    plan = plan_tables(stats, dim=64, batch_size=8192, optimizer="adam",
                       dense_model="twotower")
    assert plan["tables"]["user"]["fused"]
    assert plan["tables"]["item"]["fused"]


def test_plan_deterministic_and_stamped(tmp_path, criteo_stats):
    plan1 = _criteo_plan(criteo_stats)
    plan2 = _criteo_plan(criteo_stats)
    assert plan1 == plan2
    p1 = write_plan(tmp_path / "a.json", plan1)
    p2 = write_plan(tmp_path / "b.json", plan2)
    assert p1.read_bytes() == p2.read_bytes()  # byte-identical artifact
    assert plan_digest(plan1) == plan_digest(load_plan(p1))
    assert plan1["stats_digest"] == table_stats_digest(criteo_stats)
    # a different traffic profile flips the digest
    other = _criteo_plan(criteo_stats, batch_size=16384)
    assert plan_digest(other) != plan_digest(plan1)
    # the human summary carries the decisions and the digest
    text = format_plan(plan1)
    assert "cat_2" in text and plan_digest(plan1) in text


def test_planner_hbm_budget_demotes_and_refuses(criteo_stats):
    free = _criteo_plan(criteo_stats, n_devices=8)
    budget = _criteo_plan(criteo_stats, n_devices=8, hbm_gb=2.0)
    assert free["max_device_hbm_bytes"] > 0
    assert budget["max_device_hbm_bytes"] <= 2.0 * (1 << 30)
    # demotion may not break plan validity
    for e in budget["tables"].values():
        assert e["sharding"] in ("row", "replicated", "table")
    with pytest.raises(ValueError, match="cannot fit"):
        _criteo_plan(criteo_stats, n_devices=8, hbm_gb=0.001)


def test_planner_demotes_to_int8_under_tight_budget(criteo_stats):
    """A budget bf16 cannot satisfy pushes big tables onto int8 storage
    (the 3.76x d=64 / 2.67x d=16 HBM lever) and the summary reports the
    per-device HBM saved vs all-defaults.  int8 now composes with the
    fused and hot/cold layouts, but on THIS profile neither wins: the
    Criteo optimizer is rowwise_adagrad (fused int8 is a retained
    refusal — no per-row second moment to byte-pack), and uniform
    traffic has no head for hot/cold and no reuse for the update cache,
    so the tight-budget plan stays plain int8 with cache_rows 0."""
    plan = _criteo_plan(criteo_stats, n_devices=8, hbm_gb=0.25)
    int8 = {n: e for n, e in plan["tables"].items()
            if e["dtype"] == "int8"}
    assert int8, plan["tables"]
    assert plan["max_device_hbm_bytes"] <= 0.25 * (1 << 30)
    assert plan["max_device_hbm_bytes"] \
        < plan["default_max_device_hbm_bytes"]
    for n, e in int8.items():
        # rowwise_adagrad keeps the fused-int8 refusal everywhere
        assert not e["fused"], n
        # uniform traffic never justifies a PARTIAL hot head on a big
        # demoted table; small tables may keep their fully-hot MXU tier
        # while demoting — that composition is exactly what this PR lifts
        if e["vocab"] > FUSED_MIN_VOCAB:
            assert e["hot_k"] == 0, n
        elif e["hot_k"]:
            assert e["hot_k"] == e["vocab"], n
    assert plan["cache_rows"] == 0  # no reuse -> cache cannot win
    assert plan["cache_flush_every"] == 0
    text = format_plan(plan)
    assert "per-device HBM" in text and "int8" in text


@pytest.fixture(scope="module")
def criteo_zipf_stats():
    """Zipf(1.2) traffic over the Criteo vocabs: heavy reuse inside a
    flush interval, the regime the update cache was measured in
    (docs/BUDGET.md cache_zipf brackets)."""
    stats = {}
    for i, v in enumerate(CRITEO_VOCABS):
        p = np.arange(1, v + 1, dtype=np.float64) ** -1.2
        counts = np.floor(p / p.sum() * 10_000_000).astype(np.int64)
        counts[0] += 10_000_000 - counts.sum()
        stats[f"cat_{i}"] = table_stats_from_counts(counts)
    return stats


def test_planner_zipf_tight_budget_selects_int8_cache(criteo_zipf_stats):
    """The lifted composition actually gets SELECTED: under the same
    tight budget but zipf traffic (interval working set << touched rows
    x flush_every), the plan demotes to int8 AND fronts the plain-int8
    storage with the update cache, pricing the flush from the stats
    occupancy curve.  Deterministic and digest-stamped like every plan."""
    kw = dict(dim=16, batch_size=8192, optimizer="rowwise_adagrad",
              dense_model="dlrm", n_devices=8, hbm_gb=0.25)
    plan = plan_tables(criteo_zipf_stats, **kw)
    int8 = {n: e for n, e in plan["tables"].items()
            if e["dtype"] == "int8"}
    assert int8, plan["tables"]
    # the acceptance composition: at least one int8+fused table or a
    # cache-fronted int8 plan (rowwise_adagrad refuses fused int8, so
    # here it must be the cache)
    assert any(e["fused"] for e in int8.values()) \
        or plan["cache_rows"] > 0
    assert plan["cache_rows"] > 0
    assert plan["cache_flush_every"] == CACHE_FLUSH_EVERY
    # cache HBM is accounted inside the budget, not snuck past it
    assert plan["max_device_hbm_bytes"] <= 0.25 * (1 << 30)
    plan2 = plan_tables(criteo_zipf_stats, **kw)
    assert plan == plan2
    assert plan_digest(plan) == plan_digest(plan2)
    assert plan["stats_digest"] == table_stats_digest(criteo_zipf_stats)
    text = format_plan(plan)
    assert "update cache" in text and str(plan["cache_rows"]) in text


def test_unique_rows_over_and_cache_hbm():
    """Interval working set: monotone in steps, clamped by vocab and by
    total draws, and never below the single-batch unique count.  Cache
    HBM prices codes + slots + sidecars + directory per plain group."""
    p = np.arange(1, 100_001, dtype=np.float64) ** -1.2
    counts = np.floor(p / p.sum() * 1_000_000).astype(np.int64)
    counts[0] += 1_000_000 - counts.sum()
    e = table_stats_from_counts(counts)
    u1 = unique_rows_at(e, 8192)
    u64 = unique_rows_over(e, 8192, 64)
    assert u1 <= unique_rows_over(e, 8192, 1) + 1e-6
    assert u1 < u64 < 64 * u1  # reuse: sublinear growth
    assert u64 <= e["vocab"]
    assert unique_rows_over(e, 8192, 10**9) <= e["vocab"]
    # int8 rowwise cache row: 16 codes + 4 slot + 8 qscale + 16 directory
    c = cache_hbm_bytes(16, optimizer="rowwise_adagrad", dtype="int8",
                        cache_rows=1024)
    assert c == 1024 * (16 + 4 + 8 + 16)
    f = cache_hbm_bytes(16, optimizer="rowwise_adagrad", dtype="float32",
                        cache_rows=1024)
    assert f == 1024 * (16 * 4 + 4 + 16)  # d=16 keeps narrow tiles
    f64 = cache_hbm_bytes(64, optimizer="adam", dtype="float32",
                          cache_rows=1024)
    assert f64 == 1024 * (128 * 4 + 2 * 128 * 4 + 16)  # d=64 lane-pads


def test_load_plan_validation(tmp_path, criteo_stats):
    with pytest.raises(ValueError, match="launch"):
        load_plan(tmp_path / "missing.json")
    plan = _criteo_plan(criteo_stats)
    p = write_plan(tmp_path, plan)  # dir -> sharding_plan.json
    assert p.name == "sharding_plan.json"
    payload = json.loads(p.read_text())
    payload["format_version"] = 99
    p.write_text(json.dumps(payload))
    with pytest.raises(ValueError, match="format_version"):
        load_plan(p)
    payload["format_version"] = 1
    payload["tables"]["cat_0"]["hot_k"] = 2
    payload["tables"]["cat_0"]["hot_ids"] = [2, 1]
    p.write_text(json.dumps(payload))
    with pytest.raises(ValueError, match="sorted"):
        load_plan(p)


def test_apply_plan_to_specs():
    from tdfo_tpu.parallel.embedding import EmbeddingSpec

    specs = [EmbeddingSpec("a_embed", 40_000, 8, features=("a",)),
             EmbeddingSpec("b_embed", 50, 8, features=("b",))]
    plan = {"tables": {
        "a": {"vocab": 40_000, "sharding": "replicated", "fused": True,
              "dtype": "bfloat16", "hot_k": 0, "hot_ids": []},
        "b_embed": {"vocab": 50, "sharding": "row", "fused": False,
                    "dtype": "float32", "hot_k": 2, "hot_ids": [3, 7]},
    }}
    new, hot = apply_plan_to_specs(specs, plan)
    assert new[0].sharding == "replicated" and new[0].fused
    assert new[0].dtype == jnp.bfloat16
    assert new[1].sharding == "row" and not new[1].fused
    assert set(hot) == {"b_embed"}
    assert hot["b_embed"].dtype == np.int32
    np.testing.assert_array_equal(hot["b_embed"], [3, 7])
    # stale plan: vocab mismatch must refuse
    stale = {"tables": {**plan["tables"],
                        "a": dict(plan["tables"]["a"], vocab=999)}}
    with pytest.raises(ValueError, match="stale"):
        apply_plan_to_specs(specs, stale)
    # a served table missing from the plan must refuse
    with pytest.raises(ValueError, match="no entry"):
        apply_plan_to_specs(
            specs, {"tables": {"a": plan["tables"]["a"]}})


# ------------------------------------------- telemetry-refinement round trip


def test_plan_from_replayed_counters_matches_synthetic(tmp_path):
    """PR-7 feedback loop: replaying a run's counter means through
    ``refine_stats_from_metrics`` reproduces the plan the synthetic stats
    produce when the observed traffic MATCHES the analytic estimate — the
    adapter changes provenance, not decisions."""
    rng = np.random.default_rng(0)
    vocabs = {"big": 200_000, "mid": 30_000, "tiny": 500}
    stats = {}
    for n, v in vocabs.items():
        counts = rng.zipf(1.3, size=20_000) % v
        stats[n] = table_stats_from_counts(
            np.bincount(counts, minlength=v).astype(np.int64))
    batch = 8192
    metrics = tmp_path / "metrics.jsonl"
    with open(metrics, "w") as fh:
        for _ in range(3):  # several records: the adapter takes means
            rec = {}
            for n in vocabs:
                rec[f"emb/{n}/touched_ids"] = float(batch)
                rec[f"emb/{n}/unique_rows"] = unique_rows_at(stats[n], batch)
            fh.write(json.dumps(rec) + "\n")
    refined = refine_stats_from_metrics(stats, metrics, batch_size=batch)
    assert all("observed" in refined[n] for n in vocabs)

    kw = dict(dim=16, batch_size=batch, optimizer="rowwise_adagrad",
              dense_model="dlrm")
    plan_syn = plan_tables(stats, **kw)
    plan_obs = plan_tables(refined, **kw)
    for n in vocabs:
        for key in ("sharding", "fused", "dtype", "hot_k"):
            assert plan_obs["tables"][n][key] == plan_syn["tables"][n][key]
    assert plan_obs["predicted_step_ms"] == pytest.approx(
        plan_syn["predicted_step_ms"], rel=1e-3)


# --------------------------------------------------- trainer-level wiring


@pytest.fixture(scope="module")
def plan_data(tmp_path_factory):
    from tdfo_tpu.data.ctr_preprocessing import run_ctr_preprocessing
    from tdfo_tpu.data.synthetic import write_synthetic_goodreads

    d = tmp_path_factory.mktemp("gr_plan")
    write_synthetic_goodreads(d, n_users=80, n_books=120,
                              interactions_per_user=(15, 40), seed=7)
    ctr = run_ctr_preprocessing(d, hot_vocab=4, hot_fraction=0.8)
    return d, ctr


def _trainer_cfg(d, ctr, **kw):
    from tdfo_tpu.core.config import read_configs

    return read_configs(
        None, data_dir=d, model="twotower", model_parallel=True,
        mesh={"data": 4, "model": 2}, n_epochs=1, learning_rate=3e-3,
        embed_dim=8, per_device_train_batch_size=16,
        per_device_eval_batch_size=16, shuffle_buffer_size=500,
        log_every_n_steps=2, size_map=ctr,
        sparse_optimizer="rowwise_adagrad", **kw)


# twotower feature-column -> size_map vocab key
_COL_TO_VOCAB = {"user_id": "user", "item_id": "item", "language": "language",
                 "is_ebook": "is_ebook", "format": "format",
                 "publisher": "publisher", "pub_decade": "pub_decade"}


def _hand_plan(ctr, overrides=None):
    tables = {}
    for col, vkey in _COL_TO_VOCAB.items():
        tables[col] = {"vocab": int(ctr[vkey]), "sharding": "row",
                       "fused": False, "dtype": "float32",
                       "hot_k": 0, "hot_ids": []}
    for col, entry in (overrides or {}).items():
        tables[col].update(entry)
    return {"format_version": 1, "tables": tables}


def test_plan_placement_wiring(plan_data, tmp_path):
    """The plan's decisions become the ACTUAL placement: fused storage,
    storage dtype, replicated cold base + hot head, row sharding — read
    back off the trainer's specs and device arrays, and the plan digest is
    stamped for the checkpoint sidecar."""
    from jax.sharding import PartitionSpec as P

    from tdfo_tpu.train.trainer import Trainer

    d, ctr = plan_data
    plan = _hand_plan(ctr, {
        # two fused f32/row tables -> they share ONE __fatstack_ array
        "user_id": {"fused": True},
        "format": {"fused": True},
        "item_id": {"dtype": "bfloat16"},
        "language": {"sharding": "replicated", "hot_k": 2,
                     "hot_ids": [0, 1]},
        "publisher": {"sharding": "replicated"},
    })
    path = write_plan(tmp_path / "plan.json", plan)
    tr = Trainer(_trainer_cfg(d, ctr, stack_tables=False,
                              planner={"plan": str(path)}),
                 log_dir=tmp_path / "log")
    by_name = tr.coll.specs  # dict name -> (plan-replaced) spec
    assert by_name["user_embed"].fused and by_name["format_embed"].fused
    assert by_name["item_embed"].dtype == jnp.bfloat16
    assert by_name["language_embed"].sharding == "replicated"
    tables = tr.state.tables
    # the two fused tables stack into ONE fat-line 3D array
    fat = [n for n in tables if n.startswith("__fatstack_")]
    assert len(fat) == 1 and tables[fat[0]].ndim == 3
    assert "user_embed" not in tables and "format_embed" not in tables
    # plain bf16 storage, row-sharded over the model axis
    assert tables["item_embed"].dtype == jnp.bfloat16
    assert tables["item_embed"].sharding.spec[0] == "model"
    # replicated cold base + replicated hot head with the plan's id set
    assert tables["language_embed"].sharding.spec == P()
    assert tables["language_embed__hot"].shape == (2, 8)
    assert tr.coll.hot_count("language_embed") == 2
    # the checkpoint sidecar pins this placement
    assert tr._ckpt_stamps["sharding_plan"] == plan_digest(plan)
    # bf16 storage stamps ride along from the plan-replaced specs
    assert tr._ckpt_stamps["table_dtype"]["item_embed"] == "bfloat16"


def test_plan_trajectory_matches_hand_knobs(plan_data, tmp_path):
    """A plan expressing exactly the hand-set knobs (row/plain/f32 + the
    hot_ids.json head sets) trains the SAME trajectory as
    embeddings.hot_vocab — the plan is a routing change, not a math
    change."""
    from tdfo_tpu.data.hot_ids import load_hot_ids
    from tdfo_tpu.train.trainer import Trainer

    d, ctr = plan_data
    m_hand = Trainer(_trainer_cfg(d, ctr, embeddings={"hot_vocab": 4}),
                     log_dir=tmp_path / "hand").fit()
    hot = load_hot_ids(d)
    plan = _hand_plan(ctr, {
        col: {"hot_k": len(hot[col]),
              "hot_ids": [int(i) for i in hot[col]]}
        for col in ("user_id", "item_id")
    })
    path = write_plan(tmp_path / "plan.json", plan)
    m_plan = Trainer(_trainer_cfg(d, ctr, planner={"plan": str(path)}),
                     log_dir=tmp_path / "plan").fit()
    assert set(m_plan) == set(m_hand)
    for k in m_hand:
        assert m_plan[k] == m_hand[k], (k, m_plan[k], m_hand[k])


def test_launch_plan_subcommand(plan_data, tmp_path, capsys):
    from tdfo_tpu.launch import main

    d, _ = plan_data
    cfgp = tmp_path / "config.toml"
    cfgp.write_text(
        f"""
data_dir = "{d}"
model = "twotower"
model_parallel = true
embed_dim = 8
per_device_train_batch_size = 16

[planner]
n_devices = 2
"""
    )
    assert main(["plan", "--config", str(cfgp)]) == 0
    out = capsys.readouterr().out
    assert "predicted step" in out and "sharding_plan.json" in out
    plan = load_plan(d)
    assert set(plan["tables"]) == set(_COL_TO_VOCAB)
    assert plan["n_devices"] == 2
    # global batch = per-device x planned devices
    assert plan["batch_size"] == 32


def test_plan_stamp_refuses_mismatched_restore(tmp_path):
    """A plan-built checkpoint pairs state layout with the plan digest:
    restore under a different plan — or none — refuses, both directions;
    legacy stampless checkpoints restore into plan-less runs untouched."""
    from tdfo_tpu.train.checkpoint import CheckpointManager

    state = {"t": jnp.zeros((4, 8), jnp.float32)}
    stamp = {"sharding_plan": "aaaa000011112222"}
    mgr = CheckpointManager(tmp_path / "c")
    mgr.save(0, state, stamps=dict(stamp))
    step, _, _ = mgr.restore(state, stamps=dict(stamp))
    assert step == 0
    for bad in (None, {"sharding_plan": "ffff000011112222"}):
        with pytest.raises(ValueError, match="stamps"):
            mgr.restore(state, stamps=bad)
    mgr.close()
    mgr2 = CheckpointManager(tmp_path / "c2")
    mgr2.save(0, state)  # legacy, no stamps
    s, _, _ = mgr2.restore(state, stamps=None)
    assert s == 0
    with pytest.raises(ValueError, match="stamps"):
        mgr2.restore(state, stamps=dict(stamp))
    mgr2.close()
