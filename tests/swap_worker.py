"""Worker for the kill-during-swap test (run as a subprocess, NOT pytest).

Usage:
    python swap_worker.py <root>

``<root>`` is prepared by the parent test and holds ``full_v0/``,
``delta_v1/``, ``full_v1/`` (the fresh full export of the same state the
delta reaches) and ``batch.npz``.  Both runs execute the SAME code — the
restart-converges contract of ``utils/faults.py``:

  * run 1: ``kill_during_swap=1`` is armed, so ``apply_delta`` stages the
    composed v1 bundle, then dies via ``os._exit(17)`` before publishing —
    exactly a frontend crash mid-swap.
  * run 2: the one-shot marker disarms the kill; ``recover()`` cleans the
    stray staging dir and re-points CURRENT at the last verified version
    (v0), the delta re-applies, and the worker asserts the composed bundle
    AND its served logits are bitwise-equal to the fresh full export,
    printing a JSON verdict for the parent.
"""

import json
import sys
from pathlib import Path

import numpy as np


def main() -> None:
    root = Path(sys.argv[1])

    import jax

    jax.config.update("jax_default_matmul_precision", "highest")

    from tdfo_tpu.serve.export import load_bundle, read_raw_bundle
    from tdfo_tpu.serve.scoring import make_scorer
    from tdfo_tpu.serve.swap import BundleStore
    from tdfo_tpu.utils.faults import FaultSpec, configure

    configure(FaultSpec(kill_during_swap=1), workdir=root)
    store = BundleStore(root / "store")
    recovered = store.recover()
    if store.current_version() is None:
        store.ingest_full(root / "full_v0")
    version = store.apply_delta(root / "delta_v1")  # run 1 dies in here

    m_store, a_store = read_raw_bundle(store.current_dir())
    m_fresh, a_fresh = read_raw_bundle(root / "full_v1")
    assert m_store["digest"] == m_fresh["digest"], "composed != fresh export"
    for k in a_fresh:
        assert np.array_equal(a_store[k], a_fresh[k]), f"array drift: {k}"

    batch = {k: v for k, v in np.load(root / "batch.npz").items()}
    composed = make_scorer(load_bundle(store.current_dir(), verify=True))
    fresh = make_scorer(load_bundle(root / "full_v1", verify=True))
    got = np.asarray(composed.score(batch))
    want = np.asarray(fresh.score(batch))
    assert np.array_equal(got, want), "served logits drifted from fresh export"

    print(json.dumps({"recovered": recovered, "version": version, "ok": True}))


if __name__ == "__main__":
    main()
