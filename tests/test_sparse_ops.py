"""Sparse optimizer ops vs dense references (fbgemm in-backward parity)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tdfo_tpu.ops.sparse import (
    dedupe_grads,
    sparse_adagrad,
    sparse_adam,
    sparse_optimizer,
    sparse_sgd,
)

V, D = 32, 8


def dense_grad_from(ids, grads):
    g = np.zeros((V, D), np.float32)
    np.add.at(g, np.asarray(ids), np.asarray(grads))
    return g


def test_dedupe_grads_merges_duplicates():
    ids = jnp.asarray([3, 1, 3, 7, 1, 3], jnp.int32)
    grads = jnp.ones((6, D), jnp.float32)
    uids, g, valid = dedupe_grads(ids, grads)
    assert uids.shape == (6,)
    assert int(valid.sum()) == 3
    got = {int(u): float(g[i, 0]) for i, u in enumerate(uids) if bool(valid[i])}
    assert got == {1: 2.0, 3: 3.0, 7: 1.0}


def test_dedupe_pad_slots_are_oob():
    ids = jnp.asarray([0, 0, 5], jnp.int32)
    uids, g, valid = dedupe_grads(ids, jnp.ones((3, D)))
    # invalid slots must never alias row 0
    assert all(int(u) > V for i, u in enumerate(uids) if not bool(valid[i]))
    np.testing.assert_array_equal(np.asarray(g[~np.asarray(valid)]), 0.0)


def test_sparse_sgd_matches_dense_on_touched_rows():
    rng = np.random.default_rng(0)
    table = jnp.asarray(rng.normal(size=(V, D)), jnp.float32)
    ids = jnp.asarray([4, 9, 4, 30], jnp.int32)
    grads = jnp.asarray(rng.normal(size=(4, D)), jnp.float32)
    uids, g, valid = dedupe_grads(ids, grads)
    new = sparse_sgd(table, uids, g, valid, lr=0.1)
    dense = np.asarray(table) - 0.1 * dense_grad_from(ids, grads)
    touched = [4, 9, 30]
    np.testing.assert_allclose(np.asarray(new)[touched], dense[touched], rtol=1e-6)
    untouched = [i for i in range(V) if i not in touched]
    np.testing.assert_array_equal(np.asarray(new)[untouched], np.asarray(table)[untouched])


def test_sparse_adam_matches_optax_adam_step1():
    import optax

    rng = np.random.default_rng(1)
    table = jnp.asarray(rng.normal(size=(V, D)), jnp.float32)
    ids = jnp.asarray([2, 2, 11], jnp.int32)
    grads = jnp.asarray(rng.normal(size=(3, D)), jnp.float32)

    opt = sparse_optimizer("adam", lr=1e-2)
    slots = opt.init(table)
    new_table, _ = opt.update(table, slots, ids, grads)

    tx = optax.adam(1e-2)
    dense_g = jnp.asarray(dense_grad_from(ids, grads))
    st = tx.init(table)
    upd, _ = tx.update(dense_g, st, table)
    want = optax.apply_updates(table, upd)

    touched = [2, 11]
    np.testing.assert_allclose(
        np.asarray(new_table)[touched], np.asarray(want)[touched], rtol=1e-5, atol=1e-6
    )
    untouched = [i for i in range(V) if i not in touched]
    np.testing.assert_array_equal(np.asarray(new_table)[untouched], np.asarray(table)[untouched])


def test_sparse_adam_multi_step_state():
    rng = np.random.default_rng(2)
    table = jnp.asarray(rng.normal(size=(V, D)), jnp.float32)
    opt = sparse_optimizer("adam", lr=1e-2, weight_decay=1e-3)
    slots = opt.init(table)
    upd = jax.jit(lambda t, s, i, g: opt.update(t, s, i, g))
    for step in range(5):
        ids = jnp.asarray(rng.integers(0, V, 16), jnp.int32)
        grads = jnp.asarray(rng.normal(size=(16, D)), jnp.float32)
        table, slots = upd(table, slots, ids, grads)
    assert int(slots[2]) == 5
    assert np.isfinite(np.asarray(table)).all()


def test_sparse_adagrad_accumulates():
    table = jnp.zeros((V, D), jnp.float32)
    accum = jnp.zeros((V, D), jnp.float32)
    ids = jnp.asarray([1, 1], jnp.int32)
    grads = jnp.ones((2, D), jnp.float32)
    uids, g, valid = dedupe_grads(ids, grads)
    new_t, new_acc = sparse_adagrad(table, accum, uids, g, valid, lr=0.1)
    # merged grad = 2.0; accum = 4.0; delta = 0.1 * 2 / (2 + eps)
    np.testing.assert_allclose(np.asarray(new_acc)[1], 4.0)
    np.testing.assert_allclose(np.asarray(new_t)[1], -0.1, rtol=1e-5)
    np.testing.assert_array_equal(np.asarray(new_t)[0], 0.0)


def test_jit_and_donation():
    rng = np.random.default_rng(3)
    table = jnp.asarray(rng.normal(size=(V, D)), jnp.float32)
    opt = sparse_optimizer("sgd", lr=0.1)

    @jax.jit
    def step(t, ids, g):
        uids, gg, valid = dedupe_grads(ids, g)
        return sparse_sgd(t, uids, gg, valid, lr=0.1)

    out = step(table, jnp.asarray([0, 1], jnp.int32), jnp.ones((2, D)))
    assert out.shape == (V, D)


@pytest.mark.parametrize("kind", ["sgd", "adam", "adagrad"])
def test_optimizer_wrapper_roundtrip(kind):
    table = jnp.ones((V, D), jnp.float32)
    opt = sparse_optimizer(kind, lr=0.05)
    slots = opt.init(table)
    new_table, new_slots = opt.update(
        table, slots, jnp.asarray([3, 5], jnp.int32), jnp.ones((2, D))
    )
    assert float(new_table[3, 0]) < 1.0
    assert float(new_table[0, 0]) == 1.0


def test_dedupe_negative_padding_ids_do_not_corrupt():
    # regression: -1 padding used to break searchsorted's sortedness invariant
    ids = jnp.array([-1, 5, 5, 7], jnp.int32)
    grads = jnp.ones((4, 3), jnp.float32)
    uids, g, valid = dedupe_grads(ids, grads)
    table = jnp.zeros((10, 3), jnp.float32)
    out = sparse_sgd(table, uids, g, valid, lr=1.0)
    np.testing.assert_allclose(out[5], -2.0 * np.ones(3))  # two grads merged
    np.testing.assert_allclose(out[7], -1.0 * np.ones(3))
    assert np.all(np.asarray(out[jnp.array([0, 1, 2, 3, 4, 6, 8, 9])]) == 0)


def test_dedupe_all_padding():
    ids = jnp.full((4,), -1, jnp.int32)
    uids, g, valid = dedupe_grads(ids, jnp.ones((4, 2)))
    assert not bool(valid.any())
    table = jnp.zeros((5, 2))
    out = sparse_sgd(table, uids, g, valid, lr=1.0)
    assert np.all(np.asarray(out) == 0)


def test_dedupe_capacity_guard():
    """Undersized capacity is a TRACE-TIME error unless vocab proves it safe
    (VERDICT r3 weak #5: the old CPU-only runtime print doesn't exist on the
    production backend)."""
    import pytest

    ids = jnp.arange(16, dtype=jnp.int32)
    g = jnp.ones((16, 2))
    with pytest.raises(ValueError, match="capacity"):
        dedupe_grads(ids, g, capacity=8)
    with pytest.raises(ValueError, match="capacity"):
        jax.jit(lambda i, gg: dedupe_grads(i, gg, capacity=8))(ids, g)
    # vocab <= capacity licenses the small capacity, and the result is exact
    small = ids % 8
    uids, gg, valid = dedupe_grads(small, g, capacity=8, vocab=8)
    assert bool(valid.all())
    np.testing.assert_allclose(np.asarray(gg), 2.0 * np.ones((8, 2)))


def test_rowwise_adagrad_semantics():
    """fbgemm EXACT_ROWWISE_ADAGRAD: per-ROW accumulator of mean squared
    grads; dedupe merges duplicates first; padding ids contribute nothing."""
    from tdfo_tpu.ops.sparse import sparse_optimizer

    opt = sparse_optimizer("rowwise_adagrad", lr=0.5)
    table = jnp.ones((6, 4), jnp.float32)
    slots = opt.init(table)
    assert slots[0].shape == (6,)  # one cell per row, not per element
    ids = jnp.array([1, 3, 1, -1], jnp.int32)
    g = jnp.stack([
        jnp.full((4,), 1.0), jnp.full((4,), 2.0),
        jnp.full((4,), 3.0), jnp.full((4,), 99.0),  # padding row: dropped
    ])
    new_table, (accum,) = opt.update(table, slots, ids, g)
    # row 1: merged grad = 4.0 per element -> acc = mean(16) = 16
    np.testing.assert_allclose(accum[1], 16.0)
    np.testing.assert_allclose(accum[3], 4.0)
    assert accum[0] == accum[2] == accum[4] == accum[5] == 0.0
    np.testing.assert_allclose(
        np.asarray(new_table[1]), 1.0 - 0.5 * 4.0 / (4.0 + 1e-10), rtol=1e-6)
    np.testing.assert_allclose(
        np.asarray(new_table[3]), 1.0 - 0.5 * 2.0 / (2.0 + 1e-10), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(new_table[0]), 1.0)  # untouched

    # second step accumulates (adaptive: same grad moves the row LESS)
    t2, (acc2,) = opt.update(new_table, (accum,), jnp.array([1], jnp.int32),
                             jnp.full((1, 4), 4.0))
    np.testing.assert_allclose(acc2[1], 32.0)
    step2 = np.asarray(new_table[1] - t2[1])
    step1 = np.asarray(table[1] - new_table[1])
    assert (step2 < step1).all()


def test_max_distinct_licenses_tight_capacity():
    """A caller-proven distinct bound licenses capacity < B, and results are
    identical to the full-capacity run (fewer sentinel slots only)."""
    from tdfo_tpu.ops.sparse import sparse_optimizer

    opt = sparse_optimizer("adam", lr=0.1, small_vocab_threshold=0)
    r = np.random.default_rng(3)
    # two "features": 16 ids into a 6-row region + 16 into rows [6, 106)
    ids = jnp.concatenate([
        jnp.asarray(r.integers(0, 6, 16), jnp.int32),
        jnp.asarray(6 + r.integers(0, 100, 16), jnp.int32),
    ])
    g = jnp.asarray(r.standard_normal((32, 4)), jnp.float32)
    table = jnp.asarray(r.standard_normal((106, 4)), jnp.float32)
    slots = opt.init(table)
    bound = 6 + 16  # min(16, 6) + min(16, 100)
    t_full, s_full = opt.update(table, slots, ids, g)
    t_tight, s_tight = opt.update(table, slots, ids, g,
                                  capacity=bound, max_distinct=bound)
    np.testing.assert_allclose(np.asarray(t_full), np.asarray(t_tight))
    np.testing.assert_allclose(np.asarray(s_full[0]), np.asarray(s_tight[0]))
    import pytest

    with pytest.raises(ValueError, match="max_distinct"):
        opt.update(table, slots, ids, g, capacity=8, max_distinct=None)
