"""Worker for the 2-process multihost test (run as a subprocess, NOT pytest).

Usage:
    python multihost_worker.py <process_id> <num_processes> <coordinator_port>
                               <local_devices> <data_dir> <out_json> [model]

Each process spoofs ``local_devices`` CPU devices, joins the jax distributed
cluster, trains/evaluates through the SAME Trainer as single-host runs, and
writes its view of the (global) metrics to ``out_json``.  The pytest driver
asserts that every process reports identical, provably-global numbers.
"""

import json
import sys


def main() -> None:
    pid, nprocs, port, ndev = (int(a) for a in sys.argv[1:5])
    data_dir, out_json = sys.argv[5], sys.argv[6]
    model = sys.argv[7] if len(sys.argv) > 7 else "twotower"

    from tdfo_tpu.core.mesh import spoof_cpu_devices

    spoof_cpu_devices(ndev)

    import jax

    jax.distributed.initialize(
        coordinator_address=f"127.0.0.1:{port}",
        num_processes=nprocs,
        process_id=pid,
    )
    assert jax.process_count() == nprocs
    assert jax.local_device_count() == ndev

    from pathlib import Path

    from tdfo_tpu.core.config import load_size_map, read_configs
    from tdfo_tpu.train.trainer import Trainer

    if model == "bert4rec":
        seq_map = json.loads(
            (Path(data_dir) / "size_map_bert4rec.json").read_text()
        )
        extra = dict(
            size_map={"n_items": seq_map["n_items"]},
            model_parallel=True, jagged=True, max_len=12, sliding_step=6,
            n_heads=2, n_layers=1,
        )
    else:
        extra = dict(size_map=load_size_map(data_dir))
    cfg = read_configs(
        None,
        data_dir=data_dir,
        model=model,
        n_epochs=1,
        learning_rate=3e-3,
        embed_dim=8,
        per_device_train_batch_size=8,
        per_device_eval_batch_size=8,
        shuffle_buffer_size=500,
        log_every_n_steps=10_000,
        mesh={"data": nprocs * ndev},
        **extra,
    )
    tr = Trainer(cfg)
    pre = tr.evaluate(epoch=-1)  # deterministic init -> must be global-identical
    tr.train_epoch(0)
    post = tr.evaluate(epoch=0)
    record = {
        "process": pid,
        "pre": pre,
        "post": post,
        "steps": int(tr.state.step),
    }
    with open(out_json, "w") as f:
        json.dump(record, f)
    print(f"worker {pid} done: {record}", flush=True)


if __name__ == "__main__":
    main()
