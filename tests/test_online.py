"""Online-loop acceptance: kill the serve->retrain->delta-export->swap
supervisor (``train/online.py``) at stage boundaries with REAL ``os._exit``
kills, restart the same command, and require the final swapped bundle —
digest, replay cursor, AND served probe logits — bitwise-equal to an
uninterrupted run's (subprocess pattern from tests/test_crash_resume.py).

The request log is written ONCE by the module fixture with the real
``RequestLog`` writer (rotation on), so every lineage replays the same
bytes.  Kill/restart runs use drain mode (``max_cycles = 0``): the
in-memory cycle counter resets on restart, so only "consume the whole log"
is comparable across lineages.

Tier 1 runs ONE kill (cycle-2 export boundary — after the checkpoint
claimed ``target_version``, before the store caught up, i.e. the
``_catch_up`` repair path) plus the record-id accounting and jaxpr audits;
the full kill matrix is ``@pytest.mark.slow``.
"""

import json
import os
import re
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

REPO = str(Path(__file__).resolve().parents[1])
WORKER = str(Path(__file__).with_name("online_worker.py"))

LOCAL_DEVICES = 4
BATCH_ROWS = 8 * 4  # per_device_train_batch_size x data-axis size
STEPS_PER_CYCLE = 2
N_CYCLES = 2  # full cycles the log holds (plus a sub-batch tail that waits)


def _spawn(spec_path: Path) -> subprocess.Popen:
    env = os.environ.copy()
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = f"{REPO}{os.pathsep}" + env.get("PYTHONPATH", "")
    return subprocess.Popen(
        [sys.executable, WORKER, str(spec_path)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True, env=env,
    )


def _run_workers(spec_paths: list[Path]) -> tuple[list[int], list[str]]:
    procs = [_spawn(p) for p in spec_paths]
    rcs, outs = [], []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=600)
            rcs.append(p.returncode)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        raise
    return rcs, outs


def _run_worker(spec_path: Path) -> tuple[int, str]:
    rcs, outs = _run_workers([spec_path])
    return rcs[0], outs[0]


@pytest.fixture(scope="module")
def online_env(tmp_path_factory):
    """Synthetic goodreads data + a request log every lineage replays."""
    from tdfo_tpu.core.config import load_size_map, read_configs
    from tdfo_tpu.data.ctr_preprocessing import run_ctr_preprocessing
    from tdfo_tpu.data.replay import RequestLog
    from tdfo_tpu.data.synthetic import write_synthetic_goodreads
    from tdfo_tpu.serve.frontend import _column_vocab
    from tdfo_tpu.train.trainer import _ctr_columns

    d = tmp_path_factory.mktemp("gr_online")
    write_synthetic_goodreads(d, n_users=80, n_books=120,
                              interactions_per_user=(15, 40), seed=13)
    run_ctr_preprocessing(d)

    cfg = read_configs(None, data_dir=str(d), model="twotower",
                       model_parallel=True, size_map=load_size_map(str(d)))
    cat_cols, cont_cols = _ctr_columns(cfg)
    vocab = _column_vocab(cfg, cat_cols)

    root = tmp_path_factory.mktemp("reqlog") / "rl"
    log = RequestLog(root, segment_bytes=4096)  # rotation in the real stream
    rng = np.random.default_rng(7)
    rows_by_seq: dict[int, int] = {}
    total, target = 0, N_CYCLES * STEPS_PER_CYCLE * BATCH_ROWS
    while total < target + 5:  # sub-batch tail: drained runs leave it unread
        n = int(rng.integers(3, 9))
        feats = {c: rng.integers(0, vocab[c], size=n).tolist()
                 for c in cat_cols}
        for c in cont_cols:
            feats[c] = [round(float(v), 6) for v in rng.random(n)]
        feats["label"] = rng.integers(0, 2, size=n).tolist()
        seq = log.append({"event": "serve_request", "request": f"r{total}",
                          "rows": n, "outcome": "ok", "features": feats})
        rows_by_seq[seq] = n
        total += n
    log.close()
    return dict(data_dir=str(d), request_log=str(root),
                rows_by_seq=rows_by_seq, total_rows=total)


def _make_spec(tmp: Path, env: dict, name: str, *, ckpt: str, log: str,
               faults: dict | None = None) -> Path:
    spec = dict(
        data_dir=env["data_dir"], checkpoint_dir=str(tmp / ckpt),
        log_dir=str(tmp / log), request_log=env["request_log"],
        out_json=str(tmp / f"{name}.json"), local_devices=LOCAL_DEVICES,
        steps_per_cycle=STEPS_PER_CYCLE, max_cycles=0,
        faults=faults or {},
    )
    p = tmp / f"{name}_spec.json"
    p.write_text(json.dumps(spec))
    return p


@pytest.fixture(scope="module")
def kill_runs(online_env, tmp_path_factory):
    """The tier-1 scenario, run once for all audits below: kill at the
    cycle-2 EXPORT boundary (stage-call #10 — the checkpoint has claimed
    target_version 2 but the store head is still v1), restart, plus an
    uninterrupted reference lineage."""
    from tdfo_tpu.utils.faults import KILL_EXIT_CODE

    tmp = tmp_path_factory.mktemp("online_runs")
    killed_p = _make_spec(tmp, online_env, "killed", ckpt="ckpt",
                          log="log_shared",
                          faults={"kill_between_stages": 10})
    ref_p = _make_spec(tmp, online_env, "ref", ckpt="ckpt_ref", log="log_ref")

    # killed and reference lineages are independent: run them concurrently
    rcs, outs = _run_workers([killed_p, ref_p])
    assert rcs[0] == KILL_EXIT_CODE, \
        f"expected injected kill, got rc={rcs[0]}\n{outs[0][-2000:]}"
    assert not (tmp / "killed.json").exists()  # died before the verdict
    assert (tmp / "ckpt" / "faults_stage_kill.marker").exists()
    assert rcs[1] == 0, f"reference run failed rc={rcs[1]}\n{outs[1][-2000:]}"

    # restart the SAME command: the marker disarms the kill, _catch_up
    # publishes the claimed version, the loop drains the log
    rc, out = _run_worker(killed_p)
    assert rc == 0, f"resumed run failed rc={rc}\n{out[-2000:]}"

    return dict(
        resumed=json.loads((tmp / "killed.json").read_text()),
        ref=json.loads((tmp / "ref.json").read_text()),
        metrics=tmp / "log_shared" / "metrics.jsonl",
        tmp=tmp,
    )


def test_kill_restart_converges_bitwise(kill_runs):
    resumed, ref = kill_runs["resumed"], kill_runs["ref"]
    # same store version, same composed-bundle digest, same replay cursor
    assert resumed["version"] == ref["version"] >= N_CYCLES
    assert resumed["digest"] == ref["digest"]
    assert resumed["cursor"] == ref["cursor"]
    # the servable surface: probe logits through the live post-swap batcher
    # are bitwise-equal (json round-trips floats exactly)
    assert resumed["logits"] == ref["logits"]
    assert resumed["stats"]["global_step"] == ref["stats"]["global_step"]


def _online_cycles(metrics_path: Path) -> list[dict]:
    recs = [json.loads(l) for l in metrics_path.read_text().splitlines()]
    return [r for r in recs if r.get("event") == "online_cycle"]


def test_record_accounting_no_dup_no_loss(kill_runs, online_env):
    """The exactly-once audit: killed + resumed lineages share one
    metrics.jsonl; across BOTH, the consumed (seq, row_start, row_end)
    spans of the durable cycles tile each record exactly once."""
    cycles = _online_cycles(kill_runs["metrics"])
    assert len(cycles) >= N_CYCLES
    # each durable cycle published exactly one store version, no repeats
    versions = [c["version"] for c in cycles]
    assert versions == sorted(set(versions))

    spans: dict[int, list[tuple[int, int]]] = {}
    for c in cycles:
        for seq, a, b in c["consumed"]:
            spans.setdefault(seq, []).append((a, b))
    rows_by_seq = {int(k): v for k, v in online_env["rows_by_seq"].items()}
    covered = 0
    for seq, parts in spans.items():
        parts.sort()
        # no overlap (trained twice) and no hole (skipped) within a record
        assert parts[0][0] == 0, (seq, parts)
        for (a0, b0), (a1, b1) in zip(parts, parts[1:]):
            assert b0 == a1, f"seq {seq}: gap or overlap at {parts}"
        assert parts[-1][1] <= rows_by_seq[seq]
        covered += parts[-1][1] == rows_by_seq[seq]
    # fully-trained records match the durable cursor's record count
    assert covered == kill_runs["resumed"]["cursor"]["records"]


def test_replay_counters_ride_telemetry(kill_runs):
    """Acceptance: replay/records, replay/bad, replay/lag are visible
    through the PR-7 metrics path on every cycle record."""
    cycles = _online_cycles(kill_runs["metrics"])
    assert cycles
    for c in cycles:
        assert c["replay/records"] >= 1.0
        assert c["replay/bad"] == 0.0
        assert c["replay/lag"] >= 0.0
    # monotone progress across the shared log: records never regress
    recs = [c["replay/records"] for c in cycles]
    assert recs == sorted(recs)


def test_online_config_does_not_touch_step_graph(online_env, tmp_path):
    """Acceptance jaxpr pin: a loop config with replay disabled vs enabled
    compiles byte-identical step programs — [online] is pure supervisor
    plumbing, it cannot cost a single equation in the hot path."""
    import jax

    from tdfo_tpu.core.config import load_size_map, read_configs
    from tdfo_tpu.train.metrics import AUC
    from tdfo_tpu.train.trainer import Trainer

    kw = dict(data_dir=online_env["data_dir"], model="twotower",
              model_parallel=True, n_epochs=1, embed_dim=8,
              per_device_train_batch_size=8,
              size_map=load_size_map(online_env["data_dir"]))
    cfg_off = read_configs(None, **kw)
    cfg_on = read_configs(
        None, checkpoint_dir=str(tmp_path / "ckpt"),
        online=dict(request_log=online_env["request_log"]), **kw)

    norm = lambda j: re.sub(r"0x[0-9a-f]+", "0xADDR", str(j))
    jaxprs = []
    for cfg in (cfg_off, cfg_on):
        tr = Trainer(cfg)
        batch = {k: np.zeros((8 * tr.mesh.shape["data"],) + shape, dt)
                 for k, (dt, shape) in tr._eval_schema.items()}
        auc = AUC.empty() if tr._train_auc_enabled else None
        jaxprs.append(norm(jax.make_jaxpr(tr.train_step)(
            tr.state, batch, auc)))
    assert jaxprs[0] == jaxprs[1]


@pytest.mark.slow  # the full kill matrix; tier 1 covers the catch-up kill
@pytest.mark.parametrize("faults", [
    {"kill_between_stages": 1},   # cycle 1 replay: nothing durable yet
    {"kill_between_stages": 2},   # cycle 1 train: replay cursor uncommitted
    {"kill_between_stages": 3},   # before cycle-1 checkpoint: cycle discarded
    {"kill_between_stages": 4},   # after checkpoint, before export
    {"kill_between_stages": 5},   # delta exported, not published
    {"kill_between_stages": 6},   # published, serving swap never ran
    {"kill_during_replay": 2},    # mid-replay, after a record's commit
    {"kill_during_swap": 1},      # mid-apply_delta: half-published store
], ids=lambda f: "-".join(f"{k}{v}" for k, v in f.items()))
def test_kill_matrix_converges(kill_runs, online_env, tmp_path, faults):
    """Kill at EVERY stage boundary of cycle 1 (plus mid-replay and
    mid-publish): restarting the same command must always converge to the
    reference verdict, bit for bit."""
    from tdfo_tpu.utils.faults import KILL_EXIT_CODE

    spec = _make_spec(tmp_path, online_env, "killed", ckpt="ckpt",
                      log="log", faults=faults)
    rc, out = _run_worker(spec)
    assert rc == KILL_EXIT_CODE, f"rc={rc}\n{out[-2000:]}"
    assert not (tmp_path / "killed.json").exists()

    rc, out = _run_worker(spec)
    assert rc == 0, f"resumed run failed rc={rc}\n{out[-2000:]}"
    resumed = json.loads((tmp_path / "killed.json").read_text())
    ref = kill_runs["ref"]
    assert resumed["version"] == ref["version"]
    assert resumed["digest"] == ref["digest"]
    assert resumed["cursor"] == ref["cursor"]
    assert resumed["logits"] == ref["logits"]
