"""Micro-batching frontend tests: compile counts, deadlines, observability.

The frontend's three contracts:

  * COMPILE budget — an arbitrarily ragged request trace pads to the fixed
    bucket set, so the scoring jit cache holds at most ``len(buckets)``
    programs (the TPU analogue of TF-Serving's allowed_batch_sizes);
  * DEADLINE semantics — a partial batch ships exactly when the OLDEST
    pending request's deadline expires (graceful degradation), results come
    back correctly UNPADDED per request;
  * OBSERVABILITY — per-request latency lands in the metrics JSONL plus a
    p50/p99 summary record.

Serving programs must also stay scatter-free (CLAUDE.md: ~170 ns/row on
v5e); the lowering-text checks pin that for scoring AND retrieval.
"""

import json

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from tdfo_tpu.serve.frontend import MicroBatcher
from tdfo_tpu.train.trainer import MetricLogger


class FakeClock:
    """Injectable monotonic time — deadline tests must not sleep."""

    def __init__(self):
        self.t = 0.0

    def advance(self, s):
        self.t += s

    def __call__(self):
        return self.t


def _counting_score():
    """A scoring stub that records every batch shape it is traced with."""
    shapes = []

    def score(batch):
        x = np.asarray(batch["x"], np.float32)
        shapes.append(x.shape[0])
        return x * 2.0

    return score, shapes


# ----------------------------------------------------------- batching core


def test_full_batches_ship_immediately():
    score, shapes = _counting_score()
    mb = MicroBatcher(score, buckets=(8, 32), max_batch=32,
                      batch_deadline_ms=1e9, clock=FakeClock())
    for i in range(8):
        mb.submit(i, {"x": np.full(8, i)})
    # 32-row batches shipped as soon as they filled, nothing waited on time
    assert mb.shipped == [(32, 32), (32, 32)]
    for i in range(8):
        np.testing.assert_array_equal(mb.results[i], np.full(8, 2.0 * i))


def test_deadline_ships_partial_and_unpads():
    score, _ = _counting_score()
    clk = FakeClock()
    mb = MicroBatcher(score, buckets=(8, 32), max_batch=32,
                      batch_deadline_ms=5.0, clock=clk)
    mb.submit("a", {"x": np.arange(3)})
    clk.advance(0.004)
    mb.poll()
    assert mb.shipped == [] and "a" not in mb.results  # deadline not hit
    clk.advance(0.002)
    mb.poll()
    assert mb.shipped == [(3, 8)]  # partial batch, padded 3 -> bucket 8
    np.testing.assert_array_equal(mb.results["a"], np.arange(3) * 2.0)
    assert mb.results["a"].shape == (3,)  # unpadded result

    # deadline 0: every poll ships whatever is pending
    mb0 = MicroBatcher(score, buckets=(8,), max_batch=8,
                       batch_deadline_ms=0.0, clock=clk)
    mb0.submit("b", {"x": np.arange(2)})
    mb0.poll()
    assert mb0.shipped == [(2, 8)]


def test_deadline_is_oldest_request():
    """A young request cannot reset the clock for an old one."""
    score, _ = _counting_score()
    clk = FakeClock()
    mb = MicroBatcher(score, buckets=(8,), max_batch=8,
                      batch_deadline_ms=5.0, clock=clk)
    mb.submit("old", {"x": np.arange(2)})
    clk.advance(0.004)
    mb.submit("young", {"x": np.arange(2)})
    clk.advance(0.002)  # old is 6 ms stale, young only 2 ms
    mb.poll()
    assert mb.shipped == [(4, 8)]  # both ride the ship old triggered
    assert set(mb.results) == {"old", "young"}


def test_bucket_knob_changes_padding():
    """Same trace, different bucket sets -> different padded shapes (the
    [serving].buckets observability hook)."""
    trace = [(i, {"x": np.arange(5)}) for i in range(3)]
    for buckets, expect in [((8, 16), 8), ((6, 16), 6), ((16,), 16)]:
        score, shapes = _counting_score()
        mb = MicroBatcher(score, buckets=buckets, max_batch=buckets[-1],
                          batch_deadline_ms=0.0, clock=FakeClock())
        mb.run(trace)
        assert all(p == expect for _, p in mb.shipped)
        assert set(shapes) == {expect}


def test_validation():
    score, _ = _counting_score()
    with pytest.raises(ValueError, match="strictly increasing"):
        MicroBatcher(score, buckets=(8, 8), max_batch=8, batch_deadline_ms=1)
    with pytest.raises(ValueError, match="strictly increasing"):
        MicroBatcher(score, buckets=(), max_batch=8, batch_deadline_ms=1)
    with pytest.raises(ValueError, match="does not fit"):
        MicroBatcher(score, buckets=(8,), max_batch=16, batch_deadline_ms=1)
    mb = MicroBatcher(score, buckets=(8,), max_batch=8, batch_deadline_ms=1)
    with pytest.raises(ValueError, match="ragged columns"):
        mb.submit("r", {"x": np.arange(3), "y": np.arange(4)})
    with pytest.raises(ValueError, match="split it upstream"):
        mb.submit("r", {"x": np.arange(9)})


def test_latency_jsonl(tmp_path):
    """Per-request records + the p50/p99 summary land in metrics.jsonl."""
    logger = MetricLogger(tmp_path)
    score, _ = _counting_score()
    mb = MicroBatcher(score, buckets=(8,), max_batch=8, batch_deadline_ms=0.0,
                      logger=logger, clock=FakeClock())
    mb.run([(f"r{i}", {"x": np.arange(2)}) for i in range(4)])
    stats = mb.stats()
    logger.close()
    records = [json.loads(l) for l in
               (tmp_path / "metrics.jsonl").read_text().splitlines()]
    reqs = [r for r in records if r.get("event") == "serve_request"]
    assert [r["request"] for r in reqs] == ["r0", "r1", "r2", "r3"]
    assert all(r["rows"] == 2 and r["padded"] == 8 for r in reqs)
    # saturation observability: deadline 0 ships every request alone, so
    # the queue is empty after each ship and the 8-row program is 1/4 used
    assert all(r["queue_depth"] == 0 for r in reqs)
    assert all(r["batch_fill"] == 0.25 for r in reqs)
    summary = [r for r in records if r.get("event") == "serve_summary"]
    assert len(summary) == 1 and summary[0]["requests"] == 4
    assert stats["requests"] == 4 and stats["batches"] == 4
    assert stats["p99_ms"] >= stats["p50_ms"] >= 0.0


def test_queue_depth_counts_waiting_requests(tmp_path):
    """queue_depth is the number of requests still pending AFTER a ship —
    a saturated frontend shows a growing number in the latency JSONL."""
    logger = MetricLogger(tmp_path)
    score, _ = _counting_score()
    mb = MicroBatcher(score, buckets=(8,), max_batch=8, batch_deadline_ms=1e9,
                      logger=logger, clock=FakeClock())
    # 3 one-row stragglers queue, then a 5-row request fills the batch;
    # two more stragglers arrive before the drain ships them
    for i in range(3):
        mb.submit(f"s{i}", {"x": np.arange(1)})
    assert mb.shipped == []  # nothing full yet
    mb.submit("big", {"x": np.arange(5)})
    assert mb.shipped == [(8, 8)]
    mb.submit("late0", {"x": np.arange(2)})
    mb.submit("late1", {"x": np.arange(2)})
    mb.drain()
    logger.close()
    records = [json.loads(l) for l in
               (tmp_path / "metrics.jsonl").read_text().splitlines()]
    depth = {r["request"]: r["queue_depth"] for r in records
             if r.get("event") == "serve_request"}
    fill = {r["request"]: r["batch_fill"] for r in records
            if r.get("event") == "serve_request"}
    assert depth["s0"] == depth["big"] == 0  # full ship drained the queue
    assert depth["late0"] == depth["late1"] == 0
    assert fill["big"] == 1.0 and fill["late0"] == 0.5


def test_program_cache_invariant_is_a_runtime_assertion():
    """When the scorer exposes its compiled-program count, every ship
    checks it against len(buckets) — a shape leak fails LOUDLY in prod,
    not just in the test suite."""
    score, _ = _counting_score()
    mb = MicroBatcher(score, buckets=(8,), max_batch=8, batch_deadline_ms=0.0,
                      clock=FakeClock(), program_cache_size=lambda: 1)
    mb.run([("ok", {"x": np.arange(3)})])  # 1 program for 1 bucket: fine
    leaky = MicroBatcher(score, buckets=(8,), max_batch=8,
                         batch_deadline_ms=0.0, clock=FakeClock(),
                         program_cache_size=lambda: 2)
    with pytest.raises(RuntimeError, match="bounded-jit-cache"):
        leaky.submit("r", {"x": np.arange(8)})


# ------------------------------------------------- compile-count regression


@pytest.fixture(scope="module")
def scorer8(mesh8, tmp_path_factory):
    """A real sparse TwoTower scorer on the 8-device mesh (module-scoped:
    the compile-count test needs a FRESH jit cache, so it builds its own)."""
    from tests.test_serve import _export_sparse, _twotower_sparse
    from tdfo_tpu.serve.export import load_bundle
    from tdfo_tpu.serve.scoring import make_scorer

    coll, _, state = _twotower_sparse(mesh8)
    out = _export_sparse(tmp_path_factory.mktemp("bundle") / "b", coll, state)
    return make_scorer(load_bundle(out), mesh=mesh8)


def test_ragged_trace_compiles_at_most_len_buckets(scorer8):
    """40 requests of 17 distinct sizes pad to 3 buckets -> the scoring jit
    cache holds <= 3 programs.  THE compile-budget regression bar."""
    from tests.test_serve import _ctr_batch

    buckets = (8, 32, 64)
    assert scorer8.score_cache_size() == 0
    rng = np.random.default_rng(0)
    trace = [(i, _ctr_batch(rng, int(rng.integers(1, 65)), with_label=False))
             for i in range(40)]
    mb = MicroBatcher(scorer8.score, buckets=buckets, max_batch=64,
                      batch_deadline_ms=0.0, clock=FakeClock())
    mb.run(trace)
    assert len({r for r, _ in mb.shipped}) > len(buckets)  # genuinely ragged
    assert {p for _, p in mb.shipped} <= set(buckets)
    assert scorer8.score_cache_size() <= len(buckets)
    for i, batch in trace:
        assert mb.results[i].shape == (len(batch["user_id"]),)


def test_serving_programs_are_scatter_free(scorer8, mesh8):
    """No serving program may lower a scatter (CLAUDE.md: ~170 ns/row):
    scoring, both towers, corpus chunks, and sharded retrieval."""
    from tests.test_serve import SIZE_MAP, _ctr_batch
    from tdfo_tpu.serve.corpus import build_corpus, synthetic_item_features
    from tdfo_tpu.serve.retrieval import make_retrieval, mips_scores

    batch = _ctr_batch(np.random.default_rng(1), 8, with_label=False)
    lowered = scorer8._score.lower(dict(batch), *scorer8._params)
    assert "scatter" not in lowered.as_text()

    lowered = scorer8._user.lower(dict(batch), *scorer8._params)
    assert "scatter" not in lowered.as_text()
    lowered = scorer8._item.lower(dict(batch), *scorer8._params)
    assert "scatter" not in lowered.as_text()

    corpus = build_corpus(
        scorer8, synthetic_item_features(SIZE_MAP, 64, seed=0),
        corpus_batch=64, mesh=mesh8)
    queries = jnp.zeros((4, 16), jnp.float32)
    retrieve = make_retrieval(corpus, mesh=mesh8, top_k=10)
    text = retrieve.jitted.lower(
        queries, corpus.vectors, corpus.ids).as_text()
    assert "scatter" not in text
    assert "scatter" not in jax.jit(mips_scores).lower(
        queries, corpus.vectors).as_text()
    s, ids = retrieve(queries)
    assert s.shape == (4, 10) and ids.shape == (4, 10)
