"""Micro-batching frontend tests: compile counts, deadlines, observability.

The frontend's three contracts:

  * COMPILE budget — an arbitrarily ragged request trace pads to the fixed
    bucket set, so the scoring jit cache holds at most ``len(buckets)``
    programs (the TPU analogue of TF-Serving's allowed_batch_sizes);
  * DEADLINE semantics — a partial batch ships exactly when the OLDEST
    pending request's deadline expires (graceful degradation), results come
    back correctly UNPADDED per request;
  * OBSERVABILITY — per-request latency lands in the metrics JSONL plus a
    p50/p99 summary record.

Serving programs must also stay scatter-free (CLAUDE.md: ~170 ns/row on
v5e); the lowering-text checks pin that for scoring AND retrieval.
"""

import json

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from tdfo_tpu.serve.frontend import MicroBatcher
from tdfo_tpu.train.trainer import MetricLogger


class FakeClock:
    """Injectable monotonic time — deadline tests must not sleep."""

    def __init__(self):
        self.t = 0.0

    def advance(self, s):
        self.t += s

    def __call__(self):
        return self.t


def _counting_score():
    """A scoring stub that records every batch shape it is traced with."""
    shapes = []

    def score(batch):
        x = np.asarray(batch["x"], np.float32)
        shapes.append(x.shape[0])
        return x * 2.0

    return score, shapes


# ----------------------------------------------------------- batching core


def test_full_batches_ship_immediately():
    score, shapes = _counting_score()
    mb = MicroBatcher(score, buckets=(8, 32), max_batch=32,
                      batch_deadline_ms=1e9, clock=FakeClock())
    for i in range(8):
        mb.submit(i, {"x": np.full(8, i)})
    # 32-row batches shipped as soon as they filled, nothing waited on time
    assert mb.shipped == [(32, 32), (32, 32)]
    for i in range(8):
        np.testing.assert_array_equal(mb.results[i], np.full(8, 2.0 * i))


def test_deadline_ships_partial_and_unpads():
    score, _ = _counting_score()
    clk = FakeClock()
    mb = MicroBatcher(score, buckets=(8, 32), max_batch=32,
                      batch_deadline_ms=5.0, clock=clk)
    mb.submit("a", {"x": np.arange(3)})
    clk.advance(0.004)
    mb.poll()
    assert mb.shipped == [] and "a" not in mb.results  # deadline not hit
    clk.advance(0.002)
    mb.poll()
    assert mb.shipped == [(3, 8)]  # partial batch, padded 3 -> bucket 8
    np.testing.assert_array_equal(mb.results["a"], np.arange(3) * 2.0)
    assert mb.results["a"].shape == (3,)  # unpadded result

    # deadline 0: every poll ships whatever is pending
    mb0 = MicroBatcher(score, buckets=(8,), max_batch=8,
                       batch_deadline_ms=0.0, clock=clk)
    mb0.submit("b", {"x": np.arange(2)})
    mb0.poll()
    assert mb0.shipped == [(2, 8)]


def test_deadline_is_oldest_request():
    """A young request cannot reset the clock for an old one."""
    score, _ = _counting_score()
    clk = FakeClock()
    mb = MicroBatcher(score, buckets=(8,), max_batch=8,
                      batch_deadline_ms=5.0, clock=clk)
    mb.submit("old", {"x": np.arange(2)})
    clk.advance(0.004)
    mb.submit("young", {"x": np.arange(2)})
    clk.advance(0.002)  # old is 6 ms stale, young only 2 ms
    mb.poll()
    assert mb.shipped == [(4, 8)]  # both ride the ship old triggered
    assert set(mb.results) == {"old", "young"}


def test_bucket_knob_changes_padding():
    """Same trace, different bucket sets -> different padded shapes (the
    [serving].buckets observability hook)."""
    trace = [(i, {"x": np.arange(5)}) for i in range(3)]
    for buckets, expect in [((8, 16), 8), ((6, 16), 6), ((16,), 16)]:
        score, shapes = _counting_score()
        mb = MicroBatcher(score, buckets=buckets, max_batch=buckets[-1],
                          batch_deadline_ms=0.0, clock=FakeClock())
        mb.run(trace)
        assert all(p == expect for _, p in mb.shipped)
        assert set(shapes) == {expect}


def test_validation():
    score, _ = _counting_score()
    with pytest.raises(ValueError, match="strictly increasing"):
        MicroBatcher(score, buckets=(8, 8), max_batch=8, batch_deadline_ms=1)
    with pytest.raises(ValueError, match="strictly increasing"):
        MicroBatcher(score, buckets=(), max_batch=8, batch_deadline_ms=1)
    with pytest.raises(ValueError, match="does not fit"):
        MicroBatcher(score, buckets=(8,), max_batch=16, batch_deadline_ms=1)
    mb = MicroBatcher(score, buckets=(8,), max_batch=8, batch_deadline_ms=1)
    with pytest.raises(ValueError, match="ragged columns"):
        mb.submit("r", {"x": np.arange(3), "y": np.arange(4)})
    with pytest.raises(ValueError, match="split it upstream"):
        mb.submit("r", {"x": np.arange(9)})


def test_latency_jsonl(tmp_path):
    """Per-request records + the p50/p99 summary land in metrics.jsonl."""
    logger = MetricLogger(tmp_path)
    score, _ = _counting_score()
    mb = MicroBatcher(score, buckets=(8,), max_batch=8, batch_deadline_ms=0.0,
                      logger=logger, clock=FakeClock())
    mb.run([(f"r{i}", {"x": np.arange(2)}) for i in range(4)])
    stats = mb.stats()
    logger.close()
    records = [json.loads(l) for l in
               (tmp_path / "metrics.jsonl").read_text().splitlines()]
    reqs = [r for r in records if r.get("event") == "serve_request"]
    assert [r["request"] for r in reqs] == ["r0", "r1", "r2", "r3"]
    assert all(r["rows"] == 2 and r["padded"] == 8 for r in reqs)
    # saturation observability: deadline 0 ships every request alone, so
    # the queue is empty after each ship and the 8-row program is 1/4 used
    assert all(r["queue_depth"] == 0 for r in reqs)
    assert all(r["batch_fill"] == 0.25 for r in reqs)
    summary = [r for r in records if r.get("event") == "serve_summary"]
    assert len(summary) == 1 and summary[0]["requests"] == 4
    assert stats["requests"] == 4 and stats["batches"] == 4
    assert stats["p99_ms"] >= stats["p50_ms"] >= 0.0


def test_queue_depth_counts_waiting_requests(tmp_path):
    """queue_depth is the number of requests still pending AFTER a ship —
    a saturated frontend shows a growing number in the latency JSONL."""
    logger = MetricLogger(tmp_path)
    score, _ = _counting_score()
    mb = MicroBatcher(score, buckets=(8,), max_batch=8, batch_deadline_ms=1e9,
                      logger=logger, clock=FakeClock())
    # 3 one-row stragglers queue, then a 5-row request fills the batch;
    # two more stragglers arrive before the drain ships them
    for i in range(3):
        mb.submit(f"s{i}", {"x": np.arange(1)})
    assert mb.shipped == []  # nothing full yet
    mb.submit("big", {"x": np.arange(5)})
    assert mb.shipped == [(8, 8)]
    mb.submit("late0", {"x": np.arange(2)})
    mb.submit("late1", {"x": np.arange(2)})
    mb.drain()
    logger.close()
    records = [json.loads(l) for l in
               (tmp_path / "metrics.jsonl").read_text().splitlines()]
    depth = {r["request"]: r["queue_depth"] for r in records
             if r.get("event") == "serve_request"}
    fill = {r["request"]: r["batch_fill"] for r in records
            if r.get("event") == "serve_request"}
    assert depth["s0"] == depth["big"] == 0  # full ship drained the queue
    assert depth["late0"] == depth["late1"] == 0
    assert fill["big"] == 1.0 and fill["late0"] == 0.5


def test_program_cache_invariant_is_a_runtime_assertion():
    """When the scorer exposes its compiled-program count, every ship
    checks it against len(buckets) — a shape leak fails LOUDLY in prod,
    not just in the test suite."""
    score, _ = _counting_score()
    mb = MicroBatcher(score, buckets=(8,), max_batch=8, batch_deadline_ms=0.0,
                      clock=FakeClock(), program_cache_size=lambda: 1)
    mb.run([("ok", {"x": np.arange(3)})])  # 1 program for 1 bucket: fine
    leaky = MicroBatcher(score, buckets=(8,), max_batch=8,
                         batch_deadline_ms=0.0, clock=FakeClock(),
                         program_cache_size=lambda: 2)
    with pytest.raises(RuntimeError, match="bounded-jit-cache"):
        leaky.submit("r", {"x": np.arange(8)})


# ------------------------------------------------- compile-count regression


@pytest.fixture(scope="module")
def scorer8(mesh8, tmp_path_factory):
    """A real sparse TwoTower scorer on the 8-device mesh (module-scoped:
    the compile-count test needs a FRESH jit cache, so it builds its own)."""
    from tests.test_serve import _export_sparse, _twotower_sparse
    from tdfo_tpu.serve.export import load_bundle
    from tdfo_tpu.serve.scoring import make_scorer

    coll, _, state = _twotower_sparse(mesh8)
    out = _export_sparse(tmp_path_factory.mktemp("bundle") / "b", coll, state)
    return make_scorer(load_bundle(out), mesh=mesh8)


def test_ragged_trace_compiles_at_most_len_buckets(scorer8):
    """40 requests of 17 distinct sizes pad to 3 buckets -> the scoring jit
    cache holds <= 3 programs.  THE compile-budget regression bar."""
    from tests.test_serve import _ctr_batch

    buckets = (8, 32, 64)
    assert scorer8.score_cache_size() == 0
    rng = np.random.default_rng(0)
    trace = [(i, _ctr_batch(rng, int(rng.integers(1, 65)), with_label=False))
             for i in range(40)]
    mb = MicroBatcher(scorer8.score, buckets=buckets, max_batch=64,
                      batch_deadline_ms=0.0, clock=FakeClock())
    mb.run(trace)
    assert len({r for r, _ in mb.shipped}) > len(buckets)  # genuinely ragged
    assert {p for _, p in mb.shipped} <= set(buckets)
    assert scorer8.score_cache_size() <= len(buckets)
    for i, batch in trace:
        assert mb.results[i].shape == (len(batch["user_id"]),)


def test_serving_programs_are_scatter_free(scorer8, mesh8):
    """No serving program may lower a scatter (CLAUDE.md: ~170 ns/row):
    scoring, both towers, corpus chunks, and sharded retrieval."""
    from tests.test_serve import SIZE_MAP, _ctr_batch
    from tdfo_tpu.serve.corpus import build_corpus, synthetic_item_features
    from tdfo_tpu.serve.retrieval import make_retrieval, mips_scores

    batch = _ctr_batch(np.random.default_rng(1), 8, with_label=False)
    lowered = scorer8._score.lower(dict(batch), *scorer8._params)
    assert "scatter" not in lowered.as_text()

    lowered = scorer8._user.lower(dict(batch), *scorer8._params)
    assert "scatter" not in lowered.as_text()
    lowered = scorer8._item.lower(dict(batch), *scorer8._params)
    assert "scatter" not in lowered.as_text()

    corpus = build_corpus(
        scorer8, synthetic_item_features(SIZE_MAP, 64, seed=0),
        corpus_batch=64, mesh=mesh8)
    queries = jnp.zeros((4, 16), jnp.float32)
    retrieve = make_retrieval(corpus, mesh=mesh8, top_k=10)
    text = retrieve.jitted.lower(
        queries, corpus.vectors, corpus.ids).as_text()
    assert "scatter" not in text
    assert "scatter" not in jax.jit(mips_scores).lower(
        queries, corpus.vectors).as_text()
    s, ids = retrieve(queries)
    assert s.shape == (4, 10) and ids.shape == (4, 10)


# ------------------------------------------- overload shedding + hot swap


def test_shed_past_deadline_first(tmp_path):
    """With max_queue set, an arriving request first evicts pending requests
    already past the batch deadline (oldest first) — they would miss their
    promised latency anyway — and only then displaces a survivor."""
    logger = MetricLogger(tmp_path)
    score, _ = _counting_score()
    clk = FakeClock()
    mb = MicroBatcher(score, buckets=(8,), max_batch=8, batch_deadline_ms=5.0,
                      max_queue=2, shed_policy="oldest", logger=logger,
                      clock=clk)
    mb.submit("a", {"x": np.arange(1)})
    mb.submit("b", {"x": np.arange(1)})
    clk.advance(0.006)  # both now past the 5 ms deadline
    mb.submit("c", {"x": np.arange(1)})
    # exactly enough stale evictions to admit c: a sheds, b survives (a
    # stale-but-queued request still ships on the next poll — shedding it
    # without need would discard accepted work)
    assert mb.shed == [("a", "past_deadline")]
    assert mb.results["a"] is None
    mb.submit("d", {"x": np.arange(1)})  # full again; stale b evicted
    assert mb.shed == [("a", "past_deadline"), ("b", "past_deadline")]
    assert mb.results["b"] is None
    mb.submit("e", {"x": np.arange(1)})  # nothing stale -> displace oldest
    assert mb.shed[-1] == ("c", "displaced")
    mb.drain()
    logger.close()
    assert mb.results["d"] is not None and mb.results["e"] is not None
    records = [json.loads(l) for l in
               (tmp_path / "metrics.jsonl").read_text().splitlines()]
    sheds = [r for r in records if r.get("event") == "serve_request"
             and r["outcome"] == "shed"]
    assert [(r["request"], r["shed_reason"]) for r in sheds] == [
        ("a", "past_deadline"), ("b", "past_deadline"), ("c", "displaced")]
    assert mb.stats()["shed"] == 3


def test_shed_policy_reject_bounces_arrival():
    """shed_policy='reject': when nothing pending is stale, the ARRIVING
    request bounces instead of displacing an accepted one."""
    score, _ = _counting_score()
    mb = MicroBatcher(score, buckets=(8,), max_batch=8, batch_deadline_ms=1e9,
                      max_queue=1, shed_policy="reject", clock=FakeClock())
    mb.submit("kept", {"x": np.arange(1)})
    mb.submit("bounced", {"x": np.arange(1)})
    assert mb.shed == [("bounced", "rejected")]
    assert mb.results["bounced"] is None
    mb.drain()
    np.testing.assert_array_equal(mb.results["kept"], np.arange(1) * 2.0)


def test_shed_knob_validation():
    score, _ = _counting_score()
    with pytest.raises(ValueError, match="max_queue"):
        MicroBatcher(score, buckets=(8,), max_batch=8, batch_deadline_ms=1,
                     max_queue=-1)
    with pytest.raises(ValueError, match="shed_policy"):
        MicroBatcher(score, buckets=(8,), max_batch=8, batch_deadline_ms=1,
                     shed_policy="drop-newest")


def test_swap_drains_on_old_scorer_and_drops_nothing(tmp_path):
    """Hot swap under live traffic: accepted in-flight requests drain on the
    OLD scorer (tagged under_swap), post-swap traffic scores on the new one,
    and no accepted request is dropped."""
    logger = MetricLogger(tmp_path)
    old, _ = _counting_score()        # x * 2
    new = lambda batch: np.asarray(batch["x"], np.float32) * 3.0  # noqa: E731
    clk = FakeClock()
    mb = MicroBatcher(old, buckets=(8,), max_batch=8, batch_deadline_ms=1e9,
                      logger=logger, clock=clk)
    mb.submit("inflight0", {"x": np.arange(2)})
    mb.submit("inflight1", {"x": np.arange(2)})
    swap_ms = mb.swap(new, version=7)
    assert swap_ms >= 0.0
    mb.submit("after", {"x": np.arange(2)})
    mb.drain()
    logger.close()
    # zero dropped: every accepted request has a real result
    np.testing.assert_array_equal(mb.results["inflight0"], np.arange(2) * 2.0)
    np.testing.assert_array_equal(mb.results["inflight1"], np.arange(2) * 2.0)
    np.testing.assert_array_equal(mb.results["after"], np.arange(2) * 3.0)
    assert mb.swaps == [{"version": 7, "from_version": None,
                         "drained_rows": 4, "swap_ms": swap_ms}]
    records = [json.loads(l) for l in
               (tmp_path / "metrics.jsonl").read_text().splitlines()]
    by_req = {r["request"]: r for r in records
              if r.get("event") == "serve_request"}
    assert by_req["inflight0"]["under_swap"] is True
    assert by_req["after"]["under_swap"] is False
    assert by_req["after"]["version"] == 7
    swaps = [r for r in records if r.get("event") == "serve_swap"]
    assert len(swaps) == 1 and swaps[0]["drained_rows"] == 4
    stats = mb.stats()
    assert stats["swaps"] == 1
    # drain happened under a fake clock: the p99-under-swap bound is exact
    assert stats["p99_under_swap_ms"] == 0.0


def test_swap_resets_program_cache_probe():
    """The old scorer's program-cache probe is stale after a flip; keeping it
    would fail the bounded-jit-cache assertion against the WRONG scorer."""
    score, _ = _counting_score()
    mb = MicroBatcher(score, buckets=(8,), max_batch=8, batch_deadline_ms=0.0,
                      clock=FakeClock(), program_cache_size=lambda: 99)
    mb.swap(score, version=1)  # no probe passed -> probe cleared
    mb.run([("r", {"x": np.arange(3)})])  # would raise with the stale probe
    np.testing.assert_array_equal(mb.results["r"], np.arange(3) * 2.0)
    leaky = MicroBatcher(score, buckets=(8,), max_batch=8,
                         batch_deadline_ms=0.0, clock=FakeClock())
    leaky.swap(score, version=1, program_cache_size=lambda: 2)
    with pytest.raises(RuntimeError, match="bounded-jit-cache"):
        leaky.submit("r", {"x": np.arange(8)})


def test_slow_score_fault_and_serve_heartbeat(tmp_path):
    """[faults] slow_score_ms wedges the scorer deterministically; the
    frontend beats the serving watchdog per shipped batch, so a wedged
    scorer trips the SAME stall machinery as a wedged train step."""
    import time as _time

    from tdfo_tpu.obs.watchdog import StallWatchdog
    from tdfo_tpu.utils import faults
    from tdfo_tpu.utils.faults import FaultSpec

    wd = StallWatchdog(tmp_path / "hb.jsonl", 60.0, label="serve",
                       clock=lambda: 0.0)
    score, _ = _counting_score()
    mb = MicroBatcher(score, buckets=(8,), max_batch=8, batch_deadline_ms=0.0,
                      clock=FakeClock(), watchdog=wd)
    try:
        faults.configure(FaultSpec(slow_score_ms=30.0))
        t0 = _time.perf_counter()
        mb.run([("r", {"x": np.arange(2)})])
        elapsed_ms = (_time.perf_counter() - t0) * 1000.0
    finally:
        faults.configure(None)
    assert elapsed_ms >= 30.0  # the injected stall really happened
    np.testing.assert_array_equal(mb.results["r"], np.arange(2) * 2.0)
    wd.check()
    hb = [json.loads(l) for l in
          (tmp_path / "hb.jsonl").read_text().splitlines()]
    assert hb[-1]["label"] == "serve" and hb[-1]["last_step"] == 1
