"""TFRecord codec: crc vectors, proto roundtrip, cross-validation against TF.

TensorFlow happens to be present in this image, so the wire format is checked
against the real reader/writer — the framework itself never imports TF.
"""

import numpy as np
import pytest

from tdfo_tpu.data.tfrecord import (
    _crc32c_py,
    decode_example,
    encode_example,
    read_size_sidecar,
    read_tfrecord_columns,
    read_tfrecord_records,
    write_tfrecord_file,
    write_tfrecord_shards,
)
from tdfo_tpu.native import load_native, native_available


class TestCrc32c:
    # RFC 3720 test vectors
    VECTORS = [
        (b"", 0x00000000),
        (b"a", 0xC1D04330),
        (b"123456789", 0xE3069283),
        (bytes(32), 0x8A9136AA),
        (bytes([0xFF] * 32), 0x62A8AB43),
    ]

    def test_python_crc_vectors(self):
        for data, want in self.VECTORS:
            assert _crc32c_py(data) == want, data

    def test_native_crc_matches_python(self):
        lib = load_native()
        if lib is None:
            pytest.skip("native toolchain unavailable")
        import ctypes

        rng = np.random.default_rng(0)
        for n in (1, 7, 8, 9, 63, 64, 1000):
            data = rng.integers(0, 256, n, dtype=np.uint8).tobytes()
            buf = (ctypes.c_uint8 * n).from_buffer_copy(data)
            assert lib.tdfo_crc32c(buf, n) == _crc32c_py(data)


class TestExampleProto:
    def test_roundtrip(self):
        row = {
            "user_id": 42,
            "score": 0.5,
            "seq": np.asarray([1, 2, 3], np.int64),
            "floats": np.asarray([0.25, -1.5], np.float32),
            "name": b"abc",
        }
        out = decode_example(encode_example(row))
        assert out["user_id"].tolist() == [42]
        assert out["score"].astype(float).tolist() == [0.5]
        assert out["seq"].tolist() == [1, 2, 3]
        np.testing.assert_allclose(out["floats"], [0.25, -1.5])
        assert out["name"].tolist() == [b"abc"]

    def test_negative_ints(self):
        out = decode_example(encode_example({"x": np.asarray([-5, 3], np.int64)}))
        assert out["x"].tolist() == [-5, 3]

    def test_tf_can_parse_ours(self):
        tf = pytest.importorskip("tensorflow")
        payload = encode_example({"a": 7, "b": [1.0, 2.0], "c": b"hi"})
        ex = tf.train.Example.FromString(payload)
        assert ex.features.feature["a"].int64_list.value[:] == [7]
        np.testing.assert_allclose(ex.features.feature["b"].float_list.value[:], [1.0, 2.0])
        assert ex.features.feature["c"].bytes_list.value[:] == [b"hi"]

    def test_we_can_parse_tf(self):
        tf = pytest.importorskip("tensorflow")
        ex = tf.train.Example(
            features=tf.train.Features(
                feature={
                    "i": tf.train.Feature(int64_list=tf.train.Int64List(value=[3, -4])),
                    "f": tf.train.Feature(float_list=tf.train.FloatList(value=[0.5])),
                }
            )
        )
        out = decode_example(ex.SerializeToString())
        assert out["i"].tolist() == [3, -4]
        np.testing.assert_allclose(out["f"], [0.5])


class TestTFRecordFraming:
    def test_roundtrip_plain_and_gzip(self, tmp_path):
        recs = [b"hello", b"", b"world" * 100]
        for comp in (None, "GZIP"):
            p = tmp_path / f"t_{comp}.tfrecord"
            write_tfrecord_file(p, recs, comp)
            assert list(read_tfrecord_records(p, comp)) == recs

    def test_tf_reads_our_files(self, tmp_path):
        tf = pytest.importorskip("tensorflow")
        p = tmp_path / "ours.tfrecord"
        payloads = [encode_example({"x": i}) for i in range(5)]
        write_tfrecord_file(p, payloads, "GZIP")
        ds = tf.data.TFRecordDataset(str(p), compression_type="GZIP")
        got = [r.numpy() for r in ds]
        assert got == payloads

    def test_we_read_tf_files(self, tmp_path):
        tf = pytest.importorskip("tensorflow")
        p = str(tmp_path / "tf.tfrecord")
        opts = tf.io.TFRecordOptions(compression_type="GZIP")
        with tf.io.TFRecordWriter(p, opts) as w:
            for i in range(3):
                w.write(encode_example({"x": i}))
        got = [decode_example(r)["x"].tolist() for r in read_tfrecord_records(p)]
        assert got == [[0], [1], [2]]

    def test_corruption_detected(self, tmp_path):
        p = tmp_path / "c.tfrecord"
        write_tfrecord_file(p, [b"payload"], None)
        raw = bytearray(p.read_bytes())
        raw[14] ^= 0xFF  # flip a payload byte
        p.write_bytes(bytes(raw))
        with pytest.raises(IOError, match="crc mismatch"):
            list(read_tfrecord_records(p, None))


class TestColumnarShards:
    def test_shards_and_sidecar(self, tmp_path):
        cols = {
            "user_id": np.arange(20, dtype=np.int64),
            "label": (np.arange(20) % 2).astype(np.int64),
            "rating": np.linspace(0, 1, 20).astype(np.float32),
        }
        paths = write_tfrecord_shards(cols, tmp_path, "train", file_num=4)
        assert len(paths) == 4
        assert read_size_sidecar(tmp_path, "train") == 20
        back = read_tfrecord_columns(paths)
        assert sorted(back["user_id"].tolist()) == list(range(20))
        np.testing.assert_allclose(np.sort(back["rating"]), np.sort(cols["rating"]), rtol=1e-6)


class TestNativeShuffle:
    def test_permutation_exact(self):
        lib = load_native()
        if lib is None:
            pytest.skip("native toolchain unavailable")
        import ctypes

        rows = np.arange(1000, dtype=np.int64).reshape(250, 4).copy()
        before = rows.copy()
        buf = rows.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))
        lib.tdfo_shuffle_rows(buf, 250, rows.strides[0], 1234)
        # same multiset of rows, different order
        assert sorted(map(tuple, rows)) == sorted(map(tuple, before))
        assert not np.array_equal(rows, before)
        # deterministic for a fixed seed
        rows2 = before.copy()
        lib.tdfo_shuffle_rows(rows2.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
                              250, rows2.strides[0], 1234)
        np.testing.assert_array_equal(rows, rows2)


def test_native_builds():
    assert native_available(), "g++ toolchain is in this image; build must work"


class TestTFRecordStream:
    @pytest.fixture(scope="class")
    def tfr_dir(self, tmp_path_factory):
        from tdfo_tpu.data.ctr_preprocessing import run_ctr_preprocessing
        from tdfo_tpu.data.synthetic import write_synthetic_goodreads

        d = tmp_path_factory.mktemp("gr_tfr")
        write_synthetic_goodreads(d, n_users=60, n_books=100,
                                  interactions_per_user=(12, 30), seed=5)
        size_map = run_ctr_preprocessing(d, write_format="tfrecord", file_num=4)
        return d, size_map

    def test_stream_reads_all_rows(self, tfr_dir):
        from tdfo_tpu.data.loader import TFRecordStream, resolve_files

        d, _ = tfr_dir
        files = resolve_files(d, "tfrecord/train_part_*.tfrecord")
        assert len(files) == 4
        stream = TFRecordStream(files, batch_size=32, buffer_size=64,
                                drop_last=False, process_index=0, process_count=1)
        rows = sum(len(b["user_id"]) for b in stream)
        assert rows == read_size_sidecar(d / "tfrecord", "train")
        b = next(iter(stream))
        assert {"user_id", "item_id", "label", "avg_rating"} <= set(b)

    def test_missing_sidecar_scan_is_cached(self, tmp_path):
        """With no row-count sidecar the loader falls back to a full gzip
        scan — ONCE: the counts are cached back to the sidecar so later
        epoch-budget computations (and other runs) never rescan."""
        import json as _json

        from tdfo_tpu.data.ctr_preprocessing import run_ctr_preprocessing
        from tdfo_tpu.data.loader import TFRecordStream, resolve_files
        from tdfo_tpu.data.synthetic import write_synthetic_goodreads

        d = tmp_path / "gr"
        write_synthetic_goodreads(d, n_users=40, n_books=60,
                                  interactions_per_user=(8, 16), seed=3)
        run_ctr_preprocessing(d, write_format="tfrecord", file_num=2)
        sidecar = d / "tfrecord" / "train_data_size.json"
        with open(sidecar) as f:
            full = _json.load(f)
        sidecar.unlink()  # simulate a dataset delivered without the sidecar

        files = resolve_files(d, "tfrecord/train_part_*.tfrecord")
        stream = TFRecordStream(files, batch_size=16, buffer_size=32,
                                drop_last=True, process_index=0,
                                process_count=1)
        n1 = stream.max_batches_per_host()  # triggers the fallback scans
        assert n1 > 0
        with open(sidecar) as f:
            doc = _json.load(f)
        assert doc["shard_sizes"] == full["shard_sizes"]
        assert "data_size" not in doc  # partial totals never fabricated

        # a fresh stream reads the cached counts (same budget, no rescan)
        stream2 = TFRecordStream(files, batch_size=16, buffer_size=32,
                                 drop_last=True, process_index=0,
                                 process_count=1)
        assert stream2.max_batches_per_host() == n1

    def test_stream_trains_twotower(self, tfr_dir):
        import jax
        import jax.numpy as jnp
        import optax
        from tdfo_tpu.data.loader import TFRecordStream, resolve_files
        from tdfo_tpu.models.twotower import init_twotower
        from tdfo_tpu.train.state import TrainState, make_adamw
        from tdfo_tpu.train.step import make_train_step

        d, size_map = tfr_dir
        files = resolve_files(d, "tfrecord/train_part_*.tfrecord")
        model, params = init_twotower(jax.random.key(0), size_map, 8)
        state = TrainState.create(apply_fn=model.apply, params=params,
                                  tx=make_adamw(3e-3, 1e-4))
        step = make_train_step(donate_state=False)
        losses = []
        for b in TFRecordStream(files, batch_size=64, buffer_size=256,
                                drop_last=True, process_index=0, process_count=1):
            batch = {k: jnp.asarray(v) for k, v in b.items()}
            batch["label"] = batch["label"].astype(jnp.float32)
            state, loss = step(state, batch)
            losses.append(float(loss))
        assert losses and np.isfinite(losses).all()


def test_shard_sizes_sidecar_many_shards(tmp_path):
    # regression: >= 10 shards used to misorder row counts lexicographically
    from tdfo_tpu.data.loader import TFRecordStream
    from tdfo_tpu.data.tfrecord import read_shard_sizes

    cols = {"x": np.arange(100, dtype=np.int64)}
    paths = write_tfrecord_shards(cols, tmp_path, "train", file_num=16,
                                  compression=None)
    sizes = read_shard_sizes(tmp_path, "train")
    assert sum(sizes.values()) == 100
    stream = TFRecordStream([str(p) for p in paths], batch_size=1,
                            compression=None, drop_last=False,
                            process_index=0, process_count=1)
    for p in paths:
        assert stream._file_row_count(str(p)) == sizes[p.name]


def test_encode_empty_float_column_keeps_dtype():
    # regression: empty sequences fell into the int64 branch
    rows = [
        decode_example(encode_example({"f": np.asarray([], np.float32)})),
        decode_example(encode_example({"f": np.asarray([1.5], np.float32)})),
    ]
    assert rows[0]["f"].dtype == np.float32
    assert rows[1]["f"].dtype == np.float32


def test_trainer_trains_on_tfrecord(tmp_path):
    from tdfo_tpu.core.config import read_configs
    from tdfo_tpu.data.ctr_preprocessing import run_ctr_preprocessing
    from tdfo_tpu.data.synthetic import write_synthetic_goodreads
    from tdfo_tpu.train.trainer import Trainer

    d = tmp_path / "gr"
    write_synthetic_goodreads(d, n_users=60, n_books=100,
                              interactions_per_user=(12, 30), seed=6)
    size_map = run_ctr_preprocessing(d, write_format="tfrecord", file_num=4)
    cfg = read_configs(
        None, data_dir=d, model="twotower", write_format="tfrecord",
        n_epochs=1, learning_rate=3e-3, embed_dim=8,
        per_device_train_batch_size=16, per_device_eval_batch_size=16,
        shuffle_buffer_size=500, log_every_n_steps=1000, size_map=size_map,
    )
    metrics = Trainer(cfg, log_dir=tmp_path / "logs").fit()
    assert 0.0 <= metrics["auc"] <= 1.0 and np.isfinite(metrics["eval_loss"])
