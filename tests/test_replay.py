"""Crash-safe request-log replay (``tdfo_tpu/data/replay.py``): the
writer/reader contract that makes the online loop exactly-once.

Every fault here is REAL file damage produced by the deterministic
``[faults]`` triggers (``utils/faults.py``) or by hand: torn tails from a
mid-record truncation, duplicated seqs from a retried append, sealed lines
of garbage, digest-violating bit flips.  The assertions are the replay
contract: no record trains twice, none is skipped, torn tails wait instead
of erroring, and damage that cannot be waited out refuses loudly.

Also hosts the log-sink rotation regression tests (``utils/logrotate.py``:
``metrics.jsonl`` / ``retries.jsonl``) and the frontend's request-log
wiring (``MicroBatcher`` + ``RequestLog``) — the writer half of the loop.
"""

import json
import os
from pathlib import Path

import numpy as np
import pytest

from tdfo_tpu.data.replay import (
    REPLAY_SCHEMA_VERSION,
    MergedReplayConsumer,
    ReplayConsumer,
    ReplayError,
    ReplayLagError,
    RequestLog,
    make_replay_consumer,
    replica_log_dir,
)
from tdfo_tpu.utils import faults
from tdfo_tpu.utils.faults import FaultSpec

SCHEMA = {"x": (np.int32, ()), "y": (np.float32, ()),
          "label": (np.int8, ())}


@pytest.fixture(autouse=True)
def _clear_faults():
    yield
    faults.configure(None)


def _record(rows: int, x0: int = 0) -> dict:
    return {
        "event": "serve_request", "request": f"r{x0}", "rows": rows,
        "outcome": "ok",
        "features": {"x": list(range(x0, x0 + rows)),
                     "y": [0.5] * rows, "label": [1] * rows},
    }


def _write(root: Path, n_records: int, rows: int = 3,
           segment_bytes: int = 0) -> RequestLog:
    log = RequestLog(root, segment_bytes=segment_bytes)
    for i in range(n_records):
        log.append(_record(rows, x0=i * rows))
    return log


def _drain_x(consumer: ReplayConsumer) -> list[int]:
    xs: list[int] = []
    while True:
        out = consumer.next_batch()
        if out is None:
            return xs
        batch, consumed = out
        assert consumed and all(b > a for _, a, b in consumed)
        xs += batch["x"].tolist()


# ----------------------------------------------------------------- roundtrip


def test_roundtrip_exact_batches(tmp_path):
    log = _write(tmp_path / "rl", n_records=10, rows=3)
    log.close()
    c = ReplayConsumer(tmp_path / "rl", schema=SCHEMA, batch_size=6)
    xs = _drain_x(c)
    # 30 rows -> 5 full batches; order preserved, nothing duplicated
    assert xs == list(range(30))
    cur = c.cursor()
    assert cur["records"] == 10 and cur["bad"] == 0 and cur["dup"] == 0
    assert c.counters()["replay/records"] == 10.0
    assert c.counters()["replay/lag"] == 0.0


def test_partial_batch_never_commits(tmp_path):
    log = _write(tmp_path / "rl", n_records=2, rows=3)
    log.close()
    c = ReplayConsumer(tmp_path / "rl", schema=SCHEMA, batch_size=4)
    batch, _ = c.next_batch()
    assert batch["x"].tolist() == [0, 1, 2, 3]
    before = c.cursor()
    assert c.next_batch() is None  # 2 rows left < batch_size
    assert c.cursor() == before  # all-or-nothing: no partial commit


def test_mid_record_cursor_resume(tmp_path):
    """A cursor persisted at a mid-record batch boundary resumes at the
    exact ROW — the checkpoint-sidecar kill/restart shape."""
    log = _write(tmp_path / "rl", n_records=4, rows=5)
    log.close()
    c1 = ReplayConsumer(tmp_path / "rl", schema=SCHEMA, batch_size=3)
    first, _ = c1.next_batch()  # rows 0-2 of record 1 (mid-record)
    saved = c1.cursor()
    assert saved["row"] == 3
    c2 = ReplayConsumer(tmp_path / "rl", schema=SCHEMA, batch_size=3,
                        cursor=saved)
    xs = first["x"].tolist() + _drain_x(c2)
    assert xs == list(range(18))  # 20 rows, tail 2 wait for more data


def test_unknown_cursor_keys_rejected(tmp_path):
    with pytest.raises(ValueError, match="unknown replay cursor"):
        ReplayConsumer(tmp_path, schema=SCHEMA, batch_size=4,
                       cursor={"segment": 0, "bogus": 1})


def test_non_scalar_schema_rejected(tmp_path):
    # fixed-width 1-D vectors (seq eval windows / candidate panels) are
    # legal since the seq family replays; ragged/higher-rank still refuse
    ReplayConsumer(tmp_path, schema={"seq_col": (np.int32, (16,))},
                   batch_size=4)
    with pytest.raises(ValueError, match="fixed-width"):
        ReplayConsumer(tmp_path, schema={"m": (np.int32, (2, 3))},
                       batch_size=4)
    with pytest.raises(ValueError, match="fixed-width"):
        ReplayConsumer(tmp_path, schema={"z": (np.int32, (0,))},
                       batch_size=4)


# ------------------------------------------------------------------ rotation


def test_rotation_seals_complete_segments(tmp_path):
    root = tmp_path / "rl"
    log = _write(root, n_records=12, rows=3, segment_bytes=256)
    assert log.active_segment >= 2  # rotation actually happened
    segs = sorted(root.glob("requests-*.jsonl"))
    for seg in segs[:-1]:
        # every finished segment is sealed, every line complete JSON
        seal = json.loads(
            (root / seg.name.replace(".jsonl", ".seal.json")).read_text())
        data = seg.read_bytes()
        assert data.endswith(b"\n") and len(data) == seal["bytes"]
        for line in data.splitlines():
            assert json.loads(line)["schema_version"] == REPLAY_SCHEMA_VERSION
    log.close()
    c = ReplayConsumer(root, schema=SCHEMA, batch_size=6)
    assert _drain_x(c) == list(range(36))  # boundary-crossing reads


def test_writer_reopen_resumes_seq_after_seal(tmp_path):
    root = tmp_path / "rl"
    log = _write(root, n_records=4, rows=2)
    log.seal_active()
    last = log.last_seq
    log.close()
    log2 = RequestLog(root)  # crashed-between-seal-and-successor reopen
    assert log2.append(_record(2, x0=8)) == last + 1
    log2.close()
    c = ReplayConsumer(root, schema=SCHEMA, batch_size=2)
    assert _drain_x(c) == list(range(10))


def test_writer_reopen_truncates_torn_tail(tmp_path):
    root = tmp_path / "rl"
    log = _write(root, n_records=3, rows=2)
    log.close()
    seg = root / "requests-000000.jsonl"
    with open(seg, "ab") as f:
        f.write(b'{"seq": 99, "torn')  # crashed writer: no newline
    c = ReplayConsumer(root, schema=SCHEMA, batch_size=2)
    assert _drain_x(c) == list(range(6))  # reader stops BEFORE the tear
    log2 = RequestLog(root)
    assert log2.last_seq == 3  # torn line contributes no seq
    log2.append(_record(2, x0=6))
    log2.close()
    assert not seg.read_bytes().rstrip(b"\n").endswith(b"torn")
    assert _drain_x(c) == list(range(6, 8))  # continuation, no dup/loss


# ------------------------------------------------------------ fault triggers


def test_truncate_fault_torn_tail_recovery(tmp_path):
    root = tmp_path / "rl"
    log = _write(root, n_records=2, rows=2)
    size = (root / "requests-000000.jsonl").stat().st_size
    faults.configure(FaultSpec(truncate_log_at_byte=size + 7), workdir=tmp_path)
    log.append(_record(2, x0=4))  # torn back to mid-record
    log.close()
    assert (root / "requests-000000.jsonl").stat().st_size == size + 7
    c = ReplayConsumer(root, schema=SCHEMA, batch_size=2)
    assert _drain_x(c) == list(range(4))  # stops at the last-good offset
    log2 = RequestLog(root)  # writer recovery truncates the fragment
    log2.append(_record(2, x0=4))  # the "retried" append
    log2.close()
    assert _drain_x(c) == [4, 5]
    assert c.cursor()["records"] == 3


def test_dup_record_fault_is_deduped(tmp_path):
    root = tmp_path / "rl"
    faults.configure(FaultSpec(dup_record_nth=2), workdir=tmp_path)
    log = _write(root, n_records=4, rows=2)
    log.close()
    # the duplicate line is REALLY on disk
    lines = (root / "requests-000000.jsonl").read_bytes().splitlines()
    assert len(lines) == 5
    c = ReplayConsumer(root, schema=SCHEMA, batch_size=2)
    assert _drain_x(c) == list(range(8))  # each seq trains exactly once
    assert c.cursor()["dup"] == 1
    assert c.counters()["replay/dup"] == 1.0


def test_corrupt_record_fault_quarantined(tmp_path):
    root = tmp_path / "rl"
    faults.configure(FaultSpec(corrupt_record_nth=2), workdir=tmp_path)
    log = _write(root, n_records=4, rows=2)
    log.close()
    c = ReplayConsumer(root, schema=SCHEMA, batch_size=2,
                       max_bad_records=1)
    xs = _drain_x(c)
    assert xs == [0, 1] + list(range(4, 8))  # record 2's rows quarantined
    assert c.cursor()["bad"] == 1


def test_corrupt_record_exceeds_quarantine_budget(tmp_path):
    root = tmp_path / "rl"
    faults.configure(FaultSpec(corrupt_record_nth=1), workdir=tmp_path)
    log = _write(root, n_records=2, rows=2)
    log.close()
    c = ReplayConsumer(root, schema=SCHEMA, batch_size=2)  # budget 0
    with pytest.raises(ReplayError, match="max_bad_records"):
        c.next_batch()


def test_kill_during_replay_fires_at_commit(tmp_path, monkeypatch):
    fired = {}

    def fake_exit(code):
        fired["code"] = code
        raise SystemExit(code)

    monkeypatch.setattr(faults.os, "_exit", fake_exit)
    root = tmp_path / "rl"
    log = _write(root, n_records=4, rows=2)
    log.close()
    faults.configure(FaultSpec(kill_during_replay=2), workdir=tmp_path)
    c = ReplayConsumer(root, schema=SCHEMA, batch_size=2)
    assert c.next_batch() is not None  # record 1 commits, below threshold
    with pytest.raises(SystemExit):
        c.next_batch()  # record 2 commits -> kill fires AFTER the commit
    assert fired["code"] == faults.KILL_EXIT_CODE
    assert (tmp_path / "faults_replay_kill.marker").exists()
    assert c.cursor()["records"] == 2  # the commit preceded the kill
    # the marker disarms the one-shot: the restart path reads on
    c2 = ReplayConsumer(root, schema=SCHEMA, batch_size=2,
                        cursor=c.cursor())
    assert _drain_x(c2) == list(range(4, 8))


# ------------------------------------------------------------------- damage


def test_sealed_digest_mismatch_refused(tmp_path):
    root = tmp_path / "rl"
    log = _write(root, n_records=6, rows=2, segment_bytes=128)
    log.close()
    seg = root / "requests-000000.jsonl"
    data = bytearray(seg.read_bytes())
    data[5] ^= 0x40  # in-place bit flip: same length, wrong digest
    seg.write_bytes(bytes(data))
    c = ReplayConsumer(root, schema=SCHEMA, batch_size=2)
    with pytest.raises(ReplayError, match="digest mismatch"):
        c.next_batch()


def test_unsealed_segment_with_successor_refused(tmp_path):
    root = tmp_path / "rl"
    log = _write(root, n_records=8, rows=2, segment_bytes=128)
    log.close()
    seals = sorted(root.glob("*.seal.json"))
    assert seals
    os.unlink(seals[0])
    c = ReplayConsumer(root, schema=SCHEMA, batch_size=2)
    with pytest.raises(ReplayError, match="no seal"):
        c.next_batch()


def test_schema_violations_quarantined(tmp_path):
    root = tmp_path / "rl"
    log = RequestLog(root)
    log.append(_record(2, x0=0))
    bad = _record(2, x0=2)
    bad["features"]["x"] = [2]  # wrong length vs rows
    log.append(bad)
    wrong_version = _record(2, x0=4)
    log.append(wrong_version)
    log.append(_record(2, x0=6))  # good tail: the commit that seals the audit
    log.close()
    # rewrite record 3's schema_version on disk (a future-writer artifact)
    seg = root / "requests-000000.jsonl"
    lines = seg.read_bytes().splitlines()
    rec = json.loads(lines[2])
    rec["schema_version"] = REPLAY_SCHEMA_VERSION + 1
    lines[2] = json.dumps(rec).encode()
    seg.write_bytes(b"\n".join(lines) + b"\n")
    c = ReplayConsumer(root, schema=SCHEMA, batch_size=2,
                       max_bad_records=2)
    assert _drain_x(c) == [0, 1, 6, 7]  # both damaged records quarantined
    assert c.cursor()["bad"] == 2


def test_shed_and_swap_records_are_skipped(tmp_path):
    root = tmp_path / "rl"
    log = RequestLog(root)
    log.append(_record(2, x0=0))
    log.append({"event": "serve_request", "request": "s", "rows": 3,
                "outcome": "shed", "shed_reason": "displaced"})
    log.append({"event": "serve_swap", "version": 1, "from_version": 0})
    log.append(_record(2, x0=2))
    log.close()
    c = ReplayConsumer(root, schema=SCHEMA, batch_size=4)
    assert _drain_x(c) == [0, 1, 2, 3]
    assert c.cursor()["skipped"] == 2


# -------------------------------------------------------------- backpressure


def test_backpressure_fail_policy(tmp_path):
    root = tmp_path / "rl"
    log = _write(root, n_records=6, rows=2)
    log.close()
    c = ReplayConsumer(root, schema=SCHEMA, batch_size=2,
                       max_lag_records=3, lag_policy="fail")
    assert c.lag() == 6
    with pytest.raises(ReplayLagError, match="records behind"):
        c.check_backpressure()


def test_backpressure_skip_policy_drops_to_bound(tmp_path):
    root = tmp_path / "rl"
    log = _write(root, n_records=6, rows=2)
    log.close()
    c = ReplayConsumer(root, schema=SCHEMA, batch_size=2,
                       max_lag_records=3, lag_policy="skip")
    assert c.check_backpressure() == 3
    assert c.cursor()["skipped"] == 3
    # skip-to-fresh: training resumes at the surviving tail, dedup intact
    assert _drain_x(c) == list(range(6, 12))
    assert c.cursor()["records"] == 3


def test_backpressure_within_bound_is_noop(tmp_path):
    root = tmp_path / "rl"
    log = _write(root, n_records=2, rows=2)
    log.close()
    c = ReplayConsumer(root, schema=SCHEMA, batch_size=2,
                       max_lag_records=8, lag_policy="fail")
    assert c.check_backpressure() == 2
    assert c.cursor()["skipped"] == 0


# ------------------------------------------------- shadow peek + retention


def test_peek_batches_commits_nothing(tmp_path):
    log = _write(tmp_path / "rl", n_records=6, rows=3)
    log.close()
    c = ReplayConsumer(tmp_path / "rl", schema=SCHEMA, batch_size=6)
    before = c.cursor()
    peeked = c.peek_batches(2)
    assert [b["x"].tolist() for b in peeked] == [[0, 1, 2, 3, 4, 5],
                                                [6, 7, 8, 9, 10, 11]]
    assert c.cursor() == before  # the shadow slice moved NOTHING
    # the very same rows then train normally — progressive validation
    batch, _ = c.next_batch()
    assert batch["x"].tolist() == peeked[0]["x"].tolist()


def test_peek_batches_short_log_returns_partial(tmp_path):
    log = _write(tmp_path / "rl", n_records=2, rows=3)
    log.close()
    c = ReplayConsumer(tmp_path / "rl", schema=SCHEMA, batch_size=6)
    before = c.cursor()
    assert len(c.peek_batches(3)) == 1  # only one full batch exists
    assert c.cursor() == before


def test_gc_consumed_segments_deletes_only_behind_cursor(tmp_path):
    root = tmp_path / "rl"
    log = _write(root, n_records=12, rows=3, segment_bytes=256)
    n_segs = log.active_segment + 1
    assert n_segs >= 3
    log.close()
    c = ReplayConsumer(root, schema=SCHEMA, batch_size=6)
    _drain_x(c)
    final = c.cursor()["segment"]
    deleted = c.gc_consumed_segments(keep=1)
    assert deleted == list(range(final - 1))  # newest consumed one kept
    for i in deleted:
        assert not (root / f"requests-{i:06d}.jsonl").exists()
        assert not (root / f"requests-{i:06d}.seal.json").exists()
    # idempotent: nothing left below the retention line
    assert c.gc_consumed_segments(keep=1) == []
    # the survivors still replay from a persisted cursor (restart shape)
    c2 = ReplayConsumer(root, schema=SCHEMA, batch_size=6, cursor=c.cursor())
    assert c2.next_batch() is None  # fully drained, no refusal


def test_gc_refuses_candidate_segment(tmp_path):
    root = tmp_path / "rl"
    log = _write(root, n_records=12, rows=3, segment_bytes=256)
    log.close()
    c = ReplayConsumer(root, schema=SCHEMA, batch_size=6)
    batch, _ = c.next_batch()  # cursor still inside segment 0
    with pytest.raises(ValueError, match="cursor still points into"):
        c.gc_segments(c.cursor()["segment"])
    assert c.gc_consumed_segments() == []  # nothing strictly behind yet
    assert (root / "requests-000000.jsonl").exists()


def test_gc_refuses_missing_seal_below_cursor(tmp_path):
    root = tmp_path / "rl"
    log = _write(root, n_records=12, rows=3, segment_bytes=256)
    log.close()
    c = ReplayConsumer(root, schema=SCHEMA, batch_size=6)
    _drain_x(c)
    os.unlink(sorted(root.glob("*.seal.json"))[0])
    with pytest.raises(ValueError, match="no seal sidecar"):
        c.gc_consumed_segments()


# -------------------------------------------------------------- fleet merge


def _write_fleet(root: Path, n_records: int = 6, rows: int = 3,
                 segment_bytes: int = 0) -> None:
    """Two replica logs with disjoint row ids: replica 0 counts from 0,
    replica 1 from 1000 — so provenance survives into the drained rows."""
    for rid, base in ((0, 0), (1, 1000)):
        log = RequestLog(replica_log_dir(root, rid),
                         segment_bytes=segment_bytes)
        for i in range(n_records):
            log.append(_record(rows, x0=base + i * rows))
        log.close()


def _drain_merged(c: MergedReplayConsumer) -> tuple[list[int], list[tuple]]:
    xs, spans = [], []
    while True:
        out = c.next_batch()
        if out is None:
            return xs, spans
        batch, consumed = out
        assert consumed and all(b > a for _, _, a, b in consumed)
        spans += [tuple(s) for s in consumed]
        xs += batch["x"].tolist()


def test_merged_round_robin_exactly_once(tmp_path):
    _write_fleet(tmp_path / "rl", n_records=4, rows=3)
    c = make_replay_consumer(tmp_path / "rl", schema=SCHEMA, batch_size=6)
    assert isinstance(c, MergedReplayConsumer)
    xs, spans = _drain_merged(c)
    # record-level round-robin: r0's record, then r1's, alternating
    assert xs == [0, 1, 2, 1000, 1001, 1002, 3, 4, 5, 1003, 1004, 1005,
                  6, 7, 8, 1006, 1007, 1008, 9, 10, 11, 1009, 1010, 1011]
    # every (replica, seq) span tiles its record exactly once
    assert sorted(spans) == [(rid, seq, 0, 3)
                             for rid in (0, 1) for seq in (1, 2, 3, 4)]
    cur = c.cursor()
    assert set(cur) == {"rr", "replicas"}
    assert set(cur["replicas"]) == {"0", "1"}
    assert c.counters()["replay/records"] == 8.0


def test_merged_mid_record_cursor_resume(tmp_path):
    """A merged cursor persisted at a batch boundary that splits a record
    resumes at the exact row on the exact replica."""
    _write_fleet(tmp_path / "rl", n_records=3, rows=5)
    c1 = MergedReplayConsumer(tmp_path / "rl", schema=SCHEMA, batch_size=4)
    first, _ = c1.next_batch()  # splits replica 0's first record
    saved = json.loads(json.dumps(c1.cursor()))  # checkpoint round-trip
    c2 = MergedReplayConsumer(tmp_path / "rl", schema=SCHEMA, batch_size=4,
                              cursor=saved)
    resumed, _ = c2.next_batch()
    fresh = MergedReplayConsumer(tmp_path / "rl", schema=SCHEMA,
                                 batch_size=4)
    ref1, _ = fresh.next_batch()
    ref2, _ = fresh.next_batch()
    assert first["x"].tolist() == ref1["x"].tolist()
    assert resumed["x"].tolist() == ref2["x"].tolist()  # no dup, no skip


def test_merged_uncommitted_batch_leaves_subs_untouched(tmp_path):
    """All-or-nothing across replicas: a short tail commits NO sub-cursor
    even when one replica's rows were provisionally taken."""
    root = tmp_path / "rl"
    log0 = RequestLog(replica_log_dir(root, 0))
    log0.append(_record(3, x0=0))
    log0.close()
    log1 = RequestLog(replica_log_dir(root, 1))
    log1.append(_record(2, x0=1000))
    log1.close()
    c = MergedReplayConsumer(root, schema=SCHEMA, batch_size=8)
    before = json.dumps(c.cursor(), sort_keys=True)
    assert c.next_batch() is None  # 5 rows < batch_size
    assert json.dumps(c.cursor(), sort_keys=True) == before


def test_merged_peek_batches_commits_nothing(tmp_path):
    _write_fleet(tmp_path / "rl", n_records=4, rows=3)
    c = MergedReplayConsumer(tmp_path / "rl", schema=SCHEMA, batch_size=6)
    before = json.dumps(c.cursor(), sort_keys=True)
    peeked = c.peek_batches(2)
    assert len(peeked) == 2
    assert json.dumps(c.cursor(), sort_keys=True) == before
    batch, _ = c.next_batch()
    assert batch["x"].tolist() == peeked[0]["x"].tolist()


def test_merged_rejects_plain_cursor_and_vice_versa(tmp_path):
    """Cursor-shape mismatches refuse LOUDLY in both directions — a fleet
    resuming from a single-log checkpoint (or the reverse) is operator
    error, not something to paper over."""
    _write_fleet(tmp_path / "rl", n_records=2, rows=3)
    plain = {"segment": 0, "offset": 0, "row": 0, "seq": 0, "records": 2}
    with pytest.raises(ValueError, match="not a merged replay cursor"):
        MergedReplayConsumer(tmp_path / "rl", schema=SCHEMA, batch_size=6,
                             cursor=plain)
    c = MergedReplayConsumer(tmp_path / "rl", schema=SCHEMA, batch_size=6)
    c.next_batch()
    merged_cur = c.cursor()
    with pytest.raises(ValueError, match="unknown replay cursor"):
        ReplayConsumer(replica_log_dir(tmp_path / "rl", 0), schema=SCHEMA,
                       batch_size=6, cursor=merged_cur)


def test_merged_rejects_ghost_replica_cursor(tmp_path):
    _write_fleet(tmp_path / "rl", n_records=2, rows=3)
    c = MergedReplayConsumer(tmp_path / "rl", schema=SCHEMA, batch_size=6)
    cur = c.cursor()
    cur["replicas"]["7"] = dict(cur["replicas"]["0"])
    with pytest.raises(ValueError, match="no log directory"):
        MergedReplayConsumer(tmp_path / "rl", schema=SCHEMA, batch_size=6,
                             cursor=cur)


def test_merged_requires_fleet_layout(tmp_path):
    log = _write(tmp_path / "rl", n_records=2)
    log.close()
    with pytest.raises(ValueError, match="no replica"):
        MergedReplayConsumer(tmp_path / "rl", schema=SCHEMA, batch_size=6)
    # ... and the factory picks the flat consumer for the flat layout
    assert isinstance(make_replay_consumer(tmp_path / "rl", schema=SCHEMA,
                                           batch_size=6), ReplayConsumer)


def test_merged_gc_consumed_segments(tmp_path):
    _write_fleet(tmp_path / "rl", n_records=12, rows=3, segment_bytes=256)
    c = MergedReplayConsumer(tmp_path / "rl", schema=SCHEMA, batch_size=6)
    _drain_merged(c)
    deleted = c.gc_consumed_segments()
    assert deleted and {rid for rid, _ in deleted} == {0, 1}
    for rid, seg in deleted:
        assert not (replica_log_dir(tmp_path / "rl", rid)
                    / f"requests-{seg:06d}.jsonl").exists()
    assert c.gc_consumed_segments() == []


# ----------------------------------------------------- frontend log wiring


def _fake_score(batch):
    return np.asarray(batch["x"], np.float32) * 2.0


def test_microbatcher_writes_replayable_records(tmp_path):
    from tdfo_tpu.serve.frontend import MicroBatcher

    log = RequestLog(tmp_path / "rl")
    seen_cols = []
    def probe_score(batch):
        seen_cols.append(sorted(batch))
        return _fake_score(batch)
    mb = MicroBatcher(probe_score, buckets=(8,), max_batch=8,
                      batch_deadline_ms=0.0, request_log=log)
    def req(i):
        return (f"q{i}", {
            "x": np.arange(i * 2, i * 2 + 2, dtype=np.int32),
            "y": np.full(2, 0.5, np.float32),
            "label": np.ones(2, np.int8),
        })

    results = mb.run([req(0), req(1)])
    mb.swap(probe_score, version=1)  # in-stream serve_swap marker
    results.update(mb.run([req(2), req(3)]))
    log.close()
    # labels were stripped before scoring, and scores are label-free
    assert all(cols == ["x", "y"] for cols in seen_cols)
    assert all(results[f"q{i}"] is not None for i in range(4))
    # the log replays as a training stream, labels intact
    c = ReplayConsumer(tmp_path / "rl", schema=SCHEMA, batch_size=4)
    xs = _drain_x(c)
    assert xs == list(range(8))
    assert c.cursor()["records"] == 4
    assert c.cursor()["skipped"] == 1  # the serve_swap in-stream marker


def test_microbatcher_shed_records_carry_no_features(tmp_path):
    from tdfo_tpu.serve.frontend import MicroBatcher

    log = RequestLog(tmp_path / "rl")
    mb = MicroBatcher(_fake_score, buckets=(8,), max_batch=8,
                      batch_deadline_ms=1e6, max_queue=1,
                      shed_policy="oldest", request_log=log)
    for i in range(3):
        mb.submit(f"q{i}", {"x": np.arange(2, dtype=np.int32),
                            "y": np.zeros(2, np.float32),
                            "label": np.zeros(2, np.int8)})
    mb.drain()
    log.close()
    lines = [json.loads(l) for l in
             (tmp_path / "rl" / "requests-000000.jsonl").read_text().splitlines()]
    sheds = [r for r in lines if r.get("outcome") == "shed"]
    assert sheds and all("features" not in r for r in sheds)
    c = ReplayConsumer(tmp_path / "rl", schema=SCHEMA, batch_size=2)
    _drain_x(c)
    assert c.cursor()["skipped"] == len(sheds)
    assert c.cursor()["bad"] == 0


# ---------------------------------------------------------- sink rotation


def test_metric_logger_rotates_at_size(tmp_path):
    from tdfo_tpu.train.trainer import MetricLogger

    ml = MetricLogger(tmp_path, rotate_bytes=400)
    for i in range(40):
        ml.log(event="tick", step=i, value=float(i))
    ml.close()
    main, overflow = tmp_path / "metrics.jsonl", tmp_path / "metrics.jsonl.1"
    assert overflow.exists()
    assert main.stat().st_size < 400 + 200  # bounded growth
    # crash-safe rotation: every surviving line is complete JSON
    steps = []
    for p in (overflow, main):
        for line in p.read_text().splitlines():
            steps.append(json.loads(line)["step"])
    assert steps == sorted(steps)  # one generation retired, order preserved


def test_retries_log_rotates_at_size(tmp_path):
    from tdfo_tpu.utils import retry

    path = tmp_path / "retries.jsonl"
    retry.set_failure_log(path, rotate_bytes=300)
    try:
        calls = {"n": 0}

        def flaky():
            calls["n"] += 1
            raise OSError("down")

        for _ in range(12):
            with pytest.raises(OSError):
                retry.retry_call(flaky, description="flaky", attempts=2,
                                 base_delay=0.0, jitter=0.0,
                                 sleep=lambda s: None)
        overflow = tmp_path / "retries.jsonl.1"
        assert overflow.exists()
        # the live file is bounded (it may be mid-generation: absent right
        # after a rotation, until the next failure recreates it)
        if path.exists():
            assert path.stat().st_size < 300 + 300
        for p in (path, overflow):
            if not p.exists():
                continue
            for line in p.read_text().splitlines():
                assert json.loads(line)["description"] == "flaky"
    finally:
        retry.set_failure_log(None)
