"""Worker for the crash/resume test (run as a subprocess, NOT pytest).

Usage:
    python crash_worker.py <spec_json_path>

Spec keys: ``data_dir``, ``checkpoint_dir``, ``log_dir``, ``out_json``,
``kill_at_step``, ``checkpoint_every_n_steps``, ``local_devices``, and an
optional ``distributed = {port, nprocs, pid}`` to join a jax.distributed
cluster (the 2-process variant; both processes hit the lockstep kill at the
same step boundary).

Spoofs CPU devices, trains one epoch through the SAME Trainer as production
runs with the ``[faults]`` kill armed, and writes final metrics plus a
sha256 digest of this process's addressable train-state shards to
``out_json``.  When the injected kill fires, the process dies via
``os._exit(KILL_EXIT_CODE)`` and writes nothing — exactly the observable
behaviour of a real preemption.
"""

import hashlib
import json
import sys
from pathlib import Path


def _digest_state(state) -> str:
    """sha256 over this process's addressable shards, leaf order fixed by the
    pytree; deterministic across identical runs on the same mesh."""
    import jax
    import numpy as np

    h = hashlib.sha256()
    for leaf in jax.tree.leaves(state):
        if isinstance(leaf, jax.Array):
            for s in leaf.addressable_shards:
                h.update(np.ascontiguousarray(np.asarray(s.data)).tobytes())
        elif hasattr(leaf, "dtype"):
            h.update(np.ascontiguousarray(np.asarray(leaf)).tobytes())
        else:
            h.update(repr(leaf).encode())
    return h.hexdigest()


def main() -> None:
    spec = json.loads(Path(sys.argv[1]).read_text())

    from tdfo_tpu.core.mesh import spoof_cpu_devices

    spoof_cpu_devices(int(spec.get("local_devices", 4)))

    import jax

    dist = spec.get("distributed")
    if dist:
        jax.distributed.initialize(
            coordinator_address=f"127.0.0.1:{dist['port']}",
            num_processes=int(dist["nprocs"]),
            process_id=int(dist["pid"]),
        )
        assert jax.process_count() == int(dist["nprocs"])
    jax.config.update("jax_default_matmul_precision", "highest")

    from tdfo_tpu.core.config import load_size_map, read_configs
    from tdfo_tpu.train.trainer import Trainer

    cfg = read_configs(
        None,
        data_dir=spec["data_dir"],
        model="twotower",
        n_epochs=1,
        learning_rate=3e-3,
        embed_dim=8,
        per_device_train_batch_size=16,
        per_device_eval_batch_size=16,
        shuffle_buffer_size=500,
        log_every_n_steps=2,
        size_map=load_size_map(spec["data_dir"]),
        checkpoint_dir=spec["checkpoint_dir"],
        checkpoint_every_n_steps=int(spec["checkpoint_every_n_steps"]),
        faults={"kill_at_step": int(spec["kill_at_step"])},
    )
    tr = Trainer(cfg, log_dir=spec["log_dir"])
    metrics = tr.fit()

    Path(spec["out_json"]).write_text(json.dumps(
        {"metrics": metrics, "state_digest": _digest_state(tr.state)}
    ))


if __name__ == "__main__":
    main()
