"""REAL 2-process distributed test (``jax.distributed.initialize`` on CPU).

The in-process 8-device mesh used everywhere else cannot catch multi-process
bugs (host-local accumulation, non-addressable-array ``float()`` crashes,
per-host data skew, lockstep violations).  Here two OS processes with 2
spoofed CPU devices each form a 4-device mesh over the jax coordination
service and run the full Trainer — the framework's replacement for
torchrec's ``torchx dist.ddp`` / gloo process groups and TF's in-process
gRPC PS cluster (SURVEY.md §4.1).

Asserted invariants:
  * both processes finish a fit with IDENTICAL step counts (lockstep);
  * both report byte-identical global eval metrics (cross-host aggregation);
  * the pre-training metrics equal a single-process run on the same data —
    i.e. the 2-process metric is provably GLOBAL, not host-local (a
    host-local bug would see ~half the eval rows and diverge).
"""

import json
import os
import socket
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

REPO = Path(__file__).resolve().parent.parent


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.fixture(scope="module")
def ctr_data(tmp_path_factory):
    from tdfo_tpu.data.ctr_preprocessing import run_ctr_preprocessing
    from tdfo_tpu.data.synthetic import write_synthetic_goodreads

    d = tmp_path_factory.mktemp("gr_mh")
    write_synthetic_goodreads(d, n_users=120, n_books=150,
                              interactions_per_user=(15, 40), seed=11)
    run_ctr_preprocessing(d)
    return d


def _run_workers(nprocs: int, ndev: int, data_dir: Path, tmp: Path,
                 model: str = "twotower") -> list[dict]:
    port = _free_port()
    env = dict(os.environ)
    env["PYTHONPATH"] = f"{REPO}{os.pathsep}" + env.get("PYTHONPATH", "")
    procs, outs = [], []
    for pid in range(nprocs):
        out = tmp / f"worker_{nprocs}_{pid}.json"
        outs.append(out)
        procs.append(subprocess.Popen(
            [sys.executable, str(REPO / "tests" / "multihost_worker.py"),
             str(pid), str(nprocs), str(port), str(ndev), str(data_dir),
             str(out), model],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        ))
    logs = []
    for p in procs:
        try:
            stdout, _ = p.communicate(timeout=600)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("multihost workers deadlocked (lockstep violation?)")
        logs.append(stdout.decode(errors="replace"))
    for p, log in zip(procs, logs):
        assert p.returncode == 0, f"worker failed:\n{log[-4000:]}"
    return [json.loads(o.read_text()) for o in outs]


def test_two_process_fit_and_global_metrics(ctr_data, tmp_path):
    two = _run_workers(2, 2, ctr_data, tmp_path)
    one = _run_workers(1, 4, ctr_data, tmp_path)[0]

    # lockstep: both processes took exactly the same number of train steps
    assert two[0]["steps"] == two[1]["steps"] > 0

    # global metrics: every process reports the identical value
    for key in ("pre", "post"):
        for metric in two[0][key]:
            a, b = two[0][key][metric], two[1][key][metric]
            assert np.isclose(a, b, rtol=1e-6), (key, metric, a, b)

    # provably global: the pre-training eval (deterministic seed init, full
    # eval set, no training noise) matches the single-process run over the
    # same data — a host-local accumulation would miss ~half the rows
    for metric in one["pre"]:
        a, b = one["pre"][metric], two[0]["pre"][metric]
        assert np.isclose(a, b, rtol=1e-4, atol=1e-6), (metric, a, b)


@pytest.fixture(scope="module")
def seq_data(tmp_path_factory):
    from tdfo_tpu.data.seq_preprocessing import run_seq_preprocessing
    from tdfo_tpu.data.synthetic import write_synthetic_goodreads

    d = tmp_path_factory.mktemp("gr_mh_seq")
    write_synthetic_goodreads(d, n_users=100, n_books=120,
                              interactions_per_user=(15, 40), seed=13)
    run_seq_preprocessing(d, max_len=12, sliding_step=6, seed=13, pad=False)
    return d


def test_two_process_jagged_bert4rec(seq_data, tmp_path):
    """The jagged path across REAL processes: per-host (values, lengths)
    packing + jagged_to_dense_per_host's host-segmented offsets must agree.
    The single-process reference run is what actually detects an offset bug:
    a garbled 2-process conversion would be deterministic and identical on
    both hosts, so only divergence from the 1-process metrics exposes it."""
    two = _run_workers(2, 2, seq_data, tmp_path, model="bert4rec")
    one = _run_workers(1, 4, seq_data, tmp_path, model="bert4rec")[0]
    assert two[0]["steps"] == two[1]["steps"] > 0
    for key in ("pre", "post"):
        for metric in two[0][key]:
            a, b = two[0][key][metric], two[1][key][metric]
            assert np.isclose(a, b, rtol=1e-6), (key, metric, a, b)
    # pre-training eval (deterministic init, padded eval path is shared) must
    # match the single-process run exactly
    for metric in one["pre"]:
        a, b = one["pre"][metric], two[0]["pre"][metric]
        assert np.isclose(a, b, rtol=1e-4, atol=1e-6), (metric, a, b)
    # training moved the model (post != pre for at least one metric)
    assert any(
        not np.isclose(two[0]["pre"][m], two[0]["post"][m], atol=1e-9)
        for m in two[0]["pre"]
    )
