"""Worker for the online-loop kill/restart tests (run as a subprocess,
NOT pytest).

Usage:
    python online_worker.py <spec_json_path>

Spec keys: ``data_dir``, ``checkpoint_dir``, ``log_dir``, ``request_log``,
``out_json``, ``local_devices``, ``steps_per_cycle``, ``max_cycles``,
``max_bad_records``, ``max_lag_records``, ``lag_policy``, ``faults`` (a
``[faults]`` dict — kill_during_replay / kill_between_stages /
kill_during_swap), ``probe_seed``.

Spoofs CPU devices and runs the REAL ``OnlineLoop`` (``train/online.py``)
against a request log the parent test wrote with the real ``RequestLog``
writer.  On completion it scores a deterministic probe trace through the
live post-swap ``MicroBatcher`` and writes the verdict to ``out_json``:
final store version, the composed bundle's manifest digest, the replay
cursor, and the served probe logits.  When an injected kill fires, the
process dies via ``os._exit(KILL_EXIT_CODE)`` and writes nothing — exactly
a crashed supervisor.  Restarting the SAME spec must converge to a verdict
bitwise-equal to an uninterrupted run's (tests/test_online.py asserts it).
"""

import json
import sys
from pathlib import Path


def main() -> None:
    spec = json.loads(Path(sys.argv[1]).read_text())

    from tdfo_tpu.core.mesh import spoof_cpu_devices

    spoof_cpu_devices(int(spec.get("local_devices", 8)))

    import jax

    jax.config.update("jax_default_matmul_precision", "highest")

    import numpy as np

    from tdfo_tpu.core.config import load_size_map, read_configs
    from tdfo_tpu.serve.export import read_raw_bundle
    from tdfo_tpu.serve.frontend import _column_vocab
    from tdfo_tpu.train.online import OnlineLoop
    from tdfo_tpu.train.trainer import _ctr_columns

    cfg = read_configs(
        None,
        data_dir=spec["data_dir"],
        model="twotower",
        model_parallel=True,
        n_epochs=1,
        learning_rate=3e-3,
        embed_dim=8,
        per_device_train_batch_size=8,
        per_device_eval_batch_size=8,
        shuffle_buffer_size=500,
        log_every_n_steps=1000,
        size_map=load_size_map(spec["data_dir"]),
        checkpoint_dir=spec["checkpoint_dir"],
        faults=dict(spec.get("faults") or {}),
        online=dict(
            request_log=spec["request_log"],
            steps_per_cycle=int(spec.get("steps_per_cycle", 2)),
            max_cycles=int(spec.get("max_cycles", 0)),
            max_bad_records=int(spec.get("max_bad_records", 0)),
            max_lag_records=int(spec.get("max_lag_records", 0)),
            lag_policy=spec.get("lag_policy", "fail"),
        ),
    )
    loop = OnlineLoop(cfg, log_dir=spec["log_dir"])
    stats = loop.run()

    # deterministic probe trace through the live (post-swap) batcher: the
    # served-logits fingerprint the bitwise acceptance compares
    cat_cols, cont_cols = _ctr_columns(cfg)
    vocab = _column_vocab(cfg, cat_cols)
    rng = np.random.default_rng(int(spec.get("probe_seed", 606)))
    requests = []
    for i, n in enumerate((3, 5, 2, 8)):
        batch = {c: rng.integers(0, vocab[c], size=n, dtype=np.int32)
                 for c in cat_cols}
        for c in cont_cols:
            batch[c] = rng.random(n, dtype=np.float32)
        requests.append((f"probe{i}", batch))
    results = loop.probe(requests)

    manifest, _ = read_raw_bundle(loop.store.current_dir())
    Path(spec["out_json"]).write_text(json.dumps({
        "stats": stats,
        "version": int(loop.store.current_version()),
        "digest": manifest["digest"],
        "cursor": loop.consumer.cursor(),
        "logits": {rid: np.asarray(v).tolist() for rid, v in results.items()},
    }))


if __name__ == "__main__":
    main()
