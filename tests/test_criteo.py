"""Criteo-format family: ETL invariants + generic-schema DLRM end to end.

The reference has no Criteo pipeline; this family exists for the driver's
north star (BASELINE.json: DLRM-Criteo).  The ETL writes the SAME on-disk
contract as the Goodreads CTR ETL, so the trainer consumes it through the
``categorical_features`` / ``continuous_features`` schema knobs.
"""

import json

import numpy as np
import pytest

from tdfo_tpu.core.config import read_configs
from tdfo_tpu.data.criteo_preprocessing import (
    CRITEO_CATEGORICAL,
    CRITEO_CONTINUOUS,
    run_criteo_preprocessing,
)
from tdfo_tpu.data.loader import resolve_files
from tdfo_tpu.data.synthetic import write_synthetic_criteo


@pytest.fixture(scope="module")
def criteo_dir(tmp_path_factory):
    d = tmp_path_factory.mktemp("criteo")
    write_synthetic_criteo(d, n_rows=3000, seed=5)
    size_map = run_criteo_preprocessing(d, min_freq=4, eval_fraction=0.2,
                                        file_num=2, seed=5)
    return d, size_map


def _load(files):
    import pyarrow.parquet as pq

    tbl = pq.read_table(files)
    return {c: tbl[c].to_numpy() for c in tbl.column_names}


class TestCriteoEtl:
    def test_size_map_and_vocab_bounds(self, criteo_dir):
        d, size_map = criteo_dir
        assert set(size_map) == set(CRITEO_CATEGORICAL)
        assert json.loads((d / "size_map.json").read_text()) == size_map
        train = _load(resolve_files(d, "parquet/train_part_*.parquet"))
        for c in CRITEO_CATEGORICAL:
            v = train[c]
            assert v.min() >= 0 and v.max() < size_map[c], c
        # frequency thresholding folds the zipf tail into OOV id 0
        assert any((train[c] == 0).any() for c in CRITEO_CATEGORICAL)

    def test_continuous_normalised(self, criteo_dir):
        d, _ = criteo_dir
        train = _load(resolve_files(d, "parquet/train_part_*.parquet"))
        for c in CRITEO_CONTINUOUS:
            v = train[c]
            assert v.dtype == np.float32
            assert v.min() >= 0.0 and v.max() <= 1.0 + 1e-6, c

    def test_split_sizes_and_labels(self, criteo_dir):
        d, _ = criteo_dir
        train = _load(resolve_files(d, "parquet/train_part_*.parquet"))
        ev = _load(resolve_files(d, "parquet/eval_part_*.parquet"))
        n_train, n_eval = len(train["label"]), len(ev["label"])
        assert n_train + n_eval == 3000
        assert n_eval == 600  # eval_fraction=0.2, row-ordered tail
        assert set(np.unique(train["label"])) <= {0, 1}


def test_dlrm_criteo_trains(criteo_dir, tmp_path):
    """Generic-schema DLRM (26 tables from config lists) fits on the mesh:
    the full north-star family wiring, end to end on preprocessed data."""
    from tdfo_tpu.train.trainer import Trainer

    d, size_map = criteo_dir
    cfg = read_configs(
        None,
        data_dir=d,
        model="dlrm",
        model_parallel=True,
        categorical_features=list(CRITEO_CATEGORICAL),
        continuous_features=list(CRITEO_CONTINUOUS),
        n_epochs=1,
        learning_rate=3e-3,
        embed_dim=8,
        per_device_train_batch_size=16,
        per_device_eval_batch_size=16,
        shuffle_buffer_size=500,
        log_every_n_steps=1000,
        size_map=size_map,
    )
    tr = Trainer(cfg, log_dir=tmp_path)
    assert len(tr.coll.features()) == 26
    m = tr.fit()
    assert 0.0 <= m["auc"] <= 1.0
    assert m["eval_loss"] > 0
    lines = [json.loads(l) for l in (tmp_path / "metrics.jsonl").read_text().splitlines()]
    assert any("train_auc" in l for l in lines)


def test_custom_schema_knob_validation():
    with pytest.raises(ValueError, match="custom CTR"):
        read_configs(None, model="twotower", categorical_features=["a"])
    with pytest.raises(ValueError, match="custom"):
        read_configs(None, model="dlrm", continuous_features=["x"])
    cfg = read_configs(None, model="dlrm", categorical_features=["a", "b"])
    assert cfg.categorical_features == ("a", "b")
