"""Grouped cross-table all-to-all + pipelined input-dist (torchrec
``KJTAllToAll`` / ``TrainPipelineSparseDist`` parity).

The collective-count win is assertable without a chip: the grouped forward
must carry exactly 2 ``all_to_all`` ops in its jaxpr for ANY number of
row-sharded tables (vs 2 per table in the per-table program), and the
grouped update at most 2.  Numerics: the stable owner sort delivers each
shard its owned contributions in global batch order, so the grouped update
is bit-identical to the SEQUENTIAL per-table reference (per-table updates
on replicated arrays) — the per-table GSPMD program's own numerics are
layout-dependent (XLA partitions its segment-sums per shard), so that is
the well-defined parity target.  Pipelining shifts every batch's training
one call later without touching its math, so pipelined == eager grouped
bit-identically, state included.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from tdfo_tpu.ops.sparse import sparse_optimizer
from tdfo_tpu.parallel.embedding import EmbeddingSpec, ShardedEmbeddingCollection
from tdfo_tpu.train.sparse_step import (
    SparseTrainState,
    make_pipelined_sparse_train_step,
    make_sparse_train_step,
)

B, D = 64, 8


def _specs(n_tables: int, dim: int = D):
    return [
        EmbeddingSpec(name=f"t{i}", num_embeddings=40 + 9 * i,
                      embedding_dim=dim, features=(f"f{i}",),
                      sharding="row", init_scale=0.1)
        for i in range(n_tables)
    ]


def _coll(mesh, n_tables=5, *, grouped=True, stack=False, cf=None):
    return ShardedEmbeddingCollection(
        _specs(n_tables), mesh=mesh, stack_tables=stack,
        fused_kind="rowwise_adagrad", grouped_a2a=grouped,
        a2a_capacity_factor=cf,
    )


def _feats(mesh, n_tables=5, b=B, key=1, with_pad=False):
    k = jax.random.PRNGKey(key)
    out = {}
    for i in range(n_tables):
        ids = jax.random.randint(jax.random.fold_in(k, i), (b,), 0, 40)
        if with_pad:
            ids = jnp.where(jnp.arange(b) % 7 == 0, -1, ids)
        out[f"f{i}"] = jax.device_put(ids, NamedSharding(mesh, P("model")))
    return out


def test_grouped_forward_jaxpr_exactly_two_alltoall_at_26_tables(mesh8):
    """The headline O(2·tables) -> O(1) collective claim, at the DLRM-Criteo
    table count: 26 row-sharded tables of one (dim, dtype) ride ONE id +
    ONE vector exchange; the per-table program issues 52."""
    n = 26
    grouped = _coll(mesh8, n, grouped=True)
    per_table = _coll(mesh8, n, grouped=False)
    tables = grouped.init(jax.random.PRNGKey(0))
    feats = _feats(mesh8, n, b=32)
    jg = str(jax.make_jaxpr(
        lambda t, f: grouped.lookup(t, f, mode="alltoall"))(tables, feats))
    jp = str(jax.make_jaxpr(
        lambda t, f: per_table.lookup(t, f, mode="alltoall"))(tables, feats))
    assert jg.count("all_to_all") == 2, jg.count("all_to_all")
    assert jp.count("all_to_all") == 2 * n


def test_grouped_update_jaxpr_at_most_two_alltoall_at_26_tables(mesh8):
    n = 26
    coll = _coll(mesh8, n, grouped=True)
    tables = coll.init(jax.random.PRNGKey(0))
    opt = sparse_optimizer("rowwise_adagrad", lr=0.05)
    slots = {a: opt.init(t) for a, t in tables.items()}
    feats = _feats(mesh8, n, b=32)
    grads = {f: jnp.ones((32, D)) for f in feats}
    j = str(jax.make_jaxpr(
        lambda t, s, i, g: coll.grouped_update(opt, t, s, i, g)
    )(tables, slots, feats, grads))
    assert j.count("all_to_all") <= 2, j.count("all_to_all")


@pytest.mark.parametrize("stack", [False, True])
def test_grouped_forward_matches_per_table_exactly(mesh8, stack):
    """Same gathers, same unpermute: grouped vectors == per-table vectors
    bitwise on real ids, and padding ids resolve to exact zero on the
    grouped path even inside a ``__tablestack_`` (where the per-table
    program's unconditional ``ids + offset`` aliases -1 onto the previous
    member's last row — pre-existing stacked-path behavior)."""
    grouped = _coll(mesh8, grouped=True, stack=stack)
    per_table = _coll(mesh8, grouped=False, stack=stack)
    tables = grouped.init(jax.random.PRNGKey(0))
    feats = _feats(mesh8, with_pad=True)
    lk_g = jax.jit(lambda t, f: grouped.lookup(t, f, mode="alltoall"))(
        tables, feats)
    lk_p = jax.jit(lambda t, f: per_table.lookup(t, f, mode="alltoall"))(
        tables, feats)
    for f in feats:
        pad = np.asarray(feats[f]) < 0
        np.testing.assert_array_equal(
            np.asarray(lk_g[f])[~pad], np.asarray(lk_p[f])[~pad], err_msg=f)
        assert (np.asarray(lk_g[f])[pad] == 0).all()
        if not stack:  # unstacked offsets are 0: both paths drop -1
            np.testing.assert_array_equal(
                np.asarray(lk_g[f]), np.asarray(lk_p[f]), err_msg=f)


@pytest.mark.parametrize("stack", [False, True])
def test_grouped_update_matches_sequential_reference(mesh8, stack):
    """Bit-identical tables AND optimizer slots vs the sequential per-table
    reference (opt.update per table on REPLICATED arrays, feature order)."""
    coll = _coll(mesh8, grouped=True, stack=stack)
    tables = coll.init(jax.random.PRNGKey(0))
    opt = sparse_optimizer("rowwise_adagrad", lr=0.05)
    slots = {a: opt.init(t) for a, t in tables.items()}
    feats = _feats(mesh8, with_pad=True)
    k = jax.random.PRNGKey(9)
    grads = {
        f: jax.device_put(
            jax.random.normal(jax.random.fold_in(k, i), (B, D)),
            NamedSharding(mesh8, P("model", None)))
        for i, f in enumerate(feats)
    }
    # sequential reference on replicated copies
    ref_t = {a: jnp.asarray(np.asarray(t)) for a, t in tables.items()}
    ref_s = {a: tuple(jnp.asarray(np.asarray(x)) for x in s)
             for a, s in slots.items()}
    for i, f in enumerate(feats):
        aname, spec, off = coll.resolve(f)
        ids = jnp.asarray(np.asarray(feats[f]))
        ids = jnp.where(ids >= 0, ids + off, -1)
        ref_t[aname], ref_s[aname] = opt.update(
            ref_t[aname], ref_s[aname], ids,
            jnp.asarray(np.asarray(grads[f])), embedding_dim=D)
    got_t, got_s = jax.jit(
        lambda t, s, i, g: coll.grouped_update(opt, t, s, i, g)
    )(tables, slots, feats, grads)
    for a in got_t:
        np.testing.assert_array_equal(
            np.asarray(ref_t[a]), np.asarray(got_t[a]), err_msg=a)
        for x, y in zip(ref_s[a], got_s[a]):
            np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def _toy_forward(dense, embs, batch):
    h = sum(e.sum(-1) for e in embs.values()) * dense["w"]
    return jnp.mean((h - batch["label"]) ** 2)


def _toy_state(coll):
    return SparseTrainState.create(
        dense_params={"w": jnp.ones(())},
        tx=optax.adam(1e-2),
        tables=coll.init(jax.random.PRNGKey(0)),
        sparse_opt=sparse_optimizer("rowwise_adagrad", lr=0.05),
    )


def _toy_batches(n):
    key = jax.random.PRNGKey(3)
    out = []
    for s in range(n):
        b = {f"f{i}": jax.random.randint(
                jax.random.fold_in(key, 10 * s + i), (B,), 0, 40)
             for i in range(5)}
        b["label"] = jax.random.normal(jax.random.fold_in(key, 999 + s), (B,))
        out.append(b)
    return out


def test_grouped_step_losses_match_per_table(mesh8):
    """Grouped vs per-table eager: the FIRST loss (same initial tables,
    forward is bitwise-equal) must match exactly; later losses track to
    float32 resolution.  They cannot be required bit-identical multi-step:
    the per-table GSPMD update's own numerics are layout-dependent (XLA
    partitions its segment-sums per shard), which is why the bitwise update
    target above is the sequential reference instead."""
    bs = _toy_batches(6)
    losses = {}
    for grouped in (False, True):
        coll = _coll(mesh8, grouped=grouped)
        step = make_sparse_train_step(
            coll, _toy_forward, mode="alltoall", donate=False)
        st = _toy_state(coll)
        ls = []
        for b in bs:
            st, l = step(st, b)
            ls.append(float(l))
        losses[grouped] = ls
    assert losses[True][0] == losses[False][0], losses
    np.testing.assert_allclose(losses[True], losses[False], rtol=1e-6)


def test_pipelined_matches_eager_grouped_bitwise(mesh8):
    """prime/step/flush trains the same batches with the same math, one
    call later: losses, tables and slots all bit-identical to eager."""
    bs = _toy_batches(4)
    coll = _coll(mesh8, grouped=True)
    step = make_sparse_train_step(
        coll, _toy_forward, mode="alltoall", donate=False)
    st_e = _toy_state(coll)
    eager = []
    for b in bs:
        st_e, l = step(st_e, b)
        eager.append(float(l))

    pipe = make_pipelined_sparse_train_step(coll, _toy_forward, donate=False)
    st_p = _toy_state(coll)
    piped = []
    carry = pipe.prime(bs[0])
    for b in bs[1:]:
        st_p, l, carry = pipe.step(st_p, b, carry)
        piped.append(float(l))
    st_p, l = pipe.flush(st_p, carry)
    piped.append(float(l))

    assert piped == eager, (piped, eager)
    assert int(st_p.step) == int(st_e.step) == len(bs)
    for a in st_e.tables:
        np.testing.assert_array_equal(
            np.asarray(st_e.tables[a]), np.asarray(st_p.tables[a]), err_msg=a)
        for x, y in zip(st_e.slots[a], st_p.slots[a]):
            np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_pipelined_step_jaxpr_single_grouped_exchange(mesh8):
    """One pipelined step = next batch's id dist (1) + carried batch's
    vector return (1) + grouped update (2): 4 all_to_all total, independent
    of table count."""
    coll = _coll(mesh8, grouped=True)
    pipe = make_pipelined_sparse_train_step(coll, _toy_forward, jit=False)
    st = _toy_state(coll)
    b = _toy_batches(1)[0]
    carry = pipe.prime(b)
    j = str(jax.make_jaxpr(pipe.step)(st, b, carry))
    assert j.count("all_to_all") == 4, j.count("all_to_all")
    assert str(jax.make_jaxpr(pipe.prime)(b)).count("all_to_all") == 1


def test_pipelined_requires_grouped_collection(mesh8):
    coll = _coll(mesh8, grouped=False)
    with pytest.raises(ValueError, match="grouped_a2a"):
        make_pipelined_sparse_train_step(coll, _toy_forward)


def test_grouped_a2a_overflow_counts_dropped_ids(mesh8):
    """The capacity knob's failure mode stays observable in grouped mode:
    a skewed batch (every id owned by shard 0) overflows the combined
    stream's bucket cap by a hand-computable amount."""
    m = 2  # model-axis shards in mesh8
    cf = 0.5
    coll = _coll(mesh8, n_tables=2, grouped=True, cf=cf)
    tables = coll.init(jax.random.PRNGKey(0))
    # every id < rows_per_shard -> owner 0 on every shard
    feats = {f"f{i}": jnp.zeros((B,), jnp.int32) for i in range(2)}
    got = int(jax.jit(lambda t, f: coll.a2a_overflow(t, f))(tables, feats))
    # per shard: combined stream n = 2 tables x B/m ids, cap per bucket =
    # round8(cf*n/m) (same _a2a_bucket_cap the real exchange sizes its send
    # buffers with); shard 0's bucket holds ALL n ids -> n - cap dropped,
    # summed over the m shards
    n_local = 2 * B // m
    cap = min(n_local, -(-int(cf * n_local / m) // 8) * 8)
    assert cap < n_local  # the scenario really overflows
    assert got == m * (n_local - cap), (got, n_local, cap)
    # uncapped collection reports zero
    coll0 = _coll(mesh8, n_tables=2, grouped=True, cf=None)
    assert int(jax.jit(
        lambda t, f: coll0.a2a_overflow(t, f))(tables, feats)) == 0


def test_grouped_capacity_drops_same_ids_forward_and_backward(mesh8):
    """Under a finite capacity factor the stable sort makes forward and
    update drop the SAME overflowed ids: training still moves every row
    whose forward vector was non-zero, and only those."""
    coll = _coll(mesh8, n_tables=1, grouped=True, cf=0.5)
    tables = coll.init(jax.random.PRNGKey(0))
    opt = sparse_optimizer("rowwise_adagrad", lr=0.05)
    slots = {a: opt.init(t) for a, t in tables.items()}
    feats = {"f0": jnp.zeros((B,), jnp.int32)}  # all ids -> shard 0: overflow
    grads = {"f0": jnp.ones((B, D))}
    vec = jax.jit(lambda t, f: coll.lookup(t, f, mode="alltoall"))(
        tables, feats)["f0"]
    kept_fwd = int((np.abs(np.asarray(vec)).sum(-1) > 0).sum())
    nt, _ = jax.jit(lambda t, s, i, g: coll.grouped_update(opt, t, s, i, g))(
        tables, slots, feats, grads)
    aname = coll.resolve("f0")[0]
    rows_touched = int((np.abs(np.asarray(nt[aname])
                               - np.asarray(tables[aname])).sum(-1) > 0).sum())
    assert kept_fwd < B  # the cap really dropped something
    # all kept ids are id 0 -> exactly one row updates iff anything was kept
    assert rows_touched == (1 if kept_fwd else 0)


def test_grouped_routes_around_replicated_tables(mesh8):
    """A mixed spec set (row-sharded + replicated) splits cleanly: grouped
    exchange for the sharded tables, plain gather for the replicated one,
    bitwise equal to the all-per-table program."""
    specs = _specs(3) + [
        EmbeddingSpec(name="r0", num_embeddings=16, embedding_dim=D,
                      features=("fr",), sharding="replicated",
                      init_scale=0.1)
    ]
    mk = lambda grouped: ShardedEmbeddingCollection(
        specs, mesh=mesh8, fused_kind="rowwise_adagrad", grouped_a2a=grouped)
    grouped, per_table = mk(True), mk(False)
    tables = grouped.init(jax.random.PRNGKey(0))
    feats = dict(_feats(mesh8, 3),
                 fr=jnp.arange(B, dtype=jnp.int32) % 16)
    lk_g = jax.jit(lambda t, f: grouped.lookup(t, f, mode="alltoall"))(
        tables, feats)
    lk_p = jax.jit(lambda t, f: per_table.lookup(t, f, mode="alltoall"))(
        tables, feats)
    for f in feats:
        np.testing.assert_array_equal(
            np.asarray(lk_g[f]), np.asarray(lk_p[f]), err_msg=f)
