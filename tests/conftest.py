"""Test bootstrap: 8 spoofed CPU devices BEFORE jax initialises.

This is the framework-wide realisation of the reference's fake-cluster hints
(SURVEY.md §4.1: jax-flax/train_dp.py:21-24 commented XLA_FLAGS, TF logical
devices, in-process gRPC PS cluster, torchrec mp.spawn) — every multi-device
test in the suite runs on an 8-device virtual CPU mesh.
"""

from tdfo_tpu.core.mesh import spoof_cpu_devices

spoof_cpu_devices(8)

import jax  # noqa: E402

jax.config.update("jax_default_matmul_precision", "highest")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def mesh8():
    from tdfo_tpu.core.config import MeshSpec
    from tdfo_tpu.core.mesh import make_mesh

    return make_mesh(MeshSpec(data=4, model=2, seq=1))


@pytest.fixture(scope="session")
def mesh_dp():
    from tdfo_tpu.core.config import MeshSpec
    from tdfo_tpu.core.mesh import make_mesh

    return make_mesh(MeshSpec(data=8, model=1, seq=1))
