"""Quality-parity convergence floors (the reference's de-facto acceptance
test is converged in-loop metrics on real Goodreads data:
jax-flax/train_dp.py:219-245 eval ROC-AUC, torchrec/train.py:143-144
Recall@K/NDCG@K).  Reduced-scale versions of tools/quality_run.py (whose
full trajectories are committed under docs/quality/): the signal-bearing
synthetic fixtures make the metrics MEAN something — eval AUC must clear
the 0.5 noise floor decisively, and Bert4Rec's post-training ranking must
decisively beat its own pre-training validation floor."""

# (no slow-marker infra in this suite: these run unconditionally)
import json

import pytest

from tdfo_tpu.core.config import read_configs
from tdfo_tpu.data.ctr_preprocessing import run_ctr_preprocessing
from tdfo_tpu.data.seq_preprocessing import run_seq_preprocessing
from tdfo_tpu.data.synthetic import write_synthetic_goodreads
from tdfo_tpu.train.trainer import Trainer


def test_twotower_converges_above_noise_floor(tmp_path):
    d = tmp_path / "gr"
    write_synthetic_goodreads(d, n_users=800, n_books=320,
                              interactions_per_user=(30, 60), seed=5,
                              signal=0.85)
    size_map = run_ctr_preprocessing(d)
    cfg = read_configs(
        None, data_dir=d, model="twotower", model_parallel=True,
        n_epochs=10, learning_rate=3e-3, weight_decay=1e-3, embed_dim=8,
        per_device_train_batch_size=64, per_device_eval_batch_size=64,
        shuffle_buffer_size=20_000, log_every_n_steps=10_000,
        size_map=size_map,
    )
    metrics = Trainer(cfg).fit()
    # pure-noise data pins eval AUC at ~0.5 forever; the themed fixtures
    # support ~0.6+ at this scale (docs/quality: 0.66 at 15 epochs)
    assert metrics["auc"] >= 0.56, metrics


def test_bert4rec_beats_pretrain_ranking_floor(tmp_path):
    d = tmp_path / "gr"
    write_synthetic_goodreads(d, n_users=300, n_books=320,
                              interactions_per_user=(30, 60), seed=7,
                              signal=0.85)
    stats = run_seq_preprocessing(d, max_len=16, sliding_step=8, seed=7)
    cfg = read_configs(
        None, data_dir=d, model="bert4rec", model_parallel=True,
        n_epochs=10, learning_rate=3e-3, embed_dim=32, n_heads=2,
        n_layers=2, max_len=16, sliding_step=8,
        per_device_train_batch_size=32, per_device_eval_batch_size=32,
        shuffle_buffer_size=20_000, log_every_n_steps=10_000,
        size_map={"n_items": stats["n_items"]},
    )
    log_dir = tmp_path / "logs"
    metrics = Trainer(cfg, log_dir=log_dir).fit()
    # the pre-training validation (epoch -1, torchrec/train.py:159 parity)
    # is the untrained floor of the SAME protocol — convergence must beat
    # it decisively, and clear an absolute floor well above it
    pre = None
    for line in open(log_dir / "metrics.jsonl"):
        rec = json.loads(line)
        if rec.get("epoch") == -1 and "Recall@10" in rec:
            pre = rec
    assert pre is not None
    assert metrics["Recall@10"] >= 0.30, metrics
    assert metrics["Recall@10"] >= pre["Recall@10"] + 0.10, (pre, metrics)
    assert metrics["NDCG@10"] >= pre["NDCG@10"] + 0.05, (pre, metrics)


def test_no_default_method_searchsorted_in_hot_code():
    """`jnp.searchsorted`'s DEFAULT method costs ~6x the `method="sort"`
    formulation on TPU (13 serial narrow gathers vs one sort — measured
    0.86 ms vs 0.14 ms for 8192-into-8192, bit-identical results
    downstream; docs/BUDGET.md).  Every jnp/jax.numpy call site in the
    package must pass method="sort"; plain numpy searchsorted (host-side
    preprocessing/metrics) is exempt."""
    import ast
    from pathlib import Path

    import tdfo_tpu

    offenders = []
    for path in Path(tdfo_tpu.__file__).parent.rglob("*.py"):
        tree = ast.parse(path.read_text(), filename=str(path))
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "searchsorted"):
                continue
            base = node.func.value
            # jnp.searchsorted / jax.numpy.searchsorted only
            is_jnp = (isinstance(base, ast.Name) and base.id == "jnp") or (
                isinstance(base, ast.Attribute) and base.attr == "numpy"
                and isinstance(base.value, ast.Name) and base.value.id == "jax")
            if not is_jnp:
                continue
            kw = {k.arg: k.value for k in node.keywords}
            ok = ("method" in kw
                  and isinstance(kw["method"], ast.Constant)
                  and kw["method"].value == "sort")
            if not ok:
                offenders.append(f"{path}:{node.lineno}")
    assert not offenders, (
        "jnp.searchsorted without method='sort' (TPU-hostile default): "
        + ", ".join(offenders))


def test_no_jnp_unique_in_device_code():
    """`jnp.unique(size=...)` costs ~0.2 ms at 8k ids / ~0.5 ms at 16k on
    v5e; the pair-sort + first-mask-cumsum + back-sort formulation
    (`dedupe_grads`/`dedupe_ids`) does the same job in ~0.24 ms at 16k with
    2 sorts + 1 small scatter (docs/BUDGET.md).  Device-side dedupe in the
    hot paths (`ops/`, `parallel/`) must use it — `jnp.unique` creeping
    back in is a silent multi-x regression.  Host-side numpy unique
    (preprocessing, metrics, tests) is exempt."""
    import ast
    from pathlib import Path

    import tdfo_tpu

    root = Path(tdfo_tpu.__file__).parent
    offenders = []
    for sub in ("ops", "parallel"):
        for path in (root / sub).rglob("*.py"):
            tree = ast.parse(path.read_text(), filename=str(path))
            for node in ast.walk(tree):
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr == "unique"):
                    continue
                base = node.func.value
                # jnp.unique / jax.numpy.unique only (np.unique is host-side)
                is_jnp = (isinstance(base, ast.Name) and base.id == "jnp") or (
                    isinstance(base, ast.Attribute) and base.attr == "numpy"
                    and isinstance(base.value, ast.Name)
                    and base.value.id == "jax")
                if is_jnp:
                    offenders.append(f"{path}:{node.lineno}")
    assert not offenders, (
        "jnp.unique in device-side hot-path code (use the dedupe_grads/"
        "dedupe_ids sort formulation — see docs/BUDGET.md): "
        + ", ".join(offenders))


def test_no_wall_clock_differencing_around_device_work():
    """`jax.block_until_ready` does NOT wait for device execution through
    the tunnel, so `time.time()` / `time.perf_counter()` differencing
    measures RPC noise, not compute — the only honest device timing is
    chain differencing (`bench.chain_time`, CLAUDE.md).  The rule: no
    subtraction may involve those calls (or a name bound from one) in the
    package or the bench drivers, except the sanctioned chain-timer
    itself.  Host-loop timing stays legal via `time.monotonic` (the
    trainer's examples/sec, the watchdog's injectable clock) and bare
    timestamp USE (no differencing) is untouched."""
    import ast
    from pathlib import Path

    import tdfo_tpu

    root = Path(tdfo_tpu.__file__).parent
    files = sorted(root.rglob("*.py")) + sorted(root.parent.glob("bench*.py"))
    SANCTIONED = {("bench.py", "chain_time")}

    def is_wall_call(node):
        return (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in ("time", "perf_counter")
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id == "time")

    offenders, sanctioned_hits = [], 0
    for path in files:
        tree = ast.parse(path.read_text(), filename=str(path))
        parents = {}
        for node in ast.walk(tree):
            for ch in ast.iter_child_nodes(node):
                parents[ch] = node

        def enclosing_funcs(node):
            out = []
            while node in parents:
                node = parents[node]
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    out.append(node.name)
            return out

        tainted = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign) and is_wall_call(node.value):
                tainted.update(t.id for t in node.targets
                               if isinstance(t, ast.Name))
        for node in ast.walk(tree):
            if not (isinstance(node, ast.BinOp)
                    and isinstance(node.op, ast.Sub)):
                continue
            sides = (node.left, node.right)
            if not (any(is_wall_call(s) for s in sides)
                    or any(isinstance(s, ast.Name) and s.id in tainted
                           for s in sides)):
                continue
            if any((path.name, fn) in SANCTIONED
                   for fn in enclosing_funcs(node)):
                sanctioned_hits += 1
                continue
            offenders.append(f"{path}:{node.lineno}")
    assert sanctioned_hits > 0  # the scanner sees the sanctioned site
    assert not offenders, (
        "time.time()/time.perf_counter() differencing outside "
        "bench.chain_time (dishonest device timing through the tunnel — "
        "use chain differencing, or time.monotonic for host-loop wall "
        "time): " + ", ".join(offenders))


def test_monotonic_differencing_and_id_minting_confined_to_trace_module():
    """``obs/trace.py`` is the single sanctioned home for host-loop
    interval timing (``clock()``/``elapsed_ms()``/``elapsed_s()``) and for
    span-id minting (a locked deterministic counter).  Two sub-rules:

      * no ``time.monotonic()`` CALL, and no subtraction involving one (or
        a name bound from one, or from ``trace.clock()``), outside
        obs/trace.py — every wall-time measurement flows through one
        auditable site.  Injectable-clock ATTRIBUTE calls
        (``self._clock()``, the watchdog/frontend deadline machinery) and
        bare ``time.monotonic`` references passed as defaults stay legal:
        they are the test seam, not a timing fork.
      * no ``uuid``/``secrets`` import anywhere in the package or bench
        drivers — random ids would break restart determinism, and the
        causal join keys are domain ids (replica, seq, cycle, version),
        so nothing ever needs one.

    Self-tested on synthetic offenders."""
    import ast
    from pathlib import Path

    import tdfo_tpu

    root = Path(tdfo_tpu.__file__).parent
    files = sorted(root.rglob("*.py")) + sorted(root.parent.glob("bench*.py"))
    SANCTIONED = "obs/trace.py"

    def is_mono_call(node):
        return (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "monotonic"
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id == "time")

    def is_trace_clock_call(node):
        return (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "clock"
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id in ("trace", "_trace", "obs_trace"))

    def scan(tree):
        """-> (mono_call_lines, sub_lines, mint_lines)"""
        mono, subs, mints = [], [], []
        parents = {}
        for node in ast.walk(tree):
            for ch in ast.iter_child_nodes(node):
                parents[ch] = node

        def enclosing_fn(node):
            while node in parents:
                node = parents[node]
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    return node
            return None

        # taint is FUNCTION-scoped: an unrelated `t0` in another function
        # (e.g. an injectable-clock deadline) must not inherit it
        tainted = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign) and (
                    is_mono_call(node.value) or is_trace_clock_call(node.value)):
                fn = enclosing_fn(node)
                tainted.update((t.id, fn) for t in node.targets
                               if isinstance(t, ast.Name))
        for node in ast.walk(tree):
            if is_mono_call(node):
                mono.append(node.lineno)
            if (isinstance(node, ast.BinOp) and isinstance(node.op, ast.Sub)
                    and any(is_mono_call(s) or is_trace_clock_call(s)
                            or (isinstance(s, ast.Name)
                                and (s.id, enclosing_fn(node)) in tainted)
                            for s in (node.left, node.right))):
                subs.append(node.lineno)
            if isinstance(node, ast.Import):
                mints += [node.lineno for a in node.names
                          if a.name.split(".")[0] in ("uuid", "secrets")]
            if (isinstance(node, ast.ImportFrom) and node.module
                    and node.module.split(".")[0] in ("uuid", "secrets")):
                mints.append(node.lineno)
        return sorted(set(mono)), sorted(set(subs)), sorted(set(mints))

    synthetic = (
        "import time, uuid\n"
        "from tdfo_tpu.obs import trace\n"
        "def span(trace_id=None):\n"
        "    t0 = time.monotonic()\n"
        "    work()\n"
        "    dur = time.monotonic() - t0\n"
        "    tid = trace_id or str(uuid.uuid4())\n"
        "    t1 = trace.clock()\n"
        "    return dur, tid, trace.clock() - t1\n")
    m, s, i = scan(ast.parse(synthetic))
    assert m == [4, 6] and s == [6, 9] and i == [1]

    offenders, sanctioned_hits = [], 0
    for path in files:
        rel = str(path.relative_to(root)) if root in path.parents else path.name
        mono, subs, mints = scan(ast.parse(path.read_text(),
                                           filename=str(path)))
        if rel == SANCTIONED:
            assert not mints  # the sanctioned timer never mints random ids
            sanctioned_hits += len(mono) + len(subs)
            continue
        offenders += [f"{path}:{ln} (monotonic call/differencing)"
                      for ln in sorted(set(mono) | set(subs))]
        offenders += [f"{path}:{ln} (uuid/secrets import)" for ln in mints]
    assert sanctioned_hits > 0  # the scanner sees the sanctioned site
    assert not offenders, (
        "monotonic-clock timing or random id minting outside obs/trace.py "
        "— route intervals through trace.clock()/elapsed_ms() and use "
        "domain ids (replica, seq, cycle, version) as join keys: "
        + ", ".join(offenders))


def test_no_cost_constants_outside_cost_model():
    """`tdfo_tpu/plan/costs.py` is the single sanctioned home for measured
    per-descriptor cost constants (the executable docs/BUDGET.md): a
    `*_NS`/`*_US`/`*_MS` number hardcoded anywhere else is a fork of the
    chip measurements that the planner's calibration test cannot see, and
    the two copies WILL drift.  The rule: no module-level ALL_CAPS
    assignment whose name carries an NS/US/MS unit segment outside
    plan/costs.py (package + bench drivers).  Matching is on `_`-split
    SEGMENTS, so names like CONTINUOUS_COLS stay legal."""
    import ast
    from pathlib import Path

    import tdfo_tpu

    root = Path(tdfo_tpu.__file__).parent
    files = sorted(root.rglob("*.py")) + sorted(root.parent.glob("bench*.py"))
    sanctioned = root / "plan" / "costs.py"

    def is_cost_name(name: str) -> bool:
        if not name.isupper():
            return False
        return bool({"NS", "US", "MS"} & set(name.split("_")))

    offenders, sanctioned_hits = [], 0
    for path in files:
        tree = ast.parse(path.read_text(), filename=str(path))
        # module level only: locals named like units are not constant forks
        for node in tree.body:
            targets = []
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                targets = [node.target]
            for t in targets:
                if isinstance(t, ast.Name) and is_cost_name(t.id):
                    if path == sanctioned:
                        sanctioned_hits += 1
                    else:
                        offenders.append(f"{path}:{node.lineno} {t.id}")
    assert sanctioned_hits > 0  # the scanner sees the sanctioned module
    assert not offenders, (
        "measured cost constants outside tdfo_tpu/plan/costs.py (the single "
        "home for chip numbers — add it there with provenance and import "
        "it): " + ", ".join(offenders))


def test_no_precisionless_dots_in_kernel_code():
    """f32 `dot_general` INSIDE Mosaic kernels silently runs bf16 passes at
    default precision (~1e-3 rel error — enough to poison optimizer state;
    CLAUDE.md measured fact).  Every dot in ops/pallas_kernels.py must state
    its precision explicitly: HIGHEST where exactness matters, an explicit
    DEFAULT where bf16 MXU passes are the intent (the flash-attention dots).
    Implicit precision is how the bug comes back."""
    import ast
    from pathlib import Path

    import tdfo_tpu

    path = Path(tdfo_tpu.__file__).parent / "ops" / "pallas_kernels.py"
    tree = ast.parse(path.read_text(), filename=str(path))
    offenders = []
    n_dots = 0
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in ("dot_general", "dot")):
            continue
        n_dots += 1
        if "precision" not in {k.arg for k in node.keywords}:
            offenders.append(f"{path.name}:{node.lineno}")
    assert n_dots > 0  # the rule must actually be scanning something
    assert not offenders, (
        "dot_general/dot without explicit precision= in kernel code "
        "(default precision runs bf16 passes on f32 operands): "
        + ", ".join(offenders))


def test_no_bare_renames_outside_atomic_swap_helpers():
    """Crash safety is only as strong as its narrowest rename: a bare
    ``os.rename``/``os.replace`` (or keywordless ``.rename()`` method call —
    the ``Path.rename`` shape) outside the blessed helpers skips the
    fsync-file + replace + fsync-dir discipline, and a crash at that site
    leaves a torn pointer or a half-published bundle
    (``tdfo_tpu/serve/swap.py`` docstring).  The ONLY sanctioned sites are
    ``atomic_write_json`` and ``publish_dir`` there, plus
    ``utils/logrotate.py``'s ``rotate_path`` (which renames a CLOSED,
    complete diagnostics file — nothing half-written to protect).
    Keyworded ``.rename`` calls (pandas column renames) are host-side and
    exempt."""
    import ast
    from pathlib import Path

    import tdfo_tpu

    root = Path(tdfo_tpu.__file__).parent
    SANCTIONED = {("serve/swap.py", "atomic_write_json"),
                  ("serve/swap.py", "publish_dir"),
                  ("utils/logrotate.py", "rotate_path")}

    offenders, sanctioned_hits = [], 0
    for path in sorted(root.rglob("*.py")):
        rel = str(path.relative_to(root))
        tree = ast.parse(path.read_text(), filename=str(path))
        parents = {}
        for node in ast.walk(tree):
            for ch in ast.iter_child_nodes(node):
                parents[ch] = node

        def enclosing_funcs(node):
            out = []
            while node in parents:
                node = parents[node]
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    out.append(node.name)
            return out

        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)):
                continue
            f = node.func
            is_os_rename = (f.attr in ("rename", "replace")
                            and isinstance(f.value, ast.Name)
                            and f.value.id == "os")
            is_method_rename = (f.attr == "rename"
                                and not is_os_rename
                                and not node.keywords)
            if not (is_os_rename or is_method_rename):
                continue
            if any((rel, fn) in SANCTIONED for fn in enclosing_funcs(node)):
                sanctioned_hits += 1
                continue
            offenders.append(f"{path}:{node.lineno}")
    assert sanctioned_hits >= 3  # the scanner sees every blessed helper
    assert not offenders, (
        "bare rename outside serve/swap.py's atomic helpers (not crash-"
        "safe — route through atomic_write_json/publish_dir, or "
        "logrotate.rotate_path for closed diagnostics files): "
        + ", ".join(offenders))


def test_no_hand_rolled_retry_sleep_loops():
    """``utils/retry.py`` is the single backoff law (bounded attempts,
    jittered exponential delay, JSONL failure records, fault-injection
    hook).  A hand-rolled ``while/for + try + time.sleep`` retry loop
    anywhere else dodges all four — silent unbounded retries are how a
    wedged job burns a TPU reservation.  The detector flags any
    ``time.sleep`` call lexically inside a loop that also contains a
    ``try`` (the retry-loop shape); one-shot sleeps (the ``[faults]``
    stall/slow injections) stay legal.  The detector is self-tested on a
    synthetic offender because the package rightly contains none."""
    import ast
    from pathlib import Path

    import tdfo_tpu

    root = Path(tdfo_tpu.__file__).parent

    def retry_sleep_lines(tree):
        hits = []
        for node in ast.walk(tree):
            if not isinstance(node, (ast.For, ast.While)):
                continue
            body = list(ast.walk(node))
            has_try = any(isinstance(n, ast.Try) for n in body)
            for n in body:
                if (isinstance(n, ast.Call)
                        and isinstance(n.func, ast.Attribute)
                        and n.func.attr == "sleep"
                        and isinstance(n.func.value, ast.Name)
                        and n.func.value.id == "time"
                        and has_try):
                    hits.append(n.lineno)
        return hits

    synthetic = (
        "import time\n"
        "def naive(fn):\n"
        "    while True:\n"
        "        try:\n"
        "            return fn()\n"
        "        except OSError:\n"
        "            time.sleep(1.0)\n")
    assert retry_sleep_lines(ast.parse(synthetic)) == [7]

    offenders = []
    for path in sorted(root.rglob("*.py")):
        tree = ast.parse(path.read_text(), filename=str(path))
        offenders += [f"{path}:{ln}" for ln in retry_sleep_lines(tree)]
    assert not offenders, (
        "hand-rolled time.sleep retry loop (use utils/retry.py retry_call: "
        "bounded attempts, jittered backoff, JSONL records, fault hook): "
        + ", ".join(offenders))


def test_no_int8_casts_outside_quant_module():
    """``ops/quant.py`` owns the int8 grid: codes are only meaningful next
    to their per-row f32 (scale, offset) sidecar, and only
    ``quantize_rows``/``dequantize_rows`` know the grid (scale =
    (rmax-rmin)/255, offset = rmin + 128*scale, SR keyed by (step,
    table_id)).  An ``.astype(jnp.int8)`` / ``.view(jnp.int8)`` /
    ``bitcast_convert_type(..., jnp.int8)`` anywhere else mints codes with
    no sidecar (silent garbage on dequant) or re-grids stored codes
    outside the stamp the checkpoints refuse on — both unrecoverable
    after the fact.  Casts FROM int8 (``codes.astype(jnp.bfloat16)`` in
    the coarse scan) stay legal, as does host-side ``np.int8`` (labels,
    parquet).  Self-tested on a synthetic offender."""
    import ast
    from pathlib import Path

    import tdfo_tpu

    root = Path(tdfo_tpu.__file__).parent
    sanctioned = root / "ops" / "quant.py"

    def names_int8(node):
        # jnp.int8 / jax.numpy.int8, or the "int8" dtype string
        if isinstance(node, ast.Constant):
            return node.value == "int8"
        if not (isinstance(node, ast.Attribute) and node.attr == "int8"):
            return False
        base = node.value
        return (isinstance(base, ast.Name) and base.id == "jnp") or (
            isinstance(base, ast.Attribute) and base.attr == "numpy"
            and isinstance(base.value, ast.Name) and base.value.id == "jax")

    def int8_cast_lines(tree):
        hits = []
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in ("astype", "view",
                                           "bitcast_convert_type")):
                continue
            operands = list(node.args) + [k.value for k in node.keywords]
            if any(names_int8(a) for a in operands):
                hits.append(node.lineno)
        return hits

    synthetic = (
        "import jax.numpy as jnp\n"
        "def sneak(x):\n"
        "    return x.astype(jnp.int8)\n")
    assert int8_cast_lines(ast.parse(synthetic)) == [3]

    offenders, sanctioned_hits = [], 0
    for path in sorted(root.rglob("*.py")):
        lines = int8_cast_lines(ast.parse(path.read_text(),
                                          filename=str(path)))
        if path == sanctioned:
            sanctioned_hits += len(lines)
            continue
        offenders += [f"{path}:{ln}" for ln in lines]
    assert sanctioned_hits > 0  # the scanner sees quantize_rows' cast
    assert not offenders, (
        "cast to int8 outside ops/quant.py (codes without their (scale, "
        "offset) sidecar are garbage — route through quantize_rows/"
        "dequantize_rows): " + ", ".join(offenders))


def test_no_adhoc_jsonl_tailers():
    """``data/replay.py`` is the single sanctioned reader of line-oriented
    JSONL streams: it owns torn-tail truncation, seal digest verification,
    seq dedup and the byte-offset cursor that make replay exactly-once.  A
    hand-rolled ``for line in ...: json.loads(line)`` tailer anywhere else
    silently skips ALL of that — it would happily train on a torn or
    corrupted log.  The detector flags any ``json.loads`` call lexically
    inside a ``for``/``while`` loop in the package, outside the blessed
    readers: ``data/replay.py`` itself, ``plan/stats.py`` (which streams
    its OWN stats artifact, written atomically as a complete file — not a
    live log) and ``obs/aggregate.py`` (which assembles its OWN trace
    sinks — complete-line appends with no cursor to bypass; it skips, never
    parses, a live writer's torn tail).  Whole-file
    ``json.loads(path.read_text())`` reads are loop-free and stay legal.
    Self-tested on a synthetic offender."""
    import ast
    from pathlib import Path

    import tdfo_tpu

    root = Path(tdfo_tpu.__file__).parent
    BLESSED = {"data/replay.py", "plan/stats.py", "obs/aggregate.py"}

    def loop_loads_lines(tree):
        hits = []
        for node in ast.walk(tree):
            if not isinstance(node, (ast.For, ast.While)):
                continue
            for n in ast.walk(node):
                if (isinstance(n, ast.Call)
                        and isinstance(n.func, ast.Attribute)
                        and n.func.attr == "loads"
                        and isinstance(n.func.value, ast.Name)
                        and n.func.value.id == "json"):
                    hits.append(n.lineno)
        return sorted(set(hits))

    synthetic = (
        "import json\n"
        "def tail(path):\n"
        "    out = []\n"
        "    for line in open(path):\n"
        "        out.append(json.loads(line))\n"
        "    return out\n")
    assert loop_loads_lines(ast.parse(synthetic)) == [5]

    offenders, blessed_hits = [], 0
    for path in sorted(root.rglob("*.py")):
        rel = str(path.relative_to(root))
        lines = loop_loads_lines(ast.parse(path.read_text(),
                                           filename=str(path)))
        if rel in BLESSED:
            blessed_hits += len(lines)
            continue
        offenders += [f"{path}:{ln}" for ln in lines]
    assert blessed_hits > 0  # the scanner sees the sanctioned reader
    assert not offenders, (
        "ad-hoc JSONL line tailer (json.loads inside a loop) outside "
        "data/replay.py — it bypasses torn-tail recovery, seal digests and "
        "the exactly-once cursor; read through ReplayConsumer: "
        + ", ".join(offenders))


def test_no_pointer_writes_outside_swap_store_helpers():
    """The ``CURRENT``/``CANARY`` pointers are the serving fleet's single
    source of truth: every replica follows them, the canary state machine's
    crash windows are proven ONLY for the write orderings inside
    ``serve/swap.py`` (pointer-first canary publish, CURRENT-first
    promotion — see its docstring).  An ``atomic_write_json`` whose
    argument names either pointer anywhere else is an unvetted state
    machine transition: it can regress CURRENT past a verdict or publish
    an unvetted canary.  Sanctioned writers: ``_publish``, ``recover``,
    ``publish_canary``, ``promote_canary``, ``rollback_canary`` in
    serve/swap.py.  Self-tested on a synthetic offender."""
    import ast
    from pathlib import Path

    import tdfo_tpu

    root = Path(tdfo_tpu.__file__).parent
    SANCTIONED_FILE = "serve/swap.py"
    SANCTIONED_FUNCS = {"_publish", "recover", "publish_canary",
                        "promote_canary", "rollback_canary"}

    def names_pointer(node):
        # the module constants _CURRENT/_CANARY, or their literal values
        for n in ast.walk(node):
            if isinstance(n, ast.Name) and n.id in ("_CURRENT", "_CANARY"):
                return True
            if isinstance(n, ast.Constant) and n.value in ("CURRENT",
                                                           "CANARY"):
                return True
        return False

    def pointer_write_lines(tree):
        parents = {}
        for node in ast.walk(tree):
            for ch in ast.iter_child_nodes(node):
                parents[ch] = node

        def enclosing_funcs(node):
            out = []
            while node in parents:
                node = parents[node]
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    out.append(node.name)
            return out

        hits = []
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            is_writer = (isinstance(f, ast.Name)
                         and f.id == "atomic_write_json") or (
                isinstance(f, ast.Attribute)
                and f.attr == "atomic_write_json")
            if not is_writer:
                continue
            operands = list(node.args) + [k.value for k in node.keywords]
            if any(names_pointer(a) for a in operands):
                hits.append((node.lineno, enclosing_funcs(node)))
        return hits

    synthetic = (
        "from tdfo_tpu.serve.swap import atomic_write_json\n"
        "def hijack(store, v):\n"
        "    atomic_write_json(store.root / 'CURRENT', {'version': v})\n")
    syn = pointer_write_lines(ast.parse(synthetic))
    assert [(ln, fns) for ln, fns in syn] == [(3, ["hijack"])]

    offenders, sanctioned_hits = [], 0
    for path in sorted(root.rglob("*.py")):
        rel = str(path.relative_to(root))
        for ln, fns in pointer_write_lines(
                ast.parse(path.read_text(), filename=str(path))):
            if rel == SANCTIONED_FILE and SANCTIONED_FUNCS & set(fns):
                sanctioned_hits += 1
                continue
            offenders.append(f"{path}:{ln}")
    assert sanctioned_hits >= 3  # _publish + publish_canary + promote/recover
    assert not offenders, (
        "CURRENT/CANARY pointer write outside serve/swap.py's blessed "
        "helpers (unvetted canary state machine transition — route through "
        "publish_canary/promote_canary/rollback_canary): "
        + ", ".join(offenders))


def test_no_hard_exits_outside_fault_injector():
    """``os._exit`` skips every durability mechanism this repo builds on —
    atexit hooks, finally blocks, buffered writes.  That is exactly what
    the fault injector WANTS (a real preemption gives no notice, so the
    kill triggers in ``utils/faults.py`` must model it faithfully) and
    exactly what production code must never do: a convenience hard-exit in
    a serving or training path would turn an error into silent data loss
    that the kill/restart tests cannot see.  Self-tested on a synthetic
    offender."""
    import ast
    from pathlib import Path

    import tdfo_tpu

    root = Path(tdfo_tpu.__file__).parent

    def hard_exit_lines(tree):
        hits = []
        for node in ast.walk(tree):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "_exit"
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id == "os"):
                hits.append(node.lineno)
        return hits

    synthetic = (
        "import os\n"
        "def bail():\n"
        "    os._exit(1)\n")
    assert hard_exit_lines(ast.parse(synthetic)) == [3]

    offenders, sanctioned_hits = [], 0
    for path in sorted(root.rglob("*.py")):
        rel = str(path.relative_to(root))
        lines = hard_exit_lines(ast.parse(path.read_text(),
                                          filename=str(path)))
        if rel == "utils/faults.py":
            sanctioned_hits += len(lines)
            continue
        offenders += [f"{path}:{ln}" for ln in lines]
    assert sanctioned_hits > 0  # the scanner sees the kill triggers
    assert not offenders, (
        "os._exit outside utils/faults.py (skips atexit/finally/buffers — "
        "raise, or route deterministic kills through the fault injector): "
        + ", ".join(offenders))


def test_sockets_and_process_spawning_confined_to_serve_plumbing():
    """``serve/wire.py`` owns the socket monopoly (length-prefixed framing,
    max-frame refusal, connect-retry through the single backoff law) and
    ``serve/supervisor.py`` owns process spawning (respawn backoff, flap
    quarantine, child reaping).  A raw ``socket.socket`` or
    ``subprocess.Popen`` anywhere else in the package dodges framing,
    frame-size limits, retry budgets and child supervision — exactly the
    failure modes the kill -9 drills exist to catch.  ``subprocess.run``
    (bounded, reaped — ``native/__init__.py``) and pure lookups like
    ``socket.gethostname`` stay legal.  Self-tested on a synthetic
    offender."""
    import ast
    from pathlib import Path

    import tdfo_tpu

    root = Path(tdfo_tpu.__file__).parent
    BLESSED = {"serve/wire.py", "serve/supervisor.py"}
    SOCKET_CTORS = {"socket", "create_connection", "create_server",
                    "socketpair"}

    def spawn_lines(tree):
        hits = []
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and isinstance(node.func.value, ast.Name)):
                continue
            mod, attr = node.func.value.id, node.func.attr
            if (mod == "socket" and attr in SOCKET_CTORS) or (
                    mod == "subprocess" and attr == "Popen"):
                hits.append(node.lineno)
        return hits

    synthetic = (
        "import socket, subprocess\n"
        "def sneak(path):\n"
        "    s = socket.socket(socket.AF_UNIX)\n"
        "    host = socket.gethostname()\n"        # legal: pure lookup
        "    subprocess.run(['true'])\n"           # legal: bounded + reaped
        "    return subprocess.Popen(['sleep', '9'])\n")
    assert spawn_lines(ast.parse(synthetic)) == [3, 6]

    offenders, sanctioned_hits = [], 0
    for path in sorted(root.rglob("*.py")):
        rel = str(path.relative_to(root))
        lines = spawn_lines(ast.parse(path.read_text(), filename=str(path)))
        if rel in BLESSED:
            sanctioned_hits += len(lines)
            continue
        offenders += [f"{path}:{ln}" for ln in lines]
    assert sanctioned_hits >= 2  # wire's listener/dial + supervisor's Popen
    assert not offenders, (
        "raw socket/process spawning outside serve/wire.py + "
        "serve/supervisor.py (dodges framing, frame limits, retry budgets "
        "and child supervision — route through wire.listen/wire.connect or "
        "ProcessSupervisor): " + ", ".join(offenders))


def test_pad_mask_id_literals_confined_to_protocol_homes():
    """``models/bert4rec.py`` and ``data/seq_preprocessing.py`` are the two
    homes of the sequence id protocol (``PAD_ID = 0``, ``MASK = n_items +
    1``, items 1-based — torchrec/preprocessing.py:14-15).  A literal
    re-declaration anywhere else (``PAD_ID = 0`` in a serving module) is a
    fork: if the protocol ever moves, the fork silently pads with a REAL
    item id and every downstream ranking is garbage with no error.  The
    rule: no int-literal assignment to a PAD/MASK-named constant outside
    the two homes — serving code must IMPORT ``PAD_ID`` (derivations like
    ``mask_id = n_items + 1`` from an imported ``n_items`` stay legal, and
    the importer audit below proves the serve path actually does import).
    Self-tested on a synthetic offender."""
    import ast
    from pathlib import Path

    import tdfo_tpu

    root = Path(tdfo_tpu.__file__).parent
    HOMES = {"models/bert4rec.py", "data/seq_preprocessing.py"}

    def fork_lines(tree):
        hits = []
        for node in ast.walk(tree):
            targets = []
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                targets = [node.target]
            else:
                continue
            # int literals only: bools and None are not id constants, and
            # derivations (BinOp over an imported n_items) are not forks
            if not (isinstance(node.value, ast.Constant)
                    and type(node.value.value) is int):
                continue
            for t in targets:
                if isinstance(t, ast.Name) and (
                        {"PAD", "MASK"} & set(t.id.upper().split("_"))):
                    hits.append(node.lineno)
        return hits

    def pad_id_import_srcs(tree):
        return [node.module for node in ast.walk(tree)
                if isinstance(node, ast.ImportFrom) and node.module
                and any(a.name == "PAD_ID" for a in node.names)]

    synthetic = (
        "PAD_ID = 0\n"
        "MASK_TOKEN = 122\n"
        "from tdfo_tpu.models.bert4rec import PAD_ID\n"
        "def window(n_items):\n"
        "    mask_id = n_items + 1\n"   # legal: a derivation, not a fork
        "    return mask_id\n")
    tree = ast.parse(synthetic)
    assert fork_lines(tree) == [1, 2]
    assert pad_id_import_srcs(tree) == ["tdfo_tpu.models.bert4rec"]

    offenders, home_hits, importers = [], 0, {}
    for path in sorted(root.rglob("*.py")):
        rel = str(path.relative_to(root))
        tree = ast.parse(path.read_text(), filename=str(path))
        lines = fork_lines(tree)
        srcs = pad_id_import_srcs(tree)
        if srcs:
            importers[rel] = srcs
        if rel in HOMES:
            home_hits += len(lines)
            continue
        offenders += [f"{path}:{ln}" for ln in lines]
    assert home_hits >= 2  # the scanner sees both protocol homes
    assert not offenders, (
        "PAD/MASK id literal outside models/bert4rec.py + "
        "data/seq_preprocessing.py (a fork of the sequence id protocol — "
        "import PAD_ID instead): " + ", ".join(offenders))
    # every importer pulls PAD_ID from a protocol home (no third-party
    # re-export to drift behind), and the serve path IS an importer — the
    # rule has teeth where it matters
    home_mods = {"tdfo_tpu." + h[:-3].replace("/", ".") for h in HOMES}
    for rel, srcs in importers.items():
        assert set(srcs) <= home_mods, (rel, srcs)
    assert "serve/seq_scoring.py" in importers
