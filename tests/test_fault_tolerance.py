"""Fault-tolerance layer: retryable I/O, stream cursors, shard quarantine,
step-granular checkpoint/resume, and the NaN-rollback guard.

The end-to-end tests drive REAL Trainer runs with deterministic injected
faults (``tdfo_tpu/utils/faults.py``) and assert the headline contracts:
a killed-and-resumed run reproduces the uninterrupted run bit-identically,
and an injected NaN triggers a visible rollback instead of a poisoned model.
"""

import json
import math
import random
from pathlib import Path

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from tdfo_tpu.core.config import read_configs
from tdfo_tpu.utils import faults, retry
from tdfo_tpu.utils.faults import FaultInjector, FaultSpec


@pytest.fixture(autouse=True)
def _clean_fault_state():
    """Injector and failure-log path are process-global; never leak them."""
    yield
    faults.configure(None)
    retry.set_failure_log(None)


# ----------------------------------------------------------------- retry


def test_retry_backoff_and_records(tmp_path):
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise OSError("transient")
        return "ok"

    delays: list[float] = []
    retry.set_failure_log(tmp_path / "retries.jsonl")
    out = retry.retry_call(flaky, description="unit", attempts=4,
                           base_delay=0.1, max_delay=1.0, jitter=0.0,
                           sleep=delays.append, rng=random.Random(0))
    assert out == "ok" and calls["n"] == 3
    assert delays == [0.1, 0.2]  # exponential, jitter=0
    recs = [json.loads(l) for l in
            (tmp_path / "retries.jsonl").read_text().splitlines()]
    assert [r["attempt"] for r in recs] == [1, 2]
    assert all(r["description"] == "unit" and not r["final"] for r in recs)


def test_retry_exhaustion_reraises():
    def dead():
        raise OSError("gone for good")

    with pytest.raises(OSError, match="gone for good"):
        retry.retry_call(dead, description="dead", attempts=3,
                         sleep=lambda d: None)
    rec = retry.recent_failures()[-1]
    assert rec["final"] and rec["attempt"] == 3


def test_retry_passes_through_other_errors():
    calls = {"n": 0}

    def boom():
        calls["n"] += 1
        raise ValueError("not transient")

    with pytest.raises(ValueError):
        retry.retry_call(boom, description="boom", sleep=lambda d: None)
    assert calls["n"] == 1  # no retry on non-retry_on exception types


def test_injected_io_failure_retried_once():
    faults.configure(FaultSpec(fail_io_nth=1))
    sleeps: list[float] = []
    out = retry.retry_call(lambda: "ok", description="io", sleep=sleeps.append)
    assert out == "ok" and len(sleeps) == 1  # first attempt failed, one retry
    # the injection is one-shot: later protected ops run clean
    assert retry.retry_call(lambda: "ok2", description="io2",
                            sleep=sleeps.append) == "ok2"
    assert len(sleeps) == 1


# ---------------------------------------------------------------- faults


def test_kill_marker_is_one_shot(tmp_path):
    inj = FaultInjector(FaultSpec(kill_at_step=5), tmp_path)
    assert not inj.kill_due(4)
    assert inj.kill_due(5) and inj.kill_due(9)
    (tmp_path / "faults_kill.marker").write_text("already fired")
    assert not inj.kill_due(5)  # restart of the same command must converge


def test_poison_batch():
    inj = FaultInjector(FaultSpec(nan_at_step=2))
    b = {"i": np.arange(4, dtype=np.int32), "f": np.ones(4, np.float32)}
    assert inj.poison_batch(b, 1) is b  # wrong step: untouched
    out = inj.poison_batch(b, 2)
    assert np.isnan(out["f"]).all()
    assert np.isfinite(b["f"]).all()  # original batch not mutated
    with pytest.raises(ValueError, match="float"):
        inj.poison_batch({"i": np.arange(3, dtype=np.int32)}, 2)


# --------------------------------------------------- stream cursor contract


def _write_shards(d: Path, n_shards=3, rows=40, seed=0) -> list[str]:
    rng = np.random.default_rng(seed)
    paths = []
    for i in range(n_shards):
        t = pa.table({
            "a": pa.array(rng.integers(0, 100, rows).astype(np.int32)),
            "b": pa.array(rng.random(rows).astype(np.float32)),
        })
        p = d / f"part_{i}.parquet"
        pq.write_table(t, p)
        paths.append(str(p))
    return paths


def _collect(stream):
    return [{k: v.copy() for k, v in b.items()} for b in stream]


def test_parquet_stream_cursor_roundtrip(tmp_path):
    from tdfo_tpu.data.loader import ParquetStream

    files = _write_shards(tmp_path)
    kw = dict(batch_size=8, shuffle=True, buffer_size=64, seed=5,
              drop_last=True)
    full = ParquetStream(files, **kw)
    full.set_epoch(1)
    ref = _collect(full)
    assert len(ref) >= 4
    assert full.state_dict()["batches_emitted"] == len(ref)

    for skip in (0, 1, len(ref) - 1):
        resumed = ParquetStream(files, **kw)
        resumed.set_epoch(1)
        resumed.load_state_dict({"seed": 5, "epoch": 1,
                                 "batches_emitted": skip})
        tail = _collect(resumed)
        assert len(tail) == len(ref) - skip
        for got, want in zip(tail, ref[skip:]):
            for k in want:
                np.testing.assert_array_equal(got[k], want[k])

    # a cursor recorded under a different seed pins a DIFFERENT batch
    # sequence — resuming with it must refuse
    other = ParquetStream(files, **{**kw, "seed": 6})
    with pytest.raises(ValueError, match="seed"):
        other.load_state_dict({"seed": 5, "epoch": 1, "batches_emitted": 1})


def test_map_stream_cursor_roundtrip(tmp_path):
    from tdfo_tpu.data.loader import MapStream

    files = _write_shards(tmp_path)
    kw = dict(batch_size=8, shuffle=True, seed=5, drop_last=True)
    full = MapStream(files, **kw)
    full.set_epoch(2)
    ref = _collect(full)
    assert len(ref) >= 4
    resumed = MapStream(files, **kw)
    resumed.set_epoch(2)
    resumed.load_state_dict({"seed": 5, "epoch": 2, "batches_emitted": 2})
    tail = _collect(resumed)
    assert len(tail) == len(ref) - 2
    for got, want in zip(tail, ref[2:]):
        for k in want:
            np.testing.assert_array_equal(got[k], want[k])


def test_bad_shard_quarantine(tmp_path):
    from tdfo_tpu.data.loader import ParquetStream

    files = _write_shards(tmp_path, n_shards=3, rows=40)
    Path(files[1]).write_bytes(b"this is not a parquet file")
    kw = dict(batch_size=10, shuffle=False, buffer_size=64, drop_last=False)

    # within budget: the bad shard is skipped, every good row still arrives
    tolerant = ParquetStream(files, max_bad_shards=1, **kw)
    tolerant.set_epoch(0)
    rows = sum(len(next(iter(b.values()))) for b in tolerant)
    assert rows == 80  # 2 good shards x 40
    assert list(tolerant._bad_files) == [files[1]]

    # budget exceeded: data that rotten is a pipeline bug -> fatal
    strict = ParquetStream(files, max_bad_shards=0, **kw)
    strict.set_epoch(0)
    with pytest.raises(RuntimeError, match="max_bad_shards"):
        list(strict)


# --------------------------------------------------- checkpoint cursor I/O


def test_checkpoint_cursor_sidecar_and_prune(tmp_path):
    import jax.numpy as jnp

    from tdfo_tpu.train.checkpoint import CheckpointManager

    state = {"w": jnp.arange(4.0)}
    mgr = CheckpointManager(tmp_path, max_to_keep=2)
    for step in (3, 6, 9):
        mgr.save(step, state,
                 cursor={"epoch": 0, "step": step, "epoch_complete": False,
                         "global_step": step})
    # max_to_keep GC'd step 3; its cursor sidecar must not linger
    assert not (tmp_path / "cursor_3.json").exists()
    assert mgr.read_cursor(9)["step"] == 9
    step, _, cursor = mgr.restore(state)
    assert step == 9 and cursor["global_step"] == 9
    mgr.close()


# ------------------------------------------------------------- end to end


@pytest.fixture(scope="module")
def fault_data(tmp_path_factory):
    from tdfo_tpu.data.ctr_preprocessing import run_ctr_preprocessing
    from tdfo_tpu.data.synthetic import write_synthetic_goodreads

    d = tmp_path_factory.mktemp("gr_faults")
    write_synthetic_goodreads(d, n_users=80, n_books=120,
                              interactions_per_user=(15, 40), seed=7)
    ctr = run_ctr_preprocessing(d)
    return d, ctr


def _cfg(d, ctr, **kw):
    return read_configs(
        None, data_dir=d, model="twotower", n_epochs=1, learning_rate=3e-3,
        embed_dim=8, per_device_train_batch_size=16,
        per_device_eval_batch_size=16, shuffle_buffer_size=500,
        log_every_n_steps=2, size_map=ctr, **kw)


def test_midepoch_kill_resume_bit_identical(fault_data, tmp_path, monkeypatch):
    """The tentpole contract: kill mid-epoch AFTER a step-granular
    checkpoint, restart the same command, and the run must resume from the
    exact batch and land on bit-identical final state and metrics."""
    import jax

    from tdfo_tpu.train.checkpoint import CheckpointManager
    from tdfo_tpu.train.trainer import Trainer

    d, ctr = fault_data

    class Killed(SystemExit):
        pass

    def fake_exit(code):
        raise Killed(code)

    monkeypatch.setattr(faults.os, "_exit", fake_exit)
    base = dict(checkpoint_dir=str(tmp_path / "ckpt"),
                checkpoint_every_n_steps=3, faults={"kill_at_step": 5})
    with pytest.raises(Killed):
        Trainer(_cfg(d, ctr, **base), log_dir=tmp_path / "log1").fit()
    assert (tmp_path / "ckpt" / "faults_kill.marker").exists()

    mgr = CheckpointManager(tmp_path / "ckpt")
    s = mgr.latest_step()
    cursor = mgr.read_cursor(s)
    mgr.close()
    # the newest checkpoint is MID-epoch (step granular, not epoch granular)
    assert cursor is not None and not cursor["epoch_complete"]
    assert cursor["epoch"] == 0 and cursor["step"] == 3

    # restart the SAME command: the marker disarms the kill; the run resumes
    # from batch 3 and completes
    tr2 = Trainer(_cfg(d, ctr, **base), log_dir=tmp_path / "log2")
    m_resumed = tr2.fit()
    recs = [json.loads(l) for l in
            (tmp_path / "log2" / "metrics.jsonl").read_text().splitlines()]
    assert any(r.get("resumed_mid_epoch") == 0 and r.get("step") == 3
               for r in recs)

    # uninterrupted reference run, same config modulo the fault/ckpt dir
    tr_ref = Trainer(_cfg(d, ctr, checkpoint_dir=str(tmp_path / "ckpt_ref"),
                          checkpoint_every_n_steps=3),
                     log_dir=tmp_path / "log3")
    m_ref = tr_ref.fit()

    assert m_resumed == m_ref  # bit-identical eval metrics
    for a, b in zip(jax.tree.leaves(tr2.state), jax.tree.leaves(tr_ref.state)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_nan_rollback_end_to_end(fault_data, tmp_path):
    """An injected NaN batch must trigger the guard: a ``rollback`` record in
    metrics.jsonl, state restored to the last refreshed snapshot, and FINITE
    final metrics — not a silently NaN-poisoned model."""
    import jax

    from tdfo_tpu.train.trainer import Trainer

    d, ctr = fault_data
    cfg = _cfg(d, ctr, faults={"nan_at_step": 4}, nonfinite_tolerance=2,
               snapshot_every_n_steps=2)
    tr = Trainer(cfg, log_dir=tmp_path / "log")
    metrics = tr.fit()
    assert metrics and all(math.isfinite(v) for v in metrics.values())
    for leaf in jax.tree.leaves(tr.state.params):
        assert np.isfinite(np.asarray(leaf)).all()

    recs = [json.loads(l) for l in
            (tmp_path / "log" / "metrics.jsonl").read_text().splitlines()]
    rollbacks = [r for r in recs if r.get("rollback")]
    assert rollbacks, "no rollback record despite injected NaN"
    rb = rollbacks[0]
    # snapshot_every_n_steps=2 with a clean first window: the snapshot
    # refreshed at step 2, so the rollback restores there — bounded loss,
    # not an epoch restart
    assert rb["restored_to_step"] == 2
    assert rb["skipped_steps"] >= 2
    assert not math.isfinite(rb["nonfinite_loss"])
    epoch_rec = [r for r in recs if "train_loss_epoch" in r][-1]
    assert math.isfinite(epoch_rec["train_loss_epoch"])


def test_injected_io_failure_inside_training_run(fault_data, tmp_path):
    """fail_io_nth exercises the retry path on the REAL data pipeline: the
    first protected I/O op fails once, the retry succeeds, the failure lands
    in retries.jsonl, and training is unaffected."""
    from tdfo_tpu.train.trainer import Trainer

    d, ctr = fault_data
    cfg = _cfg(d, ctr, faults={"fail_io_nth": 1})
    metrics = Trainer(cfg, log_dir=tmp_path / "log").fit()
    assert all(math.isfinite(v) for v in metrics.values())
    recs = [json.loads(l) for l in
            (tmp_path / "log" / "retries.jsonl").read_text().splitlines()]
    assert any("[faults] injected I/O failure" in r["error"] for r in recs)
    assert all(not r["final"] for r in recs)  # every failure was retried away
