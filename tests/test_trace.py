"""Causal tracing (``[telemetry] trace``): sinks, assembly, zero-cost pin.

The tentpole contracts (``tdfo_tpu/obs/trace.py`` + ``obs/aggregate.py``):

  * **Off is free** — unconfigured ``emit``/``span`` touch no files, and a
    traced train step's jaxpr is BYTE-identical with tracing on: spans are
    host-side emits at serve/replay/cycle boundaries, nothing rides the
    step program.
  * **Sinks are crash-safe JSONL** — one complete line per append, rotated
    through the shared ``utils/logrotate`` machinery; the assembler skips
    (never guesses at) a torn tail.
  * **Ids join causally** — a served request's ``(replica, seq)`` flows
    from the frontend span through the replay batch into the online-cycle
    span; ``assemble`` reconstructs the chain, computes freshness lag from
    the only cross-process clock (wall ``ts``), and dedups cycle spans by
    cycle number so a killed-and-redone cycle assembles exactly once.

The multi-process version of the exactly-once audit (kill-drill fleet runs)
lives in tests/test_fleet.py; this file owns the single-process semantics.
"""

import dataclasses
import json
import re

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax

from tdfo_tpu.obs import trace
from tdfo_tpu.obs.aggregate import (assemble, chrome_trace, format_report,
                                    load_spans, percentile)

SCHEMA = {"x": (np.int32, ()), "y": (np.float32, ()),
          "label": (np.int8, ())}


@pytest.fixture(autouse=True)
def _detach_trace():
    yield
    trace.configure(None)


# ------------------------------------------------------------ sink basics


def test_emit_off_is_noop(tmp_path):
    assert not trace.active()
    trace.emit("frontend", "serve_request", seq=1)
    with trace.span("online", "stage", cycle=1) as extra:
        extra["verdict"] = "promote"
    assert list(tmp_path.iterdir()) == []  # nothing anywhere
    assert load_spans(tmp_path) == []


def test_emit_writes_complete_lines_and_load_spans_orders(tmp_path):
    trace.configure(tmp_path)
    trace.emit("frontend", "serve_request", replica=0, seq=1)
    trace.emit("replay", "replay_batch", rows=4)
    trace.emit("frontend", "serve_request", replica=0, seq=2)
    spans = load_spans(tmp_path)
    assert [s["span"] for s in spans] == [1, 2, 3]  # ts+id order
    assert (tmp_path / "trace-frontend.jsonl").exists()
    assert (tmp_path / "trace-replay.jsonl").exists()
    for p in tmp_path.glob("trace-*.jsonl"):
        for line in p.read_text().splitlines():
            json.loads(line)  # every line complete


def test_trace_sink_rotates_at_size(tmp_path):
    trace.configure(tmp_path, rotate_bytes=400)
    for i in range(40):
        trace.emit("frontend", "serve_request", replica=0, seq=i)
    main = tmp_path / "trace-frontend.jsonl"
    overflow = tmp_path / "trace-frontend.jsonl.1"
    assert overflow.exists()
    # the live file is bounded (absent right after a rotation, until the
    # next emit recreates it — the retries.jsonl shape)
    if main.exists():
        assert main.stat().st_size < 400 + 200
    # one generation of history is the contract: the survivors are a
    # contiguous, complete, ordered SUFFIX of the emitted spans
    seqs = [s["seq"] for s in load_spans(tmp_path)]
    assert seqs == list(range(seqs[0], 40))


def test_span_ids_deterministic_across_reconfigure(tmp_path):
    trace.configure(tmp_path / "a")
    for i in range(3):
        trace.emit("online", "stage", stage=f"s{i}")
    ids_a = [s["span"] for s in load_spans(tmp_path / "a")]
    trace.configure(tmp_path / "b")  # a restarted run
    for i in range(3):
        trace.emit("online", "stage", stage=f"s{i}")
    ids_b = [s["span"] for s in load_spans(tmp_path / "b")]
    assert ids_a == ids_b == [1, 2, 3]  # counter, never uuid/random


def test_span_contextmanager_emits_dur_even_on_raise(tmp_path):
    trace.configure(tmp_path)
    with pytest.raises(RuntimeError):
        with trace.span("online", "stage", cycle=2, stage="train") as extra:
            extra["steps"] = 5
            raise RuntimeError("killed mid-stage")
    (s,) = load_spans(tmp_path)
    assert s["kind"] == "stage" and s["stage"] == "train"
    assert s["steps"] == 5 and s["dur_ms"] >= 0.0


def test_load_spans_skips_torn_tail(tmp_path):
    trace.configure(tmp_path)
    trace.emit("replay", "replay_batch", rows=4)
    with open(tmp_path / "trace-replay.jsonl", "a") as f:
        f.write('{"span": 2, "ts": 1.0, "compo')  # kill mid-append
    spans = load_spans(tmp_path)
    assert len(spans) == 1 and spans[0]["rows"] == 4


# ------------------------------------------------------------- percentile


def test_percentile_nearest_rank():
    assert percentile([], 99) is None
    assert percentile([7.0], 50) == 7.0
    assert percentile([1, 2, 3, 4], 50) == 2.0  # nearest-rank, not interp
    samples = list(range(1, 101))
    assert percentile(samples, 99) == 99
    assert percentile(samples, 100) == 100
    assert percentile(samples, 0) == 1


# -------------------------------------------------------- causal assembly


def _cycle_span(cycle, *, version, verdict="promote", consumed=(),
                reason=None, digest="d0"):
    trace.emit("online", "online_cycle", cycle=cycle, verdict=verdict,
               reason=reason, version=version, digest=digest,
               step_begin=(cycle - 1) * 4, step_end=cycle * 4,
               dur_ms=12.5, consumed=[list(c) for c in consumed])


def test_end_to_end_id_chain(tmp_path):
    """Frontend serve spans -> replay batch spans -> a synthetic cycle span:
    ``assemble`` joins them on domain ids and computes freshness lag."""
    from tdfo_tpu.data.replay import ReplayConsumer, RequestLog
    from tdfo_tpu.serve.frontend import MicroBatcher

    trace.configure(tmp_path / "trace")
    log = RequestLog(tmp_path / "rl")
    mb = MicroBatcher(lambda b: np.asarray(b["x"], np.float32) * 2.0,
                      buckets=(8,), max_batch=8, batch_deadline_ms=0.0,
                      request_log=log)
    for i in range(4):
        mb.run([(f"q{i}", {
            "x": np.arange(i * 2, i * 2 + 2, dtype=np.int32),
            "y": np.full(2, 0.5, np.float32),
            "label": np.ones(2, np.int8)})])
    log.close()

    c = ReplayConsumer(tmp_path / "rl", schema=SCHEMA, batch_size=4)
    consumed = []
    while (out := c.next_batch()) is not None:
        consumed.extend(out[1])
    _cycle_span(1, version=7, consumed=consumed)
    # the produced version goes live on a replica (what lag is measured to)
    trace.emit("fleet", "replica_sync", replica=0, version=7, digest="d0",
               canary=False, skewed=False, slow=False)

    report = assemble(load_spans(tmp_path / "trace"))
    assert report["n_requests"] == 4 and report["n_replay_batches"] == 2
    (cyc,) = report["cycles"]
    assert cyc["verdict"] == "promote" and cyc["version"] == 7
    # flat single-log consumer -> replica 0 join keys, matching the
    # single frontend's spans; seqs are the log's own 1-based numbers
    assert cyc["n_consumed_requests"] == len(cyc["consumed_keys"]) == 4
    assert [k[1] for k in cyc["consumed_keys"]] == [1, 2, 3, 4]
    assert cyc["freshness_lag_s"] is not None and cyc["freshness_lag_s"] >= 0


def test_assemble_dedups_cycle_spans_last_wins(tmp_path):
    """A killed cycle is redone after restart and emits its span again —
    exactly-once accounting keeps the LAST (durable) emission."""
    trace.configure(tmp_path)
    _cycle_span(1, version=5, verdict="rollback", reason="auc",
                consumed=[(0, 1, 0, 2)])
    _cycle_span(1, version=6, verdict="promote",
                consumed=[(0, 1, 0, 2)])  # the redo, after restart
    _cycle_span(2, version=7, consumed=[(0, 2, 0, 2)])
    report = assemble(load_spans(tmp_path))
    assert [c["cycle"] for c in report["cycles"]] == [1, 2]
    assert report["cycles"][0]["version"] == 6  # last durable emission wins
    # consumed keys tile the request space exactly once across cycles
    all_keys = [k for c in report["cycles"] for k in c["consumed_keys"]]
    assert len(all_keys) == len(set(all_keys))


def test_assemble_merges_stage_and_heartbeat_spans(tmp_path):
    trace.configure(tmp_path)
    for stage, ms in (("replay", 3.0), ("train", 40.0), ("canary", 9.0)):
        trace.emit("online", "stage", cycle=1, stage=stage, dur_ms=ms)
    _cycle_span(1, version=3, consumed=[(1, 0, 2)])
    for i in range(10):
        trace.emit("fleet", "heartbeat", replica=i % 2, version=3,
                   ms=1.0 + i, canary=(i % 2 == 1), queue_depth=i,
                   batch_fill=0.5)
    report = assemble(load_spans(tmp_path))
    (cyc,) = report["cycles"]
    assert cyc["stages"] == {"replay": 3.0, "train": 40.0, "canary": 9.0}
    fl = report["fleet"]
    assert fl["heartbeats"]["n"] == 10
    assert fl["canary_heartbeats"]["n"] == fl["stable_heartbeats"]["n"] == 5
    assert fl["canary_heartbeats"]["p50_ms"] > fl["stable_heartbeats"]["p50_ms"]
    assert fl["per_replica"][0]["last_queue_depth"] == 8
    assert fl["per_replica"][1]["last_batch_fill"] == 0.5
    # the console report renders every section without raising
    text = format_report(report)
    assert "cycle 1" in text and "replica 0" in text


def test_peeked_batches_emit_no_replay_spans(tmp_path):
    """Shadow-eval reads (peek_batches) are uncommitted and must not count
    toward the exactly-once replay accounting."""
    from tdfo_tpu.data.replay import ReplayConsumer, RequestLog

    log = RequestLog(tmp_path / "rl")
    for i in range(6):
        log.append({"event": "serve_request", "request": f"r{i}", "rows": 2,
                    "outcome": "ok",
                    "features": {"x": [i * 2, i * 2 + 1], "y": [0.5, 0.5],
                                 "label": [1, 1]}})
    log.close()
    trace.configure(tmp_path / "trace")
    c = ReplayConsumer(tmp_path / "rl", schema=SCHEMA, batch_size=4)
    assert len(c.peek_batches(2)) == 2  # held-out gate slice: no spans
    assert load_spans(tmp_path / "trace") == []
    assert c.next_batch() is not None  # a committed read: one span
    (s,) = load_spans(tmp_path / "trace")
    assert s["kind"] == "replay_batch" and s["component"] == "replay"


def test_chrome_trace_shape(tmp_path):
    trace.configure(tmp_path)
    trace.emit("online", "stage", cycle=1, stage="train", dur_ms=40.0)
    trace.emit("frontend", "serve_request", replica=2, seq=9,
               latency_ms=1.5)
    obj = chrome_trace(load_spans(tmp_path))
    events = obj["traceEvents"]
    meta = [e for e in events if e["ph"] == "M"]
    assert {m["args"]["name"] for m in meta} == {"online", "frontend"}
    (complete,) = [e for e in events if e["ph"] == "X"]
    assert complete["name"] == "stage:train" and complete["dur"] == 40e3
    (instant,) = [e for e in events if e["ph"] == "i"]
    assert instant["tid"] == 2 and instant["args"]["seq"] == 9
    json.dumps(obj)  # the whole object must serialize


# ----------------------------------------------- zero-cost jaxpr pin


def test_trace_on_step_jaxpr_byte_identical(mesh8, tmp_path):
    """``trace = true`` must add ZERO equations to the train step: spans
    are host-side only, so the step jaxpr with a live trace sink is
    byte-identical to the untraced build (the ``[telemetry] counters``
    laziness pin of test_telemetry.py, applied to tracing)."""
    from tdfo_tpu.models.dlrm import DLRMBackbone
    from tdfo_tpu.ops.sparse import sparse_optimizer
    from tdfo_tpu.parallel.embedding import (EmbeddingSpec,
                                             ShardedEmbeddingCollection)
    from tdfo_tpu.train.ctr import ctr_sparse_forward
    from tdfo_tpu.train.sparse_step import (SparseTrainState,
                                            make_sparse_train_step)

    cats = ("c0", "c1")
    sizes = {"c0": 11, "c1": 40}
    specs = [EmbeddingSpec(c, sizes[c], 8, features=(c,), sharding="row")
             for c in cats]
    coll = ShardedEmbeddingCollection(specs, mesh=mesh8, stack_tables=True)
    bb = DLRMBackbone(embed_dim=8, cat_columns=cats, cont_columns=("x0",))
    dummy_e = {c: jnp.zeros((1, 8), jnp.float32) for c in cats}
    dummy_c = {"x0": jnp.zeros((1,), jnp.float32)}
    state = SparseTrainState.create(
        dense_params=bb.init(jax.random.key(1), dummy_e, dummy_c)["params"],
        tx=optax.adam(1e-2),
        tables=coll.init(jax.random.key(0)),
        sparse_opt=sparse_optimizer("rowwise_adagrad", lr=1e-2,
                                    weight_decay=0.0,
                                    small_vocab_threshold=100))
    step = make_sparse_train_step(coll, ctr_sparse_forward(bb),
                                  mode="gspmd", donate=False, jit=False)
    rr = np.random.default_rng(5)
    batch = {c: jnp.asarray(rr.integers(0, sizes[c], 16), jnp.int32)
             for c in cats}
    batch["x0"] = jnp.asarray(rr.random(16, dtype=np.float32))
    batch["label"] = jnp.asarray(rr.integers(0, 2, 16), jnp.float32)

    norm = lambda j: re.sub(r"0x[0-9a-f]+", "0xADDR", str(j))
    j_off = norm(jax.make_jaxpr(step)(state, batch))
    trace.configure(tmp_path)
    trace.emit("online", "stage", cycle=1, stage="probe")  # sink is LIVE
    j_on = norm(jax.make_jaxpr(step)(state, batch))
    assert j_on == j_off


# ---------------------------------------------- rotation of sibling sinks


def test_events_log_rotates_at_size(tmp_path):
    from tdfo_tpu.obs import events

    path = tmp_path / "events.jsonl"
    events.configure(path, rotate_bytes=400)
    try:
        for i in range(40):
            events.record("compile", name=f"fn{i}", dur_ms=float(i))
    finally:
        events.configure(None)
    overflow = tmp_path / "events.jsonl.1"
    assert overflow.exists()
    if path.exists():
        assert path.stat().st_size < 400 + 200
    names = []
    for p in (overflow, path):
        if not p.exists():
            continue
        for line in p.read_text().splitlines():
            names.append(json.loads(line)["name"])  # every line complete
    # one generation of history: a contiguous ordered suffix survives
    first = int(names[0][2:])
    assert names == [f"fn{i}" for i in range(first, 40)]


def test_heartbeat_log_rotates_at_size(tmp_path):
    from tdfo_tpu.obs.watchdog import StallWatchdog

    path = tmp_path / "heartbeat.jsonl"
    wd = StallWatchdog(path, 10.0, rotate_bytes=300)
    for i in range(30):
        wd.beat(i)
        wd.check()  # the daemon body writes the heartbeat record
    overflow = tmp_path / "heartbeat.jsonl.1"
    assert overflow.exists()
    if path.exists():
        assert path.stat().st_size < 300 + 300
    steps = []
    for p in (overflow, path):
        if not p.exists():
            continue
        for line in p.read_text().splitlines():
            steps.append(json.loads(line)["last_step"])
    assert steps == sorted(steps)  # one generation retired, order preserved


# ------------------------------------------------------ launch.py obs


def test_launch_obs_subcommand(tmp_path, capsys):
    from tdfo_tpu.launch import main

    out_dir = tmp_path / "run"
    trace.configure(out_dir / "trace")
    trace.emit("frontend", "serve_request", replica=0, seq=1,
               latency_ms=2.0, version=3, digest="d0")
    trace.emit("replay", "replay_batch", rows=4, consumed=[[1, 0, 2]])
    _cycle_span(1, version=3, consumed=[(1, 0, 2)])
    trace.configure(None)
    cfgp = tmp_path / "config.toml"
    cfgp.write_text(f'checkpoint_dir = "{out_dir}"\n')
    assert main(["obs", "--config", str(cfgp)]) == 0
    out = capsys.readouterr().out
    assert "cycle 1" in out and "verdict=promote" in out
    chrome = json.loads((out_dir / "trace" / "chrome_trace.json").read_text())
    assert chrome["traceEvents"]

    (tmp_path / "empty.toml").write_text(
        f'checkpoint_dir = "{tmp_path / "nothing"}"\n')
    with pytest.raises(SystemExit, match="no trace"):
        main(["obs", "--config", str(tmp_path / "empty.toml")])
