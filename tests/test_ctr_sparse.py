"""Sparse CTR (DMP regime) tests: dense-vs-sparse parity, DLRM, trainer wiring.

The torchrec-parity claim for the CTR family (``torchrec/train.py:235-254``
applied to TwoTower/DLRM): the 7 tables live in a ShardedEmbeddingCollection
with row-sparse in-backward Adam, dense towers under optax.  The parity bar:
with batches that touch EVERY row of every table each step, lazy (sparse)
Adam is mathematically identical to dense Adam, so the DMP regime must
reproduce the dense regime's loss trajectory exactly.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from tdfo_tpu.models.dlrm import DLRMBackbone
from tdfo_tpu.models.twotower import (
    TwoTowerBackbone,
    ctr_embedding_specs,
    init_twotower,
)
from tdfo_tpu.ops.sparse import sparse_optimizer
from tdfo_tpu.parallel.embedding import ShardedEmbeddingCollection
from tdfo_tpu.train.ctr import ctr_sparse_forward, make_ctr_sparse_eval_step
from tdfo_tpu.train.sparse_step import SparseTrainState, make_sparse_train_step
from tdfo_tpu.train.state import TrainState
from tdfo_tpu.train.step import make_train_step

# all vocab sizes even (divisible by the 2-shard model axis) and <= B so a
# single batch can cover every row
SIZE_MAP = {
    "user": 32, "item": 24, "language": 8, "is_ebook": 2,
    "format": 8, "publisher": 16, "pub_decade": 16,
}
_INPUT_KEYS = {
    "user": "user_id", "item": "item_id", "language": "language",
    "is_ebook": "is_ebook", "format": "format", "publisher": "publisher",
    "pub_decade": "pub_decade",
}
B, D = 64, 8


def full_coverage_batch(rng: np.random.Generator, b: int = B) -> dict:
    """Every row of every table appears in the batch, so lazy == dense Adam."""
    batch = {}
    for feat, key in _INPUT_KEYS.items():
        v = SIZE_MAP[feat]
        ids = np.concatenate([np.arange(v), rng.integers(0, v, b - v)]).astype(np.int32)
        rng.shuffle(ids)
        batch[key] = ids
    batch["avg_rating"] = rng.random(b, dtype=np.float32)
    batch["num_pages"] = rng.random(b, dtype=np.float32)
    batch["label"] = rng.integers(0, 2, b).astype(np.float32)
    return batch


def _sparse_setup(mesh, sharding="row", lr=1e-2):
    coll = ShardedEmbeddingCollection(
        ctr_embedding_specs(SIZE_MAP, D, sharding), mesh=mesh
    )
    tables = coll.init(jax.random.key(0))
    backbone = TwoTowerBackbone(embed_dim=D)
    dummy_embs = {f: jnp.zeros((1, D)) for f in coll.features()}
    dummy_cont = {"avg_rating": jnp.zeros((1,)), "num_pages": jnp.zeros((1,))}
    dense = backbone.init(jax.random.key(1), dummy_embs, dummy_cont)["params"]
    state = SparseTrainState.create(
        dense_params=dense,
        tx=optax.adam(lr),
        tables=tables,
        sparse_opt=sparse_optimizer("adam", lr=lr),
    )
    return coll, backbone, state


def test_dense_and_sparse_twotower_trajectories_match(mesh8):
    """Same init, same batches, full row coverage -> identical loss curves in
    the dense (nn.Embed + dense Adam) and DMP (collection + row-sparse Adam)
    regimes, with the tables row-sharded over the model axis in the latter."""
    lr = 1e-2
    model, params = init_twotower(jax.random.key(3), SIZE_MAP, D)
    dense_state = TrainState.create(
        apply_fn=model.apply, params=params, tx=optax.adam(lr)
    )
    dense_step = make_train_step(mesh=mesh8, donate_state=False)

    coll, backbone, sstate = _sparse_setup(mesh8, lr=lr)
    # graft the DENSE init into the sparse state so both start identical
    new_tables = {}
    for feat in SIZE_MAP:
        tname = f"{feat}_embed"
        src = params[tname]["embedding"]
        assert sstate.tables[tname].shape == src.shape  # even vocabs: no pad
        new_tables[tname] = jax.device_put(src, sstate.tables[tname].sharding)
    sstate = SparseTrainState.create(
        dense_params={"user_tower": params["user_tower"],
                      "item_tower": params["item_tower"]},
        tx=optax.adam(lr),
        tables=new_tables,
        sparse_opt=sparse_optimizer("adam", lr=lr),
    )
    sparse_step = make_sparse_train_step(
        coll, ctr_sparse_forward(backbone), donate=False
    )

    rng1, rng2 = np.random.default_rng(7), np.random.default_rng(7)
    dense_losses, sparse_losses = [], []
    for _ in range(5):
        batch = {k: jnp.asarray(v) for k, v in full_coverage_batch(rng1).items()}
        dense_state, dl = dense_step(dense_state, batch)
        batch2 = {k: jnp.asarray(v) for k, v in full_coverage_batch(rng2).items()}
        sstate, sl = sparse_step(sstate, batch2)
        dense_losses.append(float(dl))
        sparse_losses.append(float(sl))
    np.testing.assert_allclose(sparse_losses, dense_losses, rtol=2e-4)
    # tables end up equal too (row-sharded vs dense)
    np.testing.assert_allclose(
        np.asarray(sstate.tables["user_embed"]),
        np.asarray(dense_state.params["user_embed"]["embedding"]),
        rtol=2e-4, atol=1e-6,
    )
    assert sstate.tables["user_embed"].sharding.spec[0] == "model"


def test_ctr_sparse_eval_step_matches_train_loss(mesh8):
    coll, backbone, state = _sparse_setup(mesh8)
    eval_step = make_ctr_sparse_eval_step(coll, backbone)
    batch = {k: jnp.asarray(v) for k, v in full_coverage_batch(np.random.default_rng(0)).items()}
    loss, logits = eval_step(state, batch)
    assert logits.shape == (B,)
    fwd = ctr_sparse_forward(backbone)
    ids = {f: batch[f] for f in coll.features()}
    embs = coll.lookup(state.tables, ids)
    ref = fwd(state.dense_params, embs, batch)
    np.testing.assert_allclose(float(loss), float(ref), rtol=1e-6)


def test_dlrm_backbone_shapes_and_grads():
    coll = ShardedEmbeddingCollection(ctr_embedding_specs(SIZE_MAP, D, "replicated"))
    tables = coll.init(jax.random.key(0))
    backbone = DLRMBackbone(embed_dim=D)
    batch = {k: jnp.asarray(v) for k, v in full_coverage_batch(np.random.default_rng(1)).items()}
    ids = {f: batch[f] for f in coll.features()}
    embs = coll.lookup(tables, ids)
    params = backbone.init(jax.random.key(2), embs, batch)["params"]
    logits = backbone.apply({"params": params}, embs, batch)
    assert logits.shape == (B,)
    assert np.isfinite(np.asarray(logits)).all()
    # grads flow to every embedding input
    fwd = ctr_sparse_forward(backbone)
    g = jax.grad(lambda e: fwd(params, e, batch))(embs)
    for f, ge in g.items():
        assert float(jnp.abs(ge).sum()) > 0, f"no gradient reached {f}"


def test_dlrm_sparse_training_reduces_loss(mesh8):
    coll = ShardedEmbeddingCollection(
        ctr_embedding_specs(SIZE_MAP, D, "row"), mesh=mesh8
    )
    tables = coll.init(jax.random.key(0))
    backbone = DLRMBackbone(embed_dim=D)
    batch = {k: jnp.asarray(v) for k, v in full_coverage_batch(np.random.default_rng(2)).items()}
    ids = {f: batch[f] for f in coll.features()}
    embs = coll.lookup(tables, ids)
    dense = backbone.init(jax.random.key(1), embs, batch)["params"]
    state = SparseTrainState.create(
        dense_params=dense, tx=optax.adam(1e-2), tables=tables,
        sparse_opt=sparse_optimizer("adam", lr=1e-2),
    )
    step = make_sparse_train_step(coll, ctr_sparse_forward(backbone), donate=False)
    losses = []
    for _ in range(60):
        state, loss = step(state, batch)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.6, (losses[0], losses[-1])


# ---------------------------------------------------------------- trainer


@pytest.fixture(scope="module")
def ctr_data(tmp_path_factory):
    from tdfo_tpu.data.ctr_preprocessing import run_ctr_preprocessing
    from tdfo_tpu.data.synthetic import write_synthetic_goodreads

    d = tmp_path_factory.mktemp("gr_sparse")
    write_synthetic_goodreads(d, n_users=100, n_books=150,
                              interactions_per_user=(15, 50), seed=5)
    size_map = run_ctr_preprocessing(d)
    return d, size_map


def _trainer_cfg(d, size_map, **kw):
    from tdfo_tpu.core.config import read_configs

    base = dict(
        data_dir=d, n_epochs=1, learning_rate=3e-3, embed_dim=8,
        per_device_train_batch_size=16, per_device_eval_batch_size=16,
        shuffle_buffer_size=500, log_every_n_steps=1000, size_map=size_map,
    )
    base.update(kw)
    return read_configs(None, **base)


def test_twotower_model_parallel_routes_through_sparse_path(ctr_data, tmp_path):
    from tdfo_tpu.train.trainer import Trainer

    d, size_map = ctr_data
    cfg = _trainer_cfg(d, size_map, model="twotower", model_parallel=True,
                       mesh={"data": 4, "model": 2})
    tr = Trainer(cfg, log_dir=tmp_path)
    assert isinstance(tr.state, SparseTrainState), (
        "model_parallel CTR must run the DMP regime (sparse in-backward optimizer)"
    )
    # tables row-sharded over the model axis
    assert tr.state.tables["user_embed"].sharding.spec[0] == "model"
    metrics = tr.fit()
    assert 0.0 <= metrics["auc"] <= 1.0
    assert metrics["eval_loss"] > 0


def test_dlrm_trainer_end_to_end(ctr_data, tmp_path):
    from tdfo_tpu.train.trainer import Trainer

    d, size_map = ctr_data
    cfg = _trainer_cfg(d, size_map, model="dlrm")
    tr = Trainer(cfg, log_dir=tmp_path)
    assert isinstance(tr.state, SparseTrainState)
    metrics = tr.fit()
    assert 0.0 <= metrics["auc"] <= 1.0


def test_fused_sparse_state_checkpoint_resume(ctr_data, tmp_path):
    """DMP-regime checkpointing (torchrec sharded state_dict parity): fat-row
    tables + count slots round-trip through orbax and training resumes."""
    from tdfo_tpu.train.trainer import Trainer

    d, size_map = ctr_data
    common = dict(
        model="twotower", model_parallel=True, mesh={"data": 4, "model": 2},
        fused_table_threshold=8,  # force every table onto fat storage
        checkpoint_dir=str(tmp_path / "ckpt"), checkpoint_every_n_epochs=1,
    )
    tr1 = Trainer(_trainer_cfg(d, size_map, n_epochs=1, **common))
    # all 7 same-dim fused tables stack into ONE fat array (TBE parity):
    # one dedupe + one in-place kernel launch per step for the whole group
    (stack_name,) = [n for n in tr1.state.tables if n.startswith("__fatstack_")]
    assert tr1.state.tables[stack_name].ndim == 3  # fat rows
    # every FUSED table (vocab > threshold) lives in the stack; the tiny
    # non-fused tables keep their own 2D arrays
    assert all(t.ndim == 2 for n, t in tr1.state.tables.items()
               if n != stack_name)
    assert len(tr1.state.tables) < 7
    m1 = tr1.fit()
    tr2 = Trainer(_trainer_cfg(d, size_map, n_epochs=2, **common))
    s0 = tr2._ckpt.latest_step()
    assert s0 is not None and tr2._ckpt.read_cursor(s0)["epoch"] == 0
    m2 = tr2.fit()
    assert 0.0 <= m2["auc"] <= 1.0
    assert m2["eval_loss"] <= m1["eval_loss"] * 1.2


@pytest.mark.slow  # full fit (~17 s); tier-1 keeps the test_quant_storage
# unit coverage, this end-to-end run rides the slow tier for budget
def test_bf16_storage_through_trainer(ctr_data, tmp_path):
    """[embeddings] dtype knobs observable end to end: tables (minus the
    per-table override) and adam slots come up bf16, the checkpoint sidecar
    stamps both dtypes, and training still converges to a sane AUC."""
    from tdfo_tpu.train.trainer import Trainer

    d, size_map = ctr_data
    cfg = _trainer_cfg(
        d, size_map, model="twotower", model_parallel=True,
        mesh={"data": 4, "model": 2},
        embeddings={"table_dtype": "bfloat16", "slot_dtype": "bfloat16",
                    "table_dtype_overrides": {"user_embed": "float32"}},
    )
    tr = Trainer(cfg, log_dir=tmp_path)
    assert tr.state.tables["user_embed"].dtype == jnp.float32  # override
    others = [n for n in tr.state.tables if n != "user_embed"]
    assert others and all(
        tr.state.tables[n].dtype == jnp.bfloat16 for n in others)
    # adam mu/nu slots follow slot_dtype on the bf16 tables
    for n in others:
        assert tr.state.slots[n][0].dtype == jnp.bfloat16, n
    stamps = tr._ckpt_stamps
    assert stamps["slot_dtype"] == "bfloat16"
    assert stamps["table_dtype"]["user_embed"] == "float32"
    assert all(v == "bfloat16" for k, v in stamps["table_dtype"].items()
               if k != "user_embed")
    metrics = tr.fit()
    assert 0.0 <= metrics["auc"] <= 1.0
    assert metrics["eval_loss"] > 0
