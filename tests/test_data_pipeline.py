"""Data layer: ETL correctness, streaming loader semantics, device prefetch.

The test pyramid the reference lacks (SURVEY.md §4): synthetic raw goodreads
files -> both ETLs -> loaders -> mesh-sharded device batches.
"""

import glob

import jax
import jax.numpy as jnp
import numpy as np
import pandas as pd
import pytest
from jax.sharding import PartitionSpec as P

from tdfo_tpu.data.ctr_preprocessing import (
    FINAL_COLUMNS,
    read_interactions,
    run_ctr_preprocessing,
    split_interactions,
    year_to_decade,
)
from tdfo_tpu.data.loader import (
    ParquetStream,
    count_rows,
    load_parquet_table,
    permutation_batches,
    prefetch_to_mesh,
    resolve_files,
)
from tdfo_tpu.data.seq_preprocessing import (
    EVAL_NEG_NUM,
    PAD_ID,
    run_seq_preprocessing,
)
from tdfo_tpu.data.synthetic import write_synthetic_goodreads


@pytest.fixture(scope="module")
def data_dir(tmp_path_factory):
    d = tmp_path_factory.mktemp("goodreads")
    write_synthetic_goodreads(d, n_users=80, n_books=200,
                              interactions_per_user=(5, 60), seed=0)
    return d


@pytest.fixture(scope="module")
def ctr_size_map(data_dir):
    return run_ctr_preprocessing(data_dir)


@pytest.fixture(scope="module")
def seq_stats(data_dir):
    return run_seq_preprocessing(data_dir, max_len=12, sliding_step=6,
                                 mask_prob=0.2, seed=42)


class TestCtrEtl:
    def test_interaction_filter_bounds(self, data_dir):
        df = read_interactions(data_dir)
        counts = df.groupby("user_id").size()
        assert counts.min() >= 10 and counts.max() <= 250
        assert set(df.columns) == {"user_id", "book_id", "is_read", "is_reviewed", "label"}
        assert set(df["label"].unique()) <= {0, 1}

    def test_items_sorted_per_user(self, data_dir):
        df = read_interactions(data_dir)
        for _, g in df.groupby("user_id"):
            assert (np.diff(g["book_id"].to_numpy()) >= 0).all()

    def test_split_ratio_and_disjoint(self, data_dir):
        df = read_interactions(data_dir)
        tr = split_interactions(df, True)
        ev = split_interactions(df, False)
        n = df.groupby("user_id").size()
        ntr = tr.groupby("user_id").size().reindex(n.index, fill_value=0)
        assert (ntr == np.ceil(n * 0.8)).all()
        assert len(tr) + len(ev) == len(df)

    def test_year_to_decade_boundaries(self):
        s = pd.Series(["1900", "1910", "1911", "1999", "2000", "2030", "2031",
                       "1899", "", "garbage"])
        out = year_to_decade(s).tolist()
        # inclusive is_between semantics: boundary years -> earlier decade
        assert out == ["1900s", "1900s", "1910s", "1990s", "1990s", "2020s",
                       "unknown", "unknown", "unknown", "unknown"]

    def test_size_map_and_final_columns(self, data_dir, ctr_size_map):
        assert set(ctr_size_map) == {"user", "item", "language", "is_ebook",
                                     "format", "publisher", "pub_decade"}
        files = resolve_files(data_dir, "parquet/train_part_*.parquet")
        assert len(files) == 8
        tbl = load_parquet_table(files[:1])
        assert list(tbl) == FINAL_COLUMNS
        # encoded categoricals within vocab bounds
        for col in ("language", "format", "publisher", "pub_decade"):
            assert tbl[col].max() < ctr_size_map[col]
        # continuous normalised to [0, 1]
        for col in ("avg_rating", "num_pages"):
            assert 0.0 <= tbl[col].min() and tbl[col].max() <= 1.0

    def test_train_eval_rows_cover_split(self, data_dir, ctr_size_map):
        n_train = count_rows(resolve_files(data_dir, "parquet/train_part_*.parquet"))
        n_eval = count_rows(resolve_files(data_dir, "parquet/eval_part_*.parquet"))
        df = read_interactions(data_dir)
        assert n_train + n_eval == len(df)


class TestSeqEtl:
    def test_size_map_and_mask_ratio(self, seq_stats):
        assert seq_stats["n_users"] > 0 and seq_stats["n_items"] > 0
        # mask_prob 0.2 + always-mask-last => ratio slightly above 0.2
        assert 0.15 < seq_stats["masked_ratio"] < 0.45

    def test_train_windows_shape_and_mask_semantics(self, data_dir, seq_stats):
        files = resolve_files(data_dir, "parquet_bert4rec/train_part_*.parquet")
        tbl = load_parquet_table(files)
        items, labels = tbl["train_interactions"], tbl["labels"]
        assert items.shape == labels.shape and items.shape[1] == 12
        mask_id = seq_stats["n_items"] + 1
        is_masked = items == mask_id
        # labels are real items exactly where input is masked, PAD elsewhere
        assert (labels[is_masked] != PAD_ID).all()
        assert (labels[~is_masked] == PAD_ID).all()
        assert items.max() <= mask_id and items.min() >= PAD_ID

    def test_eval_candidates(self, data_dir, seq_stats):
        files = resolve_files(data_dir, "parquet_bert4rec/eval_part_*.parquet")
        tbl = load_parquet_table(files)
        cands = tbl["candidate_items"]
        assert cands.shape[1] == 1 + EVAL_NEG_NUM
        # positive (col 0) never repeats among its negatives
        for row in cands:
            assert row[0] not in row[1:]
            assert len(np.unique(row[1:])) == EVAL_NEG_NUM  # unique negatives
        seqs = tbl["eval_seqs"]
        mask_id = seq_stats["n_items"] + 1
        # last position is always the MASK token; left-padded
        assert (seqs[:, -1] == mask_id).all()

    def test_test_split_candidates(self, data_dir, seq_stats):
        """The TEST split (reference computes it and never consumes it,
        torchrec/train.py:147-177) is written with eval-compatible columns,
        includes the eval item as known history, and never leaks the test
        item into its negatives."""
        files = resolve_files(data_dir, "parquet_bert4rec/test_part_*.parquet")
        tbl = load_parquet_table(files)
        cands = tbl["candidate_items"]
        assert cands.shape[1] == 1 + EVAL_NEG_NUM
        for row in cands:
            assert row[0] not in row[1:]
            assert len(np.unique(row[1:])) == EVAL_NEG_NUM
        seqs = tbl["eval_seqs"]
        mask_id = seq_stats["n_items"] + 1
        assert (seqs[:, -1] == mask_id).all()

        # cross-check vs eval shards: test input history = eval history + the
        # eval positive (leave-last-one protocol), per user
        efiles = resolve_files(data_dir, "parquet_bert4rec/eval_part_*.parquet")
        etbl = load_parquet_table(efiles)
        by_user = {u: (s, c) for u, s, c in
                   zip(etbl["user_id"], etbl["eval_seqs"], etbl["candidate_items"])}
        for u, s, c in zip(tbl["user_id"], seqs, cands):
            es, ec = by_user[u]
            eval_pos = ec[0]
            assert s[-2] == eval_pos  # last known item before MASK
            assert eval_pos not in c[1:]  # eval item is a positive: excluded


class TestParquetStream:
    def test_exactly_once_per_epoch(self, data_dir, ctr_size_map):
        files = resolve_files(data_dir, "parquet/train_part_*.parquet")
        total = count_rows(files)
        stream = ParquetStream(files, batch_size=64, buffer_size=500, seed=1,
                               drop_last=False, process_index=0, process_count=1)
        seen = []
        for b in stream:
            seen.append(np.stack([b["user_id"], b["item_id"]], 1))
        seen = np.concatenate(seen)
        assert len(seen) == total
        # same multiset of rows as the raw table
        raw = load_parquet_table(files, columns=["user_id", "item_id"])
        raw_rows = np.stack([raw["user_id"], raw["item_id"]], 1)
        assert sorted(map(tuple, seen)) == sorted(map(tuple, raw_rows))

    def test_epochs_differ_and_are_seeded(self, data_dir, ctr_size_map):
        files = resolve_files(data_dir, "parquet/train_part_*.parquet")
        s = ParquetStream(files, batch_size=32, buffer_size=200, seed=7,
                          process_index=0, process_count=1)
        first = next(iter(s))["user_id"].copy()
        again = next(iter(s))["user_id"].copy()
        np.testing.assert_array_equal(first, again)  # same epoch -> same order
        s.set_epoch(1)
        other = next(iter(s))["user_id"].copy()
        assert not np.array_equal(first, other)

    def test_drop_last_gives_static_shapes(self, data_dir, ctr_size_map):
        files = resolve_files(data_dir, "parquet/train_part_*.parquet")
        sizes = {len(b["user_id"]) for b in ParquetStream(
            files, batch_size=50, buffer_size=100, process_index=0, process_count=1)}
        assert sizes == {50}

    def test_host_sharding_partitions_rows(self, data_dir, ctr_size_map):
        files = resolve_files(data_dir, "parquet/train_part_*.parquet")
        total = count_rows(files)
        all_rows = []
        for rank in range(4):
            s = ParquetStream(files, batch_size=16, buffer_size=100, seed=3,
                              drop_last=False, process_index=rank, process_count=4)
            for b in s:
                all_rows.append(np.stack([b["user_id"], b["item_id"]], 1))
        rows = np.concatenate(all_rows)
        assert len(rows) == total  # disjoint and complete across ranks
        raw = load_parquet_table(files, columns=["user_id", "item_id"])
        raw_rows = np.stack([raw["user_id"], raw["item_id"]], 1)
        assert sorted(map(tuple, rows)) == sorted(map(tuple, raw_rows))

    def test_list_columns_stack(self, data_dir, seq_stats):
        files = resolve_files(data_dir, "parquet_bert4rec/train_part_*.parquet")
        b = next(iter(ParquetStream(files, batch_size=8, buffer_size=64,
                                    process_index=0, process_count=1)))
        assert b["train_interactions"].shape == (8, 12)
        assert b["labels"].dtype == np.int32


class TestMapStyle:
    def test_permutation_batches_cover_all(self):
        data = {"x": np.arange(103), "y": np.arange(103) * 2}
        out = np.concatenate([b["x"] for b in permutation_batches(
            data, 10, drop_last=False, seed=0)])
        assert sorted(out.tolist()) == list(range(103))
        dropped = list(permutation_batches(data, 10, drop_last=True, seed=0))
        assert all(len(b["x"]) == 10 for b in dropped) and len(dropped) == 10


class TestPrefetch:
    def test_prefetch_shards_on_mesh(self, data_dir, ctr_size_map, mesh_dp):
        files = resolve_files(data_dir, "parquet/train_part_*.parquet")
        stream = ParquetStream(files, batch_size=64, buffer_size=128,
                               process_index=0, process_count=1)
        n = 0
        for batch in prefetch_to_mesh(stream, mesh_dp, P("data")):
            assert batch["user_id"].sharding.spec == P("data")
            assert batch["user_id"].shape == (64,)
            n += 1
            if n >= 3:
                break
        assert n == 3

    def test_prefetch_exhausts_short_iterators(self, mesh_dp):
        batches = [{"x": np.ones((8,), np.float32) * i} for i in range(2)]
        out = list(prefetch_to_mesh(iter(batches), mesh_dp, P("data"), size=4))
        assert len(out) == 2
        assert float(out[1]["x"][0]) == 1.0


class TestMultihostBatchBudget:
    def test_equal_batch_counts_across_hosts(self, data_dir, ctr_size_map):
        # regression: unequal per-host batch counts would deadlock collectives
        files = resolve_files(data_dir, "parquet/train_part_*.parquet")
        for pc in (2, 3, 4):
            counts = []
            for rank in range(pc):
                s = ParquetStream(files, batch_size=37, buffer_size=100, seed=5,
                                  drop_last=True, process_index=rank,
                                  process_count=pc)
                counts.append(sum(1 for _ in s))
            assert len(set(counts)) == 1, f"pc={pc}: unequal counts {counts}"
            assert counts[0] > 0
