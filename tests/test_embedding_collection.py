"""ShardedEmbeddingCollection: sharded-vs-replicated exactness on the 8-dev mesh.

The acceptance bar from SURVEY.md §7/#8: every sharding strategy and lookup
mode must produce bit-identical vectors to a plain dense take.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from tdfo_tpu.parallel.embedding import EmbeddingSpec, ShardedEmbeddingCollection

V, D = 64, 16


def reference_lookup(table, ids):
    return np.asarray(table)[np.asarray(ids)]


@pytest.fixture(scope="module")
def ids():
    rng = np.random.default_rng(0)
    return jnp.asarray(rng.integers(0, V, 128, dtype=np.int32))


def make_coll(mesh, sharding, **kw):
    spec = EmbeddingSpec("item", V, D, features=("item",), sharding=sharding, **kw)
    coll = ShardedEmbeddingCollection([spec], mesh=mesh)
    tables = coll.init(jax.random.key(0))
    return coll, tables


def test_unsharded_lookup(ids):
    coll, tables = make_coll(None, "row")
    out = coll.lookup(tables, {"item": ids})["item"]
    np.testing.assert_array_equal(out, reference_lookup(tables["item"], ids))


@pytest.mark.parametrize("sharding", ["row", "column", "replicated"])
def test_gspmd_modes_match_dense(mesh8, ids, sharding):
    coll, tables = make_coll(mesh8, sharding)
    out = jax.jit(lambda t, i: coll.lookup(t, {"item": i})["item"])(tables, ids)
    np.testing.assert_array_equal(np.asarray(out), reference_lookup(tables["item"], ids))


def test_row_table_is_actually_sharded(mesh8):
    coll, tables = make_coll(mesh8, "row")
    spec = tables["item"].sharding.spec
    assert spec[0] == "model"
    assert tables["item"].addressable_shards[0].data.shape == (V // 2, D)


def test_psum_lookup_matches_dense(mesh8, ids):
    coll, tables = make_coll(mesh8, "row")
    data_sharded = jax.device_put(ids, NamedSharding(mesh8, P("data")))
    out = jax.jit(lambda t, i: coll.lookup(t, {"item": i}, mode="psum")["item"])(
        tables, data_sharded
    )
    np.testing.assert_array_equal(np.asarray(out), reference_lookup(tables["item"], ids))


def test_psum_lookup_2d_ids(mesh8):
    rng = np.random.default_rng(1)
    ids2 = jnp.asarray(rng.integers(0, V, (16, 5), dtype=np.int32))
    coll, tables = make_coll(mesh8, "row")
    out = jax.jit(lambda t, i: coll.lookup(t, {"item": i}, mode="psum")["item"])(tables, ids2)
    assert out.shape == (16, 5, D)
    np.testing.assert_array_equal(np.asarray(out), reference_lookup(tables["item"], ids2))


def test_alltoall_lookup_matches_dense(mesh8, ids):
    coll, tables = make_coll(mesh8, "row")
    model_sharded = jax.device_put(ids, NamedSharding(mesh8, P("model")))
    out = jax.jit(lambda t, i: coll.lookup(t, {"item": i}, mode="alltoall")["item"])(
        tables, model_sharded
    )
    np.testing.assert_array_equal(np.asarray(out), reference_lookup(tables["item"], ids))


def test_alltoall_skewed_ids(mesh8):
    # all ids hit one shard — worst-case bucket capacity
    ids_skew = jnp.zeros(64, jnp.int32)
    coll, tables = make_coll(mesh8, "row")
    out = jax.jit(lambda t, i: coll.lookup(t, {"item": i}, mode="alltoall")["item"])(
        tables, ids_skew
    )
    np.testing.assert_array_equal(np.asarray(out), reference_lookup(tables["item"], ids_skew))


def test_gradients_flow_through_psum(mesh8, ids):
    coll, tables = make_coll(mesh8, "row")

    def loss(tables):
        return coll.lookup(tables, {"item": ids[:8]}, mode="psum")["item"].sum()

    g = jax.jit(jax.grad(loss))(tables)["item"]
    dense = np.zeros((V, D), np.float32)
    np.add.at(dense, np.asarray(ids[:8]), 1.0)
    np.testing.assert_array_equal(np.asarray(g), dense)


def test_table_wise_stacking(mesh8):
    specs = [
        EmbeddingSpec(f"t{i}", 10 + i, D, features=(f"f{i}",), sharding="table")
        for i in range(4)
    ]
    coll = ShardedEmbeddingCollection(specs, mesh=mesh8)
    tables = coll.init(jax.random.key(1))
    assert "__stack_16" in tables
    # shard boundaries: 2 model shards, slot height = max slot sum
    stacked = tables["__stack_16"]
    assert stacked.sharding.spec[0] == "model"
    rng = np.random.default_rng(2)
    feats = {f"f{i}": jnp.asarray(rng.integers(0, 10 + i, 32, dtype=np.int32)) for i in range(4)}
    out = jax.jit(lambda t, f: coll.lookup(t, f))(tables, feats)
    for i in range(4):
        offset, total = coll._stack_rows[f"t{i}"]
        want = np.asarray(stacked)[np.asarray(feats[f"f{i}"]) + offset]
        np.testing.assert_array_equal(np.asarray(out[f"f{i}"]), want)


def test_multi_feature_shared_table(mesh8):
    spec = EmbeddingSpec("item", V, D, features=("hist", "target"), sharding="row")
    coll = ShardedEmbeddingCollection([spec], mesh=mesh8)
    tables = coll.init(jax.random.key(3))
    out = coll.lookup(tables, {"hist": jnp.asarray([1, 2]), "target": jnp.asarray([3])})
    assert out["hist"].shape == (2, D) and out["target"].shape == (1, D)


def test_feature_errors(mesh8):
    spec = EmbeddingSpec("item", V, D, features=("a",))
    coll = ShardedEmbeddingCollection([spec], mesh=mesh8)
    tables = coll.init(jax.random.key(0))
    with pytest.raises(KeyError, match="nope"):
        coll.lookup(tables, {"nope": jnp.asarray([0])})
    with pytest.raises(ValueError, match="two tables"):
        ShardedEmbeddingCollection(
            [EmbeddingSpec("x", 4, 4, features=("f",)), EmbeddingSpec("y", 4, 4, features=("f",))]
        )


def test_vocab_padding_for_row_sharding(mesh8):
    # 63 rows over 2 shards -> padded to 64
    coll, tables = make_coll(mesh8, "row")
    spec = EmbeddingSpec("odd", 63, D, features=("odd",), sharding="row")
    c2 = ShardedEmbeddingCollection([spec], mesh=mesh8)
    t2 = c2.init(jax.random.key(0))
    assert t2["odd"].shape == (64, D)


def test_explicit_modes_reject_column_sharding(mesh8):
    coll = ShardedEmbeddingCollection(
        [EmbeddingSpec("t", 64, 8, sharding="column")], mesh=mesh8
    )
    tables = coll.init(jax.random.key(0))
    ids = {"t": jnp.arange(8, dtype=jnp.int32)}
    for mode in ("psum", "alltoall"):
        with pytest.raises(ValueError, match="requires row/table sharding"):
            coll.lookup(tables, ids, mode=mode)


def test_table_wise_group_per_table_init_scales(mesh8):
    """Stacked table-wise groups honour each member's init scale (needed by
    ctr_embedding_specs' per-table glorot bounds); dtype must still match."""
    coll = ShardedEmbeddingCollection(
        [
            EmbeddingSpec("a", 32, 8, features=("a",), sharding="table", init_scale=1.0),
            EmbeddingSpec("b", 32, 8, features=("b",), sharding="table", init_scale=0.01),
        ],
        mesh=mesh8,
    )
    tables = coll.init(jax.random.key(0))
    ids = jnp.arange(32, dtype=jnp.int32)
    out = coll.lookup(tables, {"a": ids, "b": ids})
    a_max = float(jnp.abs(out["a"]).max())
    b_max = float(jnp.abs(out["b"]).max())
    assert 0.5 < a_max <= 1.0, a_max
    assert 0.005 < b_max <= 0.01, b_max

    with pytest.raises(ValueError, match="share a dtype"):
        ShardedEmbeddingCollection(
            [
                EmbeddingSpec("a", 32, 8, sharding="table", dtype=jnp.float32),
                EmbeddingSpec("b", 32, 8, sharding="table", dtype=jnp.bfloat16),
            ],
            mesh=mesh8,
        )


def test_alltoall_capacity_factor_drops_overflow(mesh8):
    """Finite a2a_capacity_factor: balanced ids stay exact; under extreme
    skew the ids past a bucket's capacity resolve to zero vectors (the
    documented torchrec-planner-style trade)."""
    specs = [EmbeddingSpec("item", 64, D, features=("item",), sharding="row")]
    coll = ShardedEmbeddingCollection(specs, mesh=mesh8, a2a_capacity_factor=2.0)
    tables = coll.init(jax.random.key(0))
    run = jax.jit(lambda t, i: coll.lookup(t, {"item": i}, mode="alltoall")["item"])

    # balanced ids: every shard's bucket fits in 2x the fair share -> exact
    balanced = jnp.arange(64, dtype=jnp.int32) % 64
    out = run(tables, balanced)
    np.testing.assert_array_equal(np.asarray(out), reference_lookup(tables["item"], balanced))

    # total skew: one shard owns every id; capacity = 2*64/2 = 64 -> with a
    # 64-id batch nothing overflows, so shrink capacity by skewing MORE ids
    # than cap: use factor so cap < n
    coll2 = ShardedEmbeddingCollection(specs, mesh=mesh8, a2a_capacity_factor=0.5)
    skew = jnp.zeros(64, jnp.int32)  # all ids -> shard 0; cap = 16 (0.5*64/2)
    out2 = np.asarray(
        jax.jit(lambda t, i: coll2.lookup(t, {"item": i}, mode="alltoall")["item"])(tables, skew)
    )
    ref_row = np.asarray(tables["item"][0])
    n_exact = int((np.abs(out2 - ref_row[None, :]).max(axis=1) < 1e-7).sum())
    n_zero = int((out2 == 0).all(axis=1).sum())
    assert n_exact >= 16 and n_zero > 0 and n_exact + n_zero == 64, (n_exact, n_zero)

    # the observability counter reports EXACTLY the dropped-id count the
    # lookup produced — for any id distribution
    count = jax.jit(lambda t, i: coll2.a2a_overflow(t, {"item": i}))
    assert int(count(tables, skew)) == n_zero
    out_bal = np.asarray(jax.jit(
        lambda t, i: coll2.lookup(t, {"item": i}, mode="alltoall")["item"]
    )(tables, balanced))
    assert int(count(tables, balanced)) == int(
        (out_bal == 0).all(axis=1).sum())
    # factor 2.0 never overflows these batches: counter stays 0
    exact_count = jax.jit(lambda t, i: coll.a2a_overflow(t, {"item": i}))
    assert int(exact_count(tables, skew)) == 0
    assert int(exact_count(tables, balanced)) == 0


class TestFatStacking:
    """Fused fat-row tables sharing (dim, sharding) stack into ONE array —
    fbgemm's table-batched (TBE) design: one dedupe + one kernel launch per
    step for the whole group."""

    def _coll(self, mesh=None, sharding="replicated"):
        specs = [
            EmbeddingSpec("a", 24, 8, features=("fa",), sharding=sharding,
                          fused=True, init_scale=0.5),
            EmbeddingSpec("b", 16, 8, features=("fb",), sharding=sharding,
                          fused=True, init_scale=0.1),
            EmbeddingSpec("c", 10, 8, features=("fc",), sharding=sharding),
        ]
        return ShardedEmbeddingCollection(specs, mesh=mesh)

    def test_stack_layout_and_lookup(self):
        coll = self._coll()
        tables = coll.init(jax.random.key(0))
        (stack,) = [n for n in tables if n.startswith("__fatstack_")]
        assert set(tables) == {stack, "c"}
        lay = coll.fat_layout(8)
        assert tables[stack].ndim == 3
        assert tables[stack].shape[0] == lay.n_lines(40)  # 40 packed rows
        aname, spec_a, off_a = coll.resolve("fa")
        bname, spec_b, off_b = coll.resolve("fb")
        assert aname == bname == stack and off_a == 0 and off_b == 24
        from tdfo_tpu.ops.pallas_kernels import fat_unpack

        ids = jnp.array([0, 3, 15], jnp.int32)
        out = coll.lookup(tables, {"fb": ids})["fb"]
        table_vals = fat_unpack(tables[stack], lay, rows=40)[0]
        want = table_vals[24 + np.asarray(ids)]
        np.testing.assert_array_equal(np.asarray(out), np.asarray(want))
        # member init scales are respected (b's rows are much smaller)
        assert float(jnp.abs(table_vals[:24]).max()) > 0.25
        assert float(jnp.abs(table_vals[24:40]).max()) <= 0.1 + 1e-6

    def test_sparse_update_isolates_members(self):
        from tdfo_tpu.ops.pallas_kernels import fat_unpack
        from tdfo_tpu.ops.sparse import sparse_optimizer

        coll = self._coll()
        tables = coll.init(jax.random.key(1))
        (stack,) = [n for n in tables if n.startswith("__fatstack_")]
        lay = coll.fat_layout(8)
        opt = sparse_optimizer("adam", lr=0.1)
        slots = opt.init(tables[stack])
        before = fat_unpack(tables[stack], lay, rows=40)[0]
        # the train step offsets feature ids into stack space (resolve());
        # update feature b's row 2 -> stack row 26 only
        ids = jnp.array([26], jnp.int32)
        g = jnp.ones((1, 8), jnp.float32)
        new, _ = coll.sparse_update(opt, stack, tables[stack], slots, ids, g)
        after = fat_unpack(new, lay, rows=40)[0]
        changed = np.flatnonzero(
            np.any(np.asarray(before != after), axis=1))
        np.testing.assert_array_equal(changed, [26])

    def test_row_sharded_stack_trains_on_mesh(self, mesh8):
        """Row-sharded stack on the 8-device mesh: the shard_map in-place
        update path routes by the GROUP's sharding (no member spec exists
        for the stack name)."""
        from tdfo_tpu.ops.sparse import sparse_optimizer

        coll = self._coll(mesh=mesh8, sharding="row")
        tables = coll.init(jax.random.key(2))
        (stack,) = [n for n in tables if n.startswith("__fatstack_")]
        assert tables[stack].sharding.spec[0] == "model"
        opt = sparse_optimizer("adam", lr=0.1)
        slots = opt.init(tables[stack])
        ids = jnp.array([0, 7, 25, 39], jnp.int32)
        g = jnp.ones((4, 8), jnp.float32)
        new, _ = coll.sparse_update(opt, stack, tables[stack], slots, ids, g)
        assert new.shape == tables[stack].shape
        assert not np.allclose(np.asarray(new), np.asarray(tables[stack]))


def test_plain_table_stacking_opt_in():
    """stack_tables=True groups PLAIN same-shape tables into one 2D array
    (the DLRM-Criteo many-table path); default off keeps per-table arrays."""
    specs = [
        EmbeddingSpec("a", 20, 8, features=("fa",), sharding="row"),
        EmbeddingSpec("b", 12, 8, features=("fb",), sharding="row"),
    ]
    coll = ShardedEmbeddingCollection(specs, stack_tables=True)
    tables = coll.init(jax.random.key(0))
    (stack,) = tables
    assert stack.startswith("__tablestack_") and tables[stack].shape == (32, 8)
    aname, spec_b, off_b = coll.resolve("fb")
    assert aname == stack and off_b == 20
    ids = jnp.array([0, 5], jnp.int32)
    out = coll.lookup(tables, {"fb": ids})["fb"]
    np.testing.assert_array_equal(np.asarray(out),
                                  np.asarray(tables[stack][20 + np.asarray(ids)]))
    # default: no stacking
    coll2 = ShardedEmbeddingCollection(specs)
    assert set(coll2.init(jax.random.key(0))) == {"a", "b"}


def test_plain_stack_dtype_groups_do_not_collide():
    """Two same-(dim, sharding) groups of DIFFERENT dtypes must form two
    stacks; the overwritten-group bug served rows of the wrong table."""
    import jax.numpy as jnp_

    specs = [
        EmbeddingSpec("a", 20, 8, features=("fa",), sharding="row"),
        EmbeddingSpec("b", 12, 8, features=("fb",), sharding="row"),
        EmbeddingSpec("c", 10, 8, features=("fc",), sharding="row",
                      dtype=jnp_.bfloat16),
        EmbeddingSpec("d", 10, 8, features=("fd",), sharding="row",
                      dtype=jnp_.bfloat16),
    ]
    coll = ShardedEmbeddingCollection(specs, stack_tables=True)
    tables = coll.init(jax.random.key(0))
    stacks = sorted(n for n in tables if n.startswith("__tablestack_"))
    assert len(stacks) == 2, tables.keys()
    dname, _, off_d = coll.resolve("fd")
    assert tables[dname].dtype == jnp_.bfloat16 and off_d == 10
    ids = jnp.array([0, 3], jnp.int32)
    out = coll.lookup(tables, {"fd": ids})["fd"]
    np.testing.assert_array_equal(
        np.asarray(out, np.float32),
        np.asarray(tables[dname][10 + np.asarray(ids)], np.float32))
