"""Gated online-loop fleet drill for the SEQUENCE serving family: replay
-> incremental train -> delta export -> shadow eval -> canary -> promote,
over Bert4Rec bundles (``serve/seq_scoring.py``) instead of CTR scorers —
the tests/test_fleet.py acceptance applied to the second model family.

The request logs are written ONCE by the module fixture as a fleet layout
of ``seqs``/``cands`` panels whose candidate column 0 (the positive,
``torchrec/train.py:44-58``) is drawn from the TOP half of the id range
and negatives from the bottom half: the injected skew fault serves
negated candidate IDS as scores, so every skewed positive ranks strictly
below its own panel's negatives (per-row ranking-AUC exactly 0) while an
honest scorer averages the random init over ~60 distinct items per side
and sits near chance — a separation far beyond ``max_auc_regression``
with no training luck required.

On top of the CTR drill's verdict/convergence/exactly-once audits, the
worker records a served-vs-eval fingerprint: the same probe panels scored
through every replica's live scorer AND through the trainer's own seq
eval chain, BEFORE ``loop.run()`` (the pristine v0 head) and AFTER (the
promoted head) — the served masked-position logits must equal the eval
step bit for bit on both sides of the swap.
"""

import json
from pathlib import Path

import numpy as np
import pytest

from tests.test_fleet import _run_worker, _run_workers

LOCAL_DEVICES = 4
BATCH_ROWS = 8 * 4  # per_device_train_batch_size x data-axis size
STEPS_PER_CYCLE = 2
N_CYCLES = 2  # full gated cycles the fleet logs hold
N_REPLICAS = 2  # canary_fraction 0.5 -> replica 0 canaries, replica 1 stable
MAX_LEN = 12


@pytest.fixture(scope="module")
def seq_fleet_env(tmp_path_factory):
    """Seq-preprocessed synthetic goodreads + a per-replica fleet layout of
    ``serve_request`` records carrying windowed histories and candidate
    panels (what the seq frontend's micro-batcher logs for replay)."""
    from tdfo_tpu.data.replay import RequestLog, replica_log_dir
    from tdfo_tpu.data.seq_preprocessing import (EVAL_NEG_NUM,
                                                 run_seq_preprocessing)
    from tdfo_tpu.data.synthetic import write_synthetic_goodreads
    from tdfo_tpu.serve.seq_scoring import history_window

    d = tmp_path_factory.mktemp("gr_fleet_seq")
    write_synthetic_goodreads(d, n_users=80, n_books=120,
                              interactions_per_user=(15, 40), seed=29)
    seq = run_seq_preprocessing(d, max_len=MAX_LEN, sliding_step=6, seed=3)
    n_items = int(seq["n_items"])

    root = tmp_path_factory.mktemp("fleetlog_seq") / "rl"
    logs = [RequestLog(replica_log_dir(root, k), segment_bytes=4096)
            for k in range(N_REPLICAS)]
    rng = np.random.default_rng(11)
    # every gated cycle consumes steps_per_cycle train batches AND peeks one
    # shadow batch beyond them, so the log needs one extra batch of slack
    rows_by_key: dict[tuple[int, int], int] = {}
    total, target = 0, (N_CYCLES * STEPS_PER_CYCLE + 1) * BATCH_ROWS
    i = 0
    while total < target + 5:  # sub-batch tail stays unread
        n = int(rng.integers(3, 9))
        seqs = [history_window(
                    rng.integers(1, n_items + 1,
                                 size=int(rng.integers(1, 2 * MAX_LEN))),
                    n_items=n_items, max_len=MAX_LEN).tolist()
                for _ in range(n)]
        # candidate panels: positives (column 0) live in the TOP half of
        # the id range, negatives in the bottom half — the skew fault's
        # negated-id scores then rank every positive below its own panel's
        # negatives (per-row AUC exactly 0), while honest scorers average
        # the random init over ~60 items per side and sit near chance
        half = n_items // 2 + 1
        cands = np.concatenate(
            [rng.integers(half, n_items + 1, size=(n, 1)),
             rng.integers(1, half, size=(n, EVAL_NEG_NUM))],
            axis=1).tolist()
        rid = i % N_REPLICAS  # interleave traffic across the fleet
        seq_no = logs[rid].append({
            "event": "serve_request", "request": f"r{total}", "rows": n,
            "outcome": "ok", "features": {"seqs": seqs, "cands": cands}})
        rows_by_key[(rid, seq_no)] = n
        total += n
        i += 1
    for log in logs:
        log.close()
    return dict(data_dir=str(d), request_log=str(root), n_items=n_items,
                rows_by_key=rows_by_key, total_rows=total)


def _make_spec(tmp: Path, env: dict, name: str, *, ckpt: str, log: str,
               faults: dict | None = None, **knobs) -> Path:
    spec = dict(
        model="bert4rec", n_items=env["n_items"],
        data_dir=env["data_dir"], checkpoint_dir=str(tmp / ckpt),
        log_dir=str(tmp / log), request_log=env["request_log"],
        out_json=str(tmp / f"{name}.json"), local_devices=LOCAL_DEVICES,
        steps_per_cycle=STEPS_PER_CYCLE, max_cycles=0,
        replicas=N_REPLICAS, canary_cycles=1, canary_fraction=0.5,
        max_auc_regression=0.3, shadow_eval_batches=1,
        faults=faults or {}, **knobs,
    )
    p = tmp / f"{name}_spec.json"
    p.write_text(json.dumps(spec))
    return p


@pytest.fixture(scope="module")
def seq_fleet_runs(seq_fleet_env, tmp_path_factory):
    """The tier-1 seq acceptance drill:

      * ``drill`` — ``regress_auc_at_cycle=1``: cycle 1's candidate serves
        skewed logits on the canary cohort, must auto-rollback; cycle 2
        retrains and promotes.
      * ``killdrill`` — the same regression PLUS ``kill_during_canary=1``:
        dies mid-watch with no durable verdict, then restarts the same
        command and must converge bitwise.
    """
    from tdfo_tpu.utils.faults import KILL_EXIT_CODE

    tmp = tmp_path_factory.mktemp("fleet_seq_runs")
    drill_p = _make_spec(tmp, seq_fleet_env, "drill", ckpt="ckpt_drill",
                         log="log_drill",
                         faults={"regress_auc_at_cycle": 1})
    kill_p = _make_spec(tmp, seq_fleet_env, "killdrill", ckpt="ckpt_kill",
                        log="log_kill",
                        faults={"regress_auc_at_cycle": 1,
                                "kill_during_canary": 1})

    rcs, outs = _run_workers([drill_p, kill_p])
    assert rcs[0] == 0, f"seq drill failed rc={rcs[0]}\n{outs[0][-2000:]}"
    assert rcs[1] == KILL_EXIT_CODE, \
        f"expected mid-canary kill, got rc={rcs[1]}\n{outs[1][-2000:]}"
    assert not (tmp / "killdrill.json").exists()  # died before any verdict
    assert (tmp / "ckpt_kill" / "faults_canary_kill.marker").exists()

    rc, out = _run_worker(kill_p)  # marker disarms the kill; redo the cycle
    assert rc == 0, f"resumed killdrill failed rc={rc}\n{out[-2000:]}"

    return dict(
        drill=json.loads((tmp / "drill.json").read_text()),
        killdrill=json.loads((tmp / "killdrill.json").read_text()),
        drill_metrics=tmp / "log_drill" / "metrics.jsonl",
    )


def _events(path: Path, event: str) -> list[dict]:
    recs = [json.loads(l) for l in path.read_text().splitlines()]
    return [r for r in recs if r.get("event") == event]


def test_seq_drill_shadow_passes_then_canary_rolls_back(seq_fleet_runs):
    """The skewed Bert4Rec candidate's BYTES are healthy, so it passes the
    shadow gate (per-row ranking-AUC over the label-free shadow panels) and
    reaches the canary cohort — where heartbeats catch the skew (top-half
    positives scored below every in-panel negative -> AUC 0) and roll it
    back."""
    cycles = _events(seq_fleet_runs["drill_metrics"], "online_cycle")
    assert [c["verdict"] for c in cycles] == ["rollback", "promote"]
    bad = cycles[0]
    assert bad["gated"] and bad["cycle"] == 1 and bad["version"] == 1
    # shadow gate scored the candidate and passed it (bytes are honest)
    assert bad["shadow_auc"] >= bad["shadow_auc_base"] - 0.3
    # the canary watch measured the skew: near-zero AUC vs an honest stable
    assert bad["canary_auc"] < bad["stable_auc"] - 0.3
    assert bad["canary_auc"] < 0.1  # the constant positive pins it at ~0
    assert "canary AUC" in bad["reason"]
    rej = seq_fleet_runs["drill"]["rejections"]
    assert len(rej) == 1 and rej[0]["version"] == 1
    assert rej[0]["digest"] != seq_fleet_runs["drill"]["digest"]
    # cycle 2 REUSES version 1 (delta chain stays parent+1) and promotes
    good = cycles[1]
    assert good["version"] == 1 and seq_fleet_runs["drill"]["version"] == 1
    assert seq_fleet_runs["drill"]["canary_version"] is None


def test_seq_served_logits_match_eval_step_across_swap(seq_fleet_runs):
    """The acceptance bar: every replica's served masked-position logits
    equal the trainer's seq eval step bit for bit BEFORE the swap (pristine
    v0 head vs pristine state) and AFTER it (promoted head vs the state
    that exported it).  JSON round-trips repr-exact floats, so list
    equality here IS bitwise equality of the float32 scores."""
    se = seq_fleet_runs["drill"]["served_eval"]
    for side in ("pre", "final"):
        evals, served = se[side]["eval"], se[side]["served"]
        assert set(served) == {str(k) for k in range(N_REPLICAS)}
        for rid, by_req in served.items():
            assert by_req == evals, f"{side}: replica {rid} diverges"
    # the swap actually happened: the promoted head scores differently
    assert se["final"]["eval"] != se["pre"]["eval"]


def test_seq_drill_fleet_converges_bitwise(seq_fleet_runs):
    """After the rollback + the healthy promote, every replica serves the
    same version and bitwise-identical probe logits through its live
    micro-batcher — no replica is left on the rejected bundle."""
    drill = seq_fleet_runs["drill"]
    versions = set(drill["replica_versions"].values())
    assert versions == {drill["version"]}
    logits = list(drill["logits"].values())
    assert len(logits) == N_REPLICAS
    for other in logits[1:]:
        assert other == logits[0]


def test_seq_kill_during_canary_restart_converges(seq_fleet_runs):
    """A kill mid-canary-watch + restart must converge to the uninterrupted
    drill's exact fleet state — including the served-vs-eval fingerprint on
    the promoted head."""
    drill, kd = seq_fleet_runs["drill"], seq_fleet_runs["killdrill"]
    assert kd["version"] == drill["version"]
    assert kd["digest"] == drill["digest"]
    assert kd["cursor"] == drill["cursor"]
    assert kd["cycles_done"] == drill["cycles_done"]
    assert kd["logits"] == drill["logits"]
    assert kd["served_eval"]["final"] == drill["served_eval"]["final"]
    assert [(r["version"], r["digest"]) for r in kd["rejections"]] == \
        [(r["version"], r["digest"]) for r in drill["rejections"]]


def test_seq_merged_replay_exactly_once_accounting(seq_fleet_runs,
                                                   seq_fleet_env):
    """The consumed ``(replica_id, seq, row_start, row_end)`` spans tile
    each fleet record at most once with no gap and no overlap — the seq
    panel payloads batch through the same exactly-once merger as CTR."""
    cycles = _events(seq_fleet_runs["drill_metrics"], "online_cycle")
    assert len(cycles) == N_CYCLES
    spans: dict[tuple[int, int], list[tuple[int, int]]] = {}
    for c in cycles:
        for rid, seq_no, a, b in c["consumed"]:
            spans.setdefault((rid, seq_no), []).append((a, b))
    rows_by_key = seq_fleet_env["rows_by_key"]
    assert spans, "no consumed spans logged"
    for key, parts in spans.items():
        parts.sort()
        assert parts[0][0] == 0, (key, parts)
        for (a0, b0), (a1, b1) in zip(parts, parts[1:]):
            assert b0 == a1, f"{key}: gap or overlap at {parts}"
        assert parts[-1][1] <= rows_by_key[key]
    # both replicas' logs contributed to training — the merger merges
    assert {k[0] for k in spans} == set(range(N_REPLICAS))
