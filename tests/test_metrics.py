"""Metrics: exact AUC vs brute force, streaming AUC vs exact, Recall/NDCG golden."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tdfo_tpu.train.metrics import (
    AUC,
    binary_auc,
    ranking_auc,
    recalls_and_ndcgs_for_ks,
)


def _brute_auc(labels, scores):
    pos = scores[labels > 0.5]
    neg = scores[labels <= 0.5]
    wins = (pos[:, None] > neg[None, :]).sum() + 0.5 * (pos[:, None] == neg[None, :]).sum()
    return wins / (len(pos) * len(neg))


class TestBinaryAUC:
    def test_matches_brute_force(self):
        rng = np.random.default_rng(0)
        labels = rng.integers(0, 2, 500).astype(np.float32)
        scores = rng.random(500)
        assert binary_auc(labels, scores) == pytest.approx(_brute_auc(labels, scores))

    def test_ties(self):
        labels = np.array([1, 0, 1, 0], np.float32)
        scores = np.array([0.5, 0.5, 0.5, 0.5])
        assert binary_auc(labels, scores) == pytest.approx(0.5)

    def test_perfect_and_inverted(self):
        labels = np.array([1, 1, 0, 0], np.float32)
        assert binary_auc(labels, np.array([0.9, 0.8, 0.2, 0.1])) == 1.0
        assert binary_auc(labels, np.array([0.1, 0.2, 0.8, 0.9])) == 0.0

    def test_weights_mask_padding(self):
        labels = np.array([1, 0, 1, 1], np.float32)
        scores = np.array([0.9, 0.1, 0.0, 0.0])
        w = np.array([1, 1, 0, 0], np.float32)  # last two rows are padding
        assert binary_auc(labels, scores, w) == 1.0

    def test_degenerate_single_class(self):
        assert np.isnan(binary_auc(np.ones(4), np.random.rand(4)))


class TestStreamingAUC:
    def test_close_to_exact(self):
        rng = np.random.default_rng(1)
        n = 4000
        labels = rng.integers(0, 2, n).astype(np.float32)
        # separable-ish scores so AUC is away from 0.5
        scores = np.clip(labels * 0.3 + rng.random(n) * 0.7, 0, 1)
        exact = binary_auc(labels, scores)
        state = AUC.empty(400)
        for i in range(0, n, 1000):  # streaming in chunks
            state = state.update(jnp.asarray(labels[i : i + 1000]), jnp.asarray(scores[i : i + 1000]))
        assert float(state.result()) == pytest.approx(exact, abs=5e-3)

    def test_update_under_jit_and_merge(self):
        upd = jax.jit(lambda s, l, x: s.update(l, x))
        labels = jnp.array([1.0, 0.0, 1.0, 0.0])
        scores = jnp.array([0.9, 0.1, 0.8, 0.2])
        a = upd(AUC.empty(100), labels[:2], scores[:2])
        b = upd(AUC.empty(100), labels[2:], scores[2:])
        merged = a.merge(b)
        whole = AUC.empty(100).update(labels, scores)
        assert float(merged.result()) == pytest.approx(float(whole.result()))
        assert float(whole.result()) == pytest.approx(1.0)

    def test_weights(self):
        state = AUC.empty(100).update(
            jnp.array([1.0, 0.0, 1.0]), jnp.array([0.9, 0.1, 0.0]), jnp.array([1.0, 1.0, 0.0])
        )
        assert float(state.result()) == pytest.approx(1.0)

    def test_empty_is_nan(self):
        assert np.isnan(float(AUC.empty().result()))


class TestRankingAUC:
    """The seq-family gate metric: PER-ROW rank of column 0 (the positive)
    against its own panel's negatives, averaged — not a pooled flat
    Mann-Whitney statistic, so per-user score-scale shifts cannot move it."""

    def test_matches_mean_per_row_binary_auc(self):
        rng = np.random.default_rng(3)
        s = rng.random((40, 11))
        labels = np.zeros((11,))
        labels[0] = 1.0
        per_row = [binary_auc(labels, row) for row in s]
        assert ranking_auc(s) == pytest.approx(np.mean(per_row))

    def test_perfect_inverted_and_ties(self):
        assert ranking_auc(np.array([[0.9, 0.1, 0.2], [0.8, 0.0, 0.3]])) == 1.0
        assert ranking_auc(np.array([[0.1, 0.9, 0.2], [0.0, 0.8, 0.3]])) == 0.0
        assert ranking_auc(np.full((4, 5), 0.5)) == pytest.approx(0.5)

    def test_per_row_score_shifts_do_not_move_the_gate(self):
        # the property pooling breaks: adding a per-user offset leaves every
        # within-panel ranking (and so the metric) unchanged
        rng = np.random.default_rng(4)
        s = rng.random((30, 8))
        shifted = s + rng.normal(0.0, 100.0, size=(30, 1))
        assert ranking_auc(shifted) == pytest.approx(ranking_auc(s))

    def test_rejects_wrong_shapes(self):
        with pytest.raises(ValueError, match="candidate panels"):
            ranking_auc(np.zeros((5,)))
        with pytest.raises(ValueError, match="candidate panels"):
            ranking_auc(np.zeros((5, 1)))


class TestRankingMetrics:
    def test_single_positive_golden(self):
        # positive at candidate 0; rank it 2nd (one negative above)
        scores = jnp.array([[0.8, 0.9, 0.1, 0.2, 0.3]])
        labels = jnp.array([[1.0, 0.0, 0.0, 0.0, 0.0]])
        m = recalls_and_ndcgs_for_ks(scores, labels, ks=(1, 2))
        assert float(m["Recall@1"]) == 0.0
        assert float(m["Recall@2"]) == 1.0
        assert float(m["NDCG@2"]) == pytest.approx(1.0 / np.log2(3.0))

    def test_perfect_ranking(self):
        scores = jnp.array([[0.9, 0.1, 0.2], [0.8, 0.05, 0.01]])
        labels = jnp.array([[1.0, 0.0, 0.0], [1.0, 0.0, 0.0]])
        m = recalls_and_ndcgs_for_ks(scores, labels, ks=(1,))
        assert float(m["Recall@1"]) == 1.0
        assert float(m["NDCG@1"]) == pytest.approx(1.0)

    def test_torchrec_protocol_shape(self):
        # 1 positive + 100 negatives, the reference's eval protocol
        rng = np.random.default_rng(2)
        b = 32
        scores = jnp.asarray(rng.random((b, 101), dtype=np.float32))
        labels = jnp.zeros((b, 101)).at[:, 0].set(1.0)
        m = recalls_and_ndcgs_for_ks(scores, labels, ks=(10, 20, 50))
        assert set(m) == {"Recall@10", "Recall@20", "Recall@50", "NDCG@10", "NDCG@20", "NDCG@50"}
        # random scores: E[Recall@k] = k/101
        assert 0.0 <= float(m["Recall@10"]) <= 1.0
        assert float(m["Recall@10"]) <= float(m["Recall@20"]) <= float(m["Recall@50"])

    def test_multiple_positives(self):
        scores = jnp.array([[0.9, 0.8, 0.1, 0.2]])
        labels = jnp.array([[1.0, 1.0, 0.0, 0.0]])
        m = recalls_and_ndcgs_for_ks(scores, labels, ks=(1, 2))
        # Recall@1 = hits/min(1, 2 pos) = 1/1
        assert float(m["Recall@1"]) == 1.0
        assert float(m["Recall@2"]) == 1.0
        assert float(m["NDCG@2"]) == pytest.approx(1.0)

    def test_row_weights(self):
        scores = jnp.array([[0.9, 0.1], [0.1, 0.9]])
        labels = jnp.array([[1.0, 0.0], [1.0, 0.0]])
        m = recalls_and_ndcgs_for_ks(scores, labels, ks=(1,), row_weights=jnp.array([1.0, 0.0]))
        assert float(m["Recall@1"]) == 1.0  # padded failing row ignored

    def test_under_jit(self):
        f = jax.jit(lambda s, l: recalls_and_ndcgs_for_ks(s, l, ks=(2,)))
        m = f(jnp.array([[0.9, 0.1, 0.5]]), jnp.array([[1.0, 0.0, 0.0]]))
        assert float(m["Recall@2"]) == 1.0


def test_ranking_ks_larger_than_candidates_clamp():
    scores = jnp.array([[0.9, 0.1, 0.5]])
    labels = jnp.array([[1.0, 0.0, 0.0]])
    m = recalls_and_ndcgs_for_ks(scores, labels, ks=(10, 50))
    # clamped to @3: positive is ranked first
    assert float(m["Recall@10"]) == 1.0
    assert float(m["Recall@50"]) == 1.0
