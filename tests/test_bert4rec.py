"""Bert4Rec family: transformer semantics, masked-LM loss, both param regimes.

Parity anchors (behavioral, not line-for-line): torchrec/models.py:11-223
(attention masking, pre-norm residuals, positional encoding, vocab
projection) and torchrec/train.py:81-111 (CE ignore_index + label smoothing).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from tdfo_tpu.models.bert4rec import (
    PAD_ID,
    Bert4Rec,
    Bert4RecConfig,
    init_bert4rec,
    key_padding_mask,
    make_sharded_bert4rec,
)
from tdfo_tpu.models.transformer import (
    MultiHeadAttention,
    TransformerBlock,
    dot_product_attention,
)
from tdfo_tpu.ops.sparse import sparse_optimizer
from tdfo_tpu.train.seq import bert4rec_sparse_forward, masked_ce_loss, score_candidates
from tdfo_tpu.train.sparse_step import SparseTrainState, make_sparse_train_step

CFG = Bert4RecConfig(n_items=50, max_len=8, embed_dim=16, n_heads=2, n_layers=2)


class TestAttention:
    def test_softmax_rows_uniform_when_equal(self):
        q = jnp.zeros((1, 1, 3, 4))
        k = jnp.zeros((1, 1, 3, 4))
        v = jnp.ones((1, 1, 3, 4)) * jnp.arange(3.0)[None, None, :, None]
        out = dot_product_attention(q, k, v)
        np.testing.assert_allclose(np.asarray(out[0, 0, 0]), np.ones(4), rtol=1e-6)

    def test_mask_excludes_keys(self):
        rng = jax.random.key(0)
        q, k, v = (jax.random.normal(jax.random.fold_in(rng, i), (1, 1, 4, 8)) for i in range(3))
        mask = jnp.array([True, True, False, False])[None, None, None, :]
        out = dot_product_attention(q, k, v, mask)
        # masked-out keys must not influence: recompute with only first 2 keys
        ref = dot_product_attention(q, k[:, :, :2], v[:, :, :2])
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5)

    def test_mha_shapes_and_grad(self):
        m = MultiHeadAttention(n_heads=4)
        x = jax.random.normal(jax.random.key(1), (2, 6, 16))
        params = m.init(jax.random.key(0), x)["params"]
        out = m.apply({"params": params}, x)
        assert out.shape == (2, 6, 16)
        g = jax.grad(lambda p: m.apply({"params": p}, x).sum())(params)
        assert all(np.isfinite(l).all() for l in jax.tree.leaves(g))

    def test_mha_rejects_indivisible_heads(self):
        m = MultiHeadAttention(n_heads=3)
        x = jnp.zeros((1, 4, 16))
        with pytest.raises(ValueError, match="not divisible"):
            m.init(jax.random.key(0), x)

    def test_block_identity_at_init_scale(self):
        # pre-norm residual: output stays close to input at init (residual path)
        blk = TransformerBlock(n_heads=2, ff_dim=32)
        x = jax.random.normal(jax.random.key(2), (2, 5, 16))
        params = blk.init(jax.random.key(0), x)["params"]
        out = blk.apply({"params": params}, x)
        assert out.shape == x.shape


class TestMaskedCE:
    def test_ignores_pad_positions(self):
        logits = jax.random.normal(jax.random.key(0), (2, 4, 10))
        labels = jnp.array([[3, PAD_ID, PAD_ID, PAD_ID], [5, 7, PAD_ID, PAD_ID]])
        loss = masked_ce_loss(logits, labels, label_smoothing=0.0)
        # manual: mean over the 3 real labels
        logp = jax.nn.log_softmax(logits, -1)
        manual = -(logp[0, 0, 3] + logp[1, 0, 5] + logp[1, 1, 7]) / 3.0
        assert float(loss) == pytest.approx(float(manual), rel=1e-5)

    def test_label_smoothing_matches_torch_formula(self):
        logits = jax.random.normal(jax.random.key(1), (1, 2, 6))
        labels = jnp.array([[2, 4]])
        s = 0.1
        loss = masked_ce_loss(logits, labels, label_smoothing=s)
        logp = np.asarray(jax.nn.log_softmax(logits, -1), np.float64)
        per = []
        for t, y in enumerate([2, 4]):
            # torch: (1-s)*(-logp[y]) + s*mean_v(-logp[v])
            per.append((1 - s) * -logp[0, t, y] + s * -logp[0, t].mean())
        assert float(loss) == pytest.approx(np.mean(per), rel=1e-5)

    def test_all_pad_is_safe(self):
        logits = jnp.ones((1, 3, 5))
        labels = jnp.full((1, 3), PAD_ID)
        assert float(masked_ce_loss(logits, labels)) == 0.0


class TestScoring:
    def test_score_candidates_gathers_last_position(self):
        logits = jnp.arange(2 * 3 * 10, dtype=jnp.float32).reshape(2, 3, 10)
        cands = jnp.array([[1, 5], [0, 9]])
        s = score_candidates(logits, cands)
        np.testing.assert_allclose(np.asarray(s), [[21.0, 25.0], [50.0, 59.0]])


class TestBert4RecDense:
    def test_init_and_forward(self):
        model, params = init_bert4rec(jax.random.key(0), CFG)
        ids = jnp.array([[1, 2, 3, CFG.mask_id, PAD_ID, PAD_ID, PAD_ID, PAD_ID]])
        logits = model.apply({"params": params}, ids)
        assert logits.shape == (1, CFG.max_len, CFG.vocab_size)

    def test_padding_does_not_leak_into_valid_positions(self):
        model, params = init_bert4rec(jax.random.key(0), CFG)
        padded = jnp.array([[1, 2, 3, 4, PAD_ID, PAD_ID, PAD_ID, PAD_ID]])
        short = jnp.array([[1, 2, 3, 4]])  # same prefix, no pad tail at all
        lp = model.apply({"params": params}, padded)
        ls = model.apply({"params": params}, short)
        # masked pad keys must make the padded run equal the unpadded one
        np.testing.assert_allclose(
            np.asarray(lp[:, :4]), np.asarray(ls), rtol=1e-5, atol=1e-5
        )
        m = key_padding_mask(padded)
        assert m.shape == (1, 1, 1, 8)
        assert np.asarray(m)[0, 0, 0].tolist() == [True] * 4 + [False] * 4

    def test_overfits_tiny_masked_lm(self):
        import optax
        from tdfo_tpu.train.seq import bert4rec_loss_fn

        model, params = init_bert4rec(jax.random.key(0), CFG)
        tx = optax.adam(1e-2)
        opt = tx.init(params)
        item = jnp.array([[5, 6, 7, CFG.mask_id, PAD_ID, PAD_ID, PAD_ID, PAD_ID]] * 4)
        label = jnp.array([[PAD_ID, PAD_ID, PAD_ID, 8, PAD_ID, PAD_ID, PAD_ID, PAD_ID]] * 4)
        batch = {"item": item, "label": label}

        @jax.jit
        def step(params, opt):
            loss, g = jax.value_and_grad(bert4rec_loss_fn)(params, model.apply, batch)
            upd, opt = tx.update(g, opt, params)
            return optax.apply_updates(params, upd), opt, loss

        l0 = None
        for _ in range(60):
            params, opt, loss = step(params, opt)
            if l0 is None:
                l0 = float(loss)
        assert float(loss) < 0.5 * l0
        # the masked position must now rank item 8 first among candidates
        logits = model.apply({"params": params}, item[:1])
        pred = int(jnp.argmax(logits[0, 3]))
        assert pred == 8


class TestBert4RecSharded:
    def test_sharded_backbone_matches_dense_lookup(self, mesh8):
        coll, tables, backbone, dense = make_sharded_bert4rec(
            jax.random.key(0), CFG, mesh8, sharding="row"
        )
        ids = jnp.array([[1, 2, 3, CFG.mask_id, PAD_ID, PAD_ID, PAD_ID, PAD_ID]] * 8)
        embs = coll.lookup(tables, {"item": ids})
        logits = backbone.apply({"params": dense}, embs["item"], key_padding_mask(ids))
        assert logits.shape == (8, CFG.max_len, CFG.vocab_size)
        # replicated-collection run must produce identical output
        coll2, tables2, _, _ = make_sharded_bert4rec(
            jax.random.key(0), CFG, None, sharding="row"
        )
        embs2 = coll2.lookup(tables2, {"item": ids})
        logits2 = backbone.apply({"params": dense}, embs2["item"], key_padding_mask(ids))
        np.testing.assert_allclose(np.asarray(logits), np.asarray(logits2), rtol=2e-5, atol=2e-5)

    def test_sparse_train_step_runs_and_learns(self, mesh8):
        import optax

        coll, tables, backbone, dense = make_sharded_bert4rec(
            jax.random.key(0), CFG, mesh8, sharding="row"
        )
        state = SparseTrainState.create(
            dense_params=dense,
            tx=optax.adam(5e-3),
            tables=tables,
            sparse_opt=sparse_optimizer("adam", lr=5e-3),
        )
        item = jnp.array([[5, 6, 7, CFG.mask_id, PAD_ID, PAD_ID, PAD_ID, PAD_ID]] * 8)
        label = jnp.array([[PAD_ID, PAD_ID, PAD_ID, 8, PAD_ID, PAD_ID, PAD_ID, PAD_ID]] * 8)
        batch = {
            "item": jax.device_put(item, NamedSharding(mesh8, P("data"))),
            "label": jax.device_put(label, NamedSharding(mesh8, P("data"))),
        }
        step = make_sparse_train_step(coll, bert4rec_sparse_forward(backbone), donate=False)
        l0 = None
        for _ in range(30):
            state, loss = step(state, batch)
            if l0 is None:
                l0 = float(loss)
        assert float(loss) < 0.7 * l0

    def test_pad_id_rows_update_is_harmless(self, mesh8):
        # PAD appears as a real id (row 0) in the input; forward masks it via
        # attention but its row DOES get gradient traffic through lookup —
        # matching torchrec where the pad row exists in the table.  Just
        # verify the step runs with pads present and loss is finite.
        import optax

        coll, tables, backbone, dense = make_sharded_bert4rec(
            jax.random.key(1), CFG, mesh8
        )
        state = SparseTrainState.create(
            dense_params=dense, tx=optax.adam(1e-3), tables=tables,
            sparse_opt=sparse_optimizer("sgd", lr=1e-3),
        )
        item = jnp.full((8, 8), PAD_ID, jnp.int32)
        label = jnp.full((8, 8), PAD_ID, jnp.int32)
        step = make_sparse_train_step(coll, bert4rec_sparse_forward(backbone), donate=False)
        state, loss = step(state, {"item": item, "label": label})
        assert np.isfinite(float(loss))


def test_sparse_step_dropout_rng_changes_loss(mesh8):
    # dropout must actually engage when an rng is passed (and not otherwise)
    import optax

    cfg = Bert4RecConfig(n_items=30, max_len=8, embed_dim=16, n_heads=2,
                         n_layers=1, dropout=0.5)
    coll, tables, backbone, dense = make_sharded_bert4rec(jax.random.key(0), cfg, mesh8)
    state = SparseTrainState.create(
        dense_params=dense, tx=optax.adam(1e-3), tables=tables,
        sparse_opt=sparse_optimizer("sgd", lr=1e-3),
    )
    item = jnp.array([[5, 6, 7, cfg.mask_id, PAD_ID, PAD_ID, PAD_ID, PAD_ID]] * 8)
    label = jnp.array([[PAD_ID, PAD_ID, PAD_ID, 8, PAD_ID, PAD_ID, PAD_ID, PAD_ID]] * 8)
    batch = {"item": item, "label": label}
    step = make_sparse_train_step(coll, bert4rec_sparse_forward(backbone), donate=False)
    _, loss_det = step(state, batch)
    _, loss_a = step(state, batch, jax.random.key(1))
    _, loss_b = step(state, batch, jax.random.key(2))
    assert float(loss_a) != float(loss_det)  # dropout engaged
    assert float(loss_a) != float(loss_b)  # different keys, different masks
    _, loss_det2 = step(state, batch)
    assert float(loss_det) == float(loss_det2)  # no rng -> deterministic
