"""PS-strategy parity + runtime-knob coverage.

``ps_min_shard_bytes`` re-expresses TF's ParameterServerStrategy variable
partitioning (``tensorflow2/train_ps.py:55-58`` ``MinSizePartitioner``,
256 KB default) as a GSPMD sharding plan: big dense variables (and their
optimizer slots) shard over the model axis, small ones replicate, and the
training math is unchanged.  jit_xla / use_tpu / num_workers stopped being
accepted-but-ignored keys: each has observable semantics tested here.
"""

import numpy as np
import pytest

from tdfo_tpu.core.config import read_configs
from tdfo_tpu.data.ctr_preprocessing import run_ctr_preprocessing
from tdfo_tpu.data.synthetic import write_synthetic_goodreads
from tdfo_tpu.train.trainer import Trainer


@pytest.fixture(scope="module")
def ctr_data(tmp_path_factory):
    d = tmp_path_factory.mktemp("gr_ps")
    write_synthetic_goodreads(d, n_users=100, n_books=150,
                              interactions_per_user=(15, 50), seed=7)
    size_map = run_ctr_preprocessing(d)
    return d, size_map


def _cfg(d, size_map, **kw):
    base = dict(
        data_dir=d, model="twotower", n_epochs=1, learning_rate=3e-3,
        embed_dim=8, per_device_train_batch_size=16,
        per_device_eval_batch_size=16, shuffle_buffer_size=500,
        log_every_n_steps=1000, size_map=size_map,
        mesh={"data": 4, "model": 2},
    )
    base.update(kw)
    return read_configs(None, **base)


def test_ps_partitioner_shards_large_variables_only(ctr_data):
    import jax

    d, size_map = ctr_data
    # threshold chosen so the user/item tables qualify but tower kernels
    # (8x8 = 256 B) do not
    tr = Trainer(_cfg(d, size_map, ps_min_shard_bytes=512))
    sharded, replicated = [], []
    for path, leaf in jax.tree_util.tree_leaves_with_path(tr.state.params):
        name = "/".join(str(getattr(k, "key", k)) for k in path)
        n_bytes = leaf.size * leaf.dtype.itemsize
        if any(ax is not None for ax in leaf.sharding.spec):
            sharded.append((name, n_bytes))
            assert n_bytes // 2 >= 512, (name, n_bytes)
        else:
            replicated.append((name, n_bytes))
    assert sharded, "no variable was PS-partitioned"
    assert any("embed" in n for n, _ in sharded)
    # optimizer moments shard alongside their variables
    mu_sharded = [
        any(ax is not None for ax in leaf.sharding.spec)
        for _, leaf in jax.tree_util.tree_leaves_with_path(tr.state.opt_state)
        if leaf.ndim >= 1
    ]
    assert any(mu_sharded)


def test_ps_partitioned_trajectory_matches_replicated(ctr_data):
    d, size_map = ctr_data
    loss_rep = Trainer(_cfg(d, size_map)).train_epoch(0)
    loss_ps = Trainer(_cfg(d, size_map, ps_min_shard_bytes=512)).train_epoch(0)
    assert np.isclose(loss_rep, loss_ps, rtol=1e-4), (loss_rep, loss_ps)


def test_use_tpu_fails_fast_off_tpu(ctr_data):
    d, size_map = ctr_data
    with pytest.raises(RuntimeError, match="use_tpu"):
        Trainer(_cfg(d, size_map, use_tpu=True))


def test_jit_xla_false_runs_eagerly(ctr_data):
    import jax
    import jax.numpy as jnp

    d, size_map = ctr_data
    tr = Trainer(_cfg(d, size_map, jit_xla=False, shuffle_buffer_size=100,
                      per_device_train_batch_size=8,
                      per_device_eval_batch_size=8))
    # under the trainer's context, jit is a no-op: the trace re-runs on
    # every call instead of being compiled once and cached
    traces = []

    @jax.jit
    def probe(x):
        traces.append(1)
        return x + 1

    with tr._jit_ctx():
        probe(jnp.zeros(()))
        probe(jnp.zeros(()))
    assert len(traces) == 2, "jit_xla=false must disable compilation caching"
    metrics = tr.fit()
    assert 0.0 <= metrics["auc"] <= 1.0


def test_num_workers_preserves_order(ctr_data):
    from tdfo_tpu.data.loader import ParquetStream, resolve_files

    d, _ = ctr_data
    files = resolve_files(d, "parquet/train_part_*.parquet")
    base = ParquetStream(files, batch_size=32, shuffle=False, drop_last=False)
    threaded = ParquetStream(files, batch_size=32, shuffle=False,
                             drop_last=False, num_workers=3)
    for a, b in zip(base, threaded):
        for k in a:
            np.testing.assert_array_equal(a[k], b[k])


def test_sparse_optimizer_knob(tmp_path):
    """sparse_optimizer="rowwise_adagrad" trains the DMP regime with per-row
    accumulator state, packed into fused fat-line storage above the
    threshold (fbgemm EXACT_ROWWISE_ADAGRAD fused-TBE parity)."""
    import jax
    import numpy as np

    from tdfo_tpu.core.config import read_configs
    from tdfo_tpu.data.ctr_preprocessing import run_ctr_preprocessing
    from tdfo_tpu.data.synthetic import write_synthetic_goodreads
    from tdfo_tpu.train.trainer import Trainer

    d = tmp_path / "gr"
    write_synthetic_goodreads(d, n_users=60, n_books=80,
                              interactions_per_user=(12, 24), seed=9)
    ctr = run_ctr_preprocessing(d)
    cfg = read_configs(
        None, data_dir=d, model="twotower", model_parallel=True,
        sparse_optimizer="rowwise_adagrad", fused_table_threshold=8,
        n_epochs=1, learning_rate=3e-3, embed_dim=8,
        per_device_train_batch_size=16, per_device_eval_batch_size=16,
        shuffle_buffer_size=500, log_every_n_steps=1000, size_map=ctr,
    )
    tr = Trainer(cfg)
    # the tiny threshold forces fat-line storage — rowwise_adagrad composes
    # with it: the accumulator cell lives IN the packed line, so fat arrays
    # carry no slot state at all
    assert any(t.ndim == 3 for t in tr.state.tables.values())
    for name, slot in tr.state.slots.items():
        if tr.state.tables[name].ndim == 3:
            assert slot == ()
        else:  # plain tables keep the per-row accumulator slot
            assert slot[0].shape == (tr.state.tables[name].shape[0],)
    m = tr.fit()
    assert 0.0 <= m["auc"] <= 1.0

    import pytest

    with pytest.raises(ValueError, match="sparse_optimizer"):
        read_configs(None, sparse_optimizer="lion")


def test_stack_tables_knob(tmp_path):
    """Config(stack_tables=true) must reach the collection through the
    Trainer (observable: the state pytree holds one __tablestack_ array)."""
    from tdfo_tpu.core.config import read_configs
    from tdfo_tpu.data.ctr_preprocessing import run_ctr_preprocessing
    from tdfo_tpu.data.synthetic import write_synthetic_goodreads
    from tdfo_tpu.train.trainer import Trainer

    d = tmp_path / "gr"
    write_synthetic_goodreads(d, n_users=50, n_books=70,
                              interactions_per_user=(12, 22), seed=17)
    ctr = run_ctr_preprocessing(d)
    common = dict(
        data_dir=d, model="dlrm", model_parallel=True, embed_dim=8,
        per_device_train_batch_size=16, per_device_eval_batch_size=16,
        shuffle_buffer_size=200, size_map=ctr,
    )
    tr_on = Trainer(read_configs(None, stack_tables=True, **common))
    stacks = [n for n in tr_on.state.tables if n.startswith("__tablestack_")]
    assert stacks, tr_on.state.tables.keys()
    assert all(c.isalnum() or c == "_" for c in stacks[0]), stacks[0]
    tr_off = Trainer(read_configs(None, **common))
    assert not any(n.startswith("__tablestack_") for n in tr_off.state.tables)


def test_dedup_lookup_knob(tmp_path):
    """dedup_lookup=true trains the DMP regime with identical metrics to the
    default path (the knob changes the schedule, not the math)."""
    import numpy as np

    from tdfo_tpu.core.config import read_configs
    from tdfo_tpu.data.ctr_preprocessing import run_ctr_preprocessing
    from tdfo_tpu.data.synthetic import write_synthetic_goodreads
    from tdfo_tpu.train.trainer import Trainer

    d = tmp_path / "gr"
    write_synthetic_goodreads(d, n_users=50, n_books=70,
                              interactions_per_user=(12, 22), seed=23)
    ctr = run_ctr_preprocessing(d)
    common = dict(
        data_dir=d, model="twotower", model_parallel=True, n_epochs=1,
        learning_rate=3e-3, embed_dim=8, per_device_train_batch_size=16,
        per_device_eval_batch_size=16, shuffle_buffer_size=200,
        log_every_n_steps=1000, size_map=ctr,
    )
    m_on = Trainer(read_configs(None, dedup_lookup=True, **common)).fit()
    m_off = Trainer(read_configs(None, **common)).fit()
    for k in m_off:
        assert np.isclose(m_on[k], m_off[k], rtol=1e-4, atol=1e-6), (k, m_on, m_off)

    import pytest

    with pytest.raises(ValueError, match="gspmd"):
        read_configs(None, dedup_lookup=True, lookup_mode="psum")
