"""``[telemetry]`` subsystem: in-graph counters, compile events, stall watchdog.

Three contracts (``tdfo_tpu/obs``, ``core/config.py`` TelemetrySpec):

  * **Counters are free when off and inert when on.**  Emission sites call
    ``counters.emit`` unconditionally but the thunk only runs under an
    active collector, so ``telemetry.counters=false`` traces a jaxpr
    BYTE-identical to a build with no telemetry code at all (pinned below
    by stripping the module), and a counters-on EAGER run is bit-identical
    to counters-off for every optimizer kind and composition (update
    cache, grouped a2a) — eager because two different XLA programs drift
    ~1 ulp from fusion-dependent FMA contraction (the
    ``test_update_cache.py`` convention), while op-by-op execution
    preserves exact equality and counters only ADD ops.
  * **Compile events are counted and retraces are loud.**  Every jax
    compilation lands in ``events.jsonl`` with name/duration/count; the
    serve frontend's bucketed ragged trace compiles exactly one program
    per padded shape; compilations after ``mark_warmup`` warn.
  * **The watchdog notices a wedged loop.**  Heartbeats advance while
    steps complete; a stall past ``stall_timeout_s`` fires ONCE (re-armed
    by recovery) with every thread's Python stack in the record —
    exercised unit-level with an injected clock and end-to-end through
    the ``[faults]`` stall trigger inside a full Trainer fit.
"""

import dataclasses
import json
import logging
import re

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from tdfo_tpu.models.dlrm import DLRMBackbone
from tdfo_tpu.obs import counters as C
from tdfo_tpu.obs import events
from tdfo_tpu.obs.watchdog import StallWatchdog
from tdfo_tpu.ops.sparse import sparse_optimizer
from tdfo_tpu.parallel.embedding import EmbeddingSpec, ShardedEmbeddingCollection
from tdfo_tpu.train.ctr import ctr_sparse_forward
from tdfo_tpu.train.sparse_step import (
    SparseTrainState,
    make_cache_flush_fn,
    make_sparse_train_step,
)

CATS = ("c0", "c1", "c2")
CONTS = ("x0",)
SIZES = {"c0": 7, "c1": 50, "c2": 300}
N_STEPS = 3


# --------------------------------------------------- unit: the registry


def test_emit_is_lazy_scoped_and_suppressible():
    """No collector -> the value thunk is never evaluated (the zero-cost
    contract); scope() prefixes names; suppress() blacks out a region."""
    calls = []

    def thunk():
        calls.append(1)
        return jnp.float32(3.0)

    assert not C.enabled()
    C.emit("x", thunk)  # falls on the floor, thunk unevaluated
    assert not calls
    with C.collect() as c:
        assert C.enabled()
        C.emit("x", thunk)
        with C.scope("emb/c0/"):
            C.emit("touched", 5)
        with C.suppress():
            assert not C.enabled()
            C.emit("hidden", thunk)
    assert not C.enabled()
    got = {k: float(v) for k, v in c.items()}
    assert got == {"x": 3.0, "emb/c0/touched": 5.0}
    assert len(calls) == 1  # the suppressed emit never ran its thunk


def test_nested_collectors_are_independent():
    with C.collect() as outer:
        C.emit("a", 1)
        with C.collect() as inner:
            C.emit("b", 2)
        C.emit("c", 3)
    assert set(outer) == {"a", "c"} and set(inner) == {"b"}


# ------------------------------------- trajectory bit-equivalence (eager)


def _build(mesh, kind, *, cache_rows=0, grouped=False, flush_counters=False):
    """The test_update_cache.py harness, jit=False throughout: counters
    can only be read across an eager step (a collector cannot see through
    an inner jit boundary), and eager execution is what makes the
    on-vs-off comparison exactly bitwise."""
    specs = [EmbeddingSpec(c, SIZES[c], 8, features=(c,), sharding="row")
             for c in CATS]
    coll = ShardedEmbeddingCollection(
        specs, mesh=mesh, stack_tables=not grouped, grouped_a2a=grouped,
        cache_rows=cache_rows)
    bb = DLRMBackbone(embed_dim=8, cat_columns=CATS, cont_columns=CONTS)
    dummy_e = {c: jnp.zeros((1, 8), jnp.float32) for c in CATS}
    dummy_c = {c: jnp.zeros((1,), jnp.float32) for c in CONTS}
    state = SparseTrainState.create(
        dense_params=bb.init(jax.random.key(1), dummy_e, dummy_c)["params"],
        tx=optax.adam(1e-2),
        tables=coll.init(jax.random.key(0)),
        sparse_opt=sparse_optimizer(kind, lr=1e-2, weight_decay=1e-3,
                                    small_vocab_threshold=100))
    flush = None
    if cache_rows:
        caches = coll.init_caches(state.tables, state.sparse_opt)
        state = dataclasses.replace(state, slots={**state.slots, **caches})
        flush = make_cache_flush_fn(donate=False, jit=False,
                                    counters=flush_counters)
    step = make_sparse_train_step(
        coll, ctr_sparse_forward(bb), mode="alltoall" if grouped else "gspmd",
        donate=False, jit=False)
    return step, flush, state


def _batches(n):
    rr = np.random.default_rng(12)
    for _ in range(n):
        batch = {c: jnp.asarray(rr.integers(0, SIZES[c], 32), jnp.int32)
                 for c in CATS}
        batch["x0"] = jnp.asarray(rr.random(32, dtype=np.float32))
        batch["label"] = jnp.asarray(rr.integers(0, 2, 32), jnp.float32)
        yield batch


def _traj(mesh, kind, *, cache_rows=0, grouped=False, counters=False,
          n=N_STEPS):
    step, flush, state = _build(mesh, kind, cache_rows=cache_rows,
                                grouped=grouped, flush_counters=counters)
    losses, ctr_log = [], []
    for i, batch in enumerate(_batches(n)):
        if counters:
            with C.collect() as c:
                state, loss = step(state, batch)
            ctr_log.append({k: float(v) for k, v in c.items()})
        else:
            state, loss = step(state, batch)
        losses.append(
            np.asarray(loss).astype(np.float32).view(np.uint32).item())
        if flush is not None and (i + 1) % 2 == 0:
            if counters:
                state, over, fc = flush(state)
                ctr_log[-1].update({k: float(v) for k, v in fc.items()})
            else:
                state, over = flush(state)
            assert all(int(v) == 0 for v in over.values()), over
    if flush is not None:
        out = flush(state)
        state, over = out[0], out[1]
        assert all(int(v) == 0 for v in over.values()), over
    return losses, state, ctr_log


def _assert_state_bitwise(s0, s1, ctx=""):
    for a in s0.tables:
        np.testing.assert_array_equal(
            np.asarray(s0.tables[a]).view(np.uint32),
            np.asarray(s1.tables[a]).view(np.uint32),
            err_msg=f"{ctx}: table {a}")
    for a in s0.slots:
        for j, (x, y) in enumerate(zip(
                jax.tree_util.tree_leaves(s0.slots[a]),
                jax.tree_util.tree_leaves(s1.slots[a]))):
            assert np.asarray(x).tobytes() == np.asarray(y).tobytes(), \
                f"{ctx}: slot {a} leaf {j}"
    for j, (x, y) in enumerate(zip(
            jax.tree_util.tree_leaves(s0.dense_params),
            jax.tree_util.tree_leaves(s1.dense_params))):
        assert np.asarray(x).tobytes() == np.asarray(y).tobytes(), \
            f"{ctx}: dense leaf {j}"


@pytest.mark.parametrize("kind", [
    # tier-1 keeps the north-star rowwise kind; the other slot layouts
    # cover the same emit sites (test_update_cache slow-marking idiom)
    pytest.param("sgd", marks=pytest.mark.slow),
    pytest.param("adagrad", marks=pytest.mark.slow),
    "rowwise_adagrad",
    pytest.param("adam", marks=pytest.mark.slow),
])
def test_counters_do_not_change_trajectory(mesh8, kind):
    """Counters-on vs counters-off, same seed, eager: losses and final
    state bit-identical for every optimizer kind — and the collector
    actually filled (per-table touched counts + grad/param norms)."""
    l_off, s_off, _ = _traj(mesh8, kind)
    l_on, s_on, ctrs = _traj(mesh8, kind, counters=True)
    assert l_off == l_on
    _assert_state_bitwise(s_off, s_on, kind)
    assert len(ctrs) == N_STEPS
    for c in ctrs:
        assert "grad_norm" in c and "param_norm" in c
        touched = {k: v for k, v in c.items()
                   if k.startswith("emb/") and k.endswith("touched_ids")}
        assert touched, sorted(c)
        # every id in the synthetic batch is valid (no negative padding)
        assert sum(touched.values()) == 32 * len(CATS)
        assert c["grad_norm"] > 0 and c["param_norm"] > 0


@pytest.mark.slow  # 2 eager trajectories; tier-1 covers the cache counters
# + hit_rate end-to-end via test_trainer_full_telemetry_run
def test_counters_cache_composition(mesh8):
    """Update-cache run: hit/miss counters ride the step, flushed/resident
    ride the flush program — and the trajectory stays bit-identical."""
    kw = dict(cache_rows=1024)
    l_off, s_off, _ = _traj(mesh8, "rowwise_adagrad", **kw)
    l_on, s_on, ctrs = _traj(mesh8, "rowwise_adagrad", counters=True, **kw)
    assert l_off == l_on
    _assert_state_bitwise(s_off, s_on, "cache")
    seen = set().union(*ctrs)
    for suffix in ("cache_hit_rows", "cache_miss_rows"):
        assert any(k.startswith("emb/") and k.endswith(suffix)
                   for k in seen), (suffix, sorted(seen))
    # flush-step records carry the write-back counters
    flush_recs = [c for c in ctrs
                  if any(k.endswith("cache_flushed_rows") for k in c)]
    assert flush_recs
    # step 0 is all misses (cold cache); flushed rows cover what was dirty
    first = ctrs[0]
    hits0 = sum(v for k, v in first.items() if k.endswith("cache_hit_rows"))
    misses0 = sum(v for k, v in first.items() if k.endswith("cache_miss_rows"))
    assert hits0 == 0 and misses0 > 0


@pytest.mark.slow  # 2 eager trajectories; the shard_map suppression
# mechanism stays tier-1-covered by test_trainer_a2a_fill_telemetry
def test_counters_grouped_a2a_composition(mesh8):
    """Grouped cross-table exchange (shard_map inside): emission inside
    manual-SPMD bodies is suppressed rather than leaking tracers, the
    step-level norms still report, and the math is untouched."""
    l_off, s_off, _ = _traj(mesh8, "sgd", grouped=True)
    l_on, s_on, ctrs = _traj(mesh8, "sgd", grouped=True, counters=True)
    assert l_off == l_on
    _assert_state_bitwise(s_off, s_on, "grouped")
    for c in ctrs:
        assert "grad_norm" in c and "param_norm" in c


def test_counters_off_jaxpr_byte_identical(mesh8, monkeypatch):
    """The laziness pin: tracing with no collector produces the SAME jaxpr
    text as tracing with emit/enabled stubbed out entirely — counters=false
    cannot cost even one equation.  (Addresses normalised: jaxpr printing
    embeds object ids.)"""
    step, _, state = _build(mesh8, "rowwise_adagrad")
    batch = next(_batches(1))
    norm = lambda j: re.sub(r"0x[0-9a-f]+", "0xADDR", str(j))

    def step_with_ctrs(state, batch):
        # how the trainer wires counters: they ride the return pytree
        with C.collect() as c:
            state, loss = step(state, batch)
        return state, loss, dict(c)

    j_on = norm(jax.make_jaxpr(step_with_ctrs)(state, batch))
    j_off = norm(jax.make_jaxpr(step)(state, batch))
    monkeypatch.setattr(C, "enabled", lambda: False)
    monkeypatch.setattr(C, "emit", lambda *a, **k: None)
    j_stripped = norm(jax.make_jaxpr(step)(state, batch))
    assert j_off == j_stripped
    assert j_on != j_off  # the pin detects what it claims to detect


# ------------------------------------------------------- stall watchdog


class FakeClock:
    def __init__(self, t=100.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, s):
        self.t += s


def test_watchdog_detects_stall_and_rearms(tmp_path):
    hb = tmp_path / "heartbeat.jsonl"
    clk = FakeClock()
    wd = StallWatchdog(hb, 10.0, clock=clk)
    wd.beat(1)
    clk.advance(5.0)
    assert wd.check() is False  # fresh heartbeat, no stall
    clk.advance(6.0)  # age 11 s > 10 s
    assert wd.check() is True  # fires exactly once...
    assert wd.check() is False  # ...until a beat re-arms it
    wd.beat(2)
    assert wd.check() is False  # recovered
    clk.advance(11.0)
    assert wd.check() is True  # re-armed detection fires again
    lines = [json.loads(l) for l in hb.read_text().splitlines()]
    stalls = [l for l in lines if l.get("kind") == "stall"]
    assert len(stalls) == 2 == len(wd.stall_events)
    # the dump names this very function's frame — diagnosable from the log
    assert "test_watchdog_detects_stall" in stalls[0]["stacks"]
    assert stalls[0]["last_step"] == 1 and stalls[1]["last_step"] == 2
    beats = [l for l in lines if "stalled" in l]
    steps = [l["last_step"] for l in beats]
    assert steps == sorted(steps) and steps[-1] == 2  # monotone heartbeat


def test_watchdog_thread_lifecycle(tmp_path):
    wd = StallWatchdog(tmp_path / "hb.jsonl", 0.08)
    wd.start()
    assert wd._thread is not None and wd._thread.daemon
    import time as _time

    _time.sleep(0.3)  # several poll intervals with no beat -> stall fires
    wd.stop()
    assert wd._thread is None
    assert wd.stall_events  # the daemon itself detected the silence
    # zero timeout = disabled: start() must not spawn a thread
    off = StallWatchdog(tmp_path / "hb2.jsonl", 0.0)
    off.start()
    assert off._thread is None
    off.stop()


# ------------------------------------------- compile events + retraces


def test_compile_events_count_frontend_programs(tmp_path, caplog):
    """The frontend's bucketed ragged trace compiles EXACTLY one program
    per padded shape (the bounded-jit-cache contract, now observable), a
    steady-state replay adds zero, and a post-warmup compile warns."""
    from tdfo_tpu.serve.frontend import MicroBatcher

    path = tmp_path / "events.jsonl"
    events.configure(path)
    try:
        assert events.active()

        def bucketed_score(batch):
            return batch["x"] * 2.0

        score = jax.jit(bucketed_score)

        def trace(mb):
            rng = np.random.default_rng(0)
            for i in range(24):
                n = int(rng.integers(1, 33))
                mb.submit(f"r{i}", {"x": np.arange(n, dtype=np.float32)})
                mb.poll()
            mb.drain()

        mb = MicroBatcher(score, buckets=(8, 16, 32), max_batch=32,
                          batch_deadline_ms=0.0)
        trace(mb)
        shapes = {padded for _, padded in mb.shipped}
        assert shapes
        n_compiles = events.compile_count("bucketed_score")
        assert n_compiles == len(shapes) <= 3
        events.mark_warmup()
        # steady state: same buckets hit the jit cache, zero new programs
        mb2 = MicroBatcher(score, buckets=(8, 16, 32), max_batch=32,
                           batch_deadline_ms=0.0)
        trace(mb2)
        assert events.compile_count("bucketed_score") == n_compiles
        # a genuinely new program after warmup is flagged LOUDLY
        with caplog.at_level(logging.WARNING, logger="tdfo_tpu.obs.events"):
            jax.jit(lambda x: x - 1.0)(jnp.zeros((3,), jnp.float32))
        assert any("UNEXPECTED RETRACE" in r.getMessage()
                   for r in caplog.records)
        recs = [json.loads(l) for l in path.read_text().splitlines()]
        compiles = [r for r in recs if r["kind"] == "compile"]
        assert any("bucketed_score" in r["name"] for r in compiles)
        assert all(r["duration_s"] >= 0 and r["count"] >= 1
                   for r in compiles)
        assert any(r["kind"] == "warmup_done" for r in recs)
        late = [r for r in compiles if r["after_warmup"]]
        assert late  # the post-warmup lambda landed with the flag set
    finally:
        events.configure(None)
    assert not events.active()
    assert events.compile_count() == 0  # detached recorder counts nothing


def test_events_do_not_leak_debug_spam_to_console(tmp_path):
    """jax mounts a level-NOTSET stderr StreamHandler on the "jax" logger,
    so lowering the dispatch logger to DEBUG would flood the console via
    propagation.  While recording, the DEBUG records must stay out of the
    parent chain; records at the logger's ORIGINAL threshold (real
    warnings) still pass through, and propagation is restored on stop."""
    jl = logging.getLogger("jax._src.dispatch")
    sink = logging.Handler(level=logging.DEBUG)
    seen = []
    sink.emit = seen.append
    root = logging.getLogger()
    root.addHandler(sink)
    try:
        events.configure(tmp_path / "ev.jsonl")
        jax.jit(lambda x: x * 3.0)(jnp.ones((4,), jnp.float32))
        assert events.compile_count() >= 1  # the recorder saw the compiles
        leaked = [r for r in seen if r.name == "jax._src.dispatch"
                  and r.levelno < logging.WARNING]
        assert not leaked, [r.getMessage() for r in leaked]
        jl.warning("dispatch warning passthrough")
        assert any(r.getMessage() == "dispatch warning passthrough"
                   for r in seen)
    finally:
        events.configure(None)
        root.removeHandler(sink)
    assert jl.propagate  # restored


def test_memory_snapshot_gated_on_backend():
    """Spoofed CPU devices expose no memory_stats: the sampler returns
    None instead of fabricating numbers, and the peak watermark is empty."""
    assert events.memory_snapshot() is None
    assert events.peak_memory() == {}


# ------------------------------------------------- config + MetricLogger


def test_telemetry_config_validation():
    from tdfo_tpu.core.config import read_configs

    cfg = read_configs(None, model="dlrm",
                       telemetry={"counters": True, "events": True,
                                  "stall_timeout_s": 5.0})
    assert cfg.telemetry.counters and cfg.telemetry.events
    assert cfg.telemetry.stall_timeout_s == 5.0
    dflt = read_configs(None, model="dlrm")
    assert not dflt.telemetry.counters and not dflt.telemetry.events
    assert dflt.telemetry.stall_timeout_s == 0.0
    with pytest.raises(ValueError, match="telemetry"):
        read_configs(None, model="dlrm", telemetry={"bogus": 1})
    with pytest.raises(ValueError, match="stall_timeout_s"):
        read_configs(None, model="dlrm", telemetry={"stall_timeout_s": -1.0})


def test_events_and_watchdog_need_an_output_dir():
    """events.jsonl / heartbeat.jsonl have nowhere to go without a
    checkpoint_dir or log_dir — refuse at construction, not mid-fit."""
    from tdfo_tpu.core.config import read_configs
    from tdfo_tpu.train.trainer import Trainer

    with pytest.raises(ValueError, match="checkpoint_dir"):
        Trainer(read_configs(None, model="twotower",
                             telemetry={"stall_timeout_s": 1.0}))
    with pytest.raises(ValueError, match="checkpoint_dir"):
        Trainer(read_configs(None, model="twotower",
                             telemetry={"events": True}))


def test_metric_logger_coerces_numpy_scalars(tmp_path, capsys):
    """Fetched device values arrive as numpy scalars/0-d arrays — the
    logger must coerce them to native types (json.dumps rejects np.float32)
    and route them through the float-format branch."""
    from tdfo_tpu.train.trainer import MetricLogger

    lg = MetricLogger(tmp_path)
    lg.log(step=np.int64(3), loss=np.float32(0.25),
           fill=np.float64(0.5) + np.zeros(()), plain=7)
    lg.close()
    rec = json.loads((tmp_path / "metrics.jsonl").read_text().splitlines()[0])
    assert rec["step"] == 3 and type(rec["step"]) is int
    assert rec["loss"] == 0.25 and type(rec["loss"]) is float
    assert rec["fill"] == 0.5 and rec["plain"] == 7
    out = capsys.readouterr().out
    assert "loss=0.25000" in out  # float formatting applied post-coercion


# ------------------------------------------- end-to-end: a full fit


@pytest.fixture(scope="module")
def tele_data(tmp_path_factory):
    from tdfo_tpu.data.ctr_preprocessing import run_ctr_preprocessing
    from tdfo_tpu.data.synthetic import write_synthetic_goodreads

    d = tmp_path_factory.mktemp("gr_tele")
    write_synthetic_goodreads(d, n_users=64, n_books=100,
                              interactions_per_user=(12, 30), seed=3)
    ctr = run_ctr_preprocessing(d)
    return d, ctr


def _tele_cfg(d, ctr, **kw):
    from tdfo_tpu.core.config import read_configs

    return read_configs(
        None, data_dir=d, model="twotower", model_parallel=True,
        mesh={"data": 4, "model": 2}, n_epochs=1, learning_rate=3e-3,
        embed_dim=8, per_device_train_batch_size=16,
        per_device_eval_batch_size=16, shuffle_buffer_size=500,
        log_every_n_steps=2, size_map=ctr,
        sparse_optimizer="rowwise_adagrad", **kw)


def test_trainer_full_telemetry_run(tele_data, tmp_path, capsys):
    """The acceptance run: counters + events + watchdog + update cache +
    an injected [faults] stall, one 8-device fit.  metrics.jsonl carries
    per-table touched counts, cache hit rate and grad/param norms at the
    log cadence; events.jsonl records the compilations and the final
    run summary; heartbeat.jsonl advances monotonically and the injected
    stall trips the watchdog end-to-end."""
    from tdfo_tpu.train.trainer import Trainer

    d, ctr = tele_data
    cfg = _tele_cfg(
        d, ctr,
        embeddings={"cache_rows": 512, "flush_every": 3},
        faults={"stall_at_step": 2, "stall_seconds": 1.0},
        telemetry={"counters": True, "events": True, "stall_timeout_s": 0.25})
    tr = Trainer(cfg, log_dir=tmp_path)
    metrics = tr.fit()
    assert np.isfinite(metrics["eval_loss"])

    recs = [json.loads(l)
            for l in (tmp_path / "metrics.jsonl").read_text().splitlines()]
    step_recs = [r for r in recs if "grad_norm" in r]
    assert step_recs  # counters landed at the existing log cadence
    last = step_recs[-1]
    assert last["param_norm"] > 0
    assert any(k.startswith("emb/") and k.endswith("touched_ids")
               for k in last), sorted(last)
    rate_keys = [k for r in step_recs for k in r
                 if k.endswith("cache_hit_rate")]
    assert rate_keys  # the cache composition reports hit rate
    assert all(0.0 <= r[k] <= 1.0
               for r in step_recs for k in r if k.endswith("cache_hit_rate"))

    ev = [json.loads(l)
          for l in (tmp_path / "events.jsonl").read_text().splitlines()]
    assert any(e["kind"] == "compile" for e in ev)
    assert any(e["kind"] == "warmup_done" for e in ev)
    assert ev[-1]["kind"] == "run_summary"  # fit() detached the recorder
    assert not events.active()

    hb = [json.loads(l)
          for l in (tmp_path / "heartbeat.jsonl").read_text().splitlines()]
    steps = [l["last_step"] for l in hb if "stalled" in l]
    assert steps and steps == sorted(steps)  # heartbeat advanced, monotone
    assert steps[-1] >= 2
    # the injected 1.0 s stall (timeout 0.25 s) tripped the watchdog
    assert "[faults] injected 1.0s stall" in capsys.readouterr().out
    assert tr._watchdog is not None and tr._watchdog.stall_events
    assert any(l.get("kind") == "stall" and "stacks" in l for l in hb)


def test_trainer_a2a_fill_telemetry(tele_data, tmp_path):
    """alltoall regime: the log-cadence fill probe reports exchange-bucket
    utilisation in (0, 1] and zero dropped ids at the default (exact)
    capacity."""
    from tdfo_tpu.train.trainer import Trainer

    d, ctr = tele_data
    cfg = _tele_cfg(d, ctr, lookup_mode="alltoall",
                    telemetry={"counters": True})
    tr = Trainer(cfg, log_dir=tmp_path)
    tr.fit()
    recs = [json.loads(l)
            for l in (tmp_path / "metrics.jsonl").read_text().splitlines()]
    fills = [r for r in recs if "a2a_fill" in r]
    assert fills
    assert all(0.0 < r["a2a_fill"] <= 1.0 for r in fills)
    assert all(r["a2a_dropped_ids"] == 0 for r in fills)
    assert all("grad_norm" in r for r in fills)
