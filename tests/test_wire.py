"""Wire-protocol unit tests (``tdfo_tpu/serve/wire.py``): framing
round-trips, torn/partial frames, oversized-payload refusal, the f32
JSON codec, and the connect-retry backoff schedule under an injected rng
— every failure mode a kill -9 mid-write can produce, without spawning a
process.

Raw ``socket`` use is legal here: the test_quality.py monopoly rule scans
``tdfo_tpu/`` only, and these tests ARE the monopoly's contract checks.
"""

import random
import socket
import struct
import threading

import numpy as np
import pytest

from tdfo_tpu.serve import wire
from tdfo_tpu.utils.retry import backoff_delay


@pytest.fixture()
def pair():
    a, b = socket.socketpair()
    yield a, b
    a.close()
    b.close()


def test_roundtrip_and_frame_boundaries(pair):
    """Messages round-trip exactly, back-to-back frames stay separated,
    and a clean close at a frame boundary raises Disconnect (the shape a
    graceful peer shutdown produces)."""
    a, b = pair
    msgs = [{"type": "score", "rid": 7, "feats": {}},
            {"type": "drain"},
            {"type": "reply", "rid": 7, "scores": [0.25, -1.5]}]
    for m in msgs:
        wire.send_msg(a, m)
    assert [wire.recv_msg(b) for _ in msgs] == msgs
    a.close()
    with pytest.raises(wire.Disconnect):
        wire.recv_msg(b)


def test_torn_header_and_torn_body_are_loud(pair):
    """EOF mid-header or mid-body is a torn frame — a WireError naming the
    tear, never a silent Disconnect: the bytes already read would
    otherwise desync every later frame on a reused connection."""
    a, b = pair
    a.sendall(b"\x00\x00")  # 2 of 4 header bytes
    a.close()
    with pytest.raises(wire.WireError, match="torn frame"):
        wire.recv_msg(b)

    c, d = socket.socketpair()
    try:
        c.sendall(wire._HEADER.pack(100) + b'{"type":')  # 8 of 100 body bytes
        c.close()
        with pytest.raises(wire.WireError, match="torn frame"):
            wire.recv_msg(d)
    finally:
        d.close()


def test_oversized_frame_refused_from_declared_length(pair):
    """The receiver refuses an oversized frame from the DECLARED length —
    before buffering a single body byte — and the sender refuses to send
    one at all.  max_frame is the memory-safety valve: without the header
    check a hostile or corrupt peer makes the ingress allocate the whole
    declared length."""
    a, b = pair
    with pytest.raises(wire.FrameTooLarge):
        wire.send_msg(a, {"blob": "x" * 2048}, max_frame=1024)
    a.sendall(wire._HEADER.pack(1 << 30))  # declared 1 GiB, no body
    with pytest.raises(wire.FrameTooLarge):
        wire.recv_msg(b, max_frame=1024)


def test_non_dict_payload_rejected(pair):
    a, b = pair
    payload = b'[1, 2, 3]'
    a.sendall(wire._HEADER.pack(len(payload)) + payload)
    with pytest.raises(wire.WireError, match="JSON object"):
        wire.recv_msg(b)


def test_feats_codec_is_bitwise_for_f32_and_preserves_dtypes():
    """f32 round-trips bitwise through JSON binary64 (every binary32 is
    exactly representable), and int32/int8 shapes + dtypes survive — the
    probe-trace bitwise acceptance depends on this codec being lossless."""
    rng = np.random.default_rng(0)
    batch = {
        "user_id": rng.integers(0, 1 << 31 - 1, size=7, dtype=np.int32),
        "avg_rating": rng.random(7, dtype=np.float32) * 1e-7,
        "label": rng.integers(0, 2, size=7, dtype=np.int8),
        "mat": rng.standard_normal((2, 3)).astype(np.float32),
    }
    out = wire.decode_feats(wire.encode_feats(batch))
    assert set(out) == set(batch)
    for k in batch:
        assert out[k].dtype == batch[k].dtype, k
        assert out[k].shape == batch[k].shape, k
        np.testing.assert_array_equal(out[k], batch[k])


def test_connect_backoff_schedule_is_the_single_retry_law(tmp_path):
    """``wire.connect`` against a listener that does not exist yet sleeps
    exactly the ``utils/retry.backoff_delay`` schedule (capped exponential
    from base_ms, jitter drawn from the injected rng) — bit-for-bit the
    delays an identically-seeded rng predicts — then surfaces the OSError
    once the attempt budget is spent."""
    path = tmp_path / "nobody-home.sock"
    slept: list[float] = []
    with pytest.raises(OSError):
        wire.connect(path, attempts=4, base_ms=10.0, max_ms=2000.0,
                     sleep=slept.append, rng=random.Random(13))
    ref_rng = random.Random(13)
    expected = [backoff_delay(i, base_delay=0.010, max_delay=2.0,
                              rng=ref_rng) for i in range(3)]
    assert slept == expected
    assert len(slept) == 3  # attempts - 1 sleeps, budget respected


def test_connect_rides_out_a_late_binding_listener(tmp_path):
    """The supervisor's contract with a freshly-spawned child: the child
    binds its listener late (interpreter + imports), the ingress's retry
    schedule covers the window, and the connect succeeds without manual
    coordination."""
    path = tmp_path / "late.sock"
    ready = threading.Event()

    def bind_late():
        listener = wire.listen(path)
        ready.set()
        conn, _ = listener.accept()
        wire.send_msg(conn, {"type": "hello"})
        conn.close()
        listener.close()

    t = threading.Thread(target=bind_late, daemon=True)

    slept: list[float] = []

    def sleep_then_bind(dt):
        slept.append(dt)
        if len(slept) == 2 and not t.is_alive():
            t.start()
            ready.wait(timeout=5)

    sock = wire.connect(path, attempts=10, base_ms=1.0,
                        sleep=sleep_then_bind, rng=random.Random(0))
    try:
        assert wire.recv_msg(sock) == {"type": "hello"}
    finally:
        sock.close()
        t.join(timeout=5)
    assert len(slept) >= 2  # it actually had to retry


def test_listener_from_fd_adopts_a_prebound_socket(tmp_path):
    """The socket-activation handoff: a listener bound by one owner keeps
    accepting through a SECOND fd (the child's inherited copy) after the
    first owner closes its own — connects made before the adopter even
    existed are waiting in the backlog."""
    import os

    path = tmp_path / "activated.sock"
    listener = wire.listen(path)
    fd = os.dup(listener.fileno())  # what pass_fds gives the child
    client = wire.connect(path, attempts=1)  # lands in the backlog now
    listener.close()  # parent drops its copy; the socket stays bound
    adopted = wire.listener_from_fd(fd)
    try:
        conn, _ = adopted.accept()
        wire.send_msg(conn, {"type": "hello"})
        assert wire.recv_msg(client) == {"type": "hello"}
        conn.close()
    finally:
        client.close()
        adopted.close()


def test_listen_replaces_stale_socket_path(tmp_path):
    """A SIGKILLed replica leaves its socket file behind; the respawned
    child must bind over it (stale-path unlink) or every respawn would
    need manual cleanup."""
    path = tmp_path / "stale.sock"
    first = wire.listen(path)
    first.close()  # dies without unlinking — the kill -9 shape
    assert path.exists()
    second = wire.listen(path)
    try:
        client = wire.connect(path, attempts=1)
        client.close()
    finally:
        second.close()
