"""Fleet/canary-gatekeeper acceptance: a multi-replica serving fleet over
one ``BundleStore``, a gated online supervisor (``[online] canary_cycles``)
that shadow-scores every candidate, canaries it on a fraction of replicas
and auto-rolls-back on AUC regression — drilled with REAL deterministic
faults (``regress_auc_at_cycle`` training/serving skew, ``os._exit`` kills
mid-canary) in subprocesses, the tests/test_online.py pattern.

The request logs are written ONCE by the module fixture as a FLEET layout
(``replica-<k>`` per-replica directories, the ``serve/fleet.py`` writer
contract) with labels correlated with the ``avg_rating`` feature, so the
injected skew (negated ``avg_rating``) measurably craters held-out AUC
while honest scorers do not.

Tier 1 runs the acceptance drill: ``regress_auc_at_cycle=1`` passes the
shadow gate (the bundle BYTES are healthy), reaches only the canary
cohort, rolls back bitwise with the rejection ledgered — plus the same
drill killed mid-canary-watch and restarted, which must converge to the
uninterrupted drill verdict bit for bit.  The wider supervisor/replica
kill matrix is ``@pytest.mark.slow``.
"""

import json
import os
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

REPO = str(Path(__file__).resolve().parents[1])
WORKER = str(Path(__file__).with_name("fleet_worker.py"))

LOCAL_DEVICES = 4
BATCH_ROWS = 8 * 4  # per_device_train_batch_size x data-axis size
STEPS_PER_CYCLE = 2
N_CYCLES = 2  # full gated cycles the fleet logs hold
N_REPLICAS = 2  # canary_fraction 0.5 -> replica 0 canaries, replica 1 stable


def _spawn(spec_path: Path) -> subprocess.Popen:
    env = os.environ.copy()
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = f"{REPO}{os.pathsep}" + env.get("PYTHONPATH", "")
    return subprocess.Popen(
        [sys.executable, WORKER, str(spec_path)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True, env=env,
    )


def _run_workers(spec_paths: list[Path]) -> tuple[list[int], list[str]]:
    procs = [_spawn(p) for p in spec_paths]
    rcs, outs = [], []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=600)
            rcs.append(p.returncode)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        raise
    return rcs, outs


def _run_worker(spec_path: Path) -> tuple[int, str]:
    rcs, outs = _run_workers([spec_path])
    return rcs[0], outs[0]


@pytest.fixture(scope="module")
def fleet_env(tmp_path_factory):
    """Synthetic goodreads data + a per-replica fleet request-log layout.

    Labels are a deterministic function of the first continuous column
    (``label = avg_rating > 0.5``): honest scorers sit near (or above)
    chance on the held-out slice, while the injected skew — which serves
    ``-avg_rating`` — scores near-zero AUC, so the canary watch separates
    them by a margin far beyond ``max_auc_regression``."""
    from tdfo_tpu.core.config import load_size_map, read_configs
    from tdfo_tpu.data.ctr_preprocessing import run_ctr_preprocessing
    from tdfo_tpu.data.replay import RequestLog, replica_log_dir
    from tdfo_tpu.data.synthetic import write_synthetic_goodreads
    from tdfo_tpu.serve.frontend import _column_vocab
    from tdfo_tpu.train.trainer import _ctr_columns

    d = tmp_path_factory.mktemp("gr_fleet")
    write_synthetic_goodreads(d, n_users=80, n_books=120,
                              interactions_per_user=(15, 40), seed=29)
    run_ctr_preprocessing(d)

    cfg = read_configs(None, data_dir=str(d), model="twotower",
                       model_parallel=True, size_map=load_size_map(str(d)))
    cat_cols, cont_cols = _ctr_columns(cfg)
    vocab = _column_vocab(cfg, cat_cols)

    root = tmp_path_factory.mktemp("fleetlog") / "rl"
    logs = [RequestLog(replica_log_dir(root, k), segment_bytes=4096)
            for k in range(N_REPLICAS)]
    rng = np.random.default_rng(11)
    # every gated cycle consumes steps_per_cycle train batches AND peeks one
    # shadow batch beyond them, so the log needs one extra batch of slack
    rows_by_key: dict[tuple[int, int], int] = {}
    total, target = 0, (N_CYCLES * STEPS_PER_CYCLE + 1) * BATCH_ROWS
    i = 0
    while total < target + 5:  # sub-batch tail stays unread
        n = int(rng.integers(3, 9))
        feats = {c: rng.integers(0, vocab[c], size=n).tolist()
                 for c in cat_cols}
        for c in cont_cols:
            feats[c] = [round(float(v), 6) for v in rng.random(n)]
        feats["label"] = [int(v > 0.5) for v in feats[cont_cols[0]]]
        rid = i % N_REPLICAS  # interleave traffic across the fleet
        seq = logs[rid].append({
            "event": "serve_request", "request": f"r{total}", "rows": n,
            "outcome": "ok", "features": feats})
        rows_by_key[(rid, seq)] = n
        total += n
        i += 1
    for log in logs:
        log.close()
    return dict(data_dir=str(d), request_log=str(root),
                rows_by_key=rows_by_key, total_rows=total)


def _make_spec(tmp: Path, env: dict, name: str, *, ckpt: str, log: str,
               faults: dict | None = None, **knobs) -> Path:
    spec = dict(
        data_dir=env["data_dir"], checkpoint_dir=str(tmp / ckpt),
        log_dir=str(tmp / log), request_log=env["request_log"],
        out_json=str(tmp / f"{name}.json"), local_devices=LOCAL_DEVICES,
        steps_per_cycle=STEPS_PER_CYCLE, max_cycles=0,
        replicas=N_REPLICAS, canary_cycles=1, canary_fraction=0.5,
        max_auc_regression=0.3, shadow_eval_batches=1,
        faults=faults or {}, **knobs,
    )
    p = tmp / f"{name}_spec.json"
    p.write_text(json.dumps(spec))
    return p


@pytest.fixture(scope="module")
def fleet_runs(fleet_env, tmp_path_factory):
    """The tier-1 acceptance drill, run once for every audit below:

      * ``drill`` — ``regress_auc_at_cycle=1``: cycle 1's candidate serves
        skewed logits, must auto-rollback; cycle 2 retrains and promotes.
      * ``killdrill`` — the same regression PLUS ``kill_during_canary=1``:
        dies mid-watch with the candidate on the canary cohort and no
        durable verdict, then restarts the same command.
      * ``p99drill`` — ``slow_canary_at_cycle=1`` + ``slow_score_ms``:
        cycle 1's candidate serves CORRECT logits slowly; the
        ``max_p99_regression_ms`` verdict term must roll it back while the
        stable cohort's latency never regresses.

    All three run with ``[telemetry] trace = true`` so the assembled
    causal timelines are audited against the metrics ground truth.
    """
    from tdfo_tpu.utils.faults import KILL_EXIT_CODE

    tmp = tmp_path_factory.mktemp("fleet_runs")
    drill_p = _make_spec(tmp, fleet_env, "drill", ckpt="ckpt_drill",
                         log="log_drill", telemetry={"trace": True},
                         faults={"regress_auc_at_cycle": 1})
    kill_p = _make_spec(tmp, fleet_env, "killdrill", ckpt="ckpt_kill",
                        log="log_kill", telemetry={"trace": True},
                        faults={"regress_auc_at_cycle": 1,
                                "kill_during_canary": 1})
    p99_p = _make_spec(tmp, fleet_env, "p99drill", ckpt="ckpt_p99",
                       log="log_p99", telemetry={"trace": True},
                       max_p99_regression_ms=100.0,
                       faults={"slow_canary_at_cycle": 1,
                               "slow_score_ms": 400})

    rcs, outs = _run_workers([drill_p, kill_p, p99_p])
    assert rcs[0] == 0, f"drill run failed rc={rcs[0]}\n{outs[0][-2000:]}"
    assert rcs[1] == KILL_EXIT_CODE, \
        f"expected mid-canary kill, got rc={rcs[1]}\n{outs[1][-2000:]}"
    assert not (tmp / "killdrill.json").exists()  # died before any verdict
    assert (tmp / "ckpt_kill" / "faults_canary_kill.marker").exists()
    assert rcs[2] == 0, f"p99 drill failed rc={rcs[2]}\n{outs[2][-2000:]}"

    rc, out = _run_worker(kill_p)  # marker disarms the kill; redo the cycle
    assert rc == 0, f"resumed killdrill failed rc={rc}\n{out[-2000:]}"

    return dict(
        drill=json.loads((tmp / "drill.json").read_text()),
        killdrill=json.loads((tmp / "killdrill.json").read_text()),
        p99drill=json.loads((tmp / "p99drill.json").read_text()),
        drill_metrics=tmp / "log_drill" / "metrics.jsonl",
        p99_metrics=tmp / "log_p99" / "metrics.jsonl",
        drill_trace=tmp / "log_drill" / "trace",
        kill_trace=tmp / "log_kill" / "trace",
        p99_trace=tmp / "log_p99" / "trace",
        tmp=tmp,
    )


def _events(path: Path, event: str) -> list[dict]:
    recs = [json.loads(l) for l in path.read_text().splitlines()]
    return [r for r in recs if r.get("event") == event]


def test_drill_shadow_passes_then_canary_rolls_back(fleet_runs):
    """The acceptance fault drill: the regressing bundle's BYTES are
    healthy, so it passes the shadow gate and reaches the canary cohort —
    where held-out heartbeats catch the skew and roll it back, with the
    rejection recorded in cycle metrics and the store ledger."""
    cycles = _events(fleet_runs["drill_metrics"], "online_cycle")
    assert [c["verdict"] for c in cycles] == ["rollback", "promote"]
    bad = cycles[0]
    assert bad["gated"] and bad["cycle"] == 1 and bad["version"] == 1
    # shadow gate scored the candidate and passed it (bytes are honest)
    assert bad["shadow_auc"] >= bad["shadow_auc_base"] - 0.3
    # the canary watch measured the skew: near-zero AUC vs an honest stable
    assert bad["canary_auc"] < bad["stable_auc"] - 0.3
    assert "canary AUC" in bad["reason"]
    # the rejection is ledgered durably, keyed (version, digest)
    rej = fleet_runs["drill"]["rejections"]
    assert len(rej) == 1 and rej[0]["version"] == 1
    assert rej[0]["digest"] != fleet_runs["drill"]["digest"]
    # cycle 2 REUSES version 1 (delta chain stays parent+1) and promotes
    good = cycles[1]
    assert good["version"] == 1 and fleet_runs["drill"]["version"] == 1
    assert fleet_runs["drill"]["canary_version"] is None


def test_drill_canary_containment(fleet_runs):
    """While the bad candidate was live it served AT MOST the canary
    fraction of the fleet: watch-round heartbeats show the canary replica
    on the candidate and every stable replica still on the last good
    version."""
    hbs = _events(fleet_runs["drill_metrics"], "canary_heartbeat")
    round1 = [h for h in hbs if h["cycle"] == 1]
    assert {h["replica"] for h in round1} == set(range(N_REPLICAS))
    for h in round1:
        if h["canary"]:
            assert h["version"] == 1  # the candidate, canary cohort only
        else:
            assert h["version"] == 0  # stable stayed on the last good head


def test_drill_fleet_converges_bitwise(fleet_runs):
    """After the rollback + the healthy promote, every replica serves the
    same version and bitwise-identical probe logits — no replica is left
    on the rejected bundle."""
    drill = fleet_runs["drill"]
    versions = set(drill["replica_versions"].values())
    assert versions == {drill["version"]}
    logits = list(drill["logits"].values())
    assert len(logits) == N_REPLICAS
    for other in logits[1:]:
        assert other == logits[0]


def test_kill_during_canary_restart_converges(fleet_runs):
    """A kill mid-canary-watch (candidate live on the cohort, no durable
    verdict) + restart must converge to the uninterrupted drill's exact
    fleet state: store version AND digest, rejection ledger, merged replay
    cursor, per-replica served logits."""
    drill, kd = fleet_runs["drill"], fleet_runs["killdrill"]
    assert kd["version"] == drill["version"]
    assert kd["digest"] == drill["digest"]
    assert kd["cursor"] == drill["cursor"]
    assert kd["cycles_done"] == drill["cycles_done"]
    assert kd["logits"] == drill["logits"]
    assert [(r["version"], r["digest"]) for r in kd["rejections"]] == \
        [(r["version"], r["digest"]) for r in drill["rejections"]]


def test_merged_replay_exactly_once_accounting(fleet_runs, fleet_env):
    """Across the drill's durable cycles the consumed ``(replica_id, seq,
    row_start, row_end)`` spans tile each fleet record at most once with
    no gap and no overlap — replica interleave does not break the
    exactly-once contract, and rejected cycles still account their
    consumed-but-discarded records."""
    cycles = _events(fleet_runs["drill_metrics"], "online_cycle")
    assert len(cycles) == N_CYCLES
    spans: dict[tuple[int, int], list[tuple[int, int]]] = {}
    for c in cycles:
        for rid, seq, a, b in c["consumed"]:
            spans.setdefault((rid, seq), []).append((a, b))
    rows_by_key = {tuple(map(int, k)) if isinstance(k, tuple) else k: v
                   for k, v in fleet_env["rows_by_key"].items()}
    assert spans, "no consumed spans logged"
    for key, parts in spans.items():
        parts.sort()
        assert parts[0][0] == 0, (key, parts)
        for (a0, b0), (a1, b1) in zip(parts, parts[1:]):
            assert b0 == a1, f"{key}: gap or overlap at {parts}"
        assert parts[-1][1] <= rows_by_key[key]
    # both replicas' logs contributed to training — the merger merges
    assert {k[0] for k in spans} == set(range(N_REPLICAS))


def test_p99_regression_rolls_back_and_stable_never_regresses(fleet_runs):
    """The latency twin of the AUC drill: cycle 1's candidate serves
    correct logits slowly (``slow_canary_at_cycle``), passes the shadow
    and AUC gates, and is rolled back by the ``max_p99_regression_ms``
    verdict term — while the stable cohort's heartbeat p99 stays far under
    the budget.  Cycle 2's honest candidate promotes."""
    cycles = _events(fleet_runs["p99_metrics"], "online_cycle")
    assert [c["verdict"] for c in cycles] == ["rollback", "promote"]
    bad = cycles[0]
    # the AUC gates saw nothing wrong — the logits are correct
    assert bad["shadow_auc"] >= bad["shadow_auc_base"] - 0.3
    assert bad["canary_auc"] >= bad["stable_auc"] - 0.3
    # the latency term caught it: canary p99 carries the injected sleep,
    # the stable cohort never slowed down
    assert "p99" in bad["reason"]
    assert bad["canary_p99_ms"] > bad["stable_p99_ms"] + 100.0
    assert bad["stable_p99_ms"] < 100.0
    # ledgered exactly like an AUC rejection; cycle 2 reuses the version
    res = fleet_runs["p99drill"]
    assert len(res["rejections"]) == 1 and res["rejections"][0]["version"] == 1
    assert res["version"] == 1 and res["canary_version"] is None
    assert set(res["replica_versions"].values()) == {1}
    good = cycles[1]
    assert good["canary_p99_ms"] is not None  # measured, under budget
    assert not good["reason"]


def test_trace_assembly_reconstructs_drill(fleet_runs):
    """The assembled causal timeline agrees with the metrics ground truth:
    per-cycle verdicts/versions, per-stage breakdowns for the full gated
    chain, cohort-split heartbeat histograms, and the pointer-flip ledger
    (canary -> rollback, canary -> promote)."""
    from tdfo_tpu.obs.aggregate import assemble, chrome_trace, load_spans

    spans = load_spans(fleet_runs["drill_trace"])
    assert spans, "trace=true produced no spans"
    report = assemble(spans)
    metrics = _events(fleet_runs["drill_metrics"], "online_cycle")
    assert [c["cycle"] for c in report["cycles"]] == [1, 2]
    for traced, logged in zip(report["cycles"], metrics):
        assert traced["verdict"] == logged["verdict"]
        assert traced["version"] == logged["version"]
        # the span's consumed ranges are the metrics record's, verbatim —
        # the exactly-once row audit above therefore covers the trace too
        assert traced["consumed_keys"] == sorted(
            {(rid, seq) for rid, seq, _, _ in logged["consumed"]})
        assert set(traced["stages"]) >= {"replay", "train", "verdict",
                                         "commit", "swap"}
        assert traced["dur_ms"] > 0
        assert traced["steps"][1] - traced["steps"][0] == STEPS_PER_CYCLE
    fl = report["fleet"]
    assert fl["canary_heartbeats"]["n"] > 0
    assert fl["stable_heartbeats"]["n"] > 0
    ops = [f["op"] for f in report["pointer_flips"]]
    assert ops.count("canary") == N_CYCLES  # one candidate staged per cycle
    assert "rollback" in ops and "promote" in ops
    # the chrome export of a real run serializes end to end
    json.dumps(chrome_trace(spans))


def test_trace_killdrill_assembles_exactly_once(fleet_runs):
    """The acceptance bar: the killed-and-restarted run's sinks hold
    partial spans from BOTH lineages, yet the assembled timeline
    reconstructs every cycle exactly once and converges to the
    uninterrupted drill — cycle spans land only at the verdict durability
    point, and the assembler keeps the last durable emission per cycle."""
    from tdfo_tpu.obs.aggregate import assemble, load_spans

    kd = assemble(load_spans(fleet_runs["kill_trace"]))
    drill = assemble(load_spans(fleet_runs["drill_trace"]))
    assert [c["cycle"] for c in kd["cycles"]] == [1, 2]  # no dup, no gap
    for k, d in zip(kd["cycles"], drill["cycles"]):
        assert k["verdict"] == d["verdict"]
        assert k["version"] == d["version"]
        assert k["consumed_keys"] == d["consumed_keys"]
    # row-level exactly-once from the TRACE spans alone: the per-key
    # ranges across cycles tile contiguously from 0 with no overlap
    ranges: dict[tuple[int, int], list[tuple[int, int]]] = {}
    kd_cycle_spans = [s for s in load_spans(fleet_runs["kill_trace"])
                      if s.get("kind") == "online_cycle"]
    by_cycle = {int(s["cycle"]): s for s in kd_cycle_spans}  # last wins
    for s in by_cycle.values():
        for rid, seq, a, b in s["consumed"]:
            ranges.setdefault((rid, seq), []).append((a, b))
    assert ranges
    for key, parts in ranges.items():
        parts.sort()
        assert parts[0][0] == 0, (key, parts)
        for (_, b0), (a1, _) in zip(parts, parts[1:]):
            assert b0 == a1, f"{key}: gap or overlap at {parts}"


# --------------------------------------------------------------------------
# the wider kill matrix: supervisor kills at gated stage boundaries and
# replica deaths mid-watch.  Tier 1 covers the mid-canary kill above.


@pytest.fixture(scope="module")
def healthy_ref(fleet_env, tmp_path_factory):
    """Uninterrupted fault-free gated run — the slow matrix's reference."""
    tmp = tmp_path_factory.mktemp("fleet_ref")
    spec = _make_spec(tmp, fleet_env, "ref", ckpt="ckpt_ref", log="log_ref")
    rc, out = _run_worker(spec)
    assert rc == 0, f"reference run failed rc={rc}\n{out[-2000:]}"
    ref = json.loads((tmp / "ref.json").read_text())
    ref["_metrics"] = str(tmp / "log_ref" / "metrics.jsonl")
    return ref


@pytest.mark.slow
@pytest.mark.parametrize("faults", [
    {"kill_between_stages": 6},  # canary watched, verdict not yet durable
    {"kill_between_stages": 7},  # verdict durable, store commit missing
    {"kill_between_stages": 8},  # committed, fleet re-sync + GC missing
    {"kill_during_swap": 1},     # mid-publish_canary: torn canary dir
    {"corrupt_candidate": 1},    # gate catches the bit-flip, re-export heals
], ids=lambda f: "-".join(f"{k}{v}" for k, v in f.items()))
def test_gated_kill_matrix_converges(healthy_ref, fleet_env, tmp_path,
                                     faults):
    """Kill the gated supervisor at every post-publish stage boundary (and
    corrupt a candidate export): restarting the same command must converge
    to the fault-free reference, bit for bit."""
    from tdfo_tpu.utils.faults import KILL_EXIT_CODE

    spec = _make_spec(tmp_path, fleet_env, "killed", ckpt="ckpt",
                      log="log", faults=faults)
    rc, out = _run_worker(spec)
    if "corrupt_candidate" in faults:
        assert rc == 0, f"rc={rc}\n{out[-2000:]}"  # healed in-line, no kill
    else:
        assert rc == KILL_EXIT_CODE, f"rc={rc}\n{out[-2000:]}"
        assert not (tmp_path / "killed.json").exists()
        rc, out = _run_worker(spec)
        assert rc == 0, f"resumed run failed rc={rc}\n{out[-2000:]}"
    resumed = json.loads((tmp_path / "killed.json").read_text())
    assert resumed["version"] == healthy_ref["version"]
    assert resumed["digest"] == healthy_ref["digest"]
    assert resumed["cursor"] == healthy_ref["cursor"]
    assert resumed["logits"] == healthy_ref["logits"]
    assert resumed["rejections"] == []


@pytest.mark.slow
def test_drill_kill_before_commit_converges(fleet_runs, fleet_env, tmp_path):
    """The rollback twin of the promote catch-up: die AFTER the rollback
    verdict is durable but BEFORE the store rollback executes —
    ``_catch_up_gated`` must replay the recorded verdict on restart and
    converge to the uninterrupted drill."""
    from tdfo_tpu.utils.faults import KILL_EXIT_CODE

    spec = _make_spec(tmp_path, fleet_env, "killed", ckpt="ckpt", log="log",
                      faults={"regress_auc_at_cycle": 1,
                              "kill_between_stages": 7})
    rc, out = _run_worker(spec)
    assert rc == KILL_EXIT_CODE, f"rc={rc}\n{out[-2000:]}"
    rc, out = _run_worker(spec)
    assert rc == 0, f"resumed run failed rc={rc}\n{out[-2000:]}"
    resumed = json.loads((tmp_path / "killed.json").read_text())
    drill = fleet_runs["drill"]
    assert resumed["version"] == drill["version"]
    assert resumed["digest"] == drill["digest"]
    assert resumed["cursor"] == drill["cursor"]
    assert resumed["logits"] == drill["logits"]
    assert [(r["version"], r["digest"]) for r in resumed["rejections"]] == \
        [(r["version"], r["digest"]) for r in drill["rejections"]]


@pytest.mark.slow
@pytest.mark.parametrize("nth,expect", [
    (1, "rollback"),  # the only canary replica dies: no signal -> rollback
    (2, "promote"),   # a stable replica dies: stable AUC falls back to the
                      # shadow baseline and the healthy candidate promotes
], ids=["kill-canary-replica", "kill-stable-replica"])
def test_replica_death_mid_watch(fleet_env, tmp_path, nth, expect):
    """Replica death during the watch: losing the canary cohort forces a
    conservative rollback (no signal is not good signal); losing a stable
    replica must NOT block promotion of a healthy candidate."""
    spec = _make_spec(tmp_path, fleet_env, "rk", ckpt="ckpt", log="log",
                      faults={"kill_replica_nth": nth})
    rc, out = _run_worker(spec)
    assert rc == 0, f"rc={rc}\n{out[-2000:]}"
    res = json.loads((tmp_path / "rk.json").read_text())
    assert res["dead_replicas"] == [nth - 1]
    cycles = _events(tmp_path / "log" / "metrics.jsonl", "online_cycle")
    assert cycles and all(c["verdict"] == expect for c in cycles)
    if expect == "rollback":
        assert res["version"] == 0  # nothing ever promoted
        assert all(c["reason"] == "no alive canary replica" for c in cycles)
    else:
        assert res["version"] == N_CYCLES
        assert res["rejections"] == []
    # the dead replica serves nothing; survivors converge on the head
    assert str(nth - 1) not in res["replica_versions"]
    assert set(res["replica_versions"].values()) == {res["version"]}
