"""Benchmark harness — prints ONE JSON line with the headline metric.

Headline: CTR train-step throughput in the sparse/DMP regime (TwoTower by
default; ``--model dlrm`` for the BASELINE.json north-star family;
``--dense`` for the reference-parity dense regime), examples/sec/chip on the
real device, plus MFU, HBM utilisation vs the roofline floor, the 100M-row
big-table demo, and the embedding lookup latency microbench (gspmd vs
explicit psum vs all-to-all programs — the BASELINE.json metric family).

Measurement discipline — what the tunnelled TPU runtime actually does:

  * ``jax.block_until_ready`` does NOT wait for device execution through the
    tunnel (a 512 MB-traffic op "completes" in 0.05 ms), so any per-step
    wall-clock timing measures dispatch, not compute — the round-1 failure
    mode (42M examples/sec/chip, 6x beyond the memory roofline).
  * fetching a VALUE (device->host) is the only true sync, but costs a ~100 ms
    RPC round trip, swamping ms-scale steps.

  The honest recipe used here: compile a ``lax.scan`` chain of K steps into
  one executable, force completion with a scalar value fetch, and measure two
  chain lengths — ``step_time = (T(K2) - T(K1)) / (K2 - K1)`` cancels the
  constant RPC latency exactly.  Each rep feeds a fresh on-device batch stack
  so no two timed executions are identical (defeats result caching).

  An HBM-roofline sanity floor is computed from the optimizer's minimum
  memory traffic; the harness REFUSES to report a step time that beats the
  roofline (exit 1) instead of printing an impossible number.

``vs_baseline`` compares against ``BENCH_BASELINE.json`` (auto-written on
first accepted run; the reference publishes no numbers — BASELINE.md — so
the baseline is this framework's first honest measurement).
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

import numpy as np

# device_kind substring -> (peak bf16 TFLOP/s, HBM GB/s) per chip.
# Public spec-sheet numbers (v5e: 197 bf16 TFLOPs, 819 GB/s).
CHIP_SPECS = {
    "v5 lite": (197.0, 819.0),
    "v5e": (197.0, 819.0),
    "v5p": (459.0, 2765.0),
    "v6": (918.0, 1640.0),
    "v4": (275.0, 1228.0),
    "v3": (123.0, 900.0),
}
_DEFAULT_SPEC = (197.0, 819.0)

SIZE_MAP = {
    "user": 500_000, "item": 200_000, "language": 32, "is_ebook": 2,
    "format": 16, "publisher": 5_000, "pub_decade": 16,
}

# Criteo-Kaggle per-column vocabulary sizes (the standard 26-table profile
# used by the public DLRM benchmarks) — 33.76M embedding rows total, the
# BASELINE.json "DLRM-Criteo examples/sec/chip" workload.
CRITEO_KAGGLE_VOCABS = (
    1460, 583, 10131227, 2202608, 305, 24, 12517, 633, 3, 93145, 5683,
    8351593, 3194, 27, 14992, 5461306, 10, 5652, 2173, 4, 7046547, 18, 15,
    286181, 105, 142572,
)


def chip_peaks() -> tuple[float, float, bool]:
    """(peak bf16 TFLOP/s, HBM GB/s, spec_assumed).  ``spec_assumed`` is True
    when the device kind is unrecognised and the v5e fallback was used — MFU /
    HBM-utilisation numbers are then approximate and the record says so."""
    import jax

    kind = jax.devices()[0].device_kind.lower()
    for key, spec in CHIP_SPECS.items():
        if key in kind:
            return (*spec, False)
    print(
        f"bench: unrecognised device_kind {kind!r}; assuming v5e peaks "
        f"{_DEFAULT_SPEC} — MFU/HBM-utilisation and the roofline guard are "
        "approximate for this chip",
        file=sys.stderr,
    )
    return (*_DEFAULT_SPEC, True)


def _make_host_batch(rng: np.random.Generator, b: int) -> dict[str, np.ndarray]:
    return {
        "user_id": rng.integers(0, SIZE_MAP["user"], b, dtype=np.int32),
        "item_id": rng.integers(0, SIZE_MAP["item"], b, dtype=np.int32),
        "language": rng.integers(0, SIZE_MAP["language"], b, dtype=np.int32),
        "is_ebook": rng.integers(0, 2, b, dtype=np.int32),
        "format": rng.integers(0, SIZE_MAP["format"], b, dtype=np.int32),
        "publisher": rng.integers(0, SIZE_MAP["publisher"], b, dtype=np.int32),
        "pub_decade": rng.integers(0, SIZE_MAP["pub_decade"], b, dtype=np.int32),
        "avg_rating": rng.random(b, dtype=np.float32),
        "num_pages": rng.random(b, dtype=np.float32),
        "label": rng.integers(0, 2, b).astype(np.float32),
    }


def dense_flops_per_example(params) -> float:
    """Model FLOPs per example for a training step: 2*m*n per dense kernel
    forward, x3 for fwd + both backward matmuls (standard MFU accounting;
    embedding gathers contribute no matmul FLOPs)."""
    import jax

    fwd = 0.0
    for path, leaf in jax.tree_util.tree_leaves_with_path(params):
        name = "/".join(str(getattr(k, "key", k)) for k in path)
        if "kernel" in name and leaf.ndim == 2:
            fwd += 2.0 * leaf.shape[0] * leaf.shape[1]
    return 3.0 * fwd


def chain_time(run, make_args, ks: tuple[int, int] = (5, 45), reps: int = 3) -> float:
    """Per-step seconds via chain-length differencing.

    ``run(k)`` -> a compiled fn of ``make_args(k, seed)`` outputs returning a
    scalar; each timed call gets fresh args (unique execution) and is forced
    by the float() fetch.  Returns the median over per-rep differenced
    estimates — robust to tunnel-latency outliers.
    """
    k1, k2 = ks
    times: dict[int, list[float]] = {k1: [], k2: []}
    for k in (k1, k2):
        fn = run(k)
        warm = make_args(k, seed=k)
        float(fn(*warm))  # compile + warm (not timed)
        for rep in range(reps):
            args = make_args(k, seed=1000 + 10 * k + rep)
            t0 = time.perf_counter()
            float(fn(*args))
            times[k].append(time.perf_counter() - t0)
    diffs = sorted(
        (t2 - t1) / (k2 - k1) for t1, t2 in zip(times[k1], times[k2])
    )
    return diffs[len(diffs) // 2]


def _stack_batches(mesh, host: dict, k: int, b: int):
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    stack = {
        kk: jax.device_put(
            v.reshape(k, b, *v.shape[1:]),
            NamedSharding(mesh, P(None, "data")),
        )
        for kk, v in host.items()
    }
    # force EVERY leaf's host->device transfer to finish OUTSIDE the
    # timed window (transfer cost scales with k just like compute, so
    # the differencing would not cancel it)
    float(sum(jnp.sum(v.astype(jnp.float32)) for v in stack.values()))
    return stack


def build_train_bench(batch_size: int, embed_dim: int):
    """Dense regime (reference parity): nn.Embed tables + dense AdamW.

    Kept as the comparison path; the headline is the sparse/DMP regime below,
    whose optimizer traffic is O(batch) instead of O(vocab)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tdfo_tpu.core.config import MeshSpec
    from tdfo_tpu.core.mesh import make_mesh
    from tdfo_tpu.models.twotower import init_twotower
    from tdfo_tpu.train.state import TrainState, make_adamw
    from tdfo_tpu.train.step import make_train_step

    platform = jax.devices()[0].platform
    dtype = jnp.bfloat16 if platform != "cpu" else jnp.float32
    model, params = init_twotower(jax.random.key(0), SIZE_MAP, embed_dim, dtype=dtype)
    mesh = make_mesh(MeshSpec(data=-1, model=1, seq=1))
    state = jax.device_put(
        TrainState.create(apply_fn=model.apply, params=params, tx=make_adamw(3e-4, 1e-4)),
        NamedSharding(mesh, P()),
    )
    b = batch_size * mesh.shape["data"]

    # inner step WITHOUT donation: every chained execution must be free to
    # start from the same persistent state buffers.
    inner = make_train_step(mesh=mesh, donate_state=False)
    # unjitted twin for the one-off counters probe (a collector cannot see
    # through an inner jit boundary)
    probe_inner = make_train_step(mesh=mesh, donate_state=False, jit=False)

    def counters_probe(seed: int = 7) -> dict[str, float]:
        from tdfo_tpu.obs import counters as obs_counters

        @jax.jit
        def one(state, batch):
            with obs_counters.collect() as c:
                _, loss = probe_inner(state, batch)
            return loss, dict(c)

        host = _make_host_batch(np.random.default_rng(seed), b)
        stack = _stack_batches(mesh, host, 1, b)
        _, ctrs = one(state, {k: v[0] for k, v in stack.items()})
        return {k: round(float(v), 3) for k, v in ctrs.items()}

    def run(k):
        @jax.jit
        def chain(state, stack):
            final, losses = jax.lax.scan(lambda st, bt: inner(st, bt), state, stack)
            return losses[-1]

        return lambda stack: chain(state, stack)

    def make_args(k, seed):
        r = np.random.default_rng(seed)
        host = _make_host_batch(r, b * k)
        return (_stack_batches(mesh, host, k, b),)

    # roofline: dense AdamW must read+write params/mu/nu every step (6x param
    # bytes) — an irreducible HBM-traffic floor for this optimizer.  (Forward/
    # backward param reads and gradient traffic come on top; excluding them
    # keeps this a true lower bound.)
    param_bytes = sum(leaf.nbytes for leaf in jax.tree.leaves(state.params))
    floor_bytes = 6.0 * param_bytes
    flops_per_example = dense_flops_per_example(state.params)
    return run, make_args, b, floor_bytes, flops_per_example, counters_probe


# Why the sparse headline sits far above its BYTE-roofline floor: the floor
# prices touched-row traffic at full HBM bandwidth, but row-granular access
# on v5e is DESCRIPTOR- and SORT-RATE bound, not bandwidth bound.  Round-4
# ablation on the real chip (step ~1.17 ms total): fwd+bwd+dense-optax
# ~0.38 ms, the five one-hot small-table updates ~0.11 ms, and the STACKED
# fat-table group (user+item in one array, one launch) ~0.8 ms = ~0.24 ms
# dedupe (single-sort formulation, ops/sparse.py:dedupe_grads — the round-3
# figure was ~2 ms across two per-table jnp.unique + default-searchsorted
# dedupes) + ~0.57 ms for the in-place row-DMA kernel on ~16k touched rows
# x 2 directions.  The per-descriptor cost is the hardware floor for
# scattered single-row access on this chip generation (the dedicated
# SparseCore units on larger TPUs exist precisely for this); the byte floor
# is kept as the REFUSAL threshold because it is the only bound that is
# provably irreducible.


def _make_criteo_host_batch(rng: np.random.Generator, b: int,
                            powerlaw: bool = False) -> dict[str, np.ndarray]:
    if powerlaw:
        from tdfo_tpu.data.synthetic import zipf_ids

        out: dict[str, np.ndarray] = {
            f"cat_{i}": zipf_ids(rng, v, b)
            for i, v in enumerate(CRITEO_KAGGLE_VOCABS)
        }
    else:
        out = {
            f"cat_{i}": rng.integers(0, v, b, dtype=np.int32)
            for i, v in enumerate(CRITEO_KAGGLE_VOCABS)
        }
    for i in range(13):
        out[f"cont_{i}"] = rng.random(b, dtype=np.float32)
    out["label"] = rng.integers(0, 2, b).astype(np.float32)
    return out


def build_criteo_train_bench(batch_size: int, embed_dim: int,
                             hot_vocab: int = 0, powerlaw: bool = False,
                             fused_threshold: int | None = None):
    """DLRM over the Criteo-Kaggle table profile (26 tables, 33.76M rows):
    the BASELINE.json north-star metric measured directly.  Big tables live
    in ONE fused rowwise-adagrad fat-line stack (4 packed rows per 128-lane
    line; in-place DMA kernel update — no XLA scatter in the step), small
    tables in one plain 2D stack; dedup_lookup shares one sort between the
    forward gather and the update (fbgemm fused-TBE parity, the huge-table
    configuration: one f32 accumulator per row).

    ``hot_vocab > 0`` enables the frequency-partitioned hot/cold mode
    (``parallel/embedding.py``): every table's ``[0, min(hot_vocab, V))``
    prefix — the Criteo-ETL frequency-ranked layout — becomes a replicated
    hot head updated scatter-free via one-hot MXU contractions, and the
    batches switch to power-law (zipf-ranked) ids so the lookup traffic
    concentrates on the head like real Criteo traffic does.  ``powerlaw``
    alone keeps the single-table layout under the same skewed traffic —
    the honest ablation baseline.

    ``fused_threshold`` overrides the storage/update path for the big
    tables: ``None`` (default) keeps everything in plain 2D stacks — the
    measured-fastest layout for this profile — while a vocab threshold
    routes the tables above it into the fused rowwise-adagrad fat-line
    stack (the config-defaults build; the planner bench's "defaults" arm).
    """
    import jax
    import jax.numpy as jnp

    from tdfo_tpu.core.config import MeshSpec
    from tdfo_tpu.core.mesh import make_mesh
    from tdfo_tpu.models.dlrm import DLRMBackbone, generic_embedding_specs
    from tdfo_tpu.ops.sparse import sparse_optimizer
    from tdfo_tpu.parallel.embedding import ShardedEmbeddingCollection
    from tdfo_tpu.train.ctr import ctr_sparse_forward
    from tdfo_tpu.train.sparse_step import SparseTrainState, make_sparse_train_step

    platform = jax.devices()[0].platform
    dtype = jnp.bfloat16 if platform != "cpu" else jnp.float32
    mesh = make_mesh(MeshSpec(data=-1, model=1, seq=1))
    cats = tuple(f"cat_{i}" for i in range(26))
    conts = tuple(f"cont_{i}" for i in range(13))
    size_map = {c: v for c, v in zip(cats, CRITEO_KAGGLE_VOCABS)}
    # Plain stacked tables measured FASTER than fused fat-line storage for
    # this profile (22.5 vs ~29 ms/step): at ~100k scattered row-touches the
    # XLA row scatter (~10 ms at the deduped 101k-slot bound) beats the
    # per-line DMA kernel + its operand routing, while the fat layout's
    # 512B line granularity also taxes the forward gather.  The fused path
    # remains the right choice for memory-bound tables (optimizer state
    # packed in-line) and for small touch counts (twotower d=64 adam);
    # docs/BUDGET.md carries the full measured decomposition.
    powerlaw = powerlaw or hot_vocab > 0
    hot_ids = None
    if hot_vocab > 0:
        hot_ids = {c: np.arange(min(hot_vocab, v), dtype=np.int32)
                   for c, v in size_map.items()}
    coll = ShardedEmbeddingCollection(
        generic_embedding_specs(size_map, cats, embed_dim, "row",
                                fused_threshold=fused_threshold),
        mesh=mesh, stack_tables=True, fused_kind="rowwise_adagrad",
        hot_ids=hot_ids,
    )
    # shapes only — the real tables are built INSIDE the jitted chain (a
    # per-chain constant the differencing cancels): an 8.65 GB table passed
    # as a chain ARGUMENT would need disjoint input+output copies (~17 GB,
    # OOM); zeroed in-chain tables alias through the scan carry and row-RMW
    # timing is content-independent (cf. bench_big_table).
    table_shapes = jax.eval_shape(coll.init, jax.random.key(0))
    backbone = DLRMBackbone(embed_dim=embed_dim, dtype=dtype,
                            cat_columns=cats, cont_columns=conts)
    dummy_embs = {f: jnp.zeros((1, embed_dim), jnp.float32)
                  for f in coll.features()}
    dummy_cont = {c: jnp.zeros((1,)) for c in conts}
    import optax

    dense = backbone.init(jax.random.key(1), dummy_embs, dummy_cont)["params"]
    opt = sparse_optimizer("rowwise_adagrad", lr=3e-4)
    b = batch_size * mesh.shape["data"]
    inner = make_sparse_train_step(
        coll, ctr_sparse_forward(backbone), jit=False, donate=False,
        dedup_lookup=True,
    )

    def run(k):
        @jax.jit
        def chain(dense, stack):
            tables = {n: jnp.zeros(sh.shape, sh.dtype)
                      for n, sh in table_shapes.items()}
            state = SparseTrainState.create(
                dense_params=dense,
                tx=optax.adamw(3e-4, weight_decay=1e-4),
                tables=tables,
                sparse_opt=opt,
            )
            final, losses = jax.lax.scan(lambda st, bt: inner(st, bt), state, stack)
            return losses[-1]

        return lambda stack: chain(dense, stack)

    def counters_probe(seed: int = 7) -> dict[str, float]:
        # one counters-on step (telemetry registry riding the real step):
        # per-table touched/unique rows + grad/param norms in the record.
        # The TIMED chain above stays counters-off — byte-identical program.
        from tdfo_tpu.obs import counters as obs_counters

        @jax.jit
        def one(dense, batch):
            tables = {n: jnp.zeros(sh.shape, sh.dtype)
                      for n, sh in table_shapes.items()}
            state = SparseTrainState.create(
                dense_params=dense,
                tx=optax.adamw(3e-4, weight_decay=1e-4),
                tables=tables, sparse_opt=opt)
            with obs_counters.collect() as c:
                _, loss = inner(state, batch)
            return loss, dict(c)

        r = np.random.default_rng(seed)
        host = _make_criteo_host_batch(r, b, powerlaw=powerlaw)
        stack = _stack_batches(mesh, host, 1, b)
        _, ctrs = one(dense, {k: v[0] for k, v in stack.items()})
        return {k: round(float(v), 3) for k, v in ctrs.items()}

    unique_rows_per_step: list[float] = []
    hot_k = {c: coll.hot_count(f"{c}_embed") for c in cats}
    hot_info = {
        "enabled": hot_vocab > 0, "hot_vocab": hot_vocab,
        "powerlaw": powerlaw,
        "fully_hot_tables": sum(coll.hot_full(f"{c}_embed") for c in cats),
        "hit_rates": [],
    }

    def make_args(k, seed):
        r = np.random.default_rng(seed)
        host = _make_criteo_host_batch(r, b * k, powerlaw=powerlaw)
        ids = {c: host[c].reshape(k, b) for c in cats}
        for step in range(k):
            # COLD uniques only: hot hits never reach the scatter path, so
            # the roofline floor must not charge row traffic for them
            unique_rows_per_step.append(float(sum(
                len(np.unique(v[step][v[step] >= hot_k[c]]))
                for c, v in ids.items()
            )))
        if hot_vocab > 0:
            # lookup-mass fraction landing on the hot heads (power-law
            # traffic concentrates here — the number the split banks on)
            hits = sum(int((v < hot_k[c]).sum()) for c, v in ids.items())
            hot_info["hit_rates"].append(hits / (len(cats) * k * b))
        return (_stack_batches(mesh, host, k, b),)

    dense_bytes = sum(leaf.nbytes for leaf in jax.tree.leaves(dense))
    flops_per_example = dense_flops_per_example(dense)

    def floor_bytes_fn() -> float:
        # the fused update reads+writes packed 128-lane lines (table rows +
        # accumulator cells together); best case every touched row shares
        # its line fully -> w lanes x 4B x 2 directions per row.  Plus the
        # dense 6x AdamW sweep, and — in hot/cold mode — the hot heads'
        # dense masked RMW (whole [K, D] table + [K] rowwise accumulator,
        # read and write, every step).
        from tdfo_tpu.ops.pallas_kernels import line_layout

        lay = line_layout(embed_dim, "rowwise_adagrad")
        u_mean = float(np.mean(unique_rows_per_step)) if unique_rows_per_step else 0.0
        hot_bytes = sum(2.0 * 4.0 * (k_ * embed_dim + k_)
                        for k_ in hot_k.values())
        return 2.0 * u_mean * lay.w * 4.0 + 6.0 * dense_bytes + hot_bytes

    return (run, make_args, b, floor_bytes_fn, flops_per_example, hot_info,
            counters_probe)


def bench_planner_dlrm(batch_size: int, embed_dim: int, *,
                       on_tpu: bool,
                       headline_step_ms: float | None = None) -> dict:
    """Planner-chosen vs all-defaults placement on the DLRM-Criteo profile
    (the ``planner_dlrm8`` record).

    The auto-sharding planner (``tdfo_tpu/plan``) prices every per-table
    placement from the measured v5e cost table over the SAME uniform-id
    traffic this benchmark generates (uniform per-id counts -> occupancy
    uniques, exactly the ``_make_criteo_host_batch`` distribution).  The
    predicted numbers are pure host math and always present; the measured
    arms (chain-differenced like the headline) run on TPU only:

      * ``step_ms_default`` — what the config defaults build: fused
        fat-line storage for every table above the 16384-row threshold;
      * ``step_ms_chosen`` — the planner's placement.  On this profile the
        planner keeps the big tables PLAIN (docs/BUDGET.md: 22.4 vs
        29-32 ms measured), so when no big table chose fused the arm is the
        headline configuration and reuses its measurement instead of
        re-timing a byte-identical program (one TPU job at a time; a rerun
        would only add tunnel noise).

    Hot-head choices are priced into the prediction but NOT rebuilt in the
    measured arms — the storage/update-path decision is the arm under test;
    the hot-split payoff is measured separately (``--hot-vocab`` /
    ``record["hot_cold"]``).
    """
    import jax

    from tdfo_tpu.plan import plan_digest, plan_tables, table_stats_from_counts
    from tdfo_tpu.plan.planner import FUSED_MIN_VOCAB

    b = batch_size * max(1, jax.device_count())
    stats = {f"cat_{i}": table_stats_from_counts(np.ones(v, np.int64))
             for i, v in enumerate(CRITEO_KAGGLE_VOCABS)}
    plan = plan_tables(stats, dim=embed_dim, batch_size=b,
                       optimizer="rowwise_adagrad", dense_model="dlrm",
                       n_devices=1)
    tables = plan["tables"]
    rec = {
        "plan_digest": plan_digest(plan),
        "predicted_chosen_ms": plan["predicted_step_ms"],
        "predicted_default_ms": plan["predicted_default_ms"],
        "predicted_speedup": round(
            plan["predicted_default_ms"] / plan["predicted_step_ms"], 3),
        "fused_tables": int(sum(t["fused"] for t in tables.values())),
        "hot_tables": int(sum(t["hot_k"] > 0 for t in tables.values())),
        "bf16_tables": int(sum(t["dtype"] == "bfloat16"
                               for t in tables.values())),
    }
    if not on_tpu:
        return rec
    run_d, make_args_d, *_ = build_criteo_train_bench(
        batch_size, embed_dim, fused_threshold=FUSED_MIN_VOCAB)
    rec["step_ms_default"] = round(chain_time(run_d, make_args_d) * 1e3, 3)
    big_fused = any(t["vocab"] > FUSED_MIN_VOCAB and t["fused"]
                    for t in tables.values())
    if not big_fused and headline_step_ms is not None:
        rec["step_ms_chosen"] = round(headline_step_ms, 3)
        rec["chosen_is_headline"] = True
    else:
        run_c, make_args_c, *_ = build_criteo_train_bench(
            batch_size, embed_dim,
            fused_threshold=FUSED_MIN_VOCAB if big_fused else None)
        rec["step_ms_chosen"] = round(chain_time(run_c, make_args_c) * 1e3, 3)
    rec["measured_speedup"] = round(
        rec["step_ms_default"] / rec["step_ms_chosen"], 3)
    return rec


def build_sparse_train_bench(batch_size: int, embed_dim: int,
                             model: str = "twotower",
                             table_dtype: str = "float32"):
    """HEADLINE: the DMP regime — ShardedEmbeddingCollection + row-sparse
    in-backward Adam (``make_sparse_train_step``), the torchrec
    ``DistributedModelParallel`` + fused-optimizer equivalent.  ``model``
    picks the CTR head: "twotower" or "dlrm" (the BASELINE.json north-star
    family — feature-interaction head over the same 7 tables).

    Roofline floor recomputed for the sparse path: the optimizer only
    read-modify-writes the TOUCHED rows of table/mu/nu (6 x unique-rows x D x
    4B per table, measured from the actual benchmark batches) plus the dense
    tower params — per-step traffic is O(batch), not O(vocab), which is
    exactly the capability the dense path lacked (VERDICT r2 Missing #2).
    """
    import jax
    import jax.numpy as jnp

    from tdfo_tpu.core.config import MeshSpec
    from tdfo_tpu.core.mesh import make_mesh
    from tdfo_tpu.models.twotower import TwoTowerBackbone, ctr_embedding_specs
    from tdfo_tpu.ops.sparse import sparse_optimizer
    from tdfo_tpu.parallel.embedding import ShardedEmbeddingCollection
    from tdfo_tpu.train.ctr import ctr_sparse_forward
    from tdfo_tpu.train.sparse_step import SparseTrainState, make_sparse_train_step

    platform = jax.devices()[0].platform
    dtype = jnp.bfloat16 if platform != "cpu" else jnp.float32
    mesh = make_mesh(MeshSpec(data=-1, model=1, seq=1))
    specs = ctr_embedding_specs(SIZE_MAP, embed_dim, "row")
    if table_dtype != "float32":
        # quantized STORAGE (bf16/int8 tables + stochastic-rounding writes);
        # compute stays f32 either way, so the step program only differs by
        # the storage width and the SR key threading.  int8 rows carry a
        # per-row (scale, offset) sidecar and never ride fat lines, so the
        # int8 arm rebuilds the specs plain
        import dataclasses as _dc

        if table_dtype == "int8":
            specs = ctr_embedding_specs(SIZE_MAP, embed_dim, "row",
                                        fused_threshold=None)
        specs = [_dc.replace(s, dtype=jnp.dtype(table_dtype)) for s in specs]
    coll = ShardedEmbeddingCollection(specs, mesh=mesh)
    tables = coll.init(jax.random.key(0))
    table_bytes = int(sum(t.nbytes for t in tables.values()))
    if model == "dlrm":
        from tdfo_tpu.models.dlrm import DLRMBackbone

        backbone = DLRMBackbone(embed_dim=embed_dim, dtype=dtype)
    else:
        backbone = TwoTowerBackbone(embed_dim=embed_dim, dtype=dtype)
    dummy_embs = {f: jnp.zeros((1, embed_dim), jnp.float32) for f in coll.features()}
    dummy_cont = {"avg_rating": jnp.zeros((1,)), "num_pages": jnp.zeros((1,))}
    import optax

    dense = backbone.init(jax.random.key(1), dummy_embs, dummy_cont)["params"]
    state = SparseTrainState.create(
        dense_params=dense,
        tx=optax.adamw(3e-4, weight_decay=1e-4),
        tables=tables,
        sparse_opt=sparse_optimizer("adam", lr=3e-4, weight_decay=1e-4),
    )
    b = batch_size * mesh.shape["data"]
    # no dedup_lookup here: at ~8k touched rows/step the shared-sort
    # machinery costs more than it saves (measured 2.08 vs 1.3 ms/step);
    # dedup pays off at the Criteo profile's ~100k touches
    inner = make_sparse_train_step(
        coll, ctr_sparse_forward(backbone), jit=False, donate=False
    )

    def counters_probe(seed: int = 7) -> dict[str, float]:
        from tdfo_tpu.obs import counters as obs_counters

        @jax.jit
        def one(state, batch):
            with obs_counters.collect() as c:
                _, loss = inner(state, batch)
            return loss, dict(c)

        host = _make_host_batch(np.random.default_rng(seed), b)
        stack = _stack_batches(mesh, host, 1, b)
        _, ctrs = one(state, {k: v[0] for k, v in stack.items()})
        return {k: round(float(v), 3) for k, v in ctrs.items()}

    def run(k):
        @jax.jit
        def chain(state, stack):
            final, losses = jax.lax.scan(lambda st, bt: inner(st, bt), state, stack)
            return losses[-1]

        return lambda stack: chain(state, stack)

    unique_rows_per_step: list[float] = []

    def make_args(k, seed):
        r = np.random.default_rng(seed)
        host = _make_host_batch(r, b * k)
        # exact touched-row counts for the roofline floor, from the real data
        # (the id columns are exactly the features the collection serves)
        ids = {c: host[c].reshape(k, b) for c in coll.features()}
        for step in range(k):
            unique_rows_per_step.append(
                float(sum(len(np.unique(v[step])) for v in ids.values()))
            )
        return (_stack_batches(mesh, host, k, b),)

    dense_bytes = sum(leaf.nbytes for leaf in jax.tree.leaves(dense))
    flops_per_example = dense_flops_per_example(dense)

    t_item = jnp.dtype(table_dtype).itemsize

    def floor_bytes_fn() -> float:
        # sparse Adam read-modify-writes table/mu/nu rows for touched rows
        # only: table rows at the STORAGE dtype width (read + write), mu/nu
        # slots at f32 (4 passes), U measured per step above; dense params
        # still pay the full 6x dense AdamW sweep (they're tiny).
        u_mean = float(np.mean(unique_rows_per_step)) if unique_rows_per_step else 0.0
        per_row = 2.0 * t_item + 4.0 * 4.0
        return per_row * u_mean * embed_dim + 6.0 * dense_bytes

    return (run, make_args, b, floor_bytes_fn, flops_per_example, table_bytes,
            counters_probe)


def bench_embedding_lookup(batch_size: int = 8192, vocab: int = 2_000_000,
                           dim: int = 128) -> dict:
    """Median latency of the three embedding-lookup programs on the real mesh,
    measured by the same chain-differencing (a scan of dependent lookups).

    Single-chip caveat: on one chip the model axis has a single shard, so the
    collectives are degenerate — the number measures the lookup *program*
    (gather + bucketing/permute overhead), reported with ``n_shards`` so it
    is never mistaken for a multi-chip ICI measurement.  The multi-chip path
    is validated separately by the driver's ``dryrun_multichip``.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tdfo_tpu.core.config import MeshSpec
    from tdfo_tpu.core.mesh import make_mesh
    from tdfo_tpu.parallel.embedding import EmbeddingSpec, ShardedEmbeddingCollection

    mesh = make_mesh(MeshSpec(data=1, model=-1, seq=1))
    n_shards = mesh.shape["model"]
    coll = ShardedEmbeddingCollection(
        [EmbeddingSpec("table", vocab, dim, features=("ids",), sharding="row")],
        mesh=mesh,
    )
    tables = coll.init(jax.random.key(0))

    out: dict[str, object] = {}
    for mode in ("gspmd", "psum", "alltoall"):
        # feed each program the id sharding its shard_map declares: alltoall
        # wants ids sharded over the model axis (torchrec regime); psum wants
        # them replicated — a mismatched layout would time an artifact
        # resharding collective, not the lookup
        ids_spec = P(None, "model") if (mode == "alltoall" and n_shards > 1) else P()

        def run(k, mode=mode):
            @jax.jit
            def chain(tables, ids_stack):
                def body(carry, ids):
                    # fold the carry into the ids so each lookup depends on
                    # the previous one's result — scan can't overlap them
                    ids = (ids + carry.astype(jnp.int32)) % vocab
                    vecs = coll.lookup(tables, {"ids": ids}, mode=mode)["ids"]
                    return jnp.abs(vecs).sum().astype(jnp.float32) % 1024, None

                final, _ = jax.lax.scan(body, jnp.float32(0), ids_stack)
                return final

            return lambda stack: chain(tables, stack)

        def make_args(k, seed, ids_spec=ids_spec):
            r = np.random.default_rng(seed)
            ids = r.integers(0, vocab, (k, batch_size)).astype(np.int32)
            stack = jax.device_put(ids, NamedSharding(mesh, ids_spec))
            float(jnp.sum(stack))
            return (stack,)

        # us-scale ops need long chains so the signal (hundreds of chained
        # lookups) clears the few-ms tunnel-latency noise on each fetch
        sec = chain_time(run, make_args, ks=(64, 512), reps=3)
        out[mode] = round(sec * 1e6, 1)  # us

    # The grouped exchange's claim is per-TABLE collective elimination
    # (2 all_to_all per step regardless of table count vs 2 per table), so
    # its honest baseline is a MULTI-table spec: same total vocab split
    # over n_tables, per-table alltoall vs one grouped exchange.
    n_tables = 8
    tv = vocab // n_tables
    specs = [
        EmbeddingSpec(f"t{i}", tv, dim, features=(f"ids{i}",), sharding="row")
        for i in range(n_tables)
    ]
    for key, grouped in (("alltoall_per_table8", False),
                         ("alltoall_grouped8", True)):
        mcoll = ShardedEmbeddingCollection(specs, mesh=mesh,
                                           grouped_a2a=grouped)
        mtables = mcoll.init(jax.random.key(0))
        ids_spec = P(None, "model") if n_shards > 1 else P()

        def run(k, mcoll=mcoll, mtables=mtables):
            @jax.jit
            def chain(tables, ids_stack):
                def body(carry, feats):
                    feats = {f: (v + carry.astype(jnp.int32)) % tv
                             for f, v in feats.items()}
                    vecs = mcoll.lookup(tables, feats, mode="alltoall")
                    tot = sum(jnp.abs(v).sum() for v in vecs.values())
                    return tot.astype(jnp.float32) % 1024, None

                final, _ = jax.lax.scan(body, jnp.float32(0), ids_stack)
                return final

            return lambda stack: chain(mtables, stack)

        def make_args(k, seed, ids_spec=ids_spec):
            r = np.random.default_rng(seed)
            stack = {
                f"ids{i}": jax.device_put(
                    r.integers(0, tv, (k, batch_size)).astype(np.int32),
                    NamedSharding(mesh, ids_spec))
                for i in range(n_tables)
            }
            float(sum(jnp.sum(v) for v in stack.values()))
            return (stack,)

        sec = chain_time(run, make_args, ks=(64, 512), reps=3)
        out[key] = round(sec * 1e6, 1)  # us
    out["n_shards"] = n_shards
    out["shape"] = f"B{batch_size}xV{vocab}xD{dim}"
    return out


def bench_big_table(vocab_tiny: int = 2_000_000, vocab_small: int = 50_000_000,
                    vocab_big: int = 400_000_000, dim: int = 8,
                    batch: int = 8192, kind: str = "rowwise_adagrad",
                    include_tiny: bool = True) -> dict:
    """O(batch)-traffic demonstration: the row-sparse step's latency must not
    scale with the table's vocab.  The headline pair runs fbgemm's huge-table
    configuration — EXACT_ROWWISE_ADAGRAD, one f32 accumulator per row — at
    4x10^8 rows x dim 8: table 12.8 GB + accumulator 1.6 GB ~ 14.4 GB, the
    largest adaptive-optimizer table one 16 GB v5e holds (Adam's two full
    moments cap out near 1.3x10^8 rows; see ``adam_100m`` in the output).

    ``big_over_small`` compares 50M -> 400M rows (8x) — both DRAM-resident,
    so the ratio isolates vocab scaling (measured 0.98-1.2 across runs;
    chain-differencing noise straddles 1.0).  The 2M ``tiny`` point is
    reported separately: a 64 MB table enjoys on-chip locality and makes a
    naive tiny-vs-big ratio (~1.9-2.4x) read as vocab scaling when it is a
    cache effect.  A dense optimizer sweep would be 8x slower at each step
    of this ladder; the sparse path touches O(batch) rows throughout."""
    import jax
    import jax.numpy as jnp

    from tdfo_tpu.ops.sparse import sparse_optimizer

    opt = sparse_optimizer(kind, lr=1e-3)
    out: dict[str, object] = {"vocab_tiny": vocab_tiny,
                              "vocab_small": vocab_small,
                              "vocab_big": vocab_big,
                              "dim": dim, "batch": batch, "optimizer": kind}
    points = [("small", vocab_small), ("big", vocab_big)]
    if include_tiny:
        points.insert(0, ("tiny", vocab_tiny))
    for label, vocab in points:
        # table + moments are created INSIDE the jitted chain: a per-chain
        # constant that the chain-length differencing cancels, and — unlike a
        # passed-in argument — XLA keeps exactly one copy (donating loop-carry
        # arguments would invalidate them between reps; a 100M-row table + f32
        # moments is ~9.6 GB, so an argument copy OOMs a 16 GB chip).  The
        # table starts ZEROED: random init pays an RNG temp the size of the
        # table (OOMs the 14.4 GB rowwise config), and row-RMW timing is
        # content-independent — each rep still runs unique work because the
        # ids/grads args are fresh.
        def run(k, vocab=vocab):
            @jax.jit
            def chain(key, ids_stack, grads_stack):
                del key
                table = jnp.zeros((vocab, dim), jnp.float32)
                slots = opt.init(table)

                def body(carry, xs):
                    t, s = carry
                    ids, g = xs
                    t, s = opt.update(t, s, ids, g)
                    return (t, s), None

                (t, s), _ = jax.lax.scan(body, (table, slots), (ids_stack, grads_stack))
                return t[0].sum()  # force dependency; O(D) fetch

            return lambda key, ids, grads: chain(key, ids, grads)

        def make_args(k, seed, vocab=vocab):
            r = np.random.default_rng(seed)
            ids = jax.device_put(r.integers(0, vocab, (k, batch)).astype(np.int32))
            grads = jax.device_put(r.standard_normal((k, batch, dim), np.float32))
            float(jnp.sum(ids) + jnp.sum(grads))
            return (jax.random.key(seed), ids, grads)

        # long chains: the per-step signal must clear the tunnel-RPC noise
        sec = chain_time(run, make_args, ks=(32, 160), reps=3)
        out[f"step_ms_{label}"] = round(sec * 1e3, 4)
    if out["step_ms_small"] <= 0 or out["step_ms_big"] <= 0:
        # differencing lost to measurement noise; say so rather than report
        # a meaningless ratio
        out["invalid"] = True
        out["big_over_small"] = None
    else:
        out["big_over_small"] = round(out["step_ms_big"] / out["step_ms_small"], 3)
    return out


def _sim_cache_hit_rate(vocab: int, batch: int, cache_rows: int,
                        flush_every: int, steps: int = 192,
                        seed: int = 1234) -> tuple[float, int]:
    """Host-side replay of the update-cache directory policy (admit-all
    misses, retain the hottest C//2 by (freq desc, recency desc, id) at
    each flush, age retained frequencies //2 — ``ops/sparse.py``
    cache_flush) under the same zipf a=1.2 traffic the timed chains see.
    Returns ``(steady-state hit rate over the last half of the replay,
    peak directory occupancy)`` — the peak validates that ``cache_rows``
    really holds a flush interval's distinct ids (overflow means lost
    updates, which the trainer treats as a hard error)."""
    from tdfo_tpu.data.synthetic import zipf_ids

    r = np.random.default_rng(seed)
    keep_k = cache_rows // 2
    dir_ids = np.empty((0,), np.int64)
    freq: dict[int, int] = {}
    last: dict[int, int] = {}
    hits = total = peak = 0
    for step in range(steps):
        ids = zipf_ids(r, vocab, batch).astype(np.int64)
        u, cnt = np.unique(ids, return_counts=True)
        resident = np.isin(u, dir_ids)
        if step >= steps // 2:
            hits += int(cnt[resident].sum())
            total += batch
        dir_ids = np.union1d(dir_ids, u[~resident])
        for i in u.tolist():
            freq[i] = freq.get(i, 0) + 1
            last[i] = step
        peak = max(peak, len(dir_ids))
        if (step + 1) % flush_every == 0:
            retained = set(sorted(
                dir_ids.tolist(),
                key=lambda i: (-freq[i], -last[i], i))[:keep_k])
            # evicted entries lose their counters (re-admission resets
            # freq to 0, matching _cache_admit); retained ones age //2
            freq = {i: f // 2 for i, f in freq.items() if i in retained}
            last = {i: t for i, t in last.items() if i in retained}
            dir_ids = np.asarray(sorted(retained), np.int64)
    return hits / max(total, 1), peak


def bench_cache_zipf(vocab: int = 10_131_227, dim: int = 16,
                     batch: int = 8192, cache_rows: int = 131_072,
                     kind: str = "rowwise_adagrad",
                     flush_everies: tuple[int, ...] = (1, 8, 64),
                     ks: tuple[int, int] = (64, 192), reps: int = 3) -> dict:
    """Software MANAGED_CACHING amortization under power-law traffic: the
    cached step (directory route + cache-resident update; the big table is
    scattered into only on flush) vs the eager per-step dedupe + scatter,
    on the largest Criteo-Kaggle table (10.13M x 16, rowwise-adagrad) at
    zipf a=1.2 ids.  Emits the amortized ms/step at flush_every {1, 8, 64}
    — chain lengths are multiples of every interval, so each chain carries
    exactly k/flush_every coalesced flushes and the differencing amortizes
    them exactly — plus the host-simulated steady-state hit rate of the
    same retention policy.  flush_every=1 bounds the cache's overhead
    (route + admit + flush every step); the win case is 8/64 vs
    ``eager_ms``.  vs_eager > 1 = the cache wins."""
    import jax
    import jax.numpy as jnp

    from tdfo_tpu.data.synthetic import zipf_ids
    from tdfo_tpu.ops.sparse import sparse_optimizer

    opt = sparse_optimizer(kind, lr=1e-3)
    out: dict[str, object] = {"vocab": vocab, "dim": dim, "batch": batch,
                              "cache_rows": cache_rows, "optimizer": kind,
                              "zipf_a": 1.2}

    def make_args(k, seed):
        r = np.random.default_rng(seed)
        ids = jax.device_put(zipf_ids(r, vocab, (k, batch)))
        grads = jax.device_put(r.standard_normal((k, batch, dim), np.float32))
        float(jnp.sum(ids) + jnp.sum(grads))
        return (ids, grads)

    # eager baseline: the plain dedupe + XLA row-scatter step on the SAME
    # power-law traffic (uniform ids would overstate the cache's win)
    def run_eager(k):
        @jax.jit
        def chain(ids_stack, grads_stack):
            table = jnp.zeros((vocab, dim), jnp.float32)
            slots = opt.init(table)

            def body(carry, xs):
                t, s = carry
                ids, g = xs
                t, s = opt.update(t, s, ids, g)
                return (t, s), None

            (t, _), _ = jax.lax.scan(body, (table, slots),
                                     (ids_stack, grads_stack))
            return t[0].sum()

        return chain

    eager_sec = chain_time(run_eager, make_args, ks=ks, reps=reps)
    out["eager_ms"] = round(eager_sec * 1e3, 3)

    for fe in flush_everies:
        def run_cached(k, fe=fe):
            @jax.jit
            def chain(ids_stack, grads_stack):
                table = jnp.zeros((vocab, dim), jnp.float32)
                slots = opt.init(table)
                cache = opt.cache_init(table, cache_rows)

                def body(carry, xs):
                    t, s, c, step = carry
                    ids, g = xs
                    c, s = opt.cache_update(c, t, s, ids, g, step=step)

                    def flush(a):
                        c, t, s = a
                        c, t, s, _ = opt.cache_flush(c, t, s)
                        return c, t, s

                    c, t, s = jax.lax.cond((step + 1) % fe == 0, flush,
                                           lambda a: a, (c, t, s))
                    return (t, s, c, step + 1), None

                (t, _, c, _), _ = jax.lax.scan(
                    body, (table, slots, cache, jnp.int32(0)),
                    (ids_stack, grads_stack))
                # keep the table, the cache AND the overflow counter live
                return (t[0].sum() + c["rows"][0].sum()
                        + c["over"].astype(jnp.float32))

            return chain

        sec = chain_time(run_cached, make_args, ks=ks, reps=reps)
        hit, peak = _sim_cache_hit_rate(vocab, batch, cache_rows, fe)
        out[f"flush_every_{fe}"] = {
            "step_ms": round(sec * 1e3, 3),
            "hit_rate": round(hit, 4),
            "sim_peak_dir": peak,
            "would_overflow": peak > cache_rows,
            "vs_eager": round(eager_sec / max(sec, 1e-9), 3),  # >1 = cache wins
        }
    return out


def bench_cache_int8_zipf(vocab: int = 10_131_227, dim: int = 16,
                          batch: int = 8192, cache_rows: int = 131_072,
                          kind: str = "rowwise_adagrad",
                          flush_everies: tuple[int, ...] = (1, 64),
                          ks: tuple[int, int] = (64, 192),
                          reps: int = 3) -> dict:
    """:func:`bench_cache_zipf` on int8 STORAGE (the PR-18 composition the
    planner picks for Criteo under tight HBM): the table is 1-byte codes +
    the f32 [V, 2] (scale, offset) sidecar, cache rows mirror codes + grid,
    every cached write requantizes per row through ``quantize_rows`` with
    the eager path's SR key, and flush stays a bit-copy (codes scatter +
    one sidecar scatter).  The eager baseline is the plain-int8 dedupe +
    requantize-scatter step on the SAME power-law traffic.  vs_eager > 1 =
    the cache wins; non-flush steps never touch the [V, d] or [V, 2]
    arrays, so the win grows with flush_every exactly as in the f32
    record."""
    import jax
    import jax.numpy as jnp

    from tdfo_tpu.data.synthetic import zipf_ids
    from tdfo_tpu.ops.quant import sr_key as make_sr_key
    from tdfo_tpu.ops.sparse import sparse_optimizer

    opt = sparse_optimizer(kind, lr=1e-3)
    out: dict[str, object] = {"vocab": vocab, "dim": dim, "batch": batch,
                              "cache_rows": cache_rows, "optimizer": kind,
                              "table_dtype": "int8", "zipf_a": 1.2}

    def make_args(k, seed):
        r = np.random.default_rng(seed)
        ids = jax.device_put(zipf_ids(r, vocab, (k, batch)))
        grads = jax.device_put(r.standard_normal((k, batch, dim), np.float32))
        float(jnp.sum(ids) + jnp.sum(grads))
        return (ids, grads)

    def init_int8():
        codes = jnp.zeros((vocab, dim), jnp.int8)
        # unit grid: dequantize(0) == 0.0, matching the f32 record's zero
        # init; training writes re-grid touched rows per row as usual
        qs = jnp.tile(jnp.asarray([1.0, 0.0], jnp.float32), (vocab, 1))
        return codes, qs

    def run_eager(k):
        @jax.jit
        def chain(ids_stack, grads_stack):
            table, qs = init_int8()
            slots = opt.init(table)

            def body(carry, xs):
                t, s, q, step = carry
                ids, g = xs
                t, s, q = opt.update(
                    t, s, ids, g, qscale=q,
                    sr_key=make_sr_key(step, "bench_cache_int8"))
                return (t, s, q, step + 1), None

            (t, _, q, _), _ = jax.lax.scan(
                body, (table, slots, qs, jnp.int32(0)),
                (ids_stack, grads_stack))
            return (t[0].astype(jnp.float32) * q[0, 0] + q[0, 1]).sum()

        return chain

    eager_sec = chain_time(run_eager, make_args, ks=ks, reps=reps)
    out["eager_ms"] = round(eager_sec * 1e3, 3)

    for fe in flush_everies:
        def run_cached(k, fe=fe):
            @jax.jit
            def chain(ids_stack, grads_stack):
                table, qs = init_int8()
                slots = opt.init(table)
                cache = opt.cache_init(table, cache_rows)

                def body(carry, xs):
                    t, s, q, c, step = carry
                    ids, g = xs
                    c, s = opt.cache_update(
                        c, t, s, ids, g, step=step, qscale=q,
                        sr_key=make_sr_key(step, "bench_cache_int8"))

                    def flush(a):
                        c, t, s, q = a
                        c, t, s, q, _ = opt.cache_flush(c, t, s, q)
                        return c, t, s, q

                    c, t, s, q = jax.lax.cond(
                        (step + 1) % fe == 0, flush, lambda a: a,
                        (c, t, s, q))
                    return (t, s, q, c, step + 1), None

                (t, _, q, c, _), _ = jax.lax.scan(
                    body,
                    (table, slots, qs, cache, jnp.int32(0)),
                    (ids_stack, grads_stack))
                return ((t[0].astype(jnp.float32) * q[0, 0] + q[0, 1]).sum()
                        + c["rows"][0].astype(jnp.float32).sum()
                        + c["over"].astype(jnp.float32))

            return chain

        sec = chain_time(run_cached, make_args, ks=ks, reps=reps)
        hit, peak = _sim_cache_hit_rate(vocab, batch, cache_rows, fe)
        out[f"flush_every_{fe}"] = {
            "step_ms": round(sec * 1e3, 3),
            "hit_rate": round(hit, 4),
            "sim_peak_dir": peak,
            "would_overflow": peak > cache_rows,
            "vs_eager": round(eager_sec / max(sec, 1e-9), 3),  # >1 = cache wins
        }
    return out


def bench_quant_int8_fused(vocab: int = 2_000_000, dim: int = 64,
                           batch: int = 8192, kind: str = "adam",
                           ks: tuple[int, int] = (16, 64),
                           reps: int = 3) -> dict:
    """The other PR-18 composition: fused int8 byte-container fat lines
    (codes + bitcast (scale, offset) sidecar + f32 optimizer state in ONE
    line) vs the plain-int8 dedupe + requantize-scatter step, full update
    chain at the wide-row profile where the fat line wins on BOTH axes
    (d=64 adam: 640 B/row fused vs 1160 plain, one DMA stream vs three
    scatters + a sidecar scatter).  vs_plain > 1 = fused wins.  The two
    trajectories are bit-identical by construction (tests pin it); this
    record prices the layout choice the planner makes."""
    import jax
    import jax.numpy as jnp

    from tdfo_tpu.ops.pallas_kernels import fat_pack
    from tdfo_tpu.ops.quant import quantize_rows, sr_key as make_sr_key
    from tdfo_tpu.ops.sparse import sparse_optimizer
    from tdfo_tpu.plan.costs import table_hbm_bytes

    opt = sparse_optimizer(kind, lr=1e-2, small_vocab_threshold=0)
    out: dict[str, object] = {
        "vocab": vocab, "dim": dim, "batch": batch, "optimizer": kind,
        "hbm_bytes_fused": table_hbm_bytes(vocab, dim, optimizer=kind,
                                           dtype="int8", fused=True),
        "hbm_bytes_plain": table_hbm_bytes(vocab, dim, optimizer=kind,
                                           dtype="int8", fused=False),
    }

    def make_args(k, seed):
        r = np.random.default_rng(seed)
        ids = jax.device_put(r.integers(0, vocab, (k, batch)).astype(np.int32))
        grads = jax.device_put(
            r.standard_normal((k, batch, dim), np.float32))
        float(jnp.sum(ids) + jnp.sum(grads))
        return (jax.random.key(seed), ids, grads)

    def run_fused(k):
        @jax.jit
        def chain(key, ids_stack, grads_stack):
            fat = fat_pack(jax.random.uniform(key, (vocab, dim)),
                           dtype=jnp.int8, kind=kind)
            slots = opt.init(fat)

            def body(carry, xs):
                t, s, step = carry
                ids, g = xs
                t, s = opt.update(t, s, ids, g, embedding_dim=dim,
                                  sr_key=make_sr_key(step, "bench_qfused"))
                return (t, s, step + 1), None

            (t, _, _), _ = jax.lax.scan(body, (fat, slots, jnp.int32(0)),
                                        (ids_stack, grads_stack))
            return t[0, 0, :dim].astype(jnp.float32).sum()

        return chain

    def run_plain(k):
        @jax.jit
        def chain(key, ids_stack, grads_stack):
            codes, qs = quantize_rows(jax.random.uniform(key, (vocab, dim)))
            slots = opt.init(codes)

            def body(carry, xs):
                t, s, q, step = carry
                ids, g = xs
                t, s, q = opt.update(t, s, ids, g, qscale=q,
                                     sr_key=make_sr_key(step, "bench_qfused"))
                return (t, s, q, step + 1), None

            (t, _, q, _), _ = jax.lax.scan(
                body, (codes, slots, qs, jnp.int32(0)),
                (ids_stack, grads_stack))
            return (t[0].astype(jnp.float32) * q[0, 0] + q[0, 1]).sum()

        return chain

    fused_sec = chain_time(run_fused, make_args, ks=ks, reps=reps)
    plain_sec = chain_time(run_plain, make_args, ks=ks, reps=reps)
    out["fused_ms"] = round(fused_sec * 1e3, 3)
    out["plain_ms"] = round(plain_sec * 1e3, 3)
    out["vs_plain"] = round(plain_sec / max(fused_sec, 1e-9), 3)
    return out


def bench_serving(batch_size: int = 8192, embed_dim: int = 64,
                  top_k: int = 100) -> dict:
    """Serving-path latency: the frontend's jitted scoring program at its
    largest bucket and the exact-retrieval program, timed by the same
    chain differencing as the train benches (CLAUDE.md tunnel rules:
    ``block_until_ready`` does not wait through the tunnel; only value
    fetches sync, and the constant ~100 ms RPC cancels in the K2-K1
    difference).

    ``serve_score8`` / ``serve_retrieve8``: per-batch latency at B=8192
    plus the derived throughput (scored rows/sec; retrieval queries/sec
    against the full 200k-item corpus at ``top_k``).  Both programs take
    tables/corpus as chain ARGUMENTS — never closures (compile payload).
    """
    import tempfile

    import jax
    import jax.numpy as jnp

    from tdfo_tpu.core.config import MeshSpec
    from tdfo_tpu.core.mesh import make_mesh
    from tdfo_tpu.models.twotower import TwoTowerBackbone, ctr_embedding_specs
    from tdfo_tpu.ops.sparse import sparse_optimizer
    from tdfo_tpu.parallel.embedding import ShardedEmbeddingCollection
    from tdfo_tpu.serve.corpus import build_corpus, synthetic_item_features
    from tdfo_tpu.serve.export import export_bundle, load_bundle
    from tdfo_tpu.serve.retrieval import make_retrieval
    from tdfo_tpu.serve.scoring import make_scorer
    from tdfo_tpu.train.sparse_step import SparseTrainState

    import optax

    mesh = make_mesh(MeshSpec(data=-1, model=1, seq=1))
    coll = ShardedEmbeddingCollection(
        ctr_embedding_specs(SIZE_MAP, embed_dim, "row"), mesh=mesh)
    backbone = TwoTowerBackbone(embed_dim=embed_dim)
    dummy_e = {f: jnp.zeros((1, embed_dim), jnp.float32) for f in coll.features()}
    dummy_c = {"avg_rating": jnp.zeros((1,)), "num_pages": jnp.zeros((1,))}
    state = SparseTrainState.create(
        dense_params=backbone.init(jax.random.key(1), dummy_e, dummy_c)["params"],
        tx=optax.adamw(3e-4), tables=coll.init(jax.random.key(0)),
        sparse_opt=sparse_optimizer("adam", lr=3e-4),
    )
    with tempfile.TemporaryDirectory() as td:
        bundle = load_bundle(export_bundle(
            td + "/bundle", model="twotower", embed_dim=embed_dim,
            cat_columns=("user_id", "item_id", "language", "is_ebook",
                         "format", "publisher", "pub_decade"),
            cont_columns=("avg_rating", "num_pages"), size_map=SIZE_MAP,
            coll=coll, tables=state.tables, dense_params=state.dense_params))
    scorer = make_scorer(bundle, mesh=mesh)
    corpus_items = SIZE_MAP["item"]
    out: dict[str, object] = {"batch": batch_size, "top_k": top_k,
                              "corpus_items": corpus_items,
                              "embed_dim": embed_dim}

    # scoring chain: each scanned batch folds the carry into its ids so no
    # two scored batches are identical (defeats result caching)
    s_tables, s_dense = scorer._params

    def run_score(k):
        @jax.jit
        def chain(tables, dense, stack):
            def body(carry, batch):
                batch = dict(batch)
                batch["user_id"] = (batch["user_id"] + carry) % SIZE_MAP["user"]
                logits = scorer._score(batch, tables, dense)
                return jnp.abs(logits).sum().astype(jnp.int32) % 128, None

            final, _ = jax.lax.scan(body, jnp.int32(0), stack)
            return final

        return lambda stack: chain(s_tables, s_dense, stack)

    def make_score_args(k, seed):
        r = np.random.default_rng(seed)
        host = _make_host_batch(r, batch_size * k)
        host.pop("label")
        return (_stack_batches(mesh, host, k, batch_size),)

    sec = chain_time(run_score, make_score_args, ks=(16, 128), reps=3)
    out["serve_score8"] = {
        "batch_ms": round(sec * 1e3, 3),
        "rows_per_sec": round(batch_size / sec, 1),
    }

    corpus = build_corpus(
        scorer, synthetic_item_features(SIZE_MAP, corpus_items, seed=0),
        corpus_batch=8192, mesh=mesh)
    retrieve = make_retrieval(corpus, mesh=mesh, top_k=top_k)

    def run_retrieve(k):
        @jax.jit
        def chain(vectors, ids, qstack):
            def body(carry, q):
                s, _ = retrieve.jitted(q + carry, vectors, ids)
                return jnp.abs(s).sum() * jnp.float32(1e-9), None

            final, _ = jax.lax.scan(body, jnp.float32(0), qstack)
            return final

        return lambda qstack: chain(corpus.vectors, corpus.ids, qstack)

    def make_retrieve_args(k, seed):
        import jax

        r = np.random.default_rng(seed)
        q = jax.device_put(
            r.standard_normal((k, batch_size, embed_dim)).astype(np.float32))
        float(jnp.sum(q))
        return (q,)

    sec = chain_time(run_retrieve, make_retrieve_args, ks=(16, 128), reps=3)
    out["serve_retrieve8"] = {
        "batch_ms": round(sec * 1e3, 3),
        "queries_per_sec": round(batch_size / sec, 1),
    }
    return out


def bench_serve_seq(batch_size: int = 8192, n_items: int = 200_000,
                    max_len: int = 64, embed_dim: int = 64,
                    top_k: int = 100) -> dict:
    """``serve_seq8``: the SEQUENCE serving family's latency twins of
    ``serve_score8``/``serve_retrieve8`` — masked-position candidate
    scoring (history window in, appended-MASK logits over the 101-wide
    eval panel out) and next-item MIPS against the bias-folded output-head
    corpus (``serve/seq_scoring.py:item_corpus``, rows ``[W_out[:,v]; b_v]``
    so retrieval ranks exactly like the served logits).
    Timed by the same chain differencing as every other record (CLAUDE.md
    tunnel rules); each scanned batch folds the carry into its history ids
    so no two scored batches are identical (defeats result caching), and
    tables ride as chain ARGUMENTS, never closures (compile payload)."""
    import tempfile

    import jax
    import jax.numpy as jnp

    from tdfo_tpu.core.config import MeshSpec
    from tdfo_tpu.core.mesh import make_mesh
    from tdfo_tpu.data.seq_preprocessing import EVAL_NEG_NUM
    from tdfo_tpu.models.bert4rec import Bert4RecConfig, make_sharded_bert4rec
    from tdfo_tpu.serve.export import export_bundle, load_bundle
    from tdfo_tpu.serve.retrieval import make_retrieval
    from tdfo_tpu.serve.seq_scoring import item_corpus, make_seq_scorer

    mesh = make_mesh(MeshSpec(data=-1, model=1, seq=1))
    cfg = Bert4RecConfig(n_items=n_items, max_len=max_len,
                         embed_dim=embed_dim, n_heads=2, n_layers=2)
    coll, tables, backbone, dense = make_sharded_bert4rec(
        jax.random.key(0), cfg, mesh, sharding="row", fused_threshold=None)
    with tempfile.TemporaryDirectory() as td:
        bundle = load_bundle(export_bundle(
            td + "/bundle", model="bert4rec", embed_dim=embed_dim,
            cat_columns=(), cont_columns=(),
            size_map={"n_items": n_items}, coll=coll, tables=tables,
            dense_params=dense,
            seq={"max_len": max_len, "n_heads": cfg.n_heads,
                 "n_layers": cfg.n_layers}))
    scorer = make_seq_scorer(bundle, mesh=mesh)
    n_cands = EVAL_NEG_NUM + 1
    out: dict[str, object] = {"batch": batch_size, "n_items": n_items,
                              "max_len": max_len, "n_cands": n_cands,
                              "embed_dim": embed_dim, "top_k": top_k}
    s_tables, s_dense = scorer._params

    def _roll(batch, carry):
        # fresh valid item ids every scanned step; the window keeps its
        # appended-MASK last position so the scored program is the real one
        batch = dict(batch)
        seqs = (batch["seqs"] + carry) % n_items + 1
        batch["seqs"] = seqs.at[:, -1].set(scorer.mask_id)
        return batch

    def run_score(k):
        @jax.jit
        def chain(tables, dense, stack):
            def body(carry, batch):
                logits = scorer._score(_roll(batch, carry), tables, dense)
                return jnp.abs(logits).sum().astype(jnp.int32) % 128, None

            final, _ = jax.lax.scan(body, jnp.int32(0), stack)
            return final

        return lambda stack: chain(s_tables, s_dense, stack)

    def _make_host_panels(r, rows):
        return {
            "seqs": np.concatenate(
                [r.integers(1, n_items + 1, size=(rows, max_len - 1)),
                 np.full((rows, 1), n_items + 1)], axis=1).astype(np.int32),
            "cands": r.integers(1, n_items + 1,
                                size=(rows, n_cands)).astype(np.int32),
        }

    def make_score_args(k, seed):
        r = np.random.default_rng(seed)
        host = _make_host_panels(r, batch_size * k)
        return (_stack_batches(mesh, host, k, batch_size),)

    sec = chain_time(run_score, make_score_args, ks=(16, 128), reps=3)
    out["serve_seq_score8"] = {
        "batch_ms": round(sec * 1e3, 3),
        "rows_per_sec": round(batch_size / sec, 1),
    }

    # next-item retrieval: the output head IS the corpus (bias folded into
    # a d+1th column) — queries are [h, 1] last-position hidden states,
    # here synthesized at the right shape (query_embed cost is part of the
    # score record above)
    corpus = item_corpus(bundle, mesh=mesh)
    retrieve = make_retrieval(corpus, mesh=mesh, top_k=top_k)

    def run_retrieve(k):
        @jax.jit
        def chain(vectors, ids, qstack):
            def body(carry, q):
                s, _ = retrieve.jitted(q + carry, vectors, ids)
                return jnp.abs(s).sum() * jnp.float32(1e-9), None

            final, _ = jax.lax.scan(body, jnp.float32(0), qstack)
            return final

        return lambda qstack: chain(corpus.vectors, corpus.ids, qstack)

    def make_retrieve_args(k, seed):
        r = np.random.default_rng(seed)
        # query width d+1: [h, 1] against the bias-folded head corpus
        q = jax.device_put(
            r.standard_normal(
                (k, batch_size, embed_dim + 1)).astype(np.float32))
        float(jnp.sum(q))
        return (q,)

    sec = chain_time(run_retrieve, make_retrieve_args, ks=(16, 128), reps=3)
    out["serve_seq_retrieve8"] = {
        "batch_ms": round(sec * 1e3, 3),
        "queries_per_sec": round(batch_size / sec, 1),
    }
    return out


def bench_serve_fleet(replicas: int = 2, embed_dim: int = 16,
                      requests_per_step: int = 128, knee_steps: int = 3,
                      p99_slo_ms: float = 50.0) -> dict:
    """``serve_fleet8``: sustained QPS per replica at a fixed p99 SLO
    through the out-of-process serving stack (socket ingress -> replica
    processes, ``tdfo_tpu/serve/supervisor.py``).

    This measures the HOST serving stack — framing, balancing, process
    hops, micro-batching — not the chip: replica children always run
    ``JAX_PLATFORMS=cpu`` (one TPU job at a time through the tunnel,
    CLAUDE.md), so the record is meaningful on and off TPU and carries no
    ``on_tpu`` gate.  A closed-loop zipf sweep doubles concurrency per
    step; the knee is the last step whose p99 met the SLO.
    """
    import tempfile

    import jax

    from tdfo_tpu.core.config import Config, LoadgenSpec, ServingSpec
    from tdfo_tpu.models.twotower import TwoTowerBackbone, ctr_embedding_specs
    from tdfo_tpu.ops.sparse import sparse_optimizer
    from tdfo_tpu.parallel.embedding import ShardedEmbeddingCollection
    from tdfo_tpu.serve.export import export_bundle
    from tdfo_tpu.serve.loadgen import LoadGenerator
    from tdfo_tpu.serve.supervisor import ProcessFleet
    from tdfo_tpu.serve.swap import BundleStore
    from tdfo_tpu.train.sparse_step import SparseTrainState

    import jax.numpy as jnp
    import optax

    from tdfo_tpu.core.config import MeshSpec
    from tdfo_tpu.core.mesh import make_mesh

    mesh = make_mesh(MeshSpec(data=-1, model=1, seq=1))
    coll = ShardedEmbeddingCollection(
        ctr_embedding_specs(SIZE_MAP, embed_dim, "row"), mesh=mesh)
    backbone = TwoTowerBackbone(embed_dim=embed_dim)
    dummy_e = {f: jnp.zeros((1, embed_dim), jnp.float32)
               for f in coll.features()}
    dummy_c = {"avg_rating": jnp.zeros((1,)), "num_pages": jnp.zeros((1,))}
    state = SparseTrainState.create(
        dense_params=backbone.init(jax.random.key(1), dummy_e,
                                   dummy_c)["params"],
        tx=optax.adamw(3e-4), tables=coll.init(jax.random.key(0)),
        sparse_opt=sparse_optimizer("adam", lr=3e-4),
    )
    vocab = {"user_id": SIZE_MAP["user"], "item_id": SIZE_MAP["item"],
             "language": SIZE_MAP["language"], "is_ebook": 2,
             "format": SIZE_MAP["format"],
             "publisher": SIZE_MAP["publisher"],
             "pub_decade": SIZE_MAP["pub_decade"]}
    with tempfile.TemporaryDirectory() as td:
        bundle_dir = export_bundle(
            td + "/bundle", model="twotower", embed_dim=embed_dim,
            cat_columns=tuple(vocab), cont_columns=("avg_rating",
                                                    "num_pages"),
            size_map=SIZE_MAP, coll=coll, tables=state.tables,
            dense_params=state.dense_params)
        store = BundleStore(td + "/store")
        if store.recover() is None:
            store.ingest_full(bundle_dir)
        cfg = Config().replace(
            serving=ServingSpec(replicas=replicas, fleet_mode="process"),
            loadgen=LoadgenSpec(mode="closed", requests=requests_per_step,
                                rows_per_request=16, p99_slo_ms=p99_slo_ms))
        fleet = ProcessFleet(store, cfg, workdir=td)
        try:
            fleet.sync()
            gen = LoadGenerator(fleet.ingress, cfg.loadgen, vocab,
                                ("avg_rating", "num_pages"))
            report = gen.knee(steps=knee_steps)
        finally:
            fleet.close()
    knee = report["knee"]
    out = {
        "replicas": replicas,
        "p99_slo_ms": p99_slo_ms,
        "steps": [{k: s[k] for k in ("concurrency", "achieved_qps",
                                     "p50_ms", "p99_ms", "shed", "failed",
                                     "slo_ok")}
                  for s in report["steps"]],
    }
    if knee is not None:
        out["knee_qps"] = round(knee["achieved_qps"], 1)
        out["qps_per_replica"] = round(knee["achieved_qps"] / replicas, 1)
        out["knee_p99_ms"] = knee["p99_ms"]
    return out


def bench_retrieval_scale(n_items_list=(1_000_000, 10_000_000),
                          dim: int = 64, batch: int = 256,
                          top_k: int = 100) -> dict:
    """``retrieve_twostage8``: exact f32 scan vs the int8 two-stage program
    (coarse ``4 * top_k`` over stored codes, exact re-rank of survivors) at
    corpus scales where the split starts to matter.  Synthetic corpora are
    drawn ON DEVICE (retrieval cost depends only on geometry, and a 10M x
    64 f32 host array would crawl through the tunnel); both programs take
    the corpus as chain ARGUMENTS, timed by the same chain differencing as
    every other record (CLAUDE.md tunnel rules).  Recall@k of the two-stage
    answer is measured against the exact scan of the SAME int8 corpus —
    the exact program is the bitwise-verified reference stand-in
    (tests/test_serve.py).  Expected-budget fallback when the tunnel is
    unreachable: docs/BUDGET.md "int8 corpora and two-stage retrieval"."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tdfo_tpu.core.config import MeshSpec
    from tdfo_tpu.core.mesh import make_mesh
    from tdfo_tpu.ops.quant import quantize_rows
    from tdfo_tpu.serve.corpus import Corpus
    from tdfo_tpu.serve.retrieval import make_retrieval

    mesh = make_mesh(MeshSpec(data=-1, model=1, seq=1))
    n_shards = mesh.shape["data"]
    out: dict[str, object] = {"dim": dim, "batch": batch, "top_k": top_k}

    for n_items in n_items_list:
        n_pad = -(-n_items // n_shards) * n_shards
        sharding = NamedSharding(mesh, P("data", None))
        draw = jax.jit(
            lambda key: jax.random.normal(key, (n_pad, dim), jnp.float32),
            out_shardings=sharding)
        vectors = draw(jax.random.key(n_items))
        codes, qscale = jax.jit(quantize_rows, out_shardings=(
            sharding, sharding))(vectors)
        ids = jax.device_put(
            jnp.where(jnp.arange(n_pad) < n_items,
                      jnp.arange(n_pad, dtype=jnp.int32), -1),
            NamedSharding(mesh, P("data")))
        f32 = Corpus(vectors=vectors, ids=ids, n_items=n_items)
        i8 = Corpus(vectors=codes, ids=ids, n_items=n_items, qscale=qscale)
        exact = make_retrieval(f32, mesh=mesh, top_k=top_k)
        exact8 = make_retrieval(i8, mesh=mesh, top_k=top_k)
        two = make_retrieval(i8, mesh=mesh, top_k=top_k,
                             coarse_k=4 * top_k)

        def make_qargs(k, seed):
            r = np.random.default_rng(seed)
            q = jax.device_put(
                r.standard_normal((k, batch, dim)).astype(np.float32))
            float(jnp.sum(q))
            return (q,)

        def timed(jitted, operands):
            def run(k):
                @jax.jit
                def chain(qstack, *ops):
                    def body(carry, q):
                        s, _ = jitted(q + carry, *ops)
                        return jnp.abs(s).sum() * jnp.float32(1e-9), None

                    final, _ = jax.lax.scan(body, jnp.float32(0), qstack)
                    return final

                return lambda qstack: chain(qstack, *operands)

            return chain_time(run, make_qargs, ks=(8, 64), reps=3)

        sec_exact = timed(exact.jitted, (f32.vectors, f32.ids))
        sec_two = timed(two.jitted, (i8.vectors, i8.qscale, i8.ids))

        r = np.random.default_rng(1)
        q = jnp.asarray(r.standard_normal((batch, dim)), jnp.float32)
        _, i_ref = exact8(q)
        _, i_two = two(q)
        hits = sum(len(set(a.tolist()) & set(b.tolist()))
                   for a, b in zip(np.asarray(i_two), np.asarray(i_ref)))
        out[f"n{n_items // 1_000_000}m"] = {
            "exact_f32_ms": round(sec_exact * 1e3, 3),
            "twostage_int8_ms": round(sec_two * 1e3, 3),
            "speedup": round(sec_exact / sec_two, 2),
            "recall_at_k": round(hits / np.asarray(i_ref).size, 4),
            "corpus_bytes_f32": int(f32.vectors.nbytes),
            "corpus_bytes_int8": int(i8.vectors.nbytes + i8.qscale.nbytes),
        }
    return out


def bench_trace_overhead(run, make_args, ks=(5, 45), reps: int = 3) -> dict:
    """``trace_overhead``: the headline step chain re-timed with
    ``[telemetry] trace = true`` live (sinks in a throwaway dir) vs off.

    The step PROGRAM contains no trace calls — spans are host-side emits at
    serve/replay/cycle boundaries, and ``obs/trace.emit`` early-returns when
    unconfigured — so the on-vs-off delta is the claim itself: it must sit
    inside chain-differencing noise.  tests/test_trace.py pins the stronger
    static fact (trace on adds ZERO step-program equations, jaxpr
    byte-identity); this record is the measured companion.  Recipe and
    expected numbers: docs/BUDGET.md "trace overhead"."""
    import tempfile

    from tdfo_tpu.obs import trace as obs_trace

    sec_off = chain_time(run, make_args, ks=ks, reps=reps)
    with tempfile.TemporaryDirectory() as td:
        obs_trace.configure(td)
        try:
            sec_on = chain_time(run, make_args, ks=ks, reps=reps)
        finally:
            obs_trace.configure(None)
    return {
        "step_ms_trace_off": round(sec_off * 1e3, 3),
        "step_ms_trace_on": round(sec_on * 1e3, 3),
        "on_over_off": round(sec_on / sec_off, 4) if sec_off else None,
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch-size", type=int, default=8192)
    ap.add_argument("--embed-dim", type=int, default=64)
    ap.add_argument("--write-baseline", action="store_true",
                    help="record this run as BENCH_BASELINE.json")
    ap.add_argument("--skip-lookup-bench", action="store_true")
    ap.add_argument("--dense", action="store_true",
                    help="bench the dense regime (nn.Embed + dense AdamW) "
                         "instead of the sparse/DMP headline")
    ap.add_argument("--model", default="twotower",
                    choices=["twotower", "dlrm", "dlrm-criteo"],
                    help="CTR head for the sparse headline (dlrm-criteo = "
                         "the BASELINE.json north-star workload: 26 "
                         "Criteo-Kaggle tables, 33.76M rows, stacked, "
                         "rowwise-adagrad)")
    ap.add_argument("--table-dtype", default="float32",
                    choices=["float32", "bfloat16", "int8"],
                    help="twotower/dlrm sparse headline only: embedding "
                         "STORAGE dtype (bfloat16 halves table HBM; int8 "
                         "quarters it plus an 8 B/row f32 (scale, offset) "
                         "sidecar — both keep compute f32 and write with "
                         "stochastic rounding)")
    ap.add_argument("--skip-big-table", action="store_true")
    ap.add_argument("--skip-serve-fleet", action="store_true",
                    help="skip the out-of-process fleet record "
                    "(serve_fleet8: ingress + replica processes on host "
                    "CPU — spawns subprocesses)")
    ap.add_argument("--skip-serving", action="store_true",
                    help="skip the serving-path records (serve_score8 / "
                         "serve_retrieve8)")
    ap.add_argument("--skip-serve-seq", action="store_true",
                    help="skip the sequence-serving records (serve_seq8: "
                         "masked-position scoring + next-item retrieval "
                         "against the item-table corpus)")
    ap.add_argument("--skip-cache", action="store_true",
                    help="skip the update-cache amortization record "
                         "(cache_zipf)")
    ap.add_argument("--skip-cache-int8", action="store_true",
                    help="skip the int8-storage update-cache record "
                         "(cache_int8_zipf)")
    ap.add_argument("--skip-quant-fused", action="store_true",
                    help="skip the fused-int8 fat-line vs plain-int8 "
                         "record (quant_int8_fused)")
    ap.add_argument("--skip-planner", action="store_true",
                    help="dlrm-criteo only: skip the planner-vs-defaults "
                         "record (planner_dlrm8)")
    ap.add_argument("--skip-trace-overhead", action="store_true",
                    help="skip the trace on-vs-off step-chain record "
                         "(trace_overhead) — re-times the headline chain "
                         "once more with span sinks live")
    ap.add_argument("--skip-retrieval-scale", action="store_true",
                    help="skip the 1M/10M-corpus exact-vs-two-stage record "
                         "(retrieve_twostage8) — the slowest serving record "
                         "(builds a 10M-row corpus on device)")
    ap.add_argument("--hot-vocab", type=int, default=0,
                    help="dlrm-criteo only: split every table's [0, K) "
                         "frequency-ranked prefix into a replicated hot head "
                         "(scatter-free one-hot MXU updates) and switch the "
                         "batches to power-law ids")
    ap.add_argument("--powerlaw", action="store_true",
                    help="dlrm-criteo only: power-law (zipf-ranked) ids "
                         "WITHOUT the hot/cold split — the ablation baseline "
                         "for --hot-vocab")
    args = ap.parse_args()
    if args.model == "dlrm-criteo" and args.embed_dim > 32:
        ap.error("dlrm-criteo: use --embed-dim 16 (the standard Kaggle-DLRM "
                 "dim; XLA lane-pads wider narrow tables past v5e HBM)")
    if args.dense and args.model != "twotower":
        # validate BEFORE measuring: a bad combination must not waste a run
        ap.error("--model is only valid for the sparse headline (drop --dense)")
    if (args.hot_vocab or args.powerlaw) and args.model != "dlrm-criteo":
        ap.error("--hot-vocab/--powerlaw require --model dlrm-criteo")
    if args.table_dtype != "float32" and (
            args.dense or args.model == "dlrm-criteo"):
        ap.error("--table-dtype applies to the twotower/dlrm sparse headline")

    import jax

    hot_info = None
    table_bytes = None
    if args.dense:
        (run, make_args, global_batch, floor_bytes, flops_per_ex,
         counters_probe) = build_train_bench(args.batch_size, args.embed_dim)
    elif args.model == "dlrm-criteo":
        (run, make_args, global_batch, floor_bytes, flops_per_ex, hot_info,
         counters_probe) = (
            build_criteo_train_bench(args.batch_size, args.embed_dim,
                                     hot_vocab=args.hot_vocab,
                                     powerlaw=args.powerlaw)
        )
    else:
        (run, make_args, global_batch, floor_bytes, flops_per_ex, table_bytes,
         counters_probe) = (
            build_sparse_train_bench(args.batch_size, args.embed_dim,
                                     args.model, args.table_dtype)
        )
    sec_per_step = chain_time(run, make_args)
    if callable(floor_bytes):  # sparse floor depends on the generated batches
        floor_bytes = floor_bytes()

    peak_tflops, hbm_gbps, spec_assumed = chip_peaks()
    n_chips = jax.device_count()
    on_tpu = jax.devices()[0].platform == "tpu"

    # --- roofline sanity: refuse to report the impossible -----------------
    floor_sec = floor_bytes / (hbm_gbps * 1e9)
    if on_tpu and not spec_assumed and sec_per_step < floor_sec * 0.9:
        print(
            f"BENCH INVALID: measured {sec_per_step*1e3:.3f} ms/step beats the "
            f"HBM roofline floor {floor_sec*1e3:.3f} ms/step "
            f"({floor_bytes/1e6:.0f} MB optimizer traffic @ {hbm_gbps:.0f} GB/s). "
            "This is a caching/measurement artifact, not a real number.",
            file=sys.stderr,
        )
        sys.exit(1)

    examples_per_sec_per_chip = global_batch / sec_per_step / n_chips
    mfu = (flops_per_ex * global_batch / sec_per_step) / (n_chips * peak_tflops * 1e12)
    hbm_util = floor_bytes / sec_per_step / (hbm_gbps * 1e9)

    # one counters-on step AFTER the timed chains: the telemetry registry's
    # per-step numbers (touched/unique rows per table, grad/param norms) in
    # the record, from a separate program — the timed program stays
    # counters-off (byte-identity pinned by tests/test_telemetry.py)
    try:
        step_counters = counters_probe()
    except Exception as e:  # the probe must never kill the headline
        print(f"bench: counters probe failed: {e!r}", file=sys.stderr)
        step_counters = {}

    lookup = {} if args.skip_lookup_bench else bench_embedding_lookup()

    big_table = {}
    if on_tpu and not args.skip_big_table and not args.dense:
        try:
            big_table = bench_big_table()
            # the headline optimizer's own (smaller) scale pair rides along
            adam = bench_big_table(vocab_big=100_000_000, kind="adam",
                                   include_tiny=False)
            big_table["adam_100m"] = {
                k: adam[k] for k in ("vocab_big", "step_ms_small",
                                     "step_ms_big", "big_over_small")
            }
        except Exception as e:  # the demo must never kill the headline
            print(f"bench: big-table demo failed: {e!r}", file=sys.stderr)

    serving = {}
    if on_tpu and not args.skip_serving and not args.dense:
        try:
            serving = bench_serving(args.batch_size)
        except Exception as e:  # serving records must never kill the headline
            print(f"bench: serving bench failed: {e!r}", file=sys.stderr)

    serve_seq = {}
    if on_tpu and not args.skip_serve_seq and not args.dense:
        try:
            serve_seq = bench_serve_seq(args.batch_size)
        except Exception as e:  # seq records must never kill the headline
            print(f"bench: serve-seq bench failed: {e!r}", file=sys.stderr)

    serve_fleet = {}
    # no on_tpu gate: the fleet record measures the HOST serving stack
    # (replica children are always JAX_PLATFORMS=cpu)
    if not args.skip_serve_fleet and not args.dense:
        try:
            serve_fleet = bench_serve_fleet()
        except Exception as e:  # fleet record must never kill the headline
            print(f"bench: serve-fleet bench failed: {e!r}", file=sys.stderr)

    cache_zipf = {}
    if on_tpu and not args.skip_cache and not args.dense:
        try:
            cache_zipf = bench_cache_zipf()
        except Exception as e:  # cache record must never kill the headline
            print(f"bench: cache bench failed: {e!r}", file=sys.stderr)

    cache_int8_zipf = {}
    if on_tpu and not args.skip_cache_int8 and not args.dense:
        try:
            cache_int8_zipf = bench_cache_int8_zipf()
        except Exception as e:  # cache record must never kill the headline
            print(f"bench: int8-cache bench failed: {e!r}", file=sys.stderr)

    quant_int8_fused = {}
    if on_tpu and not args.skip_quant_fused and not args.dense:
        try:
            quant_int8_fused = bench_quant_int8_fused()
        except Exception as e:  # quant record must never kill the headline
            print(f"bench: fused-int8 bench failed: {e!r}", file=sys.stderr)

    retrieval_scale = {}
    if on_tpu and not args.skip_retrieval_scale and not args.dense:
        try:
            retrieval_scale = bench_retrieval_scale()
        except Exception as e:  # scale record must never kill the headline
            print(f"bench: retrieval-scale bench failed: {e!r}",
                  file=sys.stderr)

    trace_overhead = {}
    if on_tpu and not args.skip_trace_overhead:
        try:
            trace_overhead = bench_trace_overhead(run, make_args)
        except Exception as e:  # trace record must never kill the headline
            print(f"bench: trace-overhead bench failed: {e!r}",
                  file=sys.stderr)

    planner_rec = {}
    if args.model == "dlrm-criteo" and not args.skip_planner:
        # predictions are cheap host math and always emitted; the measured
        # arms only run on TPU under the DEFAULT (uniform-id) traffic the
        # planner's synthetic stats describe
        uniform = not args.hot_vocab and not args.powerlaw
        try:
            planner_rec = bench_planner_dlrm(
                args.batch_size, args.embed_dim,
                on_tpu=on_tpu and uniform,
                headline_step_ms=sec_per_step * 1e3 if uniform else None,
            )
        except Exception as e:  # planner record must never kill the headline
            print(f"bench: planner bench failed: {e!r}", file=sys.stderr)

    repo = Path(__file__).parent
    baseline_path = repo / "BENCH_BASELINE.json"
    model_name = "twotower" if args.dense else args.model
    bench_config = {"batch_size": args.batch_size, "embed_dim": args.embed_dim}
    if model_name != "twotower":
        # a different model family must never be compared against the
        # twotower baseline record (config equality gates vs_baseline)
        bench_config["model"] = model_name
    if args.hot_vocab or args.powerlaw:
        # hot/cold and power-law traffic change the workload: the config
        # keys gate vs_baseline so a skewed-traffic run never claims a
        # speedup over the uniform-traffic baseline record
        bench_config["hot_vocab"] = args.hot_vocab
        bench_config["powerlaw"] = True
    if args.table_dtype != "float32":
        # quantized storage changes the per-step byte budget: gate
        # vs_baseline so a bf16 run never claims a speedup over f32
        bench_config["table_dtype"] = args.table_dtype
    record = {
        "metric": f"{model_name.replace('-', '_')}_train_examples_per_sec_per_chip",
        "value": round(examples_per_sec_per_chip, 1),
        "unit": "examples/sec/chip",
        "regime": "dense_adamw" if args.dense else "dmp_sparse",
        "step_ms": round(sec_per_step * 1e3, 3),
        "roofline_floor_ms": round(floor_sec * 1e3, 3),
        # storage/traffic at the table STORAGE dtype: bf16 halves
        # table_bytes and the table share of bytes_per_step
        "table_bytes": table_bytes,
        "bytes_per_step": round(floor_bytes, 1),
        "hbm_utilization": round(hbm_util, 3),
        "mfu": round(mfu, 5),
        "counters": step_counters,
        "embedding_lookup_p50_us": lookup,
        "big_table_demo": big_table,
        "serving": serving,
        "serve_seq8": serve_seq,
        "serve_fleet8": serve_fleet,
        "cache_zipf": cache_zipf,
        "cache_int8_zipf": cache_int8_zipf,
        "quant_int8_fused": quant_int8_fused,
        "retrieve_twostage8": retrieval_scale,
        "planner_dlrm8": planner_rec,
        "trace_overhead": trace_overhead,
        "spec_assumed": spec_assumed,
        "device_kind": jax.devices()[0].device_kind,
        "config": bench_config,
    }
    if args.table_dtype in ("bfloat16", "int8"):
        # the quantized-storage record: same workload as the f32 headline,
        # half (bf16) / roughly a quarter (int8 codes + 8 B/row sidecar) the
        # table HBM — compare step_ms against the f32 run directly
        record[f"quant_{'bf16' if args.table_dtype == 'bfloat16' else 'int8'}"] = {
            "table_bytes": table_bytes,
            "bytes_per_step": round(floor_bytes, 1),
            "step_ms": round(sec_per_step * 1e3, 3),
        }
    if hot_info is not None and (hot_info["enabled"] or hot_info["powerlaw"]):
        record["hot_cold"] = {
            "enabled": hot_info["enabled"],
            "hot_vocab": hot_info["hot_vocab"],
            "powerlaw": hot_info["powerlaw"],
            "fully_hot_tables": hot_info["fully_hot_tables"],
            "hit_rate": (round(float(np.mean(hot_info["hit_rates"])), 4)
                         if hot_info["hit_rates"] else None),
            "step_ms": round(sec_per_step * 1e3, 3),
        }
    # only the DEFAULT headline config may claim the auto-written baseline
    # slot (a first-ever --model dlrm run must not disable twotower
    # regression tracking); explicit --write-baseline always wins
    default_cfg = model_name == "twotower" and not args.dense
    if on_tpu and (args.write_baseline
                   or (default_cfg and not baseline_path.exists())):
        baseline_path.write_text(json.dumps(record, indent=1) + "\n")

    vs_baseline = 1.0
    if baseline_path.exists():
        base = json.loads(baseline_path.read_text())
        comparable = (
            base.get("config") == record["config"]
            and base.get("device_kind") == record["device_kind"]
        )
        if comparable and base.get("value"):
            vs_baseline = round(examples_per_sec_per_chip / base["value"], 3)
            # same workload/metric, but say which regime produced the
            # baseline so a cross-regime speedup is legible as exactly that
            record["baseline_regime"] = base.get("regime", "dense_adamw")
        elif not comparable:
            print(
                f"bench: baseline config {base.get('config')}/{base.get('device_kind')} "
                f"!= run config {record['config']}/{record['device_kind']}; "
                "vs_baseline not comparable, reporting 1.0",
                file=sys.stderr,
            )

    print(json.dumps({**record, "vs_baseline": vs_baseline}))


if __name__ == "__main__":
    main()
