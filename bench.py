"""Benchmark harness — prints ONE JSON line with the headline metric.

Metric: TwoTower CTR train-step throughput, examples/sec/chip on the real
device (the BASELINE.json target metric family; the reference publishes no
numbers — BASELINE.md — so ``vs_baseline`` compares against the recorded
number in ``BENCH_BASELINE.json`` when present, else 1.0).
"""

from __future__ import annotations

import json
import time
from pathlib import Path

import jax
import numpy as np


def build_bench(batch_size: int = 8192, embed_dim: int = 64):
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tdfo_tpu.core.config import MeshSpec
    from tdfo_tpu.core.mesh import make_mesh
    from tdfo_tpu.models.twotower import init_twotower
    from tdfo_tpu.train.state import TrainState, make_adamw
    from tdfo_tpu.train.step import make_train_step

    size_map = {
        "user": 500_000, "item": 200_000, "language": 32, "is_ebook": 2,
        "format": 16, "publisher": 5_000, "pub_decade": 16,
    }
    platform = jax.devices()[0].platform
    dtype = jnp.bfloat16 if platform != "cpu" else jnp.float32
    model, params = init_twotower(jax.random.key(0), size_map, embed_dim, dtype=dtype)
    # data-parallel over every chip present; per-chip throughput then divides
    # honestly on multi-device hosts
    mesh = make_mesh(MeshSpec(data=-1, model=1, seq=1))
    state = jax.device_put(
        TrainState.create(apply_fn=model.apply, params=params, tx=make_adamw(3e-4, 1e-4)),
        NamedSharding(mesh, P()),
    )
    rng = np.random.default_rng(0)
    b = batch_size * mesh.shape["data"]
    batch = {
        "user_id": rng.integers(0, size_map["user"], b, dtype=np.int32),
        "item_id": rng.integers(0, size_map["item"], b, dtype=np.int32),
        "language": rng.integers(0, size_map["language"], b, dtype=np.int32),
        "is_ebook": rng.integers(0, 2, b, dtype=np.int32),
        "format": rng.integers(0, size_map["format"], b, dtype=np.int32),
        "publisher": rng.integers(0, size_map["publisher"], b, dtype=np.int32),
        "pub_decade": rng.integers(0, size_map["pub_decade"], b, dtype=np.int32),
        "avg_rating": rng.random(b, dtype=np.float32),
        "num_pages": rng.random(b, dtype=np.float32),
        "label": rng.integers(0, 2, b).astype(np.float32),
    }
    batch = jax.device_put(batch, NamedSharding(mesh, P("data")))
    return make_train_step(mesh=mesh), state, batch, b


def main() -> None:
    step, state, batch, global_batch = build_bench()

    # warmup + compile
    state, loss = step(state, batch)
    jax.block_until_ready(loss)

    n_iters = 50
    t0 = time.perf_counter()
    for _ in range(n_iters):
        state, loss = step(state, batch)
    jax.block_until_ready(loss)
    dt = time.perf_counter() - t0

    n_chips = jax.device_count()
    examples_per_sec_per_chip = global_batch * n_iters / dt / n_chips

    baseline_path = Path(__file__).parent / "BENCH_BASELINE.json"
    vs_baseline = 1.0
    if baseline_path.exists():
        base = json.loads(baseline_path.read_text()).get("value")
        if base:
            vs_baseline = examples_per_sec_per_chip / base

    print(
        json.dumps(
            {
                "metric": "twotower_train_examples_per_sec_per_chip",
                "value": round(examples_per_sec_per_chip, 1),
                "unit": "examples/sec/chip",
                "vs_baseline": round(vs_baseline, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
