"""CTR training in the DMP regime: sparse forward + eval for TwoTower/DLRM.

The torchrec DMP + CombinedOptimizer pattern (``torchrec/train.py:235-254``)
applied to the CTR family: the 7 embedding tables live in a
ShardedEmbeddingCollection and get row-sparse in-backward updates
(``make_sparse_train_step``); the dense towers / MLPs stay under optax.  This
is what eliminates the dense-AdamW full-table optimizer sweep — per-step HBM
traffic becomes O(batch rows), making >=1B-row tables feasible (SURVEY.md §7
hard part #2, BASELINE.json north star).

Adapters here mirror ``tdfo_tpu/train/seq.py`` for the sequential family.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp

from tdfo_tpu.parallel.embedding import ShardedEmbeddingCollection
from tdfo_tpu.train.step import bce_with_logits_loss

__all__ = ["ctr_sparse_forward", "make_ctr_sparse_eval_step"]


def ctr_sparse_forward(backbone, with_logits: bool = False) -> Callable:
    """Forward for ``make_sparse_train_step``: the collection has already
    gathered the categorical vectors; run the dense backbone (TwoTowerBackbone
    or DLRMBackbone — both take ``(embs, batch)``) and the sigmoid BCE.
    ``with_logits=True`` returns ``(loss, logits)`` for ``with_aux`` steps."""

    def forward(dense_params, embs, batch):
        logits = backbone.apply({"params": dense_params}, embs, batch)
        loss = bce_with_logits_loss(logits, batch["label"].astype(jnp.float32))
        return (loss, logits) if with_logits else loss

    return forward


def make_ctr_sparse_eval_step(
    coll: ShardedEmbeddingCollection, backbone, *, mode: str = "gspmd"
):
    """Jitted eval step, (state, batch) -> (loss, logits) — same contract as
    ``make_eval_step`` so the trainer's eval loop serves both regimes.  The
    lookup honours the configured ``lookup_mode`` (same program as training).
    """
    features = list(coll.features())

    @jax.jit
    def step(state, batch):
        ids = {f: batch[f] for f in features}
        embs = coll.lookup(state.tables, ids, mode=mode)
        logits = backbone.apply({"params": state.dense_params}, embs, batch)
        loss = bce_with_logits_loss(logits, batch["label"].astype(jnp.float32))
        return loss, logits

    return step
