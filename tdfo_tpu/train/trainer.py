"""Epoch driver — train/eval loops, metrics, checkpoint/resume, profiling.

Unifies the reference's five ``main()`` loops (``jax-flax/train.py:95-164``,
``train_dp.py:144-247``, ``tensorflow2/train.py:22-57``, ``train_dp.py:107-190``,
``torchrec/train.py:147-273``) into one mesh-aware driver:

  * TwoTower CTR: streaming parquet epochs, BCE train loss, padded-final-batch
    eval (``pad_shard_unpad`` parity, ``jax-flax/train_dp.py:182-184,233-240``)
    with in-framework streaming AUC (replacing the borrowed keras metric).
  * Bert4Rec: masked-LM train epochs; sampled-candidate eval
    (Recall@K/NDCG@K, 1+100 protocol), pre-training validation as a sanity
    floor (``torchrec/train.py:159``).
  * checkpoint/resume every N epochs incl. optimizer state + mid-training
    restart (supersedes all three reference mechanisms, see
    ``tdfo_tpu/train/checkpoint.py``), JSONL metric logging (observability
    the reference lacks, SURVEY.md §5.5), optional ``jax.profiler`` traces
    (§5.1).

Fault tolerance: training survives preemption by construction — restart the
same command and the driver resumes from the newest checkpoint (the
``BackupAndRestore`` capability, ``tensorflow2/train_ps.py:156``), now at
STEP granularity: ``checkpoint_every_n_steps`` saves mid-epoch with a
data-stream cursor, and resume fast-forwards the stream to the exact batch.
A non-finite-loss guard keeps a bounded on-device snapshot and rolls back to
it (skipping the offending batch window) instead of training through NaNs;
checkpoint I/O retries with backoff (``tdfo_tpu/utils/retry.py``); the
``[faults]`` config section injects deterministic kills/NaNs/I/O failures so
all of this is testable (``tdfo_tpu/utils/faults.py``).
"""

from __future__ import annotations

import dataclasses
import json
import math
import time
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Callable, Iterator

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from tdfo_tpu.core.config import Config
from tdfo_tpu.core.mesh import make_mesh
from tdfo_tpu.obs import counters as obs_counters
from tdfo_tpu.obs import events as obs_events
from tdfo_tpu.obs import trace as obs_trace
from tdfo_tpu.data.loader import (
    MapStream,
    ParquetStream,
    prefetch_to_mesh,
    resolve_files,
)
from tdfo_tpu.train.metrics import AUC, recalls_and_ndcgs_for_ks
from tdfo_tpu.train.state import TrainState, make_adamw
from tdfo_tpu.train.step import make_eval_step, make_multi_step, make_train_step
from tdfo_tpu.utils import faults as _faults
from tdfo_tpu.utils import retry as _retry

__all__ = ["Trainer", "MetricLogger", "pad_batch"]


class MetricLogger:
    """stdout + JSONL metrics (the observability layer the reference lacks —
    its closest analogue is tqdm bars + prints, SURVEY.md §5.5)."""

    def __init__(self, log_dir: str | Path | None = None,
                 tensorboard: bool = False, rotate_bytes: int = 0):
        self._f = None
        self._tb = None
        self._n = 0
        # size-based rotation ([telemetry] log_rotate_bytes): a long-running
        # online loop must not grow metrics.jsonl without bound
        self._rotate_bytes = int(rotate_bytes)
        self._path: Path | None = None
        # telemetry norm scalars accumulate here and flush as ONE histogram
        # summary per tag at close() (run-wide distribution view)
        self._hist_buf: dict[str, list[float]] = {}
        if log_dir is not None and jax.process_index() == 0:
            Path(log_dir).mkdir(parents=True, exist_ok=True)
            self._path = Path(log_dir) / "metrics.jsonl"
            self._f = open(self._path, "a")
            if tensorboard:
                # TF-free tfevents mirror of every scalar (the PS recipe's
                # TensorBoard callback, tensorflow2/train_ps.py:154, made
                # framework-wide): `tensorboard --logdir` shows the curves
                from tdfo_tpu.utils.tensorboard import TBScalarWriter

                self._tb = TBScalarWriter(log_dir)

    def log(self, **record: Any) -> None:
        # numpy scalars (device fetches, np.float32 arithmetic) are not JSON
        # serialisable and dodge the float-format branch below — coerce at
        # the door so callers can pass fetched values straight through
        record = {
            k: (v.item() if isinstance(v, np.generic)
                or (isinstance(v, np.ndarray) and v.ndim == 0) else v)
            for k, v in record.items()
        }
        record.setdefault("time", time.time())
        if jax.process_index() == 0:
            msg = ", ".join(
                f"{k}={v:.5f}" if isinstance(v, float) else f"{k}={v}"
                for k, v in record.items() if k != "time"
            )
            print(msg, flush=True)
            if self._f is not None:
                self._f.write(json.dumps(record) + "\n")
                self._f.flush()
                if self._rotate_bytes:
                    from tdfo_tpu.utils.logrotate import maybe_rotate_file

                    self._f = maybe_rotate_file(
                        self._f, self._path, self._rotate_bytes)
            if self._tb is not None:
                scalars = {
                    k: float(v) for k, v in record.items()
                    if k not in ("time", "step", "epoch", "global_step")
                    and isinstance(v, (int, float))
                }
                # per-tag x-axis: run-global step when the caller provides
                # one (per-epoch `step` resets and would fold curves back),
                # else epoch, else a monotone event counter
                step = record.get(
                    "global_step", record.get("epoch", self._n))
                self._tb.scalars(int(step), scalars,
                                 wall_time=record["time"])
                for k in ("grad_norm", "param_norm"):
                    if k in scalars:
                        self._hist_buf.setdefault(k, []).append(scalars[k])
            self._n += 1

    def close(self) -> None:
        """Idempotent: ``fit`` closes in a ``finally`` block, and a caller
        logging afterwards falls back to stdout-only instead of crashing."""
        if self._f is not None:
            self._f.close()
            self._f = None
        if self._tb is not None:
            for tag, vals in self._hist_buf.items():
                self._tb.histogram(self._n, f"{tag}_dist", vals)
            self._hist_buf = {}
            self._tb.close()
            self._tb = None


def pad_batch(batch: dict[str, np.ndarray], size: int) -> tuple[dict[str, np.ndarray], np.ndarray]:
    """Pad a short final eval batch to ``size`` rows; returns (batch, weights)
    with 0-weight padding rows (``flax.jax_utils.pad_shard_unpad`` parity,
    ``jax-flax/train_dp.py:182-184``)."""
    n = len(next(iter(batch.values())))
    w = np.zeros((size,), np.float32)
    w[:n] = 1.0
    if n == size:
        return batch, w
    out = {}
    for k, v in batch.items():
        pad_width = [(0, size - n)] + [(0, 0)] * (v.ndim - 1)
        out[k] = np.pad(v, pad_width)
    return out, w


def _ctr_columns(cfg: Config) -> tuple[tuple[str, ...], tuple[str, ...]]:
    """(categorical input columns, continuous columns) for the CTR family —
    the custom schema (``categorical_features``, e.g. Criteo's 26+13) or the
    Goodreads TwoTower default."""
    if cfg.categorical_features:
        return tuple(cfg.categorical_features), tuple(cfg.continuous_features)
    from tdfo_tpu.models.twotower import (
        TWOTOWER_CATEGORICAL,
        TWOTOWER_CONTINUOUS,
        _FEATURE_TO_INPUT,
    )

    return (tuple(_FEATURE_TO_INPUT[f] for f in TWOTOWER_CATEGORICAL),
            TWOTOWER_CONTINUOUS)


def _ctr_eval_schema(cat_columns: tuple[str, ...],
                     cont_columns: tuple[str, ...]) -> dict[str, tuple]:
    """Post-rename eval-batch schema for the CTR family: key ->
    (numpy dtype, trailing shape).  The authority for (a) restricting real
    batches so every host ships an identical pytree and (b) synthesising
    zero-weight template batches on hosts with no eval rows — dtypes match
    what the CTR preprocessing writes to parquet."""
    schema: dict[str, tuple] = {c: (np.int32, ()) for c in cat_columns}
    for c in cont_columns:
        schema[c] = (np.float32, ())
    schema["label"] = (np.int8, ())
    return schema


def _make_ctr_eval_accum(logits_fn: Callable):
    """Device-side eval accumulator for the CTR family.

    One jitted call per batch folds (weighted loss sum, weight sum, streaming
    AUC histograms) into a replicated accumulator pytree — the host only
    fetches floats ONCE at epoch end.  Under a multi-host mesh the reductions
    are global (GSPMD inserts the cross-host psums), replacing torchrec's
    ``all_gather_object`` metric aggregation (``torchrec/train.py:108-111``)
    and never touching non-addressable shards from the host.
    """

    @jax.jit
    def accum(state, batch, acc):
        w = batch["_weight"]
        logits = logits_fn(state, batch)
        labels = batch["label"].astype(jnp.float32)
        loss_vec = optax.sigmoid_binary_cross_entropy(logits, labels)
        # non-finite logits (mixed-precision overflow) must not fold a
        # backend-defined NaN->bin cast into the headline eval AUC
        ok = jnp.isfinite(logits)
        return {
            "loss_sum": acc["loss_sum"] + (loss_vec * w).sum(),
            "w_sum": acc["w_sum"] + w.sum(),
            "auc": acc["auc"].update(
                labels, jax.nn.sigmoid(jnp.where(ok, logits, 0.0)),
                w * ok.astype(jnp.float32)),
        }

    return accum


def _wrap_auc_step(inner, *, donate_state: bool = True,
                   counters: bool = False):
    """Fuse the train-side streaming-AUC fold INTO the step's single jitted
    program: ``(state, batch, acc) -> (state, loss, acc)``.

    One global program per step matters beyond speed: in multi-process runs a
    SEPARATE jitted fold interleaved with the loop's eager loss arithmetic
    deadlocked the cross-process dispatch rendezvous (two global programs
    racing for the mesh in different orders on different hosts).  ``inner``
    is an unjitted ``with_aux`` step returning ``(state, (loss, logits))``.

    ``counters=True`` opens a telemetry collector around the trace and
    appends the gathered dict as an extra return; ``False`` keeps the
    construction — and the jaxpr — exactly as without telemetry (the lazy
    ``emit`` thunks below add zero equations when no collector is open).
    """

    def _step(state, batch, acc: AUC):
        state, (loss, logits) = inner(state, batch)
        # mixed-precision overflow steps can emit non-finite logits; a
        # NaN->int32 histogram-bin cast is backend-defined, so weight those
        # samples out of the streaming AUC instead of folding garbage in
        ok = jnp.isfinite(logits)
        obs_counters.emit("nonfinite_logits", lambda: (~ok).sum())
        acc = acc.update(batch["label"].astype(jnp.float32),
                         jax.nn.sigmoid(jnp.where(ok, logits, 0.0)),
                         ok.astype(jnp.float32))
        return state, loss, acc

    if counters:
        def step(state, batch, acc: AUC):
            with obs_counters.collect() as c:
                out = _step(state, batch, acc)
            return (*out, dict(c))
    else:
        step = _step

    return jax.jit(step, donate_argnums=(0,) if donate_state else ())


def _wrap_auc_multi_step(inner, *, donate_state: bool = True,
                         counters: bool = False):
    """steps_per_execution twin of :func:`_wrap_auc_step`: scan the unjitted
    step over a stacked chunk, folding AUC in the scan carry.  With
    ``counters`` the collector opens INSIDE the scan body (a collector
    opened outside would capture body tracers and leak them through the
    scan boundary); counter dicts stack as scan outputs and the chunk
    reports the final step's values."""

    def _body(carry, batch):
        st, a = carry
        st, (loss, logits) = inner(st, batch)
        ok = jnp.isfinite(logits)  # see _wrap_auc_step
        obs_counters.emit("nonfinite_logits", lambda: (~ok).sum())
        a = a.update(batch["label"].astype(jnp.float32),
                     jax.nn.sigmoid(jnp.where(ok, logits, 0.0)),
                     ok.astype(jnp.float32))
        return (st, a), loss

    if counters:
        def multi(state, stack, acc: AUC):
            def body(carry, batch):
                with obs_counters.collect() as c:
                    carry, loss = _body(carry, batch)
                return carry, (loss, dict(c))

            (state, acc), (losses, cs) = jax.lax.scan(body, (state, acc), stack)
            return (state, losses.mean(), acc,
                    jax.tree.map(lambda x: x[-1], cs))
    else:
        def multi(state, stack, acc: AUC):
            (state, acc), losses = jax.lax.scan(_body, (state, acc), stack)
            return state, losses.mean(), acc

    return jax.jit(multi, donate_argnums=(0,) if donate_state else ())


def _wrap_auc_pipelined(pipe, *, donate_state: bool = False,
                        counters: bool = False):
    """Pipelined twin of :func:`_wrap_auc_step`: the step trains the CARRIED
    batch, so the AUC fold reads the carry's labels — folding the incoming
    batch's labels would pair them with the previous batch's logits.
    Returns jitted ``(prime, step, flush)``; ``counters`` appends the
    telemetry dict to step/flush returns (see :func:`_wrap_auc_step`)."""

    def _fold(acc: AUC, labels, logits):
        ok = jnp.isfinite(logits)  # see _wrap_auc_step
        obs_counters.emit("nonfinite_logits", lambda: (~ok).sum())
        return acc.update(labels.astype(jnp.float32),
                          jax.nn.sigmoid(jnp.where(ok, logits, 0.0)),
                          ok.astype(jnp.float32))

    def _step(state, batch, carry, acc: AUC):
        labels = carry[0]["label"]
        state, (loss, logits), carry = pipe.step(state, batch, carry)
        return state, loss, carry, _fold(acc, labels, logits)

    def _flush(state, carry, acc: AUC):
        labels = carry[0]["label"]
        state, (loss, logits) = pipe.flush(state, carry)
        return state, loss, _fold(acc, labels, logits)

    if counters:
        def step(state, batch, carry, acc: AUC):
            with obs_counters.collect() as c:
                out = _step(state, batch, carry, acc)
            return (*out, dict(c))

        def flush(state, carry, acc: AUC):
            with obs_counters.collect() as c:
                out = _flush(state, carry, acc)
            return (*out, dict(c))
    else:
        step, flush = _step, _flush

    d = (0,) if donate_state else ()
    return (jax.jit(pipe.prime), jax.jit(step, donate_argnums=d),
            jax.jit(flush, donate_argnums=d))


def _wrap_counters_step(fn, *, donate_state: bool = False):
    """Counter-collecting jit wrapper for steps WITHOUT an AUC fold
    (bert4rec): append the telemetry dict to ``fn``'s return tuple.  Only
    built when ``telemetry.counters`` is on — the off path keeps the
    original (wrapper-free) construction, so its jaxpr cannot drift."""

    def wrapped(*args):
        with obs_counters.collect() as c:
            out = fn(*args)
        out = out if isinstance(out, tuple) else (out,)
        return (*out, dict(c))

    return jax.jit(wrapped, donate_argnums=(0,) if donate_state else ())


def _wrap_counters_multi_step(step_fn, *, donate_state: bool = False):
    """steps_per_execution twin of :func:`_wrap_counters_step` (the
    counter-aware variant of ``step.make_multi_step``): collect inside the
    scan body, stack as scan outputs, report the final step's values."""

    def multi(state, stack, *rest):
        def body(st, batch):
            with obs_counters.collect() as c:
                st, loss = step_fn(st, batch, *rest)
            return st, (loss, dict(c))

        state, (losses, cs) = jax.lax.scan(body, state, stack)
        return state, losses.mean(), jax.tree.map(lambda x: x[-1], cs)

    return jax.jit(multi, donate_argnums=(0,) if donate_state else ())


def _commit_replicated(state, mesh):
    """Pin every uncommitted leaf of a state pytree to the mesh, replicated.

    Sharded leaves (embedding tables placed by the collection) keep their
    shardings; everything else (step counter, dense params, optax state,
    count slots) commits as replicated.  Without this, checkpoint restore
    materialises the uncommitted leaves on device 0 only and the next jitted
    step fails with incompatible-device errors against the sharded tables.
    """
    repl = NamedSharding(mesh, P())

    def commit(leaf):
        if isinstance(leaf, jax.Array) and leaf.committed:
            return leaf
        return jax.device_put(leaf, repl)

    return jax.tree.map(commit, state)


def _copy_tree(tree):
    """Deep-copy the array leaves of a pytree into FRESH device buffers
    (shardings preserved — the copy is an eager op and computation follows
    data).  Needed wherever a tree must survive donation: the dense train
    step donates its state, so a rollback snapshot aliasing live buffers
    would be invalidated by the very next step."""
    return jax.tree.map(
        lambda x: jnp.copy(x) if isinstance(x, jax.Array) else x, tree)


def _check_cache_overflow(overflow: dict) -> None:
    """Fail LOUDLY on update-cache admission overflow: ids past the free
    capacity never entered the cache, so their updates were silently lost
    and the bit-exactness contract is already broken — continuing would
    train on corrupt tables."""
    bad = {a: int(v) for a, v in overflow.items() if int(v) > 0}
    if bad:
        raise RuntimeError(
            f"update-cache admission overflow (distinct ids whose updates "
            f"were LOST): {bad}.  embeddings.cache_rows is too small for "
            "the per-flush-interval working set — raise cache_rows (the "
            "retained half must cover the interval's distinct touched "
            "rows) or lower flush_every.")


class Trainer:
    """Config-driven trainer for both workload families."""

    def __init__(self, config: Config, *, log_dir: str | Path | None = None):
        self.config = config
        if config.use_tpu and jax.default_backend() != "tpu":
            raise RuntimeError(
                f"use_tpu = true but the jax backend is "
                f"{jax.default_backend()!r} (TPUStrategy-resolution parity: "
                "refuse to silently train a TPU config elsewhere)"
            )
        self.mesh = make_mesh(config.mesh)
        self.logger = MetricLogger(log_dir or config.checkpoint_dir,
                                   tensorboard=config.tensorboard,
                                   rotate_bytes=config.telemetry.log_rotate_bytes)
        self._ckpt = None
        self._ckpt_stamps = None  # compatibility stamps (hot/cold digests)
        self._logged_steps = 0  # run-global data-step counter (batches consumed)
        self._a2a_overflow = None  # alltoall dropped-id diagnostic (jitted)
        self._pipelined = False  # train.pipeline_overlap (prime/step/flush)
        self._cache_flush = None  # update-cache write-back program (jitted)
        self._flush_every = 0  # cache write-back cadence in train steps
        self._map_streams: dict = {}  # streaming=false table cache
        # retryable-I/O observability: failed attempts land next to
        # metrics.jsonl (process 0 only; set_failure_log is a no-op path-wise
        # on other processes because MetricLogger made the dir on p0)
        out_dir = log_dir or config.checkpoint_dir
        if out_dir and jax.process_index() == 0:
            _retry.set_failure_log(Path(out_dir) / "retries.jsonl",
                                   rotate_bytes=config.telemetry.log_rotate_bytes)
        # arm (or clear) the process-global deterministic fault injector from
        # THIS config — the kill marker lives in checkpoint_dir so "restart
        # the same command" converges instead of crash-looping
        _faults.configure(config.faults, config.checkpoint_dir or None)
        # [telemetry]: counters ride the step's return pytree and are fetched
        # at the existing log boundary (no extra host syncs); compile/memory
        # events stream to events.jsonl; the stall watchdog heartbeats to
        # heartbeat.jsonl from a daemon thread while fit() runs
        tele = config.telemetry
        self._counters_on = tele.counters
        self._flush_ctrs: dict = {}  # latest cache-flush counter fetch
        self._a2a_fill = None  # alltoall bucket-utilisation probe (jitted)
        self._watchdog = None
        if (tele.events or tele.stall_timeout_s > 0 or tele.trace) \
                and not out_dir:
            raise ValueError(
                "telemetry.events / telemetry.stall_timeout_s / "
                "telemetry.trace need a checkpoint_dir (or log_dir) to "
                "write events.jsonl / heartbeat.jsonl / trace-*.jsonl")
        if tele.events and jax.process_index() == 0:
            obs_events.configure(Path(out_dir) / "events.jsonl",
                                 rotate_bytes=tele.log_rotate_bytes)
        if tele.trace and jax.process_index() == 0:
            obs_trace.configure(Path(out_dir) / "trace",
                                rotate_bytes=tele.log_rotate_bytes)
        if tele.stall_timeout_s > 0 and jax.process_index() == 0:
            from tdfo_tpu.obs.watchdog import StallWatchdog

            self._watchdog = StallWatchdog(
                Path(out_dir) / "heartbeat.jsonl", tele.stall_timeout_s,
                rotate_bytes=tele.log_rotate_bytes)
        if config.checkpoint_dir:
            from tdfo_tpu.train.checkpoint import CheckpointManager

            self._ckpt = CheckpointManager(config.checkpoint_dir)
        self._build()

    # ------------------------------------------------------------- building

    def _build(self) -> None:
        cfg = self.config
        if cfg.model in ("twotower", "dlrm"):
            self._build_ctr()
        elif cfg.model == "bert4rec":
            self._build_bert4rec()
        else:
            raise ValueError(f"unknown model {cfg.model!r}")
        # model.tabulate-equivalent observability (jax-flax/models.py:154-155)
        if jax.process_index() == 0:
            from tdfo_tpu.utils.summary import param_summary

            if hasattr(self.state, "dense_params"):  # sparse/DMP regime
                summary = param_summary(
                    self.state.dense_params, tables=self.state.tables,
                    coll=self.coll, title=f"{cfg.model} parameters",
                )
            else:
                summary = param_summary(self.state.params,
                                        title=f"{cfg.model} parameters")
            print(summary, flush=True)

    def _set_ctr_streams(self) -> None:
        cfg = self.config
        if cfg.write_format == "tfrecord":
            from tdfo_tpu.data.loader import TFRecordStream

            self._stream_cls = TFRecordStream
            to_tfr = lambda pat: pat.replace(".parquet", ".tfrecord")
            self._train_pattern = str(Path("tfrecord") / to_tfr(cfg.train_data))
            self._eval_pattern = str(Path("tfrecord") / to_tfr(cfg.eval_data))
        else:
            self._stream_cls = ParquetStream
            self._train_pattern = str(Path("parquet") / cfg.train_data)
            self._eval_pattern = str(Path("parquet") / cfg.eval_data)

    def _build_ctr(self) -> None:
        """CTR family.  TwoTower without model_parallel keeps the reference's
        dense regime (nn.Embed tables, dense AdamW).  TwoTower with
        model_parallel — and DLRM always — run the DMP regime: tables in a
        ShardedEmbeddingCollection with the row-sparse in-backward optimizer
        (``torchrec/train.py:235-254`` parity, O(batch) optimizer traffic)."""
        cfg = self.config
        self._set_ctr_streams()
        if cfg.model == "twotower" and not cfg.model_parallel:
            self._build_twotower_dense()
        else:
            self._build_ctr_sparse()

    def _build_twotower_dense(self) -> None:
        from tdfo_tpu.core.precision import DynamicLossScale, compute_dtype
        from tdfo_tpu.models.twotower import init_twotower

        cfg = self.config
        dtype = compute_dtype(cfg.mixed_precision)
        model, params = init_twotower(
            jax.random.key(cfg.seed), cfg.size_map, cfg.embed_dim, dtype=dtype
        )
        loss_scale = (
            DynamicLossScale.create()
            if cfg.mixed_precision and cfg.loss_scale == "dynamic"
            and dtype == jnp.float16
            else None
        )
        state = TrainState.create(
            apply_fn=model.apply,
            params=params,
            tx=make_adamw(cfg.learning_rate, cfg.weight_decay),
            loss_scale=loss_scale,
        )
        if cfg.ps_min_shard_bytes > 0:
            # PS-strategy parity (tensorflow2/train_ps.py:55-58): partition
            # any variable big enough that a shard stays >= the threshold.
            # Under GSPMD "parameter servers" are just sharded arrays; the
            # optimizer state shards alongside each variable automatically
            # (the plan maps over the whole state pytree).
            from tdfo_tpu.parallel.sharding import (
                min_size_partitioner_rule,
                shard_state,
            )

            self.state = shard_state(
                state, self.mesh,
                min_size_partitioner_rule(self.mesh, cfg.ps_min_shard_bytes),
            )
        else:
            self.state = jax.device_put(state, NamedSharding(self.mesh, P()))
        inner = make_train_step(mesh=self.mesh, jit=False, with_aux=True)
        if cfg.steps_per_execution > 1:
            self.train_step = _wrap_auc_multi_step(
                inner, counters=self._counters_on)
        else:
            self.train_step = _wrap_auc_step(inner, counters=self._counters_on)
        self._train_auc_enabled = True
        self.eval_step = make_eval_step(mesh=self.mesh)
        self._eval_schema = _ctr_eval_schema(*_ctr_columns(cfg))
        self.eval_accum = _make_ctr_eval_accum(
            lambda state, batch: state.apply_fn({"params": state.params}, batch)
        )

    def _build_ctr_sparse(self) -> None:
        import optax as _optax

        from tdfo_tpu.core.precision import compute_dtype
        from tdfo_tpu.models.twotower import (
            TWOTOWER_CONTINUOUS,
            TwoTowerBackbone,
            ctr_embedding_specs,
        )
        from tdfo_tpu.ops.sparse import sparse_optimizer
        from tdfo_tpu.parallel.embedding import ShardedEmbeddingCollection
        from tdfo_tpu.train.ctr import ctr_sparse_forward, make_ctr_sparse_eval_step
        from tdfo_tpu.train.sparse_step import SparseTrainState, make_sparse_train_step

        from tdfo_tpu.models.twotower import TWOTOWER_CATEGORICAL

        cfg = self.config
        cat_cols, cont_cols = _ctr_columns(cfg)
        custom = bool(cfg.categorical_features)
        # every table's vocab must be present — a partial size_map should
        # fail with this message, not a KeyError downstream
        vocab_keys = cat_cols if custom else TWOTOWER_CATEGORICAL
        missing = [f for f in vocab_keys if f not in cfg.size_map]
        if missing:
            raise ValueError(
                f"{cfg.model} needs vocab sizes {missing} in size_map (run preprocessing)"
            )
        dtype = compute_dtype(cfg.mixed_precision)
        sharding = cfg.embedding_sharding if cfg.model_parallel else "replicated"
        if custom:
            from tdfo_tpu.models.dlrm import generic_embedding_specs

            specs = generic_embedding_specs(
                cfg.size_map, cat_cols, cfg.embed_dim, sharding,
                fused_threshold=cfg.effective_fused_threshold)
        else:
            specs = ctr_embedding_specs(
                cfg.size_map, cfg.embed_dim, sharding,
                fused_threshold=cfg.effective_fused_threshold)
        # storage dtype is a per-table property of the spec; the collection,
        # kernels and optimizer all read it from spec.dtype downstream
        specs = [
            dataclasses.replace(
                s, dtype=jnp.dtype(cfg.embeddings.dtype_for(s.name))
            )
            for s in specs
        ]
        plan = None
        hot_ids = None
        # the plan owns the update-cache decision when present (config
        # validation refuses hand-set cache_rows alongside a plan)
        cache_rows_eff = cfg.embeddings.cache_rows
        flush_every_eff = cfg.embeddings.flush_every
        if cfg.planner.plan:
            from tdfo_tpu.plan.planner import apply_plan_to_specs, load_plan

            # cost-model-chosen per-table placement: the plan artifact
            # rewrites each spec's sharding / fused storage / dtype and
            # carries its own hot-split id sets (config validation refuses
            # hot_vocab / cache_rows / hand-set dtypes alongside a plan, so
            # the plan is the single owner of the per-table levers)
            plan = load_plan(cfg.planner.plan)
            specs, hot_ids = apply_plan_to_specs(specs, plan)
            cache_rows_eff = int(plan.get("cache_rows", 0) or 0)
            if cache_rows_eff > 0:
                flush_every_eff = int(plan.get("cache_flush_every") or
                                      cfg.embeddings.flush_every)
                # the config-time cache gates only see embeddings.cache_rows;
                # a plan-carried cache must honor the same contracts
                if cfg.steps_per_execution != 1 or cfg.train.pipeline_overlap:
                    raise ValueError(
                        "the sharding plan enables the update cache "
                        f"(cache_rows = {cache_rows_eff}), which requires "
                        "steps_per_execution = 1 and train.pipeline_overlap "
                        "= false — adjust the config or re-plan")
        if cfg.embeddings.hot_vocab > 0:
            from tdfo_tpu.data.hot_ids import load_hot_ids

            artifact = load_hot_ids(cfg.data_dir)
            if artifact is None:
                raise ValueError(
                    "embeddings.hot_vocab > 0 but no hot_ids.json under "
                    f"{cfg.data_dir!r} — re-run preprocessing with this "
                    "config to emit the hot/cold remap artifact"
                )
            # the artifact keys by feature/column name; keep only tables this
            # model actually serves (a schema subset is fine, the rest of the
            # artifact is simply unused)
            served = {f for s in specs for f in s.features} | {s.name for s in specs}
            hot_ids = {k: v for k, v in artifact.items() if k in served} or None
        coll = ShardedEmbeddingCollection(
            specs,
            mesh=self.mesh,
            a2a_capacity_factor=cfg.a2a_capacity_factor or None,
            stack_tables=cfg.stack_tables,
            fused_kind=cfg.sparse_optimizer,
            hot_ids=hot_ids,
            grouped_a2a=cfg.embeddings.grouped_a2a,
            cache_rows=cache_rows_eff,
        )
        # hot/cold checkpoints are only loadable under the SAME hot sets —
        # stamp the digests into the checkpoint sidecar so a mismatched
        # restore refuses instead of silently mis-routing rows.  Same for
        # storage dtypes: a bf16-stored table restored into an f32 run (or
        # vice versa) would silently change every subsequent update, so the
        # stamp pins them.  Defaults-only runs keep the stamp absent — their
        # sidecars stay byte-compatible with pre-dtype checkpoints.
        stamps: dict[str, Any] = {}
        if coll.hot_ids:
            stamps["hot_ids"] = coll.hot_digest()
        tstamp = {s.name: jnp.dtype(s.dtype).name for s in specs}
        if (any(v != "float32" for v in tstamp.values())
                or cfg.embeddings.slot_dtype != "float32"):
            stamps["table_dtype"] = tstamp
            stamps["slot_dtype"] = cfg.embeddings.slot_dtype
        if any(v == "int8" for v in tstamp.values()):
            # int8 state carries extra __qscale__/ arrays in state.tables;
            # stamp their layout so a restore into a run that would lay the
            # sidecar out differently (or not at all) refuses loudly
            from tdfo_tpu.ops.quant import QSCALE_LAYOUT

            stamps["qscale_layout"] = QSCALE_LAYOUT
            # fused int8 arrays pack the sidecar IN-LINE (byte-container fat
            # lines, no __qscale__/ entry): stamp per-array storage so a
            # legacy int8-unfused checkpoint refuses to restore into an
            # int8-fused run and vice versa.  Unfused int8 runs add no key,
            # keeping their sidecars byte-identical to pre-fused-int8 ones.
            fat_inline = {
                s.name: "fat-inline" for s in specs
                if jnp.dtype(s.dtype) == jnp.int8 and s.fused}
            if fat_inline:
                stamps["qscale_storage"] = fat_inline
        if cache_rows_eff > 0:
            # the cache arrays live in state.slots: a cached checkpoint
            # cannot restore into a cache-off run (or vice versa, or across
            # cache_rows), so stamp both knobs — flush_every too, so the
            # restored run's flush cadence matches what the operator (or
            # the plan) asked for rather than silently inheriting the
            # sidecar-less default
            stamps["update_cache"] = {
                "cache_rows": int(cache_rows_eff),
                "flush_every": int(flush_every_eff),
            }
        if plan is not None:
            from tdfo_tpu.plan.planner import plan_digest

            # a checkpoint written under a plan pairs the whole state
            # layout (shardings, fat lines, hot heads, dtypes) with that
            # placement; stamp the plan digest so a restore under a
            # different plan — or none — refuses instead of mis-routing
            stamps["sharding_plan"] = plan_digest(plan)
        self._ckpt_stamps = stamps or None
        k_tables, k_dense = jax.random.split(jax.random.key(cfg.seed))
        tables = coll.init(k_tables)
        if cfg.model == "dlrm":
            from tdfo_tpu.models.dlrm import DLRMBackbone

            backbone = DLRMBackbone(embed_dim=cfg.embed_dim, dtype=dtype,
                                    cat_columns=cat_cols,
                                    cont_columns=cont_cols)
        else:
            backbone = TwoTowerBackbone(embed_dim=cfg.embed_dim, dtype=dtype)
        dummy_embs = {
            f: jnp.zeros((1, cfg.embed_dim), jnp.float32) for f in coll.features()
        }
        dummy_cont = {c: jnp.zeros((1,), jnp.float32) for c in cont_cols}
        dense = backbone.init(k_dense, dummy_embs, dummy_cont)["params"]
        self.coll = coll
        self.state = _commit_replicated(SparseTrainState.create(
            dense_params=dense,
            tx=_optax.adamw(cfg.learning_rate, weight_decay=cfg.weight_decay),
            tables=tables,
            # small_vocab_threshold stays at its own default: the one-hot
            # tier's viability is a fixed TPU property, while
            # fused_table_threshold is a storage-layout choice — one knob
            # must not drag the other
            sparse_opt=sparse_optimizer(
                cfg.sparse_optimizer, lr=cfg.learning_rate,
                weight_decay=cfg.weight_decay,
                slot_dtype=cfg.embeddings.slot_dtype,
            ),
        ), self.mesh)
        if cache_rows_eff > 0:
            # device-resident update cache: empty caches ride state.slots
            # (kill/resume, NaN-rollback snapshots and donation all cover
            # them for free); the coalesced write-back runs as a SEPARATE
            # jitted program every flush_every steps + before checkpoint/
            # eval/export, so train-step jaxprs carry no big-table scatter
            from tdfo_tpu.train.sparse_step import make_cache_flush_fn

            caches = coll.init_caches(self.state.tables,
                                      self.state.sparse_opt)
            if caches:
                self.state = dataclasses.replace(
                    self.state, slots={**self.state.slots, **caches})
                self._cache_flush = make_cache_flush_fn(
                    mesh=coll.mesh, counters=self._counters_on)
                self._flush_every = flush_every_eff
        if cfg.train.pipeline_overlap:
            # TrainPipelineSparseDist parity: batch N+1's input-dist issues
            # inside the jitted step ahead of batch N's fwd/bwd/update.  The
            # epoch loop primes on the first batch and flushes the last.
            from tdfo_tpu.train.sparse_step import (
                make_pipelined_sparse_train_step,
            )

            if cfg.dedup_lookup:
                raise ValueError(
                    "dedup_lookup (gspmd-only) does not compose with "
                    "train.pipeline_overlap")
            pipe = make_pipelined_sparse_train_step(
                coll, ctr_sparse_forward(backbone, with_logits=True),
                jit=False, with_aux=True,
            )
            self._pipelined = True
            self._prime_step, self.train_step, self._flush_step = (
                _wrap_auc_pipelined(pipe, donate_state=False,
                                    counters=self._counters_on))
        else:
            inner = make_sparse_train_step(
                coll, ctr_sparse_forward(backbone, with_logits=True),
                mode=cfg.lookup_mode, jit=False, with_aux=True,
                dedup_lookup=cfg.dedup_lookup,
            )
            if cfg.steps_per_execution > 1:
                self.train_step = _wrap_auc_multi_step(
                    inner, donate_state=False, counters=self._counters_on)
            else:
                self.train_step = _wrap_auc_step(
                    inner, donate_state=False, counters=self._counters_on)
        self._train_auc_enabled = True
        self.eval_step = make_ctr_sparse_eval_step(coll, backbone, mode=cfg.lookup_mode)
        self._eval_schema = _ctr_eval_schema(cat_cols, cont_cols)
        features, mode = list(coll.features()), cfg.lookup_mode
        if (mode == "alltoall" and cfg.a2a_capacity_factor
                and cfg.steps_per_execution == 1):
            # a finite capacity factor silently zeroes overflowed ids under
            # skew: surface the dropped-id count in the JSONL log
            # (steps_per_execution > 1 logs stacked chunks whose leading dim
            # is steps, not batch — skipped there)
            self._a2a_overflow = jax.jit(lambda st, bt: coll.a2a_overflow(
                st.tables, {f: bt[f] for f in features}))
        if (mode == "alltoall" and self._counters_on
                and cfg.steps_per_execution == 1):
            # telemetry companion of the capacity knob: bucket fill fraction
            # + dropped ids, logged alongside the step counters
            self._a2a_fill = jax.jit(lambda st, bt: coll.a2a_fill_stats(
                st.tables, {f: bt[f] for f in features}))

        def sparse_logits(state, batch):
            embs = coll.lookup(state.tables, {f: batch[f] for f in features}, mode=mode)
            return backbone.apply({"params": state.dense_params}, embs, batch)

        self.eval_accum = _make_ctr_eval_accum(sparse_logits)

    def _build_bert4rec(self) -> None:
        from tdfo_tpu.models.bert4rec import Bert4RecConfig, make_sharded_bert4rec
        from tdfo_tpu.ops.sparse import sparse_optimizer
        from tdfo_tpu.train.seq import bert4rec_sparse_forward
        from tdfo_tpu.train.sparse_step import SparseTrainState, make_sparse_train_step

        cfg = self.config
        n_items = int(cfg.size_map.get("n_items", cfg.size_map.get("item", 0)))
        if not n_items:
            raise ValueError("bert4rec needs n_items in size_map (run preprocessing)")
        self.model_cfg = Bert4RecConfig(
            n_items=n_items,
            max_len=cfg.max_len,
            embed_dim=cfg.embed_dim,
            n_heads=cfg.n_heads,
            n_layers=cfg.n_layers,
            dropout=cfg.dropout,
        )
        sharding = cfg.embedding_sharding if cfg.model_parallel else "replicated"
        self.coll, tables, self.backbone, dense = make_sharded_bert4rec(
            jax.random.key(cfg.seed), self.model_cfg, self.mesh,
            sharding=sharding, attn=cfg.attn,
            fused_threshold=cfg.effective_fused_threshold,
            fused_kind=cfg.sparse_optimizer,
            a2a_capacity_factor=cfg.a2a_capacity_factor or None,
            ring_block_k=cfg.ring_block_k or None,
            tp_heads=cfg.tensor_parallel and cfg.attn in ("ring", "ring_flash"),
            grouped_a2a=cfg.embeddings.grouped_a2a,
        )
        if cfg.tensor_parallel:
            from tdfo_tpu.parallel.sharding import megatron_tp_rule, shard_state

            # optax moments mirror the params and inherit these shardings;
            # n_heads licenses the attention (head-parallel) split and
            # rejects head-indivisible meshes at plan time.  attn="flash"
            # keeps attention replicated (n_heads=None): the Pallas kernel
            # has no GSPMD partitioning rule, so head-sharded params would
            # all-gather inside every layer.
            dense = shard_state(
                dense, self.mesh,
                megatron_tp_rule(
                    self.mesh,
                    n_heads=cfg.n_heads if cfg.attn != "flash" else None,
                ),
            )
        self.state = _commit_replicated(SparseTrainState.create(
            dense_params=dense,
            tx=optax.adamw(cfg.learning_rate, weight_decay=cfg.weight_decay),
            tables=tables,
            # small_vocab_threshold stays at its own default: the one-hot
            # tier's viability is a fixed TPU property, while
            # fused_table_threshold is a storage-layout choice — one knob
            # must not drag the other
            sparse_opt=sparse_optimizer(
                cfg.sparse_optimizer, lr=cfg.learning_rate,
                weight_decay=cfg.weight_decay,
            ),
        ), self.mesh)
        # jagged mode: batches arrive as (values, lengths) pairs packed per
        # host; jagged_to_dense runs INSIDE the jitted step (fbgemm
        # jagged_2d_to_dense parity, torchrec/models.py:168-172)
        transform = None
        if cfg.jagged:
            from tdfo_tpu.data.jagged import jagged_to_dense_per_host
            from tdfo_tpu.models.bert4rec import PAD_ID

            t_len, n_hosts = cfg.max_len, jax.process_count()

            def transform(batch):
                item = jagged_to_dense_per_host(
                    batch["item_values"], batch["item_lengths"], t_len,
                    PAD_ID, n_hosts)
                label = jagged_to_dense_per_host(
                    batch["label_values"], batch["item_lengths"], t_len,
                    PAD_ID, n_hosts)
                return {"item": item, "label": label}

        if cfg.train.pipeline_overlap:
            from tdfo_tpu.train.sparse_step import (
                make_pipelined_sparse_train_step,
            )

            if cfg.dedup_lookup:
                raise ValueError(
                    "dedup_lookup (gspmd-only) does not compose with "
                    "train.pipeline_overlap")
            if self._counters_on:
                # counter collection needs the UNJITTED prime/step/flush (a
                # collector cannot reach across an inner jit boundary); the
                # off path below keeps the original construction untouched
                pipe = make_pipelined_sparse_train_step(
                    self.coll, bert4rec_sparse_forward(self.backbone),
                    jit=False, batch_transform=transform,
                )
                self._pipelined = True
                self._prime_step = jax.jit(pipe.prime)
                self.train_step = _wrap_counters_step(pipe.step)
                self._flush_step = _wrap_counters_step(pipe.flush)
            else:
                pipe = make_pipelined_sparse_train_step(
                    self.coll, bert4rec_sparse_forward(self.backbone),
                    donate=False, batch_transform=transform,
                )
                self._pipelined = True
                self._prime_step = pipe.prime
                self.train_step = pipe.step
                self._flush_step = pipe.flush
        elif cfg.steps_per_execution > 1:
            inner = make_sparse_train_step(
                self.coll, bert4rec_sparse_forward(self.backbone),
                mode=cfg.lookup_mode, jit=False, batch_transform=transform,
                dedup_lookup=cfg.dedup_lookup,
            )
            if self._counters_on:
                self.train_step = _wrap_counters_multi_step(inner)
            else:
                self.train_step = make_multi_step(inner, donate_state=False)
        elif self._counters_on:
            self.train_step = _wrap_counters_step(make_sparse_train_step(
                self.coll, bert4rec_sparse_forward(self.backbone),
                mode=cfg.lookup_mode, jit=False, batch_transform=transform,
                dedup_lookup=cfg.dedup_lookup,
            ))
        else:
            self.train_step = make_sparse_train_step(
                self.coll, bert4rec_sparse_forward(self.backbone),
                mode=cfg.lookup_mode, donate=False, batch_transform=transform,
                dedup_lookup=cfg.dedup_lookup,
            )
        self._train_auc_enabled = False  # AUC is a binary-CTR metric
        self._dropout_rng = jax.random.key(cfg.seed + 1)
        if (cfg.lookup_mode == "alltoall" and cfg.a2a_capacity_factor
                and not cfg.jagged and cfg.steps_per_execution == 1):
            # surface the capacity knob's silent failure mode (dropped ids
            # -> zero vectors) in the JSONL log
            seq_coll = self.coll
            self._a2a_overflow = jax.jit(lambda st, bt: seq_coll.a2a_overflow(
                st.tables, {"item": bt["item"]}))
        if (cfg.lookup_mode == "alltoall" and self._counters_on
                and not cfg.jagged and cfg.steps_per_execution == 1):
            fill_coll = self.coll
            self._a2a_fill = jax.jit(lambda st, bt: fill_coll.a2a_fill_stats(
                st.tables, {"item": bt["item"]}))
        self._stream_cls = ParquetStream  # seq ETL writes parquet only
        self._train_pattern = str(Path("parquet_bert4rec") / cfg.train_data)
        self._eval_pattern = str(Path("parquet_bert4rec") / cfg.eval_data)

        # eval accumulator built ONCE (a fresh jit closure per eval epoch
        # would recompile every time), honouring the configured lookup
        # program, and folding metrics on device — multihost-global by
        # construction (see _make_ctr_eval_accum's docstring).
        from tdfo_tpu.data.seq_preprocessing import EVAL_NEG_NUM
        from tdfo_tpu.models.bert4rec import key_padding_mask
        from tdfo_tpu.train.seq import score_candidates

        self._eval_schema = {
            "seqs": (np.int32, (cfg.max_len,)),
            "cands": (np.int32, (EVAL_NEG_NUM + 1,)),
        }
        coll, backbone, mode = self.coll, self.backbone, cfg.lookup_mode

        @jax.jit
        def eval_accum(state, batch, acc):
            w = batch["_weight"]
            embs = coll.lookup(state.tables, {"item": batch["seqs"]}, mode=mode)
            logits = backbone.apply(
                {"params": state.dense_params}, embs["item"],
                key_padding_mask(batch["seqs"]),
            )
            scores = score_candidates(logits, batch["cands"])
            labels = jnp.zeros_like(scores).at[:, 0].set(1.0)
            # ks from the same constant that seeds the accumulator dict
            m = recalls_and_ndcgs_for_ks(scores, labels, ks=self._METRIC_KS,
                                         row_weights=w)
            out = {"w_sum": acc["w_sum"] + w.sum()}
            for k, v in m.items():
                out[k] = acc[k] + v * w.sum()
            return out

        self.eval_accum = eval_accum

    # --------------------------------------------------------------- epochs

    def _stream(self, pattern: str, *, train: bool):
        cfg = self.config
        files = resolve_files(cfg.data_dir, pattern)
        # each host streams only its local slice of the global batch: the
        # data axis spans every host's devices, and prefetch_to_mesh
        # assembles the global array from per-process chunks.
        local_data = max(1, self.mesh.shape["data"] // jax.process_count())
        bsz = (cfg.per_device_train_batch_size if train
               else cfg.per_device_eval_batch_size) * local_data
        if not cfg.streaming:
            # map-style in-memory epochs (config streaming=false,
            # jax-flax/train.py:52-70 parity); table cached across epochs
            key = (pattern, bsz, train)
            if key not in self._map_streams:
                self._map_streams[key] = MapStream(
                    files, batch_size=bsz, shuffle=train, seed=cfg.seed,
                    drop_last=train,
                )
            return self._map_streams[key]
        return self._stream_cls(
            files,
            batch_size=bsz,
            shuffle=train,
            buffer_size=cfg.shuffle_buffer_size,
            seed=cfg.seed,
            drop_last=train,
            # eval shards are always fixed-length (padded seqs + candidate
            # lists); only the jagged TRAIN stream opts into object columns
            allow_ragged=train and cfg.model == "bert4rec" and cfg.jagged,
            num_workers=cfg.num_workers,
            max_bad_shards=cfg.max_bad_shards,
        )

    def _train_batches(self, epoch: int, skip: int = 0) -> Iterator[tuple[dict, int]]:
        """Yields ``(device_batch, n_steps_in_batch)``.

        With ``steps_per_execution > 1`` host batches are stacked into
        [K, B, ...] chunks and the whole chunk ships as one transfer feeding
        one compiled multi-step dispatch; a short tail chunk recompiles at
        most once per distinct K.

        ``skip`` resumes mid-epoch: the stream fast-forwards that many host
        batches (the checkpoint cursor's step count) before yielding, so the
        post-resume batch sequence is bit-identical to the uninterrupted
        epoch's tail.  With spe>1 the chunk BOUNDARIES shift relative to the
        uninterrupted run, but a chunk is a ``lax.scan`` of the same single
        step over the same ordered batches — state evolution is unchanged.
        """
        cfg = self.config
        stream = self._stream(self._train_pattern, train=True)
        stream.set_epoch(epoch)
        if skip:
            stream.load_state_dict({"seed": cfg.seed, "epoch": epoch,
                                    "batches_emitted": skip})
        if cfg.model == "bert4rec" and cfg.jagged:
            from tdfo_tpu.data.jagged import pack_rows

            cap = stream.batch_size * cfg.max_len  # static host capacity

            def pack(b):
                iv, il = pack_rows(list(b["train_interactions"]), cap)
                lv, ll = pack_rows(list(b["labels"]), cap)
                if (il != ll).any():  # data integrity, must survive python -O
                    raise ValueError(
                        "item/label window lengths diverged — mixed-version "
                        "or corrupted jagged shards"
                    )
                return {"item_values": iv, "item_lengths": il, "label_values": lv}

            renamed = (pack(b) for b in stream)
        elif cfg.model == "bert4rec":
            renamed = (
                {"item": b["train_interactions"], "label": b["labels"]} for b in stream
            )
        else:
            renamed = iter(stream)
        inj = _faults.active()
        if inj is not None and inj.spec.nan_at_step:
            # deterministic NaN injection keyed on run-global data position
            # (stable across resume and steps_per_execution regrouping);
            # _logged_steps still holds the epoch-start value here — the
            # epoch-end += happens after this generator is exhausted
            base, poison = self._logged_steps, inj.poison_batch

            def poisoned(gen, pos):
                for b in gen:
                    pos += 1
                    yield poison(b, base + pos)

            renamed = poisoned(renamed, skip)
        spe = cfg.steps_per_execution
        if spe <= 1:
            for batch in prefetch_to_mesh(renamed, self.mesh, P("data")):
                yield batch, 1
            return

        def stacked():
            chunk: list[dict] = []
            for b in renamed:
                chunk.append(b)
                if len(chunk) == spe:
                    yield {k: np.stack([c[k] for c in chunk]) for k in chunk[0]}
                    chunk = []
            if chunk:
                yield {k: np.stack([c[k] for c in chunk]) for k in chunk[0]}

        for stack in prefetch_to_mesh(stacked(), self.mesh, P(None, "data")):
            yield stack, int(next(iter(stack.values())).shape[0])

    def _jit_ctx(self):
        """jit_xla = false -> the loop runs under jax.disable_jit(): op-by-op
        eager execution for numerics debugging (TF jit_compile=False parity)."""
        import contextlib

        if self.config.jit_xla is False:
            return jax.disable_jit()
        return contextlib.nullcontext()

    def train_epoch(self, epoch: int, *, start_step: int = 0,
                    loss_sum: float = 0.0, contributed: int = 0) -> float:
        with self._jit_ctx():
            return self._train_epoch(epoch, start_step=start_step,
                                     loss_sum=loss_sum, contributed=contributed)

    def _train_epoch(self, epoch: int, *, start_step: int = 0,
                     loss_sum: float = 0.0, contributed: int = 0) -> float:
        """One training epoch, resumable at step granularity.

        ``start_step`` (plus the matching partial ``loss_sum``/``contributed``
        from the checkpoint cursor) restarts the epoch at an exact batch; the
        stream fast-forwards, so the tail is bit-identical to an
        uninterrupted epoch.  Device losses queue in a pending window and are
        fetched together at log/checkpoint boundaries — the same sync cadence
        as before (a per-step ``float()`` would serialise dispatch and defeat
        the double-buffered prefetch), so the non-finite guard below adds NO
        extra host round-trips.

        Non-finite guard: with ``nonfinite_tolerance`` = K > 0, a known-good
        (state, train-AUC, loss-sums) snapshot is kept ON DEVICE — refreshed
        every ``snapshot_every_n_steps`` once the window since the last
        snapshot verified finite — and K consecutive non-finite batch losses
        roll back to it, SKIPPING the offending batch window (data position
        stays monotone; ``state.step`` rewinds).  Each rollback emits a
        ``rollback`` record to metrics.jsonl.  The snapshot costs one extra
        state copy in device memory; set ``nonfinite_tolerance = 0`` to
        disable the guard (and the copy) on memory-tight runs.
        """
        cfg = self.config
        inj = _faults.active()
        # host-loop wall time (throughput) via obs.trace's clock helpers —
        # the single sanctioned monotonic-differencing site (time.time /
        # perf_counter / raw monotonic differencing is rejected by
        # tests/test_quality.py)
        t0 = obs_trace.clock()
        n_steps = start_step
        step_ctrs: dict = {}  # latest step's device counter pytree
        # update-cache write-back schedule: the periodic flush runs async
        # (overflow counters queue like the pending losses and are verified
        # at the same cadence — no extra host sync); checkpoint/eval/epoch
        # boundaries flush synchronously
        flush_n = self._flush_every if self._cache_flush is not None else 0
        next_flush = (n_steps // flush_n + 1) * flush_n if flush_n else None
        pending_over: list[dict] = []
        next_log = start_step + cfg.log_every_n_steps
        profiled = cfg.profile and epoch == 0 and jax.process_index() == 0
        # train-side streaming AUC on this epoch's predictions, folded ON
        # DEVICE from the step's aux logits — no second forward pass
        # (jax-flax/train_dp.py:190,219-220 parity).  Not persisted in the
        # cursor (device histograms): after a mid-epoch resume the epoch AUC
        # covers post-resume steps only.  State evolution is unaffected.
        train_auc = AUC.empty() if self._train_auc_enabled else None
        # pipeline_overlap carry: (transformed batch, input-dist ctx) one
        # batch ahead of training.  Not persisted in cursors: n_steps counts
        # TRAINED batches, so a resume fast-forwards past exactly those and
        # re-primes on the batch the carry held — state evolution is
        # bit-identical to the uninterrupted run.
        carry = None
        tol = cfg.nonfinite_tolerance
        guard = tol > 0
        # pending: (device loss, steps in batch, global data step)
        pending: list[tuple[jax.Array, int, int]] = []
        pending_steps = 0
        flush_every = max(1, cfg.log_every_n_steps)
        consec_bad = 0
        snap = None  # (state, auc, loss_sum, contributed, global data step)
        steps_at_snap = n_steps
        if guard:
            snap = (_copy_tree(self.state), _copy_tree(train_auc),
                    loss_sum, contributed, self._logged_steps + n_steps)

        def flush_checks() -> None:
            """Fetch queued losses: fold finite ones into the epoch sums,
            roll back on ``tol`` consecutive non-finite steps, refresh the
            snapshot after a clean window."""
            nonlocal loss_sum, contributed, consec_bad, snap, train_auc
            nonlocal steps_at_snap, pending_steps
            for over in pending_over:
                _check_cache_overflow(over)
            pending_over.clear()
            rolled = False
            for loss_dev, k, gstep in pending:
                v = float(loss_dev)
                if math.isfinite(v):
                    consec_bad = 0
                    loss_sum += v * k
                    contributed += k
                    continue
                consec_bad += k  # non-finite losses never fold into the sums
                if not guard or consec_bad < tol:
                    continue
                # bounded rollback: restore the last known-good snapshot
                # (device copy, no disk) and keep consuming data FORWARD —
                # the poisoned window is skipped, not retried
                state_c, auc_c, ls, ct, sg = snap
                self.state = _copy_tree(state_c)  # snapshot must survive donation
                train_auc = _copy_tree(auc_c)
                loss_sum, contributed = ls, ct
                consec_bad = 0
                rolled = True
                self.logger.log(
                    epoch=epoch, rollback=1, global_step=gstep,
                    restored_to_step=sg, skipped_steps=gstep - sg,
                    nonfinite_loss=v,
                )
                break  # later pending losses came from the poisoned lineage
            pending.clear()
            pending_steps = 0
            if (guard and not rolled and consec_bad == 0
                    and n_steps - steps_at_snap >= cfg.snapshot_every_n_steps):
                snap = (_copy_tree(self.state), _copy_tree(train_auc),
                        loss_sum, contributed, self._logged_steps + n_steps)
                steps_at_snap = n_steps

        ckpt_n = cfg.checkpoint_every_n_steps if self._ckpt is not None else 0
        next_ckpt = (n_steps // ckpt_n + 1) * ckpt_n if ckpt_n else None
        loss = None
        try:
            for batch, k in self._train_batches(epoch, skip=start_step):
                if profiled is True and n_steps >= 10:
                    jax.profiler.start_trace(str(Path(cfg.checkpoint_dir or ".") / "profile"))
                    profiled = "tracing"
                if self._pipelined and carry is None:
                    # pipeline prime: the first batch's input-dist only;
                    # training starts next iteration
                    carry = self._prime_step(batch)
                    continue
                if self._pipelined:
                    if cfg.model == "bert4rec":
                        out = self.train_step(
                            self.state, batch, carry, self._dropout_rng)
                        self.state, loss, carry = out[:3]
                    else:
                        out = self.train_step(
                            self.state, batch, carry, train_auc)
                        self.state, loss, carry, train_auc = out[:4]
                elif cfg.model == "bert4rec":
                    out = self.train_step(self.state, batch, self._dropout_rng)
                    self.state, loss = out[:2]
                else:
                    out = self.train_step(self.state, batch, train_auc)
                    self.state, loss, train_auc = out[:3]
                if self._counters_on:
                    # DEVICE dict (the step's extra return) — floats are
                    # pulled at the log boundary with the train_loss fetch
                    step_ctrs = out[-1]
                n_steps += k
                gstep = self._logged_steps + n_steps
                if self._watchdog is not None:
                    self._watchdog.beat(gstep)
                pending.append((loss, k, gstep))
                pending_steps += k
                if next_flush is not None and n_steps >= next_flush:
                    # coalesced cache write-back: the ONLY big-table scatter
                    # in the cadence — one per flush_every steps
                    pending_over.append(self._run_cache_flush())
                    next_flush = (n_steps // flush_n + 1) * flush_n
                if pending_steps >= flush_every:
                    flush_checks()
                if profiled == "tracing" and n_steps >= 20:
                    jax.block_until_ready(loss)
                    jax.profiler.stop_trace()
                    profiled = False
                if next_ckpt is not None and n_steps >= next_ckpt:
                    # never persist an unverified window: flushing first means
                    # a detected-NaN state rolls back BEFORE the save; force
                    # overwrites a step id a prior (crashed) run already wrote
                    flush_checks()
                    # cache flush BEFORE the save (post-rollback state):
                    # checkpoints always hold flushed tables, so restores
                    # and exports never depend on cache contents
                    self._flush_cache_sync()
                    self._ckpt.save(
                        gstep, self.state, force=True,
                        cursor={"epoch": epoch, "step": n_steps,
                                "epoch_complete": False, "global_step": gstep,
                                "loss_sum": loss_sum,
                                "contributed": contributed},
                        stamps=self._ckpt_stamps,
                    )
                    next_ckpt = (n_steps // ckpt_n + 1) * ckpt_n
                if inj is not None:
                    inj.maybe_stall(gstep)  # host-side sleep (watchdog test)
                    inj.maybe_kill(gstep)  # after the save: ckpt is durable
                if n_steps >= next_log:
                    rec = dict(epoch=epoch, step=n_steps, train_loss=float(loss))
                    if self._a2a_overflow is not None:
                        # ids dropped by the finite a2a capacity THIS batch
                        # (zero vectors under skew — watch for quality decay)
                        rec["a2a_overflow_ids"] = int(
                            self._a2a_overflow(self.state, batch))
                    if self._counters_on:
                        # ONE host fetch of the latest step's counter pytree
                        # — the same boundary the train_loss float() above
                        # already syncs on, so the cadence is unchanged
                        for ck, cv in {**step_ctrs, **self._flush_ctrs}.items():
                            rec[ck] = float(cv)
                        for ck in [c for c in rec
                                   if c.endswith("cache_hit_rows")]:
                            base = ck[: -len("hit_rows")]
                            tot = rec[ck] + rec.get(base + "miss_rows", 0.0)
                            if tot:
                                rec[base + "hit_rate"] = rec[ck] / tot
                        if self._a2a_fill is not None:
                            fill, dropped = self._a2a_fill(self.state, batch)
                            rec["a2a_fill"] = float(fill)
                            rec["a2a_dropped_ids"] = int(dropped)
                    # TB charts need a run-global x (per-epoch `step` resets,
                    # which would fold multi-epoch curves back on themselves)
                    rec["global_step"] = gstep
                    self.logger.log(**rec)
                    # device-memory watermark at the log cadence (no-op on
                    # backends without memory_stats, e.g. spoofed CPU)
                    if obs_events.active():
                        obs_events.memory_snapshot()
                    # chunked counting can jump n_steps past several
                    # intervals; advance past n_steps so each interval logs
                    # at most once
                    next_log = n_steps + cfg.log_every_n_steps
            if self._pipelined and carry is not None:
                # drain the pipeline: the last carried batch trains here
                # (flush is prime's twin — together they shift every batch's
                # training one call later without changing its math)
                if cfg.model == "bert4rec":
                    out = self._flush_step(self.state, carry, self._dropout_rng)
                    self.state, loss = out[:2]
                else:
                    out = self._flush_step(self.state, carry, train_auc)
                    self.state, loss, train_auc = out[:3]
                carry = None
                n_steps += 1
                pending.append((loss, 1, self._logged_steps + n_steps))
                pending_steps += 1
        finally:
            if profiled == "tracing":
                # epoch ended (or raised) inside the trace window: close the
                # trace so the next epoch/run can profile again
                if loss is not None:
                    jax.block_until_ready(loss)
                jax.profiler.stop_trace()
        flush_checks()
        self._flush_cache_sync()  # epoch boundary: leave the tables flushed
        dt = obs_trace.elapsed_s(t0)
        ran = n_steps - start_step  # steps actually executed THIS session
        self._logged_steps += n_steps
        avg = loss_sum / contributed if contributed else 0.0
        extra: dict[str, float] = {}
        if train_auc is not None and n_steps:
            extra["train_auc"] = float(train_auc.result())
        self.logger.log(
            epoch=epoch, train_loss_epoch=avg, steps=n_steps,
            examples_per_sec=ran * cfg.per_device_train_batch_size
            * self.mesh.shape["data"] / max(dt, 1e-9),
            **extra,
        )
        return avg

    def _run_cache_flush(self) -> dict:
        """One cache write-back dispatch.  With telemetry counters on, the
        flush program returns a third element (the flush-scoped counter
        dict) — stash it for the next log boundary.  Returns overflow."""
        if self._counters_on:
            self.state, over, self._flush_ctrs = self._cache_flush(self.state)
        else:
            self.state, over = self._cache_flush(self.state)
        return over

    def _flush_cache_sync(self) -> None:
        """Write the update cache back NOW and verify zero admission
        overflow — the synchronous flush used at checkpoint, eval, and
        epoch boundaries (no-op when the cache is off)."""
        if self._cache_flush is None:
            return
        _check_cache_overflow(self._run_cache_flush())

    # ----------------------------------------------------------------- eval

    def evaluate(self, epoch: int) -> dict[str, float]:
        # the eval step reads state.tables directly; flush first so it
        # never sees values the cache holds (bit-equal to an eager run)
        self._flush_cache_sync()
        with self._jit_ctx():
            if self.config.model == "bert4rec":
                return self._evaluate_bert4rec(epoch)
            return self._evaluate_twotower(epoch)

    def _eval_batches(self, rename: Callable[[dict], dict] | None = None,
                      pattern: str | None = None) -> Iterator[dict]:
        """Padded, budgeted, mesh-sharded eval batches.

        Every host yields exactly ``max_batches_per_host()`` batches — short
        hosts (including hosts with NO eval rows at all) top up with
        zero-weight template batches synthesised from ``self._eval_schema``
        — so the jitted eval computation (a global-mesh program) runs in
        lockstep and never deadlocks (the drop_last=False twin of the
        train-loop invariant).  Real batches are restricted to the schema's
        keys so every host ships an identical pytree regardless of which
        extra columns its files carry.  Each batch has a ``_weight`` row
        mask.
        """
        stream = self._stream(pattern or self._eval_pattern, train=False)
        budget = stream.max_batches_per_host()
        bsz = stream.batch_size
        schema = self._eval_schema

        def template() -> dict[str, np.ndarray]:
            t = {k: np.zeros((bsz, *shape), dtype) for k, (dtype, shape) in schema.items()}
            t["_weight"] = np.zeros((bsz,), np.float32)
            return t

        def gen():
            n = 0
            for raw in stream:
                if rename is not None:
                    try:
                        raw = rename(raw)
                    except KeyError as e:
                        raise ValueError(
                            f"eval shard is missing column {e} "
                            f"(has {sorted(raw)}); it was likely written by "
                            "an older or mismatched preprocessing run — "
                            "re-run preprocessing for this data_dir"
                        ) from None
                # cast to the schema dtypes: loaders differ (tfrecord decodes
                # ints as int64, parquet as int32/int8) and real batches must
                # be aval-identical to synthesized templates on EVERY host
                missing = schema.keys() - raw.keys()
                if missing:
                    raise ValueError(
                        f"eval shard is missing columns {sorted(missing)} "
                        f"(has {sorted(raw)}); it was likely written by an "
                        "older or mismatched preprocessing run — re-run "
                        "preprocessing for this data_dir"
                    )
                real = {
                    k: np.asarray(raw[k]).astype(dtype, copy=False)
                    for k, (dtype, _) in schema.items()
                }
                batch, w = pad_batch(real, bsz)
                batch = dict(batch, _weight=w)
                n += 1
                yield batch
            while n < budget:
                yield template()
                n += 1

        yield from prefetch_to_mesh(gen(), self.mesh, P("data"))

    def _evaluate_twotower(self, epoch: int) -> dict[str, float]:
        """Eval metrics accumulate ON DEVICE as a replicated pytree; the host
        fetches floats once at the end.  Every reduction is global across the
        whole mesh (multi-host included), so this is the ``all_gather_object``
        capability (``torchrec/train.py:108-111``) with zero host collectives
        — and no per-batch ``float()`` sync stalling the eval pipeline."""
        acc = {
            "loss_sum": jnp.zeros(()),
            "w_sum": jnp.zeros(()),
            "auc": AUC.empty(),
        }
        for batch in self._eval_batches():
            acc = self.eval_accum(self.state, batch, acc)
            if self._watchdog is not None:  # eval batches count as liveness
                self._watchdog.beat(self._logged_steps)
        w = max(float(acc["w_sum"]), 1.0)
        metrics = {
            "eval_loss": float(acc["loss_sum"]) / w,
            "auc": float(acc["auc"].result()),
        }
        self.logger.log(epoch=epoch, **metrics)
        return metrics

    _METRIC_KS = (10, 20, 50)

    def _evaluate_bert4rec(self, epoch: int, pattern: str | None = None,
                           prefix: str = "") -> dict[str, float]:
        acc: dict[str, jax.Array] = {"w_sum": jnp.zeros(())}
        for k in self._METRIC_KS:
            acc[f"Recall@{k}"] = jnp.zeros(())
            acc[f"NDCG@{k}"] = jnp.zeros(())
        rename = lambda raw: {"seqs": raw["eval_seqs"], "cands": raw["candidate_items"]}
        for batch in self._eval_batches(rename, pattern=pattern):
            acc = self.eval_accum(self.state, batch, acc)
            if self._watchdog is not None:  # eval batches count as liveness
                self._watchdog.beat(self._logged_steps)
        w = max(float(acc.pop("w_sum")), 1.0)
        metrics = {prefix + k: float(v) / w for k, v in acc.items()}
        self.logger.log(epoch=epoch, **metrics)
        return metrics

    def evaluate_test(self) -> dict[str, float]:
        """Final held-out TEST evaluation (bert4rec leave-last-one).

        Beats the reference's dead code: ``train_val_test`` never tests
        despite its name (``torchrec/train.py:147-177``).  Returns {} when
        the data dir has no test shards (older preprocessing runs) or the
        knob is disabled.  Runs the same lockstep-budgeted eval machinery,
        so multi-host meshes stay in step.
        """
        cfg = self.config
        if cfg.model != "bert4rec" or not cfg.test_data:
            return {}
        pattern = str(Path("parquet_bert4rec") / cfg.test_data)
        try:
            resolve_files(cfg.data_dir, pattern)
        except FileNotFoundError:
            self.logger.log(test_split="absent (re-run preprocess-seq to write it)")
            return {}
        with self._jit_ctx():
            return self._evaluate_bert4rec(
                epoch=self.config.n_epochs, pattern=pattern, prefix="test_"
            )

    # ------------------------------------------------------------------ fit

    def fit(self) -> dict[str, float]:
        """Train/eval until ``n_epochs``, resuming from the newest checkpoint.

        Resume is cursor-aware: a mid-epoch checkpoint (written every
        ``checkpoint_every_n_steps``) re-enters its epoch at the exact batch
        — the data stream fast-forwards, so a killed-and-restarted run
        replays the identical batch sequence and lands on bit-identical
        state.  Checkpoints without a cursor sidecar are the legacy
        epoch-indexed format and resume at the following epoch."""
        cfg = self.config
        if self._watchdog is not None:
            self._watchdog.start()
        start_epoch = 0
        resume = {"step": 0, "loss_sum": 0.0, "contributed": 0}
        if self._ckpt is not None:
            restored = self._ckpt.restore(self.state,
                                          stamps=self._ckpt_stamps)
            if restored is not None:
                step_id, self.state, cursor = restored
                if cursor is None:
                    # legacy epoch-indexed checkpoint: step_id IS the epoch
                    start_epoch = step_id + 1
                    self.logger.log(resumed_from_epoch=step_id)
                elif cursor.get("epoch_complete"):
                    start_epoch = int(cursor["epoch"]) + 1
                    self._logged_steps = int(cursor["global_step"])
                    self.logger.log(resumed_from_epoch=int(cursor["epoch"]),
                                    global_step=self._logged_steps)
                else:
                    start_epoch = int(cursor["epoch"])
                    resume = {"step": int(cursor["step"]),
                              "loss_sum": float(cursor.get("loss_sum", 0.0)),
                              "contributed": int(cursor.get("contributed", 0))}
                    self._logged_steps = (int(cursor["global_step"])
                                          - resume["step"])
                    self.logger.log(resumed_mid_epoch=start_epoch,
                                    step=resume["step"],
                                    global_step=int(cursor["global_step"]))
        metrics: dict[str, float] = {}
        try:
            if cfg.model == "bert4rec" and start_epoch == 0 and not resume["step"]:
                # pre-training validation sanity floor (torchrec/train.py:159)
                self.evaluate(epoch=-1)
            for epoch in range(start_epoch, cfg.n_epochs):
                self.train_epoch(epoch, start_step=resume["step"],
                                 loss_sum=resume["loss_sum"],
                                 contributed=resume["contributed"])
                resume = {"step": 0, "loss_sum": 0.0, "contributed": 0}
                metrics = self.evaluate(epoch)
                if epoch == start_epoch and obs_events.active():
                    # every program of the steady-state cadence (train step,
                    # cache flush, eval accum) has compiled by the end of the
                    # first epoch+eval cycle; later compiles are retraces
                    obs_events.mark_warmup()
                if self._ckpt is not None and (
                    (epoch + 1) % cfg.checkpoint_every_n_epochs == 0
                    or epoch == cfg.n_epochs - 1
                ):
                    # checkpoint ids live in the global data-step namespace
                    # (shared with mid-epoch saves); force overwrites a
                    # mid-epoch save that landed on the same step
                    gstep = self._logged_steps
                    self._ckpt.save(
                        gstep, self.state, force=True,
                        cursor={"epoch": epoch, "step": 0,
                                "epoch_complete": True, "global_step": gstep},
                        stamps=self._ckpt_stamps,
                    )
            # final held-out test evaluation (bert4rec; no-op elsewhere)
            metrics.update(self.evaluate_test())
        finally:
            # crash or success: release the JSONL/TB handles and the orbax
            # manager's background machinery (both leaked on error before),
            # stop the watchdog thread, and detach the compile-event handler
            # (with the run-peak device-memory watermark as its last record)
            if self._watchdog is not None:
                self._watchdog.stop()
            if obs_events.active():
                obs_events.record("run_summary",
                                  peak_bytes=obs_events.peak_memory())
                obs_events.configure(None)
            if obs_trace.active():
                obs_trace.configure(None)
            self.logger.close()
            if self._ckpt is not None:
                self._ckpt.close()
        return metrics
