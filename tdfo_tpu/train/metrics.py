"""Evaluation metrics — in-framework, jit-friendly, sharding-aware.

The reference borrows ``tf.keras.metrics.AUC`` even in its jax recipe
(``jax-flax/train_dp.py:190,223``) and hand-rolls Recall@K/NDCG@K in torch
(``torchrec/train.py:61-78``).  Here both live in-framework:

  * :func:`binary_auc` — exact ROC-AUC (rank statistic, tie-aware), host-side
    numpy; the gold reference for tests and small evals.
  * :class:`AUC` — streaming thresholded AUC as a jax pytree accumulator
    (keras-AUC equivalent, 200 thresholds by default).  ``update`` runs under
    jit; per-shard partial states are summed (a ``psum``/``process_allgather``
    away from a global metric) — replacing the reference's host-side
    ``all_gather_object`` aggregation (``torchrec/train.py:108-111``).
  * :func:`recalls_and_ndcgs_for_ks` — torchrec's sampled-candidate ranking
    protocol (1 positive + 100 negatives, ``torchrec/train.py:44-78``) via
    ``lax.top_k``.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["binary_auc", "ranking_auc", "AUC", "recalls_and_ndcgs_for_ks"]


def binary_auc(labels, scores, weights=None) -> float:
    """Exact ROC-AUC via the Mann-Whitney U statistic with tie handling.

    Host-side numpy: each positive/negative pair contributes 1 if the positive
    scores higher, 0.5 on ties.  ``weights`` masks padded eval rows
    (``jax-flax/train_dp.py:233-240`` pads the last batch; padding must not
    count).
    """
    labels = np.asarray(labels).reshape(-1).astype(np.float64)
    scores = np.asarray(scores).reshape(-1).astype(np.float64)
    if weights is not None:
        keep = np.asarray(weights).reshape(-1) > 0
        labels, scores = labels[keep], scores[keep]
    pos = scores[labels > 0.5]
    neg = scores[labels <= 0.5]
    if len(pos) == 0 or len(neg) == 0:
        return float("nan")
    neg_sorted = np.sort(neg)
    below = np.searchsorted(neg_sorted, pos, side="left")
    below_or_eq = np.searchsorted(neg_sorted, pos, side="right")
    u = below.sum() + 0.5 * (below_or_eq - below).sum()
    return float(u / (len(pos) * len(neg)))


def ranking_auc(scores) -> float:
    """AUC over sampled-candidate panels: ``scores`` is [N, C] with column 0
    the positive and columns 1.. the negatives (the torchrec eval protocol,
    ``torchrec/train.py:44-58``) — the seq family's online-gate analogue of
    the CTR :func:`binary_auc` over labelled rows.  PER-ROW: each panel's
    positive is ranked against its OWN negatives (win = 1, tie = 0.5 — the
    row-level U statistic) and rows average, so per-user score-scale shifts
    (common in seq models) cannot move the gate while within-panel ranking
    is unchanged.  Pooling all panels into one flat Mann-Whitney statistic
    would compare positives against other users' negatives — deliberately
    NOT what a sampled-panel gate should measure."""
    s = np.asarray(scores, np.float64)
    if s.ndim != 2 or s.shape[1] < 2:
        raise ValueError(
            f"ranking_auc needs [N, C>=2] candidate panels, got {s.shape}")
    pos, neg = s[:, :1], s[:, 1:]
    wins = (pos > neg).sum(axis=1) + 0.5 * (pos == neg).sum(axis=1)
    return float(np.mean(wins / neg.shape[1]))


@jax.tree_util.register_dataclass
@dataclass(frozen=True)
class AUC:
    """Streaming thresholded ROC-AUC (``tf.keras.metrics.AUC`` parity).

    Histograms of sigmoid scores per class over ``num_thresholds`` equal-width
    bins in [0, 1]; ``result`` integrates the ROC curve by trapezoid.  A plain
    pytree: jit/shard-safe, and two partial states combine by ``+`` (so a
    cross-host reduction is ``jax.tree.map(operator.add, *states)``).
    """

    pos_hist: jax.Array  # [num_thresholds] weighted positive counts per bin
    neg_hist: jax.Array  # [num_thresholds]

    @classmethod
    def empty(cls, num_thresholds: int = 200) -> "AUC":
        z = jnp.zeros((num_thresholds,), jnp.float32)
        return cls(pos_hist=z, neg_hist=z)

    @property
    def num_thresholds(self) -> int:
        return self.pos_hist.shape[0]

    def update(self, labels, scores, weights=None) -> "AUC":
        """Accumulate a batch.  ``scores`` are probabilities in [0,1] (apply
        sigmoid to logits first); ``weights`` zero out padded rows."""
        n = self.num_thresholds
        labels = labels.reshape(-1).astype(jnp.float32)
        scores = scores.reshape(-1)
        w = jnp.ones_like(labels) if weights is None else weights.reshape(-1).astype(jnp.float32)
        bins = jnp.clip((scores * n).astype(jnp.int32), 0, n - 1)
        pos = jnp.zeros((n,), jnp.float32).at[bins].add(w * labels)
        neg = jnp.zeros((n,), jnp.float32).at[bins].add(w * (1.0 - labels))
        return AUC(pos_hist=self.pos_hist + pos, neg_hist=self.neg_hist + neg)

    def merge(self, other: "AUC") -> "AUC":
        return AUC(self.pos_hist + other.pos_hist, self.neg_hist + other.neg_hist)

    def result(self) -> jax.Array:
        """Trapezoidal area under (FPR, TPR); ties within a bin count half."""
        total_pos = self.pos_hist.sum()
        total_neg = self.neg_hist.sum()
        # neg_above[i] = negatives in bins strictly above i; within-bin = tie
        neg_above = jnp.cumsum(self.neg_hist[::-1])[::-1] - self.neg_hist
        # Each bin-b positive beats neg strictly below, halves neg in-bin:
        # U = sum_b pos[b] * (neg_below[b] + 0.5 * neg[b])
        neg_below = total_neg - neg_above - self.neg_hist
        u = jnp.sum(self.pos_hist * (neg_below + 0.5 * self.neg_hist))
        return jnp.where(
            (total_pos > 0) & (total_neg > 0),
            u / jnp.maximum(total_pos * total_neg, 1.0),
            jnp.nan,
        )


def recalls_and_ndcgs_for_ks(
    scores: jax.Array,
    labels: jax.Array,
    ks: tuple[int, ...] = (10, 20, 50),
    row_weights: jax.Array | None = None,
) -> dict[str, jax.Array]:
    """Sampled-candidate ranking metrics (``torchrec/train.py:61-78`` parity).

    ``scores``/``labels``: [B, C] over C candidates per row (reference: 1
    positive + 100 popularity-sampled negatives, EVAL_NEG_NUM=100,
    ``torchrec/preprocessing.py:16,260-299``).  Recall@k = hits-in-top-k /
    min(k, positives); NDCG@k with the standard 1/log2(rank+2) gain.
    ``row_weights`` masks padded rows; returns batch means.
    """
    b, c = scores.shape
    w = jnp.ones((b,), jnp.float32) if row_weights is None else row_weights.astype(jnp.float32)
    denom = jnp.maximum(w.sum(), 1.0)
    labels = labels.astype(jnp.float32)
    n_pos = labels.sum(axis=1)
    out: dict[str, jax.Array] = {}
    # k larger than the candidate count degrades to @C (all candidates ranked)
    k_max = min(max(ks), c)
    _, topk_idx = jax.lax.top_k(scores, k_max)  # [B, k_max]
    hit = jnp.take_along_axis(labels, topk_idx, axis=1)  # [B, k_max]
    positions = jnp.arange(k_max, dtype=jnp.float32)
    gains = 1.0 / jnp.log2(positions + 2.0)
    for k in ks:
        kk = min(k, c)  # clamp the cut, keep the requested name
        hits_k = hit[:, :kk]
        recall = hits_k.sum(axis=1) / jnp.maximum(jnp.minimum(float(kk), n_pos), 1.0)
        dcg = (hits_k * gains[:kk]).sum(axis=1)
        ideal_hits = (positions[:kk][None, :] < n_pos[:, None]).astype(jnp.float32)
        idcg = (ideal_hits * gains[:kk]).sum(axis=1)
        ndcg = dcg / jnp.maximum(idcg, 1e-9)
        out[f"Recall@{k}"] = (recall * w).sum() / denom
        out[f"NDCG@{k}"] = (ndcg * w).sum() / denom
    return out
