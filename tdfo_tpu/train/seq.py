"""Sequential-recommendation training: masked-LM loss + candidate scoring.

The Bert4Rec training protocol from the reference (``torchrec/train.py``):

  * loss: cross-entropy over the vocab at every position, ignoring PAD
    labels, with label smoothing 0.1 (``torchrec/train.py:93,101`` —
    ``nn.CrossEntropyLoss(ignore_index=PAD_ID, label_smoothing=0.1)``).
    Labels are the original item where the input was masked, PAD elsewhere
    (``torchrec/preprocessing.py:122-150``).
  * eval: score the LAST position (the appended MASK token,
    ``torchrec/preprocessing.py:229-239``) against 1 positive + 100 sampled
    negatives and rank (``torchrec/train.py:44-58``).

Both factories produce jit-compiled, mesh-sharded steps in either parameter
regime: a single flax param tree (:class:`~tdfo_tpu.models.bert4rec.Bert4Rec`,
DDP-equivalent) via ``make_train_step(loss_fn=...)``, or the sparse/dense
split via ``make_sparse_train_step`` (DMP-equivalent).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import optax

from tdfo_tpu.models.bert4rec import PAD_ID

__all__ = ["masked_ce_loss", "score_candidates", "bert4rec_loss_fn", "bert4rec_sparse_forward"]


def masked_ce_loss(
    logits: jax.Array,  # [B, T, V]
    labels: jax.Array,  # [B, T] int; PAD_ID = ignore
    *,
    pad_id: int = PAD_ID,
    label_smoothing: float = 0.1,
) -> jax.Array:
    """Mean CE over non-PAD positions (torch ``ignore_index`` semantics)."""
    v = logits.shape[-1]
    mask = (labels != pad_id).astype(jnp.float32)  # [B, T]
    safe_labels = jnp.where(labels == pad_id, 0, labels)
    losses = optax.softmax_cross_entropy_with_integer_labels(
        logits.astype(jnp.float32), safe_labels
    )
    if label_smoothing:
        # optax integer-label CE has no smoothing knob; blend in the uniform
        # term explicitly: (1-s)*CE(onehot) + s*mean(-log p).
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        uniform = -logp.mean(axis=-1)
        losses = (1.0 - label_smoothing) * losses + label_smoothing * uniform
    return (losses * mask).sum() / jnp.maximum(mask.sum(), 1.0)


def score_candidates(logits: jax.Array, candidates: jax.Array) -> jax.Array:
    """Last-position candidate scores (``torchrec/train.py:44-58``).

    ``logits``: [B, T, V]; ``candidates``: [B, C] item ids (column 0 = the
    positive, rest negatives).  Returns [B, C] scores.
    """
    last = logits[:, -1, :]  # [B, V]
    return jnp.take_along_axis(last, candidates, axis=1)


def bert4rec_loss_fn(params, apply_fn, batch, *, label_smoothing: float = 0.1,
                     dropout_rng=None):
    """Loss adapter for ``make_train_step`` (dense/DDP regime).

    ``batch``: ``{"item": [B,T] masked input ids, "label": [B,T] targets}``.
    """
    kwargs = {}
    if dropout_rng is not None:
        kwargs = {"rngs": {"dropout": dropout_rng}, "deterministic": False}
    logits = apply_fn({"params": params}, batch["item"], **kwargs)
    return masked_ce_loss(logits, batch["label"], label_smoothing=label_smoothing)


def bert4rec_sparse_forward(backbone, *, label_smoothing: float = 0.1):
    """Forward for ``make_sparse_train_step`` (DMP regime): the collection has
    already gathered item vectors; run the dense backbone and the masked CE.
    Pass an rng to the step (``step(state, batch, rng)``) to enable dropout."""
    from tdfo_tpu.models.bert4rec import key_padding_mask

    def forward(dense_params, embs, batch, dropout_rng=None):
        kwargs = (
            {"rngs": {"dropout": dropout_rng}, "deterministic": False}
            if dropout_rng is not None
            else {}
        )
        logits = backbone.apply(
            {"params": dense_params}, embs["item"], key_padding_mask(batch["item"]),
            **kwargs,
        )
        return masked_ce_loss(logits, batch["label"], label_smoothing=label_smoothing)

    return forward
