"""Sharded checkpoint / resume — orbax-backed, covering all reference regimes.

Supersedes the reference's three checkpoint mechanisms (SURVEY.md §5.4):
flax byte blobs written once at train end with no optimizer state
(``jax-flax/models.py:128-139``), ``torch.save(state_dict())`` every 10
epochs whose DMP shards live per-rank (``torchrec/train.py:172-177``), and
keras ``ModelCheckpoint``/``BackupAndRestore`` (``tensorflow2/train_ps.py:155-157``)
— the only reference path with preemption resume.

Here: ONE mechanism.  The full train state (params, optimizer state/slots,
step/epoch counters, loss-scale) is a pytree of (possibly sharded) arrays;
orbax writes each host's shards and restores onto the same mesh/sharding
layout, giving mid-training resume with optimizer state for every model
family and parallelism regime — the BackupAndRestore capability, generalised.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Any

import jax
import numpy as np
import orbax.checkpoint as ocp

from tdfo_tpu.utils.retry import retry_call

__all__ = ["CheckpointManager", "LAYOUT_VERSION"]

# Storage-layout schema version, stamped into every checkpoint and verified
# on restore.  Bump whenever a parameter's in-memory LAYOUT changes in a way
# that restores without shape errors but scrambles values:
#   1: original layouts
#   2: fused-QKV feature order changed (qkv, head, dh) -> (head, qkv, dh)
#      (same shapes — silent q/k/v scramble on resume)
#   3: fat-line embedding storage (line_layout packing; adam d<64 moved from
#      stride-64 to d-contiguous component offsets, non-adam kinds gained
#      in-line state)
# A version mismatch (or a pre-stamping checkpoint) REFUSES to restore with
# a clear error instead of silently corrupting the resumed run.
LAYOUT_VERSION = 3


class CheckpointManager:
    """Step-indexed save/restore of an arbitrary train-state pytree.

    ``save(step_id, state, cursor=...)`` / ``restore(state_like)`` ->
    (step_id, state, cursor) or None.  ``step_id`` is whatever monotone id
    the caller uses (the Trainer uses the run-global data step, so mid-epoch
    checkpoints and epoch-end checkpoints share one ordered namespace).
    ``state_like`` provides structure, shardings, and dtypes (use the freshly
    initialised state); restored arrays land with the same shardings.  Static
    leaves (``apply_fn``, ``tx``...) registered as dataclass static fields
    are not serialised — they come from ``state_like``.
    """

    def __init__(self, directory: str | Path, *, max_to_keep: int = 3):
        self._dir = Path(directory).absolute()
        self._dir.mkdir(parents=True, exist_ok=True)
        self._mgr = ocp.CheckpointManager(
            self._dir,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True
            ),
        )

    def save(
        self,
        step_id: int,
        state: Any,
        *,
        cursor: dict[str, Any] | None = None,
        stamps: dict[str, Any] | None = None,
        force: bool = False,
    ) -> None:
        """Write the state pytree (and an optional data-stream ``cursor``)
        under ``step_id``.  The cursor — epoch, batches consumed, shuffle-seed
        provenance — is a small JSON sidecar (``cursor_<step_id>.json``)
        written by process 0 only, AFTER the orbax write is durable, so a
        cursor file on disk always refers to a complete checkpoint.  Saves
        retry with backoff (``tdfo_tpu/utils/retry.py``): transient storage
        failures must not kill an otherwise-healthy run.

        ``stamps``: JSON-able compatibility fingerprints beyond the layout
        version (e.g. the hot/cold mode's per-table hot-id digests — same
        shapes under a DIFFERENT hot set would restore cleanly but pair
        every hot row with the wrong id).  Written as a
        ``stamps_<step_id>.json`` sidecar and VERIFIED on restore: a
        mismatch (or a missing side) refuses the resume."""
        payload = {
            "layout_version": np.asarray(LAYOUT_VERSION, np.int32),
            "state": state,
        }
        retry_call(
            self._mgr.save,
            step_id,
            args=ocp.args.StandardSave(payload),
            force=force,
            description=f"ckpt_save:{step_id}",
        )
        self._mgr.wait_until_finished()
        if jax.process_index() == 0:
            cpath = self._cursor_path(step_id)
            if cursor is not None:
                retry_call(
                    cpath.write_text,
                    json.dumps(cursor),
                    description=f"cursor_save:{step_id}",
                )
            elif cpath.exists():
                cpath.unlink()  # force-overwrite must not keep a stale cursor
            spath = self._stamps_path(step_id)
            if stamps:
                retry_call(
                    spath.write_text,
                    json.dumps(stamps),
                    description=f"stamps_save:{step_id}",
                )
            elif spath.exists():
                spath.unlink()
            self._prune_cursors()

    def latest_step(self) -> int | None:
        return self._mgr.latest_step()

    def _cursor_path(self, step_id: int) -> Path:
        return self._dir / f"cursor_{step_id}.json"

    def _stamps_path(self, step_id: int) -> Path:
        return self._dir / f"stamps_{step_id}.json"

    def _prune_cursors(self) -> None:
        """Drop cursor/stamps sidecars whose checkpoint was garbage-collected
        by ``max_to_keep`` so the directory never accumulates orphans."""
        live = set(self._mgr.all_steps())
        for p in (*self._dir.glob("cursor_*.json"),
                  *self._dir.glob("stamps_*.json")):
            try:
                step = int(p.stem.split("_", 1)[1])
            except ValueError:
                continue
            if step not in live:
                p.unlink(missing_ok=True)

    def read_cursor(self, step_id: int) -> dict[str, Any] | None:
        """The data-stream cursor saved with ``step_id``, or None when absent
        (legacy epoch-indexed checkpoints have no cursor)."""
        cpath = self._cursor_path(step_id)
        if not cpath.exists():
            return None
        return json.loads(cpath.read_text())

    def restore(self, state_like: Any, step_id: int | None = None, *,
                stamps: dict[str, Any] | None = None):
        """Restore into the structure/shardings of ``state_like``.  Returns
        ``(step_id, state, cursor)`` or ``None`` when no checkpoint exists;
        ``cursor`` is the data-stream position saved alongside (None for
        legacy epoch-indexed checkpoints).  Refuses checkpoints whose
        storage-layout version differs from :data:`LAYOUT_VERSION` (same
        shapes, different value layout — a silent-corruption hazard, e.g. the
        round-4 fused-QKV reorder or the round-5 fat-line packing), and
        checkpoints whose ``stamps`` sidecar does not match the caller's
        ``stamps`` (e.g. a hot/cold run resumed under a different hot-id
        set: identical shapes, every hot row paired with the wrong id)."""
        step_id = self._mgr.latest_step() if step_id is None else step_id
        if step_id is None:
            return None
        spath = self._stamps_path(step_id)
        saved_stamps = json.loads(spath.read_text()) if spath.exists() else {}
        if (stamps or {}) != saved_stamps:
            raise ValueError(
                f"checkpoint step {step_id} in {self._dir} was saved with "
                f"compatibility stamps {saved_stamps!r}, but this run "
                f"expects {(stamps or {})!r}.  The state trees may restore "
                "cleanly anyway (identical shapes) with values paired to "
                "the WRONG ids — e.g. a hot/cold embedding run resumed "
                "under a different hot-id set — so resuming is refused.  "
                "Re-run with the matching artifacts (same data_dir "
                "hot_ids.json), or retrain."
            )
        # probe the SAVED tree's metadata for the stamp before restoring:
        # a missing stamp is the legacy (pre-versioning) format and must be
        # refused — without conflating genuine I/O or sharding errors from
        # the restore itself with layout incompatibility.  Only the probe's
        # expected failure modes are swallowed (absent/partial metadata,
        # schema drift across orbax versions); anything else propagates.
        try:
            meta = self._mgr.item_metadata(step_id)
        except (OSError, ValueError, KeyError, TypeError):
            meta = None
        meta_tree = getattr(meta, "tree", meta)
        if meta_tree is not None and "layout_version" not in meta_tree:
            raise ValueError(
                f"checkpoint step {step_id} in {self._dir} does not carry a "
                "layout_version stamp (it predates the versioned format).  "
                "Parameter LAYOUT changes (fused-QKV reorder, fat-line "
                "packing) restore without shape errors but scramble values, "
                "so resuming it is refused.  Retrain, or convert the "
                "checkpoint offline."
            )
        abstract = {
            "layout_version": jax.ShapeDtypeStruct((), np.int32),
            "state": jax.tree.map(ocp.utils.to_shape_dtype_struct, state_like),
        }
        try:
            restored = retry_call(
                self._mgr.restore,
                step_id,
                args=ocp.args.StandardRestore(abstract),
                description=f"ckpt_restore:{step_id}",
            )
        except (ValueError, KeyError, TypeError) as e:
            if meta_tree is not None:
                raise
            # the metadata probe failed (meta is None), so the legacy-format
            # refusal above could not fire — a pre-versioning checkpoint then
            # surfaces here as an opaque orbax structure mismatch (the
            # abstract tree expects a layout_version leaf the legacy save
            # never wrote).  Re-raise with the layout-version guidance
            # appended so the operator sees the real cause.
            raise ValueError(
                f"restoring checkpoint step {step_id} in {self._dir} failed "
                f"with: {e}.  Its metadata could not be probed, which "
                "together with this structure mismatch usually means the "
                "checkpoint predates the layout_version stamp "
                "(tdfo_tpu/train/checkpoint.py LAYOUT_VERSION).  Parameter "
                "LAYOUT changes restore without shape errors but scramble "
                "values, so unstamped checkpoints cannot be resumed.  "
                "Retrain, or convert the checkpoint offline."
            ) from e
        found = int(np.asarray(restored["layout_version"]))
        if found != LAYOUT_VERSION:
            raise ValueError(
                f"checkpoint step {step_id} in {self._dir} was written with "
                f"storage-layout version {found}, but this build uses "
                f"{LAYOUT_VERSION}.  The layouts are not value-compatible "
                "(see tdfo_tpu/train/checkpoint.py LAYOUT_VERSION history); "
                "resuming would silently scramble parameters, so it is "
                "refused.  Retrain, or convert the checkpoint offline."
            )
        return (
            step_id,
            _merge_static(state_like, restored["state"]),
            self.read_cursor(step_id),
        )

    def close(self) -> None:
        self._mgr.close()


def _merge_static(like: Any, restored: Any) -> Any:
    """Rebuild the full state: restored array leaves + static fields from
    ``like`` (tree structure carries them for registered dataclasses)."""
    leaves, treedef = jax.tree.flatten(restored)
    return jax.tree.unflatten(jax.tree.structure(like), leaves)
