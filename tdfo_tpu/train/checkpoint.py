"""Sharded checkpoint / resume — orbax-backed, covering all reference regimes.

Supersedes the reference's three checkpoint mechanisms (SURVEY.md §5.4):
flax byte blobs written once at train end with no optimizer state
(``jax-flax/models.py:128-139``), ``torch.save(state_dict())`` every 10
epochs whose DMP shards live per-rank (``torchrec/train.py:172-177``), and
keras ``ModelCheckpoint``/``BackupAndRestore`` (``tensorflow2/train_ps.py:155-157``)
— the only reference path with preemption resume.

Here: ONE mechanism.  The full train state (params, optimizer state/slots,
step/epoch counters, loss-scale) is a pytree of (possibly sharded) arrays;
orbax writes each host's shards and restores onto the same mesh/sharding
layout, giving mid-training resume with optimizer state for every model
family and parallelism regime — the BackupAndRestore capability, generalised.
"""

from __future__ import annotations

from pathlib import Path
from typing import Any

import jax
import numpy as np
import orbax.checkpoint as ocp

__all__ = ["CheckpointManager"]


class CheckpointManager:
    """Epoch-indexed save/restore of an arbitrary train-state pytree.

    ``save(step_id, state)`` / ``restore(state_like)`` -> (step_id, state) or
    None.  ``state_like`` provides structure, shardings, and dtypes (use the
    freshly initialised state); restored arrays land with the same shardings.
    Static leaves (``apply_fn``, ``tx``...) registered as dataclass static
    fields are not serialised — they come from ``state_like``.
    """

    def __init__(self, directory: str | Path, *, max_to_keep: int = 3):
        self._dir = Path(directory).absolute()
        self._dir.mkdir(parents=True, exist_ok=True)
        self._mgr = ocp.CheckpointManager(
            self._dir,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True
            ),
        )

    def save(self, step_id: int, state: Any, *, force: bool = False) -> None:
        self._mgr.save(step_id, args=ocp.args.StandardSave(state), force=force)
        self._mgr.wait_until_finished()

    def latest_step(self) -> int | None:
        return self._mgr.latest_step()

    def restore(self, state_like: Any, step_id: int | None = None):
        """Restore into the structure/shardings of ``state_like``.  Returns
        ``(step_id, state)`` or ``None`` when no checkpoint exists."""
        step_id = self._mgr.latest_step() if step_id is None else step_id
        if step_id is None:
            return None
        abstract = jax.tree.map(ocp.utils.to_shape_dtype_struct, state_like)
        restored = self._mgr.restore(
            step_id, args=ocp.args.StandardRestore(abstract)
        )
        return step_id, _merge_static(state_like, restored)

    def close(self) -> None:
        self._mgr.close()


def _merge_static(like: Any, restored: Any) -> Any:
    """Rebuild the full state: restored array leaves + static fields from
    ``like`` (tree structure carries them for registered dataclasses)."""
    leaves, treedef = jax.tree.flatten(restored)
    return jax.tree.unflatten(jax.tree.structure(like), leaves)
