"""The online-learning supervisor: serve -> retrain -> delta-export -> swap.

Monolith (§3.3) keeps CTR models fresh by feeding served traffic back into
training and streaming parameter deltas to the serving fleet; torchrec's
streaming-retrain loop is the same shape.  This module closes that loop for
this repo: it tails the frontend's request log through the crash-safe
``ReplayConsumer`` (``data/replay.py``), trains ``steps_per_cycle``
incremental steps, persists the replay cursor as a checkpoint sidecar,
exports a delta bundle (``serve/export.py:export_delta``), publishes it to
the ``BundleStore`` and hot-swaps the in-process ``MicroBatcher`` — forever,
or until the log drains / ``max_cycles``.

Crash-safety is a single-durability-point design.  Each cycle runs stages

    replay -> train -> checkpoint -> export -> publish -> swap

and the CHECKPOINT is the only commit: state and replay cursor land
atomically in one ``CheckpointManager.save`` (plus a ``target_version``
claim for the store).  A kill before the checkpoint discards the cycle —
the restart re-reads the same records from the last durable cursor and
retrains them onto the matching restored state, so each record contributes
to the state lineage exactly once.  A kill after the checkpoint but before
the store caught up is repaired by ``_catch_up`` at startup: the store head
still names a version below ``target_version``, so the supervisor re-exports
the (deterministic) delta from the head to the checkpointed state and
publishes it before entering the loop.  Either way "restart the same
command" converges to the uninterrupted run's bundle, bit for bit — the
property ``tests/test_online.py`` asserts with real ``os._exit`` kills at
every stage boundary (``[faults] kill_between_stages`` /
``kill_during_replay`` / ``kill_during_swap``).

Stage boundaries consult ``FaultInjector.maybe_kill_stage`` so the kill
matrix is deterministic, and every cycle logs an ``online_cycle`` record —
consumed ``(seq, row_start, row_end)`` spans plus the ``replay/*`` counters
— through the trainer's ``metrics.jsonl`` (PR-7 telemetry path), which is
the record-id accounting the no-dup/no-loss test audits.
"""

from __future__ import annotations

import shutil
from pathlib import Path
from typing import Any

import numpy as np

from tdfo_tpu.utils import faults as _faults

__all__ = ["OnlineLoop", "online_from_config"]


def _stage(name: str) -> None:
    """A supervisor stage boundary: the deterministic kill-matrix hook.
    The named stage has NOT run yet when the injected kill fires."""
    inj = _faults.active()
    if inj is not None:
        inj.maybe_kill_stage(name)


class OnlineLoop:
    """One supervisor process: trainer + replay consumer + bundle store +
    serving batcher, advancing in checkpointed cycles.

    Restricted to the DMP/sparse CTR regime (DLRM, or TwoTower with
    model_parallel): delta export diffs embedding tables, and online
    freshness is an embedding-dominated problem (Monolith §3.3).
    """

    def __init__(self, config, *, log_dir: str | Path | None = None):
        import jax

        from tdfo_tpu.data.replay import ReplayConsumer
        from tdfo_tpu.serve.swap import BundleStore
        from tdfo_tpu.train.trainer import Trainer

        if not config.online.request_log:
            raise ValueError(
                "the online loop needs [online] request_log — the directory "
                "a serving frontend (serve --serving.log_features) wrote")
        if config.model not in ("twotower", "dlrm"):
            raise ValueError(
                f"online supports the CTR family (twotower/dlrm), not "
                f"{config.model!r}")
        if jax.process_count() > 1:
            raise ValueError(
                "the online supervisor is single-process (one serving "
                "replica owns its request log and bundle store)")
        if config.steps_per_execution > 1:
            raise ValueError(
                "online requires steps_per_execution = 1: cycles are short "
                "and the cursor commits per cycle, not per scan chunk")

        self.config = config
        self.trainer = Trainer(config, log_dir=log_dir)
        if not hasattr(self.trainer.state, "tables"):
            raise ValueError(
                "online requires the DMP/sparse regime (dlrm, or twotower "
                "with model_parallel) — delta export diffs embedding tables")
        if self.trainer._pipelined:
            raise ValueError(
                "online does not support train.pipeline_overlap: the "
                "checkpoint stage needs the cycle's updates flushed")
        if self.trainer._ckpt is None:
            raise ValueError("online requires checkpoint_dir")

        self.workdir = Path(config.checkpoint_dir)
        self.store = BundleStore(self.workdir / "bundle_store")
        self.store.recover()  # half-published strays from a killed publish
        self.chain = self.workdir / "delta_chain"
        self.chain.mkdir(parents=True, exist_ok=True)

        # restore: state + replay cursor land together, so a resumed process
        # continues at the exact record the durable state has seen
        self.gstep = 0
        cursor: dict[str, Any] | None = None
        if self.trainer._ckpt.latest_step() is not None:
            self.gstep, self.trainer.state, cursor = self.trainer._ckpt.restore(
                self.trainer.state, stamps=self.trainer._ckpt_stamps)
        replay_cursor = (cursor or {}).get("replay")
        self._claimed_version = int((cursor or {}).get("target_version") or 0)

        mesh = self.trainer.mesh
        self.consumer = ReplayConsumer(
            config.online.request_log,
            schema=self.trainer._eval_schema,
            batch_size=config.per_device_train_batch_size
            * mesh.shape["data"],
            max_bad_records=config.online.max_bad_records,
            max_lag_records=config.online.max_lag_records,
            lag_policy=config.online.lag_policy,
            cursor=replay_cursor,
        )
        self._bootstrap_store()
        self._catch_up()
        self.batcher = self._make_batcher()
        self.cycles = 0

    # ----------------------------------------------------------- store side

    def _export_kwargs(self) -> dict[str, Any]:
        from tdfo_tpu.train.trainer import _ctr_columns

        cfg = self.config
        cat_cols, cont_cols = _ctr_columns(cfg)
        state = self.trainer.state
        return dict(
            model=cfg.model, embed_dim=cfg.embed_dim, cat_columns=cat_cols,
            cont_columns=cont_cols, size_map=cfg.size_map, step=self.gstep,
            coll=self.trainer.coll, tables=state.tables,
            dense_params=state.dense_params,
            mixed_precision=cfg.mixed_precision,
        )

    def _bootstrap_store(self) -> None:
        """First launch: publish the current state as full bundle v0 so every
        later cycle is a delta on a verified base.  Idempotent — a restart
        that finds a store head skips this entirely."""
        from tdfo_tpu.serve.export import export_bundle
        from tdfo_tpu.serve.swap import _version_name

        if self.store.current_version() is not None:
            return
        v0 = self.chain / _version_name(0)
        if v0.exists():
            shutil.rmtree(v0)  # crashed between export and ingest: redo
        export_bundle(v0, version=0, **self._export_kwargs())
        self.store.ingest_full(v0)

    def _publish_state(self, target: int) -> None:
        """Export the delta from the store head to the CURRENT trainer state
        and publish it as ``target``.  Deterministic and redoable: a stale
        half-exported directory is discarded and rebuilt from the same
        state, and the store refuses to regress versions."""
        from tdfo_tpu.serve.export import export_delta
        from tdfo_tpu.serve.swap import _version_name

        _stage("export")
        delta_dir = self.chain / _version_name(target)
        if delta_dir.exists():
            shutil.rmtree(delta_dir)
        export_delta(delta_dir, self.store.current_dir(),
                     **self._export_kwargs())
        _stage("publish")
        self.store.apply_delta(delta_dir)  # kill_during_swap fires in here

    def _catch_up(self) -> None:
        """Repair a kill between checkpoint and publish: the checkpoint
        claimed ``target_version`` but the store head is still behind it, so
        the durable state has never reached serving.  Re-export + publish
        before the loop — without this, a drained log would strand the last
        trained cycle in the checkpoint forever."""
        if self._claimed_version <= int(self.store.current_version() or 0):
            return
        self._publish_state(self._claimed_version)

    def _make_batcher(self):
        from tdfo_tpu.serve.frontend import MicroBatcher

        spec = self.config.serving
        scorer = self._build_scorer(self.store.current_dir())
        return MicroBatcher(
            scorer.score, buckets=spec.buckets, max_batch=spec.max_batch,
            batch_deadline_ms=spec.batch_deadline_ms,
            logger=self.trainer.logger,
            program_cache_size=scorer.score_cache_size,
            max_queue=spec.max_queue, shed_policy=spec.shed_policy,
        )

    def _build_scorer(self, bundle_dir):
        from tdfo_tpu.serve.export import load_bundle
        from tdfo_tpu.serve.scoring import make_scorer

        return make_scorer(load_bundle(bundle_dir), mesh=self.trainer.mesh)

    # ------------------------------------------------------------ the cycle

    def _train_cycle(self, batches: list[dict[str, np.ndarray]]) -> float:
        """Run one incremental step per replay batch.  Same step program as
        offline fit — [online] adds no graph edits (jaxpr-pinned by
        tests/test_online.py), so serving-loop configs never recompile."""
        from jax.sharding import PartitionSpec as P

        from tdfo_tpu.data.loader import prefetch_to_mesh
        from tdfo_tpu.train.metrics import AUC

        trainer, loss = self.trainer, 0.0
        auc = AUC.empty() if trainer._train_auc_enabled else None
        for batch in prefetch_to_mesh(iter(batches), trainer.mesh, P("data")):
            out = trainer.train_step(trainer.state, batch, auc)
            trainer.state, step_loss, auc = out[:3]
            self.gstep += 1
            loss = float(step_loss)
        trainer._flush_cache_sync()  # update cache -> tables before export
        return loss

    def run_cycle(self) -> dict[str, Any] | None:
        """One full serve->retrain->swap cycle; ``None`` when the durable
        log has fewer than one batch of unread rows (drained)."""
        cfg = self.config
        _stage("replay")
        self.consumer.check_backpressure()
        batches, consumed = [], []
        while len(batches) < cfg.online.steps_per_cycle:
            out = self.consumer.next_batch()
            if out is None:
                break
            batches.append(out[0])
            consumed.extend(out[1])
        if not batches:
            return None

        _stage("train")
        loss = self._train_cycle(batches)

        _stage("checkpoint")
        target = int(self.store.current_version() or 0) + 1
        self.trainer._ckpt.save(
            self.gstep, self.trainer.state, force=True,
            cursor={"online": True, "global_step": self.gstep,
                    "replay": self.consumer.cursor(),
                    "target_version": target},
            stamps=self.trainer._ckpt_stamps)
        self._claimed_version = target
        rec = {
            "event": "online_cycle", "cycle": self.cycles,
            "global_step": self.gstep, "steps": len(batches),
            "loss": loss, "version": target,
            "consumed": [list(span) for span in consumed],
            **self.consumer.counters(),
        }
        self.trainer.logger.log(**rec)

        self._publish_state(target)  # stages: export -> publish

        _stage("swap")
        scorer = self._build_scorer(self.store.current_dir())
        self.batcher.swap(scorer.score, version=target,
                          program_cache_size=scorer.score_cache_size)
        self.cycles += 1
        return rec

    def run(self) -> dict[str, Any]:
        """Cycle until the log drains or ``max_cycles``; returns run stats."""
        max_cycles = self.config.online.max_cycles
        while not max_cycles or self.cycles < max_cycles:
            if self.run_cycle() is None:
                break
        ctrs = self.consumer.counters()
        return {
            "cycles": self.cycles,
            "global_step": self.gstep,
            "version": int(self.store.current_version() or 0),
            "bundle": str(self.store.current_dir()),
            **ctrs,
        }

    def probe(self, requests) -> dict[Any, np.ndarray]:
        """Score a request trace through the live (post-swap) batcher — the
        served-logits fingerprint the bitwise-equality acceptance compares."""
        return self.batcher.run(requests)


def online_from_config(config, *, log_dir: str | Path | None = None
                       ) -> dict[str, Any]:
    """The ``python -m tdfo_tpu.launch online`` body."""
    return OnlineLoop(config, log_dir=log_dir).run()
